"""The rate-limit engine: host routing + one sharded device step per window.

This is the TPU-native collapse of three reference components:

  * the owner's batch drain (gubernator.go:210-227) → `window_step` per shard;
  * the consistent-hash peer routing (hash.go:80-96, gubernator.go:114) →
    `crc32(key) % num_shards` choosing the mesh-axis shard, resolved on the
    host while packing the window;
  * the GLOBAL async-hits + broadcast dance (global.go:72-232) → one
    `lax.psum` of per-slot hit deltas over the mesh axis, after which the
    authoritative state is already resident on every shard.

One call to `step()` plays the role of one 500µs batching window being shipped
to the owner (peers.go:176-207): the host packs per-shard request lanes into
dense arrays, the device applies them in a single jitted shard_map step, and
the responses demux back by lane index.

State layout: regular (sharded) keys live in BucketState arrays of shape
[S, C] partitioned over the "shard" mesh axis; GLOBAL keys live in a
replicated [G] arena whose updates flow only through the psum so replicas stay
bit-exact.  Host-side key→slot tables (state/arena.py) are per shard.
"""

from __future__ import annotations

import logging
import zlib
from functools import lru_cache
from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from gubernator_tpu.api.types import (
    Algorithm,
    Behavior,
    RateLimitReq,
    RateLimitResp,
    millisecond_now,
)
from gubernator_tpu.compat import shard_map as _compat_shard_map
from gubernator_tpu.ops import kernel
from gubernator_tpu.ops.kernel import (
    BucketState,
    GlobalConfig,
    WindowBatch,
    WindowOutput,
)
from gubernator_tpu.parallel.mesh import (SHARD_AXIS, make_mesh, shard_spec,
                                          stacked_spec)
from gubernator_tpu.state.arena import SlotTable

log = logging.getLogger("gubernator.engine")


# Stacked-window buckets for the serving pipeline (core/pipeline.py): a
# drain dispatches its windows padded up to the nearest bucket, and
# warmup() pre-compiles exactly these shapes.  Single source of truth —
# a bucket missing here would compile mid-serving on the engine thread.
# Stacked-drain depth ladder: each bucket is one compiled executable (the
# scan body is K-independent, so deeper stacks amortize the per-dispatch
# cost linearly — the decisions-per-dispatch lever).  GUBER_PIPELINE_KMAX
# extends the ladder without code changes once the on-chip stack-depth
# probe (scripts/probe_stack_depth.py) picks the serving optimum.
def _k_buckets_from_env():
    from gubernator_tpu.config import env_int
    kmax = env_int("GUBER_PIPELINE_KMAX", 8)
    # dense through 8, sparse above: dispatch cost is linear in the PADDED
    # bucket, so a k=3 drain padded to kb=4 wastes a third of its device
    # time — and k in [1, 8] is exactly where the overlapped pipeline's
    # occupancy gate lands under steady load.  Above 8 every bucket is one
    # warmup compile (tens of seconds over a tunneled chip), so the
    # extended ladder keeps trading shape fit for boot time.
    buckets = list(range(1, min(kmax, 8) + 1))
    buckets += [b for b in (32, 128, 512) if buckets[-1] < b < kmax]
    if kmax > buckets[-1]:
        buckets.append(kmax)
    return tuple(buckets)


PIPELINE_K_BUCKETS = _k_buckets_from_env()


def shard_of(key: str, num_shards: int) -> int:
    """Map a hash key to its owning shard.

    Same hash family as the reference's ring (crc32 IEEE, hash.go:41) but a
    plain modulus: mesh shards are homogeneous and resize by re-sharding the
    arena, so ring semantics (minimal movement on membership change) buy
    nothing inside a mesh.
    """
    return zlib.crc32(key.encode("utf-8")) % num_shards


class _PackedWindow:
    """Host-side staging buffers for one window (numpy, reused per step)."""

    def __init__(self, S: int, B: int, Bg: int, Kg: int):
        self.slot = np.full((S, B), kernel.PAD_SLOT, dtype=np.int32)
        self.hits = np.zeros((S, B), dtype=np.int64)
        self.limit = np.zeros((S, B), dtype=np.int64)
        self.duration = np.zeros((S, B), dtype=np.int64)
        self.algo = np.zeros((S, B), dtype=np.int32)
        self.is_init = np.zeros((S, B), dtype=bool)
        self.gslot = np.full((S, Bg), kernel.PAD_SLOT, dtype=np.int32)
        self.ghits = np.zeros((S, Bg), dtype=np.int64)
        # hits contributed to the psum (0 for lanes whose hits reconcile via
        # the cross-host path instead — see RateLimitEngine.step(accumulate))
        self.ghits_acc = np.zeros((S, Bg), dtype=np.int64)
        self.glimit = np.zeros((S, Bg), dtype=np.int64)
        self.gduration = np.zeros((S, Bg), dtype=np.int64)
        self.galgo = np.zeros((S, Bg), dtype=np.int32)
        self.gis_init = np.zeros((S, Bg), dtype=bool)
        self.uslot = np.zeros((Kg,), dtype=np.int32)
        self.ulimit = np.zeros((Kg,), dtype=np.int64)
        self.uduration = np.zeros((Kg,), dtype=np.int64)
        self.ualgo = np.zeros((Kg,), dtype=np.int32)
        self.rslot = np.zeros((Kg,), dtype=np.int32)
        # owner-broadcast upsert lanes (cross-host GLOBAL replicas)
        self.pslot = np.zeros((Kg,), dtype=np.int32)
        self.plimit = np.zeros((Kg,), dtype=np.int64)
        self.pduration = np.zeros((Kg,), dtype=np.int64)
        self.premaining = np.zeros((Kg,), dtype=np.int64)
        self.ptstamp = np.zeros((Kg,), dtype=np.int64)
        self.pexpire = np.zeros((Kg,), dtype=np.int64)
        self.palgo = np.zeros((Kg,), dtype=np.int32)

    def reset(self, G: int):
        self.slot.fill(kernel.PAD_SLOT)
        self.gslot.fill(kernel.PAD_SLOT)
        self.ghits.fill(0)
        self.ghits_acc.fill(0)
        # pad config-update/reset lanes point one past the global arena → dropped
        self.uslot.fill(G)
        self.rslot.fill(G)
        self.pslot.fill(G)


class RateLimitEngine:
    """Dense sharded rate-limit state + one jitted device step per window.

    capacity_per_shard: slots per shard (reference default cache size is
        50k per node, cache/lru.go:50; ours defaults to 64k per shard).
    batch_per_shard: max regular-key request lanes per shard per window.
    global_capacity: slots in the replicated GLOBAL arena.
    global_batch_per_shard: max GLOBAL request lanes per shard per window.
    """

    def __init__(
        self,
        mesh: Optional[Mesh] = None,
        capacity_per_shard: int = 65536,
        batch_per_shard: int = 1024,
        global_capacity: int = 4096,
        global_batch_per_shard: int = 256,
        max_global_updates: int = 256,
        use_native: str = "auto",
        exact_keys: bool = False,
        replay_cap: "Optional[int]" = None,
        skip_global: bool = False,
    ):
        self.mesh = mesh if mesh is not None else make_mesh()
        self.num_shards = int(np.prod(list(self.mesh.shape.values())))
        self.capacity_per_shard = capacity_per_shard
        self.batch_per_shard = batch_per_shard
        self.global_capacity = global_capacity
        self.global_batch_per_shard = global_batch_per_shard
        self.max_global_updates = max_global_updates
        # Config-level promise of zero GLOBAL traffic (EngineConfig
        # .skip_global / GUBER_SKIP_GLOBAL): stacked dispatches always
        # lower to the GLOBAL-skipping twin.  Being config-driven it is
        # identical on every mesh process, which is what makes the skip
        # legal under the mesh collective contract — unlike the
        # single-process per-stack inertness gate in step_windows.
        self._skip_global = bool(skip_global)

        # Mesh mode (parallel/distributed.py): the mesh spans processes;
        # this host stages lanes only for its contiguous run of shards and
        # reads back only its addressable output blocks.  All processes must
        # dispatch in lockstep.
        from gubernator_tpu.parallel.distributed import local_device_indices
        local_ids = local_device_indices(self.mesh)
        self.multiprocess = len(local_ids) != self.mesh.devices.size
        self.num_local_shards = len(local_ids)
        self.local_shard_offset = min(local_ids) if local_ids else 0
        if self.multiprocess:
            if local_ids != list(range(self.local_shard_offset,
                                       self.local_shard_offset + len(local_ids))):
                raise ValueError(
                    "mesh mode needs each process's devices contiguous on the "
                    "shard axis (default jax.devices() order satisfies this)")
            # dynamic GLOBAL registration and gRPC upserts would diverge the
            # replicated arena across processes — see step()/register_global_keys
            self._dynamic_global = False
        else:
            self._dynamic_global = True

        S, C, G = self.num_shards, capacity_per_shard, global_capacity
        shard_sharding = NamedSharding(self.mesh, P(SHARD_AXIS))
        repl_sharding = NamedSharding(self.mesh, P())
        self._shard_sharding = shard_sharding
        self._repl_sharding = repl_sharding

        def sharded_zeros(shape, dtype, sharding):
            # compiled constant: works when the sharding spans non-addressable
            # devices (multi-host), unlike device_put of a host array
            return jax.jit(lambda: jnp.zeros(shape, dtype),
                           out_shardings=sharding)()

        self.state = BucketState(
            limit=sharded_zeros((S, C), jnp.int64, shard_sharding),
            duration=sharded_zeros((S, C), jnp.int64, shard_sharding),
            remaining=sharded_zeros((S, C), jnp.int64, shard_sharding),
            tstamp=sharded_zeros((S, C), jnp.int64, shard_sharding),
            expire=sharded_zeros((S, C), jnp.int64, shard_sharding),
            algo=sharded_zeros((S, C), jnp.int32, shard_sharding),
        )
        self.gstate = BucketState(
            limit=sharded_zeros((G,), jnp.int64, repl_sharding),
            duration=sharded_zeros((G,), jnp.int64, repl_sharding),
            remaining=sharded_zeros((G,), jnp.int64, repl_sharding),
            tstamp=sharded_zeros((G,), jnp.int64, repl_sharding),
            expire=sharded_zeros((G,), jnp.int64, repl_sharding),
            algo=sharded_zeros((G,), jnp.int32, repl_sharding),
        )
        self.gcfg = GlobalConfig(
            limit=sharded_zeros((G,), jnp.int64, repl_sharding),
            duration=sharded_zeros((G,), jnp.int64, repl_sharding),
            algo=sharded_zeros((G,), jnp.int32, repl_sharding),
        )

        # host routing state covers local shards only (all of them when
        # single-process)
        self.tables = [SlotTable(C) for _ in range(self.num_local_shards)]
        self.gtable = SlotTable(G)
        # dynamic mesh registrations applied (phase 1) but not yet activated
        # mesh-wide (phase 2) — not servable until then
        self._gpending: set = set()
        # step_stacked staging, cached per stack depth K
        self._stacked_bufs: dict = {}
        self._buf = _PackedWindow(self.num_local_shards, batch_per_shard,
                                  global_batch_per_shard, max_global_updates)
        self._step_fn = self._build_step()
        self._multi_fn = _compiled_multi_step(self.mesh)
        self._compact_fn = _compiled_step_compact(self.mesh)
        # Sound-saturation guard for the compact wire format: once any
        # out-of-range config enters the arena via the full path, stored
        # limits/durations may exceed what the compact response can carry, so
        # compact dispatch is disabled for the engine's lifetime (see the
        # format note in ops/kernel.py).  Mesh mode's LEGACY step paths
        # always use the full format: per-window compact eligibility is a
        # per-host data-dependent choice, and hosts picking different
        # executables for the same lockstep window would wedge the
        # collectives.  The lockstep pipeline drain instead keeps the
        # EXECUTABLE fixed every tick and moves the data-dependence into
        # STAGING (_compact_sound gates which lanes enter the compact
        # stack; the drain dispatches either way), so mesh serving gets
        # the compact wire + fold without executable divergence.
        self._compact_enabled = not self.multiprocess
        self._compact_sound = True
        self.windows_processed = 0
        self.decisions_processed = 0
        # occupied-prefix lane buckets (see _lane_bucket): powers-of-4 steps
        # down from B, floored at 64 — at most 3 shapes per executable family
        B = batch_per_shard
        self._lane_bucket_list = sorted(
            {b for b in (max(64, B // 16), max(64, B // 4)) if b < B} | {B})

        # Tiered key state (state/tiers.py): installed by enable_tiers on
        # Python-routed single-process engines; None = single-tier seed
        # behavior, byte-identical hot path
        self._tiers = None

        # Native C++ window router (gubernator_tpu/native): batch key hashing,
        # shard routing, slot lookup + LRU in one C call per window, replacing
        # the per-key Python dict path.  The two backends are exclusive —
        # regular-key routing state lives in exactly one of them.
        # replay-bound guard (GUBER_REPLAY_CAP overrides the param/config
        # unconditionally, like GUBER_EXACT_KEYS; default 128, 0 disables)
        import os as _os
        _env_cap = _os.environ.get("GUBER_REPLAY_CAP")
        if _env_cap is not None:
            try:
                self.replay_cap = int(_env_cap)
            except ValueError:
                raise ValueError(
                    f"GUBER_REPLAY_CAP must be an integer (lanes; 0 "
                    f"disables the replay-bound guard), got {_env_cap!r}"
                ) from None
        else:
            self.replay_cap = 128 if replay_cap is None else replay_cap
        self.native = None
        if use_native in ("auto", True, "on"):
            from gubernator_tpu import native as native_mod
            if native_mod.available():
                self.native = native_mod.NativeRouter(
                    self.num_local_shards, C,
                    num_global_shards=S,
                    shard_offset=self.local_shard_offset)
                # opt-in exact-key guard (GUBER_EXACT_KEYS=1 or
                # EngineConfig.exact_keys): store full keys so a 64-bit
                # fingerprint collision probes onward instead of silently
                # merging two keys' counters
                import os
                if exact_keys or os.environ.get("GUBER_EXACT_KEYS") == "1":
                    self.native.set_exact_keys()
                self.native.set_replay_cap(self.replay_cap)
            elif use_native != "auto":
                raise RuntimeError("native router requested but unavailable")

    # ------------------------------------------------------------------ device

    def _build_step(self):
        # All engines with the same mesh geometry share one compiled
        # executable — a 4-node in-process cluster compiles once, not four
        # times (each Instance owns an engine but the computation is pure).
        return _compiled_step(self.mesh)



    def step(
        self,
        requests: Sequence[RateLimitReq],
        now: Optional[int] = None,
        accumulate: Optional[Sequence[bool]] = None,
        upserts: Optional[Sequence] = None,
    ) -> List[RateLimitResp]:
        """Process one window of requests synchronously.

        accumulate[i]=False keeps request i's GLOBAL hits out of the psum:
        used by a non-owner *host* in a multi-host cluster, which answers
        from its replica and reconciles hits with the owner over gRPC
        (reference gubernator.go:173-195) rather than over the mesh.
        upserts: UpdatePeerGlobal-shaped records (key, status, algorithm,
        duration) from an owner broadcast, written into the replica arena
        before this window's reads.

        Caller must respect the window caps (use `process` for auto-chunking):
        per-shard regular lanes <= batch_per_shard, total GLOBAL lanes <=
        num_local_shards * global_batch_per_shard (they spread round-robin
        over local shards), distinct GLOBAL keys + upserts <=
        max_global_updates.
        """
        if self.native is not None:
            return self._process_native(requests, now, accumulate, upserts)
        now = self._resolve_now(now)
        S = self.num_shards
        buf = self._buf
        buf.reset(self.global_capacity)
        # init-pending protocol (state/arena.py): fresh allocations keep
        # reporting is_init until the dispatch below commits this window
        for t in self.tables:
            t.begin_window()
        self.gtable.begin_window()

        if upserts and not self._dynamic_global:
            # gRPC-broadcast upserts are host-local writes; in mesh mode they
            # would diverge the replicated arena across processes
            raise ValueError("upserts are not supported in mesh mode "
                             "(GLOBAL state replicates via the in-mesh psum)")
        if upserts:
            for i, u in enumerate(upserts):
                slot, _ = self.gtable.lookup(u.key, now, u.duration)
                st = u.status
                buf.pslot[i] = slot
                buf.plimit[i] = st.limit
                buf.pduration[i] = u.duration
                buf.premaining[i] = st.remaining
                is_token = u.algorithm == Algorithm.TOKEN_BUCKET
                # token: tstamp/expire are the bucket's reset_time; leaky: the
                # timestamp restarts here and the entry lives a full duration
                # (the reference's Add(key, status, status.ResetTime) leaves
                # leaky replicas instantly expired — divergence documented in
                # api/proto/peers.proto)
                buf.ptstamp[i] = st.reset_time if is_token else now
                buf.pexpire[i] = st.reset_time if is_token else now + u.duration
                buf.palgo[i] = u.algorithm

        lanes, gcfg_upd, greset, max_fill, g_count = self._stage_requests(
            buf, requests, now, accumulate)

        for i, (slot, cfg) in enumerate(gcfg_upd.items()):
            buf.uslot[i] = slot
            buf.ulimit[i], buf.uduration[i], buf.ualgo[i] = cfg
        for i, slot in enumerate(greset):
            buf.rslot[i] = slot

        if self._tiers is not None:
            self._tier_fence(now)
        out, gout = self._dispatch(
            now, reg_fill=max_fill, fetch_global=g_count > 0)
        for t in self.tables:
            t.commit_window()
        self.gtable.commit_window()

        self.decisions_processed += len(requests)

        responses = []
        for s, lane, is_global in lanes:
            o = gout if is_global else out
            responses.append(
                RateLimitResp(
                    status=int(o.status[s, lane]),
                    limit=int(o.limit[s, lane]),
                    remaining=int(o.remaining[s, lane]),
                    reset_time=int(o.reset_time[s, lane]),
                )
            )
        return responses

    def _stage_requests(self, buf, requests, now, accumulate):
        """Stage one window's requests into `buf` (the engine's
        _PackedWindow, or a per-window view over stacked staging arrays —
        anything exposing the same lane arrays).

        Returns (lanes, gcfg_upd, greset, max_reg_fill, g_count) where
        lanes is [(shard, lane, is_global)] per request for demux."""
        S = self.num_shards
        reg_fill = [0] * self.num_local_shards
        glob_fill = [0] * self.num_local_shards
        # slot -> (limit, duration, algo): latest request's config wins within
        # the window (deduped host-side — a device scatter with duplicate
        # indices has no ordering guarantee)
        gcfg_upd = {}
        greset: List[int] = []
        lanes: List[tuple] = []

        g_count = 0
        for i, r in enumerate(requests):
            key = r.hash_key()
            if r.behavior == Behavior.GLOBAL:
                if not self._dynamic_global and not self.global_ready(key):
                    raise ValueError(
                        f"GLOBAL key {key!r} is not registered; mesh mode "
                        "registers GLOBAL keys through the registrar "
                        "(core/service.py) before serving them")
                slot, is_init = self.gtable.lookup(key, now, r.duration)
                contribute = accumulate is None or accumulate[i]
                if contribute and self._dynamic_global:
                    # per-request config refresh diverges replicas in mesh
                    # mode; there configs are fixed at registration
                    gcfg_upd[slot] = (r.limit, r.duration, r.algorithm)
                    if is_init:
                        greset.append(slot)
                # GLOBAL lanes are shard-agnostic (the psum covers every
                # shard), so spread them round-robin over LOCAL shards
                if g_count >= self.num_local_shards * self.global_batch_per_shard:
                    raise ValueError(
                        "window exceeds the GLOBAL lane cap "
                        f"({self.num_local_shards} local shards x "
                        f"{self.global_batch_per_shard}); use process() for "
                        "auto-chunking")
                s = g_count % self.num_local_shards
                g_count += 1
                lane = glob_fill[s]
                glob_fill[s] += 1
                buf.gslot[s, lane] = slot
                buf.ghits[s, lane] = r.hits
                buf.ghits_acc[s, lane] = r.hits if contribute else 0
                buf.glimit[s, lane] = r.limit
                buf.gduration[s, lane] = r.duration
                buf.galgo[s, lane] = r.algorithm
                buf.gis_init[s, lane] = is_init
                lanes.append((s, lane, True))
            else:
                s = shard_of(key, S) - self.local_shard_offset
                if not 0 <= s < self.num_local_shards:
                    raise ValueError(
                        f"key {key!r} belongs to shard "
                        f"{shard_of(key, S)}, not owned by this process — "
                        "the serving layer must route it to the owning host")
                slot = None
                is_init = False
                if self._tiers is not None and key not in self.tables[s]:
                    # warm-tier rehydration: a demoted key re-enters the hot
                    # arena with its LIVE row (scattered at the pre-dispatch
                    # fence), so the decision matches the infinite-arena
                    # oracle bit for bit; a miss in warm too falls through
                    # to the ordinary cold-init lookup
                    slot = self._tiers.stage_promote(
                        s, self.tables[s], key, now, r.duration)
                if slot is None:
                    slot, is_init = self.tables[s].lookup(
                        key, now, r.duration)
                lane = reg_fill[s]
                reg_fill[s] += 1
                buf.slot[s, lane] = slot
                buf.hits[s, lane] = r.hits
                buf.limit[s, lane] = r.limit
                buf.duration[s, lane] = r.duration
                buf.algo[s, lane] = r.algorithm
                buf.is_init[s, lane] = is_init
                lanes.append((s, lane, False))
        return lanes, gcfg_upd, greset, max(reg_fill, default=0), g_count

    def step_stacked(
        self,
        windows: Sequence[Sequence[RateLimitReq]],
        now: Optional[int] = None,
        accumulates: Optional[Sequence[Optional[Sequence[bool]]]] = None,
        k_stack: Optional[int] = None,
    ) -> List[List[RateLimitResp]]:
        """K serving windows in ONE device dispatch — the lockstep
        saturation path (the mesh analog of the reference's back-to-back
        queue drain, peers.go:143-172).

        Semantics equal K sequential step() calls at the same `now`, with
        one documented divergence in single-process dynamic-GLOBAL mode:
        per-request GLOBAL config refreshes from ALL windows merge
        (last-wins) and apply once before window 0, because the stacked
        executable applies the control plane only there
        (_compiled_multi_step).  Mesh mode has no dynamic GLOBAL config, so
        its semantics are exact.

        Mesh mode: every process must call this in lockstep with the SAME
        `k_stack` (the executable's shape is part of the collective
        contract), the same cluster-agreed `now`, and its own local
        windows.  `k_stack` pads the stack with empty windows so a fixed
        tick shape can carry a variable backlog.
        """
        now = self._resolve_now(now)
        K = k_stack if k_stack is not None else max(len(windows), 1)
        if len(windows) > K:
            raise ValueError(f"{len(windows)} windows exceed k_stack={K}")
        SL, B = self.num_local_shards, self.batch_per_shard
        Bg, Kg = self.global_batch_per_shard, self.max_global_updates
        G = self.global_capacity

        # Per-K cached stacked staging (the hot lockstep path ticks every
        # batch_wait; reuse is safe because this method fetches the
        # responses before returning, so the previous tick's transfer is
        # complete).  Reset like _PackedWindow.reset: PAD slots drop lanes;
        # other fields only matter on non-PAD lanes except ghits_acc, whose
        # stale values would leak into the psum via jnp.zeros scatter-add.
        st = self._stacked_bufs.get(K)
        if st is None:
            st = _PackedWindow.__new__(_PackedWindow)
            st.slot = np.empty((K, SL, B), np.int32)
            st.hits = np.empty((K, SL, B), np.int64)
            st.limit = np.empty((K, SL, B), np.int64)
            st.duration = np.empty((K, SL, B), np.int64)
            st.algo = np.empty((K, SL, B), np.int32)
            st.is_init = np.empty((K, SL, B), bool)
            st.gslot = np.empty((K, SL, Bg), np.int32)
            st.ghits = np.empty((K, SL, Bg), np.int64)
            st.ghits_acc = np.empty((K, SL, Bg), np.int64)
            st.glimit = np.empty((K, SL, Bg), np.int64)
            st.gduration = np.empty((K, SL, Bg), np.int64)
            st.galgo = np.empty((K, SL, Bg), np.int32)
            st.gis_init = np.empty((K, SL, Bg), bool)
            self._stacked_bufs[K] = st
        st.slot.fill(kernel.PAD_SLOT)
        st.gslot.fill(kernel.PAD_SLOT)
        st.ghits_acc.fill(0)

        class _View:
            """One window's writable slice of the stacked staging arrays."""
            def __init__(self, k):
                for f in ("slot", "hits", "limit", "duration", "algo",
                          "is_init", "gslot", "ghits", "ghits_acc",
                          "glimit", "gduration", "galgo", "gis_init"):
                    setattr(self, f, getattr(st, f)[k])

        for t in self.tables:
            t.begin_window()
        self.gtable.begin_window()
        if self.native is not None:
            self.native.drain_begin()
        all_lanes: List[List[tuple]] = []
        merged_upd: dict = {}
        merged_reset: List[int] = []
        try:
            for k, reqs in enumerate(windows):
                acc = accumulates[k] if accumulates is not None else None
                if self.native is None:
                    lanes, gcfg_upd, greset, _, _ = self._stage_requests(
                        _View(k), reqs, now, acc)
                else:
                    lanes, gcfg_upd, greset = self._stage_window_native(
                        _View(k), reqs, now, acc)
                all_lanes.append(lanes)
                merged_upd.update(gcfg_upd)
                merged_reset.extend(greset)
            if len(merged_upd) > Kg or len(merged_reset) > Kg:
                raise ValueError("stacked windows carry more GLOBAL config "
                                 f"updates than max_global_updates ({Kg})")
        except Exception:
            # staging failed before dispatch: keep the drain's fresh
            # allocations pending (their slots were never initialized on
            # device; the next touch must re-init them)
            if self.native is not None:
                self.native.abort()
            raise

        uslot = np.full((Kg,), G, np.int32)
        ulimit = np.zeros((Kg,), np.int64)
        uduration = np.zeros((Kg,), np.int64)
        ualgo = np.zeros((Kg,), np.int32)
        rslot = np.full((Kg,), G, np.int32)
        for i, (slot, cfg) in enumerate(merged_upd.items()):
            uslot[i] = slot
            ulimit[i], uduration[i], ualgo[i] = cfg
        for i, slot in enumerate(merged_reset):
            rslot[i] = slot
        _, _, _, ups = self.empty_control()

        batches = WindowBatch(slot=st.slot, hits=st.hits, limit=st.limit,
                              duration=st.duration, algo=st.algo,
                              is_init=st.is_init)
        gbatches = WindowBatch(slot=st.gslot, hits=st.ghits, limit=st.glimit,
                               duration=st.gduration, algo=st.galgo,
                               is_init=st.gis_init)
        nows = np.full((K,), now, np.int64)

        if self._tiers is not None:
            # one fence covers the whole stack: begin_window ran ONCE above,
            # so every spill/promotion staged across the K windows resolves
            # here, before the single fused dispatch reads the arena
            self._tier_fence(now)
        try:
            fused = self.step_windows(
                batches, gbatches, st.ghits_acc,
                (uslot, ulimit, uduration, ualgo, rslot), ups, nows,
                n_decisions=sum(len(w) for w in windows))
        except Exception:
            if self.native is not None:
                self.native.abort()
            raise
        for t in self.tables:
            t.commit_window()
        self.gtable.commit_window()
        if self.native is not None:
            self.native.commit()

        fused = self._fetch_local_stacked(fused)
        responses: List[List[RateLimitResp]] = []
        for k, lanes in enumerate(all_lanes):
            out, gout = kernel.split_outputs(fused[k], B)
            resp = []
            for s, lane, is_global in lanes:
                o = gout if is_global else out
                resp.append(RateLimitResp(
                    status=int(o.status[s, lane]),
                    limit=int(o.limit[s, lane]),
                    remaining=int(o.remaining[s, lane]),
                    reset_time=int(o.reset_time[s, lane]),
                ))
            responses.append(resp)
        return responses

    def _stage_window_native(self, view, requests, now, accumulate):
        """step_stacked staging with the C router resolving regular keys
        (the native sibling of _stage_requests; must run inside a
        native drain_begin .. commit/abort bracket).  GLOBAL lanes keep the
        Python gtable path as everywhere else."""
        B = self.batch_per_shard
        reg_idx, glob_idx = [], []
        for i, r in enumerate(requests):
            (glob_idx if r.behavior == Behavior.GLOBAL else reg_idx).append(i)
        lanes: List[Optional[tuple]] = [None] * len(requests)

        if reg_idx:
            # single pass over the window: one walk fills the key blob and
            # all four numeric columns (the old per-field list
            # comprehensions re-touched every request object five times)
            n = len(reg_idx)
            keys_b = []
            rhits, rlim, rdur, ralgo = [], [], [], []
            for i in reg_idx:
                r = requests[i]
                keys_b.append(r.hash_key().encode("utf-8"))
                rhits.append(r.hits)
                rlim.append(r.limit)
                rdur.append(r.duration)
                ralgo.append(r.algorithm)
            key_bytes = np.frombuffer(b"".join(keys_b), dtype=np.uint8)
            key_ends = np.cumsum([len(k) for k in keys_b]).astype(np.int64)
            out_shard = np.empty(n, np.int32)
            out_lane = np.empty(n, np.int32)
            shard_fill = np.zeros(self.num_local_shards, np.int32)
            packed = self.native.pack_window(
                key_bytes, key_ends,
                np.asarray(rhits, np.int64),
                np.asarray(rlim, np.int64),
                np.asarray(rdur, np.int64),
                np.asarray(ralgo, np.int32),
                now, B,
                view.slot, view.hits, view.limit, view.duration, view.algo,
                view.is_init.view(np.uint8),
                out_shard, out_lane, shard_fill,
            )
            if packed < n:
                raise ValueError(
                    "stacked window overflows batch_per_shard — size "
                    "windows with max_window_prefix before step_stacked")
            bad = out_shard < 0
            if bad.any():
                r_bad = requests[reg_idx[int(np.argmax(bad))]]
                raise ValueError(
                    f"key {r_bad.hash_key()!r} belongs to shard "
                    f"{shard_of(r_bad.hash_key(), self.num_shards)}, "
                    "not owned by this process")
            for j, i in enumerate(reg_idx):
                lanes[i] = (int(out_shard[j]), int(out_lane[j]), False)

        gcfg_upd: dict = {}
        greset: List[int] = []
        if glob_idx:
            greqs = [requests[i] for i in glob_idx]
            gacc = ([accumulate[i] for i in glob_idx]
                    if accumulate is not None else None)
            glanes, gcfg_upd, greset, _, _ = self._stage_requests(
                view, greqs, now, gacc)
            for (s, lane, is_global), i in zip(glanes, glob_idx):
                lanes[i] = (s, lane, is_global)
        return lanes, gcfg_upd, greset

    def _process_native(
        self,
        requests: Sequence[RateLimitReq],
        now: Optional[int] = None,
        accumulate: Optional[Sequence[bool]] = None,
        upserts: Optional[Sequence] = None,
        columns: Optional[tuple] = None,
    ) -> List[RateLimitResp]:
        """Window processing with the C++ router resolving regular keys.

        One `router_pack` call hashes, routes, and slot-allocates a whole
        window directly into the staging buffers; lane overflow returns a
        partial pack and the loop ships what fit (built-in chunking).  GLOBAL
        keys and upserts are rare control-plane traffic and keep the Python
        gtable path, packed into the same device dispatch.
        """
        now = self._resolve_now(now)
        if upserts and not self._dynamic_global:
            raise ValueError("upserts are not supported in mesh mode "
                             "(GLOBAL state replicates via the in-mesh psum)")
        S = self.num_shards
        B = self.batch_per_shard
        buf = self._buf
        responses: List[Optional[RateLimitResp]] = [None] * len(requests)

        single_chunk_cap = min(
            self.batch_per_shard,
            self.num_local_shards * self.global_batch_per_shard)
        if self.multiprocess and len(requests) > single_chunk_cap:
            # The call may need multiple chunks (worst case: every key lands
            # on one shard), so validate EVERY request's routing before the
            # first dispatch — a mis-routed key discovered in a later chunk
            # would raise after earlier chunks already committed hits
            # (double-count on client retry).  Windows that provably fit one
            # chunk skip this: the C router marks bad keys and the GLOBAL
            # loop checks registration BEFORE that chunk's (only) dispatch,
            # so the lockstep hot path — pre-validated by _take_window —
            # pays no second hashing pass.
            for r in requests:
                err = self.routing_error(r)
                if err is not None:
                    raise ValueError(err)

        # split into regular (columnar) and global (listed) requests —
        # unless the caller already accumulated the window columnarly
        # (RequestColumns), in which case the split is known to be trivial
        # (no GLOBAL lanes) and the columns arrive as zero-copy slices
        glob: List[tuple] = []
        if columns is not None:
            key_bytes, key_ends, c_hits, c_lim, c_dur, c_algo = columns
            nreg = len(key_ends)
            if nreg != len(requests):
                raise ValueError("prebuilt columns must cover every request")
            reg_idx: Sequence[int] = range(nreg)
        else:
            reg_idx = []
            keys_b: List[bytes] = []
            rhits: List[int] = []
            rlim: List[int] = []
            rdur: List[int] = []
            ralgo: List[int] = []
            for i, r in enumerate(requests):
                if r.behavior == Behavior.GLOBAL:
                    glob.append((i, r, accumulate is None or accumulate[i]))
                else:
                    reg_idx.append(i)
                    keys_b.append(r.hash_key().encode("utf-8"))
                    rhits.append(r.hits)
                    rlim.append(r.limit)
                    rdur.append(r.duration)
                    ralgo.append(r.algorithm)
            nreg = len(reg_idx)
            if nreg:
                key_bytes = np.frombuffer(b"".join(keys_b), dtype=np.uint8)
                key_ends = np.cumsum([len(k) for k in keys_b]).astype(np.int64)
                c_hits = np.asarray(rhits, dtype=np.int64)
                c_lim = np.asarray(rlim, dtype=np.int64)
                c_dur = np.asarray(rdur, dtype=np.int64)
                c_algo = np.asarray(ralgo, dtype=np.int32)
        if nreg:
            out_shard = np.zeros(nreg, np.int32)
            out_lane = np.zeros(nreg, np.int32)
        shard_fill = np.zeros(self.num_local_shards, np.int32)

        pending_upserts = list(upserts) if upserts else []
        pos = 0
        gpos = 0
        # Dispatch parity with the Python path: step() always issues exactly
        # one device dispatch per call — including for an EMPTY window.  In
        # mesh mode every process must issue an identical dispatch sequence
        # per lockstep tick (core/batcher.py), so a zero-dispatch empty tick
        # on one host would wedge the collectives cluster-wide.
        first = True
        while first or pos < nreg or gpos < len(glob) or pending_upserts:
            first = False
            buf.reset(self.global_capacity)
            shard_fill[:] = 0
            self.gtable.begin_window()

            ups_chunk = pending_upserts[: self.max_global_updates]
            pending_upserts = pending_upserts[self.max_global_updates:]
            for i, u in enumerate(ups_chunk):
                slot, _ = self.gtable.lookup(u.key, now, u.duration)
                st = u.status
                buf.pslot[i] = slot
                buf.plimit[i] = st.limit
                buf.pduration[i] = u.duration
                buf.premaining[i] = st.remaining
                is_token = u.algorithm == Algorithm.TOKEN_BUCKET
                buf.ptstamp[i] = st.reset_time if is_token else now
                buf.pexpire[i] = st.reset_time if is_token else now + u.duration
                buf.palgo[i] = u.algorithm

            packed = 0
            if pos < nreg:
                base = 0 if pos == 0 else int(key_ends[pos - 1])
                packed = self.native.pack(
                    key_bytes[base:], key_ends[pos:] - base,
                    c_hits[pos:], c_lim[pos:], c_dur[pos:], c_algo[pos:],
                    now, B,
                    buf.slot, buf.hits, buf.limit, buf.duration, buf.algo,
                    buf.is_init.view(np.uint8),
                    out_shard[pos:], out_lane[pos:], shard_fill,
                )
                # mesh mode: the C router marks keys hashing to remote
                # shards; reject BEFORE dispatch (no hits committed)
                bad = out_shard[pos:pos + packed] < 0
                if bad.any():
                    r_bad = requests[reg_idx[pos + int(np.argmax(bad))]]
                    raise ValueError(
                        f"key {r_bad.hash_key()!r} belongs to shard "
                        f"{shard_of(r_bad.hash_key(), S)}, not owned by "
                        "this process")

            # global lanes (python table), bounded by caps; spread
            # round-robin over LOCAL shards (the psum is shard-agnostic)
            glanes: List[tuple] = []
            g_count = 0
            gcfg_upd = {}
            greset: List[int] = []
            while gpos + len(glanes) < len(glob):
                i, r, contribute = glob[gpos + len(glanes)]
                key = r.hash_key()
                if not self._dynamic_global and not self.global_ready(key):
                    raise ValueError(
                        f"GLOBAL key {key!r} is not registered; mesh mode "
                        "registers GLOBAL keys through the registrar")
                if g_count + 1 > self.num_local_shards * self.global_batch_per_shard:
                    break
                if len(gcfg_upd) + 1 > self.max_global_updates:
                    break
                slot, is_init = self.gtable.lookup(key, now, r.duration)
                if contribute and self._dynamic_global:
                    gcfg_upd[slot] = (r.limit, r.duration, r.algorithm)
                    if is_init:
                        greset.append(slot)
                s = g_count % self.num_local_shards
                lane = g_count // self.num_local_shards
                g_count += 1
                buf.gslot[s, lane] = slot
                buf.ghits[s, lane] = r.hits
                buf.ghits_acc[s, lane] = r.hits if contribute else 0
                buf.glimit[s, lane] = r.limit
                buf.gduration[s, lane] = r.duration
                buf.galgo[s, lane] = r.algorithm
                buf.gis_init[s, lane] = is_init
                glanes.append((i, s, lane))
            for j, (slot, cfg) in enumerate(gcfg_upd.items()):
                buf.uslot[j] = slot
                buf.ulimit[j], buf.uduration[j], buf.ualgo[j] = cfg
            for j, slot in enumerate(greset):
                buf.rslot[j] = slot

            if (packed == 0 and not glanes and not ups_chunk
                    and (pos < nreg or gpos < len(glob))):
                raise RuntimeError("window packing made no progress")

            out, gout = self._dispatch(
                now, reg_fill=int(shard_fill.max()) if packed else 0,
                fetch_global=bool(glanes))
            self.native.commit()
            self.gtable.commit_window()
            if packed:
                # vectorized demux: one fancy-indexed gather per field, then
                # plain-python scalars (per-item numpy indexing is ~10x slower)
                sh = out_shard[pos:pos + packed]
                ln = out_lane[pos:pos + packed]
                sts = out.status[sh, ln].tolist()
                lims = out.limit[sh, ln].tolist()
                rems = out.remaining[sh, ln].tolist()
                rsts = out.reset_time[sh, ln].tolist()
                for j, i in enumerate(reg_idx[pos:pos + packed]):
                    responses[i] = RateLimitResp(
                        status=sts[j], limit=lims[j],
                        remaining=rems[j], reset_time=rsts[j],
                    )
            for i, s, lane in glanes:
                responses[i] = RateLimitResp(
                    status=int(gout.status[s, lane]),
                    limit=int(gout.limit[s, lane]),
                    remaining=int(gout.remaining[s, lane]),
                    reset_time=int(gout.reset_time[s, lane]),
                )
            pos += packed
            gpos += len(glanes)
            self.decisions_processed += packed + len(glanes)

        return responses  # type: ignore[return-value]

    def step_windows(
        self,
        batches: WindowBatch,
        gbatches: WindowBatch,
        gaccs,
        upd,
        ups,
        nows,
        compact_safe: bool = False,
        n_decisions: Optional[int] = None,
    ) -> jax.Array:
        """Apply K stacked windows in one device dispatch (see
        _compiled_multi_step).  All arguments carry a leading K dimension
        except upd/ups (control plane, applied ONCE, before window 0) — so
        this equals K sequential step() calls whose first window carries all
        the control-plane writes; callers with upserts destined for a later
        window must split the dispatch at that window.

        Inputs may be numpy or device arrays.  Returns the fused response
        array (i64[K, S, B+Bg, 4], see kernel.pack_outputs) left un-fetched
        so callers can overlap demux with the next dispatch; split it with
        kernel.split_outputs(jax.device_get(fused), batch_per_shard).

        This path performs NO range checks on the stacked lanes (they may be
        resident device arrays), so unless the caller asserts
        `compact_safe=True` — promising every lane satisfies the
        COMPACT_MAX_* ranges — compact dispatch is permanently disabled to
        keep the saturation guard sound (see ops/kernel.py format note).

        Mesh mode: inputs are this process's LOCAL staging blocks
        ([K, S_local, ...]); every process must dispatch in lockstep with
        the SAME K (the stacked executable's shape is part of the
        collective contract) and identical replicated upd/ups/nows.
        """
        if not compact_safe:
            # legacy contract: unscanned stacks conservatively disable
            # compact dispatch for the engine (test_compact_wire pins it)
            self._compact_enabled = False
            if self._compact_sound:
                if isinstance(batches.slot, np.ndarray):
                    # host staging: the real cfg-range scan (occupied
                    # lanes only — the reused stacked buffers carry stale
                    # values in padded lanes).  Keeps _compact_sound
                    # accurate on the mesh lockstep tick path so the
                    # pipeline drain may keep staging compact lanes.
                    occ = batches.slot >= 0
                    dur_cap = np.where(
                        batches.algo == kernel.SLIDING_WINDOW,
                        kernel.SLIDING_MAX_DURATION,
                        kernel.COMPACT_MAX_DURATION)
                    ok = bool((((batches.limit >= 0)
                                & (batches.limit < kernel.COMPACT_MAX_LIMIT)
                                & (batches.duration >= 0)
                                & (batches.duration < dur_cap))
                               | ~occ).all())
                else:
                    ok = False  # resident arrays: unscannable
                if not ok:
                    self._compact_sound = False
        k = int(batches.slot.shape[0])
        if n_decisions is None:
            if (isinstance(batches.slot, np.ndarray)
                    and isinstance(gbatches.slot, np.ndarray)):
                # host staging (counted BEFORE any mesh rebind to sharded
                # arrays): occupied regular + GLOBAL lanes, exactly —
                # matching what process()/step() count for the same traffic
                n_decisions = (int((batches.slot >= 0).sum())
                               + int((gbatches.slot >= 0).sum()))
            else:
                # resident device arrays: the real count isn't host-visible
                # without a fetch — callers with partially-filled resident
                # stacks should pass n_decisions to keep the counter honest
                n_decisions = k * int(np.prod(batches.slot.shape[1:]))
        # Empty-GLOBAL skip: when this stack carries no GLOBAL lanes and
        # the control plane is inert (every slot points one past the
        # arena), dispatch the GLOBAL-skipping twin — same output shape,
        # minus the per-window GLOBAL gathers/scatters/psum.  Two gates:
        #
        #   * static (mesh-legal): the engine was configured skip_global —
        #     a config-level promise of zero GLOBAL traffic, identical on
        #     every process, so the twin IS the collective sequence.
        #     Active GLOBAL lanes under the promise are a caller bug and
        #     raise (host-staged stacks only; resident are unscannable).
        #   * dynamic (single-process only): host-staged inertness picks
        #     the twin per stack.  In mesh mode this choice would depend
        #     on per-process staging and break the collective contract.
        fn = self._multi_fn
        G = self.global_capacity
        inert = (isinstance(gbatches.slot, np.ndarray)
                 and not (gbatches.slot >= 0).any()
                 and (np.asarray(upd[0]) >= G).all()
                 and (np.asarray(upd[4]) >= G).all()
                 and (np.asarray(ups[0]) >= G).all())
        if self._skip_global:
            if isinstance(gbatches.slot, np.ndarray) and not inert:
                raise ValueError(
                    "engine configured skip_global=True received GLOBAL "
                    "lanes or control-plane writes")
            fn = _compiled_multi_step(self.mesh, with_global=False)
        elif not self.multiprocess and inert:
            fn = _compiled_multi_step(self.mesh, with_global=False)
        if self.multiprocess:
            batches = WindowBatch(*[self._sharded_in_stacked(np.asarray(a))
                                    for a in batches])
            gbatches = WindowBatch(*[self._sharded_in_stacked(np.asarray(a))
                                     for a in gbatches])
            gaccs = self._sharded_in_stacked(np.asarray(gaccs))
            upd = tuple(self._repl_in(a) for a in upd)
            ups = tuple(self._repl_in(a) for a in ups)
            nows = self._repl_in(np.asarray(nows, np.int64))
        self.state, fused, self.gstate, self.gcfg = fn(
            self.state, self.gstate, self.gcfg, batches, gbatches, gaccs,
            upd, ups, nows,
        )
        self.windows_processed += k
        self.decisions_processed += n_decisions
        return fused

    def empty_control(self):
        """(gbatch, gacc, upd, ups) padding values for windows that carry no
        GLOBAL traffic — lanes point one past the arena and are dropped."""
        S, Bg, G, Kg = (self.num_shards, self.global_batch_per_shard,
                        self.global_capacity, self.max_global_updates)
        gbatch = WindowBatch(
            slot=np.full((S, Bg), kernel.PAD_SLOT, np.int32),
            hits=np.zeros((S, Bg), np.int64),
            limit=np.zeros((S, Bg), np.int64),
            duration=np.zeros((S, Bg), np.int64),
            algo=np.zeros((S, Bg), np.int32),
            is_init=np.zeros((S, Bg), bool),
        )
        gacc = np.zeros((S, Bg), np.int64)
        upd = (np.full((Kg,), G, np.int32), np.zeros((Kg,), np.int64),
               np.zeros((Kg,), np.int64), np.zeros((Kg,), np.int32),
               np.full((Kg,), G, np.int32))
        ups = (np.full((Kg,), G, np.int32), np.zeros((Kg,), np.int64),
               np.zeros((Kg,), np.int64), np.zeros((Kg,), np.int64),
               np.zeros((Kg,), np.int64), np.zeros((Kg,), np.int64),
               np.zeros((Kg,), np.int32))
        return gbatch, gacc, upd, ups

    def empty_drain_control(self):
        """(gbatch, gacc, upd) padding for a pipeline drain that carries no
        GLOBAL lanes — LOCAL block shapes ([S_local, Bg]), unlike
        empty_control's global ones, because the drain stages per-process
        blocks (pipeline_dispatch_global reshards them).  Lanes point one
        past the arena and are dropped."""
        SL, Bg, G, Kg = (self.num_local_shards, self.global_batch_per_shard,
                         self.global_capacity, self.max_global_updates)
        gbatch = WindowBatch(
            slot=np.full((SL, Bg), kernel.PAD_SLOT, np.int32),
            hits=np.zeros((SL, Bg), np.int64),
            limit=np.zeros((SL, Bg), np.int64),
            duration=np.zeros((SL, Bg), np.int64),
            algo=np.zeros((SL, Bg), np.int32),
            is_init=np.zeros((SL, Bg), bool),
        )
        gacc = np.zeros((SL, Bg), np.int64)
        upd = (np.full((Kg,), G, np.int32), np.zeros((Kg,), np.int64),
               np.zeros((Kg,), np.int64), np.zeros((Kg,), np.int32),
               np.full((Kg,), G, np.int32))
        return gbatch, gacc, upd

    def register_global_keys(self, specs: Sequence[tuple],
                             now: Optional[int] = None,
                             pending: bool = False) -> None:
        """Register GLOBAL limits: (key, limit, duration, algorithm).

        Runs through a COLLECTIVE-FREE replicated executable
        (_compiled_global_register): it only scatters into the replicated
        gstate/gcfg arrays, so in mesh mode each process may run it at its
        own wall time — no lockstep tick needed — provided every process
        applies the IDENTICAL ordered batches with the identical `now`
        (boot preload, or registrar-ordered dynamic batches; see
        core/service.py register_globals).  Until a batch is applied on a
        process, that process has no lanes for the keys, so the slots'
        psum deltas are zero everywhere and replicas cannot diverge.

        pending=True (dynamic mesh registration, phase 1): the keys are
        allocated and configured but NOT yet servable — routing_error keeps
        rejecting them until activate_global_keys (phase 2, issued by the
        registrar only after EVERY process applied phase 1, so no host
        contributes hits to a slot some replica hasn't configured).

        Mesh-determinism guard: in mesh mode registration only ever
        allocates from the free list — when the arena is full it FAILS
        instead of reclaiming, because reclaim/LRU order depends on each
        host's local serving history and would diverge the replicated slot
        assignment.
        """
        now = self._resolve_now(now)
        K = self.max_global_updates
        G = self.global_capacity
        # last-wins dedupe BEFORE staging: duplicate keys would put duplicate
        # indices in one device scatter, whose ordering XLA does not define
        deduped = {key: (key, limit, duration, algorithm)
                   for key, limit, duration, algorithm in specs}
        specs = list(deduped.values())
        if self.multiprocess:
            new = sum(1 for s in specs if s[0] not in self.gtable)
            if len(self.gtable) + new > G:
                raise ValueError(
                    f"GLOBAL arena full ({G} slots): mesh-mode registration "
                    "never reclaims (host-local LRU order would diverge the "
                    "replicated slot assignment); raise global_capacity")
        fn = _compiled_global_register(self.mesh)
        for base in range(0, len(specs), K):
            chunk = specs[base:base + K]
            self.gtable.begin_window()
            uslot = np.full((K,), G, np.int32)
            ulimit = np.zeros((K,), np.int64)
            uduration = np.zeros((K,), np.int64)
            ualgo = np.zeros((K,), np.int32)
            rslot = np.full((K,), G, np.int32)
            r = 0
            for i, (key, limit, duration, algorithm) in enumerate(chunk):
                slot, is_init = self.gtable.lookup(key, now, duration)
                uslot[i] = slot
                ulimit[i] = limit
                uduration[i] = duration
                ualgo[i] = algorithm
                if is_init:
                    rslot[r] = slot
                    r += 1
                if pending:
                    self._gpending.add(key)
            upd = tuple(self._repl_in(a) for a in
                        (uslot, ulimit, uduration, ualgo, rslot))
            self.gstate, self.gcfg = fn(self.gstate, self.gcfg, upd)
            self.gtable.commit_window()

    def activate_global_keys(self, keys: Sequence[str]) -> None:
        """Phase 2 of dynamic mesh registration: begin serving the keys
        (every process has applied their phase-1 arena writes)."""
        self._gpending.difference_update(keys)

    def global_ready(self, key: str) -> bool:
        """Is this GLOBAL hash key servable on this engine right now?"""
        return key in self.gtable and key not in self._gpending

    def warmup(self, now: Optional[int] = None,
               k_stack: Optional[int] = None) -> None:
        """Compile and execute one empty window per serving executable —
        every lane bucket of both wire formats, plus the pipeline's
        stacked-window buckets — so serving never pays a jit stall (a
        cluster's 500ms peer deadline does not survive a mid-serving
        compile).  Mesh mode: pass the cluster-agreed timestamp (every
        process must warm up in lockstep), and the tick's lockstep_stack as
        `k_stack` so the stacked tick executable compiles here too.

        (An empty `process()` call is a no-op on the native path, so callers
        that need the compile — cluster boot, daemon start — use this.)"""
        now = self._resolve_now(now)
        if k_stack is not None and k_stack > 1:
            self.step_stacked([[]], now, k_stack=k_stack)
            # skip_global engines never dispatch the GLOBAL-carrying
            # variant, so there is nothing extra to warm
            if not self.multiprocess and not self._skip_global:
                # the empty warm stack above lowers to the GLOBAL-skipping
                # twin (step_windows inertness gate); execute the
                # GLOBAL-carrying variant on the same inert stack too —
                # identical to the pre-skip warmup dispatch — so the first
                # stacked window with real GLOBAL lanes never pays a
                # mid-serving compile
                K = k_stack
                SL, B = self.num_local_shards, self.batch_per_shard
                gb, ga, upd, ups = self.empty_control()
                stk = lambda a: np.stack([a] * K)  # noqa: E731
                batches = WindowBatch(
                    slot=np.full((K, SL, B), kernel.PAD_SLOT, np.int32),
                    hits=np.zeros((K, SL, B), np.int64),
                    limit=np.zeros((K, SL, B), np.int64),
                    duration=np.zeros((K, SL, B), np.int64),
                    algo=np.zeros((K, SL, B), np.int32),
                    is_init=np.zeros((K, SL, B), bool))
                self.state, _, self.gstate, self.gcfg = \
                    _compiled_multi_step(self.mesh)(
                        self.state, self.gstate, self.gcfg, batches,
                        WindowBatch(*[stk(a) for a in gb]), stk(ga),
                        upd, ups, np.full((K,), now, np.int64))
        # full format compiles only at full width (it is the rare fallback
        # once compact serving is up; each extra shape is a whole XLA
        # compile, which over a tunneled chip costs tens of seconds)
        saved = self._compact_enabled
        self._compact_enabled = False
        self._buf.reset(self.global_capacity)
        self._dispatch(now)
        self._compact_enabled = saved
        if saved:
            for lanes in self._lane_bucket_list:
                self._buf.reset(self.global_capacity)
                self._dispatch(now, reg_fill=lanes)
        if self.native is not None and not self.multiprocess:
            for kb in PIPELINE_K_BUCKETS:
                packed = np.zeros(
                    (kb, self.num_shards, self.batch_per_shard, 2), np.int64)
                _, _, mism = self.pipeline_dispatch(
                    packed, np.full(kb, now, np.int64), n_windows=0)
            jax.device_get(mism)
            if k_stack is not None:
                # lockstep serving (single-process mesh behind a tick
                # clock): the tick's drain is the GLOBAL-composed variant
                # at the tick's fixed shape — the analytics-composed
                # flavor when analytics is wired (that IS the tick
                # executable then; the plain one would never run)
                kb = max(k_stack, 1)
                packed = np.zeros(
                    (kb, self.num_shards, self.batch_per_shard, 2), np.int64)
                gbatch, gacc, upd = self.empty_drain_control()
                out = self.pipeline_dispatch_global(
                    packed, np.full(kb, now, np.int64), gbatch, gacc, upd,
                    n_windows=0,
                    analytics_args=self._warm_analytics_args(kb))
                jax.device_get(out[3])
        elif self.native is not None and self.multiprocess:
            # mesh lockstep drain: ONE fixed shape (the tick's k_stack),
            # dispatched collectively — every process warms it together.
            # The tick drain is the GLOBAL-composed variant (one psum per
            # drain, core/pipeline.py lockstep mode), analytics-composed
            # when analytics is wired.
            kb = max(k_stack or 1, 1)
            packed = np.zeros(
                (kb, self.num_local_shards, self.batch_per_shard, 2),
                np.int64)
            gbatch, gacc, upd = self.empty_drain_control()
            out = self.pipeline_dispatch_global(
                packed, np.full(kb, now, np.int64), gbatch, gacc, upd,
                n_windows=0, analytics_args=self._warm_analytics_args(kb))
            self._fetch_local_stacked(out[2])

    def _warm_analytics_args(self, kb: int):
        """Inert analytics_args for warmup's composed-drain dispatch, or
        None when analytics is not wired (matching the executable the
        lockstep tick will actually use).  Zero tenants + decay=0 leave
        the fresh sketch all-zero."""
        if self._an_conf is None:
            return None
        return (np.zeros((kb, self.num_local_shards, self.batch_per_shard),
                         np.int32), 0)

    def _resolve_now(self, now: Optional[int]) -> int:
        """Default `now` to wall clock — except in mesh mode, where the
        window timestamp is a REPLICATED input: every process must pass the
        same agreed value (e.g. the lockstep clock's tick time), so a
        per-host wall-clock default would silently diverge the replicas."""
        if now is not None:
            return now
        if self.multiprocess:
            raise ValueError(
                "mesh mode requires an explicit, cluster-agreed `now` "
                "per window (the lockstep clock provides one)")
        return millisecond_now()

    def _compact_eligible(self, buf) -> bool:
        """May this window travel in the compact wire format?  Vectorized
        range checks over the staged buffers (padded lanes are zeros and
        always pass).

        A limit/duration violation disables compact dispatch permanently —
        those values persist in the arena and could later saturate a compact
        response.  A hits violation only routes THIS window to the full
        path: hits are consumed, not stored.

        The cfg scan runs even when compact dispatch is already off (mesh
        legacy path): it maintains _compact_sound, which gates what the
        lockstep pipeline drain may STAGE in compact form."""
        if self._compact_sound:
            # sliding-window rows halve the duration cap: the compact
            # lowering's rebased-i32 exactness proof needs
            # now - window_start < 2*duration (ops/kernel.py)
            dur_cap = np.where(buf.algo == kernel.SLIDING_WINDOW,
                               kernel.SLIDING_MAX_DURATION,
                               kernel.COMPACT_MAX_DURATION)
            cfg_ok = (
                bool((buf.limit >= 0).all())
                and bool((buf.limit < kernel.COMPACT_MAX_LIMIT).all())
                and bool((buf.duration >= 0).all())
                and bool((buf.duration < dur_cap).all())
            )
            if not cfg_ok:
                self._compact_enabled = False
                self._compact_sound = False
        if not self._compact_enabled or not self._compact_sound:
            return False
        # concurrency releases carry negative hits, sign-extended through
        # bit 27 of the compact hits field; every other algorithm keeps the
        # full non-negative 28-bit range.  Algorithms outside the 3-bit wire
        # alphabet (0..4) take the full path, where the token fallback is
        # applied without re-encoding.
        conc = buf.algo == kernel.CONCURRENCY
        h_lo = np.where(conc, 1 - kernel.CONC_MAX_HITS, 0)
        h_hi = np.where(conc, kernel.CONC_MAX_HITS, kernel.COMPACT_MAX_HITS)
        return (
            bool(((buf.hits >= h_lo) & (buf.hits < h_hi)).all())
            and bool(((buf.algo >= 0)
                      & (buf.algo <= kernel.CONCURRENCY)).all())
        )

    def _sharded_in(self, local_np):
        """Local [S_local, ...] staging block -> global [S, ...] array."""
        if not self.multiprocess:
            return local_np
        gshape = (self.num_shards,) + local_np.shape[1:]
        return jax.make_array_from_process_local_data(
            self._shard_sharding, local_np, gshape)

    def _sharded_in_stacked(self, local_np):
        """Local [K, S_local, ...] stacked staging -> global [K, S, ...]."""
        if not self.multiprocess:
            return local_np
        from gubernator_tpu.parallel.distributed import stacked_sharding
        gshape = ((local_np.shape[0], self.num_shards) + local_np.shape[2:])
        return jax.make_array_from_process_local_data(
            stacked_sharding(self.mesh), local_np, gshape)

    def _repl_in(self, arr):
        """Replicated input: every process MUST pass identical values."""
        if not self.multiprocess:
            return arr
        arr = np.asarray(arr)
        return jax.make_array_from_process_local_data(
            self._repl_sharding, arr, arr.shape)

    def _fetch_local(self, arr):
        """device_get of this process's shard blocks, in shard order:
        [S_local, ...] (the whole array when single-process)."""
        if not self.multiprocess:
            return jax.device_get(arr)
        shards = sorted(arr.addressable_shards,
                        key=lambda s: s.index[0].start or 0)
        return np.concatenate([np.asarray(s.data) for s in shards], axis=0)

    def _fetch_local_stacked(self, arr):
        """Like _fetch_local for a stacked output [K, S, ...]: this
        process's blocks along the shard axis -> [K, S_local, ...]."""
        if not self.multiprocess:
            return jax.device_get(arr)
        shards = sorted(arr.addressable_shards,
                        key=lambda s: s.index[1].start or 0)
        return np.concatenate([np.asarray(s.data) for s in shards], axis=1)

    def fetch_stacked_many(self, arrs):
        """Fetch several stacked outputs of ONE drain in a single
        device_get.  The pipeline's fetch stage previously issued one
        blocking device_get per plane (words, then mismatch flag, then
        stats) — each is a separate host sync point on the transfer stream;
        batching them into one call lets the runtime coalesce the copies
        (core/pipeline.py `_complete_sync`).

        The guber_fetch annotation is a devprof classification anchor
        (observability/devprof.py): kernels inside it are the D2H copy
        cost, not drain-body time."""
        with jax.profiler.TraceAnnotation("guber_fetch"):
            if not self.multiprocess:
                return jax.device_get(list(arrs))
            return [self._fetch_local_stacked(a) for a in arrs]

    def _lane_bucket(self, max_fill: int) -> int:
        """Occupied-prefix lane width: the smallest compiled lane-bucket
        >= max_fill.  Slicing the staged window to the occupied prefix makes
        the host<->device transfer proportional to occupancy instead of to
        batch_per_shard (a 1000-request window in a 32k-lane engine otherwise
        moves 32x more bytes than it has lanes).  Buckets are powers-of-4
        steps of B so at most 3 executables exist per step family.

        Mesh mode always uses the full width: the bucket choice is
        per-host data-dependent, and hosts picking different executables
        for the same lockstep tick would wedge the collectives."""
        if self.multiprocess:
            return self.batch_per_shard
        for b in self._lane_bucket_list:
            if b >= max_fill:
                return b
        return self.batch_per_shard

    def _dispatch(self, now: int, reg_fill: Optional[int] = None,
                  fetch_global: bool = True):
        """Run the staged buffers through the device step; returns host copies
        of the (regular, global) outputs.

        The transfer is the dominant per-window fixed cost (catastrophically
        so on a tunneled chip; PCIe-bound otherwise), so eligible windows use
        the compact wire format (_compiled_step_compact), slice the regular
        lanes to the occupied-prefix bucket (reg_fill = max per-shard fill;
        None = full width), and skip fetching the GLOBAL output block when the
        window carries no GLOBAL lanes (fetch_global=False -> gout is None).

        `windows_processed` increments immediately after the device call is
        issued — before any fetch/demux — so it counts exactly the dispatches
        the device saw (the lockstep batcher's parity accounting relies on
        this, core/batcher.py).

        In mesh mode every process must call this in lockstep (same dispatch
        sequence), staging its own local lanes; replicated control inputs
        (upd/ups/now) must be identical everywhere."""
        buf = self._buf
        if self._skip_global:
            # same config-level promise as step_stacked's static gate:
            # zero GLOBAL traffic ever reaches a skip_global engine.
            # (warmup dispatches inert buffers with fetch_global=True, so
            # the check scans the staged lanes, not the fetch flag)
            G = self.global_capacity
            if ((buf.gslot >= 0).any() or (buf.uslot < G).any()
                    or (buf.rslot < G).any() or (buf.pslot < G).any()):
                raise ValueError(
                    "engine configured skip_global=True received GLOBAL "
                    "lanes or control-plane writes")
        compact = self._compact_eligible(buf)
        # Occupied-prefix buckets apply only to the compact path: the full
        # format is the rare fallback and warmup compiles it only at full
        # width, so slicing it would trigger a mid-serving XLA compile per
        # bucket shape.
        lanes = (self._lane_bucket(reg_fill)
                 if compact and reg_fill is not None
                 else self.batch_per_shard)
        gbatch = WindowBatch(
            slot=self._sharded_in(buf.gslot), hits=self._sharded_in(buf.ghits),
            limit=self._sharded_in(buf.glimit),
            duration=self._sharded_in(buf.gduration),
            algo=self._sharded_in(buf.galgo),
            is_init=self._sharded_in(buf.gis_init),
        )
        gacc = self._sharded_in(buf.ghits_acc)
        upd = tuple(self._repl_in(a) for a in (
            buf.uslot, buf.ulimit, buf.uduration, buf.ualgo, buf.rslot))
        ups = tuple(self._repl_in(a) for a in (
            buf.pslot, buf.plimit, buf.pduration, buf.premaining,
            buf.ptstamp, buf.pexpire, buf.palgo))
        now_in = self._repl_in(np.int64(now)) if self.multiprocess \
            else jnp.int64(now)
        # SURVEY §5 tracing analog: window dispatches show up as named steps
        # in a jax.profiler trace (GUBER_PROFILE in bench.py, or any
        # profiler session); no-op otherwise
        with jax.profiler.StepTraceAnnotation(
                "guber_window", step_num=self.windows_processed):
            return self._dispatch_inner(buf, compact, lanes, gbatch, gacc,
                                        upd, ups, now, now_in, fetch_global)

    def _dispatch_inner(self, buf, compact, lanes, gbatch, gacc, upd, ups,
                        now, now_in, fetch_global):
        if compact:
            packed = self._sharded_in(kernel.encode_batch_host(
                buf.slot[:, :lanes], buf.hits[:, :lanes],
                buf.limit[:, :lanes], buf.duration[:, :lanes],
                buf.algo[:, :lanes], buf.is_init[:, :lanes]))
            self.state, cword, gfused, self.gstate, self.gcfg = self._compact_fn(
                self.state, self.gstate, self.gcfg, packed, gbatch,
                gacc, upd, ups, now_in,
            )
            self.windows_processed += 1
            out = kernel.decode_output_host(self._fetch_local(cword), now)
            if not fetch_global:
                return out, None
            gfused = self._fetch_local(gfused)
            gout = WindowOutput(
                status=gfused[..., 0], limit=gfused[..., 1],
                remaining=gfused[..., 2], reset_time=gfused[..., 3])
            return out, gout
        batch = WindowBatch(
            slot=self._sharded_in(buf.slot[:, :lanes]),
            hits=self._sharded_in(buf.hits[:, :lanes]),
            limit=self._sharded_in(buf.limit[:, :lanes]),
            duration=self._sharded_in(buf.duration[:, :lanes]),
            algo=self._sharded_in(buf.algo[:, :lanes]),
            is_init=self._sharded_in(buf.is_init[:, :lanes]),
        )
        self.state, fused, self.gstate, self.gcfg = self._step_fn(
            self.state, self.gstate, self.gcfg, batch, gbatch, gacc,
            upd, ups, now_in,
        )
        self.windows_processed += 1
        return kernel.split_outputs(self._fetch_local(fused), lanes)

    # per-engine cache of the compiled stacked-drain executable (the mesh
    # never changes after construction)
    _pipeline_fn = None

    def pipeline_dispatch(self, packed, nows, n_windows: Optional[int] = None):
        """Dispatch a stacked compact drain (core/pipeline.py) WITHOUT
        fetching: K serving windows in one device call, regular keys only
        (GLOBAL traffic needs the control plane + psum and rides the legacy
        step path, serialized on the same executor thread).

        packed: i64[K, S_local, B, 2] compact request stack (numpy or
        resident); nows: i64[K] per-window timestamps.  Returns un-fetched
        device arrays (words i64[K, S, B], limits i64[K, S, B], mism
        bool[K, S]; fetch the local blocks with _fetch_local_stacked):
        the caller overlaps their fetch with the next drain's dispatch and
        reads `limits` only when a mismatch flag fired (see
        kernel.encode_output_word).

        Mesh mode: the drain is part of the lockstep collective contract —
        every process must dispatch it at the same sequence position with
        the SAME K and identical `nows`, every tick, even when its own
        stack is empty (an all-zero stack stages no lanes and is inert).
        Per-host compact ELIGIBILITY never changes the executable: an
        unsound host just stops staging lanes (core/pipeline.py
        lockstep mode) while still issuing the dispatch.
        """
        if self.multiprocess:
            packed = self._sharded_in_stacked(np.ascontiguousarray(packed))
            nows = self._repl_in(np.asarray(nows, np.int64))
        # cache the compiled step on the engine: the lru_cache lookup in
        # _compiled_pipeline_step hashes the mesh on EVERY drain, which is
        # measurable at sub-ms dispatch cadence
        fn = self._pipeline_fn
        if fn is None:
            fn = self._pipeline_fn = _compiled_pipeline_step(self.mesh)
        with jax.profiler.StepTraceAnnotation(
                "guber_drain", step_num=self.windows_processed):
            self.state, words, limits, mism = fn(self.state, packed, nows)
        self.windows_processed += (int(packed.shape[0]) if n_windows is None
                                   else n_windows)
        return words, limits, mism

    def pipeline_dispatch_global(self, packed, nows, gbatch, gacc, upd,
                                 n_windows: Optional[int] = None,
                                 analytics_args=None):
        """The mesh serving drain: pipeline_dispatch's K-window compact
        stack PLUS one GLOBAL window (replica reads + the reconciliation
        psum + config writes), all in ONE device call with ONE collective
        (_compiled_pipeline_step_global).  This is the lockstep tick's
        drain executable — GLOBAL traffic no longer needs the legacy step
        path to reach the mesh.

        packed/nows: as pipeline_dispatch.  gbatch: full-format GLOBAL
        WindowBatch [S_local, Bg] (PAD_SLOT lanes drop); gacc: the psum
        hit contributions i64[S_local, Bg]; upd: the 5-tuple of replicated
        config-update/reset lanes (engine.empty_drain_control provides
        inert padding for all three).  Returns un-fetched (words, limits,
        mism, gfused) — gfused i64[S, Bg, 4] is the GLOBAL response block
        (status/limit/remaining/reset_time; fetch local rows with
        _fetch_local).

        Mesh mode: same lockstep contract as pipeline_dispatch — every
        process dispatches this at the same sequence position with the
        same K and identical nows/upd, every tick, staged lanes or not.

        `analytics_args=(tenants, decay)` composes the per-drain stats
        reduction into THE SAME dispatch (the analytics-geometry variant
        of the composed executable): tenants i32[K, S_local, B] host-staged
        ids, decay the 0/1 halving flag.  Returns an extra `stats`
        i64[S, V] (un-fetched) and updates the resident sketch in place.
        Enablement is config-level, so every mesh process picks the same
        variant — the executable choice never depends on per-tick data."""
        if self.multiprocess:
            packed = self._sharded_in_stacked(np.ascontiguousarray(packed))
            nows = self._repl_in(np.asarray(nows, np.int64))
            gbatch = WindowBatch(*[self._sharded_in(np.asarray(a))
                                   for a in gbatch])
            gacc = self._sharded_in(np.asarray(gacc))
            upd = tuple(self._repl_in(a) for a in upd)
        if analytics_args is not None:
            conf = self._an_conf
            tenants, decay = analytics_args
            if self.multiprocess:
                tenants = self._sharded_in_stacked(
                    np.ascontiguousarray(tenants))
                decay_in = self._repl_in(np.int64(decay))
            else:
                decay_in = jnp.int64(decay)
            fn = _compiled_pipeline_step_global(
                self.mesh, (conf.sketch_depth, conf.sketch_width,
                            conf.tenant_slots, conf.topk, conf.over_weight))
            with jax.profiler.StepTraceAnnotation(
                    "guber_drain", step_num=self.windows_processed):
                (self.state, words, limits, mism, gfused,
                 self.gstate, self.gcfg, self._an_sketch, stats) = fn(
                    self.state, self.gstate, self.gcfg, packed, gbatch,
                    gacc, upd, nows, self._an_sketch, tenants, decay_in)
            self.windows_processed += (int(packed.shape[0])
                                       if n_windows is None else n_windows)
            return words, limits, mism, gfused, stats
        fn = _compiled_pipeline_step_global(self.mesh)
        with jax.profiler.StepTraceAnnotation(
                "guber_drain", step_num=self.windows_processed):
            (self.state, words, limits, mism, gfused,
             self.gstate, self.gcfg) = fn(
                self.state, self.gstate, self.gcfg, packed, gbatch, gacc,
                upd, nows)
        self.windows_processed += (int(packed.shape[0]) if n_windows is None
                                   else n_windows)
        return words, limits, mism, gfused

    # ------------------------------------------------------ traffic analytics
    #
    # The per-drain stats reduction (ops/analytics.py) has two homes:
    #
    #   * the regular (non-lockstep) pipeline runs it as its OWN
    #     executable over the drain's inputs/outputs (analytics_dispatch
    #     below), so the drain builders stay byte-identical whether
    #     analytics is on or off — the disabled serving path is provably
    #     unchanged (tests/test_analytics.py census);
    #   * the lockstep tick composes it INTO the GLOBAL-composed drain
    #     (pipeline_dispatch_global's analytics_args): one dispatch, one
    #     collective-sequence slot, and the reduction reads the drain's
    #     words and post-drain expiry plane in place.  The analytics=None
    #     builder is still byte-identical — composition is a separate
    #     lru_cache entry keyed on the config-level geometry.
    #
    # The reduction is collective-free either way: each shard emits its
    # own stats row and the host merges its local blocks, so the separate
    # executable is safe to dispatch outside the lockstep collective
    # contract, and the composed variant adds no collective to the drain.

    _an_conf = None
    _an_sketch = None

    def enable_analytics(self, conf) -> None:
        """Allocate the resident per-shard count-min sketch and record the
        reduction geometry (config.AnalyticsConfig).  Call once at wiring
        time (core/service.py), before serving starts."""
        self._an_conf = conf
        self._an_sketch = self._put_sharded(
            np.zeros((self.num_local_shards, conf.sketch_depth,
                      conf.sketch_width), np.int64), np.int64)

    def analytics_dispatch(self, packed, words, tenants, now: int,
                           decay: int):
        """Per-drain stats reduction: consume the drain's compact request
        stack (host [K, S_local, B, 2] — re-staged host→device, the cheap
        direction), its resident response words i64[K, S, B], and the
        host-staged tenant lanes i32[K, S_local, B]; update the resident
        sketch in place (donated carry) and return the UN-FETCHED stats
        array i64[S, V] (fetch local rows with _fetch_local, overlapped
        with the drain's own fetch — no extra device→host round trip).
        decay=1 halves the sketch before accumulating (host cadence)."""
        conf = self._an_conf
        if self.multiprocess:
            packed = self._sharded_in_stacked(np.ascontiguousarray(packed))
            tenants = self._sharded_in_stacked(np.ascontiguousarray(tenants))
            now_in = self._repl_in(np.int64(now))
            decay_in = self._repl_in(np.int64(decay))
        else:
            now_in = jnp.int64(now)
            decay_in = jnp.int64(decay)
        fn = _compiled_analytics_reduce(self.mesh, conf.sketch_depth,
                                        conf.sketch_width, conf.tenant_slots,
                                        conf.topk, conf.over_weight)
        # guber_analytics: devprof classification anchor — the standalone
        # reduction's kernels attribute to the analytics arm, not the drain
        with jax.profiler.TraceAnnotation("guber_analytics"):
            self._an_sketch, stats = fn(self._an_sketch, self.state.expire,
                                        packed, words, tenants, now_in,
                                        decay_in)
        return stats

    def process(
        self,
        requests: Sequence[RateLimitReq],
        now: Optional[int] = None,
        accumulate: Optional[Sequence[bool]] = None,
        columns: Optional[tuple] = None,
    ) -> List[RateLimitResp]:
        """step() with automatic chunking when a window overflows the caps.

        `columns` is an optional prebuilt (key_bytes, key_ends, hits, limit,
        duration, algo) tuple covering ALL of `requests` (native path only,
        no GLOBAL requests) — callers that accumulate submissions in
        RequestColumns (core/window_buffers.py) hand over array slices
        instead of having this method re-walk the request objects."""
        if self.native is not None:
            return self._process_native(requests, now, accumulate,
                                        columns=columns)
        S = self.num_shards
        SL = self.num_local_shards
        if self.multiprocess:
            # validate routing BEFORE dispatching anything: a mis-routed key
            # discovered mid-stream would fail requests whose hits earlier
            # chunks already committed (double-count on client retry)
            for r in requests:
                if r.behavior != Behavior.GLOBAL:
                    key = r.hash_key()
                    if not (0 <= shard_of(key, S) - self.local_shard_offset < SL):
                        raise ValueError(
                            f"key {key!r} belongs to shard {shard_of(key, S)}, "
                            "not owned by this process")
        out: List[RateLimitResp] = []
        acc = list(accumulate) if accumulate is not None else [True] * len(requests)
        pos = 0
        while pos < len(requests):
            n = self.max_window_prefix(requests[pos:])
            out.extend(self.step(requests[pos:pos + n], now, acc[pos:pos + n]))
            pos += n
        return out

    def routing_error(self, r: RateLimitReq) -> Optional[str]:
        """Why this request cannot be served by THIS engine, or None.

        Used by the lockstep batcher to fail bad requests individually
        instead of letting a packing exception skip a mesh tick."""
        key = r.hash_key()
        if r.behavior == Behavior.GLOBAL:
            if not self._dynamic_global and not self.global_ready(key):
                return (f"GLOBAL key {key!r} is not registered; mesh mode "
                        "registers GLOBAL keys through the registrar")
            return None
        s = shard_of(key, self.num_shards)
        if not 0 <= s - self.local_shard_offset < self.num_local_shards:
            return (f"key {key!r} belongs to shard {s}, "
                    "not owned by this process")
        return None

    def max_window_prefix(self, requests: Sequence[RateLimitReq]) -> int:
        """How many leading requests fit in ONE step() window (>=1 when any
        are given).  Shared by process() chunking and the lockstep batcher's
        per-tick window assembly.

        Also enforces the replay-bound guard on this FULL-FORMAT path (the
        stacked compact paths enforce it natively — host_router.cc
        rep_track): a NON-uniform duplicate-key run longer than replay_cap
        lanes cuts the window there, so the kernel's per-window replay loop
        stays bounded even for traffic that fell off the compact path
        (e.g. after an out-of-range config permanently disabled it)."""
        S, SL = self.num_shards, self.num_local_shards
        reg_fill = [0] * SL
        g_count = 0
        gkeys: set = set()
        cap = self.replay_cap
        runs: dict = {}  # key -> [first (h,l,d,a), lanes, nonuniform]
        for i, r in enumerate(requests):
            key = r.hash_key()
            if r.behavior == Behavior.GLOBAL:
                new_gkey = 0 if key in gkeys else 1
                if (g_count + 1 > SL * self.global_batch_per_shard
                        or len(gkeys) + new_gkey > self.max_global_updates):
                    return max(i, 1)
                g_count += 1
                gkeys.add(key)
            else:
                s = shard_of(key, S) - self.local_shard_offset
                if not 0 <= s < SL:
                    raise ValueError(
                        f"key {key!r} belongs to shard {shard_of(key, S)}, "
                        "not owned by this process")
                if reg_fill[s] + 1 > self.batch_per_shard:
                    return max(i, 1)
                if cap:
                    tup = (r.hits, r.limit, r.duration, r.algorithm)
                    run = runs.get(key)
                    if run is None:
                        runs[key] = [tup, 1, r.hits == 0]
                    else:
                        run[1] += 1
                        if not run[2] and (tup != run[0] or r.hits == 0):
                            run[2] = True
                        if run[2] and run[1] > cap:
                            return max(i, 1)
                reg_fill[s] += 1
        return len(requests)

    # ---------------------------------------------------------------- metrics

    @property
    def cache_size(self) -> int:
        reg = (self.native.size if self.native is not None
               else sum(len(t) for t in self.tables))
        return reg + len(self.gtable)

    @property
    def cache_hits(self) -> int:
        reg = (self.native.hits if self.native is not None
               else sum(t.hits for t in self.tables))
        return reg + self.gtable.hits

    @property
    def cache_misses(self) -> int:
        reg = (self.native.misses if self.native is not None
               else sum(t.misses for t in self.tables))
        return reg + self.gtable.misses

    def cache_stats(self, now: Optional[int] = None) -> dict:
        """One coherent view of the key-map caches: hit/miss counters plus
        free/live/expired slot occupancy (by the host expiry estimates),
        covering the regular tables AND the GLOBAL table.  Replaces reading
        cache_size/cache_hits/cache_misses piecemeal — a scrape sees one
        consistent set."""
        now = int(now) if now is not None else millisecond_now()
        if self.native is not None:
            live, expired, free = self.native.occupancy(now)
            hits, misses = self.native.hits, self.native.misses
            size = self.native.size
        else:
            hits = sum(t.hits for t in self.tables)
            misses = sum(t.misses for t in self.tables)
            size = sum(len(t) for t in self.tables)
            live = expired = free = 0
            for t in self.tables:
                st = t.stats(now)
                free += st["free"]
                live += st["live"]
                expired += st["expired"]
        g = self.gtable.stats(now)
        return {
            "size": size + len(self.gtable),
            "capacity": (self.num_local_shards * self.capacity_per_shard
                         + self.global_capacity),
            "hits": hits + self.gtable.hits,
            "misses": misses + self.gtable.misses,
            "free": free + g["free"],
            "live": live + g["live"],
            "expired": expired + g["expired"],
        }

    # ------------------------------------------------------- state lifecycle
    #
    # Snapshot/restore and live key migration (state/snapshot.py,
    # state/migrate.py).  Every method here touches the device arenas and
    # the host tables together, so callers MUST quiesce serving first: run
    # them on the same single-thread executor that dispatches windows (the
    # lockstep batcher's), exactly like apply_global_registration.

    def _put_sharded(self, local_np, dtype):
        """Host [S_local, ...] block -> device array with the shard
        sharding (global [S, ...] when the mesh spans processes)."""
        arr = np.ascontiguousarray(local_np, dtype=dtype)
        if self.multiprocess:
            return self._sharded_in(arr)
        return jax.device_put(jnp.asarray(arr), self._shard_sharding)

    def _put_repl(self, arr, dtype):
        """Host [G] array -> replicated device array (every process must
        pass identical values, as with any replicated input)."""
        arr = np.ascontiguousarray(arr, dtype=dtype)
        if self.multiprocess:
            return self._repl_in(arr)
        return jax.device_put(jnp.asarray(arr), self._repl_sharding)

    def export_state(self, now: Optional[int] = None, layout: str = "auto"):
        """Device->host export of this process's arena blocks + key maps as
        an ArenaSnapshot.  `layout` picks the wire time-encoding ("int64" |
        "compact32" | "auto" = compact32 iff the engine is compact-sound);
        serialization falls back to int64 whenever compact32 cannot
        represent the data exactly, so the choice is never lossy."""
        from gubernator_tpu.state.snapshot import ArenaSnapshot, SnapshotError
        now = self._resolve_now(now)
        planes = {n: np.asarray(self._fetch_local(getattr(self.state, n)))
                  for n in BucketState._fields}
        gplanes = {n: np.asarray(jax.device_get(getattr(self.gstate, n)))
                   for n in BucketState._fields}
        gcfg = {n: np.asarray(jax.device_get(getattr(self.gcfg, n)))
                for n in GlobalConfig._fields}

        tables, native_tables = [], []
        if self.native is not None:
            if self.native.exact:
                raise SnapshotError(
                    "exact-keys native router cannot export its key map "
                    "(key bytes are not part of the export format); disable "
                    "GUBER_EXACT_KEYS / EngineConfig.exact_keys to snapshot")
            backend = "native"
            for s in range(self.num_local_shards):
                native_tables.append(self.native.export_keys(s))
        else:
            backend = "python"
            for t in self.tables:
                ents = t.export_entries()
                tables.append((
                    [e[0] for e in ents],
                    np.asarray([e[1] for e in ents], np.int32),
                    np.asarray([e[2] for e in ents], np.int64)))
        gents = self.gtable.export_entries()
        gtable = ([e[0] for e in gents],
                  np.asarray([e[1] for e in gents], np.int32),
                  np.asarray([e[2] for e in gents], np.int64))

        warm = None
        if self._tiers is not None:
            # the warm tier rides the same snapshot: rows exported in
            # canonical int64 absolute form (dumps re-encodes per layout)
            warm = self._tiers.warm.export_rows()

        if layout == "auto":
            layout = "compact32" if self._compact_sound else "int64"
        return ArenaSnapshot(
            now=now, layout=layout, warm=warm,
            num_shards=self.num_shards,
            capacity_per_shard=self.capacity_per_shard,
            global_capacity=self.global_capacity,
            num_local_shards=self.num_local_shards,
            local_shard_offset=self.local_shard_offset,
            compact_sound=self._compact_sound,
            backend=backend,
            planes=planes, gplanes=gplanes, gcfg=gcfg,
            tables=tables, native_tables=native_tables, gtable=gtable,
            gpending=sorted(self._gpending),
        )

    def import_state(self, snap, rebase_to: Optional[int] = None) -> None:
        """Replace the arenas + key maps with a snapshot's contents.

        By default times stay ABSOLUTE: downtime between export and restore
        counts against every TTL, exactly as if the process had kept
        running (restart equivalence vs an uninterrupted oracle).
        `rebase_to` instead shifts every live timestamp by
        (rebase_to - snap.now), preserving each bucket's remaining lifetime
        across a clock-domain change."""
        from gubernator_tpu.state.snapshot import SnapshotError
        for attr in ("num_shards", "capacity_per_shard", "global_capacity",
                     "num_local_shards", "local_shard_offset"):
            if getattr(snap, attr) != getattr(self, attr):
                raise SnapshotError(
                    f"snapshot geometry mismatch: {attr}={getattr(snap, attr)}"
                    f" but engine has {getattr(self, attr)}")
        if snap.backend == "native" and self.native is None:
            raise SnapshotError(
                "snapshot holds a native fingerprint table but this engine "
                "routes in Python; key strings cannot be recovered from "
                "fingerprints")
        if self.native is not None and self.native.exact:
            raise SnapshotError(
                "exact-keys native router cannot import a snapshot key map "
                "(stored keys would stay empty and every lookup would "
                "collide); disable exact_keys to restore")
        shift = 0 if rebase_to is None else int(rebase_to) - snap.now

        def shifted(planes):
            if shift == 0:
                return planes
            out = dict(planes)
            live = planes["expire"] != 0
            for name in ("tstamp", "expire"):
                a = planes[name].copy()
                a[live] += shift
                out[name] = a
            return out

        rp, gp = shifted(snap.planes), shifted(snap.gplanes)
        self.state = BucketState(
            limit=self._put_sharded(rp["limit"], np.int64),
            duration=self._put_sharded(rp["duration"], np.int64),
            remaining=self._put_sharded(rp["remaining"], np.int64),
            tstamp=self._put_sharded(rp["tstamp"], np.int64),
            expire=self._put_sharded(rp["expire"], np.int64),
            algo=self._put_sharded(rp["algo"], np.int32),
        )
        self.gstate = BucketState(
            limit=self._put_repl(gp["limit"], np.int64),
            duration=self._put_repl(gp["duration"], np.int64),
            remaining=self._put_repl(gp["remaining"], np.int64),
            tstamp=self._put_repl(gp["tstamp"], np.int64),
            expire=self._put_repl(gp["expire"], np.int64),
            algo=self._put_repl(gp["algo"], np.int32),
        )
        self.gcfg = GlobalConfig(
            limit=self._put_repl(snap.gcfg["limit"], np.int64),
            duration=self._put_repl(snap.gcfg["duration"], np.int64),
            algo=self._put_repl(snap.gcfg["algo"], np.int32),
        )

        if snap.backend == "native":
            for s in range(self.num_local_shards):
                fp, slots, exps = snap.native_tables[s]
                self.native.import_keys(
                    s, np.asarray(fp, np.uint64), np.asarray(slots, np.int32),
                    np.asarray(exps, np.int64) + shift)
        elif self.native is not None:
            # python-table snapshot into a native-routed engine: recompute
            # the fingerprints the C router would have assigned (same
            # FNV-1a 64, host_router.cc fnv1a64).  Expiry comes from the
            # DEVICE plane, not the table: the Python table's estimate may
            # lag the kernel (leaky hits extend expire on device only),
            # which is harmless under Python routing (the kernel owns lazy
            # expiry) but the native router trusts its host expire at
            # lookup and would spuriously re-init a still-live bucket.
            for s, (keys, slots, exps) in enumerate(snap.tables):
                fp = np.asarray([_fnv1a64(k.encode("utf-8")) for k in keys],
                                np.uint64)
                si = np.asarray(slots, np.int64)
                dev = rp["expire"][s, si] if len(si) else \
                    np.empty(0, np.int64)
                self.native.import_keys(
                    s, fp, np.asarray(slots, np.int32),
                    np.maximum(np.asarray(exps, np.int64) + shift, dev))
        else:
            for t, (keys, slots, exps) in zip(self.tables, snap.tables):
                t.restore_entries(zip(
                    keys, np.asarray(slots, np.int64).tolist(),
                    (np.asarray(exps, np.int64) + shift).tolist()))
        gkeys, gslots, gexps = snap.gtable if snap.gtable else ([], [], [])
        self.gtable.restore_entries(zip(
            gkeys, np.asarray(gslots, np.int64).tolist(),
            (np.asarray(gexps, np.int64) + shift).tolist()))
        self._gpending = set(snap.gpending)
        warm = getattr(snap, "warm", None)
        if self._tiers is not None:
            from gubernator_tpu.state.tiers import WarmStore
            tm = self._tiers
            now_r = self._resolve_now(rebase_to)
            # import replaces ALL key state: rebuild the warm store fresh
            # (new epoch == the restore clock) and re-insert the snapshot's
            # warm rows with the same shift as the arenas
            tm.warm = WarmStore(tm.conf.warm_rows, tm.conf.layout,
                                epoch=now_r)
            tm.pending_spills.clear()
            tm.pending_promos.clear()
            if warm is not None:
                tm.warm.restore_rows(warm[0], warm[1], now=now_r,
                                     shift=shift)
        elif warm is not None and len(warm[0]):
            log.warning(
                "snapshot carries %d warm-tier rows but tiers are disabled "
                "on this engine; dropping them to cold (keys re-init from "
                "request configs)", len(warm[0]))
        if not snap.compact_sound:
            # the snapshotted arena held out-of-range configs; the compact
            # wire could saturate serving them, same guard as the live path
            self._compact_sound = False
            self._compact_enabled = False

    # Live key migration (state/migrate.py) — cluster mode only.  The mesh
    # resizes by re-sharding the arena, not by moving keys, and the native
    # router keeps fingerprints rather than key strings, so the row-level
    # API below requires single-process engines routing in Python.

    def _check_migratable(self) -> None:
        if self.native is not None:
            raise RuntimeError(
                "native router does not retain key strings; live migration "
                "needs the Python tables (EngineConfig use_native=False)")
        if self.multiprocess:
            raise RuntimeError(
                "live key migration applies to cluster mode (one process "
                "per instance); a mesh resizes by re-sharding the arena")

    def local_keys(self) -> List[str]:
        """Every committed regular key resident on this engine."""
        self._check_migratable()
        out: List[str] = []
        for t in self.tables:
            out.extend(k for k in t.keys() if not t.is_pending(k))
        return out

    def global_keys(self) -> List[str]:
        """Every committed GLOBAL key registered on this engine."""
        return [k for k in self.gtable.keys()
                if not self.gtable.is_pending(k)]

    def export_rows(self, keys: Sequence[str]) -> List[dict]:
        """Gather the live device rows for `keys` (regular arena) as host
        dicts.  Keys not resident here, still pending their initializing
        dispatch, or whose device row was never written are skipped."""
        self._check_migratable()
        picks = []
        for key in keys:
            s = shard_of(key, self.num_shards)
            t = self.tables[s]
            slot = t.peek(key)
            if slot is None or t.is_pending(key):
                continue
            picks.append((key, s, slot))
        if not picks:
            return []
        n = len(picks)
        m = _pad_pow2(n)
        si = np.full(m, self.num_shards, np.int32)       # OOB pad -> fill 0
        li = np.full(m, self.capacity_per_shard, np.int32)
        si[:n] = [p[1] for p in picks]
        li[:n] = [p[2] for p in picks]
        got = _gather_rows_jit(self.state, jnp.asarray(si), jnp.asarray(li))
        vals = {f: np.asarray(getattr(got, f))[:n]
                for f in BucketState._fields}
        rows = []
        for j, (key, _s, _slot) in enumerate(picks):
            if vals["expire"][j] == 0:
                continue  # registered but never device-initialized
            rows.append({
                "key": key,
                "limit": int(vals["limit"][j]),
                "duration": int(vals["duration"][j]),
                "remaining": int(vals["remaining"][j]),
                "tstamp": int(vals["tstamp"][j]),
                "expire": int(vals["expire"][j]),
                "algo": int(vals["algo"][j]),
            })
        return rows

    def import_rows(self, rows: Sequence[dict],
                    now: Optional[int] = None) -> tuple:
        """Install migrated regular rows into the local arena.  Returns
        (imported, skipped_stale).

        Init-flag semantics: an incoming row NEVER clobbers a fresher local
        entry.  Fresher means a local pending-init entry (a request already
        arrived here and its slot initializes this window — created after
        the source stopped being authoritative) or a committed local row
        whose device expire >= the incoming row's."""
        self._check_migratable()
        now = self._resolve_now(now)
        skipped = 0
        cand = []
        for row in rows:
            key = row["key"]
            s = shard_of(key, self.num_shards)
            t = self.tables[s]
            if t.is_pending(key):
                skipped += 1
                continue
            cand.append((key, s, t.peek(key), row))
        # one gather for every already-resident key's device expire
        resident = [(i, c[1], c[2]) for i, c in enumerate(cand)
                    if c[2] is not None]
        dev_expire = {}
        if resident:
            n = len(resident)
            m = _pad_pow2(n)
            si = np.full(m, self.num_shards, np.int32)
            li = np.full(m, self.capacity_per_shard, np.int32)
            si[:n] = [r[1] for r in resident]
            li[:n] = [r[2] for r in resident]
            exp = np.asarray(_gather_rows_jit(
                self.state, jnp.asarray(si), jnp.asarray(li)).expire)[:n]
            dev_expire = {r[0]: int(exp[j]) for j, r in enumerate(resident)}
        winners = []
        for i, (key, s, slot, row) in enumerate(cand):
            if i in dev_expire and dev_expire[i] >= row["expire"]:
                skipped += 1
                continue
            winners.append((key, s, row))
        if not winners:
            return 0, skipped
        n = len(winners)
        m = _pad_pow2(n)
        si = np.full(m, self.num_shards, np.int32)      # OOB pad -> dropped
        li = np.full(m, self.capacity_per_shard, np.int32)
        vals = {f: np.zeros(m, np.int64) for f in BucketState._fields}
        for j, (key, s, row) in enumerate(winners):
            si[j] = s
            li[j] = self.tables[s].upsert(key, now, row["expire"])
            for f in BucketState._fields:
                vals[f][j] = row[f]
        self.state = _scatter_rows_jit(
            self.state, jnp.asarray(si), jnp.asarray(li),
            BucketState(**{f: jnp.asarray(vals[f]) for f in
                           BucketState._fields}))
        return n, skipped

    def export_global_rows(self, keys: Sequence[str]) -> List[dict]:
        """Gather GLOBAL rows (replicated arena state + registration
        config) for re-registration on a new owner.  A registered key whose
        state row was never written still exports (expire 0): its CONFIG
        must move for the new owner to serve it."""
        picks = []
        for key in keys:
            slot = self.gtable.peek(key)
            if slot is None or self.gtable.is_pending(key):
                continue
            picks.append((key, slot))
        if not picks:
            return []
        n = len(picks)
        m = _pad_pow2(n)
        gi = np.full(m, self.global_capacity, np.int32)
        gi[:n] = [p[1] for p in picks]
        gst = _gather_grows_jit(self.gstate, jnp.asarray(gi))
        gcf = _gather_gcfg_jit(self.gcfg, jnp.asarray(gi))
        rows = []
        for j, (key, _slot) in enumerate(picks):
            rows.append({
                "key": key,
                "cfg_limit": int(np.asarray(gcf.limit)[j]),
                "cfg_duration": int(np.asarray(gcf.duration)[j]),
                "cfg_algo": int(np.asarray(gcf.algo)[j]),
                **{f: int(np.asarray(getattr(gst, f))[j])
                   for f in BucketState._fields},
            })
        return rows

    def import_global_rows(self, rows: Sequence[dict],
                           now: Optional[int] = None) -> tuple:
        """Register + install migrated GLOBAL rows.  Same staleness rule as
        import_rows; a row with expire 0 registers config only (its state
        row stays dead until traffic initializes it)."""
        now = self._resolve_now(now)
        skipped = 0
        winners = []
        for row in rows:
            key = row["key"]
            if self.gtable.is_pending(key):
                skipped += 1
                continue
            slot = self.gtable.peek(key)
            if slot is not None:
                dev = int(np.asarray(
                    jax.device_get(self.gstate.expire[slot])))
                if dev >= row["expire"] and not (dev == 0
                                                 and row["expire"] == 0):
                    skipped += 1
                    continue
            winners.append(row)
        if not winners:
            return 0, skipped
        n = len(winners)
        m = _pad_pow2(n)
        gi = np.full(m, self.global_capacity, np.int32)
        svals = {f: np.zeros(m, np.int64) for f in BucketState._fields}
        cvals = {f: np.zeros(m, np.int64) for f in GlobalConfig._fields}
        for j, row in enumerate(winners):
            est = row["expire"] if row["expire"] else now + row["cfg_duration"]
            gi[j] = self.gtable.upsert(row["key"], now, est)
            for f in BucketState._fields:
                svals[f][j] = row[f]
            cvals["limit"][j] = row["cfg_limit"]
            cvals["duration"][j] = row["cfg_duration"]
            cvals["algo"][j] = row["cfg_algo"]
            self._gpending.discard(row["key"])
        gij = jnp.asarray(gi)
        self.gstate = _scatter_grows_jit(
            self.gstate, gij,
            BucketState(**{f: jnp.asarray(svals[f])
                           for f in BucketState._fields}))
        self.gcfg = _scatter_gcfg_jit(
            self.gcfg, gij,
            GlobalConfig(**{f: jnp.asarray(cvals[f])
                            for f in GlobalConfig._fields}))
        return n, skipped

    def remove_keys(self, keys: Sequence[str]) -> int:
        """Drop regular keys from the host tables after they migrated away.
        The device rows become dead tenants: slot reuse re-initializes them
        (is_init), and routing no longer sends these keys here."""
        self._check_migratable()
        removed = 0
        for key in keys:
            s = shard_of(key, self.num_shards)
            if key in self.tables[s]:
                self.tables[s].remove(key)
                removed += 1
        return removed

    # --------------------------------------------------------- tiered state
    #
    # Warm tier (state/tiers.py): the fixed arena becomes a managed cache
    # over an unbounded keyspace.  Demotion rides SlotTable._reclaim via
    # the spill hook; promotion happens in _stage_requests; both resolve in
    # ONE batched gather + scatter at the pre-dispatch fence below.  All of
    # it runs on the dispatch thread (same quiesce contract as migration).

    def enable_tiers(self, conf, analytics=None,
                     epoch: Optional[int] = None):
        """Install the warm tier.  Requires Python routing tables and a
        single-process engine — the same constraint as live key migration
        (the native router keeps fingerprints, not key strings, and a mesh
        resizes by re-sharding rather than spilling).  `epoch` anchors the
        warm store's compact32 pair-rebase domain (defaults to now)."""
        from gubernator_tpu.state.tiers import TierManager
        self._check_migratable()
        if conf.warm_rows <= 0:
            raise ValueError(
                "enable_tiers needs warm capacity (GUBER_TIER_WARM > 0); "
                "warm_rows=0 means tiers stay off")
        t = TierManager(conf, epoch=self._resolve_now(epoch),
                        analytics=analytics)
        self._tiers = t
        for s, table in enumerate(self.tables):
            table.spill_cb = (
                lambda key, slot, expire, stale, _s=s:
                t.on_spill(_s, key, slot, expire, stale))
            table.heat_fn = t.heat
            table.victim_sample = conf.victim_sample
        return t

    def tier_stats(self) -> Optional[dict]:
        """Tier counters + warm occupancy for /metrics and cli debug;
        None when tiers are off."""
        return None if self._tiers is None else self._tiers.stats()

    def _tier_fence(self, now: int) -> None:
        """Resolve every demotion/promotion pending since the last dispatch
        — BEFORE this window's dispatch, while the victims' device rows are
        still intact and so the promoted rows are resident when the kernel
        reads them.  One gather + one scatter per window regardless of how
        many keys moved; spill rows found dead or expired on device drop to
        cold (the kernel's lazy expiry already treats them as misses, so
        the infinite-arena oracle would re-init them too)."""
        t = self._tiers
        t.fences += 1
        if t.analytics is not None and t.fences % 256 == 0:
            t.refresh_heat()
        spills, promos = t.drain_pending()
        if not spills and not promos:
            return
        # one gather covers the spills AND the from-spill promotion sources
        gather = [(k, sh, sl) for k, sh, sl in spills]
        src_ix = {}
        for key, p in promos:
            if p[3] is not None:
                src_ix[key] = len(gather)
                gather.append((key, p[3][0], p[3][1]))
        vals = None
        if gather:
            n = len(gather)
            m = _pad_pow2(n)
            si = np.full(m, self.num_shards, np.int32)   # OOB pad -> fill 0
            li = np.full(m, self.capacity_per_shard, np.int32)
            si[:n] = [g[1] for g in gather]
            li[:n] = [g[2] for g in gather]
            got = _gather_rows_jit(self.state, jnp.asarray(si),
                                   jnp.asarray(li))
            vals = {f: np.asarray(getattr(got, f))[:n]
                    for f in BucketState._fields}
        puts = []
        for j, (key, _sh, _sl) in enumerate(spills):
            if vals["expire"][j] <= now:
                # dead (never written) or already expired on device: cold
                t.counters["demote_dropped_expired"] += 1
                continue
            row = {f: int(vals[f][j]) for f in BucketState._fields}
            row["key"] = key
            puts.append(row)
        if puts:
            t.warm.put_batch(puts, now)
            t.counters["demotions"] += len(puts)
        if promos:
            rows = []
            for key, p in promos:
                if p[3] is not None:
                    j = src_ix[key]
                    row = {f: int(vals[f][j]) for f in BucketState._fields}
                    row["key"] = key
                    row["rel"] = False
                else:
                    row = p[2]
                rows.append((p[0], p[1], row))
            t.decode_rows([r for _, _, r in rows])
            n = len(rows)
            m = _pad_pow2(n)
            si = np.full(m, self.num_shards, np.int32)   # OOB pad -> dropped
            li = np.full(m, self.capacity_per_shard, np.int32)
            svals = {f: np.zeros(m, np.int64) for f in BucketState._fields}
            for j, (sh, sl, row) in enumerate(rows):
                si[j] = sh
                li[j] = sl
                for f in BucketState._fields:
                    svals[f][j] = row[f]
            self.state = _scatter_rows_jit(
                self.state, jnp.asarray(si), jnp.asarray(li),
                BucketState(**{f: jnp.asarray(svals[f])
                               for f in BucketState._fields}))
            t.counters["promotions"] += n

    def tier_maintain(self, now: Optional[int] = None) -> int:
        """Proactive demotion between windows: shards running above the
        demote watermark spill their coldest committed entries to warm in
        one batch, so staging under a full arena pays fence-time spills
        instead of per-lookup forced evictions.  Also refreshes the heat
        map from analytics.  Returns entries demoted or dropped."""
        if self._tiers is None:
            return 0
        t = self._tiers
        now = self._resolve_now(now)
        t.refresh_heat()
        if t.pending_spills or t.pending_promos:
            # a staging pass aborted before its dispatch: resolve the
            # leftovers first (their device rows are still pre-dispatch)
            self._tier_fence(now)
        hi = int(t.conf.demote_watermark * self.capacity_per_shard)
        picks = []
        for s, table in enumerate(self.tables):
            excess = len(table) - hi
            if excess <= 0:
                continue
            take = min(excess, t.conf.demote_batch)
            scanned = 0
            for key in table.keys():              # LRU order, oldest first
                if take <= 0 or scanned >= 4 * t.conf.demote_batch:
                    break
                scanned += 1
                if table.is_pending(key) or t.heat(key) > 0.0:
                    continue                      # hot by analytics: keep
                picks.append((key, s, table.peek(key)))
                take -= 1
        if not picks:
            return 0
        n = len(picks)
        m = _pad_pow2(n)
        si = np.full(m, self.num_shards, np.int32)
        li = np.full(m, self.capacity_per_shard, np.int32)
        si[:n] = [p[1] for p in picks]
        li[:n] = [p[2] for p in picks]
        got = _gather_rows_jit(self.state, jnp.asarray(si), jnp.asarray(li))
        vals = {f: np.asarray(getattr(got, f))[:n]
                for f in BucketState._fields}
        puts = []
        for j, (key, s, _slot) in enumerate(picks):
            self.tables[s].remove(key)
            if vals["expire"][j] <= now:
                t.counters["demote_dropped_expired"] += 1
                continue
            row = {f: int(vals[f][j]) for f in BucketState._fields}
            row["key"] = key
            puts.append(row)
        if puts:
            t.warm.put_batch(puts, now)
            t.counters["demotions"] += len(puts)
        return n

    def tier_warmup(self, max_rows: int = 512) -> None:
        """Pre-compile the fence's gather/scatter pow2 ladder up to
        `max_rows` so serving never pays the jit stall mid-window (the
        same contract as warmup(); the helpers compile per padded shape).
        All-OOB indices make every dispatch a no-op on the arena."""
        if self._tiers is None:
            return
        m = 8
        while m <= _pad_pow2(max_rows):
            si = jnp.full(m, self.num_shards, jnp.int32)
            li = jnp.full(m, self.capacity_per_shard, jnp.int32)
            got = _gather_rows_jit(self.state, si, li)
            zeros = BucketState(**{f: jnp.zeros(m, jnp.int64)
                                   for f in BucketState._fields})
            self.state = _scatter_rows_jit(self.state, si, li, zeros)
            jax.block_until_ready(got)
            m *= 2


def _pad_pow2(n: int) -> int:
    """Pad gather/scatter index vectors to a power of two (>= 8) so the
    jitted helpers compile for a handful of shapes, not one per call."""
    return max(8, 1 << (n - 1).bit_length())


def _fnv1a64(data: bytes) -> int:
    """FNV-1a 64 over key bytes — bit-identical to host_router.cc fnv1a64,
    for restoring a Python-table snapshot into a native-routed engine.
    The seed below is the router's literal constant, NOT the textbook FNV
    offset basis (the .cc drops the basis's last digit); what matters here
    is agreeing with the fingerprints the C side assigns, so mirror the
    code, not the spec.  0 is remapped to 1 (0 marks an empty table cell)."""
    h = 1469598103934665603
    for b in data:
        h ^= b
        h = (h * 1099511628211) & 0xFFFFFFFFFFFFFFFF
    return h if h else 1


@jax.jit
def _gather_rows_jit(state: BucketState, si, li) -> BucketState:
    # OOB padded indices read as 0 (mode="fill"); callers slice them off
    return jax.tree.map(
        lambda a: a.at[si, li].get(mode="fill", fill_value=0), state)


@jax.jit
def _scatter_rows_jit(state: BucketState, si, li, vals) -> BucketState:
    return jax.tree.map(
        lambda a, v: a.at[si, li].set(v.astype(a.dtype), mode="drop"),
        state, vals)


@jax.jit
def _gather_grows_jit(gstate: BucketState, gi) -> BucketState:
    return jax.tree.map(
        lambda a: a.at[gi].get(mode="fill", fill_value=0), gstate)


@jax.jit
def _scatter_grows_jit(gstate: BucketState, gi, vals) -> BucketState:
    return jax.tree.map(
        lambda a, v: a.at[gi].set(v.astype(a.dtype), mode="drop"),
        gstate, vals)


@jax.jit
def _gather_gcfg_jit(gcfg: GlobalConfig, gi) -> GlobalConfig:
    return jax.tree.map(
        lambda a: a.at[gi].get(mode="fill", fill_value=0), gcfg)


@jax.jit
def _scatter_gcfg_jit(gcfg: GlobalConfig, gi, vals) -> GlobalConfig:
    return jax.tree.map(
        lambda a, v: a.at[gi].set(v.astype(a.dtype), mode="drop"),
        gcfg, vals)


def _use_pallas() -> bool:
    """Opt-in Pallas lowering (GUBER_PALLAS=1) for the window kernel and
    the GLOBAL apply pass (ops/pallas_kernel.py).  Read at trace time —
    i.e. once per mesh, when each executable family builds."""
    from gubernator_tpu.config import env_bool
    return env_bool("GUBER_PALLAS", False)


def _use_compact32_xla() -> bool:
    """Default-on rebased-int32 XLA math for compact call sites
    (GUBER_COMPACT32_XLA=0 reverts to the int64 kernel).  Same read-at-
    build-time discipline as _use_pallas: the flag is part of each
    compiled builder's cache key, never read mid-trace."""
    from gubernator_tpu.config import env_bool
    return env_bool("GUBER_COMPACT32_XLA", True)


def _use_pallas_fused() -> bool:
    """Opt-in FUSED Pallas serving window (GUBER_PALLAS_FUSED=1): the whole
    compact window — decode, sort, segment prep, transitions, commit,
    response encode — as ONE pallas_call (ops/pallas_kernel.py
    window_step_fused) instead of the ~hundreds of executed kernels the
    compact32-XLA drain lowers to.  Default off; adopted by bench.py's
    parity-gated A/B.  Same read-at-build-time discipline as _use_pallas.
    Takes precedence over GUBER_PALLAS at compact call sites; full-format
    call sites are unaffected (their lanes may exceed the rebase range)."""
    from gubernator_tpu.ops.pallas_kernel import fused_enabled
    return fused_enabled(False)


def _use_pallas_staged() -> bool:
    """Default-on STAGED drain lowering (GUBER_PALLAS_STAGED=0 reverts to
    the K-scan of single-window megakernels): with the fused megakernel
    enabled, the pipeline drain's K windows run as ONE pallas_call with a
    K-major grid dimension (the arena carried across grid steps through
    the aliased planes) and the GLOBAL sub-window's transition ladder runs
    as one pair-arithmetic kernel — the composed drain traces to O(1)
    kernels total instead of K pallas_calls plus the scan/staging/GLOBAL
    shoulders.  No effect unless GUBER_PALLAS_FUSED is on.  Same
    read-at-build-time discipline as _use_pallas: part of each compiled
    builder's cache key, never read mid-trace."""
    from gubernator_tpu.config import env_bool
    return env_bool("GUBER_PALLAS_STAGED", True)


def _recursion_guarded(fn):
    """Wrap a compiled executable so every call runs under the Mosaic
    recursion-limit guard (ops/pallas_kernel.py mosaic_recursion_guard).

    Real-Mosaic lowering of the big fused window jaxpr recurses deeper than
    CPython's default 1000 frames, and jax lowers lazily — at the FIRST CALL
    of the jitted object, not at jit() time — so the guard must wrap the
    call site.  Scoping it here (instead of the old module-import
    setrecursionlimit side effect) keeps the process global untouched for
    every embedder that never runs the Pallas path."""
    from functools import wraps

    from gubernator_tpu.ops.pallas_kernel import mosaic_recursion_guard

    @wraps(fn)
    def guarded(*args, **kwargs):
        with mosaic_recursion_guard():
            return fn(*args, **kwargs)

    return guarded


def _window_step_fn(mesh: Mesh, compact32: bool, pallas: bool,
                    c32xla: bool):
    """kernel.window_step, or its Pallas lowering under GUBER_PALLAS=1
    (interpret mode when the MESH's devices are CPU — Mosaic is TPU-only,
    and the process default backend may differ from the mesh platform).

    compact32 marks call sites whose lanes are guaranteed inside the
    compact wire-format ranges (the pipeline drain): there the Pallas
    kernel runs in rebased int32, which is the ONLY form Mosaic accepts
    on real TPU (no 64-bit vector types).  Without Pallas those call
    sites run the SAME rebased-int32 math as plain XLA by default
    (window_step_compact32_xla, c32xla): TPU XLA emulates int64
    arithmetic as i32-pair ops, so the int64 ladder pays roughly double
    the math op count for no benefit inside the compact ranges.
    Full-format call sites keep the int64 kernel — their lanes can
    exceed the rebase range.

    `pallas`/`c32xla` are REQUIRED and threaded from the compiled-builder
    cache keys so a jit object built under one env setting cannot trace
    under another; an env-reading default here would reintroduce the
    trace-time read the cache keys exist to eliminate."""
    if pallas:
        from functools import partial

        from gubernator_tpu.ops.pallas_kernel import window_step_pallas
        on_cpu = _mesh_on_cpu(mesh)
        if compact32:
            return partial(window_step_pallas, interpret=on_cpu,
                           compact32=True)
        if on_cpu:
            return partial(window_step_pallas, interpret=True)
        return kernel.window_step
    if compact32 and c32xla:
        from gubernator_tpu.ops.pallas_kernel import (
            window_step_compact32_xla,
        )
        return window_step_compact32_xla
    return kernel.window_step


def _mesh_on_cpu(mesh: Mesh) -> bool:
    return mesh.devices.flat[0].platform == "cpu"


def _apply_control(gstate: BucketState, gcfg: GlobalConfig, upd, ups):
    """Apply host control-plane writes to the GLOBAL arena (once per dispatch).

    Upserts land first: authoritative replica state pushed by a cross-host
    owner (the reference's UpdatePeerGlobals -> Cache.Add path,
    gubernator.go:199-207).  Then host-issued slot (re)configurations: the
    config write refreshes limit/duration/algorithm from the latest request
    each window (the reference owner applies the config carried on each
    aggregated request, global.go:115-153); the state reset (expire=0 reads
    as never-initialized) happens only for lanes the host just (re)allocated.
    """
    (pslot, plimit, pduration, premaining, ptstamp, pexpire, palgo) = ups
    gstate = BucketState(
        limit=gstate.limit.at[pslot].set(plimit, mode="drop"),
        duration=gstate.duration.at[pslot].set(pduration, mode="drop"),
        remaining=gstate.remaining.at[pslot].set(premaining, mode="drop"),
        tstamp=gstate.tstamp.at[pslot].set(ptstamp, mode="drop"),
        expire=gstate.expire.at[pslot].set(pexpire, mode="drop"),
        algo=gstate.algo.at[pslot].set(palgo, mode="drop"),
    )
    gcfg = GlobalConfig(
        limit=gcfg.limit.at[pslot].set(plimit, mode="drop"),
        duration=gcfg.duration.at[pslot].set(pduration, mode="drop"),
        algo=gcfg.algo.at[pslot].set(palgo, mode="drop"),
    )
    return _apply_config(gstate, gcfg, upd)


def _apply_config(gstate: BucketState, gcfg: GlobalConfig, upd):
    """The host-issued slot-(re)configuration half of _apply_control: the
    config write refreshes limit/duration/algorithm from the latest request
    each window; the state reset (expire=0 reads as never-initialized)
    happens only for lanes the host just (re)allocated.  The pipeline
    drain's GLOBAL window applies ONLY this half — drains never carry
    upserts (mesh mode forbids them outright, and the single-process
    batcher routes them through step())."""
    uslot, ulimit, uduration, ualgo, rslot = upd
    gcfg = GlobalConfig(
        limit=gcfg.limit.at[uslot].set(ulimit, mode="drop"),
        duration=gcfg.duration.at[uslot].set(uduration, mode="drop"),
        algo=gcfg.algo.at[uslot].set(ualgo, mode="drop"),
    )
    gstate = gstate._replace(
        expire=gstate.expire.at[rslot].set(jnp.int64(0), mode="drop")
    )
    return gstate, gcfg


def _global_window(gstate: BucketState, gcfg: GlobalConfig, gb: WindowBatch,
                   gacc_row, now, mesh: Mesh, pallas: bool,
                   staged: bool = False):
    """One window of GLOBAL traffic: replica reads + the reconciliation psum.

    The whole GLOBAL dance — the reference's async hit send plus owner
    broadcast (global.go:72-232) — is this one collective.  The read and
    apply halves share one transition ladder (kernel.global_combined):
    reads see the pre-apply replica either way, so concatenating the lane
    sets halves the sub-window's executed kernels without changing a bit.
    """
    delta = kernel.global_accumulate(
        jnp.zeros_like(gstate.remaining), gb._replace(hits=gacc_row)
    )
    summed = lax.psum(delta, SHARD_AXIS)
    if staged:
        # The whole read+apply transition ladder as ONE pallas_call: the
        # i64 arena crosses as bitcast (lo, hi) i32 pairs (Mosaic has no
        # 64-bit vectors) and the ladder runs in exact pair arithmetic;
        # only the leaky path's two integer divisions stay in XLA
        # (kernel.transition_precompute) — they depend solely on pre-psum
        # data, so hoisting them is bit-free.  fused_out: the read half
        # comes back as the wire's gfused block i64[Bg, 4] directly.
        from gubernator_tpu.ops.pallas_kernel import global_combined_staged
        return global_combined_staged(gstate, gcfg, gb, summed, now,
                                      interpret=_mesh_on_cpu(mesh),
                                      fused_out=True)
    # Pallas GLOBAL apply only in interpret mode (CPU meshes/tests): the
    # kernel is int64 and Mosaic has no 64-bit vectors on real TPU, and
    # unlike the serving window the GLOBAL arena is EXEMPT from the
    # compact range caps (core/engine.py _compiled_step_compact note),
    # so a rebased-i32 form would not be exact — XLA serves the TPU path.
    if pallas and _mesh_on_cpu(mesh):
        from gubernator_tpu.ops.pallas_kernel import global_apply_pallas
        gout = kernel.global_read(gstate, gb, now)
        new_g = global_apply_pallas(
            gstate, gcfg, summed, now, interpret=True)
        return new_g, gout
    return kernel.global_combined(gstate, gcfg, gb, summed, now)


def _compiled_step(mesh: Mesh):
    return _compiled_step_impl(mesh, _use_pallas())


@lru_cache(maxsize=None)
def _compiled_step_impl(mesh: Mesh, pallas: bool):
    def shard_fn(state, gstate, gcfg, batch, gbatch, gacc, upd, ups, now):
            # Block shapes inside shard_map: state [1, C]; batch/gbatch [1, B*];
            # gstate/gcfg [G] (replicated); upd/ups [K*] (replicated).
            st = BucketState(*jax.tree.map(lambda a: a[0], state))
            bt = WindowBatch(*jax.tree.map(lambda a: a[0], batch))
            new_st, out = _window_step_fn(mesh, compact32=False, pallas=pallas,
                                      c32xla=False)(st, bt, now)

            gstate, gcfg = _apply_control(gstate, gcfg, upd, ups)
            gb = WindowBatch(*jax.tree.map(lambda a: a[0], gbatch))
            new_g, gout = _global_window(gstate, gcfg, gb, gacc[0], now, mesh, pallas)

            expand = lambda a: a[None]
            return (
                BucketState(*jax.tree.map(expand, new_st)),
                kernel.pack_outputs(out, gout)[None],
                new_g,
                gcfg,
            )

    state_sharded = BucketState(*[P(SHARD_AXIS)] * 6)
    state_repl = BucketState(*[P()] * 6)
    sharded = _compat_shard_map(
        shard_fn,
        mesh=mesh,
        # the Pallas window kernel cannot carry vma tags through its
        # interpret-mode while_loop (jnp.take drops them); vma checking is
        # an XLA-path-only invariant here
        check_vma=not pallas,
        in_specs=(
            state_sharded,
            state_repl,
            GlobalConfig(*[P()] * 3),
            WindowBatch(*[P(SHARD_AXIS)] * 6),
            WindowBatch(*[P(SHARD_AXIS)] * 6),
            P(SHARD_AXIS),
            (P(), P(), P(), P(), P()),
            (P(),) * 7,
            P(),
        ),
        out_specs=(
            state_sharded,
            P(SHARD_AXIS),
            state_repl,
            GlobalConfig(*[P()] * 3),
        ),
    )
    fn = jax.jit(sharded, donate_argnums=(0, 1, 2))
    return _recursion_guarded(fn) if pallas else fn


def _compiled_step_compact(mesh: Mesh):
    return _compiled_step_compact_impl(mesh, _use_pallas(),
                                       _use_compact32_xla(),
                                       _use_pallas_fused())


@lru_cache(maxsize=None)
def _compiled_step_compact_impl(mesh: Mesh, pallas: bool,
                                c32xla: bool, fused: bool = False):
    """The serving fast path: compact request/response wire format.

    Same computation as _compiled_step, but the regular-key window crosses
    host<->device packed (kernel.decode_batch / encode_output_compact — 16B
    up + 8B down per lane instead of ~41B + 32B), cutting the per-window
    transfer cost ~3x.  GLOBAL lanes keep the full format: they are few
    (Bg ≈ 128) and their stored state may carry configs that predate the
    host's range checks, so they are exempt from compact saturation rules.
    """
    def shard_fn(state, gstate, gcfg, packed, gbatch, gacc, upd, ups, now):
        st = BucketState(*jax.tree.map(lambda a: a[0], state))
        # The fused megakernel's in-kernel bitonic sort needs a power-of-two
        # lane count; other widths fall back to the compact32-XLA drain at
        # trace time (B is static).
        B = packed.shape[-2]
        if fused and (B & (B - 1)) == 0:
            from gubernator_tpu.ops.pallas_kernel import window_step_fused
            new_st, words, limits, _ = window_step_fused(
                st, packed[0], now, interpret=_mesh_on_cpu(mesh))
            enc = jnp.stack([words, limits], axis=-1)
        else:
            bt = kernel.decode_batch(packed[0])
            new_st, out = _window_step_fn(mesh, compact32=True,
                                          pallas=pallas,
                                          c32xla=c32xla)(st, bt, now)
            enc = kernel.encode_output_compact(out, now)

        gstate, gcfg = _apply_control(gstate, gcfg, upd, ups)
        gb = WindowBatch(*jax.tree.map(lambda a: a[0], gbatch))
        new_g, gout = _global_window(gstate, gcfg, gb, gacc[0], now, mesh, pallas)

        expand = lambda a: a[None]
        gfused = jnp.stack(
            [gout.status.astype(jnp.int64), gout.limit, gout.remaining,
             gout.reset_time], axis=-1)
        return (
            BucketState(*jax.tree.map(expand, new_st)),
            enc[None],
            gfused[None],
            new_g,
            gcfg,
        )

    state_sharded = BucketState(*[P(SHARD_AXIS)] * 6)
    state_repl = BucketState(*[P()] * 6)
    sharded = _compat_shard_map(
        shard_fn,
        mesh=mesh,
        # the Pallas window kernel cannot carry vma tags through its
        # interpret-mode while_loop (jnp.take drops them); vma checking is
        # an XLA-path-only invariant here
        check_vma=not (pallas or fused),
        in_specs=(
            state_sharded,
            state_repl,
            GlobalConfig(*[P()] * 3),
            P(SHARD_AXIS),
            WindowBatch(*[P(SHARD_AXIS)] * 6),
            P(SHARD_AXIS),
            (P(), P(), P(), P(), P()),
            (P(),) * 7,
            P(),
        ),
        out_specs=(
            state_sharded,
            P(SHARD_AXIS),
            P(SHARD_AXIS),
            state_repl,
            GlobalConfig(*[P()] * 3),
        ),
    )
    fn = jax.jit(sharded, donate_argnums=(0, 1, 2))
    return _recursion_guarded(fn) if (pallas or fused) else fn


@lru_cache(maxsize=None)
def _compiled_global_register(mesh: Mesh):
    """GLOBAL registration writes into the replicated arena — deliberately
    COLLECTIVE-FREE (pure scatters on fully-replicated arrays), so mesh
    processes may execute it at different wall times without wedging the
    lockstep: there is nothing to synchronize.  Correctness across hosts
    comes from every process applying identical registrar-ordered batches
    (see RateLimitEngine.register_global_keys)."""
    repl6 = BucketState(*[NamedSharding(mesh, P())] * 6)
    repl3 = GlobalConfig(*[NamedSharding(mesh, P())] * 3)

    def fn(gstate: BucketState, gcfg: GlobalConfig, upd):
        uslot, ulimit, uduration, ualgo, rslot = upd
        gcfg = GlobalConfig(
            limit=gcfg.limit.at[uslot].set(ulimit, mode="drop"),
            duration=gcfg.duration.at[uslot].set(uduration, mode="drop"),
            algo=gcfg.algo.at[uslot].set(ualgo, mode="drop"),
        )
        # expire=0 reads as never-initialized: a freshly (re)allocated slot
        # must not inherit its previous tenant's live counters
        gstate = gstate._replace(
            expire=gstate.expire.at[rslot].set(jnp.int64(0), mode="drop"))
        return gstate, gcfg

    return jax.jit(fn, donate_argnums=(0, 1),
                   out_shardings=(repl6, repl3))


def _compiled_pipeline_step(mesh: Mesh):
    return _compiled_pipeline_step_impl(mesh, _use_pallas(),
                                        _use_compact32_xla(),
                                        _use_pallas_fused(),
                                        _use_pallas_staged())


@lru_cache(maxsize=None)
def _compiled_pipeline_step_impl(mesh: Mesh, pallas: bool,
                                 c32xla: bool, fused: bool = False,
                                 staged: bool = False):
    """K compact serving windows in ONE device dispatch — the drain
    executable of the serving pipeline (core/pipeline.py).

    Differences from _compiled_multi_step, all in service of making the
    response transfer as small and as late-bound as possible (on a remote/
    tunneled chip the fetch round trip IS the serving cost; on PCIe it still
    bounds small-window latency):

      * regular keys only — GLOBAL traffic needs the psum + control-plane
        writes and rides the legacy step path instead, so this executable
        carries zero GLOBAL inputs and outputs;
      * requests arrive in the compact 16B/lane format (kernel.decode_batch)
        and responses leave as ONE 8B word per lane (encode_output_word);
      * the response's `limit` field (stored limit, which on hit paths can
        differ from the request's) is NOT shipped per lane: the host echoes
        the request limit and fetches the device-side limit plane only when
        a window's mismatch flag fires (config changed on a live bucket —
        rare).

    The reference analog of the stacking is a peer draining its queue
    back-to-back without waiting for each response (peers.go:143-172).
    """
    def shard_fn(state, packed, nows):
        # Block shapes: state [1, C]; packed [K, 1, B, 2]; nows [K].
        st = BucketState(*jax.tree.map(lambda a: lax.squeeze(a, (0,)),
                                       state))
        st, words, limits, mism, _ = _drain_scan(mesh, pallas, c32xla, fused,
                                                 staged, st, packed, nows)
        expand = lambda a: a[None]
        return (
            BucketState(*jax.tree.map(expand, st)),
            words[:, None],
            limits[:, None],
            mism[:, None],
        )

    state_sharded = BucketState(*[P(SHARD_AXIS)] * 6)
    stackedP = stacked_spec()
    sharded = _compat_shard_map(
        shard_fn,
        mesh=mesh,
        # the Pallas window kernel cannot carry vma tags through its
        # interpret-mode while_loop (jnp.take drops them); vma checking is
        # an XLA-path-only invariant here
        check_vma=not (pallas or fused),
        in_specs=(state_sharded, stackedP, P()),
        out_specs=(state_sharded, stackedP, stackedP, stackedP),
    )
    fn = jax.jit(sharded, donate_argnums=(0,))
    return _recursion_guarded(fn) if (pallas or fused) else fn


def _drain_scan(mesh: Mesh, pallas: bool, c32xla: bool, fused: bool,
                staged: bool, st: BucketState, packed, nows,
                tenants=None, tenant_slots: int = 0):
    """The drain's regular-key K windows (shared by the regular and the
    GLOBAL-composed drain executables): K compact windows applied
    sequentially to one shard's block, each window's decode→transition→
    word-encode either fused into ONE pallas_call or lowered per-op by
    compact32-XLA.  With `staged` the K windows collapse further: the
    lax.scan of single-window megakernels becomes ONE pallas_call with a
    K-major grid dimension whose aliased plane outputs carry the arena
    across grid steps — the drain traces to a single kernel.  When
    `tenants` is given (staged only), the per-drain analytics reductions
    (dense/tenant/header sums) accumulate inside that same kernel and
    come back as `dstats` (see ops/analytics.py staged_stats_tail).
    Returns (state, words[K,B], limits[K,B], mism[K], dstats-or-None)."""
    # Fused megakernel needs a power-of-two lane count for its in-kernel
    # bitonic sort; other widths fall back to compact32-XLA (B static).
    B = packed.shape[-2]
    use_fused = fused and (B & (B - 1)) == 0
    use_staged = use_fused and staged

    if use_staged:
        from gubernator_tpu.ops.pallas_kernel import (
            fused_state_from_planes,
            fused_state_to_planes,
            window_drain_fused_planes,
        )
        st32, words, limits, mism, dstats = window_drain_fused_planes(
            fused_state_to_planes(st), lax.squeeze(packed, (1,)), nows,
            interpret=_mesh_on_cpu(mesh),
            tenants=tenants, tenant_slots=tenant_slots)
        return fused_state_from_planes(st32), words, limits, mism, dstats

    def body(st, xs):
        pk, now = xs
        bt = kernel.decode_batch(pk[0])
        st, out = _window_step_fn(mesh, compact32=True, pallas=pallas,
                                  c32xla=c32xla)(st, bt, now)
        word = kernel.encode_output_word(out, now)
        mism = jnp.any((out.limit != bt.limit) & (bt.slot >= 0))
        return st, (word, out.limit, mism)

    if use_fused:
        # decode, sort, prep, transitions, commit AND the word encode
        # all happen inside ONE pallas_call per window — O(1) executed
        # kernels instead of the XLA drain's per-op launches.  The
        # arena converts to its i32 plane form ONCE per drain and the
        # scan carries that form, so the O(C) conversion amortizes
        # over all K windows.
        from gubernator_tpu.ops.pallas_kernel import (
            fused_state_from_planes,
            fused_state_to_planes,
            window_step_fused_planes,
        )
        on_cpu = _mesh_on_cpu(mesh)

        def body32(st32, xs):
            pk, now = xs
            st32, word, limit, mism = window_step_fused_planes(
                st32, pk[0], now, interpret=on_cpu)
            return st32, (word, limit, mism)

        st32, (words, limits, mism) = lax.scan(
            body32, fused_state_to_planes(st), (packed, nows))
        st = fused_state_from_planes(st32)
    else:
        st, (words, limits, mism) = lax.scan(body, st, (packed, nows))
    return st, words, limits, mism, None


@lru_cache(maxsize=None)
def _compiled_analytics_reduce(mesh: Mesh, depth: int, width: int,
                               tenant_slots: int, topk: int,
                               over_weight: int):
    """The traffic-analytics reduction (ops/analytics.py shard_stats) as a
    collective-free shard_map'd executable: per shard, fold one drain's
    (packed, words, tenants) into the resident count-min sketch (donated
    carry) and emit one flat stats row.  Deliberately NOT part of the
    drain builders: keyed only on geometry, it composes unchanged with
    every drain lowering (compact32-XLA, fused Pallas, GLOBAL-composed
    mesh) and leaves their jaxprs byte-identical when analytics is off."""
    from gubernator_tpu.ops import analytics as ops_analytics

    def shard_fn(sketch, expire, packed, words, tenants, now, decay):
        # Block shapes: sketch [1, D, W]; expire [1, C]; packed
        # [K, 1, B, 2]; words [K, 1, B]; tenants [K, 1, B]; now/decay [].
        sk, stats = ops_analytics.shard_stats(
            sketch[0], packed[:, 0], words[:, 0], tenants[:, 0], expire[0],
            now, decay, tenant_slots=tenant_slots, topk=topk,
            over_weight=over_weight)
        return sk[None], stats[None]

    sharded = _compat_shard_map(
        shard_fn,
        mesh=mesh,
        in_specs=(P(SHARD_AXIS), P(SHARD_AXIS), stacked_spec(),
                  stacked_spec(), stacked_spec(), P(), P()),
        out_specs=(P(SHARD_AXIS), P(SHARD_AXIS)),
    )
    return jax.jit(sharded, donate_argnums=(0,))


def _compiled_pipeline_step_global(mesh: Mesh, analytics=None):
    return _compiled_pipeline_step_global_impl(mesh, _use_pallas(),
                                               _use_compact32_xla(),
                                               _use_pallas_fused(),
                                               _use_pallas_staged(),
                                               analytics)


@lru_cache(maxsize=None)
def _compiled_pipeline_step_global_impl(mesh: Mesh, pallas: bool,
                                        c32xla: bool, fused: bool = False,
                                        staged: bool = False,
                                        analytics=None):
    """The mesh serving drain: _compiled_pipeline_step's K-scan PLUS one
    GLOBAL reconciliation window composed around it — the lockstep tick's
    single executable.

    Every chip runs the fused (or compact32-XLA) kernel per window over
    its own plane-arena shard, and the whole drain pays exactly ONE
    collective: the GLOBAL hit-delta psum of `_global_window`, applied
    once at the drain's timestamp (nows[0]; the lockstep tick stages all
    K windows at the tick time, so there is nothing later to order
    against).  This replaces the legacy mesh path's per-stage kernels and
    per-window psum — the drain's cost model becomes
    (K pallas_calls + one GLOBAL window) / K windows, against the legacy
    step's ~hundreds of launches per window.

    GLOBAL lanes keep the FULL wire format (they are few — Bg per shard —
    and exempt from the compact saturation rules); the control plane is
    the upd 5-tuple only (config refresh + reallocation resets): drains
    never carry upserts.  Donation covers the sharded arena and the
    replicated GLOBAL arena/config, so planes are carried, not copied,
    across ticks.

    `analytics` (None or the geometry 5-tuple (sketch_depth, sketch_width,
    tenant_slots, topk, over_weight)) composes the per-drain stats
    reduction (ops/analytics.py shard_stats) INTO this executable: the
    reduction reads the drain's own packed stack, its response words and
    the post-drain expiry plane IN PLACE — no second dispatch, no second
    executable in the tick's collective sequence.  With analytics=None the
    traced body is byte-identical to the pre-analytics builder (the
    analytics-off serving path is provably unchanged); the geometry is
    config-level and identical on every process, so the executable choice
    is mesh-legal."""
    def shard_fn(state, gstate, gcfg, packed, gbatch, gacc, upd, nows, *an):
        # Block shapes: state [1, C]; packed [K, 1, B, 2]; gbatch/gacc
        # [1, Bg]; gstate/gcfg [G] (replicated); upd [Kg] (replicated);
        # nows [K]; analytics extras: sketch [1, D, W]; tenants [K, 1, B];
        # decay [].
        # Squeezes, not [0]-indexing: each a[0] traces as slice+squeeze (2
        # census equations per leaf) where squeeze alone is 1 — the staged
        # ladder's budget counts every surviving op, and the shard_map
        # block-unpack glue is most of what remains around the kernels.
        sq = lambda a: lax.squeeze(a, (0,))
        sq1 = lambda a: lax.squeeze(a, (1,))
        st = BucketState(*jax.tree.map(sq, state))
        # With staged analytics the drain kernel itself accumulates the
        # dense/tenant/header sums (dstats) while it drains — the stats
        # tail below then only runs the one-kernel sketch/top-k finish.
        drain_tenants, drain_slots = None, 0
        if analytics is not None and staged:
            drain_tenants, drain_slots = sq1(an[1]), analytics[2]
        st, words, limits, mism, dstats = _drain_scan(
            mesh, pallas, c32xla, fused, staged, st, packed, nows,
            tenants=drain_tenants, tenant_slots=drain_slots)

        gstate, gcfg = _apply_config(gstate, gcfg, upd)
        gb = WindowBatch(*jax.tree.map(sq, gbatch))
        new_g, gout = _global_window(gstate, gcfg, gb, sq(gacc), nows[0],
                                     mesh, pallas, staged=staged)
        # staged hands back the gfused wire block straight from the kernel
        gfused = gout if staged else jnp.stack(
            [gout.status.astype(jnp.int64), gout.limit, gout.remaining,
             gout.reset_time], axis=-1)

        expand = lambda a: a[None]
        outs = (
            BucketState(*jax.tree.map(expand, st)),
            words[:, None],
            limits[:, None],
            mism[:, None],
            gfused[None],
            new_g,
            gcfg,
        )
        if analytics is not None:
            _, _, tenant_slots, topk, over_weight = analytics
            sketch, tenants, decay = an
            if dstats is not None:
                from gubernator_tpu.ops.pallas_kernel import (
                    staged_stats_finish,
                )
                sk, stats = staged_stats_finish(
                    sq(sketch), dstats, st.expire, nows[0], decay,
                    tenant_slots=tenant_slots, topk=topk,
                    over_weight=over_weight,
                    interpret=_mesh_on_cpu(mesh))
            else:
                from gubernator_tpu.ops import analytics as ops_analytics
                sk, stats = ops_analytics.shard_stats(
                    sq(sketch), sq1(packed), words, sq1(tenants), st.expire,
                    nows[0], decay, tenant_slots=tenant_slots, topk=topk,
                    over_weight=over_weight)
            outs = outs + (sk[None], stats[None])
        return outs

    state_sharded = BucketState(*[P(SHARD_AXIS)] * 6)
    state_repl = BucketState(*[P()] * 6)
    stackedP = stacked_spec()
    in_specs = (
        state_sharded,
        state_repl,
        GlobalConfig(*[P()] * 3),
        stackedP,
        WindowBatch(*[shard_spec()] * 6),
        shard_spec(),
        (P(), P(), P(), P(), P()),
        P(),
    )
    out_specs = (
        state_sharded,
        stackedP,
        stackedP,
        stackedP,
        shard_spec(),
        state_repl,
        GlobalConfig(*[P()] * 3),
    )
    donate = (0, 1, 2)
    if analytics is not None:
        in_specs = in_specs + (P(SHARD_AXIS), stackedP, P())
        out_specs = out_specs + (P(SHARD_AXIS), P(SHARD_AXIS))
        donate = donate + (8,)  # the resident sketch is a carried plane
    sharded = _compat_shard_map(
        shard_fn,
        mesh=mesh,
        # the Pallas window kernel cannot carry vma tags through its
        # interpret-mode while_loop (jnp.take drops them); vma checking is
        # an XLA-path-only invariant here
        check_vma=not (pallas or fused),
        in_specs=in_specs,
        out_specs=out_specs,
    )
    fn = jax.jit(sharded, donate_argnums=donate)
    return _recursion_guarded(fn) if (pallas or fused) else fn


def _compiled_multi_step(mesh: Mesh, with_global: bool = True):
    return _compiled_multi_step_impl(mesh, _use_pallas(), with_global)


@lru_cache(maxsize=None)
def _compiled_multi_step_impl(mesh: Mesh, pallas: bool,
                              with_global: bool = True):
    """K batching windows applied in ONE device dispatch via lax.scan.

    Each scanned iteration is a full serving window — its own timestamp, its
    own in-window sequencing, its own GLOBAL psum — identical in semantics to
    K sequential `_compiled_step` calls.  What it saves is K-1 host→device
    dispatch round trips: on a tunneled/remote chip the round trip (~200µs)
    dominates the ~25µs window compute, so scanning windows is the throughput
    path when the host has a backlog (the reference analog: a peer draining
    its queue ships batches back-to-back without waiting for each response,
    peers.go:143-172).

    Control-plane writes (GLOBAL upserts/config, host-rare) are applied once,
    before the first window.  Stacked inputs carry a leading K dimension;
    `nows` is i64[K], one timestamp per window.

    `with_global=False` compiles the GLOBAL-skipping variant: most stacked
    dispatches carry ZERO GLOBAL lanes and inert control (every slot points
    one past the arena), yet the composed executable still ran the whole
    GLOBAL sub-window — gathers, scatters and a psum per scanned iteration
    — just to produce an all-dropped output block.  Statically skipping it
    removes those kernels per window (the round-5 calibration showed the
    window cost is per-executed-kernel overhead); the fused output keeps
    its [K, B+Bg, 4] shape (GLOBAL rows zero-filled) so every decode path
    is unchanged.  step_windows picks the variant from host-staged
    inertness, single-process only — a per-process data-dependent
    executable choice would break the mesh collective contract.
    """
    def shard_fn(state, gstate, gcfg, batches, gbatches, gaccs, upd, ups, nows):
        # Block shapes: state [1, C]; batches [K, 1, B]; gbatches [K, 1, Bg];
        # gaccs [K, 1, Bg]; gstate/gcfg [G] replicated; nows [K].
        st = BucketState(*jax.tree.map(lambda a: a[0], state))
        if with_global:
            gstate, gcfg = _apply_control(gstate, gcfg, upd, ups)

        def body(carry, xs):
            st, gst = carry
            b, gb, gacc, now = xs
            bt = WindowBatch(*jax.tree.map(lambda a: a[0], b))
            st, out = _window_step_fn(mesh, compact32=False, pallas=pallas,
                                      c32xla=False)(st, bt, now)
            if not with_global:
                o = jnp.stack([out.status.astype(jnp.int64), out.limit,
                               out.remaining, out.reset_time], axis=-1)
                Bg = gb.slot.shape[-1]
                fused = jnp.concatenate(
                    [o, jnp.zeros((Bg, 4), jnp.int64)], axis=0)
                return (st, gst), fused
            gbt = WindowBatch(*jax.tree.map(lambda a: a[0], gb))
            gst, gout = _global_window(gst, gcfg, gbt, gacc[0], now, mesh, pallas)
            return (st, gst), kernel.pack_outputs(out, gout)

        (st, gst), fused = lax.scan(
            body, (st, gstate), (batches, gbatches, gaccs, nows)
        )
        expand = lambda a: a[None]
        # fused: [K, B+Bg, 4] -> [K, 1, B+Bg, 4] so the shard axis is explicit
        return (
            BucketState(*jax.tree.map(expand, st)),
            fused[:, None],
            gst,
            gcfg,
        )

    state_sharded = BucketState(*[P(SHARD_AXIS)] * 6)
    state_repl = BucketState(*[P()] * 6)
    stackedP = P(None, SHARD_AXIS)
    sharded = _compat_shard_map(
        shard_fn,
        mesh=mesh,
        # the Pallas window kernel cannot carry vma tags through its
        # interpret-mode while_loop (jnp.take drops them); vma checking is
        # an XLA-path-only invariant here
        check_vma=not pallas,
        in_specs=(
            state_sharded,
            state_repl,
            GlobalConfig(*[P()] * 3),
            WindowBatch(*[stackedP] * 6),
            WindowBatch(*[stackedP] * 6),
            stackedP,
            (P(), P(), P(), P(), P()),
            (P(),) * 7,
            P(),
        ),
        out_specs=(
            state_sharded,
            stackedP,
            state_repl,
            GlobalConfig(*[P()] * 3),
        ),
    )
    fn = jax.jit(sharded, donate_argnums=(0, 1, 2))
    return _recursion_guarded(fn) if pallas else fn
