"""The rate-limit engine: host routing + one sharded device step per window.

This is the TPU-native collapse of three reference components:

  * the owner's batch drain (gubernator.go:210-227) → `window_step` per shard;
  * the consistent-hash peer routing (hash.go:80-96, gubernator.go:114) →
    `crc32(key) % num_shards` choosing the mesh-axis shard, resolved on the
    host while packing the window;
  * the GLOBAL async-hits + broadcast dance (global.go:72-232) → one
    `lax.psum` of per-slot hit deltas over the mesh axis, after which the
    authoritative state is already resident on every shard.

One call to `step()` plays the role of one 500µs batching window being shipped
to the owner (peers.go:176-207): the host packs per-shard request lanes into
dense arrays, the device applies them in a single jitted shard_map step, and
the responses demux back by lane index.

State layout: regular (sharded) keys live in BucketState arrays of shape
[S, C] partitioned over the "shard" mesh axis; GLOBAL keys live in a
replicated [G] arena whose updates flow only through the psum so replicas stay
bit-exact.  Host-side key→slot tables (state/arena.py) are per shard.
"""

from __future__ import annotations

import zlib
from functools import partial
from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from gubernator_tpu.api.types import (
    Algorithm,
    Behavior,
    RateLimitReq,
    RateLimitResp,
    millisecond_now,
)
from gubernator_tpu.ops import kernel
from gubernator_tpu.ops.kernel import (
    BucketState,
    GlobalConfig,
    WindowBatch,
    WindowOutput,
)
from gubernator_tpu.parallel.mesh import SHARD_AXIS, make_mesh
from gubernator_tpu.state.arena import SlotTable


def shard_of(key: str, num_shards: int) -> int:
    """Map a hash key to its owning shard.

    Same hash family as the reference's ring (crc32 IEEE, hash.go:41) but a
    plain modulus: mesh shards are homogeneous and resize by re-sharding the
    arena, so ring semantics (minimal movement on membership change) buy
    nothing inside a mesh.
    """
    return zlib.crc32(key.encode("utf-8")) % num_shards


class _PackedWindow:
    """Host-side staging buffers for one window (numpy, reused per step)."""

    def __init__(self, S: int, B: int, Bg: int, Kg: int):
        self.slot = np.full((S, B), kernel.PAD_SLOT, dtype=np.int32)
        self.hits = np.zeros((S, B), dtype=np.int64)
        self.limit = np.zeros((S, B), dtype=np.int64)
        self.duration = np.zeros((S, B), dtype=np.int64)
        self.algo = np.zeros((S, B), dtype=np.int32)
        self.is_init = np.zeros((S, B), dtype=bool)
        self.gslot = np.full((S, Bg), kernel.PAD_SLOT, dtype=np.int32)
        self.ghits = np.zeros((S, Bg), dtype=np.int64)
        self.glimit = np.zeros((S, Bg), dtype=np.int64)
        self.gduration = np.zeros((S, Bg), dtype=np.int64)
        self.galgo = np.zeros((S, Bg), dtype=np.int32)
        self.gis_init = np.zeros((S, Bg), dtype=bool)
        self.uslot = np.zeros((Kg,), dtype=np.int32)
        self.ulimit = np.zeros((Kg,), dtype=np.int64)
        self.uduration = np.zeros((Kg,), dtype=np.int64)
        self.ualgo = np.zeros((Kg,), dtype=np.int32)
        self.rslot = np.zeros((Kg,), dtype=np.int32)

    def reset(self, G: int):
        self.slot.fill(kernel.PAD_SLOT)
        self.gslot.fill(kernel.PAD_SLOT)
        self.ghits.fill(0)
        # pad config-update/reset lanes point one past the global arena → dropped
        self.uslot.fill(G)
        self.rslot.fill(G)


class RateLimitEngine:
    """Dense sharded rate-limit state + one jitted device step per window.

    capacity_per_shard: slots per shard (reference default cache size is
        50k per node, cache/lru.go:50; ours defaults to 64k per shard).
    batch_per_shard: max regular-key request lanes per shard per window.
    global_capacity: slots in the replicated GLOBAL arena.
    global_batch_per_shard: max GLOBAL request lanes per shard per window.
    """

    def __init__(
        self,
        mesh: Optional[Mesh] = None,
        capacity_per_shard: int = 65536,
        batch_per_shard: int = 1024,
        global_capacity: int = 4096,
        global_batch_per_shard: int = 256,
        max_global_updates: int = 256,
    ):
        self.mesh = mesh if mesh is not None else make_mesh()
        self.num_shards = int(np.prod(list(self.mesh.shape.values())))
        self.capacity_per_shard = capacity_per_shard
        self.batch_per_shard = batch_per_shard
        self.global_capacity = global_capacity
        self.global_batch_per_shard = global_batch_per_shard
        self.max_global_updates = max_global_updates

        S, C, G = self.num_shards, capacity_per_shard, global_capacity
        shard_sharding = NamedSharding(self.mesh, P(SHARD_AXIS))
        repl_sharding = NamedSharding(self.mesh, P())

        def sharded_zeros(shape, dtype, sharding):
            return jax.device_put(jnp.zeros(shape, dtype), sharding)

        self.state = BucketState(
            limit=sharded_zeros((S, C), jnp.int64, shard_sharding),
            duration=sharded_zeros((S, C), jnp.int64, shard_sharding),
            remaining=sharded_zeros((S, C), jnp.int64, shard_sharding),
            tstamp=sharded_zeros((S, C), jnp.int64, shard_sharding),
            expire=sharded_zeros((S, C), jnp.int64, shard_sharding),
            algo=sharded_zeros((S, C), jnp.int32, shard_sharding),
        )
        self.gstate = BucketState(
            limit=sharded_zeros((G,), jnp.int64, repl_sharding),
            duration=sharded_zeros((G,), jnp.int64, repl_sharding),
            remaining=sharded_zeros((G,), jnp.int64, repl_sharding),
            tstamp=sharded_zeros((G,), jnp.int64, repl_sharding),
            expire=sharded_zeros((G,), jnp.int64, repl_sharding),
            algo=sharded_zeros((G,), jnp.int32, repl_sharding),
        )
        self.gcfg = GlobalConfig(
            limit=sharded_zeros((G,), jnp.int64, repl_sharding),
            duration=sharded_zeros((G,), jnp.int64, repl_sharding),
            algo=sharded_zeros((G,), jnp.int32, repl_sharding),
        )

        self.tables = [SlotTable(C) for _ in range(S)]
        self.gtable = SlotTable(G)
        self._buf = _PackedWindow(S, batch_per_shard, global_batch_per_shard, max_global_updates)
        self._step_fn = self._build_step()
        self.windows_processed = 0
        self.decisions_processed = 0

    # ------------------------------------------------------------------ device

    def _build_step(self):
        mesh = self.mesh

        def shard_fn(state, gstate, gcfg, batch, gbatch, upd, now):
            # Block shapes inside shard_map: state [1, C]; batch [1, B];
            # gstate/gcfg [G] (replicated); upd [Kg] (replicated).
            st = BucketState(*jax.tree.map(lambda a: a[0], state))
            bt = WindowBatch(*jax.tree.map(lambda a: a[0], batch))
            new_st, out = kernel.window_step(st, bt, now)

            # Apply host-issued GLOBAL slot (re)configurations.  The config
            # write refreshes limit/duration/algorithm from the latest request
            # each window (the reference owner applies the config carried on
            # each aggregated request, global.go:115-153); the state reset
            # (expire=0 reads as never-initialized) happens only for lanes the
            # host just (re)allocated.
            uslot, ulimit, uduration, ualgo, rslot = upd
            gcfg = GlobalConfig(
                limit=gcfg.limit.at[uslot].set(ulimit, mode="drop"),
                duration=gcfg.duration.at[uslot].set(uduration, mode="drop"),
                algo=gcfg.algo.at[uslot].set(ualgo, mode="drop"),
            )
            gstate = gstate._replace(
                expire=gstate.expire.at[rslot].set(jnp.int64(0), mode="drop")
            )

            gb = WindowBatch(*jax.tree.map(lambda a: a[0], gbatch))
            gout = kernel.global_read(gstate, gb, now)
            delta = kernel.global_accumulate(jnp.zeros_like(gstate.remaining), gb)
            # The whole GLOBAL reconciliation — the reference's async hit send
            # plus owner broadcast (global.go:72-232) — is this one collective.
            summed = lax.psum(delta, SHARD_AXIS)
            new_g = kernel.global_apply(gstate, gcfg, summed, now)

            expand = lambda a: a[None]
            return (
                BucketState(*jax.tree.map(expand, new_st)),
                WindowOutput(*jax.tree.map(expand, out)),
                new_g,
                gcfg,
                WindowOutput(*jax.tree.map(expand, gout)),
            )

        sharded = jax.shard_map(
            shard_fn,
            mesh=mesh,
            in_specs=(
                jax.tree.map(lambda _: P(SHARD_AXIS), self.state),
                jax.tree.map(lambda _: P(), self.gstate),
                jax.tree.map(lambda _: P(), self.gcfg),
                WindowBatch(*[P(SHARD_AXIS)] * 6),
                WindowBatch(*[P(SHARD_AXIS)] * 6),
                (P(), P(), P(), P(), P()),
                P(),
            ),
            out_specs=(
                jax.tree.map(lambda _: P(SHARD_AXIS), self.state),
                WindowOutput(*[P(SHARD_AXIS)] * 4),
                jax.tree.map(lambda _: P(), self.gstate),
                jax.tree.map(lambda _: P(), self.gcfg),
                WindowOutput(*[P(SHARD_AXIS)] * 4),
            ),
        )
        return jax.jit(sharded, donate_argnums=(0, 1, 2))

    # ------------------------------------------------------------------- host

    def step(
        self, requests: Sequence[RateLimitReq], now: Optional[int] = None
    ) -> List[RateLimitResp]:
        """Process one window of requests synchronously.

        Caller must respect the window caps (use `process` for auto-chunking):
        per-shard regular lanes <= batch_per_shard, per-shard GLOBAL lanes <=
        global_batch_per_shard, distinct GLOBAL keys <= max_global_updates.
        """
        if now is None:
            now = millisecond_now()
        S = self.num_shards
        buf = self._buf
        buf.reset(self.global_capacity)

        reg_fill = [0] * S
        glob_fill = [0] * S
        # slot -> (limit, duration, algo): latest request's config wins within
        # the window (deduped host-side — a device scatter with duplicate
        # indices has no ordering guarantee)
        gcfg_upd = {}
        greset = []
        # (shard, lane, is_global) per request, for demux
        lanes: List[tuple] = []

        for r in requests:
            key = r.hash_key()
            s = shard_of(key, S)
            if r.behavior == Behavior.GLOBAL:
                slot, is_init = self.gtable.lookup(key, now, r.duration)
                gcfg_upd[slot] = (r.limit, r.duration, r.algorithm)
                if is_init:
                    greset.append(slot)
                lane = glob_fill[s]
                glob_fill[s] += 1
                buf.gslot[s, lane] = slot
                buf.ghits[s, lane] = r.hits
                buf.glimit[s, lane] = r.limit
                buf.gduration[s, lane] = r.duration
                buf.galgo[s, lane] = r.algorithm
                buf.gis_init[s, lane] = is_init
                lanes.append((s, lane, True))
            else:
                slot, is_init = self.tables[s].lookup(key, now, r.duration)
                lane = reg_fill[s]
                reg_fill[s] += 1
                buf.slot[s, lane] = slot
                buf.hits[s, lane] = r.hits
                buf.limit[s, lane] = r.limit
                buf.duration[s, lane] = r.duration
                buf.algo[s, lane] = r.algorithm
                buf.is_init[s, lane] = is_init
                lanes.append((s, lane, False))

        for i, (slot, cfg) in enumerate(gcfg_upd.items()):
            buf.uslot[i] = slot
            buf.ulimit[i], buf.uduration[i], buf.ualgo[i] = cfg
        for i, slot in enumerate(greset):
            buf.rslot[i] = slot

        batch = WindowBatch(
            slot=buf.slot, hits=buf.hits, limit=buf.limit,
            duration=buf.duration, algo=buf.algo, is_init=buf.is_init,
        )
        gbatch = WindowBatch(
            slot=buf.gslot, hits=buf.ghits, limit=buf.glimit,
            duration=buf.gduration, algo=buf.galgo, is_init=buf.gis_init,
        )
        upd = (buf.uslot, buf.ulimit, buf.uduration, buf.ualgo, buf.rslot)

        self.state, out, self.gstate, self.gcfg, gout = self._step_fn(
            self.state, self.gstate, self.gcfg, batch, gbatch, upd,
            jnp.int64(now),
        )
        out = jax.device_get(out)
        gout = jax.device_get(gout)

        self.windows_processed += 1
        self.decisions_processed += len(requests)

        responses = []
        for s, lane, is_global in lanes:
            o = gout if is_global else out
            responses.append(
                RateLimitResp(
                    status=int(o.status[s, lane]),
                    limit=int(o.limit[s, lane]),
                    remaining=int(o.remaining[s, lane]),
                    reset_time=int(o.reset_time[s, lane]),
                )
            )
        return responses

    def process(
        self, requests: Sequence[RateLimitReq], now: Optional[int] = None
    ) -> List[RateLimitResp]:
        """step() with automatic chunking when a window overflows the caps."""
        S = self.num_shards
        out: List[RateLimitResp] = []
        chunk: List[RateLimitReq] = []
        reg_fill = [0] * S
        glob_fill = [0] * S
        gkeys: set = set()
        for r in requests:
            key = r.hash_key()
            s = shard_of(key, S)
            g = r.behavior == Behavior.GLOBAL
            new_gkey = 1 if (g and key not in gkeys) else 0
            over = (
                (g and glob_fill[s] + 1 > self.global_batch_per_shard)
                or ((not g) and reg_fill[s] + 1 > self.batch_per_shard)
                or (len(gkeys) + new_gkey > self.max_global_updates)
            )
            if over:
                out.extend(self.step(chunk, now))
                chunk = []
                reg_fill = [0] * S
                glob_fill = [0] * S
                gkeys = set()
            chunk.append(r)
            if g:
                glob_fill[s] += 1
                gkeys.add(key)
            else:
                reg_fill[s] += 1
        if chunk:
            out.extend(self.step(chunk, now))
        return out

    # ---------------------------------------------------------------- metrics

    @property
    def cache_size(self) -> int:
        return sum(len(t) for t in self.tables) + len(self.gtable)

    @property
    def cache_hits(self) -> int:
        return sum(t.hits for t in self.tables) + self.gtable.hits

    @property
    def cache_misses(self) -> int:
        return sum(t.misses for t in self.tables) + self.gtable.misses
