"""Device-side traffic analytics: the per-drain stats reduction.

The serving drain already moves every number the operator wants — which
slots were hit, how hard, which lanes went over limit, whether a lane
initialized a bucket — it just throws them away after encoding the
response words.  `shard_stats` is a second, tiny executable over the SAME
arrays the drain consumed/produced (the compact request stack and the
response words of `engine.pipeline_dispatch`, plus the resident expiry
plane), so it composes with every drain lowering unchanged: compact32-XLA,
the fused Pallas megakernel, and the mesh's GLOBAL-composed drain all feed
it the identical (packed, words) pair.  Per shard it accumulates:

  * outcome counts — occupied lanes, total hits, under/over-limit, inits
    (arena churn), plus post-drain live/expired slot counts from the
    expiry plane (occupancy);
  * a count-min sketch over slot ids, persistent on device across drains
    (decayed by halving on a host-driven cadence), weighted
    `hits + over_weight * over` so keys burning their limit rank above
    merely chatty ones;
  * a candidate top-K: the drain's touched slots ranked by their
    CUMULATIVE sketch estimate (not just this drain's sample), shipped as
    (slot, estimate, drain_hits, drain_over) rows for the host's rolling
    merge (observability/analytics.py);
  * per-tenant rows (decisions, hits, over) keyed by the small-int tenant
    ids the host staged alongside the lanes (qos/fairness tenant = the
    request `name`).

Everything packs into ONE flat i64 stats vector per shard so the host
fetch piggybacks on the drain result's async copies — no extra
device→host round trip, and nothing here touches the drain executables
themselves (the analytics-off serving path is byte-identical).

`oracle_stats` is the numpy mirror used by the differential tests and the
hot-key probe: same hash mix, same decay, same candidate rule, exact.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from gubernator_tpu.ops.kernel import AGG_SLOT_BIT, COMPACT_MAX_HITS

# The native compact path tags every lane's slot+1 field with the
# aggregated-run flag (host_router.cc AGG_W0_BIT, even for n=1 runs);
# analytics wants the arena slot, so the flag is stripped on decode.
# An AGG lane's hits field already carries the folded run's TOTAL n.
_SLOT_MASK = 0xFFFFFFFF & ~AGG_SLOT_BIT

# Stats-vector layout: [HEADER | T tenant rows x 3 | K candidate rows x 4]
HEADER = 8
(IDX_LANES, IDX_HITS, IDX_UNDER, IDX_OVER, IDX_INIT, IDX_LIVE, IDX_EXPIRED,
 IDX_RESERVED) = range(HEADER)
TENANT_COLS = 3   # decisions, hits, over
CAND_COLS = 4     # slot, sketch estimate, drain hits, drain over

# Odd 62-bit multipliers (splitmix64-flavored) — one per sketch row.  The
# mask keeps every intermediate non-negative so `>>` and `%` behave the
# same in jnp (arithmetic shift) and numpy: the oracle must be bit-exact.
_MASK62 = (1 << 62) - 1
_MULTS = (
    0x2545F4914F6CDD1D, 0x369DEA0F31A53F85, 0x27BB2EE687B0B0FD,
    0x106689D45497FDB5, 0x1B873593CC9E2D51, 0x2127599BF4325C37,
    0x0B4B82E749B0A2F5, 0x3C6EF372FE94F82B,
)
MAX_SKETCH_DEPTH = len(_MULTS)


def stats_len(tenant_slots: int, topk: int) -> int:
    return HEADER + tenant_slots * TENANT_COLS + topk * CAND_COLS


def hash_slots(xp, slots, row: int, width: int):
    """Sketch row hash of slot ids, shared by device and oracle (xp is
    jnp or np; `slots` i64).  Multiply-xorshift keeps rows pairwise
    independent enough for the count-min guarantee to hold in practice."""
    x = ((slots + 1 + row) * _MULTS[row % MAX_SKETCH_DEPTH]) & _MASK62
    x = x ^ (x >> 31)
    return x % width


class DecodedLanes(NamedTuple):
    """Per-lane fields the reduction reads from the drain's wire arrays."""

    slot: jax.Array     # i32, PAD lanes < 0
    occupied: jax.Array  # i64 0/1
    hits: jax.Array     # i64, 0 on PAD
    is_init: jax.Array  # i64 0/1, 0 on PAD
    over: jax.Array     # i64 0/1, 0 on PAD


def _decode(xp, packed, words) -> DecodedLanes:
    """Compact request word0 + response word → the reduction's inputs
    (kernel.decode_batch / encode_output_word wire layout)."""
    w0 = packed[..., 0]
    slot = (w0 & _SLOT_MASK) - 1
    occ = (slot >= 0).astype(w0.dtype)
    return DecodedLanes(
        slot=slot,
        occupied=occ,
        hits=((w0 >> 34) & (COMPACT_MAX_HITS - 1)) * occ,
        is_init=((w0 >> 32) & 1) * occ,
        over=((words >> 31) & 1) * occ,
    )


def shard_stats(sketch, packed, words, tenants, expire, now, decay, *,
                tenant_slots: int, topk: int, over_weight: int):
    """One shard's per-drain reduction (runs under the engine's shard_map).

    sketch  i64[D, W]  persistent count-min rows (carried across drains)
    packed  i64[K, B, 2] the drain's compact request stack (this shard)
    words   i64[K, B]  the drain's response words (this shard)
    tenants i32[K, B]  host-staged tenant ids (0 = unattributed)
    expire  i64[C]     the post-drain expiry plane (resident, not copied)
    now     i64        the drain timestamp (ms)
    decay   i64        0 or 1: halve the sketch before accumulating

    Returns (new_sketch, stats i64[V]) with V = stats_len(T, K_top).
    """
    C = expire.shape[0]
    d = _decode(jnp, packed, words)
    cslot = jnp.clip(d.slot, 0, C - 1).ravel()

    # Dense per-slot aggregation of THIS drain (O(C) scratch, like the
    # fused path's plane conversion — amortized over all K windows).
    # ONE [C, 3] scatter-add instead of three [C] ones: integer adds are
    # exact and per-column independent, so the split arrays are
    # bit-identical to the oracle's three np.add.at passes — at a third
    # of the executed scatter kernels.
    dense = jnp.zeros((C, 3), jnp.int64).at[cslot].add(
        jnp.stack([d.hits.ravel(), d.over.ravel(), d.occupied.ravel()],
                  axis=-1))
    dense_h, dense_o, touched = dense[:, 0], dense[:, 1], dense[:, 2]
    dense_w = dense_h + over_weight * dense_o

    # Count-min update: decay-by-halving (decay is 0 or 1, so `>>` is a
    # no-op on the hot path — no branch), then scatter-add the drain's
    # per-slot weights into each hashed row.  All D rows go in ONE flat
    # [D*W] scatter (row r offset by r*W, so rows can never collide) —
    # same per-bucket integer sums as the oracle's per-row np.add.at
    # loop, D-fold fewer scatter/gather kernels.
    D, W = sketch.shape
    all_slots = jnp.arange(C, dtype=jnp.int64)
    rr = jnp.arange(D, dtype=jnp.int64)[:, None]
    mults = jnp.asarray([_MULTS[r % MAX_SKETCH_DEPTH] for r in range(D)],
                        jnp.int64)[:, None]
    x = ((all_slots[None, :] + 1 + rr) * mults) & _MASK62
    x = x ^ (x >> 31)
    h = x % W  # [D, C] — hash_slots for every row at once
    flat = (sketch >> decay).ravel().at[(rr * W + h).ravel()].add(
        jnp.broadcast_to(dense_w, (D, C)).ravel())
    new_sketch = flat.reshape(D, W)
    est = jnp.min(jnp.take_along_axis(new_sketch, h, axis=1), axis=0)

    # Candidates: slots touched this drain, ranked by cumulative estimate.
    score = jnp.where(touched > 0, est, jnp.int64(-1))
    top_est, top_slot = jax.lax.top_k(score, topk)
    valid = top_est >= 0
    cand = jnp.stack([
        jnp.where(valid, top_slot.astype(jnp.int64), -1),
        jnp.where(valid, top_est, 0),
        jnp.where(valid, dense_h[top_slot], 0),
        jnp.where(valid, dense_o[top_slot], 0),
    ], axis=-1)

    # Per-tenant rows (host staged ids; clip defends against garbage).
    # Same one-scatter shape as `dense` above.
    t = jnp.clip(tenants.astype(jnp.int64), 0, tenant_slots - 1).ravel()
    trows = jnp.zeros((tenant_slots, TENANT_COLS), jnp.int64).at[t].add(
        jnp.stack([d.occupied.ravel(), d.hits.ravel(), d.over.ravel()],
                  axis=-1))

    lanes = d.occupied.sum()
    over = d.over.sum()
    header = jnp.stack([
        lanes, d.hits.sum(), lanes - over, over, d.is_init.sum(),
        jnp.sum((expire > now).astype(jnp.int64)),
        jnp.sum(((expire != 0) & (expire <= now)).astype(jnp.int64)),
        jnp.int64(0),
    ])
    return new_sketch, jnp.concatenate([header, trows.ravel(), cand.ravel()])


def staged_stats_tail(sketch, drain_stats, expire, now, decay, *,
                      tenant_slots: int, topk: int, over_weight: int):
    """Finish the staged drain's in-kernel stats planes into the canonical
    (new_sketch, stats vector) pair — bit-identical to `shard_stats` over
    the same drain, with the whole per-lane decode/scatter half already
    folded INTO the drain megakernel (ops/pallas_kernel.py
    window_drain_fused_planes).  What remains here is only what the kernel
    cannot or should not do: the count-min scatter (the hash lattice is a
    pure function of the slot ids, so it traces as a numpy CONSTANT — zero
    equations for the hashing itself), the top-k candidate ranking, and
    the expiry-plane occupancy counts.

    drain_stats: the nine i32 planes from the drain kernel —
    (d_occ, d_over, d_hlo, d_hhi) [C], (t_occ, t_over, t_hlo, t_hhi)
    [tenant_slots], hdr [8] = [lanes, hits_lo, hits_hi, over, init, 0,0,0].
    Hit counts travel as exact (lo, hi) i32 pairs (see the drain kernel's
    limb-split accumulation) and reassemble here by bitcast."""
    d_occ, d_over, d_hlo, d_hhi, t_occ, t_over, t_hlo, t_hhi, hdr = (
        drain_stats)
    C = d_occ.shape[0]
    D, W = sketch.shape
    pair64 = lambda lo, hi: jax.lax.bitcast_convert_type(
        jnp.stack([lo, hi], axis=-1), jnp.int64)
    dense_h = pair64(d_hlo, d_hhi)
    dense_o = d_over.astype(jnp.int64)
    touched = d_occ.astype(jnp.int64)
    dense_w = dense_h + over_weight * dense_o

    # the hash lattice is data-independent — numpy at trace time, so the
    # multiply-xorshift mix contributes ZERO jaxpr equations (the staged
    # census budget counts every surviving op)
    all_slots = np.arange(C, dtype=np.int64)
    h_np = np.stack([hash_slots(np, all_slots, r, W) for r in range(D)])
    flat_idx = (np.arange(D, dtype=np.int64)[:, None] * W + h_np).ravel()
    flat = (sketch >> decay).ravel().at[flat_idx].add(
        jnp.broadcast_to(dense_w, (D, C)).ravel())
    new_sketch = flat.reshape(D, W)
    est = jnp.min(jnp.take_along_axis(new_sketch, jnp.asarray(h_np),
                                      axis=1), axis=0)

    score = jnp.where(touched > 0, est, jnp.int64(-1))
    top_est, top_slot = jax.lax.top_k(score, topk)
    valid = top_est >= 0
    cand = jnp.stack([
        jnp.where(valid, top_slot.astype(jnp.int64), -1),
        jnp.where(valid, top_est, 0),
        jnp.where(valid, dense_h[top_slot], 0),
        jnp.where(valid, dense_o[top_slot], 0),
    ], axis=-1)

    trows = jnp.stack([t_occ.astype(jnp.int64), pair64(t_hlo, t_hhi),
                       t_over.astype(jnp.int64)], axis=-1)

    lanes = hdr[0].astype(jnp.int64)
    hits_total = pair64(hdr[1:2], hdr[2:3])[0]
    over = hdr[3].astype(jnp.int64)
    header = jnp.stack([
        lanes, hits_total, lanes - over, over, hdr[4].astype(jnp.int64),
        jnp.sum((expire > now).astype(jnp.int64)),
        jnp.sum(((expire != 0) & (expire <= now)).astype(jnp.int64)),
        jnp.int64(0),
    ])
    return new_sketch, jnp.concatenate([header, trows.ravel(), cand.ravel()])


def oracle_stats(sketch, packed, words, tenants, expire, now, decay, *,
                 tenant_slots: int, topk: int, over_weight: int):
    """Numpy mirror of `shard_stats` — the differential tests' ground
    truth.  Bit-exact by construction: same hash mix, same halving decay,
    same candidate rule (ties broken by slot index, like lax.top_k on the
    flipped-index tiebreak below)."""
    sketch = np.asarray(sketch, np.int64).copy()
    packed = np.asarray(packed, np.int64)
    words = np.asarray(words, np.int64)
    C = int(np.asarray(expire).shape[0])
    d = _decode(np, packed, words)
    cslot = np.clip(d.slot, 0, C - 1).ravel()

    dense_h = np.zeros(C, np.int64)
    dense_o = np.zeros(C, np.int64)
    touched = np.zeros(C, np.int64)
    np.add.at(dense_h, cslot, d.hits.ravel())
    np.add.at(dense_o, cslot, d.over.ravel())
    np.add.at(touched, cslot, d.occupied.ravel())
    dense_w = dense_h + over_weight * dense_o

    all_slots = np.arange(C, dtype=np.int64)
    ests = np.full((sketch.shape[0], C), np.iinfo(np.int64).max)
    for r in range(sketch.shape[0]):
        h = hash_slots(np, all_slots, r, sketch.shape[1])
        sketch[r] >>= decay
        np.add.at(sketch[r], h, dense_w)
        ests[r] = sketch[r][h]
    est = ests.min(axis=0)

    score = np.where(touched > 0, est, -1)
    # lax.top_k returns the FIRST index on ties; argsort on (-score, slot)
    order = np.lexsort((all_slots, -score))[:topk]
    cand = np.zeros((topk, CAND_COLS), np.int64)
    for i, s in enumerate(order):
        if score[s] >= 0:
            cand[i] = (s, score[s], dense_h[s], dense_o[s])
        else:
            cand[i] = (-1, 0, 0, 0)

    t = np.clip(np.asarray(tenants, np.int64), 0, tenant_slots - 1).ravel()
    trows = np.zeros((tenant_slots, TENANT_COLS), np.int64)
    np.add.at(trows[:, 0], t, d.occupied.ravel())
    np.add.at(trows[:, 1], t, d.hits.ravel())
    np.add.at(trows[:, 2], t, d.over.ravel())

    expire = np.asarray(expire, np.int64)
    lanes = int(d.occupied.sum())
    over = int(d.over.sum())
    header = np.array([
        lanes, d.hits.sum(), lanes - over, over, d.is_init.sum(),
        int((expire > now).sum()), int(((expire != 0) & (expire <= now)).sum()),
        0,
    ], np.int64)
    return sketch, np.concatenate([header, trows.ravel(), cand.ravel()])
