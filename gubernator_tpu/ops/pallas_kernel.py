"""Pallas TPU kernels for the rate-limit hot passes.

Three lowerings, chosen by what actually profits from hand-scheduling on TPU
(everything here is gated behind env flags; the engine defaults to the XLA
implementations, which are semantically identical):

1. `global_apply_pallas` (GUBER_PALLAS=1) — the GLOBAL aggregate-apply: a
   pure elementwise transition over the whole replicated arena,
   grid-blocked through VMEM.

2. `window_step_pallas` (GUBER_PALLAS=1) — the per-shard serving window.
   The WINDOW MATH (closed-form uniform segments + the duplicate-key replay
   rounds) runs as ONE VMEM-resident kernel over the [B] lane vectors, with
   the replay's register state formulated REPLICATED-per-lane so each round
   is elementwise + one vector gather (no scatters in the kernel).  The
   argsort and the arena gather/scatter stay in XLA.

3. `window_step_fused` (GUBER_PALLAS_FUSED=1) — the FULL compact serving
   window as ONE pallas_call: wire decode, slot sort (in-kernel bitonic),
   segment prep, uniform/replay transitions, the replay-free fold path,
   arena commit (one write per touched slot) and the compact response
   encode all inside a single kernel whose arena planes are aliased
   in/out.  This is the per-kernel-overhead killer: the compact32-XLA
   drain lowers a K-window dispatch to hundreds of executed kernels
   (gathers, scatters, sort passes, elementwise stages — each a measured
   fixed launch cost on remote runtimes, BENCH_NOTES round 4), where the
   fused form executes O(1) kernels per window.  Everything runs in
   rebased int32 (arena i64 timestamps enter as (lo, hi) half planes and
   are rebased with explicit borrow/carry pair arithmetic), which is the
   only form Mosaic accepts on real TPU — no 64-bit vector types.

All kernel bodies *reuse* `kernel.transition` / `kernel.uniform_closed_form`
/ `_window_math` / `kernel.segment_structure` — the exact branch ladders
that mirror reference algorithms.go:24-186 — so the Pallas and XLA paths
cannot drift semantically, and the fuzz oracle (tests/pyref.py) plus the
int64 kernel (ops/kernel.py, kept as the bit-exact oracle) pin all of them.

State is int64 (ms-epoch timestamps + proto-contract counters).  Mosaic's
int64 support on real TPU is not yet validated in this environment (the
device tunnel was down when this was written), so the engine keeps the XLA
path by default; enable with the env flags or interpret=True (CPU tests run
the kernels in interpret mode and pin them against the XLA implementation).
"""

from __future__ import annotations

import contextlib
import functools
import sys
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

from gubernator_tpu.compat import shape_dtype_struct, typeof_vma
from gubernator_tpu.ops import kernel
from gubernator_tpu.ops.kernel import (
    BucketState,
    GlobalConfig,
    WindowBatch,
    WindowOutput,
    _Reg,
    I32,
    I64,
)

# lanes per grid step; arenas are sized in powers of two >= 1024
BLOCK = 1024


def fused_enabled(default: bool = False) -> bool:
    """Shared GUBER_PALLAS_FUSED reader (config.env_bool normalization:
    0/1/true/false/yes/no/on/off, warn on anything else).  The engine's
    compiled-builder cache keys, the bench probes, and tests must all
    normalize this flag identically — a reader that only accepted the
    literal "1" silently disabled the megakernel on `=true`."""
    from gubernator_tpu.config import env_bool
    return env_bool("GUBER_PALLAS_FUSED", default)


def kernel_census(closed) -> int:
    """Executed-kernel proxy over a ClosedJaxpr: count equations, recursing
    into sub-jaxprs (scan/while/cond/pjit bodies count once — per-window
    cost), with a pallas_call counting as ONE kernel regardless of its
    body.  On real TPU each surviving top-level op is at least one kernel
    launch (XLA fusion only merges elementwise neighbors; the gathers,
    scatters, sort passes and the scan skeleton stay distinct), so census
    ratios are a conservative stand-in for launch-count ratios.  Shared by
    the fused-megakernel test suites and bench.py's per-arm census."""
    def walk(jaxpr):
        n = 0
        for eqn in jaxpr.eqns:
            if eqn.primitive.name == "pallas_call":
                n += 1
                continue
            subs = []
            for v in eqn.params.values():
                vs = v if isinstance(v, (tuple, list)) else (v,)
                for x in vs:
                    if hasattr(x, "jaxpr"):
                        subs.append(x.jaxpr)   # ClosedJaxpr
                    elif hasattr(x, "eqns"):
                        subs.append(x)         # Jaxpr
            n += sum(walk(s) for s in subs) if subs else 1
        return n
    return walk(closed.jaxpr)


@contextlib.contextmanager
def mosaic_recursion_guard(limit: int = 20000):
    """Temporarily raise the recursion ceiling around a Mosaic lowering.

    Lowering the fused window-math jaxpr (closed-form ladder + replay loop
    as ONE Mosaic kernel) recurses past CPython's default 1000 frames
    inside jax's mlir lowering on real TPU (observed: RecursionError during
    the OUTER jit's compile, at first call of the compiled step — interpret
    mode on CPU stays shallower and never trips it).  The lowering runs at
    the first CALL of the engine's compiled executables, so the engine
    wraps those call sites in this guard (core/engine.py _recursion_guarded)
    rather than bumping the limit process-globally at import — an import
    side effect would leak a 20x ceiling into every embedding application
    (ADVICE.md #1).  The jaxpr nesting is finite (a few thousand frames),
    and CPython 3.12 heap-allocates Python-to-Python frames, so the
    temporary ceiling does not threaten the C stack.
    """
    prev = sys.getrecursionlimit()
    if prev < limit:
        sys.setrecursionlimit(limit)
    try:
        yield
    finally:
        sys.setrecursionlimit(prev)


def _apply_kernel(now_ref, limit_ref, dur_ref, rem_ref, ts_ref, exp_ref,
                  algo_ref, cl_ref, cd_ref, ca_ref, sum_ref,
                  o_limit, o_dur, o_rem, o_ts, o_exp, o_algo):
    reg = _Reg(
        limit=limit_ref[:],
        duration=dur_ref[:],
        remaining=rem_ref[:],
        tstamp=ts_ref[:],
        expire=exp_ref[:],
        algo=algo_ref[:],
    )
    now = now_ref[0]
    summed = sum_ref[:]
    cfg_algo = ca_ref[:]
    fresh = (reg.expire < now) | (cfg_algo != reg.algo)
    new_reg, _ = kernel.transition(
        reg, summed, cl_ref[:], cd_ref[:], cfg_algo, now, fresh)
    touched = summed != 0
    o_limit[:] = jnp.where(touched, new_reg.limit, reg.limit)
    o_dur[:] = jnp.where(touched, new_reg.duration, reg.duration)
    o_rem[:] = jnp.where(touched, new_reg.remaining, reg.remaining)
    o_ts[:] = jnp.where(touched, new_reg.tstamp, reg.tstamp)
    o_exp[:] = jnp.where(touched, new_reg.expire, reg.expire)
    o_algo[:] = jnp.where(touched, new_reg.algo, reg.algo)


@functools.partial(jax.jit, static_argnames=("interpret",))
def global_apply_pallas(state: BucketState, cfg: GlobalConfig,
                        summed_hits: jax.Array, now, *,
                        interpret: bool = False) -> BucketState:
    """Drop-in replacement for kernel.global_apply via pallas_call."""
    G = state.limit.shape[0]
    block = min(BLOCK, G)
    assert G % block == 0, "global arena capacity must be a multiple of the block"
    grid = (G // block,)
    spec = pl.BlockSpec((block,), lambda i: (i,))
    now_arr = jnp.asarray(now, jnp.int64).reshape((1,))

    # the global arena is replicated across the mesh, so under shard_map
    # with check_vma the outputs vary over no axes (vma=()); with check_vma
    # off (the engine's Pallas mode) or outside shard_map, vma is None
    vma = typeof_vma(state.limit)
    sds = lambda dt: shape_dtype_struct((G,), dt, vma=vma)
    out_shapes = [sds(jnp.int64)] * 5 + [sds(jnp.int32)]
    outs = pl.pallas_call(
        _apply_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1,), lambda i: (0,)),  # now (broadcast)
            spec, spec, spec, spec, spec, spec,  # state
            spec, spec, spec,                    # cfg
            spec,                                # summed
        ],
        out_specs=[spec] * 6,
        out_shape=out_shapes,
        interpret=interpret,
    )(now_arr, state.limit, state.duration, state.remaining, state.tstamp,
      state.expire, state.algo, cfg.limit, cfg.duration, cfg.algo, summed_hits)
    return BucketState(*outs)


# ---- the serving window kernel ------------------------------------------


# The one window-math body — the generalized zero-replay fold plus the
# residual replay loop — lives in ops/kernel.py (window_math) so the
# int64 oracle, the compact32 XLA path, the per-window Pallas kernel and
# the fused megakernel all run literally the same function.
_window_math = kernel.window_math


def _window_math_kernel(now_ref, maxpos_ref,
                        s_valid, s_hits, s_limit, s_duration, s_algo,
                        s_init, s_agg, pos, seg_len, seg_start_idx,
                        seg_fold, h0, l0, d0, a0, fresh_seg, nz, n_lead,
                        hstar,
                        r_lim, r_dur, r_rem, r_ts, r_exp, r_algo,
                        o_status, o_limit, o_rem, o_reset,
                        f_lim, f_dur, f_rem, f_ts, f_exp, f_algo):
    """Pallas Ref wrapper around _window_math (reads refs, writes refs)."""
    reg = _Reg(limit=r_lim[:], duration=r_dur[:], remaining=r_rem[:],
               tstamp=r_ts[:], expire=r_exp[:], algo=r_algo[:])
    out_sorted, fin = _window_math(
        now_ref[0], maxpos_ref[0], s_valid[:], s_hits[:], s_limit[:],
        s_duration[:], s_algo[:], s_agg[:], pos[:], seg_len[:],
        seg_start_idx[:], seg_fold[:], h0[:], l0[:], d0[:], a0[:],
        fresh_seg[:], reg, nz[:], n_lead[:], hstar[:])
    o_status[:] = out_sorted.status
    o_limit[:] = out_sorted.limit
    o_rem[:] = out_sorted.remaining
    o_reset[:] = out_sorted.reset_time
    f_lim[:] = fin.limit
    f_dur[:] = fin.duration
    f_rem[:] = fin.remaining
    f_ts[:] = fin.tstamp
    f_exp[:] = fin.expire
    f_algo[:] = fin.algo


@functools.partial(jax.jit,
                   static_argnames=("interpret", "compact32", "use_pallas"))
def window_step_pallas(state: BucketState, batch: WindowBatch, now, *,
                       interpret: bool = False, compact32: bool = False,
                       use_pallas: bool = True
                       ) -> tuple[BucketState, WindowOutput]:
    """Drop-in replacement for kernel.window_step with the window math in
    one Pallas kernel.  Sort, segment indexing, the arena gather, and the
    final scatter/unsort stay in XLA (see the module docstring for why).

    use_pallas=False runs the IDENTICAL math (_window_math, same rebase
    and re-absolutize) as plain traced XLA — with compact32=True that is
    the engine's default serving form (window_step_compact32_xla below):
    int64 arithmetic on TPU lowers to multi-op i32-pair emulation, so
    running the ladder in rebased int32 roughly halves the math's op
    count even without Mosaic.

    compact32=True runs the kernel body entirely in int32 with times
    REBASED to the window's `now` — Mosaic on real TPU has no 64-bit
    vector types (round-4 probe: "64-bit types are not supported"), and
    this is what makes the Pallas path runnable on hardware.  It is exact
    iff every lane satisfies the compact wire-format ranges
    (kernel.COMPACT_MAX_*: hits < 2^28, limit < 2^31, duration < 2^31-16)
    AND the arena rows it reads were written under the same caps — both
    guaranteed on the engine's compact serving path (the engine
    permanently drops to the full-format XLA path the first time an
    out-of-range config appears, core/engine.py _dispatch).  Rebased
    time identities: every absolute time the ladder computes is now+X
    with X in (-2^31, 2^31); non-fresh registers satisfy
    |t - now| <= max request duration < 2^31-16 (token: tstamp = expire
    >= now and <= write_now+duration; leaky: expire = last-decrement
    now+duration >= now) PROVIDED the window clock is monotonic — the
    engine's serving clocks are.  A clock that jumps backward by D ms
    can push a stored time up to D past the rebase range; the clip then
    bounds the resulting expiry error to D (graceful, not wrong-branch)."""
    B = batch.slot.shape[0]
    now = jnp.asarray(now, dtype=I64)

    # identical sort/segment/uniform prep as the XLA path — shared code, so
    # the two implementations cannot drift
    prep = kernel.window_prep(state, batch, now)
    (_, _, s_valid, s_hits, s_limit, s_duration, s_algo, s_init,
     _, seg_start_idx, pos, seg_len, cur, fresh_seg, h0, l0, d0, a0,
     nz, n_lead, hstar, seg_fold, max_pos, _commit_mask, s_agg) = prep

    if compact32:
        lim = jnp.int64(2**31 - 16)
        rel = lambda t: jnp.clip(t - now, -lim, lim).astype(I32)
        cnt = lambda x: x.astype(I32)
        k_hits, k_limit, k_dur = cnt(s_hits), cnt(s_limit), cnt(s_duration)
        k_h0, k_l0, k_d0 = cnt(h0), cnt(l0), cnt(d0)
        k_hstar = cnt(hstar)
        k_cur = _Reg(limit=cnt(cur.limit), duration=cnt(cur.duration),
                     remaining=cnt(cur.remaining), tstamp=rel(cur.tstamp),
                     expire=rel(cur.expire), algo=cur.algo)
        k_now = jnp.zeros((1,), I32)
        VD = I32
    else:
        k_hits, k_limit, k_dur = s_hits, s_limit, s_duration
        k_h0, k_l0, k_d0 = h0, l0, d0
        k_hstar = hstar
        k_cur = cur
        k_now = now.reshape((1,))
        VD = I64

    # under shard_map with check_vma the window arrays vary over the shard
    # axis; mirror the input's vma on the outputs.  The engine disables
    # check_vma on its shard_maps when Pallas is enabled (vma tags do not
    # survive the kernel's interpret-mode while_loop), in which case typeof
    # has no vma and None is correct.
    if use_pallas:
        vma = typeof_vma(batch.slot)
        sds = lambda dt: shape_dtype_struct((B,), dt, vma=vma)
        spec = pl.BlockSpec((B,), lambda: (0,))
        sspec = pl.BlockSpec((1,), lambda: (0,))
        outs = pl.pallas_call(
            _window_math_kernel,
            in_specs=[sspec, sspec] + [spec] * 25,
            out_specs=[spec] * 10,
            out_shape=[sds(I32), sds(VD), sds(VD), sds(VD),   # outputs
                       sds(VD), sds(VD), sds(VD), sds(VD), sds(VD),
                       sds(I32)],                             # final regs
            interpret=interpret,
        )(k_now, max_pos.reshape((1,)),
          s_valid, k_hits, k_limit, k_dur, s_algo, s_init, s_agg,
          pos, seg_len, seg_start_idx, seg_fold,
          k_h0, k_l0, k_d0, a0, fresh_seg, nz, n_lead, k_hstar,
          k_cur.limit, k_cur.duration, k_cur.remaining, k_cur.tstamp,
          k_cur.expire, k_cur.algo)
        out_sorted = WindowOutput(status=outs[0], limit=outs[1],
                                  remaining=outs[2], reset_time=outs[3])
        fin = _Reg(limit=outs[4], duration=outs[5], remaining=outs[6],
                   tstamp=outs[7], expire=outs[8], algo=outs[9])
    else:
        out_sorted, fin = _window_math(
            k_now[0], max_pos, s_valid, k_hits, k_limit, k_dur, s_algo,
            s_agg, pos, seg_len, seg_start_idx, seg_fold,
            k_h0, k_l0, k_d0, a0, fresh_seg, k_cur, nz, n_lead, k_hstar)
    if compact32:
        # re-absolutize.  reset_time: leaky uses 0 as the "no reset"
        # sentinel and every leaky non-zero reset is now+rate with
        # rate >= 1, so rel == 0 distinguishes exactly; token lanes always
        # carry a real time (rel 0 == "resets at now") and never the
        # sentinel (algorithms.go:130-141 vs :69-74).
        leaky_lane = s_algo == kernel.LEAKY_BUCKET
        reset64 = jnp.where(
            leaky_lane & (out_sorted.reset_time == 0), jnp.int64(0),
            out_sorted.reset_time.astype(I64) + now)
        out_sorted = WindowOutput(
            status=out_sorted.status, limit=out_sorted.limit.astype(I64),
            remaining=out_sorted.remaining.astype(I64), reset_time=reset64)
        fin = _Reg(limit=fin.limit.astype(I64),
                   duration=fin.duration.astype(I64),
                   remaining=fin.remaining.astype(I64),
                   tstamp=fin.tstamp.astype(I64) + now,
                   expire=fin.expire.astype(I64) + now,
                   algo=fin.algo)
    return kernel.window_commit(state, prep, fin, out_sorted)


def window_step_compact32_xla(state: BucketState, batch: WindowBatch, now
                              ) -> tuple[BucketState, WindowOutput]:
    """The serving drain's default window step: the rebased-int32 math as
    plain traced XLA (no Mosaic dependency).  Exact under the compact
    wire-format range caps — the only context the engine calls it in
    (see window_step_pallas's compact32 notes for the rebase identities).
    """
    return window_step_pallas(state, batch, now, compact32=True,
                              use_pallas=False)


# ---- the fused serving-window megakernel --------------------------------

_REBASE_LIM = 2**31 - 16


def _u32(x):
    return lax.bitcast_convert_type(x, jnp.uint32)


def _pair_rebase(t_lo, t_hi, n_lo, n_hi):
    """clip(t - now, -REBASE_LIM, REBASE_LIM) on (lo, hi) i32 halves.

    Exact vs the int64 form for every input: the borrow subtract yields the
    wrapped i64 difference's halves; when it fits int32 the clip sees the
    true difference, otherwise the hi half's sign picks the saturation end
    — identical to clipping the i64 value (verified over random i64s in
    tests/test_fused_megakernel.py)."""
    d_lo = t_lo - n_lo
    borrow = (_u32(t_lo) < _u32(n_lo)).astype(I32)
    d_hi = t_hi - n_hi - borrow
    fits = d_hi == (d_lo >> 31)
    lim = jnp.int32(_REBASE_LIM)
    return jnp.where(fits, jnp.clip(d_lo, -lim, lim),
                     jnp.where(d_hi < 0, -lim, lim))


def _pair_reabs(rel, n_lo, n_hi):
    """now + rel on (lo, hi) i32 halves (exact i64 add: sign-extended rel,
    carry from unsigned lo overflow)."""
    a_lo = n_lo + rel
    carry = (_u32(a_lo) < _u32(rel)).astype(I32)
    a_hi = n_hi + (rel >> 31) + carry
    return a_lo, a_hi


def _bitonic_sort_by_slot(sort_key):
    """(sorted_key, order) for a power-of-two lane vector — the in-kernel
    equivalent of `jnp.argsort(sort_key)` + gather.

    Lexicographic (key, lane) comparisons make the network STABLE despite
    bitonic networks not being: the lane index breaks every tie in arrival
    order, which the replay semantics require (duplicate hits to one slot
    must apply in arrival order).  XOR-partner exchanges are two vector
    gathers + elementwise selects per stage, log2(B)·(log2(B)+1)/2 stages,
    all Mosaic-legal — no sort primitive needed."""
    B = sort_key.shape[0]
    lane = lax.iota(I32, B)
    key, idx = sort_key, lane
    k = 2
    while k <= B:
        j = k // 2
        while j >= 1:
            partner = lane ^ j
            p_key = jnp.take(key, partner)
            p_idx = jnp.take(idx, partner)
            ascending = (lane & k) == 0
            less = (key < p_key) | ((key == p_key) & (idx < p_idx))
            is_lower = (lane & j) == 0
            keep = jnp.where(is_lower, less == ascending, less != ascending)
            key = jnp.where(keep, key, p_key)
            idx = jnp.where(keep, idx, p_idx)
            j //= 2
        k *= 2
    return key, idx


class FusedState32(NamedTuple):
    """The bucket arena as i32 planes — the form the fused megakernel
    reads/writes in place (aliased pallas_call operands).

    limit/duration/remaining are plain truncations: the compact serving
    path guarantees their stored values are inside the compact caps
    (< 2^31, engine._compact_eligible), so the low half IS the value.
    tstamp/expire are ms-epoch int64s that do NOT fit 32 bits; they travel
    as exact (lo, hi) bitcast halves and only ever get rebased/committed
    through the pair helpers above.  The pipeline drain converts once per
    K-window dispatch and carries THIS form through the scan, so the O(C)
    plane conversion is amortized over the whole drain."""

    limit: jax.Array      # i32[C]
    duration: jax.Array   # i32[C]
    remaining: jax.Array  # i32[C]
    t_lo: jax.Array       # i32[C]
    t_hi: jax.Array       # i32[C]
    e_lo: jax.Array       # i32[C]
    e_hi: jax.Array       # i32[C]
    algo: jax.Array       # i32[C]


def fused_state_to_planes(state: BucketState) -> FusedState32:
    tp = lax.bitcast_convert_type(state.tstamp, I32)
    ep = lax.bitcast_convert_type(state.expire, I32)
    return FusedState32(
        limit=state.limit.astype(I32),
        duration=state.duration.astype(I32),
        remaining=state.remaining.astype(I32),
        t_lo=tp[:, 0], t_hi=tp[:, 1],
        e_lo=ep[:, 0], e_hi=ep[:, 1],
        algo=state.algo)


def fused_state_from_planes(st32: FusedState32) -> BucketState:
    pair64 = lambda lo, hi: lax.bitcast_convert_type(
        jnp.stack([lo, hi], axis=-1), I64)
    return BucketState(
        limit=st32.limit.astype(I64),
        duration=st32.duration.astype(I64),
        remaining=st32.remaining.astype(I64),
        tstamp=pair64(st32.t_lo, st32.t_hi),
        expire=pair64(st32.e_lo, st32.e_hi),
        algo=st32.algo)


def _fused_kernel(now_ref, req_ref,
                  a_lim, a_dur, a_rem, a_tlo, a_thi, a_elo, a_ehi, a_algo,
                  o_lim, o_dur, o_rem, o_tlo, o_thi, o_elo, o_ehi, o_algo,
                  o_wlo, o_whi, o_rlimit, o_mism):
    """The whole compact serving window as one kernel body.

    Stages (each the i32-halves image of the XLA path's stage, same order):
    decode (kernel.decode_batch) → sort (stable bitonic ≡ jnp.argsort) →
    segment prep (kernel.segment_structure / segment_all — the SAME
    functions window_prep calls) → window math (_window_math — the same
    body the split Pallas/XLA paths run) → commit (kernel.window_commit's
    one-write-per-slot scatter, race-free form) → response word encode
    (kernel.encode_output_word) + unsort.  The o_* arena planes alias the
    a_* inputs, so the arena never leaves device memory."""
    B = req_ref.shape[0]
    C = a_lim.shape[0]
    n_lo = now_ref[0]
    n_hi = now_ref[1]
    req = req_ref[:]
    w0lo, w0hi, w1lo, w1hi = req[:, 0], req[:, 1], req[:, 2], req[:, 3]

    # ---- decode: kernel.decode_batch, reformulated on i32 halves ----
    # (bit 32 group of the i64 word lands in the hi half's low bits; the
    # hits mask clears the arithmetic-shift sign smear)
    slot_raw = w0lo - 1
    hits = (w0hi >> 2) & jnp.int32(kernel.COMPACT_MAX_HITS - 1)
    limit = w1lo
    duration = w1hi & jnp.int32(0x7FFFFFFF)
    algo = (w0hi >> 1) & 1
    is_init = (w0hi & 1) == 1

    # ---- window_prep in sorted, rebased-i32 form ----
    valid = slot_raw >= 0
    agg = valid & ((slot_raw & jnp.int32(kernel.AGG_SLOT_BIT)) != 0)
    slot_clean = jnp.where(agg, slot_raw & jnp.int32(~kernel.AGG_SLOT_BIT),
                           slot_raw)
    sort_key = jnp.where(valid, slot_clean, jnp.int32(2**31 - 1))
    s_slot, order = _bitonic_sort_by_slot(sort_key)
    s_valid = jnp.take(valid, order)
    s_hits = jnp.take(hits, order)
    s_limit = jnp.take(limit, order)
    s_duration = jnp.take(duration, order)
    s_algo = jnp.take(algo, order)
    s_init = jnp.take(is_init, order)
    s_agg = jnp.take(agg, order)

    seg_start, seg_start_idx, pos, seg_len, commit_mask = (
        kernel.segment_structure(s_slot, s_valid, s_init))

    g = jnp.clip(s_slot, 0, C - 1)
    raw_lim = a_lim[g]
    raw_dur = a_dur[g]
    raw_rem = a_rem[g]
    raw_tlo = a_tlo[g]
    raw_thi = a_thi[g]
    raw_elo = a_elo[g]
    raw_ehi = a_ehi[g]
    raw_algo = a_algo[g]
    cur = _Reg(limit=raw_lim, duration=raw_dur, remaining=raw_rem,
               tstamp=_pair_rebase(raw_tlo, raw_thi, n_lo, n_hi),
               expire=_pair_rebase(raw_elo, raw_ehi, n_lo, n_hi),
               algo=raw_algo)
    # rebased image of prep's `s_init | (cur.expire < now)`: the clip
    # preserves the difference's sign, so rel < 0 ⇔ expire < now
    cur_fresh = s_init | (cur.expire < 0)

    h0 = jnp.take(s_hits, seg_start_idx)
    l0 = jnp.take(s_limit, seg_start_idx)
    d0 = jnp.take(s_duration, seg_start_idx)
    a0 = jnp.take(s_algo, seg_start_idx)
    fresh_seg = jnp.take(cur_fresh, seg_start_idx)
    # fold classification in the rebased-i32 domain (cur is already
    # rebased to now=0, so fold_classify's leak math matches the split
    # paths' int64 classification under the compact caps)
    seg_fold, nz, n_lead, hstar = kernel.fold_classify(
        s_hits, s_limit, s_duration, s_algo, s_agg, seg_start_idx,
        seg_len, h0, l0, d0, a0, fresh_seg, cur, jnp.int32(0))
    seg_single = s_valid & ~seg_fold & (seg_len == 1)
    max_pos = jnp.max(jnp.where(s_valid & ~seg_fold & ~seg_single, pos,
                                jnp.int32(-1)))

    # ---- the window math: the SAME body as the split paths ----
    out_sorted, fin = _window_math(
        jnp.int32(0), max_pos, s_valid, s_hits, s_limit, s_duration,
        s_algo, s_agg, pos, seg_len, seg_start_idx, seg_fold,
        h0, l0, d0, a0, fresh_seg, cur, nz, n_lead, hstar)

    # ---- commit: one write per touched slot, race-free scatter form ----
    # window_commit redirects non-commit lanes to slot C (out of range,
    # mode="drop"); Pallas refs have no drop mode, so instead every
    # non-commit lane REJOINS the first committing lane's write — same
    # target, same value, so duplicate-scatter order can't matter.  With
    # zero commit lanes (all-pad window) every lane rewrites the raw
    # current value of lane 0's row: a no-op.
    f_tlo, f_thi = _pair_reabs(fin.tstamp, n_lo, n_hi)
    f_elo, f_ehi = _pair_reabs(fin.expire, n_lo, n_hi)
    any_commit = jnp.any(commit_mask)
    safe = jnp.argmax(commit_mask).astype(I32)
    tgt = jnp.where(commit_mask, g, jnp.take(g, safe))

    def commit_plane(ref, fin_vals, raw_vals):
        cand = jnp.where(any_commit, fin_vals, raw_vals)
        ref[tgt] = jnp.where(commit_mask, fin_vals, jnp.take(cand, safe))

    commit_plane(o_lim, fin.limit, raw_lim)
    commit_plane(o_dur, fin.duration, raw_dur)
    commit_plane(o_rem, fin.remaining, raw_rem)
    commit_plane(o_tlo, f_tlo, raw_tlo)
    commit_plane(o_thi, f_thi, raw_thi)
    commit_plane(o_elo, f_elo, raw_elo)
    commit_plane(o_ehi, f_ehi, raw_ehi)
    commit_plane(o_algo, fin.algo, raw_algo)

    # ---- response encode (kernel.encode_output_word image) + unsort ----
    # reset word: enc 0 iff the ABSOLUTE reset is 0 — the leaky no-reset
    # sentinel (rel == 0 on a leaky lane) or an absolute time that lands
    # exactly on zero; otherwise clip(rel, 0, 2^31-2) + 1, exact because
    # reset64 - now == rel in int64
    leaky0 = (s_algo == kernel.LEAKY_BUCKET) & (out_sorted.reset_time == 0)
    ab_lo, ab_hi = _pair_reabs(out_sorted.reset_time, n_lo, n_hi)
    reset_zero = leaky0 | ((ab_lo == 0) & (ab_hi == 0))
    enc = jnp.where(reset_zero, jnp.int32(0),
                    jnp.clip(out_sorted.reset_time, 0,
                             jnp.int32(2**31 - 2)) + 1)
    w_lo = (out_sorted.status << 31) | jnp.maximum(out_sorted.remaining, 0)
    o_wlo[order] = w_lo
    o_whi[order] = enc
    o_rlimit[order] = out_sorted.limit
    o_mism[0] = jnp.any((out_sorted.limit != s_limit)
                        & s_valid).astype(I32)


def window_step_fused_planes(st32: FusedState32, packed, now, *,
                             interpret: bool = False):
    """One compact serving window as ONE pallas_call over the plane-form
    arena.  Returns (new_st32, words i64[B], limits i64[B], mism bool) —
    `words` is exactly kernel.encode_output_word(out, now) and `limits`
    the stored-limit response plane, matching the pipeline drain's wire.

    Exactness contract: identical to decode_batch → window_step (the int64
    oracle) → encode_output_word under the compact wire caps plus
    arena-written-under-caps — the same contract window_step_compact32_xla
    carries, pinned by tests/test_fused_megakernel.py differentials.
    """
    B = packed.shape[0]
    C = st32.limit.shape[0]
    assert B & (B - 1) == 0, "fused megakernel needs power-of-two lanes"
    now = jnp.asarray(now, I64)
    req32 = lax.bitcast_convert_type(packed, I32).reshape(B, 4)
    now32 = lax.bitcast_convert_type(now.reshape((1,)), I32).reshape((2,))

    vma = typeof_vma(packed)
    lane_sds = lambda shape: shape_dtype_struct(shape, I32, vma=vma)
    plane_sds = lambda: shape_dtype_struct((C,), I32,
                                           vma=typeof_vma(st32.limit))
    bspec = pl.BlockSpec((B,), lambda: (0,))
    aspec = pl.BlockSpec(memory_space=pl.ANY)
    outs = pl.pallas_call(
        _fused_kernel,
        in_specs=[pl.BlockSpec((2,), lambda: (0,)),
                  pl.BlockSpec((B, 4), lambda: (0, 0))] + [aspec] * 8,
        out_specs=[aspec] * 8 + [bspec] * 3
        + [pl.BlockSpec((1,), lambda: (0,))],
        out_shape=[plane_sds() for _ in range(8)]
        + [lane_sds((B,)) for _ in range(3)] + [lane_sds((1,))],
        # arena planes update in place: inputs 2..9 alias outputs 0..7
        input_output_aliases={i + 2: i for i in range(8)},
        interpret=interpret,
    )(now32, req32, *st32)
    new32 = FusedState32(*outs[:8])
    words = lax.bitcast_convert_type(
        jnp.stack([outs[8], outs[9]], axis=-1), I64)
    limits = outs[10].astype(I64)
    return new32, words, limits, outs[11][0] != 0


@functools.partial(jax.jit, static_argnames=("interpret",))
def window_step_fused(state: BucketState, packed, now, *,
                      interpret: bool = False):
    """BucketState-in/BucketState-out wrapper around the fused megakernel
    (single-window call sites).  The pipeline drain avoids the per-window
    O(C) plane conversion by carrying FusedState32 through its scan and
    calling window_step_fused_planes directly."""
    st32, words, limits, mism = window_step_fused_planes(
        fused_state_to_planes(state), packed, now, interpret=interpret)
    return fused_state_from_planes(st32), words, limits, mism
