"""Pallas TPU kernels for the rate-limit hot passes.

Three lowerings, chosen by what actually profits from hand-scheduling on TPU
(everything here is gated behind env flags; the engine defaults to the XLA
implementations, which are semantically identical):

1. `global_apply_pallas` (GUBER_PALLAS=1) — the GLOBAL aggregate-apply: a
   pure elementwise transition over the whole replicated arena,
   grid-blocked through VMEM.

2. `window_step_pallas` (GUBER_PALLAS=1) — the per-shard serving window.
   The WINDOW MATH (closed-form uniform segments + the duplicate-key replay
   rounds) runs as ONE VMEM-resident kernel over the [B] lane vectors, with
   the replay's register state formulated REPLICATED-per-lane so each round
   is elementwise + one vector gather (no scatters in the kernel).  The
   argsort and the arena gather/scatter stay in XLA.

3. `window_step_fused` (GUBER_PALLAS_FUSED=1) — the FULL compact serving
   window as ONE pallas_call: wire decode, slot sort (in-kernel bitonic),
   segment prep, uniform/replay transitions, the replay-free fold path,
   arena commit (one write per touched slot) and the compact response
   encode all inside a single kernel whose arena planes are aliased
   in/out.  This is the per-kernel-overhead killer: the compact32-XLA
   drain lowers a K-window dispatch to hundreds of executed kernels
   (gathers, scatters, sort passes, elementwise stages — each a measured
   fixed launch cost on remote runtimes, BENCH_NOTES round 4), where the
   fused form executes O(1) kernels per window.  Everything runs in
   rebased int32 (arena i64 timestamps enter as (lo, hi) half planes and
   are rebased with explicit borrow/carry pair arithmetic), which is the
   only form Mosaic accepts on real TPU — no 64-bit vector types.

All kernel bodies *reuse* `kernel.transition` / `kernel.uniform_closed_form`
/ `_window_math` / `kernel.segment_structure` — the exact branch ladders
that mirror reference algorithms.go:24-186 — so the Pallas and XLA paths
cannot drift semantically, and the fuzz oracle (tests/pyref.py) plus the
int64 kernel (ops/kernel.py, kept as the bit-exact oracle) pin all of them.

State is int64 (ms-epoch timestamps + proto-contract counters).  Mosaic's
int64 support on real TPU is not yet validated in this environment (the
device tunnel was down when this was written), so the engine keeps the XLA
path by default; enable with the env flags or interpret=True (CPU tests run
the kernels in interpret mode and pin them against the XLA implementation).
"""

from __future__ import annotations

import contextlib
import functools
import sys
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl

from gubernator_tpu.compat import shape_dtype_struct, typeof_vma
from gubernator_tpu.ops import kernel
from gubernator_tpu.ops.kernel import (
    BucketState,
    GlobalConfig,
    WindowBatch,
    WindowOutput,
    _Reg,
    I32,
    I64,
)

# lanes per grid step; arenas are sized in powers of two >= 1024
BLOCK = 1024


def fused_enabled(default: bool = False) -> bool:
    """Shared GUBER_PALLAS_FUSED reader (config.env_bool normalization:
    0/1/true/false/yes/no/on/off, warn on anything else).  The engine's
    compiled-builder cache keys, the bench probes, and tests must all
    normalize this flag identically — a reader that only accepted the
    literal "1" silently disabled the megakernel on `=true`."""
    from gubernator_tpu.config import env_bool
    return env_bool("GUBER_PALLAS_FUSED", default)


def kernel_census(closed) -> int:
    """Executed-kernel proxy over a ClosedJaxpr: count equations, recursing
    into sub-jaxprs (scan/while/cond/pjit bodies count once — per-window
    cost), with a pallas_call counting as ONE kernel regardless of its
    body.  On real TPU each surviving top-level op is at least one kernel
    launch (XLA fusion only merges elementwise neighbors; the gathers,
    scatters, sort passes and the scan skeleton stay distinct), so census
    ratios are a conservative stand-in for launch-count ratios.  Shared by
    the fused-megakernel test suites and bench.py's per-arm census."""
    def walk(jaxpr):
        n = 0
        for eqn in jaxpr.eqns:
            if eqn.primitive.name == "pallas_call":
                n += 1
                continue
            subs = []
            for v in eqn.params.values():
                vs = v if isinstance(v, (tuple, list)) else (v,)
                for x in vs:
                    if hasattr(x, "jaxpr"):
                        subs.append(x.jaxpr)   # ClosedJaxpr
                    elif hasattr(x, "eqns"):
                        subs.append(x)         # Jaxpr
            n += sum(walk(s) for s in subs) if subs else 1
        return n
    return walk(closed.jaxpr)


@contextlib.contextmanager
def mosaic_recursion_guard(limit: int = 20000):
    """Temporarily raise the recursion ceiling around a Mosaic lowering.

    Lowering the fused window-math jaxpr (closed-form ladder + replay loop
    as ONE Mosaic kernel) recurses past CPython's default 1000 frames
    inside jax's mlir lowering on real TPU (observed: RecursionError during
    the OUTER jit's compile, at first call of the compiled step — interpret
    mode on CPU stays shallower and never trips it).  The lowering runs at
    the first CALL of the engine's compiled executables, so the engine
    wraps those call sites in this guard (core/engine.py _recursion_guarded)
    rather than bumping the limit process-globally at import — an import
    side effect would leak a 20x ceiling into every embedding application
    (ADVICE.md #1).  The jaxpr nesting is finite (a few thousand frames),
    and CPython 3.12 heap-allocates Python-to-Python frames, so the
    temporary ceiling does not threaten the C stack.
    """
    prev = sys.getrecursionlimit()
    if prev < limit:
        sys.setrecursionlimit(limit)
    try:
        yield
    finally:
        sys.setrecursionlimit(prev)


def _apply_kernel(now_ref, limit_ref, dur_ref, rem_ref, ts_ref, exp_ref,
                  algo_ref, cl_ref, cd_ref, ca_ref, sum_ref,
                  o_limit, o_dur, o_rem, o_ts, o_exp, o_algo):
    reg = _Reg(
        limit=limit_ref[:],
        duration=dur_ref[:],
        remaining=rem_ref[:],
        tstamp=ts_ref[:],
        expire=exp_ref[:],
        algo=algo_ref[:],
    )
    now = now_ref[0]
    summed = sum_ref[:]
    cfg_algo = ca_ref[:]
    fresh = (reg.expire < now) | (cfg_algo != reg.algo)
    new_reg, _ = kernel.transition(
        reg, summed, cl_ref[:], cd_ref[:], cfg_algo, now, fresh)
    touched = summed != 0
    o_limit[:] = jnp.where(touched, new_reg.limit, reg.limit)
    o_dur[:] = jnp.where(touched, new_reg.duration, reg.duration)
    o_rem[:] = jnp.where(touched, new_reg.remaining, reg.remaining)
    o_ts[:] = jnp.where(touched, new_reg.tstamp, reg.tstamp)
    o_exp[:] = jnp.where(touched, new_reg.expire, reg.expire)
    o_algo[:] = jnp.where(touched, new_reg.algo, reg.algo)


@functools.partial(jax.jit, static_argnames=("interpret",))
def global_apply_pallas(state: BucketState, cfg: GlobalConfig,
                        summed_hits: jax.Array, now, *,
                        interpret: bool = False) -> BucketState:
    """Drop-in replacement for kernel.global_apply via pallas_call."""
    G = state.limit.shape[0]
    block = min(BLOCK, G)
    assert G % block == 0, "global arena capacity must be a multiple of the block"
    grid = (G // block,)
    spec = pl.BlockSpec((block,), lambda i: (i,))
    now_arr = jnp.asarray(now, jnp.int64).reshape((1,))

    # the global arena is replicated across the mesh, so under shard_map
    # with check_vma the outputs vary over no axes (vma=()); with check_vma
    # off (the engine's Pallas mode) or outside shard_map, vma is None
    vma = typeof_vma(state.limit)
    sds = lambda dt: shape_dtype_struct((G,), dt, vma=vma)
    out_shapes = [sds(jnp.int64)] * 5 + [sds(jnp.int32)]
    outs = pl.pallas_call(
        _apply_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1,), lambda i: (0,)),  # now (broadcast)
            spec, spec, spec, spec, spec, spec,  # state
            spec, spec, spec,                    # cfg
            spec,                                # summed
        ],
        out_specs=[spec] * 6,
        out_shape=out_shapes,
        interpret=interpret,
    )(now_arr, state.limit, state.duration, state.remaining, state.tstamp,
      state.expire, state.algo, cfg.limit, cfg.duration, cfg.algo, summed_hits)
    return BucketState(*outs)


# ---- the serving window kernel ------------------------------------------


# The one window-math body — the generalized zero-replay fold plus the
# residual replay loop — lives in ops/kernel.py (window_math) so the
# int64 oracle, the compact32 XLA path, the per-window Pallas kernel and
# the fused megakernel all run literally the same function.
_window_math = kernel.window_math


def _window_math_kernel(now_ref, maxpos_ref,
                        s_valid, s_hits, s_limit, s_duration, s_algo,
                        s_init, s_agg, pos, seg_len, seg_start_idx,
                        seg_fold, h0, l0, d0, a0, fresh_seg, nz, n_lead,
                        hstar,
                        r_lim, r_dur, r_rem, r_ts, r_exp, r_algo,
                        o_status, o_limit, o_rem, o_reset,
                        f_lim, f_dur, f_rem, f_ts, f_exp, f_algo):
    """Pallas Ref wrapper around _window_math (reads refs, writes refs)."""
    reg = _Reg(limit=r_lim[:], duration=r_dur[:], remaining=r_rem[:],
               tstamp=r_ts[:], expire=r_exp[:], algo=r_algo[:])
    out_sorted, fin = _window_math(
        now_ref[0], maxpos_ref[0], s_valid[:], s_hits[:], s_limit[:],
        s_duration[:], s_algo[:], s_agg[:], pos[:], seg_len[:],
        seg_start_idx[:], seg_fold[:], h0[:], l0[:], d0[:], a0[:],
        fresh_seg[:], reg, nz[:], n_lead[:], hstar[:])
    o_status[:] = out_sorted.status
    o_limit[:] = out_sorted.limit
    o_rem[:] = out_sorted.remaining
    o_reset[:] = out_sorted.reset_time
    f_lim[:] = fin.limit
    f_dur[:] = fin.duration
    f_rem[:] = fin.remaining
    f_ts[:] = fin.tstamp
    f_exp[:] = fin.expire
    f_algo[:] = fin.algo


@functools.partial(jax.jit,
                   static_argnames=("interpret", "compact32", "use_pallas"))
def window_step_pallas(state: BucketState, batch: WindowBatch, now, *,
                       interpret: bool = False, compact32: bool = False,
                       use_pallas: bool = True
                       ) -> tuple[BucketState, WindowOutput]:
    """Drop-in replacement for kernel.window_step with the window math in
    one Pallas kernel.  Sort, segment indexing, the arena gather, and the
    final scatter/unsort stay in XLA (see the module docstring for why).

    use_pallas=False runs the IDENTICAL math (_window_math, same rebase
    and re-absolutize) as plain traced XLA — with compact32=True that is
    the engine's default serving form (window_step_compact32_xla below):
    int64 arithmetic on TPU lowers to multi-op i32-pair emulation, so
    running the ladder in rebased int32 roughly halves the math's op
    count even without Mosaic.

    compact32=True runs the kernel body entirely in int32 with times
    REBASED to the window's `now` — Mosaic on real TPU has no 64-bit
    vector types (round-4 probe: "64-bit types are not supported"), and
    this is what makes the Pallas path runnable on hardware.  It is exact
    iff every lane satisfies the compact wire-format ranges
    (kernel.COMPACT_MAX_*: hits < 2^28, limit < 2^31, duration < 2^31-16)
    AND the arena rows it reads were written under the same caps — both
    guaranteed on the engine's compact serving path (the engine
    permanently drops to the full-format XLA path the first time an
    out-of-range config appears, core/engine.py _dispatch).  Rebased
    time identities: every absolute time the ladder computes is now+X
    with X in (-2^31, 2^31); non-fresh registers satisfy
    |t - now| <= max request duration < 2^31-16 (token: tstamp = expire
    >= now and <= write_now+duration; leaky: expire = last-decrement
    now+duration >= now) PROVIDED the window clock is monotonic — the
    engine's serving clocks are.  A clock that jumps backward by D ms
    can push a stored time up to D past the rebase range; the clip then
    bounds the resulting expiry error to D (graceful, not wrong-branch)."""
    B = batch.slot.shape[0]
    now = jnp.asarray(now, dtype=I64)

    # identical sort/segment/uniform prep as the XLA path — shared code, so
    # the two implementations cannot drift
    prep = kernel.window_prep(state, batch, now)
    (_, _, s_valid, s_hits, s_limit, s_duration, s_algo, s_init,
     _, seg_start_idx, pos, seg_len, cur, fresh_seg, h0, l0, d0, a0,
     nz, n_lead, hstar, seg_fold, max_pos, _commit_mask, s_agg) = prep

    if compact32:
        lim = jnp.int64(2**31 - 16)
        rel = lambda t: jnp.clip(t - now, -lim, lim).astype(I32)
        cnt = lambda x: x.astype(I32)
        k_hits, k_limit, k_dur = cnt(s_hits), cnt(s_limit), cnt(s_duration)
        k_h0, k_l0, k_d0 = cnt(h0), cnt(l0), cnt(d0)
        k_hstar = cnt(hstar)
        k_cur = _Reg(limit=cnt(cur.limit), duration=cnt(cur.duration),
                     remaining=cnt(cur.remaining), tstamp=rel(cur.tstamp),
                     expire=rel(cur.expire), algo=cur.algo)
        k_now = jnp.zeros((1,), I32)
        VD = I32
    else:
        k_hits, k_limit, k_dur = s_hits, s_limit, s_duration
        k_h0, k_l0, k_d0 = h0, l0, d0
        k_hstar = hstar
        k_cur = cur
        k_now = now.reshape((1,))
        VD = I64

    # under shard_map with check_vma the window arrays vary over the shard
    # axis; mirror the input's vma on the outputs.  The engine disables
    # check_vma on its shard_maps when Pallas is enabled (vma tags do not
    # survive the kernel's interpret-mode while_loop), in which case typeof
    # has no vma and None is correct.
    if use_pallas:
        vma = typeof_vma(batch.slot)
        sds = lambda dt: shape_dtype_struct((B,), dt, vma=vma)
        spec = pl.BlockSpec((B,), lambda: (0,))
        sspec = pl.BlockSpec((1,), lambda: (0,))
        outs = pl.pallas_call(
            _window_math_kernel,
            in_specs=[sspec, sspec] + [spec] * 25,
            out_specs=[spec] * 10,
            out_shape=[sds(I32), sds(VD), sds(VD), sds(VD),   # outputs
                       sds(VD), sds(VD), sds(VD), sds(VD), sds(VD),
                       sds(I32)],                             # final regs
            interpret=interpret,
        )(k_now, max_pos.reshape((1,)),
          s_valid, k_hits, k_limit, k_dur, s_algo, s_init, s_agg,
          pos, seg_len, seg_start_idx, seg_fold,
          k_h0, k_l0, k_d0, a0, fresh_seg, nz, n_lead, k_hstar,
          k_cur.limit, k_cur.duration, k_cur.remaining, k_cur.tstamp,
          k_cur.expire, k_cur.algo)
        out_sorted = WindowOutput(status=outs[0], limit=outs[1],
                                  remaining=outs[2], reset_time=outs[3])
        fin = _Reg(limit=outs[4], duration=outs[5], remaining=outs[6],
                   tstamp=outs[7], expire=outs[8], algo=outs[9])
    else:
        out_sorted, fin = _window_math(
            k_now[0], max_pos, s_valid, k_hits, k_limit, k_dur, s_algo,
            s_agg, pos, seg_len, seg_start_idx, seg_fold,
            k_h0, k_l0, k_d0, a0, fresh_seg, k_cur, nz, n_lead, k_hstar)
    if compact32:
        # re-absolutize.  reset_time: leaky and concurrency use 0 as the
        # "no reset" sentinel (leaky's non-zero resets are now+rate with
        # rate >= 1; concurrency resets are ALWAYS the sentinel), so
        # rel == 0 distinguishes exactly; token/GCRA/sliding lanes always
        # carry a real time (rel 0 == "resets at now") and never the
        # sentinel (algorithms.go:130-141 vs :69-74).
        leaky_lane = ((s_algo == kernel.LEAKY_BUCKET)
                      | (s_algo == kernel.CONCURRENCY))
        reset64 = jnp.where(
            leaky_lane & (out_sorted.reset_time == 0), jnp.int64(0),
            out_sorted.reset_time.astype(I64) + now)
        out_sorted = WindowOutput(
            status=out_sorted.status, limit=out_sorted.limit.astype(I64),
            remaining=out_sorted.remaining.astype(I64), reset_time=reset64)
        fin = _Reg(limit=fin.limit.astype(I64),
                   duration=fin.duration.astype(I64),
                   remaining=fin.remaining.astype(I64),
                   tstamp=fin.tstamp.astype(I64) + now,
                   expire=fin.expire.astype(I64) + now,
                   algo=fin.algo)
    return kernel.window_commit(state, prep, fin, out_sorted)


def window_step_compact32_xla(state: BucketState, batch: WindowBatch, now
                              ) -> tuple[BucketState, WindowOutput]:
    """The serving drain's default window step: the rebased-int32 math as
    plain traced XLA (no Mosaic dependency).  Exact under the compact
    wire-format range caps — the only context the engine calls it in
    (see window_step_pallas's compact32 notes for the rebase identities).
    """
    return window_step_pallas(state, batch, now, compact32=True,
                              use_pallas=False)


# ---- the fused serving-window megakernel --------------------------------

_REBASE_LIM = 2**31 - 16


def _u32(x):
    return lax.bitcast_convert_type(x, jnp.uint32)


def _pair_rebase(t_lo, t_hi, n_lo, n_hi):
    """clip(t - now, -REBASE_LIM, REBASE_LIM) on (lo, hi) i32 halves.

    Exact vs the int64 form for every input: the borrow subtract yields the
    wrapped i64 difference's halves; when it fits int32 the clip sees the
    true difference, otherwise the hi half's sign picks the saturation end
    — identical to clipping the i64 value (verified over random i64s in
    tests/test_fused_megakernel.py)."""
    d_lo = t_lo - n_lo
    borrow = (_u32(t_lo) < _u32(n_lo)).astype(I32)
    d_hi = t_hi - n_hi - borrow
    fits = d_hi == (d_lo >> 31)
    lim = jnp.int32(_REBASE_LIM)
    return jnp.where(fits, jnp.clip(d_lo, -lim, lim),
                     jnp.where(d_hi < 0, -lim, lim))


def _pair_reabs(rel, n_lo, n_hi):
    """now + rel on (lo, hi) i32 halves (exact i64 add: sign-extended rel,
    carry from unsigned lo overflow)."""
    a_lo = n_lo + rel
    carry = (_u32(a_lo) < _u32(rel)).astype(I32)
    a_hi = n_hi + (rel >> 31) + carry
    return a_lo, a_hi


# ---- general (lo, hi) i32-pair arithmetic ---------------------------------
#
# The rebase/reabs helpers above only cover times within +/-2^31 of `now`.
# The GLOBAL ladder has no such contract (its stored state is exempt from
# the compact caps), so its Mosaic form runs FULL i64 arithmetic as exact
# two's-complement pair ops: lo halves add/subtract as u32 with explicit
# carry/borrow, hi halves carry the sign.  Every op below is the bit-exact
# image of the corresponding i64 op (wrap included), so a ladder built from
# them cannot diverge from the int64 oracle even on adversarial inputs.

# the zero pair as plain Python ints: weak-typed literals inline into any
# kernel trace (a module-level jnp scalar would be a captured constant,
# which pallas_call kernels reject)
_P0 = (0, 0)


def _p_add(a, b):
    lo = a[0] + b[0]
    carry = (_u32(lo) < _u32(a[0])).astype(I32)
    return lo, a[1] + b[1] + carry


def _p_sub(a, b):
    borrow = (_u32(a[0]) < _u32(b[0])).astype(I32)
    return a[0] - b[0], a[1] - b[1] - borrow


def _p_lt(a, b):
    """Signed a < b."""
    return (a[1] < b[1]) | ((a[1] == b[1]) & (_u32(a[0]) < _u32(b[0])))


def _p_eq(a, b):
    return (a[0] == b[0]) & (a[1] == b[1])


def _p_is0(a):
    return (a[0] | a[1]) == 0


def _p_where(c, a, b):
    return jnp.where(c, a[0], b[0]), jnp.where(c, a[1], b[1])


def _p_min(a, b):
    return _p_where(_p_lt(a, b), a, b)


def _p_chain(pairs, default):
    """kernel._chain for pair values: first-match-wins where-fold."""
    out = default
    for cond, val in reversed(pairs):
        out = _p_where(cond, val, out)
    return out


def _p_sext(v):
    """i32 value -> its exact i64 image as a (lo, hi) pair."""
    return v, v >> 31


def _shr_u(x, s):
    """Logical (zero-fill) right shift on i32, via the u32 view — jnp
    right_shift on int32 is arithmetic, and the lax logical shift does
    not broadcast a scalar count."""
    return lax.bitcast_convert_type(_u32(x) >> s, I32)


def _p_shr(p, d):
    """Arithmetic right shift of an i64 pair by a traced scalar d in
    [0, 63] — the sketch decay (`sketch >> decay`; the engine passes the
    0/1 halving flag, but the oracle semantics hold for the whole range).
    Shift counts of 0 and >=32 are special-cased: XLA shifts are
    undefined at the word width, so the three ranges select explicitly."""
    lo, hi = p
    d = jnp.clip(d, 0, 63)
    sa = jnp.clip(d, 1, 31)                 # in-word case: d in [1, 31]
    lo_a = _shr_u(lo, sa.astype(jnp.uint32)) | (hi << (32 - sa))
    hi_a = hi >> sa
    sb = jnp.clip(d - 32, 0, 31)            # cross-word case: d in [32, 63]
    lo_b, hi_b = hi >> sb, hi >> 31
    big = d >= 32
    lo_s = jnp.where(big, lo_b, lo_a)
    hi_s = jnp.where(big, hi_b, hi_a)
    return _p_where(d == 0, p, (lo_s, hi_s))


# 14-bit limb decomposition of a pair: l4..l0 are the literal bit fields
# (14, 14, 14, 14, 8 bits), so sum(l_j << 14j) mod 2^64 reconstructs the
# value exactly — two's complement included.  Limbs let per-bucket i64
# totals accumulate through i32 lane sums (each partial < lanes * 2^14)
# without a 64-bit vector ALU.
def _p_limbs(p):
    lo, hi = p
    M = 0x3FFF
    return (lo & M,
            _shr_u(lo, 14) & M,
            (_shr_u(lo, 28) | (hi << 4)) & M,
            _shr_u(hi, 10) & M,
            _shr_u(hi, 24) & 0xFF)


def _p_from_limbs(c0, c1, c2, c3, c4):
    """Rebuild the pair from (possibly carried-into) non-negative i32 limb
    sums: value = sum(c_j * 2^(14 j)) mod 2^64.  Exact for any c_j in
    [0, 2^31): the shifted partials are each exact u64 images and pair
    addition wraps like i64."""
    z = jnp.zeros_like(c0)
    p = (c0, z)
    p = _p_add(p, (c1 << 14, _shr_u(c1, 18)))
    p = _p_add(p, (c2 << 28, _shr_u(c2, 4)))
    p = _p_add(p, (z, c3 << 10))
    return _p_add(p, (z, c4 << 24))


def _bitonic_sort_by_slot(sort_key):
    """(sorted_key, order) for a power-of-two lane vector — the in-kernel
    equivalent of `jnp.argsort(sort_key)` + gather.

    Lexicographic (key, lane) comparisons make the network STABLE despite
    bitonic networks not being: the lane index breaks every tie in arrival
    order, which the replay semantics require (duplicate hits to one slot
    must apply in arrival order).  XOR-partner exchanges are two vector
    gathers + elementwise selects per stage, log2(B)·(log2(B)+1)/2 stages,
    all Mosaic-legal — no sort primitive needed."""
    B = sort_key.shape[0]
    lane = lax.iota(I32, B)
    key, idx = sort_key, lane
    k = 2
    while k <= B:
        j = k // 2
        while j >= 1:
            partner = lane ^ j
            p_key = jnp.take(key, partner)
            p_idx = jnp.take(idx, partner)
            ascending = (lane & k) == 0
            less = (key < p_key) | ((key == p_key) & (idx < p_idx))
            is_lower = (lane & j) == 0
            keep = jnp.where(is_lower, less == ascending, less != ascending)
            key = jnp.where(keep, key, p_key)
            idx = jnp.where(keep, idx, p_idx)
            j //= 2
        k *= 2
    return key, idx


class FusedState32(NamedTuple):
    """The bucket arena as i32 planes — the form the fused megakernel
    reads/writes in place (aliased pallas_call operands).

    limit/duration/remaining are plain truncations: the compact serving
    path guarantees their stored values are inside the compact caps
    (< 2^31, engine._compact_eligible), so the low half IS the value.
    tstamp/expire are ms-epoch int64s that do NOT fit 32 bits; they travel
    as exact (lo, hi) bitcast halves and only ever get rebased/committed
    through the pair helpers above.  The pipeline drain converts once per
    K-window dispatch and carries THIS form through the scan, so the O(C)
    plane conversion is amortized over the whole drain."""

    limit: jax.Array      # i32[C]
    duration: jax.Array   # i32[C]
    remaining: jax.Array  # i32[C]
    t_lo: jax.Array       # i32[C]
    t_hi: jax.Array       # i32[C]
    e_lo: jax.Array       # i32[C]
    e_hi: jax.Array       # i32[C]
    algo: jax.Array       # i32[C]


def fused_state_to_planes(state: BucketState) -> FusedState32:
    tp = lax.bitcast_convert_type(state.tstamp, I32)
    ep = lax.bitcast_convert_type(state.expire, I32)
    return FusedState32(
        limit=state.limit.astype(I32),
        duration=state.duration.astype(I32),
        remaining=state.remaining.astype(I32),
        t_lo=tp[:, 0], t_hi=tp[:, 1],
        e_lo=ep[:, 0], e_hi=ep[:, 1],
        algo=state.algo)


def fused_state_from_planes(st32: FusedState32) -> BucketState:
    pair64 = lambda lo, hi: lax.bitcast_convert_type(
        jnp.stack([lo, hi], axis=-1), I64)
    return BucketState(
        limit=st32.limit.astype(I64),
        duration=st32.duration.astype(I64),
        remaining=st32.remaining.astype(I64),
        tstamp=pair64(st32.t_lo, st32.t_hi),
        expire=pair64(st32.e_lo, st32.e_hi),
        algo=st32.algo)


class _FusedAux(NamedTuple):
    """Sorted-domain facts one fused window leaves behind for the in-kernel
    analytics accumulator (_accumulate_window_stats): everything the stats
    reduction needs is already computed by the window body — re-deriving it
    outside the kernel would resurrect the XLA shoulder the fold removes."""

    order: jax.Array        # i32[B] sort permutation (sorted -> lane)
    g: jax.Array            # i32[B] clipped sorted slot (arena gather index)
    s_slot: jax.Array       # i32[B] sorted clean slot (pads -> 2^31-1)
    s_valid: jax.Array      # bool[B]
    s_hits: jax.Array       # i32[B]
    s_init: jax.Array       # bool[B]
    status: jax.Array       # i32[B] sorted response status (0/1)
    commit_mask: jax.Array  # bool[B] one lane per valid slot
    any_commit: jax.Array   # bool scalar
    safe: jax.Array         # i32 scalar: first committing lane (0 if none)
    tgt: jax.Array          # i32[B] rejoined scatter targets


def _commit_ref(ref, aux_or_tuple, fin_vals, raw_vals):
    """One write per touched slot in race-free rejoin form (see the commit
    notes in _fused_window_body): non-commit lanes duplicate the first
    committing lane's write — same target, same value — because Pallas refs
    have no mode="drop" scatter.  Shared by the arena commit and the stats
    plane accumulation so the two scatters cannot drift."""
    commit_mask, any_commit, safe, tgt = aux_or_tuple
    cand = jnp.where(any_commit, fin_vals, raw_vals)
    ref[tgt] = jnp.where(commit_mask, fin_vals, jnp.take(cand, safe))


def _fused_window_body(n_lo, n_hi, req, arena):
    """The whole compact serving window as one kernel-body function over
    VALUES (decoded i32 word columns) and the 8 arena plane REFS — shared
    verbatim by the single-window kernel (_fused_kernel) and the K-grid
    drain kernel (_make_drain_kernel), so the two lowerings cannot drift.

    Stages (each the i32-halves image of the XLA path's stage, same order):
    decode (kernel.decode_batch) → sort (stable bitonic ≡ jnp.argsort) →
    segment prep (kernel.segment_structure / segment_all — the SAME
    functions window_prep calls) → window math (_window_math — the same
    body the split Pallas/XLA paths run) → commit (kernel.window_commit's
    one-write-per-slot scatter, race-free form) → response word encode
    (kernel.encode_output_word) + unsort.  The arena refs are the OUTPUT
    refs of an aliased pallas_call: aliasing initializes them from the
    inputs, so reading them before the commit reads the current arena —
    and in the K-grid drain the same read picks up the PREVIOUS grid
    step's commit, which is exactly the scan carry it replaces.

    Returns (w_lo, w_hi, rlimit, mism, aux) in REQUEST lane order (the
    in-body scatter unsort), with `mism` the i32 stored-vs-request limit
    mismatch flag and `aux` the sorted-domain facts for in-kernel stats."""
    (o_lim, o_dur, o_rem, o_tlo, o_thi, o_elo, o_ehi, o_algo) = arena
    B = req.shape[0]
    C = o_lim.shape[0]
    w0lo, w0hi, w1lo, w1hi = req[:, 0], req[:, 1], req[:, 2], req[:, 3]

    # ---- decode: kernel.decode_batch, reformulated on i32 halves ----
    # (bit 32 group of the i64 word lands in the hi half's low bits; the
    # hits mask clears the arithmetic-shift sign smear)
    slot_raw = w0lo - 1
    hits_raw = (w0hi >> 2) & jnp.int32(kernel.COMPACT_MAX_HITS - 1)
    limit = w1lo
    duration = w1hi & jnp.int32(0x7FFFFFFF)
    # 3-bit algorithm: i64 bit 33 -> hi bit 1, i64 bits 62..63 -> hi bits
    # 30..31 (the & 3 masks the arithmetic-shift sign smear)
    algo = ((w0hi >> 1) & 1) | (((w0hi >> 30) & 3) << 1)
    # concurrency releases: hits sign-extend from bit 27 (kernel.decode_batch)
    conc = jnp.int32(kernel.CONC_MAX_HITS)
    hits = jnp.where(algo == kernel.CONCURRENCY,
                     (hits_raw ^ conc) - conc, hits_raw)
    is_init = (w0hi & 1) == 1

    # ---- window_prep in sorted, rebased-i32 form ----
    valid = slot_raw >= 0
    agg = valid & ((slot_raw & jnp.int32(kernel.AGG_SLOT_BIT)) != 0)
    slot_clean = jnp.where(agg, slot_raw & jnp.int32(~kernel.AGG_SLOT_BIT),
                           slot_raw)
    sort_key = jnp.where(valid, slot_clean, jnp.int32(2**31 - 1))
    s_slot, order = _bitonic_sort_by_slot(sort_key)
    s_valid = jnp.take(valid, order)
    s_hits = jnp.take(hits, order)
    s_limit = jnp.take(limit, order)
    s_duration = jnp.take(duration, order)
    s_algo = jnp.take(algo, order)
    s_init = jnp.take(is_init, order)
    s_agg = jnp.take(agg, order)

    seg_start, seg_start_idx, pos, seg_len, commit_mask = (
        kernel.segment_structure(s_slot, s_valid, s_init))

    g = jnp.clip(s_slot, 0, C - 1)
    raw_lim = o_lim[g]
    raw_dur = o_dur[g]
    raw_rem = o_rem[g]
    raw_tlo = o_tlo[g]
    raw_thi = o_thi[g]
    raw_elo = o_elo[g]
    raw_ehi = o_ehi[g]
    raw_algo = o_algo[g]
    cur = _Reg(limit=raw_lim, duration=raw_dur, remaining=raw_rem,
               tstamp=_pair_rebase(raw_tlo, raw_thi, n_lo, n_hi),
               expire=_pair_rebase(raw_elo, raw_ehi, n_lo, n_hi),
               algo=raw_algo)
    # rebased image of prep's `s_init | (cur.expire < now)`: the clip
    # preserves the difference's sign, so rel < 0 ⇔ expire < now
    cur_fresh = s_init | (cur.expire < 0)

    h0 = jnp.take(s_hits, seg_start_idx)
    l0 = jnp.take(s_limit, seg_start_idx)
    d0 = jnp.take(s_duration, seg_start_idx)
    a0 = jnp.take(s_algo, seg_start_idx)
    fresh_seg = jnp.take(cur_fresh, seg_start_idx)
    # fold classification in the rebased-i32 domain (cur is already
    # rebased to now=0, so fold_classify's leak math matches the split
    # paths' int64 classification under the compact caps)
    seg_fold, nz, n_lead, hstar = kernel.fold_classify(
        s_hits, s_limit, s_duration, s_algo, s_agg, seg_start_idx,
        seg_len, h0, l0, d0, a0, fresh_seg, cur, jnp.int32(0))
    seg_single = s_valid & ~seg_fold & (seg_len == 1)
    max_pos = jnp.max(jnp.where(s_valid & ~seg_fold & ~seg_single, pos,
                                jnp.int32(-1)))

    # ---- the window math: the SAME body as the split paths ----
    out_sorted, fin = _window_math(
        jnp.int32(0), max_pos, s_valid, s_hits, s_limit, s_duration,
        s_algo, s_agg, pos, seg_len, seg_start_idx, seg_fold,
        h0, l0, d0, a0, fresh_seg, cur, nz, n_lead, hstar)

    # ---- commit: one write per touched slot, race-free scatter form ----
    # window_commit redirects non-commit lanes to slot C (out of range,
    # mode="drop"); Pallas refs have no drop mode, so instead every
    # non-commit lane REJOINS the first committing lane's write — same
    # target, same value, so duplicate-scatter order can't matter.  With
    # zero commit lanes (all-pad window) every lane rewrites the raw
    # current value of lane 0's row: a no-op.
    f_tlo, f_thi = _pair_reabs(fin.tstamp, n_lo, n_hi)
    f_elo, f_ehi = _pair_reabs(fin.expire, n_lo, n_hi)
    any_commit = jnp.any(commit_mask)
    safe = jnp.argmax(commit_mask).astype(I32)
    tgt = jnp.where(commit_mask, g, jnp.take(g, safe))
    cm = (commit_mask, any_commit, safe, tgt)

    _commit_ref(o_lim, cm, fin.limit, raw_lim)
    _commit_ref(o_dur, cm, fin.duration, raw_dur)
    _commit_ref(o_rem, cm, fin.remaining, raw_rem)
    _commit_ref(o_tlo, cm, f_tlo, raw_tlo)
    _commit_ref(o_thi, cm, f_thi, raw_thi)
    _commit_ref(o_elo, cm, f_elo, raw_elo)
    _commit_ref(o_ehi, cm, f_ehi, raw_ehi)
    _commit_ref(o_algo, cm, fin.algo, raw_algo)

    # ---- response encode (kernel.encode_output_word image) + unsort ----
    # reset word: enc 0 iff the ABSOLUTE reset is 0 — the leaky no-reset
    # sentinel (rel == 0 on a leaky lane) or an absolute time that lands
    # exactly on zero; otherwise clip(rel, 0, 2^31-2) + 1, exact because
    # reset64 - now == rel in int64
    # leaky AND concurrency use reset 0 as the no-reset sentinel
    leaky0 = (((s_algo == kernel.LEAKY_BUCKET)
               | (s_algo == kernel.CONCURRENCY))
              & (out_sorted.reset_time == 0))
    ab_lo, ab_hi = _pair_reabs(out_sorted.reset_time, n_lo, n_hi)
    reset_zero = leaky0 | ((ab_lo == 0) & (ab_hi == 0))
    enc = jnp.where(reset_zero, jnp.int32(0),
                    jnp.clip(out_sorted.reset_time, 0,
                             jnp.int32(2**31 - 2)) + 1)
    w_lo_s = (out_sorted.status << 31) | jnp.maximum(out_sorted.remaining, 0)
    unsort = lambda v: jnp.zeros_like(v).at[order].set(v)
    w_lo = unsort(w_lo_s)
    w_hi = unsort(enc)
    rlimit = unsort(out_sorted.limit)
    mism = jnp.any((out_sorted.limit != s_limit) & s_valid).astype(I32)
    aux = _FusedAux(order=order, g=g, s_slot=s_slot, s_valid=s_valid,
                    s_hits=s_hits, s_init=s_init, status=out_sorted.status,
                    commit_mask=commit_mask, any_commit=any_commit,
                    safe=safe, tgt=tgt)
    return w_lo, w_hi, rlimit, mism, aux


def _fused_kernel(now_ref, req_ref,
                  a_lim, a_dur, a_rem, a_tlo, a_thi, a_elo, a_ehi, a_algo,
                  o_lim, o_dur, o_rem, o_tlo, o_thi, o_elo, o_ehi, o_algo,
                  o_wlo, o_whi, o_rlimit, o_mism):
    """Single-window fused kernel: one _fused_window_body call.  The a_*
    input refs alias the o_* outputs (and so are never read — the body
    reads the aliased o_* planes, which IS the input arena)."""
    del a_lim, a_dur, a_rem, a_tlo, a_thi, a_elo, a_ehi, a_algo
    w_lo, w_hi, rlimit, mism, _ = _fused_window_body(
        now_ref[0], now_ref[1], req_ref[:],
        (o_lim, o_dur, o_rem, o_tlo, o_thi, o_elo, o_ehi, o_algo))
    o_wlo[...] = w_lo
    o_whi[...] = w_hi
    o_rlimit[...] = rlimit
    o_mism[0] = mism


def window_step_fused_planes(st32: FusedState32, packed, now, *,
                             interpret: bool = False):
    """One compact serving window as ONE pallas_call over the plane-form
    arena.  Returns (new_st32, words i64[B], limits i64[B], mism bool) —
    `words` is exactly kernel.encode_output_word(out, now) and `limits`
    the stored-limit response plane, matching the pipeline drain's wire.

    Exactness contract: identical to decode_batch → window_step (the int64
    oracle) → encode_output_word under the compact wire caps plus
    arena-written-under-caps — the same contract window_step_compact32_xla
    carries, pinned by tests/test_fused_megakernel.py differentials.
    """
    B = packed.shape[0]
    C = st32.limit.shape[0]
    assert B & (B - 1) == 0, "fused megakernel needs power-of-two lanes"
    now = jnp.asarray(now, I64)
    req32 = lax.bitcast_convert_type(packed, I32).reshape(B, 4)
    now32 = lax.bitcast_convert_type(now.reshape((1,)), I32).reshape((2,))

    vma = typeof_vma(packed)
    lane_sds = lambda shape: shape_dtype_struct(shape, I32, vma=vma)
    plane_sds = lambda: shape_dtype_struct((C,), I32,
                                           vma=typeof_vma(st32.limit))
    bspec = pl.BlockSpec((B,), lambda: (0,))
    aspec = pl.BlockSpec(memory_space=pl.ANY)
    outs = pl.pallas_call(
        _fused_kernel,
        in_specs=[pl.BlockSpec((2,), lambda: (0,)),
                  pl.BlockSpec((B, 4), lambda: (0, 0))] + [aspec] * 8,
        out_specs=[aspec] * 8 + [bspec] * 3
        + [pl.BlockSpec((1,), lambda: (0,))],
        out_shape=[plane_sds() for _ in range(8)]
        + [lane_sds((B,)) for _ in range(3)] + [lane_sds((1,))],
        # arena planes update in place: inputs 2..9 alias outputs 0..7
        input_output_aliases={i + 2: i for i in range(8)},
        interpret=interpret,
    )(now32, req32, *st32)
    new32 = FusedState32(*outs[:8])
    words = lax.bitcast_convert_type(
        jnp.stack([outs[8], outs[9]], axis=-1), I64)
    limits = outs[10].astype(I64)
    return new32, words, limits, outs[11][0] != 0


@functools.partial(jax.jit, static_argnames=("interpret",))
def window_step_fused(state: BucketState, packed, now, *,
                      interpret: bool = False):
    """BucketState-in/BucketState-out wrapper around the fused megakernel
    (single-window call sites).  The pipeline drain avoids the per-window
    O(C) plane conversion by carrying FusedState32 through its scan and
    calling window_step_fused_planes directly."""
    st32, words, limits, mism = window_step_fused_planes(
        fused_state_to_planes(state), packed, now, interpret=interpret)
    return fused_state_from_planes(st32), words, limits, mism


# ---- the K-grid staged drain: all K windows in ONE pallas_call ------------


def _accumulate_window_stats(aux: _FusedAux, ten, tenant_slots,
                             d_occ, d_over, d_hlo, d_hhi,
                             t_occ, t_over, t_hlo, t_hhi, hdr):
    """Fold one window's analytics contributions into the drain's resident
    stats planes, entirely in-kernel — the i32-halves image of
    analytics.shard_stats's dense / tenant / header accumulation.

    Hit counts are i64 in the oracle (per-lane hits < 2^28, but a window's
    per-slot total can reach B * 2^28 and the drain total K times that), and
    Mosaic has no 64-bit vectors — so hits are summed as SPLIT 14-bit limbs
    (lo14 = hits & 0x3FFF, hi14 = hits >> 14; each limb's window sum stays
    under B * 2^14 ≪ 2^31) and reconstructed into an exact (lo, hi) pair
    via value = lo14_sum + hi14_sum * 2^14 before the pair-add into the
    accumulator planes.  All adds are exact integer ops in both domains, so
    the result is bit-identical to the oracle's i64 scatter-adds.

    The dense per-slot planes accumulate at the window's commit lanes (one
    per valid slot — kernel.segment_structure's commit_mask) over the
    slot's PHYSICAL lane range [phys_start, next_phys): virtual segments
    split on is_init lanes, but the stats group purely by slot, so the
    range sums must span every virtual segment of the slot.  Tenant rows
    and the header use full-plane adds (tenant_slots is small)."""
    B = aux.order.shape[0]
    occ_i = aux.s_valid.astype(I32)
    over_i = jnp.where(aux.s_valid, aux.status, 0)
    hits_m = jnp.where(aux.s_valid, aux.s_hits, 0)
    init_i = (aux.s_init & aux.s_valid).astype(I32)
    lo14 = hits_m & jnp.int32(0x3FFF)
    hi14 = hits_m >> 14
    limb_pair = lambda lo, hi: _p_add((lo, jnp.int32(0)),
                                      (hi << 14, hi >> 18))

    # physical slot boundaries (segment_structure's phys_start lattice,
    # recomputed here because the body only exposes the virtual structure)
    idx = lax.iota(I32, B)
    prev_slot = jnp.take(aux.s_slot, jnp.maximum(idx - 1, 0))
    phys_start = (idx == 0) | (aux.s_slot != prev_slot)
    phys_start_idx = lax.cummax(jnp.where(phys_start, idx, jnp.int32(0)))
    nxt = jnp.minimum(idx + 1, B - 1)
    shifted = jnp.where(jnp.take(phys_start, nxt) & (idx < B - 1),
                        idx + 1, jnp.int32(B))
    next_phys = lax.cummin(shifted, reverse=True)

    def rng_sum(f):
        # sum of f over [phys_start_idx, next_phys) via prefix differences
        cs = jnp.cumsum(f)
        return (jnp.take(cs, next_phys - 1) - jnp.take(cs, phys_start_idx)
                + jnp.take(f, phys_start_idx))

    cm = (aux.commit_mask, aux.any_commit, aux.safe, aux.tgt)
    occ_w = rng_sum(occ_i)
    over_w = rng_sum(over_i)
    w_pair = limb_pair(rng_sum(lo14), rng_sum(hi14))
    cur_occ = d_occ[aux.g]
    cur_over = d_over[aux.g]
    cur_h = (d_hlo[aux.g], d_hhi[aux.g])
    new_h = _p_add(cur_h, w_pair)
    _commit_ref(d_occ, cm, cur_occ + occ_w, cur_occ)
    _commit_ref(d_over, cm, cur_over + over_w, cur_over)
    _commit_ref(d_hlo, cm, new_h[0], cur_h[0])
    _commit_ref(d_hhi, cm, new_h[1], cur_h[1])

    # tenant rows: one-hot masked column sums (no scatter needed — the
    # tenant axis is small), full-plane accumulate
    tid = jnp.clip(jnp.take(ten, aux.order), 0,
                   jnp.int32(tenant_slots - 1))
    oh = (tid[:, None] == lax.iota(I32, tenant_slots)[None, :]).astype(I32)
    col = lambda v: jnp.sum(oh * v[:, None], axis=0, dtype=I32)
    t_occ[...] = t_occ[...] + col(occ_i)
    t_over[...] = t_over[...] + col(over_i)
    t_pair = _p_add((t_hlo[...], t_hhi[...]),
                    limb_pair(col(lo14), col(hi14)))
    t_hlo[...] = t_pair[0]
    t_hhi[...] = t_pair[1]

    # header counters: [lanes, hits_lo, hits_hi, over, init, 0, 0, 0]
    h_pair = _p_add((hdr[1], hdr[2]),
                    limb_pair(jnp.sum(lo14, dtype=I32),
                              jnp.sum(hi14, dtype=I32)))
    hdr[0] = hdr[0] + jnp.sum(occ_i, dtype=I32)
    hdr[1] = h_pair[0]
    hdr[2] = h_pair[1]
    hdr[3] = hdr[3] + jnp.sum(over_i, dtype=I32)
    hdr[4] = hdr[4] + jnp.sum(init_i, dtype=I32)


def _make_drain_kernel(with_stats: bool, tenant_slots: int):
    """Kernel factory for the K-grid drain: grid=(K,), one
    _fused_window_body call per grid step over per-window request blocks,
    with the arena planes carried ACROSS grid steps through the aliased
    ANY-space output refs (step k reads the planes step k-1 committed —
    the in-kernel image of the lax.scan carry it replaces).  With stats,
    nine accumulator planes ride along: zeroed on the first grid step,
    folded per window by _accumulate_window_stats."""
    def drain_kernel(*refs):
        now_ref, req_ref = refs[0], refs[1]
        i = 2
        ten_ref = None
        if with_stats:
            ten_ref = refs[i]
            i += 1
        arena = refs[i + 8:i + 16]   # outputs; refs[i:i+8] are the aliases
        j = i + 16
        o_wlo, o_whi, o_rlimit, o_mism = refs[j:j + 4]
        stats_refs = refs[j + 4:]
        if with_stats:
            @pl.when(pl.program_id(0) == 0)
            def _zero_stats():
                for r in stats_refs:
                    r[...] = jnp.zeros(r.shape, r.dtype)
        w_lo, w_hi, rlimit, mism, aux = _fused_window_body(
            now_ref[0, 0], now_ref[0, 1], req_ref[0], arena)
        o_wlo[0, :] = w_lo
        o_whi[0, :] = w_hi
        o_rlimit[0, :] = rlimit
        o_mism[0] = mism
        if with_stats:
            _accumulate_window_stats(aux, ten_ref[0], tenant_slots,
                                     *stats_refs)
    return drain_kernel


def window_drain_fused_planes(st32: FusedState32, packed, nows, *,
                              interpret: bool = False, tenants=None,
                              tenant_slots: int = 0):
    """The WHOLE K-window compact drain as ONE pallas_call: the K-major
    grid dimension replaces the lax.scan skeleton, so the scan's
    per-iteration slice/convert/stack shoulders vanish from the trace and
    the composed drain executes O(1) kernels total instead of O(K).

    packed i64[K, B, 2], nows i64[K]; returns (new_st32, words i64[K, B],
    limits i64[K, B], mism bool[K], stats) — bit-identical per window to K
    sequential window_step_fused_planes calls (same body, same carry, just
    carried through the grid instead of a scan).

    With `tenants` (i32[K, B]) the drain ALSO folds the analytics
    accumulation in-kernel and `stats` returns the nine i32 planes
    (d_occ/d_over/d_hlo/d_hhi [C], t_occ/t_over/t_hlo/t_hhi [tenant_slots],
    hdr [8]) that analytics.staged_stats_tail finishes into the canonical
    stats vector; otherwise stats is None."""
    K, B = packed.shape[0], packed.shape[1]
    C = st32.limit.shape[0]
    assert B & (B - 1) == 0, "fused megakernel needs power-of-two lanes"
    req32 = lax.bitcast_convert_type(packed, I32).reshape(K, B, 4)
    nows32 = lax.bitcast_convert_type(nows, I32).reshape(K, 2)
    with_stats = tenants is not None

    lane_sds = lambda shape: shape_dtype_struct(shape, I32,
                                                vma=typeof_vma(packed))
    plane_sds = lambda shape: shape_dtype_struct(
        shape, I32, vma=typeof_vma(st32.limit))
    aspec = pl.BlockSpec(memory_space=pl.ANY)
    in_specs = [pl.BlockSpec((1, 2), lambda k: (k, 0)),
                pl.BlockSpec((1, B, 4), lambda k: (k, 0, 0))]
    inputs = [nows32, req32]
    if with_stats:
        in_specs.append(pl.BlockSpec((1, B), lambda k: (k, 0)))
        inputs.append(tenants.astype(I32))
    arena_base = len(inputs)
    in_specs += [aspec] * 8
    inputs += list(st32)
    out_specs = ([aspec] * 8
                 + [pl.BlockSpec((1, B), lambda k: (k, 0))] * 3
                 + [pl.BlockSpec((1,), lambda k: (k,))])
    out_shape = ([plane_sds((C,)) for _ in range(8)]
                 + [lane_sds((K, B)) for _ in range(3)]
                 + [lane_sds((K,))])
    if with_stats:
        out_specs += [aspec] * 9
        out_shape += ([plane_sds((C,)) for _ in range(4)]
                      + [plane_sds((tenant_slots,)) for _ in range(4)]
                      + [plane_sds((8,))])
    outs = pl.pallas_call(
        _make_drain_kernel(with_stats, tenant_slots),
        grid=(K,),
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        input_output_aliases={arena_base + i: i for i in range(8)},
        interpret=interpret,
    )(*inputs)
    new32 = FusedState32(*outs[:8])
    words = lax.bitcast_convert_type(
        jnp.stack([outs[8], outs[9]], axis=-1), I64)
    limits = outs[10].astype(I64)
    mism = outs[11] != 0
    stats = tuple(outs[12:]) if with_stats else None
    return new32, words, limits, mism, stats


# ---- the analytics finisher: sketch + top-k as ONE kernel -----------------


def _make_stats_finish_kernel(C, D, W, tenant_slots, topk, over_weight):
    """Kernel factory for the staged analytics FINISH: everything
    analytics.staged_stats_tail does in ~110 XLA equations — count-min
    decay + scatter, per-slot estimates, top-k candidate ranking, tenant
    rows, header — as one kernel body (census cost: 1).

    The tricky lowerings, all Mosaic-legal:
      * scatter-add with DUPLICATE hash buckets (np.add.at semantics)
        becomes a one-hot masked sum per sketch row: bucket w receives
        sum_c [h[r, c] == w] * dense_w[c], accumulated in 14-bit limbs so
        the i32 lanes never overflow, recombined into exact i64 pairs;
      * the i64 sketch decays via the variable pair shift (_p_shr);
      * lax.top_k (ties -> lowest index first) becomes the descending
        bitonic network with the index as explicit tiebreak, padded to a
        power of two with INT64_MIN scores.
    over_weight enters as static 14-bit limbs so dense_w = dense_h +
    over_weight * dense_o stays exact in pair space for any config value
    (dense_o is a lane count, < 2^17 for every real geometry)."""
    N = 1
    while N < C:
        N *= 2
    ow = int(over_weight)
    ow_limbs = [(ow >> (14 * j)) & 0x3FFF for j in range(4)] + [
        (ow >> 56) & 0xFF]

    def kern(now_ref, dk_ref, h_ref, docc_ref, dover_ref, dhlo_ref,
             dhhi_ref, tocc_ref, tover_ref, thlo_ref, thhi_ref, hdr_ref,
             exp_ref, a_sk_ref, o_sk_ref, o_stats_ref):
        del a_sk_ref  # aliased: o_sk_ref initializes from it
        now = (now_ref[0, 0], now_ref[0, 1])
        dk = dk_ref[0]
        docc, dover = docc_ref[...], dover_ref[...]
        dh = (dhlo_ref[...], dhhi_ref[...])

        # dense_w = dense_h + over_weight * dense_o, exact via limb products
        dw = _p_add(dh, _p_from_limbs(*[dover * l for l in ow_limbs]))
        limbs = _p_limbs(dw)

        # sketch rows: decay, duplicate-safe scatter-add, per-slot estimate
        iota_wc = lax.broadcasted_iota(I32, (W, C), 0)
        est = None
        for r in range(D):
            hr = h_ref[r]
            onehot = (iota_wc == hr[None, :]).astype(I32)
            sums = [jnp.sum(onehot * l[None, :], axis=1, dtype=I32)
                    for l in limbs]
            contrib = _p_from_limbs(*sums)
            old = (o_sk_ref[r, :, 0], o_sk_ref[r, :, 1])
            new = _p_add(_p_shr(old, dk), contrib)
            o_sk_ref[r] = jnp.stack([new[0], new[1]], axis=-1)
            est_r = (jnp.take(new[0], hr), jnp.take(new[1], hr))
            est = est_r if est is None else _p_min(est, est_r)

        # top-k by estimate over touched slots (untouched score -1), ties
        # to the LOWER slot — lax.top_k semantics, which the candidate
        # table's rolling host merge relies on
        touched = docc > 0
        s_lo = jnp.where(touched, est[0], -1)
        s_hi = jnp.where(touched, est[1], -1)
        lane = lax.iota(I32, N)
        if N > C:
            pad_lo = jnp.zeros((N - C,), I32)
            pad_hi = jnp.full((N - C,), -2147483648, I32)
            s_lo = jnp.concatenate([s_lo, pad_lo])
            s_hi = jnp.concatenate([s_hi, pad_hi])
        key_lo, key_hi, idx = s_lo, s_hi, lane
        k = 2
        while k <= N:
            j = k // 2
            while j >= 1:
                partner = lane ^ j
                p_lo = jnp.take(key_lo, partner)
                p_hi = jnp.take(key_hi, partner)
                p_idx = jnp.take(idx, partner)
                kp, pp = (key_lo, key_hi), (p_lo, p_hi)
                prec = _p_lt(pp, kp) | (_p_eq(kp, pp) & (idx < p_idx))
                ascending = (lane & k) == 0
                is_lower = (lane & j) == 0
                keep = jnp.where(is_lower, prec == ascending,
                                 prec != ascending)
                key_lo = jnp.where(keep, key_lo, p_lo)
                key_hi = jnp.where(keep, key_hi, p_hi)
                idx = jnp.where(keep, idx, p_idx)
                j //= 2
            k *= 2
        top_slot = idx[:topk]
        top = (key_lo[:topk], key_hi[:topk])
        valid = top[1] >= 0
        c_slot = _p_where(valid, _p_sext(top_slot), (-1, -1))
        c_est = _p_where(valid, top, _P0)
        c_h = _p_where(valid, (jnp.take(dh[0], top_slot),
                               jnp.take(dh[1], top_slot)), _P0)
        c_o = _p_where(valid, _p_sext(jnp.take(dover, top_slot)), _P0)
        cand_lo = jnp.stack([c_slot[0], c_est[0], c_h[0], c_o[0]], axis=-1)
        cand_hi = jnp.stack([c_slot[1], c_est[1], c_h[1], c_o[1]], axis=-1)

        tocc, tover = tocc_ref[...], tover_ref[...]
        t_lo = jnp.stack([tocc, thlo_ref[...], tover], axis=-1)
        t_hi = jnp.stack([tocc >> 31, thhi_ref[...], tover >> 31], axis=-1)

        exp = (exp_ref[:, 0], exp_ref[:, 1])
        live = jnp.sum(_p_lt(now, exp).astype(I32), dtype=I32)
        expd = jnp.sum(((~_p_is0(exp)) & ~_p_lt(now, exp)).astype(I32),
                       dtype=I32)
        hdr = hdr_ref[...]
        lanes, over, init = hdr[0], hdr[3], hdr[4]
        under = lanes - over
        zero = jnp.zeros_like(lanes)
        head_lo = jnp.stack([lanes, hdr[1], under, over, init,
                             live, expd, zero])
        head_hi = jnp.stack([lanes >> 31, hdr[2], under >> 31, over >> 31,
                             init >> 31, zero, zero, zero])

        Tn = tenant_slots
        o_stats_ref[0:8] = jnp.stack([head_lo, head_hi], axis=-1)
        o_stats_ref[8:8 + 3 * Tn] = jnp.stack(
            [t_lo.reshape(3 * Tn), t_hi.reshape(3 * Tn)], axis=-1)
        o_stats_ref[8 + 3 * Tn:] = jnp.stack(
            [cand_lo.reshape(4 * topk), cand_hi.reshape(4 * topk)], axis=-1)

    return kern


def staged_stats_finish(sketch, drain_stats, expire, now, decay, *,
                        tenant_slots: int, topk: int, over_weight: int,
                        interpret: bool = False):
    """analytics.staged_stats_tail as ONE pallas_call — the composed
    drain's analytics finish at census cost ~8 instead of ~110.  Consumes
    the drain kernel's nine i32 stats planes plus the resident sketch
    (aliased: decayed and accumulated in place) and returns the SAME
    (new_sketch i64[D, W], stats i64[8 + 3*tenant_slots + 4*topk]) pair,
    bit-identical to the XLA tail — pinned by the staging differential
    suites.  The hash lattice is data-independent, so it enters as ONE
    device constant ([D, C] i32) rather than traced equations."""
    from gubernator_tpu.ops.analytics import hash_slots
    D, W = sketch.shape
    C = drain_stats[0].shape[0]
    h_np = np.stack([hash_slots(np, np.arange(C, dtype=np.int64), r, W)
                     for r in range(D)]).astype(np.int32)
    pc = lambda a: lax.bitcast_convert_type(a, I32)
    now32 = pc(jnp.reshape(now, (1,)))
    dk32 = jnp.reshape(decay, (1,)).astype(I32)
    sk32 = pc(sketch)
    vma = typeof_vma(drain_stats[0])
    L = 8 + 3 * tenant_slots + 4 * topk
    aspec = pl.BlockSpec(memory_space=pl.ANY)
    new_sk, stats32 = pl.pallas_call(
        _make_stats_finish_kernel(C, D, W, tenant_slots, topk, over_weight),
        in_specs=[aspec] * 14,
        out_specs=[aspec] * 2,
        out_shape=[shape_dtype_struct((D, W, 2), I32, vma=vma),
                   shape_dtype_struct((L, 2), I32, vma=vma)],
        input_output_aliases={13: 0},
        interpret=interpret,
    )(now32, dk32, jnp.asarray(h_np), *drain_stats, pc(expire), sk32)
    p64 = lambda a: lax.bitcast_convert_type(a, I64)
    return p64(new_sk), p64(stats32)


# ---- the staged GLOBAL ladder: transition as (lo, hi) pair arithmetic -----


def _pair_transition(ent, h, req_limit, req_duration, req_algo, now, fresh,
                     rate, leak):
    """kernel.transition's non-AGG ladder on (lo, hi) i32 pairs — the
    Mosaic-legal form of the FULL-i64 GLOBAL state machine (the GLOBAL
    arena is exempt from the compact caps, so the rebased-i32 trick the
    serving window uses would not be exact here).  Every value except the
    algorithm/status columns is a pair; the two integer divisions (rate,
    leak — Mosaic has no 64-bit divide either) arrive precomputed from
    kernel.transition_precompute, which is exact because both depend only
    on pre-psum data.  Line-for-line in lockstep with transition above."""
    L, D, R, T, E, A = ent
    is_token = req_algo == kernel.TOKEN_BUCKET
    OVER, UNDER = kernel.OVER_LIMIT, kernel.UNDER_LIMIT

    # ---- init path ----
    over_init = _p_lt(req_limit, h)           # h > req_limit
    init_R = _p_where(over_init, _P0, _p_sub(req_limit, h))
    init_status = jnp.where(over_init, OVER, UNDER).astype(I32)
    now_rd = _p_add(now, req_duration)
    init_T = _p_where(is_token, now_rd, now)

    # ---- token bucket hit path ----
    tb_at_zero = _p_is0(R)
    tb_read = _p_is0(h)
    tb_drain = _p_eq(h, R)
    tb_over = _p_lt(R, h)
    R_h = _p_sub(R, h)
    t_status = kernel._chain(
        [(tb_at_zero, OVER), (tb_read, UNDER), (tb_drain, UNDER),
         (tb_over, OVER)], UNDER).astype(I32)
    t_resp_R = _p_chain(
        [(tb_at_zero, _P0), (tb_read, R), (tb_drain, _P0), (tb_over, R)],
        R_h)
    t_new_R = _p_chain(
        [(tb_at_zero, R), (tb_read, R), (tb_drain, _P0), (tb_over, R)],
        R_h)

    # ---- leaky bucket hit path ----
    R2 = _p_add(R, _p_min(leak, _p_sub(L, R)))
    T2 = _p_where(_p_is0(h), T, now)
    lb_at_zero = _p_is0(R2)
    lb_drain = _p_eq(h, R2)
    lb_over = _p_lt(R2, h)
    lb_read = _p_is0(h)
    now_rate = _p_add(now, rate)
    l_status = kernel._chain(
        [(lb_at_zero, OVER), (lb_drain, UNDER), (lb_over, OVER),
         (lb_read, UNDER)], UNDER).astype(I32)
    R2_h = _p_sub(R2, h)
    l_resp_R = _p_chain(
        [(lb_at_zero, _P0), (lb_drain, _P0), (lb_over, R2), (lb_read, R2)],
        R2_h)
    l_reset = _p_chain(
        [(lb_at_zero, now_rate), (lb_drain, _P0), (lb_over, now_rate),
         (lb_read, _P0)], _P0)
    l_new_R = _p_chain(
        [(lb_at_zero, R2), (lb_drain, _P0), (lb_over, R2), (lb_read, R2)],
        R2_h)
    l_hit = ~(lb_at_zero | lb_drain | lb_over | lb_read)
    l_new_E = _p_where(l_hit, now_rd, E)

    # ---- combine ----
    pw = lambda t, l: _p_where(is_token, t, l)
    hit_R = pw(t_new_R, l_new_R)
    hit_T = pw(T, T2)
    hit_E = pw(E, l_new_E)
    hit_status = jnp.where(is_token, t_status, l_status)
    hit_resp_R = pw(t_resp_R, l_resp_R)
    hit_reset = pw(T, l_reset)

    fw = lambda i, hh: _p_where(fresh, i, hh)
    new_reg = _Reg(
        limit=fw(req_limit, L),
        duration=fw(req_duration, D),
        remaining=fw(init_R, hit_R),
        tstamp=fw(init_T, hit_T),
        expire=fw(now_rd, hit_E),
        algo=jnp.where(fresh, req_algo, A),
    )
    out = WindowOutput(
        status=jnp.where(fresh, init_status, hit_status),
        limit=fw(req_limit, L),
        remaining=fw(init_R, hit_resp_R),
        reset_time=fw(_p_where(is_token, now_rd, _P0), hit_reset),
    )
    return new_reg, out


def _global_kernel(now_ref, bi32_ref, bi64_ref, gi32_ref, gi64_ref, rl_ref,
                   o_lim, o_dur, o_rem, o_ts, o_exp, o_algo, o_read):
    """kernel.global_combined as ONE kernel body: the replica-read gather,
    both freshness tests, the [Bg|G] lane concat, the pair transition
    ladder and the touched-merge apply — everything between the psum and
    the outputs.  Operands arrive PACKED (one concat + one bitcast per
    dtype class on the XLA side, sliced apart here where slicing is free):
    bi32 [3*Bg] = slot|algo|is_init, bi64 [3*Bg, 2] = hits|limit|duration,
    gi32 [2*G] = state.algo|cfg.algo, gi64 [8*G, 2] = state limit|duration|
    remaining|tstamp|expire then cfg limit|duration then summed, rl
    [2*(Bg+G), 2] = rate|leak.  o_read [Bg, 4, 2] is the read half already
    in the fused response layout (status|limit|remaining|reset pairs) —
    one bitcast away from the wire's gfused block."""
    now = (now_ref[0, 0], now_ref[0, 1])
    G = gi32_ref.shape[0] // 2
    Bg = bi32_ref.shape[0] // 3
    bi32, bi64 = bi32_ref[...], bi64_ref[...]
    gi32, gi64 = gi32_ref[...], gi64_ref[...]
    slot, b_algo = bi32[:Bg], bi32[Bg:2 * Bg]
    b_init = bi32[2 * Bg:]
    bp = lambda i: (bi64[i * Bg:(i + 1) * Bg, 0],
                    bi64[i * Bg:(i + 1) * Bg, 1])
    b_hits, b_lim, b_dur = bp(0), bp(1), bp(2)
    gp = lambda i: (gi64[i * G:(i + 1) * G, 0], gi64[i * G:(i + 1) * G, 1])
    st_lim, st_dur, st_rem, st_ts, st_exp = (gp(0), gp(1), gp(2), gp(3),
                                             gp(4))
    c_lim, c_dur, summed = gp(5), gp(6), gp(7)
    st_algo, c_algo = gi32[:G], gi32[G:]
    n = Bg + G
    rl = rl_ref[...]
    rate = (rl[:n, 0], rl[:n, 1])
    leak = (rl[n:, 0], rl[n:, 1])

    g = jnp.clip(slot, 0, G - 1)
    gt = lambda p: (jnp.take(p[0], g), jnp.take(p[1], g))
    r_exp = gt(st_exp)
    r_algo = jnp.take(st_algo, g)
    r_fresh = (b_init != 0) | _p_lt(r_exp, now) | (b_algo != r_algo)
    a_fresh = _p_lt(st_exp, now) | (c_algo != st_algo)

    catp = lambda a, b: (jnp.concatenate([a[0], b[0]]),
                         jnp.concatenate([a[1], b[1]]))
    cat = jnp.concatenate
    ent = _Reg(
        limit=catp(gt(st_lim), st_lim),
        duration=catp(gt(st_dur), st_dur),
        remaining=catp(gt(st_rem), st_rem),
        tstamp=catp(gt(st_ts), st_ts),
        expire=catp(r_exp, st_exp),
        algo=cat([r_algo, st_algo]),
    )
    h = catp(_p_where(r_fresh, b_hits, _P0), summed)
    new_reg, out = _pair_transition(
        ent, h,
        catp(b_lim, c_lim),
        catp(b_dur, c_dur),
        cat([b_algo, c_algo]),
        now,
        cat([r_fresh, a_fresh]),
        rate, leak)

    # read half: the first Bg lanes' responses, in fused response order
    take_bg = lambda p: (p[0][:Bg], p[1][:Bg])
    rlim, rrem, rres = (take_bg(out.limit), take_bg(out.remaining),
                        take_bg(out.reset_time))
    status = out.status[:Bg]
    o_read[...] = jnp.stack(
        [jnp.stack([status, rlim[0], rrem[0], rres[0]], axis=-1),
         jnp.stack([jnp.zeros_like(status), rlim[1], rrem[1], rres[1]],
                   axis=-1)], axis=-1)

    # apply half: the last G lanes' registers, merged on touched slots
    touched = ~_p_is0(summed)
    ap = lambda p: (p[0][Bg:], p[1][Bg:])
    mg = lambda new, old: _p_where(touched, new, old)
    w2 = lambda ref, p: ref.__setitem__(
        Ellipsis, jnp.stack([p[0], p[1]], axis=-1))
    w2(o_lim, mg(ap(new_reg.limit), st_lim))
    w2(o_dur, mg(ap(new_reg.duration), st_dur))
    w2(o_rem, mg(ap(new_reg.remaining), st_rem))
    w2(o_ts, mg(ap(new_reg.tstamp), st_ts))
    w2(o_exp, mg(ap(new_reg.expire), st_exp))
    o_algo[...] = jnp.where(touched, new_reg.algo[Bg:], st_algo)


@functools.partial(jax.jit, static_argnames=("interpret", "fused_out"))
def global_combined_staged(state: BucketState, cfg: GlobalConfig,
                           batch: WindowBatch, summed_hits, now, *,
                           interpret: bool = False, fused_out: bool = False):
    """Drop-in replacement for kernel.global_combined as ONE pallas_call
    (plus the two hoisted int64 divisions in XLA): the GLOBAL sub-window's
    ~200-equation transition ladder collapses to a single kernel, which is
    what takes the composed drain's census from tens to single digits.
    Bit-exact with global_combined for EVERY i64 input (the pair ops are
    exact two's-complement images, wrap included) — pinned by
    tests/test_fused_megakernel.py differentials.

    Same-dtype operands cross as ONE concat + ONE bitcast (the census
    counts every surviving XLA op, so nineteen per-field bitcasts would
    hand back much of what folding the ladder saved).  With
    `fused_out=True` the read half returns as the drain wire's gfused
    block i64[Bg, 4] (status|limit|remaining|reset) straight from the
    kernel — the composed drain ships it without a single stacking op;
    otherwise it unpacks to the legacy WindowOutput."""
    G = state.limit.shape[0]
    now = jnp.asarray(now, I64)
    # the only non-pair-legal ops in the ladder: two int64 floor-divides,
    # batched over the [Bg|G] concat (they read pre-psum data only)
    g = jnp.clip(batch.slot, 0, G - 1)
    rate, leak = kernel.transition_precompute(
        jnp.concatenate([state.duration[g], state.duration]),
        jnp.concatenate([state.tstamp[g], state.tstamp]),
        jnp.concatenate([batch.limit, cfg.limit]),
        now)

    pc = lambda a: lax.bitcast_convert_type(a, I32)      # i64[n] -> [n, 2]
    now32 = pc(now.reshape((1,)))
    bi32 = jnp.concatenate([batch.slot, batch.algo,
                            batch.is_init.astype(I32)])
    bi64 = pc(jnp.concatenate([batch.hits, batch.limit, batch.duration]))
    gi32 = jnp.concatenate([state.algo, cfg.algo])
    gi64 = pc(jnp.concatenate([state.limit, state.duration, state.remaining,
                               state.tstamp, state.expire, cfg.limit,
                               cfg.duration, summed_hits]))
    rl = pc(jnp.concatenate([rate, leak]))
    vma_b = typeof_vma(batch.slot)
    vma_s = typeof_vma(state.limit)
    Bg = batch.slot.shape[0]
    sds = lambda shape, vma: shape_dtype_struct(shape, I32, vma=vma)
    full = pl.BlockSpec(memory_space=pl.ANY)
    outs = pl.pallas_call(
        _global_kernel,
        in_specs=[full] * 6,
        out_specs=[full] * 7,
        out_shape=([sds((G, 2), vma_s)] * 5 + [sds((G,), vma_s)]
                   + [sds((Bg, 4, 2), vma_b)]),
        interpret=interpret,
    )(now32, bi32, bi64, gi32, gi64, rl)
    p64 = lambda a: lax.bitcast_convert_type(a, I64)     # [n, 2] -> i64[n]
    new_state = BucketState(
        limit=p64(outs[0]), duration=p64(outs[1]), remaining=p64(outs[2]),
        tstamp=p64(outs[3]), expire=p64(outs[4]), algo=outs[5])
    read64 = p64(outs[6])                                # [Bg, 4]
    if fused_out:
        return new_state, read64
    read_out = WindowOutput(
        status=read64[:, 0].astype(I32), limit=read64[:, 1],
        remaining=read64[:, 2], reset_time=read64[:, 3])
    return new_state, read_out
