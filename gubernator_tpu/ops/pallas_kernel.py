"""Pallas TPU kernel for the GLOBAL aggregate-apply step.

`global_apply` (ops/kernel.py) is a pure elementwise pass over the whole
replicated GLOBAL arena — six state arrays + config + the psum'd hit totals
— executed every window.  This module lowers it through Pallas so the pass
runs as one VMEM-resident kernel (grid-blocked over the arena) instead of an
XLA fusion chain, and serves as the template for Pallas-lowering the
per-shard window kernel.

The kernel body *reuses* `kernel.transition` — the exact branch ladders that
mirror reference algorithms.go:24-186 — applied to loaded blocks, so Pallas
and XLA paths cannot drift semantically.

State is int64 (ms-epoch timestamps + proto-contract counters).  Mosaic's
int64 support on real TPU is not yet validated in this environment (the
device tunnel was down when this was written), so the engine keeps the XLA
path by default; enable with GUBER_PALLAS=1 or interpret=True (CPU tests run
the kernel in interpret mode and pin it against the XLA implementation).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from gubernator_tpu.ops import kernel
from gubernator_tpu.ops.kernel import BucketState, GlobalConfig, _Reg

# lanes per grid step; arenas are sized in powers of two >= 1024
BLOCK = 1024


def _apply_kernel(now_ref, limit_ref, dur_ref, rem_ref, ts_ref, exp_ref,
                  algo_ref, cl_ref, cd_ref, ca_ref, sum_ref,
                  o_limit, o_dur, o_rem, o_ts, o_exp, o_algo):
    reg = _Reg(
        limit=limit_ref[:],
        duration=dur_ref[:],
        remaining=rem_ref[:],
        tstamp=ts_ref[:],
        expire=exp_ref[:],
        algo=algo_ref[:],
    )
    now = now_ref[0]
    summed = sum_ref[:]
    cfg_algo = ca_ref[:]
    fresh = (reg.expire < now) | (cfg_algo != reg.algo)
    new_reg, _ = kernel.transition(
        reg, summed, cl_ref[:], cd_ref[:], cfg_algo, now, fresh)
    touched = summed != 0
    o_limit[:] = jnp.where(touched, new_reg.limit, reg.limit)
    o_dur[:] = jnp.where(touched, new_reg.duration, reg.duration)
    o_rem[:] = jnp.where(touched, new_reg.remaining, reg.remaining)
    o_ts[:] = jnp.where(touched, new_reg.tstamp, reg.tstamp)
    o_exp[:] = jnp.where(touched, new_reg.expire, reg.expire)
    o_algo[:] = jnp.where(touched, new_reg.algo, reg.algo)


@functools.partial(jax.jit, static_argnames=("interpret",))
def global_apply_pallas(state: BucketState, cfg: GlobalConfig,
                        summed_hits: jax.Array, now, *,
                        interpret: bool = False) -> BucketState:
    """Drop-in replacement for kernel.global_apply via pallas_call."""
    G = state.limit.shape[0]
    block = min(BLOCK, G)
    assert G % block == 0, "global arena capacity must be a multiple of the block"
    grid = (G // block,)
    spec = pl.BlockSpec((block,), lambda i: (i,))
    now_arr = jnp.asarray(now, jnp.int64).reshape((1,))

    # the global arena is replicated across the mesh, so under shard_map the
    # outputs vary over no axes (vma=()); outside shard_map the annotation is
    # inert
    sds = lambda dt: jax.ShapeDtypeStruct((G,), dt, vma=frozenset())
    out_shapes = [sds(jnp.int64)] * 5 + [sds(jnp.int32)]
    outs = pl.pallas_call(
        _apply_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1,), lambda i: (0,)),  # now (broadcast)
            spec, spec, spec, spec, spec, spec,  # state
            spec, spec, spec,                    # cfg
            spec,                                # summed
        ],
        out_specs=[spec] * 6,
        out_shape=out_shapes,
        interpret=interpret,
    )(now_arr, state.limit, state.duration, state.remaining, state.tstamp,
      state.expire, state.algo, cfg.limit, cfg.duration, cfg.algo, summed_hits)
    return BucketState(*outs)
