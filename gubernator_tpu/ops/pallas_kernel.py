"""Pallas TPU kernels for the rate-limit hot passes.

Two lowerings, chosen by what actually profits from hand-scheduling on TPU
(everything here is gated behind GUBER_PALLAS=1; the engine defaults to the
XLA implementations, which are semantically identical):

1. `global_apply_pallas` — the GLOBAL aggregate-apply: a pure elementwise
   transition over the whole replicated arena, grid-blocked through VMEM.

2. `window_step_pallas` — the per-shard serving window.  The WINDOW MATH
   (closed-form uniform segments + the duplicate-key replay rounds) runs as
   ONE VMEM-resident kernel over the [B] lane vectors, with the replay's
   register state formulated REPLICATED-per-lane so each round is
   elementwise + one vector gather (no scatters in the kernel).  The
   argsort and the arena gather/scatter stay in XLA deliberately: Mosaic
   has no sort primitive, and per-lane DMAs into a 2^27-slot HBM arena
   lose to XLA's native gather/scatter — a "full" Pallas lowering of those
   ops would be slower, not faster.

Both kernel bodies *reuse* `kernel.transition` / `kernel.uniform_closed_form`
— the exact branch ladders that mirror reference algorithms.go:24-186 — so
the Pallas and XLA paths cannot drift semantically, and the fuzz oracle
(tests/pyref.py) pins both.

State is int64 (ms-epoch timestamps + proto-contract counters).  Mosaic's
int64 support on real TPU is not yet validated in this environment (the
device tunnel was down when this was written), so the engine keeps the XLA
path by default; enable with GUBER_PALLAS=1 or interpret=True (CPU tests run
the kernels in interpret mode and pin them against the XLA implementation).
"""

from __future__ import annotations

import functools
import sys

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

# Lowering the kernel's fused window-math jaxpr (closed-form ladder +
# replay loop as ONE Mosaic kernel) recurses past CPython's default 1000
# frames inside jax's mlir lowering on real TPU (observed: RecursionError
# during the OUTER jit's compile, at first call of the compiled step —
# interpret mode on CPU stays shallower and never trips it).  The bump
# must be process-global: the lowering runs at unpredictable first-call
# sites, not under any lexical scope here.  The jaxpr nesting is finite
# (a few thousand frames), and CPython 3.12 heap-allocates Python-to-
# Python frames, so the higher ceiling does not threaten the C stack.
if sys.getrecursionlimit() < 20000:
    sys.setrecursionlimit(20000)

from gubernator_tpu.ops import kernel
from gubernator_tpu.ops.kernel import (
    BucketState,
    GlobalConfig,
    WindowBatch,
    WindowOutput,
    _Reg,
    I32,
    I64,
)

# lanes per grid step; arenas are sized in powers of two >= 1024
BLOCK = 1024


def _apply_kernel(now_ref, limit_ref, dur_ref, rem_ref, ts_ref, exp_ref,
                  algo_ref, cl_ref, cd_ref, ca_ref, sum_ref,
                  o_limit, o_dur, o_rem, o_ts, o_exp, o_algo):
    reg = _Reg(
        limit=limit_ref[:],
        duration=dur_ref[:],
        remaining=rem_ref[:],
        tstamp=ts_ref[:],
        expire=exp_ref[:],
        algo=algo_ref[:],
    )
    now = now_ref[0]
    summed = sum_ref[:]
    cfg_algo = ca_ref[:]
    fresh = (reg.expire < now) | (cfg_algo != reg.algo)
    new_reg, _ = kernel.transition(
        reg, summed, cl_ref[:], cd_ref[:], cfg_algo, now, fresh)
    touched = summed != 0
    o_limit[:] = jnp.where(touched, new_reg.limit, reg.limit)
    o_dur[:] = jnp.where(touched, new_reg.duration, reg.duration)
    o_rem[:] = jnp.where(touched, new_reg.remaining, reg.remaining)
    o_ts[:] = jnp.where(touched, new_reg.tstamp, reg.tstamp)
    o_exp[:] = jnp.where(touched, new_reg.expire, reg.expire)
    o_algo[:] = jnp.where(touched, new_reg.algo, reg.algo)


@functools.partial(jax.jit, static_argnames=("interpret",))
def global_apply_pallas(state: BucketState, cfg: GlobalConfig,
                        summed_hits: jax.Array, now, *,
                        interpret: bool = False) -> BucketState:
    """Drop-in replacement for kernel.global_apply via pallas_call."""
    G = state.limit.shape[0]
    block = min(BLOCK, G)
    assert G % block == 0, "global arena capacity must be a multiple of the block"
    grid = (G // block,)
    spec = pl.BlockSpec((block,), lambda i: (i,))
    now_arr = jnp.asarray(now, jnp.int64).reshape((1,))

    # the global arena is replicated across the mesh, so under shard_map
    # with check_vma the outputs vary over no axes (vma=()); with check_vma
    # off (the engine's Pallas mode) or outside shard_map, vma is None
    vma = getattr(jax.typeof(state.limit), "vma", None)
    sds = lambda dt: jax.ShapeDtypeStruct((G,), dt, vma=vma)
    out_shapes = [sds(jnp.int64)] * 5 + [sds(jnp.int32)]
    outs = pl.pallas_call(
        _apply_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1,), lambda i: (0,)),  # now (broadcast)
            spec, spec, spec, spec, spec, spec,  # state
            spec, spec, spec,                    # cfg
            spec,                                # summed
        ],
        out_specs=[spec] * 6,
        out_shape=out_shapes,
        interpret=interpret,
    )(now_arr, state.limit, state.duration, state.remaining, state.tstamp,
      state.expire, state.algo, cfg.limit, cfg.duration, cfg.algo, summed_hits)
    return BucketState(*outs)


# ---- the serving window kernel ------------------------------------------


def _window_math(now, max_pos, s_valid, s_hits, s_limit, s_duration,
                 s_algo, s_agg, pos, seg_len, seg_start_idx, seg_uniform,
                 h0, l0, d0, a0, fresh_seg, reg):
    """One pass over the sorted window: closed-form uniform segments, then
    replay rounds for irregular ones.  Pure function of [B] lane vectors —
    the SAME body runs as a Pallas VMEM kernel (via _window_math_kernel)
    and as plain traced XLA (window_step_compact(..., use_pallas=False)),
    in either int64 or rebased-int32 form.

    Register state is REPLICATED at every lane of its segment (the arena
    gather outside already yields that: all lanes of a segment load the
    same slot), so a replay round is elementwise plus ONE vector gather —
    `computed[seg_start + p]` pulls the active lane's freshly-computed
    register back to every lane of its segment — with no scatters.

    Returns (out_sorted: WindowOutput, fin: _Reg) with fin already
    uniform-vs-replayed selected.
    """
    B = pos.shape[0]
    fresh0 = fresh_seg
    uniform = seg_uniform
    valid = s_valid
    p_arr = pos
    sidx = seg_start_idx

    # ---- closed form for uniform segments (replicated-register form) ----
    ff_reg, ff_out = kernel.uniform_closed_form(
        reg, fresh0 | (a0 != reg.algo), h0, l0, d0, a0,
        p_arr, seg_len, now)

    # ---- singleton non-uniform segments: whole-run closed form ----
    # A folded lane that owns its slot in this window (the fold's normal
    # shape) or a lone hits=0 peek gets EXACTLY what its one replay round
    # would compute — same transition call, same inputs — hoisted to
    # straight line (it fuses with the ladder above; a fold-only window
    # then runs ZERO replay trips, prep's max_pos excludes these lanes).
    seg_single = valid & ~uniform & (seg_len == 1)
    a_reg, a_out = kernel.transition(
        reg, s_hits, s_limit, s_duration, s_algo, now,
        fresh0 | (s_algo != reg.algo), agg=s_agg)

    # ---- replay rounds for irregular segments ----
    def body(carry):
        p, lim, dur, rem, ts, exp, alg, fr, ost, oli, ore, ors = carry
        r = _Reg(limit=lim, duration=dur, remaining=rem, tstamp=ts,
                 expire=exp, algo=alg)
        # is_init lanes start their own virtual segment, so their
        # freshness is carried by fr (fresh_seg) until their round clears
        # it — no per-lane s_init term needed
        fresh = fr | (s_algo != r.algo)
        new_r, resp = kernel.transition(
            r, s_hits, s_limit, s_duration, s_algo, now, fresh,
            agg=s_agg)
        active = (p_arr == p) & valid & ~uniform & ~seg_single
        # Propagate the active lane's result to its WHOLE segment (the
        # final commit reads registers at segment-start lanes, pos 0).
        # ai = my segment start + p; active[ai] holds iff pos[ai] == p,
        # which algebraically forces sidx[ai] == my sidx — i.e. ai really
        # is MY segment's round-p lane (the clamp cannot false-positive:
        # pos[B-1] == p with a clamped ai would need sidx + p > B-1 and
        # sidx + p == B-1 at once).
        ai = jnp.clip(sidx + p, 0, B - 1)
        take = jnp.take(active, ai)

        def upd(new, old):
            return jnp.where(take, jnp.take(new, ai), old)

        lim = upd(new_r.limit, lim)
        dur = upd(new_r.duration, dur)
        rem = upd(new_r.remaining, rem)
        ts = upd(new_r.tstamp, ts)
        exp = upd(new_r.expire, exp)
        alg = jnp.where(take, jnp.take(new_r.algo, ai), alg)
        fr = jnp.where(take, False, fr)
        ost = jnp.where(active, resp.status, ost)
        oli = jnp.where(active, resp.limit, oli)
        ore = jnp.where(active, resp.remaining, ore)
        ors = jnp.where(active, resp.reset_time, ors)
        return (p + 1, lim, dur, rem, ts, exp, alg, fr, ost, oli, ore, ors)

    init = (jnp.int32(0), reg.limit, reg.duration, reg.remaining,
            reg.tstamp, reg.expire, reg.algo, fresh0,
            ff_out.status, ff_out.limit, ff_out.remaining,
            ff_out.reset_time)
    carry = lax.while_loop(lambda c: c[0] <= max_pos, body, init)
    (_, lim, dur, rem, ts, exp, alg, _, ost, oli, ore, ors) = carry

    out_sorted = WindowOutput(
        status=jnp.where(seg_single, a_out.status, ost),
        limit=jnp.where(seg_single, a_out.limit, oli),
        remaining=jnp.where(seg_single, a_out.remaining, ore),
        reset_time=jnp.where(seg_single, a_out.reset_time, ors))
    fin = _Reg(
        limit=jnp.where(uniform, ff_reg.limit, lim),
        duration=jnp.where(uniform, ff_reg.duration, dur),
        remaining=jnp.where(uniform, ff_reg.remaining, rem),
        tstamp=jnp.where(uniform, ff_reg.tstamp, ts),
        expire=jnp.where(uniform, ff_reg.expire, exp),
        algo=jnp.where(uniform, ff_reg.algo, alg))
    fin = _Reg(*jax.tree.map(
        lambda a, f: jnp.where(seg_single, a, f), a_reg, fin))
    return out_sorted, fin


def _window_math_kernel(now_ref, maxpos_ref,
                        s_valid, s_hits, s_limit, s_duration, s_algo,
                        s_init, s_agg, pos, seg_len, seg_start_idx,
                        seg_uniform, h0, l0, d0, a0, fresh_seg,
                        r_lim, r_dur, r_rem, r_ts, r_exp, r_algo,
                        o_status, o_limit, o_rem, o_reset,
                        f_lim, f_dur, f_rem, f_ts, f_exp, f_algo):
    """Pallas Ref wrapper around _window_math (reads refs, writes refs)."""
    reg = _Reg(limit=r_lim[:], duration=r_dur[:], remaining=r_rem[:],
               tstamp=r_ts[:], expire=r_exp[:], algo=r_algo[:])
    out_sorted, fin = _window_math(
        now_ref[0], maxpos_ref[0], s_valid[:], s_hits[:], s_limit[:],
        s_duration[:], s_algo[:], s_agg[:], pos[:], seg_len[:],
        seg_start_idx[:], seg_uniform[:], h0[:], l0[:], d0[:], a0[:],
        fresh_seg[:], reg)
    o_status[:] = out_sorted.status
    o_limit[:] = out_sorted.limit
    o_rem[:] = out_sorted.remaining
    o_reset[:] = out_sorted.reset_time
    f_lim[:] = fin.limit
    f_dur[:] = fin.duration
    f_rem[:] = fin.remaining
    f_ts[:] = fin.tstamp
    f_exp[:] = fin.expire
    f_algo[:] = fin.algo


@functools.partial(jax.jit,
                   static_argnames=("interpret", "compact32", "use_pallas"))
def window_step_pallas(state: BucketState, batch: WindowBatch, now, *,
                       interpret: bool = False, compact32: bool = False,
                       use_pallas: bool = True
                       ) -> tuple[BucketState, WindowOutput]:
    """Drop-in replacement for kernel.window_step with the window math in
    one Pallas kernel.  Sort, segment indexing, the arena gather, and the
    final scatter/unsort stay in XLA (see the module docstring for why).

    use_pallas=False runs the IDENTICAL math (_window_math, same rebase
    and re-absolutize) as plain traced XLA — with compact32=True that is
    the engine's default serving form (window_step_compact32_xla below):
    int64 arithmetic on TPU lowers to multi-op i32-pair emulation, so
    running the ladder in rebased int32 roughly halves the math's op
    count even without Mosaic.

    compact32=True runs the kernel body entirely in int32 with times
    REBASED to the window's `now` — Mosaic on real TPU has no 64-bit
    vector types (round-4 probe: "64-bit types are not supported"), and
    this is what makes the Pallas path runnable on hardware.  It is exact
    iff every lane satisfies the compact wire-format ranges
    (kernel.COMPACT_MAX_*: hits < 2^28, limit < 2^31, duration < 2^31-16)
    AND the arena rows it reads were written under the same caps — both
    guaranteed on the engine's compact serving path (the engine
    permanently drops to the full-format XLA path the first time an
    out-of-range config appears, core/engine.py _dispatch).  Rebased
    time identities: every absolute time the ladder computes is now+X
    with X in (-2^31, 2^31); non-fresh registers satisfy
    |t - now| <= max request duration < 2^31-16 (token: tstamp = expire
    >= now and <= write_now+duration; leaky: expire = last-decrement
    now+duration >= now) PROVIDED the window clock is monotonic — the
    engine's serving clocks are.  A clock that jumps backward by D ms
    can push a stored time up to D past the rebase range; the clip then
    bounds the resulting expiry error to D (graceful, not wrong-branch)."""
    B = batch.slot.shape[0]
    now = jnp.asarray(now, dtype=I64)

    # identical sort/segment/uniform prep as the XLA path — shared code, so
    # the two implementations cannot drift
    prep = kernel.window_prep(state, batch, now)
    (_, _, s_valid, s_hits, s_limit, s_duration, s_algo, s_init,
     _, seg_start_idx, pos, seg_len, cur, fresh_seg, h0, l0, d0, a0,
     seg_uniform, max_pos, _commit_mask, s_agg) = prep

    if compact32:
        lim = jnp.int64(2**31 - 16)
        rel = lambda t: jnp.clip(t - now, -lim, lim).astype(I32)
        cnt = lambda x: x.astype(I32)
        k_hits, k_limit, k_dur = cnt(s_hits), cnt(s_limit), cnt(s_duration)
        k_h0, k_l0, k_d0 = cnt(h0), cnt(l0), cnt(d0)
        k_cur = _Reg(limit=cnt(cur.limit), duration=cnt(cur.duration),
                     remaining=cnt(cur.remaining), tstamp=rel(cur.tstamp),
                     expire=rel(cur.expire), algo=cur.algo)
        k_now = jnp.zeros((1,), I32)
        VD = I32
    else:
        k_hits, k_limit, k_dur = s_hits, s_limit, s_duration
        k_h0, k_l0, k_d0 = h0, l0, d0
        k_cur = cur
        k_now = now.reshape((1,))
        VD = I64

    # under shard_map with check_vma the window arrays vary over the shard
    # axis; mirror the input's vma on the outputs.  The engine disables
    # check_vma on its shard_maps when Pallas is enabled (vma tags do not
    # survive the kernel's interpret-mode while_loop), in which case typeof
    # has no vma and None is correct.
    if use_pallas:
        vma = getattr(jax.typeof(batch.slot), "vma", None)
        sds = lambda dt: jax.ShapeDtypeStruct((B,), dt, vma=vma)
        spec = pl.BlockSpec((B,), lambda: (0,))
        sspec = pl.BlockSpec((1,), lambda: (0,))
        outs = pl.pallas_call(
            _window_math_kernel,
            in_specs=[sspec, sspec] + [spec] * 22,
            out_specs=[spec] * 10,
            out_shape=[sds(I32), sds(VD), sds(VD), sds(VD),   # outputs
                       sds(VD), sds(VD), sds(VD), sds(VD), sds(VD),
                       sds(I32)],                             # final regs
            interpret=interpret,
        )(k_now, max_pos.reshape((1,)),
          s_valid, k_hits, k_limit, k_dur, s_algo, s_init, s_agg,
          pos, seg_len, seg_start_idx, seg_uniform,
          k_h0, k_l0, k_d0, a0, fresh_seg,
          k_cur.limit, k_cur.duration, k_cur.remaining, k_cur.tstamp,
          k_cur.expire, k_cur.algo)
        out_sorted = WindowOutput(status=outs[0], limit=outs[1],
                                  remaining=outs[2], reset_time=outs[3])
        fin = _Reg(limit=outs[4], duration=outs[5], remaining=outs[6],
                   tstamp=outs[7], expire=outs[8], algo=outs[9])
    else:
        out_sorted, fin = _window_math(
            k_now[0], max_pos, s_valid, k_hits, k_limit, k_dur, s_algo,
            s_agg, pos, seg_len, seg_start_idx, seg_uniform,
            k_h0, k_l0, k_d0, a0, fresh_seg, k_cur)
    if compact32:
        # re-absolutize.  reset_time: leaky uses 0 as the "no reset"
        # sentinel and every leaky non-zero reset is now+rate with
        # rate >= 1, so rel == 0 distinguishes exactly; token lanes always
        # carry a real time (rel 0 == "resets at now") and never the
        # sentinel (algorithms.go:130-141 vs :69-74).
        leaky_lane = s_algo == kernel.LEAKY_BUCKET
        reset64 = jnp.where(
            leaky_lane & (out_sorted.reset_time == 0), jnp.int64(0),
            out_sorted.reset_time.astype(I64) + now)
        out_sorted = WindowOutput(
            status=out_sorted.status, limit=out_sorted.limit.astype(I64),
            remaining=out_sorted.remaining.astype(I64), reset_time=reset64)
        fin = _Reg(limit=fin.limit.astype(I64),
                   duration=fin.duration.astype(I64),
                   remaining=fin.remaining.astype(I64),
                   tstamp=fin.tstamp.astype(I64) + now,
                   expire=fin.expire.astype(I64) + now,
                   algo=fin.algo)
    return kernel.window_commit(state, prep, fin, out_sorted)


def window_step_compact32_xla(state: BucketState, batch: WindowBatch, now
                              ) -> tuple[BucketState, WindowOutput]:
    """The serving drain's default window step: the rebased-int32 math as
    plain traced XLA (no Mosaic dependency).  Exact under the compact
    wire-format range caps — the only context the engine calls it in
    (see window_step_pallas's compact32 notes for the rebase identities).
    """
    return window_step_pallas(state, batch, now, compact32=True,
                              use_pallas=False)
