"""The rate-limit window kernel: both bucket algorithms over dense SoA state.

This module is the TPU-native replacement for the reference's hot loop — the
`tokenBucket`/`leakyBucket` functions applied one key at a time under a global
cache mutex (reference algorithms.go:24-186, gubernator.go:236-251).  Here one
*window* of requests (the reference's 500µs BATCHING window, peers.go:143-172)
is evaluated as a single fused XLA computation over a batch:

  * State is a structure-of-arrays arena in device memory (`BucketState`),
    replacing the map+linked-list LRU (reference cache/lru.go:30-96).  A slot
    index replaces the string key; the host keeps the key→slot table
    (state/arena.py).
  * Every request in the window is routed to a slot.  Requests to *different*
    slots are data-parallel.  Requests to the *same* slot must observe
    sequential semantics (request N+1 sees N's decrement — the reference gets
    this from the cache mutex), which we reproduce with a sorted
    segment-replay: sort the window by slot, then run `max_duplicates` rounds
    of a fully-vectorized transition, each round applying the p-th request of
    every segment simultaneously.  A window of unique keys converges in one
    round; only hot-key duplicates add rounds.
  * Lazy TTL expiry (reference cache/lru.go:110-114: entry is a miss when
    `expireAt < now`) is evaluated *inside* the kernel, so the host table
    never needs to know whether an entry is live.

Branch semantics are reproduced exactly — including the subtle ones:
no-mutation-on-over-ask (algorithms.go:57-62,143-148), hits==0 read-only
(algorithms.go:46-49,150-153), exact-drain returns UNDER_LIMIT
(algorithms.go:51-55,136-141), OVER_LIMIT *is* stored on first-request
over-ask (algorithms.go:77-83,176-181), leaky's rate computed from the stored
duration but the *request's* limit (algorithms.go:107), the leaky timestamp
advancing even when the request is rejected (algorithms.go:118-121,143-148),
and repeated leak application when zero-hit reads interleave (a consequence of
algorithms.go:110-121).

Deliberate divergences from the reference (see SURVEY.md §7 "reference bugs
not to replicate"):
  * algorithm switch mid-stream resets the entry and re-runs it under the
    *requested* algorithm (the reference falls back to tokenBucket from
    leakyBucket, algorithms.go:100-104);
  * successful leaky decrement extends expiry to now + duration (the reference
    computes `now * duration`, algorithms.go:157);
  * leaky `rate` is clamped to ≥1ms (the reference divides by zero when
    limit > duration, algorithms.go:107-111 — a Go runtime panic).

All rate quantities are int64 (proto contract, gubernator.proto:104-117) and
timestamps are unix-epoch milliseconds (cache/lru.go:99-101) passed in as the
per-window `now` scalar — one timestamp per window instead of one per request.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

# Algorithm / status constants mirrored from the proto enums
# (proto/gubernator.proto:56-61,126-129).  Kept as plain ints so they can be
# used inside jit without host lookups.  Values 2..4 extend the wire enum
# beyond the reference (gubernator_tpu/algorithms/): GCRA as TAT arithmetic
# on the tstamp column, a weighted two-bucket sliding window packed into the
# remaining column, and concurrency leases with acquire/release semantics
# (negative hits releases held slots).  Any OTHER value degrades to token
# bucket — the reference's unknown-algorithm fallback (algorithms.go:100-104).
TOKEN_BUCKET = 0
LEAKY_BUCKET = 1
GCRA = 2
SLIDING_WINDOW = 3
CONCURRENCY = 4
UNDER_LIMIT = 0
OVER_LIMIT = 1

# Sliding-window packing: the remaining column carries BOTH window counters
# as cur | prev<<15, so sliding limits are clamped to 2^15-1 (documented
# divergence: a sliding request with limit > 32767 is served against 32767;
# the response's `limit` still echoes the stored config).  The interpolation
# weight is quantized to 1/1024ths so prev*(weight) stays exact in int32.
SLIDING_PACK_BITS = 15
SLIDING_MAX_LIMIT = (1 << SLIDING_PACK_BITS) - 1
SLIDING_WEIGHT_Q = 1024
# Sliding rows need now - window_start < 2*duration to stay inside the
# rebased-i32 exactness range of the compact serving path, so the compact
# eligibility cap for sliding durations is half the generic cap.
SLIDING_MAX_DURATION = 1 << 30

# Concurrency hits travel sign-extended through the 28-bit compact hits
# field (bit 27 is the sign), so releases are range-limited to |hits| < 2^27.
CONC_MAX_HITS = 1 << 27

# Slot value marking a padded (unused) lane of a window batch.
PAD_SLOT = -1

# Aggregated-run flag, carried in bit 30 of a lane's slot (arena capacities
# are <= 2^27, so the bit is free; pads are negative and unaffected).  The
# native router collapses a UNIFORM run of n identical hits=1, limit>0
# requests to one key into ONE lane with hits=n and this bit set; the
# device consumes k* = min(n, r_start) tokens and answers with r_start,
# from which the host synthesizes every item's response (status_i =
# i < r_start, remaining_i = max(r_start-(i+1), 0) — no n needed).  Only
# the compact serving path ever sets it (host_router.cc).
AGG_SLOT_BIT = 1 << 30

I32 = jnp.int32
I64 = jnp.int64


class BucketState(NamedTuple):
    """Dense SoA arena state, one row per key slot.

    Replaces the reference's cacheRecord {value, expireAt} where value is
    either a *RateLimitResp (token) or a LeakyBucket (leaky)
    (cache/lru.go:42-46, algorithms.go:70-75,89-94,162-167):

      limit/duration: the stored config, captured at (re)initialization.
      remaining:      tokens left in the bucket.
      tstamp:         token: the bucket's reset_time (== window end, ms epoch);
                      leaky: the last-leak TimeStamp.
      expire:         cache-entry expiry (ms epoch).  0 == never initialized,
                      and `expire < now` == expired, both of which read as a
                      cache miss (lru.go:110-114).
      algo:           which algorithm initialized this slot; a mismatch with
                      the request's algorithm reads as a miss.
    """

    limit: jax.Array  # i64[C]
    duration: jax.Array  # i64[C]
    remaining: jax.Array  # i64[C]
    tstamp: jax.Array  # i64[C]
    expire: jax.Array  # i64[C]
    algo: jax.Array  # i32[C]

    @classmethod
    def zeros(cls, capacity: int) -> "BucketState":
        z64 = jnp.zeros((capacity,), dtype=I64)
        return cls(
            limit=z64,
            duration=z64,
            remaining=z64,
            tstamp=z64,
            expire=z64,
            algo=jnp.zeros((capacity,), dtype=I32),
        )


class WindowBatch(NamedTuple):
    """One batching window's requests, routed to slots and padded to length B."""

    slot: jax.Array  # i32[B], PAD_SLOT for unused lanes
    hits: jax.Array  # i64[B]
    limit: jax.Array  # i64[B]
    duration: jax.Array  # i64[B]
    algo: jax.Array  # i32[B]
    is_init: jax.Array  # bool[B]: host just allocated this slot for a new key

    @classmethod
    def pad(cls, size: int) -> "WindowBatch":
        return cls(
            slot=jnp.full((size,), PAD_SLOT, dtype=I32),
            hits=jnp.zeros((size,), dtype=I64),
            limit=jnp.zeros((size,), dtype=I64),
            duration=jnp.zeros((size,), dtype=I64),
            algo=jnp.zeros((size,), dtype=I32),
            is_init=jnp.zeros((size,), dtype=jnp.bool_),
        )


class WindowOutput(NamedTuple):
    """Per-request responses (RateLimitResp fields, proto:131-143)."""

    status: jax.Array  # i32[B]
    limit: jax.Array  # i64[B]
    remaining: jax.Array  # i64[B]
    reset_time: jax.Array  # i64[B]


class _Reg(NamedTuple):
    """A segment's live bucket state during replay (same fields as BucketState)."""

    limit: jax.Array
    duration: jax.Array
    remaining: jax.Array
    tstamp: jax.Array
    expire: jax.Array
    algo: jax.Array


def _chain(pairs, default):
    """First-match-wins selection, mirroring the reference's if/else ladders."""
    out = default
    for cond, val in reversed(pairs):
        out = jnp.where(cond, val, out)
    return out


def _sliding_roll(R, T, D, L, now):
    """Advance a sliding-window register to the window containing `now`.

    Returns (prev1, cur1, ws1, est, sl_L): the rolled previous/current
    counters, the rolled window start, the weighted estimate the admission
    check runs against, and the clamped effective limit.  Shared verbatim
    by transition's hit ladder and fold_entering's prefix fold so the two
    cannot drift (the roll depends only on (register, now), which is fixed
    per window — that is what makes the sliding fold replay-free).

    Exactness across the int64 / rebased-int32 lowerings: k*maxD <= now-T
    and off is clipped into [0, maxD] BEFORE the weight multiply, so every
    product stays below 2^25 and no intermediate can wrap in int32."""
    dt = R.dtype
    Z = jnp.asarray(0, dt)
    ONE = jnp.asarray(1, dt)
    Q = jnp.asarray(SLIDING_WEIGHT_Q, dt)
    PMASK = jnp.asarray(SLIDING_MAX_LIMIT, dt)
    sl_L = jnp.minimum(L, jnp.asarray(SLIDING_MAX_LIMIT, dt))
    cur = R & PMASK
    prev = (R >> SLIDING_PACK_BITS) & PMASK
    maxD = jnp.maximum(D, ONE)
    k = jnp.maximum((now - T) // maxD, Z)
    prev1 = _chain([(k == Z, prev), (k == ONE, cur)], Z)
    cur1 = jnp.where(k == Z, cur, Z)
    ws1 = T + k * maxD
    offc = jnp.clip(now - ws1, Z, maxD)
    pos_q = jnp.where(maxD <= Q,
                      (offc * Q) // maxD,
                      jnp.minimum(offc // jnp.maximum(maxD // Q, ONE), Q))
    pos_q = jnp.clip(pos_q, Z, Q)
    weighted = (prev1 * (Q - pos_q)) // Q
    return prev1, cur1, ws1, weighted + cur1, sl_L


def transition(reg: _Reg, hits, req_limit, req_duration, req_algo, now, fresh,
               agg=None):
    """One request applied to one bucket, vectorized over the batch dimension.

    `fresh` marks lanes that must take the cache-miss/init path (new slot,
    expired entry, or algorithm switch).  Returns (new_reg, WindowOutput).

    The branch ladders reproduce algorithms.go:24-85 (token) and
    algorithms.go:88-186 (leaky) exactly; see the module docstring for the
    three documented divergences.

    `agg` (optional bool lanes) marks AGGREGATED runs (see AGG_SLOT_BIT):
    the lane's `hits` carries the run length n of identical hits=1
    requests, the state update consumes k* = min(n, r_start) exactly as n
    sequential hits=1 transitions would, and the response's `remaining`
    returns r_start (the pre-run balance) for host-side per-item synthesis.
    """
    L, D, R, T, E, A = reg
    h = hits
    is_token = req_algo == TOKEN_BUCKET
    is_leaky = req_algo == LEAKY_BUCKET
    is_gcra = req_algo == GCRA
    is_sliding = req_algo == SLIDING_WINDOW
    is_conc = req_algo == CONCURRENCY
    # counter dtype follows the inputs: i64 normally; the Pallas TPU path
    # runs the same ladder in rebased i32 (Mosaic has no 64-bit vectors,
    # and the compact-format range caps make i32 exact — see
    # ops/pallas_kernel.py)
    Z = jnp.asarray(0, h.dtype)
    ONE = jnp.asarray(1, h.dtype)

    # ---- init path (cache miss): algorithms.go:68-84 / :161-185 ----
    # Per-algorithm only where the stored shape demands it; every init
    # default is the token image, so out-of-range algorithm values
    # degrade to token bucket here too (algorithms.go:100-104).
    # GCRA's emission interval, same stored-duration/request-limit quirk
    # as leaky's rate and clamped the same way.
    rate_q = jnp.maximum(req_duration // jnp.maximum(req_limit, ONE), ONE)
    sl_l0 = jnp.minimum(req_limit, jnp.asarray(SLIDING_MAX_LIMIT, h.dtype))
    eff_init_limit = jnp.where(is_sliding, sl_l0, req_limit)
    conc_rel0 = is_conc & (h < Z)  # release with nothing held: full bucket
    over_init = (h > eff_init_limit) & ~conc_rel0
    init_R = _chain([(conc_rel0, eff_init_limit), (over_init, Z)],
                    eff_init_limit - h)
    init_status = jnp.where(over_init, OVER_LIMIT, UNDER_LIMIT).astype(I32)
    # token stores reset_time = now+duration (:69-74); leaky stores
    # TimeStamp = now (:166) and its init response has ResetTime 0 (:173);
    # GCRA stores the theoretical-arrival-time (saturated to now+duration
    # on an over-ask so the burst refills at `rate_q`); sliding stores the
    # window start; concurrency stamps the last-touch time.
    init_T = _chain(
        [(is_leaky | is_sliding | is_conc, now),
         (is_gcra, jnp.where(over_init, now + req_duration,
                             now + h * rate_q))],
        now + req_duration)
    # sliding packs cur into the remaining column (prev == 0 at init);
    # an over-ask saturates the window so reads stay OVER until it rolls
    init_R_store = jnp.where(
        is_sliding, jnp.where(over_init, sl_l0, jnp.maximum(h, Z)), init_R)
    init_reg = _Reg(
        limit=req_limit,
        duration=req_duration,
        remaining=init_R_store,
        tstamp=init_T,
        expire=now + req_duration,
        algo=req_algo,
    )
    init_out = WindowOutput(
        status=init_status,
        limit=req_limit,
        remaining=init_R,
        reset_time=_chain(
            [(is_leaky | is_conc, Z),
             (is_gcra, jnp.where(over_init, now + rate_q,
                                 now + h * rate_q)),
             (is_sliding, now + req_duration)],
            now + req_duration),
    )

    # ---- token bucket hit path: algorithms.go:40-65 ----
    tb_at_zero = R == 0  # :41-44 -> OVER, remaining 0
    tb_read = h == 0  # :47-49 -> read-only
    tb_drain = h == R  # :52-55 -> UNDER, remaining -> 0
    tb_over = h > R  # :58-62 -> OVER, state NOT mutated
    t_status = _chain(
        [(tb_at_zero, OVER_LIMIT), (tb_read, UNDER_LIMIT), (tb_drain, UNDER_LIMIT), (tb_over, OVER_LIMIT)],
        UNDER_LIMIT,
    ).astype(I32)
    t_resp_R = _chain(
        [(tb_at_zero, Z), (tb_read, R), (tb_drain, Z), (tb_over, R)],
        R - h,
    )
    t_new_R = _chain(
        [(tb_at_zero, R), (tb_read, R), (tb_drain, Z), (tb_over, R)],
        R - h,
    )
    token_reg = _Reg(limit=L, duration=D, remaining=t_new_R, tstamp=T, expire=E, algo=A)
    # all token hit responses carry the stored limit and stored reset_time
    token_out = WindowOutput(status=t_status, limit=L, remaining=t_resp_R, reset_time=T)

    # ---- leaky bucket hit path: algorithms.go:107-158 ----
    # rate = stored duration / REQUEST limit (:107) — a reference quirk we
    # keep; clamped to >=1ms where the reference would panic on a zero rate.
    rate = D // jnp.maximum(req_limit, ONE)
    rate = jnp.maximum(rate, ONE)
    leak = (now - T) // rate  # :110-111
    # :113-115 clamp to stored limit; written add-after-min (equivalent
    # given R <= L) so the i32 Pallas path cannot overflow on R + leak
    R2 = R + jnp.minimum(leak, L - R)
    T2 = jnp.where(h != 0, now, T)  # :118-121 ts advances only on hits
    lb_at_zero = R2 == 0  # :130-134 -> OVER, reset now+rate
    lb_drain = h == R2  # :136-141 -> UNDER, remaining -> 0, reset 0
    lb_over = h > R2  # :143-148 -> OVER, no decrement, reset now+rate
    lb_read = h == 0  # :150-153 -> read-only
    l_status = _chain(
        [(lb_at_zero, OVER_LIMIT), (lb_drain, UNDER_LIMIT), (lb_over, OVER_LIMIT), (lb_read, UNDER_LIMIT)],
        UNDER_LIMIT,
    ).astype(I32)
    l_resp_R = _chain(
        [(lb_at_zero, Z), (lb_drain, Z), (lb_over, R2), (lb_read, R2)],
        R2 - h,
    )
    l_reset = _chain(
        [(lb_at_zero, now + rate), (lb_drain, Z), (lb_over, now + rate), (lb_read, Z)],
        Z,
    )
    l_new_R = _chain(
        [(lb_at_zero, R2), (lb_drain, Z), (lb_over, R2), (lb_read, R2)],
        R2 - h,
    )
    # expiry extends only on a successful decrement (:155-157, with the
    # now*duration bug corrected to now+duration using the request's duration)
    l_hit = ~(lb_at_zero | lb_drain | lb_over | lb_read)
    l_new_E = jnp.where(l_hit, now + req_duration, E)
    leaky_reg = _Reg(limit=L, duration=D, remaining=l_new_R, tstamp=T2, expire=l_new_E, algo=A)
    leaky_out = WindowOutput(status=l_status, limit=L, remaining=l_resp_R, reset_time=l_reset)

    # ---- GCRA hit path: TAT arithmetic on the tstamp column ----
    # rate reuses leaky's stored-duration // request-limit emission
    # interval (computed above).  base = max(TAT, now); the burst
    # capacity is how many emission intervals fit between base and the
    # horizon now+D, clamped to the stored limit.  Consuming h advances
    # the TAT by h*rate; rejected and read lanes never mutate (the
    # no-mutation-on-over-ask contract carried over from token).
    g_base = jnp.maximum(T, now)
    g_raw = jnp.maximum((now + D - g_base) // rate, Z)
    g_cap = jnp.minimum(g_raw, L)
    g_at_zero = g_cap == 0
    g_read = h == 0
    g_drain = h == g_cap
    g_over = h > g_cap
    g_status = _chain(
        [(g_at_zero, OVER_LIMIT), (g_read, UNDER_LIMIT),
         (g_drain, UNDER_LIMIT), (g_over, OVER_LIMIT)],
        UNDER_LIMIT,
    ).astype(I32)
    g_resp_R = _chain(
        [(g_at_zero, Z), (g_read, g_cap), (g_drain, Z), (g_over, g_cap)],
        g_cap - h,
    )
    g_consume = ~(g_at_zero | g_read | g_over)
    g_new_T = jnp.where(g_consume, g_base + h * rate, T)
    g_reset = _chain(
        [(g_at_zero, now + rate), (g_read, g_base), (g_over, now + rate)],
        g_new_T,
    )
    gcra_reg = _Reg(limit=L, duration=D, remaining=R, tstamp=g_new_T,
                    expire=E, algo=A)
    gcra_out = WindowOutput(status=g_status, limit=L, remaining=g_resp_R,
                            reset_time=g_reset)

    # ---- sliding-window hit path: weighted two-bucket interpolation ----
    # The register rolls to the window containing `now` on EVERY branch
    # (like leaky's leak, the roll commits even on reads/rejects — it is
    # idempotent, which is what keeps the prefix fold replay-free); only
    # an accepted request adds to the current counter and re-arms expiry.
    sl_prev1, sl_cur1, sl_ws, sl_est, sl_L = _sliding_roll(R, T, D, L, now)
    sl_full = sl_est >= sl_L
    sl_read = h == 0
    sl_over = sl_est + h > sl_L
    sl_status = _chain(
        [(sl_full, OVER_LIMIT), (sl_read, UNDER_LIMIT),
         (sl_over, OVER_LIMIT)],
        UNDER_LIMIT,
    ).astype(I32)
    sl_resp_R = _chain(
        [(sl_full, Z), (sl_read, sl_L - sl_est), (sl_over, sl_L - sl_est)],
        sl_L - sl_est - h,
    )
    sl_accept = ~(sl_full | sl_read | sl_over)
    sl_cur2 = jnp.where(sl_accept, sl_cur1 + h, sl_cur1)
    sl_new_R = sl_cur2 | (sl_prev1 << SLIDING_PACK_BITS)
    sl_new_E = jnp.where(sl_accept, now + req_duration, E)
    sliding_reg = _Reg(limit=L, duration=D, remaining=sl_new_R,
                       tstamp=sl_ws, expire=sl_new_E, algo=A)
    sliding_out = WindowOutput(
        status=sl_status, limit=L, remaining=sl_resp_R,
        reset_time=sl_ws + jnp.maximum(D, ONE))

    # ---- concurrency hit path: acquire/release over live leases ----
    # remaining counts FREE slots; positive hits acquires (token ladder),
    # negative hits releases (saturating add back toward the stored
    # limit, always UNDER).  reset_time is always the 0 sentinel — a
    # lease has no time-based reset; expiry re-arms on every mutation so
    # held leases keep the bucket (and the host lease book) alive.
    c_rel = h < Z
    c_at_zero = R == 0
    c_read = h == 0
    c_over = h > R
    # saturating release written add-after-min (leaky's R2 trick) so the
    # i32 lowering cannot overflow on R - h
    c_rel_R = R + jnp.minimum(-h, L - R)
    c_status = _chain(
        [(c_rel, UNDER_LIMIT), (c_at_zero, OVER_LIMIT),
         (c_read, UNDER_LIMIT), (c_over, OVER_LIMIT)],
        UNDER_LIMIT,
    ).astype(I32)
    c_resp_R = _chain(
        [(c_rel, c_rel_R), (c_at_zero, Z), (c_read, R), (c_over, R)],
        R - h,
    )
    c_new_R = _chain(
        [(c_rel, c_rel_R), (c_at_zero, R), (c_read, R), (c_over, R)],
        R - h,
    )
    c_mut = c_rel | ~(c_at_zero | c_read | c_over)
    conc_reg = _Reg(limit=L, duration=D, remaining=c_new_R,
                    tstamp=jnp.where(c_mut, now, T),
                    expire=jnp.where(c_mut, now + req_duration, E),
                    algo=A)
    conc_out = WindowOutput(status=c_status, limit=L, remaining=c_resp_R,
                            reset_time=jnp.zeros_like(T))

    # ---- combine: requested algorithm picks the hit path (non-fresh lanes
    # are guaranteed to have stored algo == requested algo).  First-match
    # select chain over all five values with token as the DEFAULT, so an
    # out-of-range algorithm degrades to token bucket exactly like the
    # reference's fallback (algorithms.go:100-104). ----
    hit_reg, hit_out = token_reg, token_out
    for sel, breg, bout in (
            (is_leaky, leaky_reg, leaky_out),
            (is_gcra, gcra_reg, gcra_out),
            (is_sliding, sliding_reg, sliding_out),
            (is_conc, conc_reg, conc_out)):
        hit_reg = _Reg(*jax.tree.map(
            lambda b, t, s=sel: jnp.where(s, b, t), breg, hit_reg))
        hit_out = WindowOutput(*jax.tree.map(
            lambda b, t, s=sel: jnp.where(s, b, t), bout, hit_out))

    new_reg = jax.tree.map(lambda i, hh: jnp.where(fresh, i, hh), init_reg, hit_reg)
    out = jax.tree.map(lambda i, hh: jnp.where(fresh, i, hh), init_out, hit_out)
    new_reg, out = _Reg(*new_reg), WindowOutput(*out)
    if agg is None:
        return new_reg, out

    # ---- aggregated runs: n sequential hits=1 transitions in one lane ----
    # r_start: post-init balance for fresh lanes (init consumes via k*, so
    # the base is the full limit), else current balance with the leak
    # applied for leaky.  limit > 0 guaranteed by the router's aggregation
    # conditions (a fresh leaky limit=0 run's first item would need the
    # init-path ResetTime=0 special the synthesis cannot express).
    n = h
    a_L = jnp.where(fresh, req_limit, L)
    a_D = jnp.where(fresh, req_duration, D)
    a_base_tok = jnp.where(fresh, req_limit, R)
    a_base_lky = jnp.where(fresh, req_limit, R2)
    a_base = jnp.where(is_token, a_base_tok, a_base_lky)
    k = jnp.minimum(n, a_base)
    a_R = a_base - k
    a_rate = jnp.maximum(a_D // jnp.maximum(req_limit, ONE), ONE)
    # leaky expiry: extends iff any GENERIC decrement happened (the last
    # consume is a drain when the balance hits 0 — same accounting as
    # uniform_closed_form)
    lky_extended = (k - (a_R == 0)) >= 1
    a_reg = _Reg(
        limit=a_L,
        duration=a_D,
        remaining=a_R,
        tstamp=jnp.where(is_token, jnp.where(fresh, now + req_duration, T),
                         now),
        expire=jnp.where(
            is_token,
            jnp.where(fresh, now + req_duration, E),
            jnp.where(fresh | lky_extended, now + req_duration, E)),
        algo=req_algo,
    )
    a_out = WindowOutput(
        # host-synthesized per item; the word carries r_start and the
        # OVER-item reset (token: the bucket's reset_time; leaky:
        # now+rate — UNDER leaky items synthesize 0)
        status=jnp.where(k < n, OVER_LIMIT, UNDER_LIMIT).astype(I32),
        limit=a_L,
        remaining=a_base,
        reset_time=jnp.where(is_token,
                             jnp.where(fresh, now + req_duration, T),
                             now + a_rate),
    )
    new_reg = jax.tree.map(lambda a, b: jnp.where(agg, a, b), a_reg, new_reg)
    out = jax.tree.map(lambda a, b: jnp.where(agg, a, b), a_out, out)
    return _Reg(*new_reg), WindowOutput(*out)


def transition_precompute(reg_duration, reg_tstamp, req_limit, now):
    """The two integer divisions of `transition`'s leaky path, factored out
    so a Mosaic lowering can run them in int64 XLA BEFORE entering a pair-
    arithmetic kernel (ops/pallas_kernel.py global_combined_staged): both
    depend only on pre-psum data (stored duration/tstamp + request limit),
    never on the evolving balance, so hoisting them is exact.  Must stay
    textually in lockstep with transition's rate/leak lines above."""
    ONE = jnp.asarray(1, reg_duration.dtype)
    rate = reg_duration // jnp.maximum(req_limit, ONE)
    rate = jnp.maximum(rate, ONE)
    leak = (now - reg_tstamp) // rate
    return rate, leak


def fold_entering(reg: _Reg, fresh0, h0, l0, d0, a0, pos, nz, n_lead,
                  hstar, now):
    """Closed-form ENTERING register for lane `pos` of a foldable segment
    (fold_classify's class): every nonzero hit in the segment equals
    `hstar`, config is uniform, no AGG lanes.  Reconstructing the register
    each lane would see lets ONE shared `transition` call replace the
    whole lane-by-lane replay — the generalization of the old
    uniform-segment closed form to mixed read/hit segments.

    The sequential recurrence folds because only three things evolve lane
    to lane: the balance (token: minus hstar per accept, accepts =
    min(#prior nonzero lanes, balance // hstar) by the greedy ladder;
    leaky: plus one read-leak per leading read, saturating at the limit,
    then the same accept arithmetic), the leaky tstamp (jumps to `now` at
    the first nonzero lane and freezes — so the read-leak is the SAME
    leak0 every application), and the leaky expiry (re-arms iff any
    generic decrement happened).  `st`/`reg` is the segment-start register
    replicated to every lane; all math is elementwise, i64 or rebased-i32
    exactly like transition.

    `nz` — exclusive count of nonzero-hit lanes before `pos` in-segment;
    `n_lead` — leading zero-hit lanes; `hstar` — the shared nonzero hits
    (0 if the segment is all reads).  All from fold_classify."""
    dt = hstar.dtype
    Z = jnp.asarray(0, dt)
    ONE = jnp.asarray(1, dt)
    is_lky = a0 == LEAKY_BUCKET
    is_gc = a0 == GCRA
    is_sl = a0 == SLIDING_WINDOW
    is_cc = a0 == CONCURRENCY
    # init path image: over-limit init stores a drained balance
    over0 = fresh0 & (h0 > l0)
    L_eff = jnp.where(fresh0, l0, reg.limit)
    D_eff = jnp.where(fresh0, d0, reg.duration)
    nzd = nz.astype(dt)

    # ---- token: balance only moves on accepts, T/E never move on hits ----
    Rt = jnp.where(fresh0, jnp.where(over0, Z, l0), reg.remaining)
    kt = jnp.minimum(nzd, Rt // jnp.maximum(hstar, ONE))
    entR_tok = Rt - hstar * kt
    T_tok = jnp.where(fresh0, now + d0, reg.tstamp)
    E_tok = jnp.where(fresh0, now + d0, reg.expire)

    # ---- leaky: leading reads each re-apply the SAME leak0 (tstamp is
    # frozen until the first nonzero hit), saturating at the limit ----
    rate0 = jnp.maximum(D_eff // jnp.maximum(l0, ONE), ONE)
    leak0 = jnp.where(fresh0, Z, (now - reg.tstamp) // rate0)
    gap = L_eff - reg.remaining
    # first application count that saturates; while p < p_sat the product
    # p*leak0 < gap, so it cannot overflow the lane dtype
    p_sat = jnp.where(leak0 > Z,
                      (gap + leak0 - ONE) // jnp.maximum(leak0, ONE),
                      jnp.asarray(1 << 30, dt))

    def satA(p):
        return jnp.where(p >= p_sat, L_eff, reg.remaining + p * leak0)

    posd = pos.astype(dt)
    fh = n_lead.astype(dt)
    # balance the FIRST nonzero lane's ladder starts from (its own
    # in-transition leak included): fh leading reads + one more leak
    Rh = jnp.where(fresh0, jnp.where(over0, Z, l0), satA(fh + ONE))
    Kf = Rh // jnp.maximum(hstar, ONE)
    kl = jnp.minimum(nzd, Kf)
    # the k-th accept is an exact drain (not generic) iff it lands on 0
    drained = (hstar > Z) & (Rh == Kf * hstar) & (kl == Kf) & (kl >= ONE)
    gen = kl - drained.astype(dt)
    phaseA = ~fresh0 & (nz == 0)
    entR_lky = jnp.where(phaseA, satA(posd), Rh - hstar * kl)
    T_lky = jnp.where(fresh0 | (nz > 0), now, reg.tstamp)
    E_lky = jnp.where(fresh0 | (gen >= ONE), now + d0, reg.expire)

    # ---- GCRA: token-shaped fold on the TAT-derived burst capacity ----
    # The capacity raw = (now+D-base)//rate drops by EXACTLY hstar per
    # accept (subtracting an exact multiple of rate commutes with the
    # floor division), so the accept count is the same greedy min as
    # token's, gated on hstar <= L (the per-hit clamp to the stored
    # limit).  Only the TAT evolves; reads and rejects freeze it, so a
    # kp == 0 non-fresh lane must see the RAW stored tstamp.
    g_rate0 = rate0
    g_base_nf = jnp.maximum(reg.tstamp, now)
    g_rawNF = jnp.maximum((now + D_eff - g_base_nf) // g_rate0, Z)
    g_rawT = jnp.where(fresh0, jnp.where(over0, Z, D_eff // g_rate0),
                       g_rawNF)
    g_kp = jnp.where((hstar > Z) & (hstar <= L_eff),
                     jnp.minimum(nzd, g_rawT // jnp.maximum(hstar, ONE)),
                     Z)
    g_baset = jnp.where(fresh0,
                        jnp.where(over0, now + d0, now), g_base_nf)
    entT_gc = jnp.where((g_kp > Z) | fresh0,
                        g_baset + g_kp * hstar * g_rate0, reg.tstamp)
    entR_gc = jnp.where(fresh0, jnp.where(over0, Z, l0 - h0),
                        reg.remaining)

    # ---- sliding: the roll happens once (now is fixed per window) and
    # every accept adds hstar to the estimate, so the accept count is the
    # token greedy min over the post-roll headroom ----
    s_prev1, s_cur1, s_ws1, s_est0, s_L = _sliding_roll(
        reg.remaining, reg.tstamp, D_eff, L_eff, now)
    s_over0 = fresh0 & (h0 > s_L)
    s_est_base = jnp.where(fresh0, jnp.where(s_over0, s_L, Z), s_est0)
    s_kp = jnp.where(hstar > Z,
                     jnp.minimum(nzd, jnp.maximum(s_L - s_est_base, Z)
                                 // jnp.maximum(hstar, ONE)),
                     Z)
    s_cur_ent = (jnp.where(fresh0, jnp.where(s_over0, s_L, Z), s_cur1)
                 + s_kp * hstar)
    s_prev_ent = jnp.where(fresh0, Z, s_prev1)
    entR_sl = s_cur_ent | (s_prev_ent << SLIDING_PACK_BITS)
    entT_sl = jnp.where(fresh0, now, s_ws1)
    E_sl = jnp.where(fresh0 | (s_kp >= ONE), now + d0, reg.expire)

    # ---- concurrency: acquires fold exactly like token; releases are a
    # saturating climb toward the stored limit (monotone, so the k-th
    # release's balance is closed-form via the saturation point) ----
    c_a = -hstar  # release magnitude (valid when hstar < 0)
    c_R0 = reg.remaining
    c_gap = L_eff - c_R0
    c_ksat = jnp.where(c_gap > Z,
                       (c_gap + c_a - ONE) // jnp.maximum(c_a, ONE), Z)
    entR_rel = jnp.where(
        fresh0, l0,
        jnp.where(nzd == Z, c_R0,
                  jnp.where(nzd >= c_ksat, L_eff, c_R0 + nzd * c_a)))
    entR_cc = jnp.where(hstar < Z, entR_rel, entR_tok)
    c_applied = jnp.where(hstar < Z, nzd, kt)
    T_cc = jnp.where(fresh0 | (c_applied >= ONE), now, reg.tstamp)
    E_cc = jnp.where(fresh0 | (c_applied >= ONE), now + d0, reg.expire)

    # default = token, matching transition's out-of-range fallback
    pick = lambda lk, gc, sl, cc, tok: _chain(  # noqa: E731
        [(is_lky, lk), (is_gc, gc), (is_sl, sl), (is_cc, cc)], tok)
    return _Reg(
        limit=L_eff,
        duration=D_eff,
        remaining=pick(entR_lky, entR_gc, entR_sl, entR_cc, entR_tok),
        tstamp=pick(T_lky, entT_gc, entT_sl, T_cc, T_tok),
        expire=pick(E_lky, E_tok, E_sl, E_cc, E_tok),
        algo=a0,
    )


def segment_structure(s_slot, s_valid, s_init):
    """Segment indexing over a slot-sorted window: virtual-segment starts,
    per-lane segment start index / position / length, and the commit mask
    (the lanes whose final register may land in the arena).

    Segments are VIRTUAL: they break at slot changes AND at is_init lanes
    (see window_prep's docstring for why).  Written in kernel-safe
    primitives only — shifted compares via `jnp.take`, `lax.cummax` /
    `lax.cummin` scans — because this exact function also runs INSIDE the
    fused Pallas megakernel (ops/pallas_kernel.py window_step_fused), where
    Mosaic has no concatenate-shift or scatter forms.  Sharing the one
    implementation is what keeps the XLA and fused paths from drifting.

    Returns (seg_start, seg_start_idx, pos, seg_len, commit_mask).
    """
    B = s_slot.shape[0]
    idx = lax.iota(I32, B)
    prev_slot = jnp.take(s_slot, jnp.maximum(idx - 1, 0))
    phys_start = (idx == 0) | (s_slot != prev_slot)
    seg_start = phys_start | (s_init & s_valid)
    seg_start_idx = lax.cummax(jnp.where(seg_start, idx, jnp.int32(0)))
    pos = idx - seg_start_idx
    # next segment start at-or-after lane i+1 (B when none): lane i's value
    # is min over j > i of {j if start[j] else B}, via a reverse cummin of
    # the shifted-start lattice
    nxt = jnp.minimum(idx + 1, B - 1)

    def _next_boundary(start):
        shifted = jnp.where(jnp.take(start, nxt) & (idx < B - 1),
                            idx + 1, jnp.int32(B))
        return lax.cummin(shifted, reverse=True)

    next_start = _next_boundary(seg_start)
    seg_len = next_start - seg_start_idx
    # a virtual segment is its slot's LAST (→ the one that commits) iff no
    # further virtual start precedes the next physical slot change
    next_phys = _next_boundary(phys_start)
    commit_mask = seg_start & s_valid & (next_start >= next_phys)
    return seg_start, seg_start_idx, pos, seg_len, commit_mask


def segment_count(flag, seg_start_idx, seg_len):
    """Per-lane: how many lanes of my segment satisfy `flag`?  Replicated
    to all lanes of the segment (i32).

    Cumsum range-count instead of a scatter-add (`.at[seg].add`): counts
    the flagged lanes inside [seg_start, seg_start+len) from an inclusive
    prefix sum — gather-only, so the SAME code runs in window_prep's XLA
    trace and inside the fused Pallas megakernel.
    """
    f = flag.astype(I32)
    csum = jnp.cumsum(f)
    seg_end = seg_start_idx + seg_len - 1
    return (jnp.take(csum, seg_end) - jnp.take(csum, seg_start_idx)
            + jnp.take(f, seg_start_idx))


def segment_all(ok, seg_start_idx, seg_len):
    """Per-lane: does EVERY lane of my segment satisfy `ok`?  Replicated to
    all lanes of the segment."""
    return segment_count(~ok, seg_start_idx, seg_len) == 0


def fold_classify(s_hits, s_limit, s_duration, s_algo, s_agg,
                  seg_start_idx, seg_len, h0, l0, d0, a0, fresh_seg, reg,
                  now):
    """Classify segments for the zero-replay fold and compute the per-lane
    prefix facts fold_entering consumes.  Returns
    (seg_fold, nz, n_lead, hstar), all replicated/aligned to lanes.

    A segment folds when one shared `transition` call per lane reproduces
    the sequential replay exactly:
      * uniform config (limit/duration/algo match the segment head), no
        AGG lanes, no negative hits;
      * every nonzero hit equals hstar (the first nonzero lane's hits) —
        reads (hits==0) may interleave anywhere;
      * leaky non-fresh registers additionally need the stored invariant
        remaining <= limit, and a non-negative read-leak whenever the
        segment has leading reads (each read re-applies leak0, which only
        telescopes when it saturates monotonically; a lone in-transition
        leak — no leading reads — is exact for any sign).
    Everything else (mixed distinct nonzero hits, mixed configs, AGG runs
    in multi-lane segments, negative hits/limits on leaky) falls back to
    the replay while_loop — rare shapes by construction, since the router
    folds duplicate identical requests into AGG singletons already.
    """
    B = s_hits.shape[0]
    dt = s_hits.dtype
    Z = jnp.asarray(0, dt)
    ONE = jnp.asarray(1, dt)
    nonzero = s_hits != 0
    nzf = nonzero.astype(I32)
    csum = jnp.cumsum(nzf)
    exc = csum - nzf
    # exclusive in-segment nonzero-lane count before each lane
    nz = exc - jnp.take(exc, seg_start_idx)
    lead = ~nonzero & (nz == 0)
    n_lead = segment_count(lead, seg_start_idx, seg_len)
    first_nz = jnp.clip(seg_start_idx + n_lead, 0, B - 1)
    hstar = jnp.where(n_lead < seg_len, jnp.take(s_hits, first_nz), Z)
    lane_ok = ((s_limit == l0) & (s_duration == d0) & (s_algo == a0)
               & ~s_agg & ((s_hits == Z) | (s_hits == hstar)))
    cfg_ok = segment_all(lane_ok, seg_start_idx, seg_len)
    fresh0 = fresh_seg | (a0 != reg.algo)
    L_eff = jnp.where(fresh0, l0, reg.limit)
    rate0 = jnp.maximum(jnp.where(fresh0, d0, reg.duration)
                        // jnp.maximum(l0, ONE), ONE)
    leak0 = jnp.where(fresh0, Z, (now - reg.tstamp) // rate0)
    lky_ok = ((a0 != LEAKY_BUCKET) | fresh0
              | ((reg.remaining <= L_eff)
                 & ((leak0 >= Z) | (n_lead == 0))))
    # negative hits (concurrency releases) fold — the saturating climb is
    # closed-form; a negative hstar under any OTHER algorithm is an
    # engine-rejected shape and replays (exact by construction)
    hstar_ok = (hstar >= Z) | (a0 == CONCURRENCY)
    seg_fold = cfg_ok & hstar_ok & lky_ok
    return seg_fold, nz, n_lead, hstar


class WindowPrep(NamedTuple):
    """Everything window_step derives from a window before the transition
    math: sorted request lanes, segment structure, gathered registers, and
    uniform-segment classification.  Shared verbatim by the XLA path below
    and the Pallas lowering (ops/pallas_kernel.py) so the two cannot drift.
    """

    order: jax.Array
    s_slot: jax.Array
    s_valid: jax.Array
    s_hits: jax.Array
    s_limit: jax.Array
    s_duration: jax.Array
    s_algo: jax.Array
    s_init: jax.Array
    seg_start: jax.Array
    seg_start_idx: jax.Array
    pos: jax.Array
    seg_len: jax.Array
    cur: _Reg          # live registers, REPLICATED at every lane
    fresh_seg: jax.Array  # segment-level miss, replicated (start lane's)
    h0: jax.Array      # segment-start request fields, replicated
    l0: jax.Array
    d0: jax.Array
    a0: jax.Array
    nz: jax.Array      # exclusive in-segment nonzero-hit lane count (i32)
    n_lead: jax.Array  # leading zero-hit lanes per segment, replicated
    hstar: jax.Array   # the segment's shared nonzero hits (0: all reads)
    seg_fold: jax.Array  # zero-replay foldable segment (fold_classify)
    max_pos: jax.Array
    commit_mask: jax.Array  # lanes whose register commits to the arena
    s_agg: jax.Array   # aggregated-run lanes (AGG_SLOT_BIT), sorted order


def window_prep(state: BucketState, batch: WindowBatch, now) -> WindowPrep:
    """Sort by slot, find segments, gather registers, classify uniform
    segments (see window_step for the semantics each piece serves).

    Segments are VIRTUAL: they break at slot changes AND at is_init lanes.
    Capacity eviction can recycle a slot to a different key mid-window
    (state/arena.py + native pack assign the new tenant's first lane
    is_init); splitting there turns [old-tenant lanes][init + new-tenant
    lanes] into two independently-uniform segments, so a recycled hot slot
    keeps the closed form instead of forcing a lane-by-lane replay of the
    whole run (a 3000-duplicate Zipf head key would otherwise cost 3000
    replay rounds in one device call).  Only the LAST virtual segment of a
    slot commits to the arena (earlier tenants' counters die with the
    eviction, exactly like the reference's cache Remove)."""
    B = batch.slot.shape[0]
    C = state.limit.shape[0]

    valid = batch.slot >= 0
    # Strip the aggregated-run flag off the slot BEFORE anything keys on
    # slot values (sorting, sharding, the arena gather).
    agg = valid & ((batch.slot & jnp.int32(AGG_SLOT_BIT)) != 0)
    slot_clean = jnp.where(agg, batch.slot & jnp.int32(~AGG_SLOT_BIT),
                           batch.slot)
    # Sort by slot (stable → arrival order preserved within a slot); pads last.
    # Packed single-key sort instead of jnp.argsort: fold (key, lane) into one
    # i64 word with the lane index in the low bits.  A single-array sort of
    # that word is bit-identical to a stable argsort (ties break on lane
    # order) but avoids XLA's variadic comparator sort, which costs ~5x more
    # per window on the CPU backend (BENCH_NOTES round 6).
    sort_key = jnp.where(valid, slot_clean, jnp.int32(2**31 - 1))
    lane_bits = max((B - 1).bit_length(), 1)
    packed_key = ((sort_key.astype(I64) << lane_bits)
                  | lax.iota(I64, B))
    sorted_key = lax.sort(packed_key, is_stable=False)
    order = (sorted_key & jnp.int64((1 << lane_bits) - 1)).astype(I32)
    s_slot = (sorted_key >> lane_bits).astype(I32)
    s_valid = valid[order]
    # Permute the request fields as ONE packed [B, 6] row gather instead of
    # six separate gathers: gather/scatter launches are a measured fixed
    # cost per op on remote runtimes (BENCH_NOTES round 4), and the
    # pack/unpack is elementwise (fused, effectively free).
    packed_req = jnp.stack(
        [batch.hits, batch.limit, batch.duration,
         batch.algo.astype(I64), batch.is_init.astype(I64),
         agg.astype(I64)], axis=-1)
    s_req = packed_req[order]
    s_hits = s_req[:, 0]
    s_limit = s_req[:, 1]
    s_duration = s_req[:, 2]
    s_algo = s_req[:, 3].astype(I32)
    s_init = s_req[:, 4].astype(jnp.bool_)
    s_agg = s_req[:, 5].astype(jnp.bool_)

    seg_start, seg_start_idx, pos, seg_len, commit_mask = segment_structure(
        s_slot, s_valid, s_init)

    # Registers: the live state of each segment's bucket.  Every lane of a
    # segment gathers the SAME slot, so these are replicated per segment.
    g = jnp.clip(s_slot, 0, C - 1)
    cur = _Reg(
        limit=state.limit[g],
        duration=state.duration[g],
        remaining=state.remaining[g],
        tstamp=state.tstamp[g],
        expire=state.expire[g],
        algo=state.algo[g],
    )
    # Miss conditions known before replay: fresh host allocation or lazy TTL
    # expiry (lru.go:110: expireAt < now).  Algorithm switches are detected
    # per-round against the live register.
    cur_fresh = s_init | (cur.expire < now)

    # Fold classification: a hot key's duplicates are usually identical
    # requests (same hits and config, reads interleaved anywhere); those
    # take the zero-replay closed form (fold_classify / fold_entering).
    # Only *irregular* segments (mixed distinct nonzero hits, mixed
    # config, AGG-in-multi-lane) replay — is_init lanes can't appear
    # mid-segment anymore (they start their own virtual segment above).
    # Segment-start replication: one packed row gather instead of five.
    packed_seg = jnp.stack(
        [s_hits, s_limit, s_duration, s_algo.astype(I64),
         cur_fresh.astype(I64)], axis=-1)
    seg0 = packed_seg[seg_start_idx]
    h0 = seg0[:, 0]
    l0 = seg0[:, 1]
    d0 = seg0[:, 2]
    a0 = seg0[:, 3].astype(I32)
    fresh_seg = seg0[:, 4].astype(jnp.bool_)
    seg_fold, nz, n_lead, hstar = fold_classify(
        s_hits, s_limit, s_duration, s_algo, s_agg, seg_start_idx,
        seg_len, h0, l0, d0, a0, fresh_seg, cur, now)
    # A singleton non-fold segment — an aggregated-run lane owning its
    # slot this window, or a lone irregular lane — needs no replay trips
    # either: its one round reads exactly the window-entry register, which
    # the shared pos==0 transition in window_math covers.
    seg_single = s_valid & ~seg_fold & (seg_len == 1)
    max_pos = jnp.max(jnp.where(s_valid & ~seg_fold & ~seg_single, pos,
                                jnp.int32(-1)))

    return WindowPrep(order, s_slot, s_valid, s_hits, s_limit, s_duration,
                      s_algo, s_init, seg_start, seg_start_idx, pos,
                      seg_len, cur, fresh_seg, h0, l0, d0, a0, nz, n_lead,
                      hstar, seg_fold, max_pos, commit_mask, s_agg)


def window_commit(state: BucketState, prep: WindowPrep, fin: _Reg,
                  outs_sorted: WindowOutput
                  ) -> tuple[BucketState, WindowOutput]:
    """Scatter the final segment registers back to the arena (one write per
    touched slot — the window's net effect) and un-sort the responses to
    arrival order.  Shared by the XLA and Pallas paths.

    commit_mask keeps the scatter one-write-per-SLOT: when eviction recycled
    a slot mid-window the slot has several virtual segments, and only the
    last tenant's final register may land in the arena (duplicate scatter
    indices have undefined order in XLA)."""
    C = state.limit.shape[0]
    wslot = jnp.where(prep.commit_mask, prep.s_slot, jnp.int32(C))
    new_state = BucketState(
        limit=state.limit.at[wslot].set(fin.limit, mode="drop"),
        duration=state.duration.at[wslot].set(fin.duration, mode="drop"),
        remaining=state.remaining.at[wslot].set(fin.remaining, mode="drop"),
        tstamp=state.tstamp.at[wslot].set(fin.tstamp, mode="drop"),
        expire=state.expire.at[wslot].set(fin.expire, mode="drop"),
        algo=state.algo.at[wslot].set(fin.algo, mode="drop"),
    )
    # Un-sort via ONE packed row scatter instead of four per-field scatters
    # (per-op launch cost, see window_prep note); unpack is fused slices.
    B = prep.order.shape[0]
    packed_out = jnp.stack(
        [outs_sorted.status.astype(I64), outs_sorted.limit,
         outs_sorted.remaining, outs_sorted.reset_time], axis=-1)
    unpacked = jnp.zeros((B, 4), I64).at[prep.order].set(packed_out)
    unsorted = WindowOutput(
        status=unpacked[:, 0].astype(I32), limit=unpacked[:, 1],
        remaining=unpacked[:, 2], reset_time=unpacked[:, 3])
    return new_state, unsorted


def window_math(now, max_pos, s_valid, s_hits, s_limit, s_duration,
                s_algo, s_agg, pos, seg_len, seg_start_idx, seg_fold,
                h0, l0, d0, a0, fresh_seg, reg, nz, n_lead, hstar):
    """One pass over the sorted window: ONE shared transition call covers
    every lane of foldable segments (entering registers reconstructed in
    closed form by fold_entering) plus every singleton and pos-0 lane,
    then replay rounds run only for the residual irregular segments.
    Pure function of [B] lane vectors — the SAME body runs as a Pallas
    VMEM kernel (ops/pallas_kernel.py), as plain traced XLA in rebased
    int32 (the engine's compact serving default), and as the int64 oracle
    (window_step below), so the three lowerings cannot drift.

    Register state is REPLICATED at every lane of its segment (the arena
    gather outside already yields that), so a replay round is elementwise
    plus ONE vector gather — `computed[seg_start + p]` pulls the active
    lane's freshly-computed register back to every lane of its segment —
    with no scatters.

    Returns (out_sorted: WindowOutput, fin: _Reg) with fin already
    fold-vs-replayed selected (replicated; commit reads any lane).
    """
    B = pos.shape[0]
    valid = s_valid
    p_arr = pos
    sidx = seg_start_idx
    fresh0 = fresh_seg | (a0 != reg.algo)
    seg_single = valid & ~seg_fold & (seg_len == 1)
    covered = seg_fold | seg_single

    # ---- the shared ladder: every covered lane in ONE transition ----
    # pos-0 lanes (any segment kind) see the RAW stored register — the
    # ladder's own init/expiry paths are the ground truth there, which is
    # exactly what the old hoisted singleton call computed.
    ent = fold_entering(reg, fresh0, h0, l0, d0, a0, p_arr, nz, n_lead,
                        hstar, now)
    first = p_arr == 0
    ent = _Reg(*[jnp.where(first, r, e) for r, e in zip(reg, ent)])
    ent_fresh = first & (fresh_seg | (s_algo != reg.algo))
    new_reg, f_out = transition(ent, s_hits, s_limit, s_duration, s_algo,
                                now, ent_fresh, agg=s_agg)
    # a fold segment's committed register is its LAST lane's result
    eidx = jnp.clip(sidx + seg_len - 1, 0, B - 1)
    fin_cov = _Reg(*[jnp.take(x, eidx) for x in new_reg])

    # ---- replay rounds for residual irregular segments ----
    def body(carry):
        p, lim, dur, rem, ts, exp, alg, fr, ost, oli, ore, ors = carry
        r = _Reg(limit=lim, duration=dur, remaining=rem, tstamp=ts,
                 expire=exp, algo=alg)
        # is_init lanes start their own virtual segment, so their
        # freshness is carried by fr (fresh_seg) until their round clears
        # it — no per-lane s_init term needed
        fresh = fr | (s_algo != r.algo)
        new_r, resp = transition(
            r, s_hits, s_limit, s_duration, s_algo, now, fresh,
            agg=s_agg)
        active = (p_arr == p) & valid & ~covered
        # Propagate the active lane's result to its WHOLE segment (the
        # final commit reads replicated registers).  ai = my segment
        # start + p; active[ai] holds iff pos[ai] == p, which
        # algebraically forces sidx[ai] == my sidx — i.e. ai really is MY
        # segment's round-p lane (the clamp cannot false-positive:
        # pos[B-1] == p with a clamped ai would need sidx + p > B-1 and
        # sidx + p == B-1 at once).
        ai = jnp.clip(sidx + p, 0, B - 1)
        take = jnp.take(active, ai)

        def upd(new, old):
            return jnp.where(take, jnp.take(new, ai), old)

        lim = upd(new_r.limit, lim)
        dur = upd(new_r.duration, dur)
        rem = upd(new_r.remaining, rem)
        ts = upd(new_r.tstamp, ts)
        exp = upd(new_r.expire, exp)
        alg = jnp.where(take, jnp.take(new_r.algo, ai), alg)
        fr = jnp.where(take, False, fr)
        ost = jnp.where(active, resp.status, ost)
        oli = jnp.where(active, resp.limit, oli)
        ore = jnp.where(active, resp.remaining, ore)
        ors = jnp.where(active, resp.reset_time, ors)
        return (p + 1, lim, dur, rem, ts, exp, alg, fr, ost, oli, ore, ors)

    init = (jnp.int32(0), reg.limit, reg.duration, reg.remaining,
            reg.tstamp, reg.expire, reg.algo, fresh0,
            f_out.status, f_out.limit, f_out.remaining, f_out.reset_time)
    carry = lax.while_loop(lambda c: c[0] <= max_pos, body, init)
    (_, lim, dur, rem, ts, exp, alg, _, ost, oli, ore, ors) = carry

    # replay rounds never touch covered lanes, so the loop's output
    # buffers (seeded from the shared ladder) are already complete
    out_sorted = WindowOutput(status=ost, limit=oli, remaining=ore,
                              reset_time=ors)
    fin = _Reg(
        limit=jnp.where(covered, fin_cov.limit, lim),
        duration=jnp.where(covered, fin_cov.duration, dur),
        remaining=jnp.where(covered, fin_cov.remaining, rem),
        tstamp=jnp.where(covered, fin_cov.tstamp, ts),
        expire=jnp.where(covered, fin_cov.expire, exp),
        algo=jnp.where(covered, fin_cov.algo, alg))
    return out_sorted, fin


def window_step(state: BucketState, batch: WindowBatch, now) -> tuple[BucketState, WindowOutput]:
    """Apply one window of requests to the arena; returns (new_state, responses).

    Equivalent to the owning node draining one batched GetPeerRateLimits RPC
    item-by-item under the cache mutex (gubernator.go:210-227,236-251), but as
    one device computation.  Responses are positionally aligned with the batch
    (the reference demuxes by index, peers.go:204-207).

    This is the int64 oracle: prep → window_math → commit, the same three
    stages every other lowering (compact32 XLA, Pallas, fused megakernel)
    composes, in full-width arithmetic.
    """
    now = jnp.asarray(now, dtype=I64)
    prep = window_prep(state, batch, now)
    out_sorted, fin = window_math(
        now, prep.max_pos, prep.s_valid, prep.s_hits, prep.s_limit,
        prep.s_duration, prep.s_algo, prep.s_agg, prep.pos, prep.seg_len,
        prep.seg_start_idx, prep.seg_fold, prep.h0, prep.l0, prep.d0,
        prep.a0, prep.fresh_seg, prep.cur, prep.nz, prep.n_lead,
        prep.hstar)
    return window_commit(state, prep, fin, out_sorted)


def pack_outputs(out: WindowOutput, gout: WindowOutput) -> jax.Array:
    """Fuse both windows' responses into one i64[B+Bg, 4] array.

    Lane rows: the regular window's B lanes then the GLOBAL window's Bg
    lanes; columns (status, limit, remaining, reset_time).  One fused array
    means the host pays ONE device→host round trip per dispatch instead of
    eight — on a tunneled chip that round trip (~20ms) dominates the whole
    serving window, and even on PCIe it cuts per-window fixed costs.
    """
    o = jnp.stack(
        [out.status.astype(I64), out.limit, out.remaining, out.reset_time],
        axis=-1)
    g = jnp.stack(
        [gout.status.astype(I64), gout.limit, gout.remaining, gout.reset_time],
        axis=-1)
    return jnp.concatenate([o, g], axis=0)


def split_outputs(fused, lanes: int) -> tuple[WindowOutput, WindowOutput]:
    """Host-side inverse of pack_outputs over [..., B+Bg, 4] numpy buffers:
    returns (regular, GLOBAL) WindowOutputs as zero-copy views."""
    def unpack(a):
        return WindowOutput(
            status=a[..., 0], limit=a[..., 1],
            remaining=a[..., 2], reset_time=a[..., 3])
    return unpack(fused[..., :lanes, :]), unpack(fused[..., lanes:, :])


# ---- compact wire format -------------------------------------------------
# The host<->device transfer is the serving path's fixed cost per window (on
# a tunneled chip it IS the window cost; on PCIe it still bounds small-window
# latency).  Eligible windows (host-checked: 0 <= hits < 2^28,
# 0 <= limit < 2^31, 0 <= duration < 2^31-16) travel packed:
#
#   request  i64[B, 2]:
#     w0: bits 0..31 slot+1 (0 = padded lane), bit 32 is_init,
#         bit 33 algorithm bit 0, bits 34..61 hits,
#         bits 62..63 algorithm bits 1..2 (zero for token/leaky, so the
#         pre-algorithm-plane encoding is bit-identical for algo 0/1;
#         concurrency hits are SIGN-EXTENDED from bit 27 of the hits
#         field, so releases travel as |hits| < 2^27)
#     w1: bits 0..31 limit, bits 32..62 duration
#   response i64[B, 2]:
#     w0: bits 0..30 remaining, bit 31 status,
#         bits 32..63 reset_enc = 0 if reset_time == 0 else reset_time - now + 1
#     w1: the response's limit, raw — it is the STORED limit on hit paths
#         (a live bucket keeps its init-time config, algorithms.go:40-65), so
#         it can exceed the request-side range checks and can't be dropped or
#         packed.
#
# Windows that fail the range checks use the full WindowBatch/pack_outputs
# path, so the compact path is lossless: remaining <= stored limit and
# reset - now <= stored duration always, and the engine permanently drops to
# the full path the first time an out-of-range config enters the arena
# (RateLimitEngine._dispatch), so compact windows only ever read state whose
# stored configs passed the same checks.

COMPACT_MAX_HITS = 1 << 28
COMPACT_MAX_LIMIT = 1 << 31
COMPACT_MAX_DURATION = (1 << 31) - 16


def decode_batch(packed) -> WindowBatch:
    """Device-side decode of the compact request pair (see layout above)."""
    w0 = packed[..., 0]
    w1 = packed[..., 1]
    algo = (((w0 >> 33) & 1) | (((w0 >> 62) & 3) << 1)).astype(I32)
    hits_raw = (w0 >> 34) & (COMPACT_MAX_HITS - 1)
    # concurrency releases: hits sign-extend from bit 27
    hits = jnp.where(algo == CONCURRENCY,
                     (hits_raw ^ CONC_MAX_HITS) - CONC_MAX_HITS, hits_raw)
    return WindowBatch(
        slot=(w0 & 0xFFFFFFFF).astype(I32) - 1,
        hits=hits,
        limit=w1 & 0xFFFFFFFF,
        duration=(w1 >> 32) & 0x7FFFFFFF,
        algo=algo,
        is_init=((w0 >> 32) & 1).astype(jnp.bool_),
    )


def encode_batch_host(slot, hits, limit, duration, algo, is_init):
    """Host-side (numpy) encode into the compact request pair.

    Caller must have verified the COMPACT_MAX_* ranges; padded lanes
    (slot == PAD_SLOT) encode to w0 == 0 regardless of other fields."""
    import numpy as np

    pad = slot < 0
    a64 = algo.astype(np.int64)
    w0 = ((slot.astype(np.int64) + 1)
          | (is_init.astype(np.int64) << 32)
          | ((a64 & 1) << 33)
          | ((hits & (COMPACT_MAX_HITS - 1)) << 34)
          | (((a64 >> 1) & 3) << 62))
    w0 = np.where(pad, 0, w0)
    w1 = limit | (duration << 32)
    return np.stack([w0, w1], axis=-1)


def encode_output_word(out: WindowOutput, now) -> jax.Array:
    """Device-side encode of (status, remaining, reset_time) into one i64
    word per lane.  The response's limit travels separately: the serving
    pipeline echoes the REQUEST limit host-side and fetches the device's
    limit plane only when a window's stored-vs-request mismatch flag fires
    (see engine._compiled_pipeline_step) — on hit paths the two differ only
    when a live bucket's config was changed mid-stream."""
    reset_enc = jnp.where(
        out.reset_time == 0,
        jnp.int64(0),
        jnp.clip(out.reset_time - now, 0, (1 << 31) - 2) + 1,
    )
    return ((reset_enc << 32)
            | (out.status.astype(I64) << 31)
            | jnp.clip(out.remaining, 0, (1 << 31) - 1))


def encode_output_compact(out: WindowOutput, now) -> jax.Array:
    """Device-side encode of responses into i64[B, 2] (packed word, limit)."""
    return jnp.stack([encode_output_word(out, now), out.limit], axis=-1)


def decode_output_host(packed, now) -> WindowOutput:
    """Host-side (numpy) decode of the compact response pair."""
    import numpy as np

    word = packed[..., 0]
    enc = (word >> 32) & 0xFFFFFFFF
    return WindowOutput(
        status=(word >> 31) & 1,
        limit=packed[..., 1],
        remaining=word & 0x7FFFFFFF,
        reset_time=np.where(enc == 0, 0, now + enc - 1),
    )


def global_read(state: BucketState, batch: WindowBatch, now) -> WindowOutput:
    """Answer GLOBAL-behavior requests from the local replica without mutating it.

    Mirrors the non-owner fast path (gubernator.go:173-195): a cached entry is
    returned as-is (hits are NOT applied locally — they reconcile via the
    window psum, see global_apply); a miss is answered as-if-initialized
    (the reference bootstraps its local cache the same way, :189-193 — since
    reads never decrement, recomputing limit-hits each time is
    response-identical while keeping replicas bit-exact across shards).
    """
    C = state.limit.shape[0]
    now = jnp.asarray(now, dtype=I64)
    g = jnp.clip(batch.slot, 0, C - 1)
    reg = _Reg(
        limit=state.limit[g],
        duration=state.duration[g],
        remaining=state.remaining[g],
        tstamp=state.tstamp[g],
        expire=state.expire[g],
        algo=state.algo[g],
    )
    fresh = batch.is_init | (reg.expire < now) | (batch.algo != reg.algo)
    # A cached read is the hit path with hits=0 (the cached status the owner
    # would broadcast, global.go:199-203 → getRateLimit with Hits cleared);
    # a miss is the init path with the request's hits.
    read_hits = jnp.where(fresh, batch.hits, jnp.int64(0))
    _, out = transition(reg, read_hits, batch.limit, batch.duration, batch.algo, now, fresh)
    return out


def global_accumulate(delta: jax.Array, batch: WindowBatch) -> jax.Array:
    """Scatter-add this shard's GLOBAL hits into the per-slot delta array.

    The device-side analog of the reference's hit aggregation map
    (global.go:81-86: `hits[key].Hits += r.Hits`).
    """
    idx = jnp.where(batch.slot >= 0, batch.slot, delta.shape[0])
    return delta.at[idx].add(batch.hits, mode="drop")


class GlobalConfig(NamedTuple):
    """Replicated per-slot config for GLOBAL limits (host-written at allocation).

    The aggregate-apply step needs limit/duration/algorithm per slot; the
    reference carries these on the queued RateLimitReq it sends to the owner
    (global.go:115-153) — here they are resident device state.
    """

    limit: jax.Array  # i64[G]
    duration: jax.Array  # i64[G]
    algo: jax.Array  # i32[G]

    @classmethod
    def zeros(cls, capacity: int) -> "GlobalConfig":
        return cls(
            limit=jnp.zeros((capacity,), I64),
            duration=jnp.zeros((capacity,), I64),
            algo=jnp.zeros((capacity,), I32),
        )


def global_apply(state: BucketState, cfg: GlobalConfig, summed_hits: jax.Array, now
                 ) -> BucketState:
    """Apply psum'd GLOBAL hit totals to the replicated arena.

    Every shard runs this on identical inputs (summed_hits is the psum over
    the mesh axis), so replicas stay bit-exact — this one collective replaces
    both the async hit send (global.go:115-156) and the owner's status
    broadcast (global.go:193-232): after it runs, the authoritative state is
    already resident on every shard.

    Matches the owner's application of the aggregated request: the reference
    sums hits per key and applies the sum as one request through the normal
    algorithm (global.go:81-86 → gubernator.go:218-226).
    """
    now = jnp.asarray(now, dtype=I64)
    reg = _Reg(
        limit=state.limit,
        duration=state.duration,
        remaining=state.remaining,
        tstamp=state.tstamp,
        expire=state.expire,
        algo=state.algo,
    )
    fresh = (reg.expire < now) | (cfg.algo != reg.algo)
    new_reg, _ = transition(reg, summed_hits, cfg.limit, cfg.duration, cfg.algo, now, fresh)
    touched = summed_hits != 0
    merged = jax.tree.map(lambda n, o: jnp.where(touched, n, o), new_reg, reg)
    return BucketState(*merged)


def global_combined(state: BucketState, cfg: GlobalConfig, batch: WindowBatch,
                    summed_hits: jax.Array, now
                    ) -> tuple[BucketState, WindowOutput]:
    """global_read + global_apply as ONE transition over concatenated lanes.

    Sequentially the GLOBAL window is two separate transition ladders —
    the Bg replica reads, then the G-wide aggregate apply — which doubles
    the sub-window's executed-kernel count for no data-dependence reason:
    reads never mutate and by construction see the PRE-apply replica
    (global_read runs before the psum lands).  Stacking both lane sets
    into one [Bg+G] batch runs the shared state machine once; the read
    half's register outputs and the apply half's response outputs are
    simply discarded, exactly as the standalone calls discard them.
    Bit-exact with global_read followed by global_apply because transition
    is purely lane-wise.  Returns (new_state, read_outputs).
    """
    C = state.limit.shape[0]
    now = jnp.asarray(now, dtype=I64)
    g = jnp.clip(batch.slot, 0, C - 1)
    reg = _Reg(*state)
    r_reg = _Reg(*[x[g] for x in state])
    r_fresh = (batch.is_init | (r_reg.expire < now)
               | (batch.algo != r_reg.algo))
    a_fresh = (reg.expire < now) | (cfg.algo != reg.algo)
    cat = lambda a, b: jnp.concatenate([a, b], axis=0)
    ent = _Reg(*[cat(r, s) for r, s in zip(r_reg, reg)])
    new_reg, out = transition(
        ent,
        cat(jnp.where(r_fresh, batch.hits, jnp.int64(0)), summed_hits),
        cat(batch.limit, cfg.limit),
        cat(batch.duration, cfg.duration),
        cat(batch.algo, cfg.algo),
        now,
        cat(r_fresh, a_fresh),
    )
    Bg = batch.slot.shape[0]
    read_out = WindowOutput(*[o[:Bg] for o in out])
    apply_reg = _Reg(*[r[Bg:] for r in new_reg])
    touched = summed_hits != 0
    merged = jax.tree.map(lambda n, o: jnp.where(touched, n, o),
                          apply_reg, reg)
    return BucketState(*merged), read_out
