"""The rate-limit window kernel: both bucket algorithms over dense SoA state.

This module is the TPU-native replacement for the reference's hot loop — the
`tokenBucket`/`leakyBucket` functions applied one key at a time under a global
cache mutex (reference algorithms.go:24-186, gubernator.go:236-251).  Here one
*window* of requests (the reference's 500µs BATCHING window, peers.go:143-172)
is evaluated as a single fused XLA computation over a batch:

  * State is a structure-of-arrays arena in device memory (`BucketState`),
    replacing the map+linked-list LRU (reference cache/lru.go:30-96).  A slot
    index replaces the string key; the host keeps the key→slot table
    (state/arena.py).
  * Every request in the window is routed to a slot.  Requests to *different*
    slots are data-parallel.  Requests to the *same* slot must observe
    sequential semantics (request N+1 sees N's decrement — the reference gets
    this from the cache mutex), which we reproduce with a sorted
    segment-replay: sort the window by slot, then run `max_duplicates` rounds
    of a fully-vectorized transition, each round applying the p-th request of
    every segment simultaneously.  A window of unique keys converges in one
    round; only hot-key duplicates add rounds.
  * Lazy TTL expiry (reference cache/lru.go:110-114: entry is a miss when
    `expireAt < now`) is evaluated *inside* the kernel, so the host table
    never needs to know whether an entry is live.

Branch semantics are reproduced exactly — including the subtle ones:
no-mutation-on-over-ask (algorithms.go:57-62,143-148), hits==0 read-only
(algorithms.go:46-49,150-153), exact-drain returns UNDER_LIMIT
(algorithms.go:51-55,136-141), OVER_LIMIT *is* stored on first-request
over-ask (algorithms.go:77-83,176-181), leaky's rate computed from the stored
duration but the *request's* limit (algorithms.go:107), the leaky timestamp
advancing even when the request is rejected (algorithms.go:118-121,143-148),
and repeated leak application when zero-hit reads interleave (a consequence of
algorithms.go:110-121).

Deliberate divergences from the reference (see SURVEY.md §7 "reference bugs
not to replicate"):
  * algorithm switch mid-stream resets the entry and re-runs it under the
    *requested* algorithm (the reference falls back to tokenBucket from
    leakyBucket, algorithms.go:100-104);
  * successful leaky decrement extends expiry to now + duration (the reference
    computes `now * duration`, algorithms.go:157);
  * leaky `rate` is clamped to ≥1ms (the reference divides by zero when
    limit > duration, algorithms.go:107-111 — a Go runtime panic).

All rate quantities are int64 (proto contract, gubernator.proto:104-117) and
timestamps are unix-epoch milliseconds (cache/lru.go:99-101) passed in as the
per-window `now` scalar — one timestamp per window instead of one per request.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

# Algorithm / status constants mirrored from the proto enums
# (proto/gubernator.proto:56-61,126-129).  Kept as plain ints so they can be
# used inside jit without host lookups.
TOKEN_BUCKET = 0
LEAKY_BUCKET = 1
UNDER_LIMIT = 0
OVER_LIMIT = 1

# Slot value marking a padded (unused) lane of a window batch.
PAD_SLOT = -1

# Aggregated-run flag, carried in bit 30 of a lane's slot (arena capacities
# are <= 2^27, so the bit is free; pads are negative and unaffected).  The
# native router collapses a UNIFORM run of n identical hits=1, limit>0
# requests to one key into ONE lane with hits=n and this bit set; the
# device consumes k* = min(n, r_start) tokens and answers with r_start,
# from which the host synthesizes every item's response (status_i =
# i < r_start, remaining_i = max(r_start-(i+1), 0) — no n needed).  Only
# the compact serving path ever sets it (host_router.cc).
AGG_SLOT_BIT = 1 << 30

I32 = jnp.int32
I64 = jnp.int64


class BucketState(NamedTuple):
    """Dense SoA arena state, one row per key slot.

    Replaces the reference's cacheRecord {value, expireAt} where value is
    either a *RateLimitResp (token) or a LeakyBucket (leaky)
    (cache/lru.go:42-46, algorithms.go:70-75,89-94,162-167):

      limit/duration: the stored config, captured at (re)initialization.
      remaining:      tokens left in the bucket.
      tstamp:         token: the bucket's reset_time (== window end, ms epoch);
                      leaky: the last-leak TimeStamp.
      expire:         cache-entry expiry (ms epoch).  0 == never initialized,
                      and `expire < now` == expired, both of which read as a
                      cache miss (lru.go:110-114).
      algo:           which algorithm initialized this slot; a mismatch with
                      the request's algorithm reads as a miss.
    """

    limit: jax.Array  # i64[C]
    duration: jax.Array  # i64[C]
    remaining: jax.Array  # i64[C]
    tstamp: jax.Array  # i64[C]
    expire: jax.Array  # i64[C]
    algo: jax.Array  # i32[C]

    @classmethod
    def zeros(cls, capacity: int) -> "BucketState":
        z64 = jnp.zeros((capacity,), dtype=I64)
        return cls(
            limit=z64,
            duration=z64,
            remaining=z64,
            tstamp=z64,
            expire=z64,
            algo=jnp.zeros((capacity,), dtype=I32),
        )


class WindowBatch(NamedTuple):
    """One batching window's requests, routed to slots and padded to length B."""

    slot: jax.Array  # i32[B], PAD_SLOT for unused lanes
    hits: jax.Array  # i64[B]
    limit: jax.Array  # i64[B]
    duration: jax.Array  # i64[B]
    algo: jax.Array  # i32[B]
    is_init: jax.Array  # bool[B]: host just allocated this slot for a new key

    @classmethod
    def pad(cls, size: int) -> "WindowBatch":
        return cls(
            slot=jnp.full((size,), PAD_SLOT, dtype=I32),
            hits=jnp.zeros((size,), dtype=I64),
            limit=jnp.zeros((size,), dtype=I64),
            duration=jnp.zeros((size,), dtype=I64),
            algo=jnp.zeros((size,), dtype=I32),
            is_init=jnp.zeros((size,), dtype=jnp.bool_),
        )


class WindowOutput(NamedTuple):
    """Per-request responses (RateLimitResp fields, proto:131-143)."""

    status: jax.Array  # i32[B]
    limit: jax.Array  # i64[B]
    remaining: jax.Array  # i64[B]
    reset_time: jax.Array  # i64[B]


class _Reg(NamedTuple):
    """A segment's live bucket state during replay (same fields as BucketState)."""

    limit: jax.Array
    duration: jax.Array
    remaining: jax.Array
    tstamp: jax.Array
    expire: jax.Array
    algo: jax.Array


def _chain(pairs, default):
    """First-match-wins selection, mirroring the reference's if/else ladders."""
    out = default
    for cond, val in reversed(pairs):
        out = jnp.where(cond, val, out)
    return out


def transition(reg: _Reg, hits, req_limit, req_duration, req_algo, now, fresh,
               agg=None):
    """One request applied to one bucket, vectorized over the batch dimension.

    `fresh` marks lanes that must take the cache-miss/init path (new slot,
    expired entry, or algorithm switch).  Returns (new_reg, WindowOutput).

    The branch ladders reproduce algorithms.go:24-85 (token) and
    algorithms.go:88-186 (leaky) exactly; see the module docstring for the
    three documented divergences.

    `agg` (optional bool lanes) marks AGGREGATED runs (see AGG_SLOT_BIT):
    the lane's `hits` carries the run length n of identical hits=1
    requests, the state update consumes k* = min(n, r_start) exactly as n
    sequential hits=1 transitions would, and the response's `remaining`
    returns r_start (the pre-run balance) for host-side per-item synthesis.
    """
    L, D, R, T, E, A = reg
    h = hits
    is_token = req_algo == TOKEN_BUCKET
    # counter dtype follows the inputs: i64 normally; the Pallas TPU path
    # runs the same ladder in rebased i32 (Mosaic has no 64-bit vectors,
    # and the compact-format range caps make i32 exact — see
    # ops/pallas_kernel.py)
    Z = jnp.asarray(0, h.dtype)
    ONE = jnp.asarray(1, h.dtype)

    # ---- init path (cache miss): algorithms.go:68-84 / :161-185 ----
    over_init = h > req_limit
    init_R = jnp.where(over_init, Z, req_limit - h)
    init_status = jnp.where(over_init, OVER_LIMIT, UNDER_LIMIT).astype(I32)
    # token stores reset_time = now+duration (:69-74); leaky stores
    # TimeStamp = now (:166) and its init response has ResetTime 0 (:173).
    init_T = jnp.where(is_token, now + req_duration, now)
    init_reg = _Reg(
        limit=req_limit,
        duration=req_duration,
        remaining=init_R,
        tstamp=init_T,
        expire=now + req_duration,
        algo=req_algo,
    )
    init_out = WindowOutput(
        status=init_status,
        limit=req_limit,
        remaining=init_R,
        reset_time=jnp.where(is_token, now + req_duration, Z),
    )

    # ---- token bucket hit path: algorithms.go:40-65 ----
    tb_at_zero = R == 0  # :41-44 -> OVER, remaining 0
    tb_read = h == 0  # :47-49 -> read-only
    tb_drain = h == R  # :52-55 -> UNDER, remaining -> 0
    tb_over = h > R  # :58-62 -> OVER, state NOT mutated
    t_status = _chain(
        [(tb_at_zero, OVER_LIMIT), (tb_read, UNDER_LIMIT), (tb_drain, UNDER_LIMIT), (tb_over, OVER_LIMIT)],
        UNDER_LIMIT,
    ).astype(I32)
    t_resp_R = _chain(
        [(tb_at_zero, Z), (tb_read, R), (tb_drain, Z), (tb_over, R)],
        R - h,
    )
    t_new_R = _chain(
        [(tb_at_zero, R), (tb_read, R), (tb_drain, Z), (tb_over, R)],
        R - h,
    )
    token_reg = _Reg(limit=L, duration=D, remaining=t_new_R, tstamp=T, expire=E, algo=A)
    # all token hit responses carry the stored limit and stored reset_time
    token_out = WindowOutput(status=t_status, limit=L, remaining=t_resp_R, reset_time=T)

    # ---- leaky bucket hit path: algorithms.go:107-158 ----
    # rate = stored duration / REQUEST limit (:107) — a reference quirk we
    # keep; clamped to >=1ms where the reference would panic on a zero rate.
    rate = D // jnp.maximum(req_limit, ONE)
    rate = jnp.maximum(rate, ONE)
    leak = (now - T) // rate  # :110-111
    # :113-115 clamp to stored limit; written add-after-min (equivalent
    # given R <= L) so the i32 Pallas path cannot overflow on R + leak
    R2 = R + jnp.minimum(leak, L - R)
    T2 = jnp.where(h != 0, now, T)  # :118-121 ts advances only on hits
    lb_at_zero = R2 == 0  # :130-134 -> OVER, reset now+rate
    lb_drain = h == R2  # :136-141 -> UNDER, remaining -> 0, reset 0
    lb_over = h > R2  # :143-148 -> OVER, no decrement, reset now+rate
    lb_read = h == 0  # :150-153 -> read-only
    l_status = _chain(
        [(lb_at_zero, OVER_LIMIT), (lb_drain, UNDER_LIMIT), (lb_over, OVER_LIMIT), (lb_read, UNDER_LIMIT)],
        UNDER_LIMIT,
    ).astype(I32)
    l_resp_R = _chain(
        [(lb_at_zero, Z), (lb_drain, Z), (lb_over, R2), (lb_read, R2)],
        R2 - h,
    )
    l_reset = _chain(
        [(lb_at_zero, now + rate), (lb_drain, Z), (lb_over, now + rate), (lb_read, Z)],
        Z,
    )
    l_new_R = _chain(
        [(lb_at_zero, R2), (lb_drain, Z), (lb_over, R2), (lb_read, R2)],
        R2 - h,
    )
    # expiry extends only on a successful decrement (:155-157, with the
    # now*duration bug corrected to now+duration using the request's duration)
    l_hit = ~(lb_at_zero | lb_drain | lb_over | lb_read)
    l_new_E = jnp.where(l_hit, now + req_duration, E)
    leaky_reg = _Reg(limit=L, duration=D, remaining=l_new_R, tstamp=T2, expire=l_new_E, algo=A)
    leaky_out = WindowOutput(status=l_status, limit=L, remaining=l_resp_R, reset_time=l_reset)

    # ---- combine: requested algorithm picks the hit path (non-fresh lanes
    # are guaranteed to have stored algo == requested algo) ----
    hit_reg = jax.tree.map(lambda t, l: jnp.where(is_token, t, l), token_reg, leaky_reg)
    hit_out = jax.tree.map(lambda t, l: jnp.where(is_token, t, l), token_out, leaky_out)

    new_reg = jax.tree.map(lambda i, hh: jnp.where(fresh, i, hh), init_reg, hit_reg)
    out = jax.tree.map(lambda i, hh: jnp.where(fresh, i, hh), init_out, hit_out)
    new_reg, out = _Reg(*new_reg), WindowOutput(*out)
    if agg is None:
        return new_reg, out

    # ---- aggregated runs: n sequential hits=1 transitions in one lane ----
    # r_start: post-init balance for fresh lanes (init consumes via k*, so
    # the base is the full limit), else current balance with the leak
    # applied for leaky.  limit > 0 guaranteed by the router's aggregation
    # conditions (a fresh leaky limit=0 run's first item would need the
    # init-path ResetTime=0 special the synthesis cannot express).
    n = h
    a_L = jnp.where(fresh, req_limit, L)
    a_D = jnp.where(fresh, req_duration, D)
    a_base_tok = jnp.where(fresh, req_limit, R)
    a_base_lky = jnp.where(fresh, req_limit, R2)
    a_base = jnp.where(is_token, a_base_tok, a_base_lky)
    k = jnp.minimum(n, a_base)
    a_R = a_base - k
    a_rate = jnp.maximum(a_D // jnp.maximum(req_limit, ONE), ONE)
    # leaky expiry: extends iff any GENERIC decrement happened (the last
    # consume is a drain when the balance hits 0 — same accounting as
    # uniform_closed_form)
    lky_extended = (k - (a_R == 0)) >= 1
    a_reg = _Reg(
        limit=a_L,
        duration=a_D,
        remaining=a_R,
        tstamp=jnp.where(is_token, jnp.where(fresh, now + req_duration, T),
                         now),
        expire=jnp.where(
            is_token,
            jnp.where(fresh, now + req_duration, E),
            jnp.where(fresh | lky_extended, now + req_duration, E)),
        algo=req_algo,
    )
    a_out = WindowOutput(
        # host-synthesized per item; the word carries r_start and the
        # OVER-item reset (token: the bucket's reset_time; leaky:
        # now+rate — UNDER leaky items synthesize 0)
        status=jnp.where(k < n, OVER_LIMIT, UNDER_LIMIT).astype(I32),
        limit=a_L,
        remaining=a_base,
        reset_time=jnp.where(is_token,
                             jnp.where(fresh, now + req_duration, T),
                             now + a_rate),
    )
    new_reg = jax.tree.map(lambda a, b: jnp.where(agg, a, b), a_reg, new_reg)
    out = jax.tree.map(lambda a, b: jnp.where(agg, a, b), a_out, out)
    return _Reg(*new_reg), WindowOutput(*out)


def uniform_closed_form(st: _Reg, fresh0, h0, l0, d0, a0, pos, seg_len, now):
    """Closed form of a UNIFORM segment (every lane same hits>0/config):
    the greedy use-it-or-lose-it sequence decrements for the first
    k* = min(len, r_start // h) lanes and rejects the rest without
    mutating — matching algorithms.go:51-65/:136-148 item by item.

    `st` is the segment's live register REPLICATED to every lane (the lane's
    own segment-start register); all math is elementwise over lanes, which
    is what lets the Pallas lowering (ops/pallas_kernel.py) run it in one
    VMEM-resident pass.  Returns (final register, per-lane outputs)."""
    is_token0 = a0 == TOKEN_BUCKET
    init_over0 = h0 > l0
    # dtype-generic like transition: i64 normally, rebased i32 on the
    # Pallas TPU path
    Z = jnp.asarray(0, h0.dtype)
    ONE = jnp.asarray(1, h0.dtype)

    L_eff = jnp.where(fresh0, l0, st.limit)
    D_eff = jnp.where(fresh0, d0, st.duration)
    # token: reset_time is now+duration on init, stored otherwise
    T0_tok = jnp.where(fresh0, now + d0, st.tstamp)
    rate0 = jnp.maximum(D_eff // jnp.maximum(l0, ONE), ONE)
    leak0 = jnp.where(fresh0, Z, (now - st.tstamp) // rate0)
    r_start_tok = jnp.where(
        fresh0, jnp.where(init_over0, Z, l0), st.remaining)
    r_start_lky = jnp.where(
        fresh0,
        jnp.where(init_over0, Z, l0),
        # add-after-min (equivalent given remaining <= limit): no i32
        # overflow on remaining + leak
        st.remaining + jnp.minimum(leak0, L_eff - st.remaining),
    )
    r_start = jnp.where(is_token0, r_start_tok, r_start_lky)
    kstar = jnp.minimum(seg_len.astype(h0.dtype), r_start // h0)
    r_end = r_start - kstar * h0

    posl = pos.astype(h0.dtype)
    under = posl < kstar
    ff_rem = jnp.where(under, r_start - (posl + 1) * h0, r_end)
    ff_status = jnp.where(under, UNDER_LIMIT, OVER_LIMIT).astype(I32)
    # leaky: UNDER lanes report 0; OVER lanes report now+rate — except the
    # very first lane of a fresh bucket, whose init response is always 0
    # (algorithms.go:169-181)
    lky_reset = jnp.where(
        under | (fresh0 & (pos == 0)), Z, now + rate0)
    ff_reset = jnp.where(is_token0, T0_tok, lky_reset)
    ff_out = WindowOutput(
        status=ff_status, limit=L_eff, remaining=ff_rem, reset_time=ff_reset)

    # Leaky expiry extends only on GENERIC decrements (algorithms.go:
    # 155-157) — the exact-drain branch (:136-141) leaves it untouched.
    # Within a uniform run a drain can only be the LAST consume (h ==
    # remaining ⇔ r_end hits 0), so the generic count is kstar minus one
    # when r_end == 0; extension happened iff that count >= 1.  (Caught
    # by the hypothesis fuzz: a lone exact drain must NOT re-arm a long
    # TTL with the request's shorter duration.)
    extended = (kstar - (r_end == 0)) >= 1
    ff_reg = _Reg(
        limit=L_eff,
        duration=D_eff,
        remaining=r_end,
        tstamp=jnp.where(is_token0, T0_tok, now),
        expire=jnp.where(
            is_token0,
            jnp.where(fresh0, now + d0, st.expire),
            jnp.where(fresh0 | extended, now + d0, st.expire),
        ),
        algo=a0,
    )
    return ff_reg, ff_out


def segment_structure(s_slot, s_valid, s_init):
    """Segment indexing over a slot-sorted window: virtual-segment starts,
    per-lane segment start index / position / length, and the commit mask
    (the lanes whose final register may land in the arena).

    Segments are VIRTUAL: they break at slot changes AND at is_init lanes
    (see window_prep's docstring for why).  Written in kernel-safe
    primitives only — shifted compares via `jnp.take`, `lax.cummax` /
    `lax.cummin` scans — because this exact function also runs INSIDE the
    fused Pallas megakernel (ops/pallas_kernel.py window_step_fused), where
    Mosaic has no concatenate-shift or scatter forms.  Sharing the one
    implementation is what keeps the XLA and fused paths from drifting.

    Returns (seg_start, seg_start_idx, pos, seg_len, commit_mask).
    """
    B = s_slot.shape[0]
    idx = lax.iota(I32, B)
    prev_slot = jnp.take(s_slot, jnp.maximum(idx - 1, 0))
    phys_start = (idx == 0) | (s_slot != prev_slot)
    seg_start = phys_start | (s_init & s_valid)
    seg_start_idx = lax.cummax(jnp.where(seg_start, idx, jnp.int32(0)))
    pos = idx - seg_start_idx
    # next segment start at-or-after lane i+1 (B when none): lane i's value
    # is min over j > i of {j if start[j] else B}, via a reverse cummin of
    # the shifted-start lattice
    nxt = jnp.minimum(idx + 1, B - 1)

    def _next_boundary(start):
        shifted = jnp.where(jnp.take(start, nxt) & (idx < B - 1),
                            idx + 1, jnp.int32(B))
        return lax.cummin(shifted, reverse=True)

    next_start = _next_boundary(seg_start)
    seg_len = next_start - seg_start_idx
    # a virtual segment is its slot's LAST (→ the one that commits) iff no
    # further virtual start precedes the next physical slot change
    next_phys = _next_boundary(phys_start)
    commit_mask = seg_start & s_valid & (next_start >= next_phys)
    return seg_start, seg_start_idx, pos, seg_len, commit_mask


def segment_all(ok, seg_start_idx, seg_len):
    """Per-lane: does EVERY lane of my segment satisfy `ok`?  Replicated to
    all lanes of the segment.

    Cumsum range-count instead of a scatter-min (`.at[seg].min`): counts the
    failing lanes inside [seg_start, seg_start+len) from an inclusive
    prefix sum — gather-only, so the SAME code runs in window_prep's XLA
    trace and inside the fused Pallas megakernel.
    """
    bad = (~ok).astype(I32)
    csum = jnp.cumsum(bad)
    seg_end = seg_start_idx + seg_len - 1
    n_bad = (jnp.take(csum, seg_end) - jnp.take(csum, seg_start_idx)
             + jnp.take(bad, seg_start_idx))
    return n_bad == 0


class WindowPrep(NamedTuple):
    """Everything window_step derives from a window before the transition
    math: sorted request lanes, segment structure, gathered registers, and
    uniform-segment classification.  Shared verbatim by the XLA path below
    and the Pallas lowering (ops/pallas_kernel.py) so the two cannot drift.
    """

    order: jax.Array
    s_slot: jax.Array
    s_valid: jax.Array
    s_hits: jax.Array
    s_limit: jax.Array
    s_duration: jax.Array
    s_algo: jax.Array
    s_init: jax.Array
    seg_start: jax.Array
    seg_start_idx: jax.Array
    pos: jax.Array
    seg_len: jax.Array
    cur: _Reg          # live registers, REPLICATED at every lane
    fresh_seg: jax.Array  # segment-level miss, replicated (start lane's)
    h0: jax.Array      # segment-start request fields, replicated
    l0: jax.Array
    d0: jax.Array
    a0: jax.Array
    seg_uniform: jax.Array
    max_pos: jax.Array
    commit_mask: jax.Array  # lanes whose register commits to the arena
    s_agg: jax.Array   # aggregated-run lanes (AGG_SLOT_BIT), sorted order


def window_prep(state: BucketState, batch: WindowBatch, now) -> WindowPrep:
    """Sort by slot, find segments, gather registers, classify uniform
    segments (see window_step for the semantics each piece serves).

    Segments are VIRTUAL: they break at slot changes AND at is_init lanes.
    Capacity eviction can recycle a slot to a different key mid-window
    (state/arena.py + native pack assign the new tenant's first lane
    is_init); splitting there turns [old-tenant lanes][init + new-tenant
    lanes] into two independently-uniform segments, so a recycled hot slot
    keeps the closed form instead of forcing a lane-by-lane replay of the
    whole run (a 3000-duplicate Zipf head key would otherwise cost 3000
    replay rounds in one device call).  Only the LAST virtual segment of a
    slot commits to the arena (earlier tenants' counters die with the
    eviction, exactly like the reference's cache Remove)."""
    B = batch.slot.shape[0]
    C = state.limit.shape[0]

    valid = batch.slot >= 0
    # Strip the aggregated-run flag off the slot BEFORE anything keys on
    # slot values (sorting, sharding, the arena gather).
    agg = valid & ((batch.slot & jnp.int32(AGG_SLOT_BIT)) != 0)
    slot_clean = jnp.where(agg, batch.slot & jnp.int32(~AGG_SLOT_BIT),
                           batch.slot)
    # Sort by slot (stable → arrival order preserved within a slot); pads last.
    # Packed single-key sort instead of jnp.argsort: fold (key, lane) into one
    # i64 word with the lane index in the low bits.  A single-array sort of
    # that word is bit-identical to a stable argsort (ties break on lane
    # order) but avoids XLA's variadic comparator sort, which costs ~5x more
    # per window on the CPU backend (BENCH_NOTES round 6).
    sort_key = jnp.where(valid, slot_clean, jnp.int32(2**31 - 1))
    lane_bits = max((B - 1).bit_length(), 1)
    packed_key = ((sort_key.astype(I64) << lane_bits)
                  | lax.iota(I64, B))
    sorted_key = lax.sort(packed_key, is_stable=False)
    order = (sorted_key & jnp.int64((1 << lane_bits) - 1)).astype(I32)
    s_slot = (sorted_key >> lane_bits).astype(I32)
    s_valid = valid[order]
    # Permute the request fields as ONE packed [B, 6] row gather instead of
    # six separate gathers: gather/scatter launches are a measured fixed
    # cost per op on remote runtimes (BENCH_NOTES round 4), and the
    # pack/unpack is elementwise (fused, effectively free).
    packed_req = jnp.stack(
        [batch.hits, batch.limit, batch.duration,
         batch.algo.astype(I64), batch.is_init.astype(I64),
         agg.astype(I64)], axis=-1)
    s_req = packed_req[order]
    s_hits = s_req[:, 0]
    s_limit = s_req[:, 1]
    s_duration = s_req[:, 2]
    s_algo = s_req[:, 3].astype(I32)
    s_init = s_req[:, 4].astype(jnp.bool_)
    s_agg = s_req[:, 5].astype(jnp.bool_)

    seg_start, seg_start_idx, pos, seg_len, commit_mask = segment_structure(
        s_slot, s_valid, s_init)

    # Registers: the live state of each segment's bucket.  Every lane of a
    # segment gathers the SAME slot, so these are replicated per segment.
    g = jnp.clip(s_slot, 0, C - 1)
    cur = _Reg(
        limit=state.limit[g],
        duration=state.duration[g],
        remaining=state.remaining[g],
        tstamp=state.tstamp[g],
        expire=state.expire[g],
        algo=state.algo[g],
    )
    # Miss conditions known before replay: fresh host allocation or lazy TTL
    # expiry (lru.go:110: expireAt < now).  Algorithm switches are detected
    # per-round against the live register.
    cur_fresh = s_init | (cur.expire < now)

    # Uniform-segment classification: a hot key's duplicates are usually
    # identical requests (same hits>0 and config); those take the closed
    # form (uniform_closed_form).  Only *irregular* segments (mixed
    # hits/config, zero-hit reads) replay — is_init lanes can't appear
    # mid-segment anymore (they start their own virtual segment above).
    # Segment-start replication: one packed row gather instead of five.
    packed_seg = jnp.stack(
        [s_hits, s_limit, s_duration, s_algo.astype(I64),
         cur_fresh.astype(I64)], axis=-1)
    seg0 = packed_seg[seg_start_idx]
    h0 = seg0[:, 0]
    l0 = seg0[:, 1]
    d0 = seg0[:, 2]
    a0 = seg0[:, 3].astype(I32)
    fresh_seg = seg0[:, 4].astype(jnp.bool_)
    lane_ok = (
        (s_hits == h0) & (s_limit == l0) & (s_duration == d0)
        & (s_algo == a0) & ~s_agg
    )
    seg_uniform = segment_all(lane_ok, seg_start_idx, seg_len) & (h0 > 0)
    # A singleton non-uniform segment — a folded (aggregated-run) lane
    # owning its slot this window, or a lone hits=0 peek — is closed-form
    # too: its one replay round would read exactly the window-entry
    # register, so window_step hoists that same transition call out of
    # the loop and it must not force replay trips here.
    seg_single = s_valid & ~seg_uniform & (seg_len == 1)
    max_pos = jnp.max(jnp.where(s_valid & ~seg_uniform & ~seg_single, pos,
                                jnp.int32(-1)))

    return WindowPrep(order, s_slot, s_valid, s_hits, s_limit, s_duration,
                      s_algo, s_init, seg_start, seg_start_idx, pos,
                      seg_len, cur, fresh_seg, h0, l0, d0, a0, seg_uniform,
                      max_pos, commit_mask, s_agg)


def window_commit(state: BucketState, prep: WindowPrep, fin: _Reg,
                  outs_sorted: WindowOutput
                  ) -> tuple[BucketState, WindowOutput]:
    """Scatter the final segment registers back to the arena (one write per
    touched slot — the window's net effect) and un-sort the responses to
    arrival order.  Shared by the XLA and Pallas paths.

    commit_mask keeps the scatter one-write-per-SLOT: when eviction recycled
    a slot mid-window the slot has several virtual segments, and only the
    last tenant's final register may land in the arena (duplicate scatter
    indices have undefined order in XLA)."""
    C = state.limit.shape[0]
    wslot = jnp.where(prep.commit_mask, prep.s_slot, jnp.int32(C))
    new_state = BucketState(
        limit=state.limit.at[wslot].set(fin.limit, mode="drop"),
        duration=state.duration.at[wslot].set(fin.duration, mode="drop"),
        remaining=state.remaining.at[wslot].set(fin.remaining, mode="drop"),
        tstamp=state.tstamp.at[wslot].set(fin.tstamp, mode="drop"),
        expire=state.expire.at[wslot].set(fin.expire, mode="drop"),
        algo=state.algo.at[wslot].set(fin.algo, mode="drop"),
    )
    # Un-sort via ONE packed row scatter instead of four per-field scatters
    # (per-op launch cost, see window_prep note); unpack is fused slices.
    B = prep.order.shape[0]
    packed_out = jnp.stack(
        [outs_sorted.status.astype(I64), outs_sorted.limit,
         outs_sorted.remaining, outs_sorted.reset_time], axis=-1)
    unpacked = jnp.zeros((B, 4), I64).at[prep.order].set(packed_out)
    unsorted = WindowOutput(
        status=unpacked[:, 0].astype(I32), limit=unpacked[:, 1],
        remaining=unpacked[:, 2], reset_time=unpacked[:, 3])
    return new_state, unsorted


def window_step(state: BucketState, batch: WindowBatch, now) -> tuple[BucketState, WindowOutput]:
    """Apply one window of requests to the arena; returns (new_state, responses).

    Equivalent to the owning node draining one batched GetPeerRateLimits RPC
    item-by-item under the cache mutex (gubernator.go:210-227,236-251), but as
    one device computation.  Responses are positionally aligned with the batch
    (the reference demuxes by index, peers.go:204-207).
    """
    B = batch.slot.shape[0]
    now = jnp.asarray(now, dtype=I64)

    prep = window_prep(state, batch, now)
    (order, s_slot, s_valid, s_hits, s_limit, s_duration, s_algo, s_init,
     seg_start, seg_start_idx, pos, seg_len, cur, fresh_seg, h0, l0, d0,
     a0, seg_uniform, max_pos, _commit_mask, s_agg) = prep
    cur_fresh = s_init | (cur.expire < now)

    # Registers travel PACKED as one [B, 7] row array (the seventh column
    # is the per-lane fresh flag): the closed-form segment gather and every
    # replay round are then one row gather + one row scatter instead of
    # 6-7 per-field launches — per-op launch cost is a measured fixed cost
    # on remote runtimes (BENCH_NOTES round 4).
    def pack_reg(reg, fresh):
        return jnp.stack(
            [reg.limit, reg.duration, reg.remaining, reg.tstamp,
             reg.expire, reg.algo.astype(I64), fresh.astype(I64)], axis=-1)

    def unpack_reg(rows):
        return _Reg(limit=rows[:, 0], duration=rows[:, 1],
                    remaining=rows[:, 2], tstamp=rows[:, 3],
                    expire=rows[:, 4],
                    algo=rows[:, 5].astype(I32)), rows[:, 6] != 0

    cur_packed = pack_reg(cur, cur_fresh)
    st, st_fresh = unpack_reg(cur_packed[seg_start_idx])
    fresh0 = fresh_seg | (a0 != st.algo)
    ff_reg, ff_out = uniform_closed_form(
        st, fresh0, h0, l0, d0, a0, pos, seg_len, now)

    # Singleton non-uniform segments (a folded lane owning its slot this
    # window — the fold's normal shape — or a lone hits=0 peek): their one
    # replay round reads exactly the window-entry register, so hoist the
    # SAME transition call (same inputs) to straight line.  It fuses with
    # the ladder above, and a fold-only window runs ZERO replay trips
    # (window_prep's max_pos already excludes these lanes).
    seg_single = s_valid & ~seg_uniform & (seg_len == 1)
    a_reg, a_out = transition(st, s_hits, s_limit, s_duration, s_algo,
                              now, st_fresh | (s_algo != st.algo),
                              agg=s_agg)

    # replay buffers start from the fast-path answers; replay rounds only
    # overwrite lanes of non-uniform segments
    outs = ff_out

    def round_body(carry):
        p, cur_packed, outs = carry
        active = (pos == p) & s_valid & ~seg_uniform & ~seg_single
        reg, reg_fresh = unpack_reg(cur_packed[seg_start_idx])
        # fresh: segment-level miss (expired/new/init at window start — an
        # is_init lane always starts its own virtual segment, so its flag
        # is carried in the packed rows until its round clears it) or an
        # algorithm switch against the live register.
        fresh = reg_fresh | (s_algo != reg.algo)
        new_reg, resp = transition(reg, s_hits, s_limit, s_duration, s_algo,
                                   now, fresh, agg=s_agg)
        # One active lane per segment → scatter back is collision-free.
        widx = jnp.where(active, seg_start_idx, jnp.int32(B))
        cur_packed = cur_packed.at[widx].set(
            pack_reg(new_reg, jnp.zeros_like(fresh)), mode="drop")
        outs = WindowOutput(*jax.tree.map(
            lambda o, r: jnp.where(active, r, o), outs, resp
        ))
        return p + 1, cur_packed, outs

    def round_cond(carry):
        p = carry[0]
        return p <= max_pos

    _, cur_packed, outs = lax.while_loop(
        round_cond, round_body, (jnp.int32(0), cur_packed, outs)
    )
    cur, _ = unpack_reg(cur_packed)

    outs = WindowOutput(*jax.tree.map(
        lambda a, o: jnp.where(seg_single, a, o), a_out, outs))

    # Uniform segments commit their closed-form state; replayed segments
    # commit the live register (one write per touched slot — the window's
    # net effect, like the mutex-serialized mutations).
    fin = _Reg(*jax.tree.map(
        lambda f, c: jnp.where(seg_uniform, f, c), ff_reg, cur))
    fin = _Reg(*jax.tree.map(
        lambda a, f: jnp.where(seg_single, a, f), a_reg, fin))
    return window_commit(state, prep, fin, outs)


def pack_outputs(out: WindowOutput, gout: WindowOutput) -> jax.Array:
    """Fuse both windows' responses into one i64[B+Bg, 4] array.

    Lane rows: the regular window's B lanes then the GLOBAL window's Bg
    lanes; columns (status, limit, remaining, reset_time).  One fused array
    means the host pays ONE device→host round trip per dispatch instead of
    eight — on a tunneled chip that round trip (~20ms) dominates the whole
    serving window, and even on PCIe it cuts per-window fixed costs.
    """
    o = jnp.stack(
        [out.status.astype(I64), out.limit, out.remaining, out.reset_time],
        axis=-1)
    g = jnp.stack(
        [gout.status.astype(I64), gout.limit, gout.remaining, gout.reset_time],
        axis=-1)
    return jnp.concatenate([o, g], axis=0)


def split_outputs(fused, lanes: int) -> tuple[WindowOutput, WindowOutput]:
    """Host-side inverse of pack_outputs over [..., B+Bg, 4] numpy buffers:
    returns (regular, GLOBAL) WindowOutputs as zero-copy views."""
    def unpack(a):
        return WindowOutput(
            status=a[..., 0], limit=a[..., 1],
            remaining=a[..., 2], reset_time=a[..., 3])
    return unpack(fused[..., :lanes, :]), unpack(fused[..., lanes:, :])


# ---- compact wire format -------------------------------------------------
# The host<->device transfer is the serving path's fixed cost per window (on
# a tunneled chip it IS the window cost; on PCIe it still bounds small-window
# latency).  Eligible windows (host-checked: 0 <= hits < 2^28,
# 0 <= limit < 2^31, 0 <= duration < 2^31-16) travel packed:
#
#   request  i64[B, 2]:
#     w0: bits 0..31 slot+1 (0 = padded lane), bit 32 is_init,
#         bit 33 algorithm, bits 34..61 hits
#     w1: bits 0..31 limit, bits 32..62 duration
#   response i64[B, 2]:
#     w0: bits 0..30 remaining, bit 31 status,
#         bits 32..63 reset_enc = 0 if reset_time == 0 else reset_time - now + 1
#     w1: the response's limit, raw — it is the STORED limit on hit paths
#         (a live bucket keeps its init-time config, algorithms.go:40-65), so
#         it can exceed the request-side range checks and can't be dropped or
#         packed.
#
# Windows that fail the range checks use the full WindowBatch/pack_outputs
# path, so the compact path is lossless: remaining <= stored limit and
# reset - now <= stored duration always, and the engine permanently drops to
# the full path the first time an out-of-range config enters the arena
# (RateLimitEngine._dispatch), so compact windows only ever read state whose
# stored configs passed the same checks.

COMPACT_MAX_HITS = 1 << 28
COMPACT_MAX_LIMIT = 1 << 31
COMPACT_MAX_DURATION = (1 << 31) - 16


def decode_batch(packed) -> WindowBatch:
    """Device-side decode of the compact request pair (see layout above)."""
    w0 = packed[..., 0]
    w1 = packed[..., 1]
    return WindowBatch(
        slot=(w0 & 0xFFFFFFFF).astype(I32) - 1,
        hits=(w0 >> 34) & (COMPACT_MAX_HITS - 1),
        limit=w1 & 0xFFFFFFFF,
        duration=(w1 >> 32) & 0x7FFFFFFF,
        algo=((w0 >> 33) & 1).astype(I32),
        is_init=((w0 >> 32) & 1).astype(jnp.bool_),
    )


def encode_batch_host(slot, hits, limit, duration, algo, is_init):
    """Host-side (numpy) encode into the compact request pair.

    Caller must have verified the COMPACT_MAX_* ranges; padded lanes
    (slot == PAD_SLOT) encode to w0 == 0 regardless of other fields."""
    import numpy as np

    pad = slot < 0
    w0 = ((slot.astype(np.int64) + 1)
          | (is_init.astype(np.int64) << 32)
          | (algo.astype(np.int64) << 33)
          | (hits << 34))
    w0 = np.where(pad, 0, w0)
    w1 = limit | (duration << 32)
    return np.stack([w0, w1], axis=-1)


def encode_output_word(out: WindowOutput, now) -> jax.Array:
    """Device-side encode of (status, remaining, reset_time) into one i64
    word per lane.  The response's limit travels separately: the serving
    pipeline echoes the REQUEST limit host-side and fetches the device's
    limit plane only when a window's stored-vs-request mismatch flag fires
    (see engine._compiled_pipeline_step) — on hit paths the two differ only
    when a live bucket's config was changed mid-stream."""
    reset_enc = jnp.where(
        out.reset_time == 0,
        jnp.int64(0),
        jnp.clip(out.reset_time - now, 0, (1 << 31) - 2) + 1,
    )
    return ((reset_enc << 32)
            | (out.status.astype(I64) << 31)
            | jnp.clip(out.remaining, 0, (1 << 31) - 1))


def encode_output_compact(out: WindowOutput, now) -> jax.Array:
    """Device-side encode of responses into i64[B, 2] (packed word, limit)."""
    return jnp.stack([encode_output_word(out, now), out.limit], axis=-1)


def decode_output_host(packed, now) -> WindowOutput:
    """Host-side (numpy) decode of the compact response pair."""
    import numpy as np

    word = packed[..., 0]
    enc = (word >> 32) & 0xFFFFFFFF
    return WindowOutput(
        status=(word >> 31) & 1,
        limit=packed[..., 1],
        remaining=word & 0x7FFFFFFF,
        reset_time=np.where(enc == 0, 0, now + enc - 1),
    )


def global_read(state: BucketState, batch: WindowBatch, now) -> WindowOutput:
    """Answer GLOBAL-behavior requests from the local replica without mutating it.

    Mirrors the non-owner fast path (gubernator.go:173-195): a cached entry is
    returned as-is (hits are NOT applied locally — they reconcile via the
    window psum, see global_apply); a miss is answered as-if-initialized
    (the reference bootstraps its local cache the same way, :189-193 — since
    reads never decrement, recomputing limit-hits each time is
    response-identical while keeping replicas bit-exact across shards).
    """
    C = state.limit.shape[0]
    now = jnp.asarray(now, dtype=I64)
    g = jnp.clip(batch.slot, 0, C - 1)
    reg = _Reg(
        limit=state.limit[g],
        duration=state.duration[g],
        remaining=state.remaining[g],
        tstamp=state.tstamp[g],
        expire=state.expire[g],
        algo=state.algo[g],
    )
    fresh = batch.is_init | (reg.expire < now) | (batch.algo != reg.algo)
    # A cached read is the hit path with hits=0 (the cached status the owner
    # would broadcast, global.go:199-203 → getRateLimit with Hits cleared);
    # a miss is the init path with the request's hits.
    read_hits = jnp.where(fresh, batch.hits, jnp.int64(0))
    _, out = transition(reg, read_hits, batch.limit, batch.duration, batch.algo, now, fresh)
    return out


def global_accumulate(delta: jax.Array, batch: WindowBatch) -> jax.Array:
    """Scatter-add this shard's GLOBAL hits into the per-slot delta array.

    The device-side analog of the reference's hit aggregation map
    (global.go:81-86: `hits[key].Hits += r.Hits`).
    """
    idx = jnp.where(batch.slot >= 0, batch.slot, delta.shape[0])
    return delta.at[idx].add(batch.hits, mode="drop")


class GlobalConfig(NamedTuple):
    """Replicated per-slot config for GLOBAL limits (host-written at allocation).

    The aggregate-apply step needs limit/duration/algorithm per slot; the
    reference carries these on the queued RateLimitReq it sends to the owner
    (global.go:115-153) — here they are resident device state.
    """

    limit: jax.Array  # i64[G]
    duration: jax.Array  # i64[G]
    algo: jax.Array  # i32[G]

    @classmethod
    def zeros(cls, capacity: int) -> "GlobalConfig":
        return cls(
            limit=jnp.zeros((capacity,), I64),
            duration=jnp.zeros((capacity,), I64),
            algo=jnp.zeros((capacity,), I32),
        )


def global_apply(state: BucketState, cfg: GlobalConfig, summed_hits: jax.Array, now
                 ) -> BucketState:
    """Apply psum'd GLOBAL hit totals to the replicated arena.

    Every shard runs this on identical inputs (summed_hits is the psum over
    the mesh axis), so replicas stay bit-exact — this one collective replaces
    both the async hit send (global.go:115-156) and the owner's status
    broadcast (global.go:193-232): after it runs, the authoritative state is
    already resident on every shard.

    Matches the owner's application of the aggregated request: the reference
    sums hits per key and applies the sum as one request through the normal
    algorithm (global.go:81-86 → gubernator.go:218-226).
    """
    now = jnp.asarray(now, dtype=I64)
    reg = _Reg(
        limit=state.limit,
        duration=state.duration,
        remaining=state.remaining,
        tstamp=state.tstamp,
        expire=state.expire,
        algo=state.algo,
    )
    fresh = (reg.expire < now) | (cfg.algo != reg.algo)
    new_reg, _ = transition(reg, summed_hits, cfg.limit, cfg.duration, cfg.algo, now, fresh)
    touched = summed_hits != 0
    merged = jax.tree.map(lambda n, o: jnp.where(touched, n, o), new_reg, reg)
    return BucketState(*merged)
