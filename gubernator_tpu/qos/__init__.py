"""QoS subsystem: admission control, congestion-adaptive windows, fair
slotting, and peer-lane circuit breaking.

The serving path this protects (core/batcher.py -> core/pipeline.py ->
device) has a fixed short-term capacity: one drain in flight per fetch
slot, each drain carrying at most K windows of S*B lanes.  Nothing in the
seed bounded what piles up BEHIND that capacity — `_pending` grew without
limit, a slow peer stalled forwards behind one static timeout, and a hot
tenant could fill every device lane.  This package is the control layer:

  * AdmissionController (admission.py): bounded pending queue with
    deadline-aware load shedding.  Requests that cannot be served before
    their propagated client deadline are rejected IMMEDIATELY with an
    in-band OVER_LIMIT-style response carrying `shed_reason` metadata,
    instead of timing out silently in the queue.
  * CongestionController (congestion.py): AIMD on the EWMA of observed
    drain wall time adapts the effective window size and pipeline
    dispatch budget — the CONCUR result (arxiv 2601.22705): congestion-
    based concurrency control beats a static batch cliff for batched
    accelerator serving.
  * fair slotting (fairness.py): device windows fill round-robin across
    `name` (tenant) groups rather than FIFO, so one hot tenant cannot
    starve the rest of the window.
  * CircuitBreaker (breaker.py): per-peer closed/open/half-open breaker
    + jittered exponential backoff used by net/peers.py, with a
    configurable fail-open (answer locally, non-authoritative, flagged
    in metadata) or fail-closed fallback while a breaker is open.

Everything takes an injectable monotonic clock so the lockstep-style
deterministic tests (tests/test_qos.py) drive state machines without
sleeping.
"""

from __future__ import annotations

import time
from typing import Dict, Optional

from gubernator_tpu.config import QoSConfig
from gubernator_tpu.qos.admission import AdmissionController, shed_response
from gubernator_tpu.qos.breaker import CircuitBreaker
from gubernator_tpu.qos.congestion import CongestionController
from gubernator_tpu.qos.fairness import interleave_by_tenant

__all__ = [
    "AdmissionController",
    "CircuitBreaker",
    "CongestionController",
    "QoSManager",
    "interleave_by_tenant",
    "shed_response",
]


class QoSManager:
    """One QoS control plane per Instance: the congestion controller and
    admission controller are shared by the batcher and the pipeline (one
    pending-decision budget per node), and breakers are minted per peer
    as the membership ring changes (net/peers.py holds them)."""

    def __init__(self, conf: Optional[QoSConfig] = None, metrics=None,
                 now_fn=time.monotonic):
        self.conf = conf or QoSConfig()
        self.conf.validate()
        self.metrics = metrics
        self.now_fn = now_fn
        self.congestion = CongestionController(self.conf, now_fn=now_fn)
        self.admission = AdmissionController(self.conf, self.congestion,
                                             metrics=metrics, now_fn=now_fn)
        self.fair_slotting = self.conf.fair_slotting
        # per-host registry of the breakers minted below, so the failure
        # detector (net/health.py) can force-trip a confirmed-down peer's
        # breaker and force-close a recovered one (latest mint wins after
        # membership churn — the ring's live PeerClient holds that one)
        self.breakers: Dict[str, CircuitBreaker] = {}

    @property
    def fail_open(self) -> bool:
        return self.conf.fail_open

    def make_breaker(self, host: str) -> CircuitBreaker:
        """Per-peer breaker wired to the state gauge (metrics)."""
        on_change = None
        if self.metrics is not None:
            m = self.metrics
            on_change = lambda state, h=host: m.observe_breaker(h, state)  # noqa: E731
        breaker = CircuitBreaker(
            fail_threshold=self.conf.breaker_fail_threshold,
            open_duration=self.conf.breaker_open_duration,
            half_open_probes=self.conf.breaker_half_open_probes,
            now_fn=self.now_fn,
            on_state_change=on_change,
        )
        self.breakers[host] = breaker
        return breaker

    def deadline_from_timeout(self, timeout_s: Optional[float]
                              ) -> Optional[float]:
        """Absolute monotonic deadline from a relative client timeout,
        falling back to the configured default deadline (0 = none)."""
        if timeout_s is None or timeout_s <= 0 or timeout_s == float("inf"):
            if self.conf.default_deadline <= 0:
                return None
            timeout_s = self.conf.default_deadline
        return self.now_fn() + timeout_s
