"""Admission controller: bounded pending queue + deadline-aware shedding.

The batched serving lane (core/batcher.py `submit`) holds one admission
slot per pending decision from submit until its future resolves.  Two
shed conditions, both decided BEFORE the request queues:

  * queue_full — admitting would push the pending count past
    `max_pending`.  The bound is what prevents congestion collapse: under
    sustained overload the queue stays a couple of drain cycles deep and
    every admitted request still completes at full goodput, instead of
    every request queueing for seconds and timing out.
  * deadline — the caller's propagated deadline (gRPC deadline / HTTP
    timeout header) cannot be met even if admitted: estimated wait is
    `(pending / cwnd + 1)` drain cycles at the congestion controller's
    EWMA cycle time.  Rejecting now turns a guaranteed client-side
    timeout into an immediate, attributable answer.

Sheds are IN-BAND: an OVER_LIMIT-style RateLimitResp with
`metadata["shed_reason"]`, mirroring the reference's graceful-degradation
requirement for distributed limiters (arxiv 2602.11741) — a limiter that
errors under overload just moves the outage one layer up.
"""

from __future__ import annotations

import time
from typing import Optional

from gubernator_tpu.api.types import RateLimitReq, RateLimitResp, Status

# canonical shed_reason values (tests and dashboards match on these)
SHED_QUEUE_FULL = "queue_full"
SHED_DEADLINE = "deadline"
SHED_BREAKER_OPEN = "breaker_open"
SHED_DRAINING = "draining"
# frontdoor-only (frontdoor.py): every slab of the worker's shm ring is
# in flight, so the worker sheds in-band without a cross-process
# round-trip — the CONCUR-style frontend/backend coupling signal.
SHED_RING_FULL = "ring_full"


def shed_response(req: RateLimitReq, reason: str) -> RateLimitResp:
    """In-band shed: OVER_LIMIT-shaped so naive clients back off, with
    metadata telling honest ones this was load shedding, not their
    configured limit ("shed": marker, "shed_reason": why)."""
    return RateLimitResp(
        status=Status.OVER_LIMIT,
        limit=req.limit,
        remaining=0,
        reset_time=0,
        metadata={"shed": "true", "shed_reason": reason},
    )


class AdmissionController:
    def __init__(self, conf, congestion, metrics=None, now_fn=time.monotonic):
        self.max_pending = conf.max_pending
        self.congestion = congestion
        self.metrics = metrics
        self.now_fn = now_fn
        self.pending = 0
        self.pending_peak = 0
        # Windows currently in flight through the overlapped drain
        # pipeline (host-encoded or dispatched, not yet committed) —
        # updated by core/pipeline.py at every in-flight transition.
        self.inflight_windows = 0
        self.shed_counts: dict = {}
        # Set during graceful departure (daemon.py stop()): new work is
        # shed in-band with reason `draining` while already-admitted
        # decisions keep their slots and drain normally.
        self.draining = False

    # ----------------------------------------------------------- accounting

    def try_admit(self, n: int = 1,
                  deadline: Optional[float] = None) -> Optional[str]:
        """Admit `n` decisions or return the shed reason.  On admission the
        caller OWNS the slots and must `release(n)` when the decisions
        resolve (success or failure)."""
        if self.draining:
            return self._shed(SHED_DRAINING, n)
        if self.max_pending > 0 and self.pending + n > self.max_pending:
            return self._shed(SHED_QUEUE_FULL, n)
        if deadline is not None:
            remaining = deadline - self.now_fn()
            if remaining <= 0 or self.estimate_wait() > remaining:
                return self._shed(SHED_DEADLINE, n)
        self.pending += n
        if self.pending > self.pending_peak:
            self.pending_peak = self.pending
        return None

    def release(self, n: int = 1) -> None:
        self.pending -= n
        if self.pending < 0:  # defensive: never let accounting go negative
            self.pending = 0

    def note_inflight(self, windows: int) -> None:
        """Pipeline depth signal: how many drain windows are currently in
        flight.  Folded into the wait estimate — work ahead of a new
        request includes windows already encoded/dispatched, not just the
        pending queue."""
        self.inflight_windows = max(0, int(windows))

    # ----------------------------------------------------------- estimates

    def estimate_wait(self) -> float:
        """Queue-theoretic wait bound: cycles to drain what's ahead plus
        the request's own drain, at the congestion EWMA cycle time."""
        cw = max(self.congestion.effective_window(), 1)
        cycles = self.pending / cw + 1.0 + self.inflight_windows
        return cycles * self.congestion.drain_cycle_estimate()

    @property
    def saturated(self) -> bool:
        """The bounded queue is at (or past) its cap — health checks
        report degraded, and the server bypasses the native RPC lane so
        per-item sheds carry their reason in-band."""
        return self.max_pending > 0 and self.pending >= self.max_pending

    def close_intake(self) -> None:
        """Graceful-departure phase 1: stop admitting, keep draining."""
        self.draining = True

    def open_intake(self) -> None:
        self.draining = False

    def record_shed(self, reason: str, n: int = 1) -> str:
        """Account a shed decided OUTSIDE try_admit (e.g. fail-closed
        forwards while a peer's breaker is open, core/service.py)."""
        return self._shed(reason, n)

    def _shed(self, reason: str, n: int) -> str:
        self.shed_counts[reason] = self.shed_counts.get(reason, 0) + n
        if self.metrics is not None:
            self.metrics.observe_shed(reason, n)
        return reason
