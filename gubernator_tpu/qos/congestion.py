"""AIMD congestion controller over observed drain latency.

Replaces the static `batch_limit=1000` cliff with a congestion window
(`cwnd`, in DECISIONS per dispatch) adapted the way TCP adapts to RTT
inflation — the CONCUR structure (arxiv 2601.22705) specialized to the
one-engine-thread drain: the observed signal is the wall time of a whole
drain cycle (dispatch + fetch), the EWMA of which inflates as soon as the
device or the fetch link saturates.

  * below target latency: additive increase (`cwnd += increase`) per
    observation — probe for more batching, which on this hardware is
    nearly free until the transfer link saturates;
  * above target latency: multiplicative decrease (`cwnd *= decrease`),
    at most once per cooldown window (one "RTT": the larger of the EWMA
    and the target), so a burst of stale in-flight drains completing
    late doesn't collapse the window to the floor in one tick.

The controller never gates correctness — it only decides how much pending
work each dispatch takes (core/batcher.py window fill, core/pipeline.py
per-drain budget and in-flight depth) and feeds the admission
controller's wait estimate.
"""

from __future__ import annotations

import time


class CongestionController:
    def __init__(self, conf, now_fn=time.monotonic):
        self.min_window = conf.min_window
        self.max_window = conf.max_window
        self.target_latency = conf.target_drain_latency
        self.increase = conf.aimd_increase
        self.decrease = conf.aimd_decrease
        self.alpha = conf.latency_ewma_alpha
        self.now_fn = now_fn
        self._cwnd = float(conf.max_window)
        self.latency_ewma = 0.0
        self.depth_ewma = 0.0
        self._observed = False
        self._last_decrease = float("-inf")
        # telemetry for tests/metrics
        self.decreases = 0
        self.increases = 0
        # stage-boundary EWMAs (overlapped pipeline): host encode, device
        # dispatch, fetch+decode — fed per drain by the pipeline's
        # completion path.  When drains overlap, the cycle cadence is the
        # BOTTLENECK stage, not the stage sum.
        self.stage_ewma = {"host_encode": 0.0, "device_dispatch": 0.0,
                           "fetch_decode": 0.0}
        self._stages_observed = False
        self._pipelined = False
        # deferred-fetch chain stride (core/pipeline.py): how many drains
        # ride one stacked D2H fetch.  Same AIMD shape as cwnd but a
        # SEPARATE state variable: stride trades per-drain latency for
        # fetch amortization, so it grows only while backlog is deep AND
        # latency still holds, and collapses toward 1 the moment either
        # signal flips.
        self._stride = 1.0
        self.stride_increases = 0
        self.stride_decreases = 0

    # ------------------------------------------------------------- signal

    def observe_drain(self, wall_seconds: float, depth: int = 1) -> None:
        """Feed one completed drain cycle (engine dispatch through fetch).
        `depth` is the occupied window depth K of the drain (EWMA'd for
        the metrics surface and the wait estimator).

        `wall_seconds` is the pipeline's traced drain boundary
        (started→fetch_done, core/pipeline.py _on_completed) — the SAME
        value observed into guber_tpu_window_duration_* and the stage
        timeline, so the controller and the dashboards read one clock."""
        a = self.alpha
        if not self._observed:
            self.latency_ewma = wall_seconds
            self.depth_ewma = float(depth)
            self._observed = True
        else:
            self.latency_ewma += a * (wall_seconds - self.latency_ewma)
            self.depth_ewma += a * (depth - self.depth_ewma)
        if self.latency_ewma > self.target_latency:
            now = self.now_fn()
            cooldown = max(self.latency_ewma, self.target_latency)
            if now - self._last_decrease >= cooldown:
                self._cwnd = max(float(self.min_window),
                                 self._cwnd * self.decrease)
                self._last_decrease = now
                self.decreases += 1
        else:
            if self._cwnd < self.max_window:
                self._cwnd = min(float(self.max_window),
                                 self._cwnd + self.increase)
                self.increases += 1

    def observe_stages(self, host: float, device: float, fetch: float,
                       pipelined: bool = True) -> None:
        """Feed one drain's stage-boundary decomposition: host encode
        (columnar pack), device dispatch (enqueue through device done) and
        fetch+decode.  With overlap enabled the steady-state cadence is
        bounded by max(stage), not the sum — drain_cycle_estimate()
        switches to that bound once stage data exists."""
        a = self.alpha
        obs = {"host_encode": host, "device_dispatch": device,
               "fetch_decode": fetch}
        if not self._stages_observed:
            self.stage_ewma.update(obs)
            self._stages_observed = True
        else:
            for k, v in obs.items():
                self.stage_ewma[k] += a * (v - self.stage_ewma[k])
        self._pipelined = bool(pipelined)

    def observe_chain(self, backlog_windows: float, cap: int) -> None:
        """Adapt the deferred-fetch stride from one chain flush: additive
        increase while at least one more window's worth of work is queued
        behind the chain and drain latency holds under target; otherwise
        multiplicative decrease toward 1 (fetch every drain — no added
        latency under light load).  `cap` is the pipeline's configured
        GUBER_FETCH_STRIDE_MAX ceiling."""
        if backlog_windows >= 1.0 and not self.congested:
            if self._stride < cap:
                # unit additive step (NOT aimd_increase, which is sized in
                # decisions-per-window units): stride is a small integer,
                # so probing one extra chained drain per flush is the
                # gentlest useful growth
                self._stride = min(float(cap), self._stride + 1.0)
                self.stride_increases += 1
        elif self._stride > 1.0:
            self._stride = max(1.0, self._stride * self.decrease)
            self.stride_decreases += 1

    # ------------------------------------------------------------- policy

    def effective_window(self) -> int:
        """Decisions one dispatch should take (window fill / drain budget)."""
        return max(self.min_window, int(self._cwnd))

    def effective_depth(self, max_depth: int) -> int:
        """In-flight drain cap scaled with the congestion window: at full
        cwnd the pipeline keeps its configured depth; as AIMD backs off,
        fewer drains ride concurrently (dispatch cadence slows with the
        same control signal)."""
        if self.max_window <= 0:
            return max_depth
        frac = self._cwnd / float(self.max_window)
        return max(1, min(max_depth, round(max_depth * frac)))

    def effective_stride(self) -> int:
        """Drains per stacked fetch the chain should currently target."""
        return max(1, int(self._stride))

    def stride_bound(self, latency_budget: float) -> int:
        """Admission-deadline cap on the chain depth: the oldest chained
        drain waits ~(stride-1) dispatch cadences plus the shared fetch
        before it commits, so the deepest stride whose head still meets
        `latency_budget` (seconds) is (budget - t_fetch) / t_exec at the
        observed stage EWMAs.  Unbounded (a huge int) while the budget is
        unset or the stages are unobserved — a fresh node has no evidence
        to cap on, and the configured GUBER_FETCH_STRIDE_MAX still rules."""
        if latency_budget <= 0 or not self._stages_observed:
            return 1 << 30
        exec_s = max(self.stage_ewma["device_dispatch"], 1e-6)
        fetch_s = self.stage_ewma["fetch_decode"]
        return max(1, int((latency_budget - fetch_s) / exec_s))

    def drain_cycle_estimate(self) -> float:
        """Expected wall time of one drain cycle, for the admission wait
        estimator.  Before any observation the target is the prior — a
        fresh node must not promise instant service to a 1ms deadline."""
        if not self._observed:
            return self.target_latency
        if self._pipelined and self._stages_observed:
            # Overlapped drains: cycles complete at the bottleneck stage's
            # cadence (BASELINE.md cost model — bound is max, not sum).
            return max(max(self.stage_ewma.values()), 1e-6)
        return max(self.latency_ewma, 1e-6)

    @property
    def congested(self) -> bool:
        return self._observed and self.latency_ewma > self.target_latency
