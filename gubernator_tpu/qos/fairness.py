"""Per-tenant weighted fair slotting for device windows.

A device window has a fixed number of lanes; filling it FIFO means one
hot tenant's burst occupies every lane and everyone else waits a full
window cycle per burst.  `interleave_by_tenant` reorders a pending list
round-robin across `name` (tenant) groups — stable WITHIN each tenant, so
per-key sequential semantics are untouched (two requests for the same key
share a tenant and keep their relative order; reordering across different
keys is always commutative for the engine).

Weighted: a tenant's integer weight (default 1) is how many slots it
takes per round-robin pass, so operators can deliberately favor a tenant
without letting it starve the rest.
"""

from __future__ import annotations

from typing import Callable, List, Optional, Sequence, TypeVar

T = TypeVar("T")


def tenant_of(req) -> str:
    """Canonical tenant identity of one request: the rate-limit `name`
    (the reference's metric/limit family; `unique_key` is the principal
    WITHIN a tenant).  The fair-slotting call sites and the traffic
    analytics' per-tenant accounting both key on THIS, so "tenant" means
    the same thing in the scheduler and on the dashboard."""
    return req.name or "default"


def interleave_by_tenant(
    items: Sequence[T],
    tenant_of: Callable[[T], str],
    weight_of: Optional[Callable[[str], int]] = None,
) -> List[T]:
    """Round-robin interleave across tenant groups (first-seen tenant
    order), stable within each group.  Single-tenant input returns the
    original order unchanged (and unallocated)."""
    groups: dict = {}
    order: List[str] = []
    for it in items:
        t = tenant_of(it)
        g = groups.get(t)
        if g is None:
            groups[t] = g = []
            order.append(t)
        g.append(it)
    if len(order) <= 1:
        return list(items)
    cursors = {t: 0 for t in order}
    weights = {t: max(1, int(weight_of(t))) if weight_of else 1
               for t in order}
    out: List[T] = []
    remaining = len(items)
    while remaining:
        for t in order:
            g = groups[t]
            i = cursors[t]
            take = min(weights[t], len(g) - i)
            if take <= 0:
                continue
            out.extend(g[i:i + take])
            cursors[t] = i + take
            remaining -= take
    return out
