"""Per-peer circuit breaker + jittered exponential backoff schedule.

The classic three-state machine guarding the cross-host forward lane
(net/peers.py):

  closed    — normal serving; `fail_threshold` CONSECUTIVE transport
              failures trip it open (a success resets the streak);
  open      — every call rejected locally for `open_duration` seconds
              (no connection attempt: a dead peer must not cost every
              forward a full timeout);
  half_open — after the open window, at most `half_open_probes`
              outstanding trial calls are let through; one success closes
              the breaker, one failure re-opens it for a fresh window.

The clock is injectable so tests drive open->half_open->closed without
sleeping.  What happens to traffic while the breaker is open (fail-open:
answer locally, non-authoritative; fail-closed: in-band shed) is the
service's decision (core/service.py), not the breaker's.
"""

from __future__ import annotations

import random
import time
from typing import Callable, Iterator, Optional

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half_open"


class CircuitBreaker:
    def __init__(self, fail_threshold: int = 5, open_duration: float = 2.0,
                 half_open_probes: int = 1, now_fn=time.monotonic,
                 on_state_change: Optional[Callable[[str], None]] = None):
        self.fail_threshold = max(1, fail_threshold)
        self.open_duration = open_duration
        self.half_open_probes = max(1, half_open_probes)
        self.now_fn = now_fn
        self.on_state_change = on_state_change
        self.state = CLOSED
        self._failures = 0
        self._opened_at = 0.0
        self._probes_in_flight = 0

    def _set_state(self, state: str) -> None:
        if state != self.state:
            self.state = state
            if self.on_state_change is not None:
                self.on_state_change(state)

    # ------------------------------------------------------------- gate

    def allow(self) -> bool:
        """May a call proceed right now?  A True from the half-open state
        consumes a probe slot — the caller MUST follow up with
        record_success() or record_failure()."""
        if self.state == OPEN:
            if self.now_fn() - self._opened_at >= self.open_duration:
                self._set_state(HALF_OPEN)
                self._probes_in_flight = 0
            else:
                return False
        if self.state == HALF_OPEN:
            if self._probes_in_flight >= self.half_open_probes:
                return False
            self._probes_in_flight += 1
            return True
        return True

    # ------------------------------------------------------------- outcomes

    def record_success(self) -> None:
        if self.state == HALF_OPEN:
            self._probes_in_flight = max(0, self._probes_in_flight - 1)
            self._set_state(CLOSED)
        self._failures = 0

    def record_failure(self) -> None:
        if self.state == HALF_OPEN:
            self._probes_in_flight = max(0, self._probes_in_flight - 1)
            self._trip()
            return
        self._failures += 1
        if self.state == CLOSED and self._failures >= self.fail_threshold:
            self._trip()

    def _trip(self) -> None:
        self._opened_at = self.now_fn()
        self._failures = 0
        self._set_state(OPEN)

    # ------------------------------------------------- external authority

    def trip(self) -> None:
        """Force-open: the failure detector (net/health.py) confirmed this
        peer DOWN out-of-band, so stop burning forward-latency on probes
        the detector already knows will fail.  The normal open→half_open
        clockwork still applies, so the breaker recovers on its own even
        if the detector is later disabled."""
        self._trip()

    def reset(self) -> None:
        """Force-closed: the detector confirmed the peer healthy again
        (its recover_after hysteresis already debounced flapping)."""
        self._failures = 0
        self._probes_in_flight = 0
        self._set_state(CLOSED)


def backoff_delays(retries: int, base: float, cap: float,
                   rng: Optional[random.Random] = None) -> Iterator[float]:
    """Jittered exponential backoff: delay i is uniform in
    (0, min(cap, base * 2**i)] — full jitter, the variant that
    decorrelates a herd of retriers hitting the same recovering peer."""
    r = rng.random if rng is not None else random.random
    for i in range(retries):
        yield min(cap, base * (2.0 ** i)) * max(r(), 1e-3)
