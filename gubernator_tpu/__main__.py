from gubernator_tpu.daemon import main

main()
