"""Standalone dev cluster: six nodes on fixed ports, prints "Ready".

Equivalent of the reference's cmd/gubernator-cluster (main.go:29-55), used
by client development and the Python client tests (which wait for the
"Ready" line, python/tests/test_client.py:24-38 in the reference).

Run: python -m gubernator_tpu.cmd.cluster_main
"""

from __future__ import annotations

import asyncio

from gubernator_tpu import cluster as cluster_mod

ADDRESSES = [f"127.0.0.1:{port}" for port in range(9090, 9096)]


async def _amain() -> None:
    from gubernator_tpu.daemon import apply_platform_env
    apply_platform_env()
    c = await cluster_mod.start_with(ADDRESSES)
    print("Ready", flush=True)
    try:
        await asyncio.Event().wait()
    finally:
        await c.stop()


def main() -> None:
    try:
        asyncio.run(_amain())
    except KeyboardInterrupt:
        pass


if __name__ == "__main__":
    main()
