"""Operator CLI: load generation + state-lifecycle admin commands.

Load generation is the reference's cmd/gubernator-cli (main.go:42-85):
generate 2000 random rate-limit configs, hit them forever with concurrency
10, print any OVER_LIMIT responses.

The snapshot/restore subcommands drive the daemon's HTTP admin plane
(api/http_gateway.py), moving the versioned, checksummed snapshot blob
(state/snapshot.py) as-is:

  python -m gubernator_tpu.cmd.cli load [address]            # default
  python -m gubernator_tpu.cmd.cli snapshot <http-addr> -o arena.snap
  python -m gubernator_tpu.cmd.cli restore  <http-addr> arena.snap
                                            [--rebase-to-now]
  python -m gubernator_tpu.cmd.cli debug    <http-addr>      # introspection
  python -m gubernator_tpu.cmd.cli top      <http-addr> [--watch N]
  python -m gubernator_tpu.cmd.cli slo      <http-addr> [--watch N]
  python -m gubernator_tpu.cmd.cli kernels  <http-addr> [--measure]

`debug` pretty-prints the daemon's /v1/admin/debug snapshot (arena
occupancy, admission queue, breaker states, congestion window, per-stage
latency quantiles, recent traces).  `load --http-address` prints the same
per-stage p50/p95/p99 table every 10 rounds while hammering.  `top` is
the hot-key live view backed by /v1/admin/topk (device count-min sketch +
candidate top-K, observability/analytics.py); `slo` renders the
multi-window burn rates of the SLO engine.  Both take `--watch SECONDS`
to refresh in place.

For compatibility, a bare address (no subcommand) runs load generation.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import random
import sys
import urllib.request

from gubernator_tpu.api.types import Algorithm, RateLimitReq, Second, Status


def _fetch_debug(http_address: str, timeout: float = 5.0) -> dict:
    url = f"{_http_base(http_address)}/v1/admin/debug"
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return json.loads(resp.read().decode("utf-8"))


def _print_stage_table(stages: dict) -> None:
    if not stages:
        print("stages: (no samples yet)")
        return
    print(f"{'stage':<18}{'count':>8}{'p50 ms':>10}{'p95 ms':>10}"
          f"{'p99 ms':>10}")
    for name, s in stages.items():
        print(f"{name:<18}{s['count']:>8}{s['p50_ms']:>10.3f}"
              f"{s['p95_ms']:>10.3f}{s['p99_ms']:>10.3f}")


async def _load(address: str, count: int, concurrency: int,
                http_address: str = "") -> None:
    from gubernator_tpu.client import AsyncClient, random_string
    client = AsyncClient(address)
    reqs = [
        RateLimitReq(
            name=random_string("ID-", 6),
            unique_key=random_string("ID-", 10),
            hits=1,
            limit=random.randint(1, 10),
            duration=random.randint(1, 10) * Second,
            algorithm=Algorithm.TOKEN_BUCKET,
        )
        for _ in range(count)
    ]
    sem = asyncio.Semaphore(concurrency)
    # distinguish real OVER_LIMITs from QoS load shedding (the daemon
    # answers sheds in-band with metadata.shed_reason, qos/admission.py)
    stats = {"served": 0, "over_limit": 0}

    async def hit(req: RateLimitReq) -> None:
        async with sem:
            resps = await client.get_rate_limits([req], timeout=0.5)
            r = resps[0]
            reason = (r.metadata or {}).get("shed_reason")
            if reason is not None:
                stats[f"shed:{reason}"] = stats.get(f"shed:{reason}", 0) + 1
            elif r.status == Status.OVER_LIMIT:
                stats["over_limit"] += 1
                print(r)
            else:
                stats["served"] += 1

    rounds = 0
    while True:
        await asyncio.gather(*(hit(r) for r in reqs))
        rounds += 1
        if rounds % 10 == 0:
            print("totals:", " ".join(
                f"{k}={v}" for k, v in sorted(stats.items())))
            if http_address:
                # per-stage serving latency from the daemon's debug
                # snapshot — where the round's time actually went
                try:
                    snap = await asyncio.to_thread(_fetch_debug,
                                                   http_address)
                    _print_stage_table(snap.get("stages", {}))
                except Exception as e:
                    print(f"(stage snapshot unavailable: {e})",
                          file=sys.stderr)


def _http_base(address: str) -> str:
    return address if "://" in address else f"http://{address}"


def cmd_snapshot(args) -> int:
    url = f"{_http_base(args.address)}/v1/admin/snapshot?layout={args.layout}"
    with urllib.request.urlopen(url, timeout=args.timeout) as resp:
        data = resp.read()
    with open(args.output, "wb") as f:
        f.write(data)
    print(f"wrote {len(data)} bytes to {args.output}")
    return 0


def cmd_restore(args) -> int:
    with open(args.file, "rb") as f:
        data = f.read()
    url = f"{_http_base(args.address)}/v1/admin/restore"
    if args.rebase_to_now:
        from gubernator_tpu.api.types import millisecond_now
        url += f"?rebase_to={millisecond_now()}"
    req = urllib.request.Request(
        url, data=data, method="POST",
        headers={"Content-Type": "application/octet-stream"})
    try:
        with urllib.request.urlopen(req, timeout=args.timeout) as resp:
            body = json.loads(resp.read().decode("utf-8"))
    except urllib.error.HTTPError as e:
        print(f"restore rejected: {e.read().decode('utf-8', 'replace')}",
              file=sys.stderr)
        return 1
    print(f"restored {body.get('restoredKeys', 0)} keys")
    return 0


def cmd_debug(args) -> int:
    try:
        snap = _fetch_debug(args.address, timeout=args.timeout)
    except Exception as e:
        print(f"debug fetch failed: {e}", file=sys.stderr)
        return 1
    eng = snap.get("engine", {})
    print(f"node {snap.get('address')} mesh_mode={snap.get('mesh_mode')} "
          f"standalone={snap.get('standalone')}")
    if eng:
        print("engine:", " ".join(f"{k}={v}" for k, v in sorted(eng.items())))
        # arena pressure in one line: the live/expired/free slot breakdown
        # next to capacity, so "is the arena full of dead weight?" needs
        # no mental arithmetic
        cap = eng.get("capacity") or 1
        print(f"arena: {eng.get('live', 0)} live / "
              f"{eng.get('expired', 0)} expired / {eng.get('free', 0)} free "
              f"of {cap} slots ({100.0 * eng.get('live', 0) / cap:.1f}% live)")
    adm = snap.get("admission")
    if adm:
        print(f"admission: pending={adm['pending']} "
              f"peak={adm['pending_peak']}/{adm['max_pending']} "
              f"saturated={adm['saturated']} sheds={adm['shed_counts']}")
    cong = snap.get("congestion")
    if cong:
        print(f"congestion: window={cong['effective_window']} "
              f"latency_ewma_ms={cong['latency_ewma_ms']:.2f} "
              f"congested={cong['congested']} "
              f"+{cong['increases']}/-{cong['decreases']}")
    for peer in snap.get("peers", []):
        print(f"peer {peer['host']}: breaker={peer['breaker']}"
              f"{' (self)' if peer['is_owner'] else ''}")
    health = snap.get("health")
    if health:
        for host, st in sorted(health.get("peers", {}).items()):
            print(f"health {host}: {st['state']} "
                  f"fail_streak={st['fail_streak']} "
                  f"probes={st['probes']} failures={st['failures']}")
    gs = snap.get("global_sync")
    if gs:
        hints = gs.get("hints", {})
        print(f"global_sync: send_errors={gs['send_errors']} "
              f"broadcast_errors={gs['broadcast_errors']}")
        print(f"hints: pending={hints.get('pending', {})} "
              f"queued={hints.get('queued_total', {})} "
              f"replayed={hints.get('replayed_total', {})} "
              f"expired={hints.get('expired_total', {})}")
    fd = snap.get("frontdoor")
    if fd:
        print(f"frontdoor: workers={fd['workers']} "
              f"mode={fd.get('port_mode')} address={fd.get('address')} "
              f"restarts={fd.get('restarts', 0)} "
              f"records_served={fd.get('records_served', 0)}")
        for i, row in enumerate(fd.get("per_worker", [])):
            print(f"  worker {i}: pid={row.get('pid')} "
                  f"port={row.get('port')} epoch={row.get('epoch')} "
                  f"restarts={row.get('restarts')} rpcs={row.get('rpcs')} "
                  f"sheds={row.get('sheds')} stalls={row.get('stalls')} "
                  f"ring_depth={row.get('ring_depth')} "
                  f"inflight={row.get('inflight')}")
    faults = snap.get("faults")
    if faults:
        print(f"faults ACTIVE: {faults}")
    pipe = snap.get("pipeline")
    if pipe:
        print("pipeline:", " ".join(
            f"{k}={v}" for k, v in sorted(pipe.items())))
    an = snap.get("analytics")
    if an:
        tot = an.get("totals", {})
        occ = an.get("occupancy", {})
        print(f"analytics: decisions={tot.get('decisions', 0)} "
              f"over_limit={tot.get('over_limit', 0)} "
              f"inits={tot.get('inits', 0)} "
              f"device_occupancy={occ.get('live', 0)} live/"
              f"{occ.get('expired', 0)} expired")
    tiers = snap.get("tiers")
    if tiers:
        print(f"tiers: warm={tiers.get('warm_rows', 0)}/"
              f"{tiers.get('warm_capacity', 0)} rows "
              f"({tiers.get('warm_layout')}, {tiers.get('warm_bytes', 0)}B) "
              f"promote={tiers.get('promotions', 0)} "
              f"demote={tiers.get('demotions', 0)} "
              f"warm_hit={tiers.get('warm_hits', 0)} "
              f"cold_miss={tiers.get('cold_misses', 0)} "
              f"warm_evict={tiers.get('warm_evictions', 0)}")
    slo = snap.get("slo")
    if slo:
        for name, obj in sorted(slo.get("burn_rates", {}).items()):
            state = "FIRING" if obj.get("firing") else "ok"
            wins = " ".join(f"{w}={b}" for w, b in
                            sorted(obj.get("windows", {}).items()))
            print(f"slo {name}: {state} budget={obj.get('budget')} {wins}")
    _print_stage_table(snap.get("stages", {}))
    tracing = snap.get("tracing")
    if tracing:
        print(f"tracing: sample={tracing['sample']}")
        for t in tracing.get("recent_traces", []):
            print(f"  trace {t['trace_id'][:16]} root={t['root']} "
                  f"spans={t['spans']} {t['duration_ms']:.2f}ms "
                  f"slowest={t['slowest_span']} ({t['slowest_ms']:.2f}ms) "
                  f"nodes={','.join(t['nodes'])}")
    prof = snap.get("profile")
    if prof:
        print(f"profile: active={prof['active']} "
              f"remaining={prof['remaining']} dir={prof['dir'] or '-'}")
    if args.json:
        print(json.dumps(snap, indent=2))
    return 0


def _watch_loop(once, interval: float) -> int:
    """Run `once` every `interval` seconds until ^C (interval 0 = single
    shot).  The live-view plumbing shared by `top` and `slo`."""
    import time as _time
    if not interval:
        return once()
    try:
        while True:
            rc = once()
            if rc:
                return rc
            _time.sleep(interval)
            print()
    except KeyboardInterrupt:
        return 0


def cmd_top(args) -> int:
    """Hot-key live view from /v1/admin/topk (traffic analytics)."""
    def once() -> int:
        url = f"{_http_base(args.address)}/v1/admin/topk?n={args.n}"
        try:
            with urllib.request.urlopen(url, timeout=args.timeout) as resp:
                snap = json.loads(resp.read().decode("utf-8"))
        except urllib.error.HTTPError as e:
            print(f"topk fetch failed: "
                  f"{e.read().decode('utf-8', 'replace')}", file=sys.stderr)
            return 1
        except Exception as e:
            print(f"topk fetch failed: {e}", file=sys.stderr)
            return 1
        tot = snap.get("totals", {})
        occ = snap.get("occupancy", {})
        print(f"decisions={tot.get('decisions', 0)} "
              f"hits={tot.get('hits', 0)} "
              f"over_limit={tot.get('over_limit', 0)} "
              f"inits={tot.get('inits', 0)} drains={tot.get('drains', 0)} "
              f"arena={occ.get('live', 0)} live/"
              f"{occ.get('expired', 0)} expired")
        rows = snap.get("topk", [])
        if not rows:
            print("(no hot keys yet)")
        else:
            print(f"{'score':>10}{'hits':>10}{'over':>8}  key")
            for r in rows:
                print(f"{r['score']:>10}{r['hits']:>10}{r['over']:>8}  "
                      f"{r['key']}")
        tenants = snap.get("tenants", {})
        if tenants:
            print("tenants:")
            for name, t in sorted(tenants.items(),
                                  key=lambda kv: -kv[1]["decisions"]):
                print(f"  {name}: decisions={t['decisions']} "
                      f"hits={t['hits']} over_limit={t['over_limit']}")
        return 0

    return _watch_loop(once, args.watch)


def cmd_slo(args) -> int:
    """SLO burn-rate live view from the debug snapshot's slo section."""
    def once() -> int:
        try:
            snap = _fetch_debug(args.address, timeout=args.timeout)
        except Exception as e:
            print(f"debug fetch failed: {e}", file=sys.stderr)
            return 1
        slo = snap.get("slo")
        if not slo:
            print("slo engine disabled (set GUBER_SLO=1)", file=sys.stderr)
            return 1
        obj = slo.get("objectives", {})
        print(f"objectives: drain_p99_ms={obj.get('drain_p99_ms')} "
              f"drain_budget={obj.get('drain_budget')} "
              f"shed_budget={obj.get('shed_budget')} "
              f"availability={obj.get('availability')}")
        wins = slo.get("burn_windows", [])
        print("windows: " + ", ".join(
            f"{w['window_s']:.0f}s>{w['threshold']}" for w in wins))
        for name, o in sorted(slo.get("burn_rates", {}).items()):
            state = "FIRING" if o.get("firing") else "ok"
            parts = " ".join(f"{w}={b}" for w, b in
                             sorted(o.get("windows", {}).items(),
                                    key=lambda kv: int(kv[0][:-1])))
            print(f"{name:<14}{state:<8}budget={o.get('budget'):<8} {parts}")
        return 0

    return _watch_loop(once, args.watch)


def cmd_kernels(args) -> int:
    """Census count × measured ms/window reconciliation table from
    /v1/admin/kernels (observability/devprof.py)."""
    def once() -> int:
        url = (f"{_http_base(args.address)}/v1/admin/kernels"
               f"?census={0 if args.no_census else 1}")
        if args.measure:
            url += f"&measure=1&iters={args.iters}"
        try:
            with urllib.request.urlopen(url, timeout=args.timeout) as resp:
                snap = json.loads(resp.read().decode("utf-8"))
        except urllib.error.HTTPError as e:
            print(f"kernels fetch failed: "
                  f"{e.read().decode('utf-8', 'replace')}", file=sys.stderr)
            return 1
        except Exception as e:
            print(f"kernels fetch failed: {e}", file=sys.stderr)
            return 1
        arms = snap.get("arms", {})
        print(f"{'arm':<22}{'census k/win':>14}{'measured ms/win':>18}")
        for arm, row in sorted(arms.items()):
            cen = row.get("census_kernels_per_window")
            ms = row.get("measured_ms_per_window")
            print(f"{arm:<22}"
                  f"{cen if cen is not None else '-':>14}"
                  f"{f'{ms:.4f}' if ms is not None else '-':>18}")
        clock = snap.get("clock")
        if clock:
            print("window clock:")
            for arm, c in sorted(clock.get("arms", {}).items()):
                print(f"  {arm}: ewma={c['ewma_ms']:.3f}ms "
                      f"count={c['count']}")
            for s in clock.get("slow_windows", []):
                ids = ",".join(s.get("trace_ids", [])) or "-"
                print(f"  slow {s['arm']}: {s['ms']}ms traces={ids}")
        rows = snap.get("table", [])
        if rows:
            print(f"{'kernel':<44}{'arm':<22}{'count':>8}{'ms/win':>10}")
            for r in rows[:args.n]:
                print(f"{r['kernel'][:43]:<44}{r['arm']:<22}"
                      f"{r['count']:>8}{r['ms_per_window']:>10.4f}")
        else:
            print("(kernel table empty — arm a capture, run `cli kernels "
                  "--measure`, or set GUBER_DEVPROF=periodic)")
        ctrl = snap.get("controller")
        if ctrl:
            print(f"continuous: interval={ctrl['interval_s']}s "
                  f"drains={ctrl['drains']} cycles={ctrl['cycles']} "
                  f"sheds={ctrl['sheds']} rows={ctrl['kernel_rows']}")
        return 0

    return _watch_loop(once, args.watch)


def main(argv=None) -> None:
    argv = list(sys.argv[1:] if argv is None else argv)
    # compatibility: a bare address (or nothing) runs load generation
    if not argv or argv[0] not in ("load", "snapshot", "restore", "debug",
                                   "top", "slo", "kernels"):
        argv.insert(0, "load")

    p = argparse.ArgumentParser("gubernator-tpu-cli")
    sub = p.add_subparsers(dest="cmd", required=True)

    pl = sub.add_parser("load", help="hammer random rate limits (default)")
    pl.add_argument("address", nargs="?", default="127.0.0.1:9090")
    pl.add_argument("--count", type=int, default=2000)
    pl.add_argument("--concurrency", type=int, default=10)
    pl.add_argument("--http-address", default="",
                    help="daemon HTTP address; when set, print per-stage "
                    "p50/p95/p99 from /v1/admin/debug every 10 rounds")

    ps = sub.add_parser("snapshot", help="pull a snapshot over HTTP admin")
    ps.add_argument("address", help="daemon HTTP address (host:port)")
    ps.add_argument("-o", "--output", default="arena.snap")
    ps.add_argument("--layout", choices=("auto", "int64", "compact32"),
                    default="auto")
    ps.add_argument("--timeout", type=float, default=30.0)

    pr = sub.add_parser("restore", help="push a snapshot over HTTP admin")
    pr.add_argument("address", help="daemon HTTP address (host:port)")
    pr.add_argument("file")
    pr.add_argument("--rebase-to-now", action="store_true",
                    help="shift all timestamps so buckets keep their "
                    "REMAINING lifetime instead of absolute expiry")
    pr.add_argument("--timeout", type=float, default=30.0)

    pd = sub.add_parser("debug", help="print the daemon's runtime "
                        "introspection snapshot")
    pd.add_argument("address", help="daemon HTTP address (host:port)")
    pd.add_argument("--json", action="store_true",
                    help="also dump the raw snapshot JSON")
    pd.add_argument("--timeout", type=float, default=5.0)

    pt = sub.add_parser("top", help="hot-key top-K live view "
                        "(traffic analytics)")
    pt.add_argument("address", help="daemon HTTP address (host:port)")
    pt.add_argument("-n", type=int, default=20,
                    help="number of hot keys to show")
    pt.add_argument("--watch", type=float, default=0.0, metavar="SECONDS",
                    help="refresh every SECONDS until ^C (0 = one shot)")
    pt.add_argument("--timeout", type=float, default=5.0)

    po = sub.add_parser("slo", help="SLO burn-rate live view")
    po.add_argument("address", help="daemon HTTP address (host:port)")
    po.add_argument("--watch", type=float, default=0.0, metavar="SECONDS",
                    help="refresh every SECONDS until ^C (0 = one shot)")
    po.add_argument("--timeout", type=float, default=5.0)

    pk = sub.add_parser("kernels", help="census × measured device-time "
                        "kernel table (devprof)")
    pk.add_argument("address", help="daemon HTTP address (host:port)")
    pk.add_argument("-n", type=int, default=20,
                    help="kernel-table rows to show")
    pk.add_argument("--measure", action="store_true",
                    help="run the arm-scoped measured probe inline "
                    "(seconds of compile on a cold daemon)")
    pk.add_argument("--iters", type=int, default=2,
                    help="measured-probe iterations per arm")
    pk.add_argument("--no-census", action="store_true",
                    help="skip the census column (faster on a cold daemon)")
    pk.add_argument("--watch", type=float, default=0.0, metavar="SECONDS",
                    help="refresh every SECONDS until ^C (0 = one shot)")
    pk.add_argument("--timeout", type=float, default=300.0)

    args = p.parse_args(argv)
    if args.cmd == "snapshot":
        sys.exit(cmd_snapshot(args))
    if args.cmd == "restore":
        sys.exit(cmd_restore(args))
    if args.cmd == "debug":
        sys.exit(cmd_debug(args))
    if args.cmd == "top":
        sys.exit(cmd_top(args))
    if args.cmd == "slo":
        sys.exit(cmd_slo(args))
    if args.cmd == "kernels":
        sys.exit(cmd_kernels(args))
    try:
        asyncio.run(_load(args.address, args.count, args.concurrency,
                          http_address=args.http_address))
    except KeyboardInterrupt:
        pass


if __name__ == "__main__":
    main()
