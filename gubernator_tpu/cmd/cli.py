"""Load-generation CLI: random token-bucket limits hammered in a loop.

Equivalent of the reference's cmd/gubernator-cli (main.go:42-85): generate
2000 random rate-limit configs, hit them forever with concurrency 10, print
any OVER_LIMIT responses.

Run: python -m gubernator_tpu.cmd.cli <address>
"""

from __future__ import annotations

import argparse
import asyncio
import random

from gubernator_tpu.api.types import Algorithm, RateLimitReq, Second, Status
from gubernator_tpu.client import AsyncClient, random_string


async def _amain(address: str, count: int, concurrency: int) -> None:
    client = AsyncClient(address)
    reqs = [
        RateLimitReq(
            name=random_string("ID-", 6),
            unique_key=random_string("ID-", 10),
            hits=1,
            limit=random.randint(1, 10),
            duration=random.randint(1, 10) * Second,
            algorithm=Algorithm.TOKEN_BUCKET,
        )
        for _ in range(count)
    ]
    sem = asyncio.Semaphore(concurrency)

    async def hit(req: RateLimitReq) -> None:
        async with sem:
            resps = await client.get_rate_limits([req], timeout=0.5)
            if resps[0].status == Status.OVER_LIMIT:
                print(resps[0])

    while True:
        await asyncio.gather(*(hit(r) for r in reqs))


def main() -> None:
    p = argparse.ArgumentParser("gubernator-tpu-cli")
    p.add_argument("address", nargs="?", default="127.0.0.1:9090")
    p.add_argument("--count", type=int, default=2000)
    p.add_argument("--concurrency", type=int, default=10)
    args = p.parse_args()
    try:
        asyncio.run(_amain(args.address, args.count, args.concurrency))
    except KeyboardInterrupt:
        pass


if __name__ == "__main__":
    main()
