"""HTTP JSON gateway: REST access to the same service.

Replaces the reference's grpc-gateway reverse proxy
(gubernator.pb.gw.go:59-148, wired in cmd/gubernator/main.go:107-116) with a
thin aiohttp app speaking the same proto3-JSON mapping (field names
camelCased, enums as strings — via google.protobuf.json_format, the same
conversion rules grpc-gateway uses):

  POST /v1/GetRateLimits   body: GetRateLimitsReq JSON
  GET  /v1/HealthCheck
  GET  /metrics            prometheus text format (main.go:113-116)
  GET  /v1/admin/debug     runtime introspection snapshot (JSON)
  GET  /v1/admin/topk      traffic analytics: hot-key top-K + tenants (JSON)
  POST /v1/admin/profile   arm a jax.profiler capture of the next N drains

Unlike the gateway in the reference (which dials the node's own gRPC port
over TCP), this calls the Instance in-process.
"""

from __future__ import annotations

import time

from aiohttp import web
from google.protobuf import json_format

from gubernator_tpu.api import pb
from gubernator_tpu.core.service import BatchTooLargeError, Instance
from gubernator_tpu.observability import (
    CONTENT_TYPE_LATEST,
    build_debug_snapshot,
)
from gubernator_tpu.observability.tracing import TRACEPARENT


def build_app(instance: Instance) -> web.Application:
    # The reference's gateway dials its own gRPC port, so gateway traffic
    # flows through the gRPC stats handler and is counted per-RPC
    # (prometheus.go:104-137).  This gateway is in-process, so the handlers
    # observe the same metric names themselves.
    async def get_rate_limits(request: web.Request) -> web.Response:
        # HTTP leg of trace propagation: continue an incoming traceparent
        # (or sample a new root) and echo the context back so callers can
        # correlate their logs with ours
        tracer = instance.tracer
        if tracer is None or not tracer.enabled:
            return await _get_rate_limits(request)
        with tracer.start_trace(
                "http", request.headers.get(TRACEPARENT)) as root:
            resp = await _get_rate_limits(request)
            if root.ctx is not None:
                resp.headers[TRACEPARENT] = root.ctx.traceparent()
            return resp

    async def _get_rate_limits(request: web.Request) -> web.Response:
        m = instance.metrics
        start = time.monotonic()
        ok = False
        try:
            try:
                body = await request.text()
                msg = json_format.Parse(body, pb.GetRateLimitsReq())
            except json_format.ParseError as e:
                return web.json_response({"error": str(e), "code": 3},
                                         status=400)
            # QoS deadline propagation: X-Guber-Timeout-Ms carries the
            # client's remaining budget (grpc-gateway's grpc-timeout
            # analog); admission sheds what cannot be served in time
            deadline = None
            if instance.qos is not None:
                timeout_ms = request.headers.get("X-Guber-Timeout-Ms")
                timeout_s = None
                if timeout_ms:
                    try:
                        timeout_s = float(timeout_ms) / 1000.0
                    except ValueError:
                        return web.json_response(
                            {"error": "invalid X-Guber-Timeout-Ms header",
                             "code": 3}, status=400)
                deadline = instance.qos.deadline_from_timeout(timeout_s)
            try:
                resps = await instance.get_rate_limits(
                    [pb.req_from_pb(r) for r in msg.requests],
                    deadline=deadline)
            except BatchTooLargeError as e:
                return web.json_response({"error": str(e), "code": 11},
                                         status=400)
            ok = True
            out = pb.GetRateLimitsResp(
                responses=[pb.resp_to_pb(r) for r in resps])
            return web.json_response(
                json_format.MessageToDict(out,
                                          preserving_proto_field_name=False))
        finally:
            # every RPC is observed, including unexpected 500s — during an
            # incident the failure rate must show up in the counters
            m.observe_rpc("/pb.gubernator.V1/GetRateLimits", start, ok=ok)

    async def health_check(request: web.Request) -> web.Response:
        start = time.monotonic()
        h = await instance.health_check()
        instance.metrics.observe_rpc(
            "/pb.gubernator.V1/HealthCheck", start, ok=True)
        msg = pb.HealthCheckResp(
            status=h.status, message=h.message, peer_count=h.peer_count)
        return web.json_response(
            json_format.MessageToDict(msg, preserving_proto_field_name=False))

    async def metrics(request: web.Request) -> web.Response:
        # the full prometheus content type, charset parameter included —
        # aiohttp's content_type kwarg rejects parameters, so it goes in
        # as a raw header
        return web.Response(
            body=instance.metrics.expose(),
            headers={"Content-Type": CONTENT_TYPE_LATEST},
        )

    # state-lifecycle admin plane (cmd/cli.py snapshot/restore): the
    # snapshot blob travels as-is — it is already versioned + checksummed
    async def admin_snapshot(request: web.Request) -> web.Response:
        data = await instance.export_snapshot_bytes(
            layout=request.query.get("layout", "auto"))
        return web.Response(body=data,
                            content_type="application/octet-stream")

    async def admin_restore(request: web.Request) -> web.Response:
        from gubernator_tpu.state.snapshot import SnapshotError
        data = await request.read()
        rebase = request.query.get("rebase_to")
        try:
            n = await instance.restore_snapshot_bytes(
                data, rebase_to=int(rebase) if rebase else None)
        except SnapshotError as e:
            return web.json_response({"error": str(e), "code": 3},
                                     status=400)
        return web.json_response({"restoredKeys": n})

    async def admin_debug(request: web.Request) -> web.Response:
        return web.json_response(build_debug_snapshot(instance))

    async def admin_topk(request: web.Request) -> web.Response:
        # hot-key view of the traffic analytics (cmd/cli.py `top`):
        # 404 when the subsystem is off so the CLI can say why
        an = getattr(instance, "analytics", None)
        if an is None:
            return web.json_response(
                {"error": "analytics disabled (set GUBER_ANALYTICS=1)",
                 "code": 12}, status=404)
        try:
            n = int(request.query.get("n", an.conf.topk))
        except ValueError:
            return web.json_response({"error": "invalid n", "code": 3},
                                     status=400)
        snap = an.snapshot()
        snap["topk"] = an.topk_snapshot(n)
        return web.json_response(snap)

    async def admin_profile(request: web.Request) -> web.Response:
        body = {}
        if request.can_read_body:
            try:
                body = await request.json()
            except Exception:
                return web.json_response(
                    {"error": "malformed JSON body", "code": 3}, status=400)
        drains = body.get("drains", request.query.get("drains", 1))
        trace_dir = body.get("dir", request.query.get("dir", ""))
        try:
            drains = int(drains)
        except (TypeError, ValueError):
            return web.json_response({"error": "invalid drains", "code": 3},
                                     status=400)
        out = instance.batcher.profile.arm(drains, trace_dir)
        # already-armed is a conflict, not a new capture
        return web.json_response(out,
                                 status=200 if out.get("armed") else 409)

    async def admin_kernels(request: web.Request) -> web.Response:
        """Census count × measured ms/window per serving arm, the rolling
        kernel table, and the window clock (observability/devprof.py).
        `?measure=1` runs the arm-scoped measured probe inline (seconds of
        compile on a cold process; 409 while a capture is armed);
        `?census=1` adds the per-arm census kernels/window (traced once,
        then cached)."""
        import asyncio as _aio
        devprof = getattr(instance, "devprof", None)
        if devprof is None:
            return web.json_response(
                {"error": "devprof unavailable", "code": 12}, status=501)
        q = request.query
        census = None
        if q.get("census", "1") not in ("0", "false"):
            from gubernator_tpu.observability.devprof import census_table
            census = await _aio.get_running_loop().run_in_executor(
                None, census_table)
            # keep the scoreboard gauge current with the freshly traced
            # table (startup publishes the same number; see
            # Instance._publish_census)
            metrics = getattr(instance, "metrics", None)
            if metrics is not None and census:
                arm = census.get("composed_analytics") \
                    or census.get("composed_drain")
                if arm:
                    metrics.kernels_per_window.set(arm)
        measured = None
        if q.get("measure") in ("1", "true"):
            if instance.batcher.profile.armed:
                return web.json_response(
                    {"error": "capture already in progress", "code": 10},
                    status=409)
            try:
                iters = max(1, int(q.get("iters", 2)))
            except (TypeError, ValueError):
                return web.json_response(
                    {"error": "invalid iters", "code": 3}, status=400)
            from gubernator_tpu.observability.devprof import (
                measure_census_arms,
            )
            measured = await _aio.get_running_loop().run_in_executor(
                None, lambda: measure_census_arms(iters=iters,
                                                  table=devprof.table))
        out = devprof.kernels_snapshot(census=census)
        if measured is not None:
            out["measured"] = measured["arms"]
            for arm, row in measured["arms"].items():
                slot = out["arms"].setdefault(
                    arm, {"census_kernels_per_window": None,
                          "measured_ms_per_window": None})
                slot["measured_ms_per_window"] = row["measured_ms_per_window"]
        return web.json_response(out)

    # a full-arena snapshot blob is tens of MB at default capacity — far
    # past aiohttp's 1 MiB default body cap, which would 413 every real
    # admin restore
    app = web.Application(client_max_size=1 << 30)
    app.router.add_post("/v1/GetRateLimits", get_rate_limits)
    app.router.add_get("/v1/HealthCheck", health_check)
    app.router.add_get("/metrics", metrics)
    app.router.add_get("/v1/admin/snapshot", admin_snapshot)
    app.router.add_post("/v1/admin/restore", admin_restore)
    app.router.add_get("/v1/admin/debug", admin_debug)
    app.router.add_get("/v1/admin/topk", admin_topk)
    app.router.add_post("/v1/admin/profile", admin_profile)
    app.router.add_get("/v1/admin/kernels", admin_kernels)
    return app


class HttpGateway:
    def __init__(self, instance: Instance, address: str):
        self.app = build_app(instance)
        host, _, port = address.rpartition(":")
        self.host = host or "127.0.0.1"
        self.port = int(port)
        self._runner: web.AppRunner | None = None

    async def start(self) -> None:
        self._runner = web.AppRunner(self.app)
        await self._runner.setup()
        site = web.TCPSite(self._runner, self.host, self.port)
        await site.start()

    async def stop(self) -> None:
        if self._runner is not None:
            await self._runner.cleanup()
