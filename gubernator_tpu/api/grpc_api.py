"""gRPC service wiring for V1 and PeersV1 (hand-wired generic handlers).

Service/method names match the reference exactly ("pb.gubernator.V1" and
"pb.gubernator.PeersV1", reference gubernator.pb.go:419, peers.pb.go:164) so
reference clients interoperate.  grpc_tools isn't available in this image, so
instead of generated *_grpc.py stubs we register method handlers directly —
functionally identical.
"""

from __future__ import annotations

import grpc

from gubernator_tpu.api import pb

V1_SERVICE = "pb.gubernator.V1"
PEERS_SERVICE = "pb.gubernator.PeersV1"


def add_v1_servicer(server: grpc.aio.Server, servicer) -> None:
    """servicer: async methods GetRateLimits(req, ctx), HealthCheck(req, ctx).

    GetRateLimits is registered at the BYTES level (no grpc-layer proto
    codec): the servicer owns decode/encode so eligible RPCs can run the
    native pipeline lane (core/pipeline.py) without ever materializing
    Python protobuf objects."""
    handlers = {
        "GetRateLimits": grpc.unary_unary_rpc_method_handler(
            servicer.GetRateLimits,
            request_deserializer=None,
            response_serializer=None,
        ),
        "HealthCheck": grpc.unary_unary_rpc_method_handler(
            servicer.HealthCheck,
            request_deserializer=pb.HealthCheckReq.FromString,
            response_serializer=pb.HealthCheckResp.SerializeToString,
        ),
    }
    server.add_generic_rpc_handlers(
        (grpc.method_handlers_generic_handler(V1_SERVICE, handlers),)
    )


def add_peers_servicer(server: grpc.aio.Server, servicer) -> None:
    """servicer: async GetPeerRateLimits(req, ctx), UpdatePeerGlobals(req,
    ctx), RegisterGlobals(req, ctx), ApplyGlobalRegistration(req, ctx)."""
    handlers = {
        # bytes-level like V1.GetRateLimits: the servicer owns
        # decode/encode so authoritative relays can run the native
        # pipeline lane without materializing protobuf objects
        "GetPeerRateLimits": grpc.unary_unary_rpc_method_handler(
            servicer.GetPeerRateLimits,
            request_deserializer=None,
            response_serializer=None,
        ),
        # bytes-level: the migration payload codec is state/migrate.py's
        # (versioned JSON), not a generated proto
        "TransferBuckets": grpc.unary_unary_rpc_method_handler(
            servicer.TransferBuckets,
            request_deserializer=None,
            response_serializer=None,
        ),
        "UpdatePeerGlobals": grpc.unary_unary_rpc_method_handler(
            servicer.UpdatePeerGlobals,
            request_deserializer=pb.UpdatePeerGlobalsReq.FromString,
            response_serializer=pb.UpdatePeerGlobalsResp.SerializeToString,
        ),
        "RegisterGlobals": grpc.unary_unary_rpc_method_handler(
            servicer.RegisterGlobals,
            request_deserializer=pb.RegisterGlobalsReq.FromString,
            response_serializer=pb.RegisterGlobalsResp.SerializeToString,
        ),
        "ApplyGlobalRegistration": grpc.unary_unary_rpc_method_handler(
            servicer.ApplyGlobalRegistration,
            request_deserializer=pb.ApplyGlobalRegistrationReq.FromString,
            response_serializer=(
                pb.ApplyGlobalRegistrationResp.SerializeToString),
        ),
    }
    server.add_generic_rpc_handlers(
        (grpc.method_handlers_generic_handler(PEERS_SERVICE, handlers),)
    )


class V1Stub:
    """Client stub for the public API (reference gubernator.pb.go:375-409)."""

    def __init__(self, channel):
        self.GetRateLimits = channel.unary_unary(
            f"/{V1_SERVICE}/GetRateLimits",
            request_serializer=pb.GetRateLimitsReq.SerializeToString,
            response_deserializer=pb.GetRateLimitsResp.FromString,
        )
        self.HealthCheck = channel.unary_unary(
            f"/{V1_SERVICE}/HealthCheck",
            request_serializer=pb.HealthCheckReq.SerializeToString,
            response_deserializer=pb.HealthCheckResp.FromString,
        )


class PeersV1Stub:
    """Client stub for the peer plane (reference peers.pb.go:122-155)."""

    def __init__(self, channel):
        self.GetPeerRateLimits = channel.unary_unary(
            f"/{PEERS_SERVICE}/GetPeerRateLimits",
            request_serializer=pb.GetPeerRateLimitsReq.SerializeToString,
            response_deserializer=pb.GetPeerRateLimitsResp.FromString,
        )
        self.UpdatePeerGlobals = channel.unary_unary(
            f"/{PEERS_SERVICE}/UpdatePeerGlobals",
            request_serializer=pb.UpdatePeerGlobalsReq.SerializeToString,
            response_deserializer=pb.UpdatePeerGlobalsResp.FromString,
        )
        self.RegisterGlobals = channel.unary_unary(
            f"/{PEERS_SERVICE}/RegisterGlobals",
            request_serializer=pb.RegisterGlobalsReq.SerializeToString,
            response_deserializer=pb.RegisterGlobalsResp.FromString,
        )
        self.ApplyGlobalRegistration = channel.unary_unary(
            f"/{PEERS_SERVICE}/ApplyGlobalRegistration",
            request_serializer=pb.ApplyGlobalRegistrationReq.SerializeToString,
            response_deserializer=pb.ApplyGlobalRegistrationResp.FromString,
        )
