"""Wire-level types for the rate-limit API.

These mirror the reference proto contract exactly (enum values and field
semantics from /root/reference/proto/gubernator.proto:56-143) so that clients
of the reference can switch over without changes.  The dataclasses here are the
in-process representation; the gRPC layer maps them 1:1 onto protobuf messages
generated from the same .proto files.
"""

from __future__ import annotations

import enum
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional


class Algorithm(enum.IntEnum):
    # reference proto/gubernator.proto:56-61; values 2..4 are the
    # algorithm-plane extension (gubernator_tpu/algorithms/): GCRA,
    # weighted sliding-window counters, and concurrency leases (negative
    # hits releases held slots).  Out-of-range values degrade to
    # TOKEN_BUCKET on-device (reference algorithms.go:100-104 fallback).
    TOKEN_BUCKET = 0
    LEAKY_BUCKET = 1
    GCRA = 2
    SLIDING_WINDOW = 3
    CONCURRENCY = 4


class Behavior(enum.IntEnum):
    # reference proto/gubernator.proto:64-95
    BATCHING = 0
    NO_BATCHING = 1
    GLOBAL = 2


class Status(enum.IntEnum):
    # reference proto/gubernator.proto:126-129
    UNDER_LIMIT = 0
    OVER_LIMIT = 1


# Duration constants in milliseconds (reference client.go:27-31).
Millisecond = 1
Second = 1000 * Millisecond
Minute = 60 * Second
Hour = 60 * Minute


def millisecond_now() -> int:
    """Unix epoch in milliseconds (reference cache/lru.go:99-101)."""
    return time.time_ns() // 1_000_000


@dataclass
class RateLimitReq:
    # reference proto/gubernator.proto:97-123
    name: str = ""
    unique_key: str = ""
    hits: int = 0
    limit: int = 0
    duration: int = 0  # milliseconds
    algorithm: int = Algorithm.TOKEN_BUCKET
    behavior: int = Behavior.BATCHING

    def hash_key(self) -> str:
        """The cache/routing key: name + "_" + unique_key (reference client.go:33-35)."""
        return self.name + "_" + self.unique_key


@dataclass
class RateLimitResp:
    # reference proto/gubernator.proto:131-143
    status: int = Status.UNDER_LIMIT
    limit: int = 0
    remaining: int = 0
    reset_time: int = 0  # unix ms epoch
    error: str = ""
    metadata: Dict[str, str] = field(default_factory=dict)


@dataclass
class GetRateLimitsReq:
    requests: List[RateLimitReq] = field(default_factory=list)


@dataclass
class GetRateLimitsResp:
    responses: List[RateLimitResp] = field(default_factory=list)


@dataclass
class HealthCheckResp:
    # reference proto/gubernator.proto:146-153
    status: str = ""
    message: str = ""
    peer_count: int = 0


@dataclass
class UpdatePeerGlobal:
    """One authoritative global-limit status pushed owner -> peers.

    The reference message carries only (key, status)
    (/root/reference/proto/peers.proto:50-53), which loses the algorithm and
    duration and silently breaks GLOBAL leaky buckets (status.reset_time is 0
    for leaky, so the reference stores an entry that is already expired).  We
    carry algorithm and duration as additive fields so replicas can upsert a
    fully-typed entry; see state/arena.py upsert.
    """

    key: str = ""
    status: Optional[RateLimitResp] = None
    algorithm: int = Algorithm.TOKEN_BUCKET
    duration: int = 0
