"""Configuration: library structs + GUBER_* environment config.

Mirrors both reference config surfaces:
  * library embedding contract (reference config.go:28-75): Config /
    BehaviorConfig structs with the same defaults (500ms timeouts, 500µs
    windows, batch limit 1000);
  * daemon env-var config (reference cmd/gubernator/config.go:59-147): the
    same GUBER_* variable names, optional KEY=value env-file, k8s/etcd
    mutual exclusivity.

New TPU-specific knobs live under GUBER_TPU_* (arena capacity, window lanes)
— absent from the reference because its cache is a host hash map.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import List, Optional

# Hard cap on items per RPC (reference gubernator.go:34).
MAX_BATCH_SIZE = 1000

# Deferred-fetch dispatch chain (core/pipeline.py) — env-only perf knobs,
# same discipline as GUBER_PIPELINE_DEPTH.  GUBER_FETCH_STRIDE pins the
# floor of drains that ride one stacked D2H fetch (1 = fetch every drain,
# the classic cadence); GUBER_FETCH_STRIDE_MAX caps how far the AIMD
# stride controller (qos/congestion.py observe_chain) may grow the chain
# as backlog deepens; GUBER_CHAIN_LINGER_MS bounds how long a chained
# drain waits for companions before the pipeline flushes anyway.
# Cost model (BASELINE.md): t/window ~= (N*t_exec + t_fetch)/N — on a
# tunneled chip whose fetch is a flat ~70ms, stride N recovers nearly N×.
FETCH_STRIDE_DEFAULT = 1
FETCH_STRIDE_MAX_DEFAULT = 8
CHAIN_LINGER_MS_DEFAULT = 2.0

# Serving-lowering ladder (core/engine.py) — env-only perf knobs, all read
# through env_bool at BUILD time (they key the compiled-builder cache, so
# flipping one mid-process only affects executables built afterwards):
#   GUBER_PALLAS=1          per-op Pallas lowerings (default: XLA)
#   GUBER_PALLAS_FUSED=1    the fused serving-window megakernel
#   GUBER_PALLAS_STAGED=0   opt OUT of the staged drain (default ON when
#                           fused): K-grid drain kernel + pair-GLOBAL
#                           kernel + analytics finisher — the folded
#                           single-digit kernels/window ladder.  0 reverts
#                           to the lax.scan drain skeleton for bisection.
#   GUBER_COMPACT32_XLA=0   opt out of the compact32 XLA window body


@dataclass
class BehaviorConfig:
    """Batching/global windows (reference config.go:43-57, defaults :59-66).

    Durations are seconds (float) — the reference uses Go time.Duration;
    0.0005 == the reference's 500µs default.
    """

    batch_timeout: float = 0.5
    batch_wait: float = 0.0005
    batch_limit: int = MAX_BATCH_SIZE
    global_sync_wait: float = 0.0005
    global_timeout: float = 0.5
    global_batch_limit: int = MAX_BATCH_SIZE
    # Mesh (lockstep) serving only: windows dispatched per tick, all as ONE
    # stacked device call (engine.step_stacked).  Every process in the mesh
    # MUST use the same value — the stacked executable's shape is part of
    # the collective contract.  1 = classic one-window ticks.
    lockstep_stack: int = 1

    def validate(self) -> None:
        if self.batch_limit > MAX_BATCH_SIZE:
            raise ValueError(f"Behaviors.BatchLimit cannot exceed '{MAX_BATCH_SIZE}'")
        if self.lockstep_stack < 1:
            raise ValueError("Behaviors.lockstep_stack must be >= 1")


@dataclass
class EngineConfig:
    """Dimensions of the device arenas (no reference analog: replaces the
    LRU cache size knob GUBER_CACHE_SIZE / cache/lru.go:50)."""

    capacity_per_shard: int = 65536
    batch_per_shard: int = 1024
    global_capacity: int = 4096
    global_batch_per_shard: int = 256
    max_global_updates: int = 256
    # Regular-key routing backend: "auto" uses the native C++ router when
    # the extension built, False forces the Python SlotTables (env:
    # GUBER_NATIVE=0).  Live key migration (state/migrate.py) requires the
    # Python tables — the native router keeps fingerprints, not keys.
    use_native: object = "auto"
    # Opt-in exact-key collision guard in the native router (env:
    # GUBER_EXACT_KEYS=1): stores full key bytes so a 64-bit fingerprint
    # collision probes onward instead of merging two keys' counters.
    # Costs ~key-length bytes per resident key.
    exact_keys: bool = False
    # Replay-bound guard (env: GUBER_REPLAY_CAP): max lanes of a
    # NON-uniform duplicate-key run per device window before the native
    # router splits the window (bounds the kernel's replay loop against
    # mixed-config hot-key floods).  0 disables; uniform duplicates are
    # never split (the closed form is O(1) in run length).
    replay_cap: int = 128
    # Operator promise that this deployment serves NO GLOBAL-behavior
    # traffic (env: GUBER_SKIP_GLOBAL=1): stacked dispatches always use
    # the GLOBAL-skipping twin executable.  Unlike the single-process
    # inertness gate (engine.step_windows), a config-level flag is
    # identical on every mesh process, so the skip is mesh-legal — the
    # executable choice never depends on per-tick staging.  GLOBAL
    # requests submitted anyway are rejected loudly.
    skip_global: bool = False


@dataclass
class QoSConfig:
    """QoS / overload-control knobs (gubernator_tpu/qos/): admission
    control, AIMD congestion window, per-tenant fair slotting, and the
    peer-lane resilience layer.  No reference analog — the reference
    queues unboundedly and surfaces peer failures as raw gRPC errors."""

    enabled: bool = True
    # ---- admission (qos/admission.py)
    # Bounded pending queue, in decisions; 0 disables the bound.  Sized a
    # few drain cycles deep: deeper only adds latency, never throughput.
    max_pending: int = 8192
    # Implicit per-request deadline (seconds) when the client sends none;
    # 0 = requests without a deadline never deadline-shed.
    default_deadline: float = 0.0
    # ---- congestion window (qos/congestion.py)
    min_window: int = 64
    max_window: int = 8192
    # Drain-latency target the AIMD tracks (seconds).  Above it: cwnd *=
    # aimd_decrease (once per cooldown); below: cwnd += aimd_increase.
    target_drain_latency: float = 0.1
    aimd_increase: float = 64.0
    aimd_decrease: float = 0.5
    latency_ewma_alpha: float = 0.3
    # ---- fair slotting (qos/fairness.py)
    fair_slotting: bool = True
    # ---- peer lane (qos/breaker.py + net/peers.py)
    peer_retries: int = 2          # retries after the first attempt
    retry_base: float = 0.025      # seconds; doubles per attempt, jittered
    retry_cap: float = 0.25
    breaker_fail_threshold: int = 5
    breaker_open_duration: float = 2.0
    breaker_half_open_probes: int = 1
    # While a peer's breaker is open: True = fail open (answer from the
    # local engine, non-authoritative, flagged in metadata); False = fail
    # closed (in-band shed with reason breaker_open).
    fail_open: bool = True

    def validate(self) -> None:
        if self.max_pending < 0:
            raise ValueError("QoS.max_pending must be >= 0")
        if self.min_window < 1 or self.max_window < self.min_window:
            raise ValueError(
                "QoS window bounds need 1 <= min_window <= max_window")
        if not (0.0 < self.aimd_decrease < 1.0):
            raise ValueError("QoS.aimd_decrease must be in (0, 1)")
        if not (0.0 < self.latency_ewma_alpha <= 1.0):
            raise ValueError("QoS.latency_ewma_alpha must be in (0, 1]")
        if self.target_drain_latency <= 0:
            raise ValueError("QoS.target_drain_latency must be > 0")
        if self.peer_retries < 0:
            raise ValueError("QoS.peer_retries must be >= 0")


@dataclass
class LeaseConfig:
    """Concurrency-lease plane knobs (gubernator_tpu/algorithms/leases.py).
    The device free-slot counters stay authoritative regardless; these
    govern the host-side book that attributes held slots to clients."""

    # Release a vanished client's held slots when the RPC that carried its
    # acquires is torn down before the response is delivered (server.py
    # stream-close hook).  Off leaves reclaim to bucket expiry alone.
    release_on_stream_close: bool = field(
        default_factory=lambda: env_bool("GUBER_LEASE_RELEASE_ON_CLOSE",
                                         True))
    # Periodic sweep of expired grants out of the book, ms (0 disables;
    # the device already expired those buckets, the sweep only keeps the
    # lease gauges honest).
    sweep_interval_ms: int = field(
        default_factory=lambda: env_int("GUBER_LEASE_SWEEP_MS", 5000,
                                        minimum=0))
    # Cap on slots one client may hold per key (0 = unlimited): an acquire
    # that would exceed it is answered OVER_LIMIT on the host, before the
    # device sees it.
    max_per_client: int = field(
        default_factory=lambda: env_int("GUBER_LEASE_MAX_PER_CLIENT", 0,
                                        minimum=0))

    def validate(self) -> None:
        if self.sweep_interval_ms < 0:
            raise ValueError("Lease.sweep_interval_ms must be >= 0")
        if self.max_per_client < 0:
            raise ValueError("Lease.max_per_client must be >= 0")


@dataclass
class HealthConfig:
    """Self-healing ring knobs (net/health.py + the hinted-handoff buffer
    in core/global_sync.py + the daemon drain phase).  No reference
    analog — the reference leans entirely on its discovery backend to
    remove dead peers, which GUBER_STATIC_PEERS never does."""

    # ---- heartbeat failure detector (net/health.py)
    heartbeat_enabled: bool = True
    # Probe cadence and per-probe deadline (seconds)
    heartbeat_interval: float = 1.0
    heartbeat_timeout: float = 0.5
    # Consecutive probe failures before a peer is confirmed DOWN (and the
    # ring re-homes around it); consecutive successes before a DOWN peer
    # is confirmed UP again.  The two-sided hysteresis is what keeps a
    # flapping peer from churning the ring on every blip.
    suspect_after: int = 3
    recover_after: int = 2
    # ---- hinted handoff (core/global_sync.py)
    # How long a failed peer's GLOBAL hits/updates are buffered before
    # being dropped as expired (seconds), and the per-peer entry bound
    # (oldest evicted first, counted as expired).
    hint_ttl: float = 30.0
    hint_max: int = 1024
    # ---- graceful departure (daemon.py stop())
    # Ceiling on each drain phase: in-flight window drain, global flush,
    # and key handoff each get at most this long (seconds).
    drain_timeout: float = 5.0

    def validate(self) -> None:
        if self.heartbeat_interval <= 0 or self.heartbeat_timeout <= 0:
            raise ValueError("Health heartbeat interval/timeout must be > 0")
        if self.suspect_after < 1 or self.recover_after < 1:
            raise ValueError("Health suspect_after/recover_after must be >= 1")
        if self.hint_ttl < 0 or self.hint_max < 0:
            raise ValueError("Health hint_ttl/hint_max must be >= 0")


@dataclass
class AnalyticsConfig:
    """Device-computed traffic analytics (ops/analytics.py +
    observability/analytics.py): per-drain outcome counts, count-min
    sketch + hot-key top-K, per-tenant usage rows, arena occupancy/churn.
    Defaults read GUBER_ANALYTICS_* at construction (trace_sample
    pattern) so library embedders get the same knobs as the daemon.
    No reference analog — the reference exposes only cache hit/miss."""

    enabled: bool = field(
        default_factory=lambda: env_bool("GUBER_ANALYTICS", False))
    # Candidate rows per shard per drain AND the host's rolling table size.
    topk: int = field(
        default_factory=lambda: env_int("GUBER_ANALYTICS_TOPK", 32))
    # Count-min sketch geometry (per shard, resident on device).
    sketch_width: int = field(
        default_factory=lambda: env_int("GUBER_ANALYTICS_SKETCH_WIDTH", 2048))
    sketch_depth: int = field(
        default_factory=lambda: env_int("GUBER_ANALYTICS_SKETCH_DEPTH", 4))
    # Sketch + rolling-table halving cadence (ms); 0 disables decay.
    decay_ms: int = field(
        default_factory=lambda: env_int("GUBER_ANALYTICS_DECAY_MS", 10_000,
                                        minimum=0))
    # Distinct tenants tracked on device; id 0 is the shared
    # "other/unattributed" row (native-fastpath lanes land there).
    tenant_slots: int = field(
        default_factory=lambda: env_int("GUBER_ANALYTICS_TENANTS", 64,
                                        minimum=2))
    # Hot-key score = hits + over_weight * over_limit decisions: keys
    # burning their limit rank above merely chatty ones.
    over_weight: int = field(
        default_factory=lambda: env_int("GUBER_ANALYTICS_OVER_WEIGHT", 4,
                                        minimum=0))

    def validate(self) -> None:
        from gubernator_tpu.ops import analytics as _ops
        if self.sketch_depth > _ops.MAX_SKETCH_DEPTH:
            raise ValueError(
                f"Analytics.sketch_depth cannot exceed {_ops.MAX_SKETCH_DEPTH}")
        if self.topk < 1 or self.sketch_width < 16:
            raise ValueError("Analytics.topk >= 1 and sketch_width >= 16 required")


@dataclass
class TierConfig:
    """Tiered key state (state/tiers.py): a host-side warm store behind
    the fixed HBM arena, turning slot exhaustion into a cache-miss cost
    over an unbounded keyspace.  Default-off (warm_rows=0): the hot path
    stays byte-identical to the single-tier engine.  Requires the Python
    routing backend and a single-process engine (config_from_env forces
    use_native=False when tiers are enabled).  Defaults read GUBER_TIER_*
    at construction (trace_sample pattern) so library embedders get the
    same knobs as the daemon.  No reference analog — the reference's LRU
    simply drops the coldest bucket's counters on the floor."""

    # Warm-store capacity in rows; 0 disables tiers entirely.
    warm_rows: int = field(
        default_factory=lambda: env_int("GUBER_TIER_WARM", 0, minimum=0))
    # Warm row layout: "int64" (absolute times) or "compact32" (int32
    # values + pair-rebased int32 times vs the store epoch — half the
    # bytes; rows outside the rebase range fall back to an int64 side
    # map, so the choice is never lossy).
    layout: str = field(
        default_factory=lambda: _env("GUBER_TIER_LAYOUT", "int64"))
    # LRU-head candidates ranked by analytics heat when picking a live
    # demotion victim (1 = strict LRU, the seed policy).
    victim_sample: int = field(
        default_factory=lambda: env_int("GUBER_TIER_VICTIM_SAMPLE", 8))
    # Proactive demotion: tier_maintain spills cold entries once a
    # shard's table runs above this occupancy fraction, demote_batch rows
    # per pass.
    demote_watermark: float = field(
        default_factory=lambda: env_float("GUBER_TIER_DEMOTE_WATERMARK",
                                          0.9, minimum=0.1))
    demote_batch: int = field(
        default_factory=lambda: env_int("GUBER_TIER_DEMOTE_BATCH", 64))

    @property
    def enabled(self) -> bool:
        return self.warm_rows > 0

    def validate(self) -> None:
        if self.layout not in ("int64", "compact32"):
            raise ValueError(
                f"GUBER_TIER_LAYOUT must be int64 or compact32, "
                f"got {self.layout!r}")
        if not (0.1 <= self.demote_watermark <= 1.0):
            raise ValueError("Tier.demote_watermark must be in [0.1, 1.0]")


@dataclass
class SLOConfig:
    """SLO burn-rate engine (observability/analytics.py SLOEngine):
    multi-window multi-burn-rate alerting over configured objectives.
    Each burn window pairs with a short window (window/12) — an alert
    fires only when BOTH exceed the threshold (Google SRE workbook ch.5),
    so a burst trips fast windows and a slow leak trips long ones."""

    enabled: bool = field(
        default_factory=lambda: env_bool("GUBER_SLO", False))
    # drain p99 objective: fraction of drains allowed over the target.
    drain_p99_ms: float = field(
        default_factory=lambda: env_float("GUBER_SLO_DRAIN_P99_MS", 100.0,
                                          minimum=1e-3))
    drain_budget: float = field(
        default_factory=lambda: env_float("GUBER_SLO_DRAIN_BUDGET", 0.01))
    # shed-rate objective: fraction of decisions allowed to shed.
    shed_budget: float = field(
        default_factory=lambda: env_float("GUBER_SLO_SHED_BUDGET", 0.01))
    # availability objective: 1 - availability is the error budget over
    # decisions (sheds + errors count as bad).
    availability: float = field(
        default_factory=lambda: env_float("GUBER_SLO_AVAILABILITY", 0.999))
    # "window_seconds:threshold" pairs, comma-separated.  The defaults are
    # the SRE-workbook ladder scaled to minutes (page = 14.4x over 5m,
    # ticket = 6x over 30m, trend = 1x over 2h).
    burn_windows: str = field(
        default_factory=lambda: _env("GUBER_SLO_BURN_WINDOWS",
                                     "300:14.4,1800:6,7200:1"))

    def windows(self) -> List[tuple]:
        """Parse burn_windows → [(seconds, threshold)], skipping malformed
        pairs (observability knobs must never crash a boot)."""
        out = []
        for part in self.burn_windows.split(","):
            part = part.strip()
            if not part:
                continue
            try:
                w, _, t = part.partition(":")
                sec, thr = float(w), float(t) if t else 1.0
                if sec > 0 and thr > 0:
                    out.append((sec, thr))
            except ValueError:
                continue
        return out or [(300.0, 14.4), (1800.0, 6.0), (7200.0, 1.0)]

    def validate(self) -> None:
        if not (0.0 < self.drain_budget <= 1.0):
            raise ValueError("SLO.drain_budget must be in (0, 1]")
        if not (0.0 < self.shed_budget <= 1.0):
            raise ValueError("SLO.shed_budget must be in (0, 1]")
        if not (0.0 < self.availability < 1.0):
            raise ValueError("SLO.availability must be in (0, 1)")


@dataclass
class PeerInfo:
    # reference etcd.go:29-32
    address: str = ""
    is_owner: bool = False


@dataclass
class Config:
    """Library config (reference config.go:28-41).  The reference requires a
    grpc.Server; here the Instance owns its grpc.aio server bound to
    `grpc_address` (or none, for embedded/standalone use)."""

    grpc_address: str = ""
    behaviors: BehaviorConfig = field(default_factory=BehaviorConfig)
    engine: EngineConfig = field(default_factory=EngineConfig)
    qos: QoSConfig = field(default_factory=QoSConfig)
    health: HealthConfig = field(default_factory=HealthConfig)
    analytics: AnalyticsConfig = field(default_factory=AnalyticsConfig)
    slo: SLOConfig = field(default_factory=SLOConfig)
    tiers: TierConfig = field(default_factory=TierConfig)
    leases: LeaseConfig = field(default_factory=LeaseConfig)
    # advertise address used for self-identification in the peer ring
    advertise_address: str = ""
    # Request tracing (observability/tracing.py): probability a request
    # starts a trace (0 disables, the default — the hot path pays one
    # attribute check) and the optional OTLP/HTTP export endpoint.
    # Defaults read the env at construction so library embedders get the
    # same GUBER_TRACE_* knobs as the daemon.
    trace_sample: float = field(
        default_factory=lambda: env_float("GUBER_TRACE_SAMPLE", 0.0))
    trace_export: str = field(
        default_factory=lambda: _env("GUBER_TRACE_EXPORT"))
    # Device-time flight recorder (observability/devprof.py).  Mode "" =
    # off (window clocks still run when metrics are wired; the kernel
    # table only fills from explicit captures); "periodic" re-arms
    # N-drain jax.profiler captures on a shedding background thread and
    # folds the parsed kernel table between intervals.
    devprof_mode: str = field(
        default_factory=lambda: _env("GUBER_DEVPROF"))
    devprof_interval_s: float = field(
        default_factory=lambda: env_float("GUBER_DEVPROF_INTERVAL_S", 30.0,
                                          minimum=0.05))
    devprof_drains: int = field(
        default_factory=lambda: env_int("GUBER_DEVPROF_DRAINS", 8))
    devprof_ring: int = field(
        default_factory=lambda: env_int("GUBER_DEVPROF_RING", 64))
    devprof_slow_ms: float = field(
        default_factory=lambda: env_float("GUBER_DEVPROF_SLOW_MS", 50.0))


@dataclass
class DaemonConfig:
    """Daemon env config (reference cmd/gubernator/config.go:42-57)."""

    grpc_listen_address: str = "localhost:81"
    http_listen_address: str = "localhost:80"
    advertise_address: str = ""
    cache_size: int = 50000  # reference default, example.conf:11
    debug: bool = False

    # Multi-process front door (frontdoor.py): 0 = classic single-process
    # serving (byte-identical to pre-frontdoor builds); N >= 1 spawns N
    # acceptor worker processes sharing the gRPC listen port via
    # SO_REUSEPORT, each handing parsed request columns to this engine
    # process over a shared-memory ring (core/shm_ring.py).
    frontdoor_workers: int = 0
    # Slabs per worker ring == max in-flight RPCs per worker; beyond it
    # workers shed in-band with shed_reason=ring_full.
    shm_ring_slots: int = 64
    # Slab size; the default fits any max-size (1MB) gRPC message in
    # either record shape (raw bytes, or 1000-item columns + keys).
    shm_slab_bytes: int = (1 << 20) + (1 << 16)
    # Response-encode side: "worker" ships packed decision columns over
    # the completion ring and each worker serializes the protobuf in its
    # own process (native frontdoor_encode_resp / pb fallback); "engine"
    # restores the classic engine-side serialization.
    frontdoor_encode: str = "worker"
    # Wire-read coalescing: up to N pending RPCs per worker event-loop
    # tick share ONE slab + ONE ring publish (amortizing per-record ring
    # overhead like the fetch chain amortized device RTT).  0/1 = off.
    frontdoor_batch_reads: int = 8

    # k8s discovery
    k8s_namespace: str = ""
    k8s_pod_ip: str = ""
    k8s_pod_port: str = ""
    k8s_endpoints_selector: str = ""

    # State lifecycle (state/snapshot.py): when snapshot_dir is set, the
    # daemon restores the arena from it on boot and re-snapshots every
    # snapshot_interval_ms (plus once on clean shutdown).
    snapshot_dir: str = ""
    snapshot_interval_ms: int = 60_000

    # etcd discovery
    etcd_addresses: List[str] = field(default_factory=list)
    etcd_prefix: str = "/gubernator/peers/"
    etcd_dial_timeout: float = 5.0
    etcd_username: str = ""
    etcd_password: str = ""
    # etcd TLS (reference cmd/gubernator/config.go:149-192): any
    # GUBER_ETCD_TLS_* variable enables TLS; CA/cert/key are file paths.
    etcd_tls_enabled: bool = False
    etcd_tls_cert: str = ""
    etcd_tls_key: str = ""
    etcd_tls_ca: str = ""
    etcd_tls_skip_verify: bool = False

    def etcd_ssl_context(self):
        """Build the ssl.SSLContext for the etcd gateway connection, or None
        when TLS is disabled (the setupTLS analog, config.go:149-192)."""
        if not self.etcd_tls_enabled:
            return None
        import ssl

        ctx = ssl.create_default_context()
        if self.etcd_tls_ca:
            ctx.load_verify_locations(cafile=self.etcd_tls_ca)
        if self.etcd_tls_cert and self.etcd_tls_key:
            ctx.load_cert_chain(self.etcd_tls_cert, self.etcd_tls_key)
        if self.etcd_tls_skip_verify:
            ctx.check_hostname = False
            ctx.verify_mode = ssl.CERT_NONE
        return ctx

    behaviors: BehaviorConfig = field(default_factory=BehaviorConfig)
    engine: EngineConfig = field(default_factory=EngineConfig)
    qos: QoSConfig = field(default_factory=QoSConfig)
    health: HealthConfig = field(default_factory=HealthConfig)
    analytics: AnalyticsConfig = field(default_factory=AnalyticsConfig)
    slo: SLOConfig = field(default_factory=SLOConfig)
    tiers: TierConfig = field(default_factory=TierConfig)
    leases: LeaseConfig = field(default_factory=LeaseConfig)

    @property
    def k8s_enabled(self) -> bool:
        return bool(self.k8s_namespace)

    @property
    def etcd_enabled(self) -> bool:
        return bool(self.etcd_addresses)


def _env(name: str, default: str = "") -> str:
    v = os.environ.get(name)
    return v if v not in (None, "") else default


def env_int(name: str, default: int, minimum: int = 1) -> int:
    """Integer GUBER_* knob with a floor; malformed values fall back to
    the default (perf tunables must never crash a boot).  Shared by the
    engine's GUBER_PIPELINE_KMAX and the pipeline's GUBER_FETCH_WORKERS."""
    try:
        return max(minimum, int(os.environ.get(name, default)))
    except ValueError:
        return default


def env_float(name: str, default: float, minimum: float = 0.0) -> float:
    """Float GUBER_* knob with a floor; malformed values fall back to the
    default (perf tunables must never crash a boot)."""
    try:
        return max(minimum, float(os.environ.get(name, default)))
    except ValueError:
        return default


_TRUTHY = frozenset(("1", "true", "yes", "on"))
_FALSY = frozenset(("0", "false", "no", "off", ""))
_warned_env: set = set()


def env_bool(name: str, default: bool = False) -> bool:
    """Boolean GUBER_* knob: accepts 0/1/true/false/yes/no/on/off
    (case-insensitive); unset means `default`.  An unrecognized value
    warns once per (name, value) and falls back to the default — the old
    `== "1"` readers silently disabled features on `GUBER_PALLAS_FUSED=true`,
    which is exactly the misconfiguration a perf flag must surface.

    One shared reader for every on/off flag (engine executables,
    pallas_kernel, probes): these flags are compiled-builder cache keys
    read at build time, so every reader normalizing identically is part
    of the executable-consistency contract."""
    v = os.environ.get(name)
    if v is None:
        return default
    s = v.strip().lower()
    if s in _TRUTHY:
        return True
    if s in _FALSY:
        return False
    if (name, v) not in _warned_env:
        _warned_env.add((name, v))
        import logging
        logging.getLogger("gubernator.config").warning(
            "unrecognized boolean value %r for %s (expected 0/1/true/false); "
            "using default %s", v, name, default)
    return default


def load_env_file(path: str) -> None:
    """Load a KEY=value file into the process env (reference
    cmd/gubernator/config.go:239-267): '#' comments, blank lines skipped,
    malformed lines rejected."""
    with open(path) as f:
        for ln, line in enumerate(f, 1):
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            if "=" not in line:
                raise ValueError(f"malformed key=value on line '{ln}'")
            k, _, v = line.partition("=")
            os.environ[k.strip()] = v.strip()


def config_from_env(env_file: Optional[str] = None) -> DaemonConfig:
    """Assemble DaemonConfig from GUBER_* env vars (reference
    cmd/gubernator/config.go:59-147; full variable list example.conf:1-96)."""
    if env_file:
        load_env_file(env_file)

    c = DaemonConfig()
    c.grpc_listen_address = _env("GUBER_GRPC_ADDRESS", c.grpc_listen_address)
    c.http_listen_address = _env("GUBER_HTTP_ADDRESS", c.http_listen_address)
    c.advertise_address = _env("GUBER_ADVERTISE_ADDRESS", c.grpc_listen_address)
    c.cache_size = int(_env("GUBER_CACHE_SIZE", str(c.cache_size)))
    c.debug = _env("GUBER_DEBUG") in ("true", "1", "yes")

    c.frontdoor_workers = env_int("GUBER_FRONTDOOR_WORKERS",
                                  c.frontdoor_workers, minimum=0)
    c.shm_ring_slots = env_int("GUBER_SHM_RING_SLOTS", c.shm_ring_slots,
                               minimum=2)
    c.shm_slab_bytes = env_int("GUBER_SHM_SLAB_BYTES", c.shm_slab_bytes,
                               minimum=1 << 16)
    enc = _env("GUBER_FRONTDOOR_ENCODE", c.frontdoor_encode)
    c.frontdoor_encode = enc if enc in ("worker", "engine") else "worker"
    c.frontdoor_batch_reads = env_int("GUBER_FRONTDOOR_BATCH_READS",
                                      c.frontdoor_batch_reads, minimum=0)

    c.snapshot_dir = _env("GUBER_SNAPSHOT_DIR")
    c.snapshot_interval_ms = env_int("GUBER_SNAPSHOT_INTERVAL_MS",
                                     c.snapshot_interval_ms, minimum=100)

    c.k8s_namespace = _env("GUBER_K8S_NAMESPACE")
    c.k8s_pod_ip = _env("GUBER_K8S_POD_IP")
    c.k8s_pod_port = _env("GUBER_K8S_POD_PORT")
    c.k8s_endpoints_selector = _env("GUBER_K8S_ENDPOINTS_SELECTOR")

    etcd = _env("GUBER_ETCD_ENDPOINTS")
    c.etcd_addresses = [a.strip() for a in etcd.split(",") if a.strip()]
    c.etcd_prefix = _env("GUBER_ETCD_KEY_PREFIX", c.etcd_prefix)
    c.etcd_dial_timeout = float(_env("GUBER_ETCD_DIAL_TIMEOUT", "5"))
    c.etcd_username = _env("GUBER_ETCD_USER")
    c.etcd_password = _env("GUBER_ETCD_PASSWORD")

    # any GUBER_ETCD_TLS_* var switches the connection to TLS
    # (reference config.go:136-140 anyHasPrefix)
    c.etcd_tls_enabled = any(k.startswith("GUBER_ETCD_TLS_") for k in os.environ)
    c.etcd_tls_cert = _env("GUBER_ETCD_TLS_CERT")
    c.etcd_tls_key = _env("GUBER_ETCD_TLS_KEY")
    c.etcd_tls_ca = _env("GUBER_ETCD_TLS_CA")
    c.etcd_tls_skip_verify = _env("GUBER_ETCD_TLS_SKIP_VERIFY").lower() in (
        "true", "1", "yes")

    # reference config.go:118-133: the two discovery backends are exclusive
    if c.k8s_enabled and c.etcd_enabled:
        raise ValueError("set only one of GUBER_K8S_NAMESPACE or GUBER_ETCD_ENDPOINTS")

    b = c.behaviors
    if _env("GUBER_BATCH_TIMEOUT"):
        b.batch_timeout = float(_env("GUBER_BATCH_TIMEOUT"))
    if _env("GUBER_BATCH_WAIT"):
        b.batch_wait = float(_env("GUBER_BATCH_WAIT"))
    if _env("GUBER_BATCH_LIMIT"):
        b.batch_limit = int(_env("GUBER_BATCH_LIMIT"))
    if _env("GUBER_GLOBAL_SYNC_WAIT"):
        b.global_sync_wait = float(_env("GUBER_GLOBAL_SYNC_WAIT"))
    if _env("GUBER_GLOBAL_TIMEOUT"):
        b.global_timeout = float(_env("GUBER_GLOBAL_TIMEOUT"))
    if _env("GUBER_GLOBAL_BATCH_LIMIT"):
        b.global_batch_limit = int(_env("GUBER_GLOBAL_BATCH_LIMIT"))
    if _env("GUBER_LOCKSTEP_STACK"):
        b.lockstep_stack = int(_env("GUBER_LOCKSTEP_STACK"))
    b.validate()

    e = c.engine
    if _env("GUBER_TPU_CAPACITY_PER_SHARD"):
        e.capacity_per_shard = int(_env("GUBER_TPU_CAPACITY_PER_SHARD"))
    elif c.cache_size:
        # honor the reference knob: spread the requested cache size across
        # the mesh
        e.capacity_per_shard = max(1024, c.cache_size)
    if _env("GUBER_TPU_BATCH_PER_SHARD"):
        e.batch_per_shard = int(_env("GUBER_TPU_BATCH_PER_SHARD"))
    if _env("GUBER_TPU_GLOBAL_CAPACITY"):
        e.global_capacity = int(_env("GUBER_TPU_GLOBAL_CAPACITY"))
    if os.environ.get("GUBER_NATIVE") is not None:
        e.use_native = "auto" if env_bool("GUBER_NATIVE", True) else False
    if _env("GUBER_EXACT_KEYS"):
        e.exact_keys = _env("GUBER_EXACT_KEYS") == "1"
    if _env("GUBER_REPLAY_CAP"):
        e.replay_cap = int(_env("GUBER_REPLAY_CAP"))
    if _env("GUBER_SKIP_GLOBAL"):
        e.skip_global = _env("GUBER_SKIP_GLOBAL") == "1"

    # QoS / overload control (gubernator_tpu/qos/; full list example.conf)
    q = c.qos
    q.enabled = env_bool("GUBER_QOS_ENABLED", q.enabled)
    q.max_pending = env_int("GUBER_QOS_MAX_PENDING", q.max_pending,
                            minimum=0)
    q.default_deadline = env_float("GUBER_QOS_DEFAULT_DEADLINE_MS",
                                   q.default_deadline * 1000.0) / 1000.0
    q.min_window = env_int("GUBER_QOS_MIN_WINDOW", q.min_window)
    q.max_window = env_int("GUBER_QOS_MAX_WINDOW", q.max_window)
    q.target_drain_latency = env_float(
        "GUBER_QOS_TARGET_DRAIN_MS",
        q.target_drain_latency * 1000.0, minimum=1e-3) / 1000.0
    q.aimd_increase = env_float("GUBER_QOS_AIMD_INCREASE", q.aimd_increase,
                                minimum=1.0)
    if _env("GUBER_QOS_AIMD_DECREASE"):
        q.aimd_decrease = float(_env("GUBER_QOS_AIMD_DECREASE"))
    q.fair_slotting = env_bool("GUBER_QOS_FAIR_SLOTTING", q.fair_slotting)
    q.peer_retries = env_int("GUBER_QOS_PEER_RETRIES", q.peer_retries,
                             minimum=0)
    q.retry_base = env_float("GUBER_QOS_RETRY_BASE_MS",
                             q.retry_base * 1000.0, minimum=1.0) / 1000.0
    q.retry_cap = env_float("GUBER_QOS_RETRY_CAP_MS",
                            q.retry_cap * 1000.0, minimum=1.0) / 1000.0
    q.breaker_fail_threshold = env_int("GUBER_QOS_BREAKER_FAILURES",
                                       q.breaker_fail_threshold)
    q.breaker_open_duration = env_float(
        "GUBER_QOS_BREAKER_OPEN_MS",
        q.breaker_open_duration * 1000.0, minimum=1.0) / 1000.0
    q.breaker_half_open_probes = env_int("GUBER_QOS_BREAKER_PROBES",
                                         q.breaker_half_open_probes)
    q.fail_open = env_bool("GUBER_QOS_FAIL_OPEN", q.fail_open)
    q.validate()

    # Self-healing ring (net/health.py + hinted handoff + graceful drain)
    h = c.health
    h.heartbeat_enabled = env_bool("GUBER_HEARTBEAT_ENABLED",
                                   h.heartbeat_enabled)
    h.heartbeat_interval = env_float(
        "GUBER_HEARTBEAT_INTERVAL_MS",
        h.heartbeat_interval * 1000.0, minimum=10.0) / 1000.0
    h.heartbeat_timeout = env_float(
        "GUBER_HEARTBEAT_TIMEOUT_MS",
        h.heartbeat_timeout * 1000.0, minimum=10.0) / 1000.0
    h.suspect_after = env_int("GUBER_HEARTBEAT_SUSPECT", h.suspect_after)
    h.recover_after = env_int("GUBER_HEARTBEAT_RECOVER", h.recover_after)
    h.hint_ttl = env_float("GUBER_HINT_TTL_MS",
                           h.hint_ttl * 1000.0, minimum=0.0) / 1000.0
    h.hint_max = env_int("GUBER_HINT_MAX", h.hint_max, minimum=0)
    h.drain_timeout = env_float("GUBER_DRAIN_TIMEOUT_MS",
                                h.drain_timeout * 1000.0,
                                minimum=0.0) / 1000.0
    h.validate()

    # Traffic analytics + SLO engine: the default_factory fields already
    # read GUBER_ANALYTICS_*/GUBER_SLO_* — rebuild after load_env_file so
    # an env-file sets them too, then validate.
    c.analytics = AnalyticsConfig()
    c.analytics.validate()
    c.slo = SLOConfig()
    c.slo.validate()

    # Tiered key state: rebuild after load_env_file like analytics/slo.
    # The warm tier lives in the Python routing tables (the native router
    # keeps fingerprints, not key strings), so enabling it pins the
    # backend — loudly, because GUBER_NATIVE=1 + GUBER_TIER_WARM>0 would
    # otherwise fail at enable_tiers during boot.
    c.tiers = TierConfig()
    c.tiers.validate()
    if c.tiers.enabled and e.use_native not in (False, "off"):
        import logging
        logging.getLogger("gubernator.config").info(
            "GUBER_TIER_WARM=%d enables the warm tier; forcing the Python "
            "routing backend (use_native=False)", c.tiers.warm_rows)
        e.use_native = False

    return c
