"""Host-side concurrency-lease book.

The CONCURRENCY algorithm's device state is one counter per key (free
slots); the device neither knows nor cares WHO holds the taken slots.  This
book is the host-side shadow that does: grants per (key, client), so that

  * a client that vanishes (gRPC stream torn down before its acquire
    response was delivered, or a forwarding peer the health detector
    declares dead) gets its held slots released back to the device,
  * ring migration can re-register in-flight leases on the new owner
    (state/migrate.py ships the book rows next to the arena rows), and
  * operators can see who is holding what (lease gauges).

The book is intentionally advisory: the device counter is the source of
truth for admission, and every grant carries the bucket's expiry, so a book
that loses rows (process restart without snapshot) self-heals as buckets
expire on-device.  All mutations are O(1) dict operations under one lock —
the book sits on the host decision path, never on the device path.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Dict, Iterable, List, Optional, Tuple


@dataclass
class LeaseGrant:
    """Live slots one client holds on one key."""

    key: str
    client: str
    count: int
    expire: int  # unix ms; mirrors the bucket row's expire column


class LeaseBook:
    """Grants per (key, client) with reverse index per client."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        # key -> client -> [count, expire]
        self._by_key: Dict[str, Dict[str, List[int]]] = {}
        # client -> set of keys (reverse index for release_client)
        self._by_client: Dict[str, set] = {}

    # ------------------------------------------------------------- mutation

    def acquire(self, key: str, client: str, n: int, expire: int) -> None:
        """Record n granted slots; re-arms the grant's expiry (the device
        re-armed the bucket's on the same decision)."""
        if n <= 0:
            return
        with self._lock:
            grants = self._by_key.setdefault(key, {})
            cell = grants.get(client)
            if cell is None:
                grants[client] = [n, expire]
                self._by_client.setdefault(client, set()).add(key)
            else:
                cell[0] += n
                cell[1] = max(cell[1], expire)

    def release(self, key: str, client: str, n: int) -> int:
        """Drop up to n granted slots; returns how many were actually
        held (the device release saturates the same way)."""
        if n <= 0:
            return 0
        with self._lock:
            grants = self._by_key.get(key)
            cell = grants.get(client) if grants else None
            if cell is None:
                return 0
            took = min(n, cell[0])
            cell[0] -= took
            if cell[0] <= 0:
                del grants[client]
                self._unlink(client, key)
                if not grants:
                    del self._by_key[key]
            return took

    def release_client(self, client: str) -> List[Tuple[str, int]]:
        """Drop EVERY grant a client holds (stream close / peer death);
        returns [(key, count)] so the caller can push the matching
        negative-hits releases through the device."""
        with self._lock:
            keys = self._by_client.pop(client, None)
            if not keys:
                return []
            out: List[Tuple[str, int]] = []
            for key in keys:
                grants = self._by_key.get(key)
                cell = grants.pop(client, None) if grants else None
                if cell and cell[0] > 0:
                    out.append((key, cell[0]))
                if grants is not None and not grants:
                    del self._by_key[key]
            return out

    def sweep(self, now: int) -> List[Tuple[str, str, int]]:
        """Drop grants whose expiry passed (the device bucket already
        expired, so there is nothing to release there); returns the dropped
        (key, client, count) rows for the lease gauges."""
        dropped: List[Tuple[str, str, int]] = []
        with self._lock:
            for key in list(self._by_key):
                grants = self._by_key[key]
                for client in list(grants):
                    cnt, exp = grants[client]
                    if exp < now:
                        dropped.append((key, client, cnt))
                        del grants[client]
                        self._unlink(client, key)
                if not grants:
                    del self._by_key[key]
        return dropped

    def _unlink(self, client: str, key: str) -> None:
        keys = self._by_client.get(client)
        if keys is not None:
            keys.discard(key)
            if not keys:
                del self._by_client[client]

    # -------------------------------------------------------------- queries

    def held(self, key: str) -> int:
        with self._lock:
            grants = self._by_key.get(key)
            return sum(c[0] for c in grants.values()) if grants else 0

    def count(self, client: str, key: str) -> int:
        """Slots this client holds on this key (0 if none) — the
        GUBER_LEASE_MAX_PER_CLIENT admission pre-check reads this."""
        with self._lock:
            grants = self._by_key.get(key)
            cell = grants.get(client) if grants else None
            return cell[0] if cell else 0

    def holds(self, client: str, key: Optional[str] = None) -> bool:
        """Does this client hold any grant (on `key`, or anywhere)?  Used
        by QoS: lease holders are exempt from deadline shedding — shedding
        a release would leak the slot until bucket expiry."""
        with self._lock:
            keys = self._by_client.get(client)
            if not keys:
                return False
            return key in keys if key is not None else True

    def stats(self) -> Tuple[int, int, int]:
        """(distinct keys, distinct clients, total held slots)."""
        with self._lock:
            total = sum(c[0] for g in self._by_key.values()
                        for c in g.values())
            return len(self._by_key), len(self._by_client), total

    # --------------------------------------------- snapshot / migration I/O

    def export_rows(self,
                    keys: Optional[Iterable[str]] = None
                    ) -> List[Tuple[str, str, int, int]]:
        """[(key, client, count, expire)]; restricted to `keys` when the
        caller is migrating a shard slice rather than snapshotting."""
        with self._lock:
            if keys is None:
                items = self._by_key.items()
            else:
                want = set(keys)
                items = ((k, g) for k, g in self._by_key.items()
                         if k in want)
            return [(k, client, cell[0], cell[1])
                    for k, grants in items
                    for client, cell in grants.items()]

    def import_rows(self,
                    rows: Iterable[Tuple[str, str, int, int]]) -> int:
        """Merge exported rows (snapshot restore, migration import);
        returns how many rows landed.  Merging is additive on count and
        max on expiry — the same shape as concurrent acquires."""
        n = 0
        for key, client, count, expire in rows:
            if count > 0:
                self.acquire(str(key), str(client), int(count), int(expire))
                n += 1
        return n

    def drop_keys(self, keys: Iterable[str]) -> None:
        """Forget grants for keys handed off to another owner (the
        importing side re-registers them from the shipped rows)."""
        with self._lock:
            for key in set(keys):
                grants = self._by_key.pop(key, None)
                if not grants:
                    continue
                for client in grants:
                    self._unlink(client, key)
