"""Serial per-algorithm oracles in plain python integers.

These mirror ops/kernel.py transition() branch for branch but share no code
with it (only the format constants), so the differential suites compare two
independent derivations of the same reference semantics.  Every function
takes one request against one stored row and returns the new row plus the
response tuple — exactly what a single-lane device window computes.

Shared contracts (carried from the reference, see ops/kernel.py docstring):
  * hits == 0 is a read and never mutates state;
  * an over-ask (hits > available) rejects WITHOUT mutating;
  * rate / emission interval = stored duration // REQUEST limit, clamped
    to >= 1ms where the reference would divide by zero;
  * out-of-range algorithm values fall back to token bucket
    (algorithms.go:100-104).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Tuple

from gubernator_tpu.ops.kernel import (
    CONCURRENCY,
    GCRA,
    LEAKY_BUCKET,
    OVER_LIMIT,
    SLIDING_MAX_LIMIT,
    SLIDING_PACK_BITS,
    SLIDING_WINDOW,
    SLIDING_WEIGHT_Q,
    TOKEN_BUCKET,
    UNDER_LIMIT,
)

ALGORITHM_NAMES = {
    TOKEN_BUCKET: "token_bucket",
    LEAKY_BUCKET: "leaky_bucket",
    GCRA: "gcra",
    SLIDING_WINDOW: "sliding_window",
    CONCURRENCY: "concurrency",
}


@dataclass
class Row:
    """One arena row (the SoA columns of a single slot)."""

    limit: int
    duration: int
    remaining: int
    tstamp: int
    expire: int
    algo: int


# response: (status, limit, remaining, reset_time)
Resp = Tuple[int, int, int, int]


def _init(hits: int, limit: int, duration: int, algo: int,
          now: int) -> Tuple[Row, Resp]:
    """Cache-miss path: algorithms.go:68-84 / :161-185 plus the three new
    stored shapes.  The default image is the token one, so out-of-range
    algorithms degrade to token here too."""
    rate_q = max(duration // max(limit, 1), 1)
    sl_l0 = min(limit, SLIDING_MAX_LIMIT)
    eff = sl_l0 if algo == SLIDING_WINDOW else limit
    conc_rel0 = algo == CONCURRENCY and hits < 0
    over = hits > eff and not conc_rel0
    if conc_rel0:
        resp_r = eff  # release with nothing held: full bucket
    elif over:
        resp_r = 0
    else:
        resp_r = eff - hits
    if algo in (LEAKY_BUCKET, SLIDING_WINDOW, CONCURRENCY):
        tstamp = now
    elif algo == GCRA:
        tstamp = now + duration if over else now + hits * rate_q
    else:
        tstamp = now + duration
    if algo == SLIDING_WINDOW:
        store_r = sl_l0 if over else max(hits, 0)
    else:
        store_r = resp_r
    if algo in (LEAKY_BUCKET, CONCURRENCY):
        reset = 0
    elif algo == GCRA:
        reset = now + rate_q if over else now + hits * rate_q
    else:
        reset = now + duration
    row = Row(limit=limit, duration=duration, remaining=store_r,
              tstamp=tstamp, expire=now + duration, algo=algo)
    status = OVER_LIMIT if over else UNDER_LIMIT
    return row, (status, limit, resp_r, reset)


def _token_hit(row: Row, h: int, now: int) -> Tuple[Row, Resp]:
    R = row.remaining
    if R == 0:
        return row, (OVER_LIMIT, row.limit, 0, row.tstamp)
    if h == 0:
        return row, (UNDER_LIMIT, row.limit, R, row.tstamp)
    if h == R:
        row.remaining = 0
        return row, (UNDER_LIMIT, row.limit, 0, row.tstamp)
    if h > R:
        return row, (OVER_LIMIT, row.limit, R, row.tstamp)
    row.remaining = R - h
    return row, (UNDER_LIMIT, row.limit, R - h, row.tstamp)


def _leaky_hit(row: Row, h: int, req_limit: int, req_duration: int,
               now: int) -> Tuple[Row, Resp]:
    rate = max(row.duration // max(req_limit, 1), 1)
    leak = (now - row.tstamp) // rate
    R2 = row.remaining + min(leak, row.limit - row.remaining)
    row.remaining = R2
    if h != 0:
        row.tstamp = now
    if R2 == 0:
        return row, (OVER_LIMIT, row.limit, 0, now + rate)
    if h == R2:
        row.remaining = 0
        return row, (UNDER_LIMIT, row.limit, 0, 0)
    if h > R2:
        return row, (OVER_LIMIT, row.limit, R2, now + rate)
    if h == 0:
        return row, (UNDER_LIMIT, row.limit, R2, 0)
    row.remaining = R2 - h
    row.expire = now + req_duration
    return row, (UNDER_LIMIT, row.limit, R2 - h, 0)


def _gcra_hit(row: Row, h: int, req_limit: int,
              now: int) -> Tuple[Row, Resp]:
    rate = max(row.duration // max(req_limit, 1), 1)
    base = max(row.tstamp, now)
    cap = min(max((now + row.duration - base) // rate, 0), row.limit)
    if cap == 0:
        return row, (OVER_LIMIT, row.limit, 0, now + rate)
    if h == 0:
        return row, (UNDER_LIMIT, row.limit, cap, base)
    if h > cap:
        return row, (OVER_LIMIT, row.limit, cap, now + rate)
    row.tstamp = base + h * rate
    return row, (UNDER_LIMIT, row.limit, cap - h, row.tstamp)


def sliding_roll(R: int, T: int, D: int, L: int,
                 now: int) -> Tuple[int, int, int, int, int]:
    """Advance a packed sliding register to the window containing `now`.
    Mirrors kernel._sliding_roll; returns (prev, cur, window_start,
    weighted_estimate, effective_limit)."""
    sl_l = min(L, SLIDING_MAX_LIMIT)
    cur = R & SLIDING_MAX_LIMIT
    prev = (R >> SLIDING_PACK_BITS) & SLIDING_MAX_LIMIT
    max_d = max(D, 1)
    k = max((now - T) // max_d, 0)
    if k == 0:
        prev1, cur1 = prev, cur
    elif k == 1:
        prev1, cur1 = cur, 0
    else:
        prev1, cur1 = 0, 0
    ws = T + k * max_d
    q = SLIDING_WEIGHT_Q
    off = min(max(now - ws, 0), max_d)
    if max_d <= q:
        pos_q = (off * q) // max_d
    else:
        pos_q = min(off // max(max_d // q, 1), q)
    pos_q = min(max(pos_q, 0), q)
    est = (prev1 * (q - pos_q)) // q + cur1
    return prev1, cur1, ws, est, sl_l


def _sliding_hit(row: Row, h: int, req_duration: int,
                 now: int) -> Tuple[Row, Resp]:
    prev, cur, ws, est, sl_l = sliding_roll(
        row.remaining, row.tstamp, row.duration, row.limit, now)
    # the roll commits on every branch (idempotent, like leaky's leak)
    row.tstamp = ws
    reset = ws + max(row.duration, 1)
    if est >= sl_l:
        row.remaining = cur | (prev << SLIDING_PACK_BITS)
        return row, (OVER_LIMIT, row.limit, 0, reset)
    if h == 0:
        row.remaining = cur | (prev << SLIDING_PACK_BITS)
        return row, (UNDER_LIMIT, row.limit, sl_l - est, reset)
    if est + h > sl_l:
        row.remaining = cur | (prev << SLIDING_PACK_BITS)
        return row, (OVER_LIMIT, row.limit, sl_l - est, reset)
    cur += h
    row.remaining = cur | (prev << SLIDING_PACK_BITS)
    row.expire = now + req_duration
    return row, (UNDER_LIMIT, row.limit, sl_l - est - h, reset)


def _conc_hit(row: Row, h: int, req_duration: int,
              now: int) -> Tuple[Row, Resp]:
    R = row.remaining
    if h < 0:
        R2 = R + min(-h, row.limit - R)  # saturate toward the limit
        row.remaining = R2
        row.tstamp = now
        row.expire = now + req_duration
        return row, (UNDER_LIMIT, row.limit, R2, 0)
    if R == 0:
        return row, (OVER_LIMIT, row.limit, 0, 0)
    if h == 0:
        return row, (UNDER_LIMIT, row.limit, R, 0)
    if h > R:
        return row, (OVER_LIMIT, row.limit, R, 0)
    row.remaining = R - h
    row.tstamp = now
    row.expire = now + req_duration
    return row, (UNDER_LIMIT, row.limit, R - h, 0)


def apply(row: Optional[Row], hits: int, limit: int, duration: int,
          algo: int, now: int) -> Tuple[Row, Resp]:
    """One request against one row; `row` is None on a cache miss.  An
    expired row or a stored-algorithm mismatch re-inits, matching the
    device's fresh-lane rule (`expire < now` in window_prep; algo switch
    in window_math)."""
    if row is None or row.expire < now or row.algo != algo:
        return _init(hits, limit, duration, algo, now)
    if algo == LEAKY_BUCKET:
        return _leaky_hit(row, hits, limit, duration, now)
    if algo == GCRA:
        return _gcra_hit(row, hits, limit, now)
    if algo == SLIDING_WINDOW:
        return _sliding_hit(row, hits, duration, now)
    if algo == CONCURRENCY:
        return _conc_hit(row, hits, duration, now)
    return _token_hit(row, hits, now)
