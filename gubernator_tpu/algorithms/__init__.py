"""Algorithm plane: the per-algorithm ladders layered over the fused drain.

The wire `algorithm` enum (api/types.py) carries five values; all of them
lower to the ONE shared transition ladder in ops/kernel.py, which every
lowering (int64 oracle, compact32-XLA, per-window Pallas, fused megakernel)
vmaps over.  This package holds what lives ABOVE the kernels:

  * oracles.py — pure-python serial references for all five algorithms,
    mirroring the device ladders branch for branch.  The differential test
    suites (tests/test_fold_fuzz.py, tests/test_algorithms.py) hold every
    lowering bit-exact against these.
  * leases.py — the host-side concurrency-lease book: who holds how many
    slots of which key, so stream-close and peer-death can release held
    slots and ring migration can re-register them.

Algorithm values (proto-compatible; 0/1 match the reference exactly):

  0 TOKEN_BUCKET    refill-on-expiry counter (algorithms.go:24-85)
  1 LEAKY_BUCKET    continuous leak (algorithms.go:88-186)
  2 GCRA            virtual-scheduling TAT arithmetic on the timestamp
                    column; emission interval = stored duration // request
                    limit (the same quirk as leaky's rate)
  3 SLIDING_WINDOW  weighted two-bucket interpolation; both counters pack
                    into the 15-bit halves of the remaining column
  4 CONCURRENCY     lease acquire/release; negative hits releases held
                    slots, remaining counts FREE slots

Out-of-range values degrade to TOKEN_BUCKET on-device, mirroring the
reference fallback (algorithms.go:100-104).
"""

from gubernator_tpu.algorithms.leases import LeaseBook, LeaseGrant
from gubernator_tpu.algorithms.oracles import ALGORITHM_NAMES, Row, apply

__all__ = ["ALGORITHM_NAMES", "LeaseBook", "LeaseGrant", "Row", "apply"]
