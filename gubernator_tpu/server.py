"""gRPC server hosting an Instance (V1 + PeersV1 services).

The reference takes a caller-owned *grpc.Server (config.go:30-31) and
registers onto it (gubernator.go:66-67); here the server wrapper owns a
grpc.aio server bound to one address, with per-RPC metrics equivalent to the
reference's stats-handler pipeline (prometheus.go:104-145).

The RPC bodies live in module-level serve_* functions taking (instance,
payload, context) so the frontdoor engine consumer (frontdoor.py) runs
LITERALLY the same code for records arriving over the shm ring as the
in-process servicers run for direct connections — byte-identical responses
in both serving modes by construction, not by parallel implementation.
"""

from __future__ import annotations

import asyncio
import time
from typing import Optional

import grpc

from gubernator_tpu.api import pb
from gubernator_tpu.api.grpc_api import add_peers_servicer, add_v1_servicer
from gubernator_tpu.api.types import Algorithm as _Algorithm
from gubernator_tpu.core.service import BatchTooLargeError, Instance
from gubernator_tpu.observability.tracing import TRACEPARENT

# Only RPCs at least this large take the native pipeline RPC lane; smaller
# ones go through the per-item path, whose requests aggregate with
# everything else pending in the next pipeline drain anyway (the reference's
# BATCHING default, peers.go:143-172).  ~32B/item on the wire, so this is
# roughly a 64-item batch.
FASTPATH_MIN_BYTES = 2048


def _client_id_from(context) -> Optional[str]:
    """Caller identity for the concurrency-lease book: the transport-level
    source ADDRESS (ports are ephemeral per connection, so identity sticks
    across reconnects; a forwarding peer's grants attribute to its host)."""
    peer = getattr(context, "peer", None)
    if not callable(peer):
        return None
    try:
        p = peer()
    except Exception:
        return None
    if not p:
        return None
    if p.startswith(("ipv4:", "ipv6:")):
        p = p.split(":", 1)[1].rsplit(":", 1)[0]
    return p or None


def _arm_lease_stream_close(inst: Instance, context,
                            client_id: Optional[str]) -> None:
    """Release a client's concurrency leases when its RPC is torn down
    before the response is delivered (gRPC cancel = the stream closed
    under us): the grants this RPC made never reached the holder, and a
    vanished holder cannot release them itself."""
    if client_id is None:
        return
    lease_conf = getattr(inst.conf, "leases", None)
    if lease_conf is not None and not lease_conf.release_on_stream_close:
        return
    add_cb = getattr(context, "add_done_callback", None)
    if not callable(add_cb):
        return
    loop = asyncio.get_running_loop()

    def _on_done(ctx, cid=client_id, loop=loop):
        cancelled = getattr(ctx, "cancelled", None)
        try:
            was = cancelled() if callable(cancelled) else False
        except Exception:
            was = False
        if was and inst.leases.holds(cid):
            loop.call_soon_threadsafe(
                lambda: loop.create_task(
                    inst.release_client_leases(cid,
                                               reason="stream_close")))

    try:
        add_cb(_on_done)
    except Exception:
        pass


def _traceparent_from(context) -> Optional[str]:
    """The caller's `traceparent` invocation-metadata entry, if any (the
    gRPC leg of W3C trace propagation — net/peers.py sets it)."""
    try:
        for k, v in context.invocation_metadata() or ():
            if k == TRACEPARENT:
                return v
    except Exception:
        return None
    return None


async def serve_get_rate_limits(inst: Instance, data: bytes,
                                context) -> bytes:
    """V1.GetRateLimits engine-side body: bytes in, response bytes out.
    `context` only needs time_remaining() and abort() (which must raise) —
    satisfied by both grpc.aio contexts and the frontdoor shim."""
    kind, val = await serve_get_rate_limits_inner(inst, data, context)
    if kind == "bytes":
        return val
    return pb.GetRateLimitsResp(
        responses=[pb.resp_to_pb(r) for r in val]).SerializeToString()


async def serve_get_rate_limits_inner(inst: Instance, data: bytes, context):
    """GetRateLimits body WITHOUT the final serialization: returns
    ("bytes", out) when the native RPC lane already encoded, or
    ("resps", [RateLimitResp]) from the Python path.  The frontdoor hub
    uses this directly so the response direction has ONE code path — it
    ships decision columns to the worker (which encodes in its own
    process) instead of serializing on the engine loop; the in-process
    server wraps it with the classic engine-side serialize above."""
    m = inst.metrics
    start = time.monotonic()
    # QoS: propagate the client's gRPC deadline into admission control,
    # and BYPASS the bytes-level native lane while the admission queue
    # is saturated — sheds must be decided per item on the Python path
    # so the response carries shed_reason metadata in-band
    qos_saturated = (inst.qos is not None
                     and inst.qos.admission.saturated)
    if (not inst.mesh_mode and not qos_saturated
            and len(data) >= FASTPATH_MIN_BYTES):
        # native RPC lane: C parse -> stacked compact dispatch -> C
        # encode (core/pipeline.py).  In cluster mode the C parser
        # classifies items per key against the installed ring and
        # forwards non-owned items to their peers; the drain re-checks
        # the gate on the engine thread, so a membership change that
        # races this RPC falls back to the full path below instead of
        # deciding keys this node does not own
        out = await inst.batcher.submit_rpc(data)
        if out is not None:
            m.observe_rpc("/pb.gubernator.V1/GetRateLimits", start,
                          ok=True)
            return "bytes", out
    try:
        request = pb.GetRateLimitsReq.FromString(data)
    except Exception:
        m.observe_rpc("/pb.gubernator.V1/GetRateLimits", start, ok=False)
        await context.abort(grpc.StatusCode.INVALID_ARGUMENT,
                            "malformed GetRateLimitsReq")
    deadline = None
    if inst.qos is not None:
        remaining = None
        tr = getattr(context, "time_remaining", None)
        if callable(tr):
            remaining = tr()
        deadline = inst.qos.deadline_from_timeout(remaining)
    reqs = [pb.req_from_pb(r) for r in request.requests]
    client_id = _client_id_from(context)
    if any(r.algorithm == _Algorithm.CONCURRENCY for r in reqs):
        _arm_lease_stream_close(inst, context, client_id)
    try:
        resps = await inst.get_rate_limits(
            reqs, deadline=deadline, client_id=client_id)
    except BatchTooLargeError as e:
        m.observe_rpc("/pb.gubernator.V1/GetRateLimits", start, ok=False)
        await context.abort(grpc.StatusCode.OUT_OF_RANGE, str(e))
    m.observe_rpc("/pb.gubernator.V1/GetRateLimits", start, ok=True)
    return "resps", resps


async def serve_peer_rate_limits(inst: Instance, data: bytes,
                                 context) -> bytes:
    """PeersV1.GetPeerRateLimits engine-side body."""
    m = inst.metrics
    start = time.monotonic()
    if not inst.mesh_mode:
        # authoritative relay through the native lane: identical wire
        # shape to GetRateLimits, ring ignored (we are the owner for
        # whatever arrives, gubernator.go:210-227)
        out = await inst.batcher.submit_rpc(data, peer_mode=True)
        if out is not None:
            m.observe_rpc("/pb.gubernator.PeersV1/GetPeerRateLimits",
                          start, ok=True)
            return out
    try:
        request = pb.GetPeerRateLimitsReq.FromString(data)
    except Exception:
        m.observe_rpc("/pb.gubernator.PeersV1/GetPeerRateLimits", start,
                      ok=False)
        await context.abort(grpc.StatusCode.INVALID_ARGUMENT,
                            "malformed GetPeerRateLimitsReq")
    try:
        resps = await inst.get_peer_rate_limits(
            [pb.req_from_pb(r) for r in request.requests],
            client_id=_client_id_from(context))
    except BatchTooLargeError as e:
        m.observe_rpc("/pb.gubernator.PeersV1/GetPeerRateLimits", start,
                      ok=False)
        await context.abort(grpc.StatusCode.OUT_OF_RANGE, str(e))
    m.observe_rpc("/pb.gubernator.PeersV1/GetPeerRateLimits", start, ok=True)
    return pb.GetPeerRateLimitsResp(
        rate_limits=[pb.resp_to_pb(r) for r in resps]).SerializeToString()


async def serve_transfer_buckets(inst: Instance, data: bytes,
                                 context) -> bytes:
    """Bucket-migration import lane (state/migrate.py): bytes in
    (versioned JSON rows), ack bytes out."""
    from gubernator_tpu.state.migrate import MigrationError
    start = time.monotonic()
    m = inst.metrics
    try:
        ack = await inst.transfer_buckets(data)
    except MigrationError as e:
        m.observe_rpc("/pb.gubernator.PeersV1/TransferBuckets", start,
                      ok=False)
        await context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(e))
    except Exception as e:
        m.observe_rpc("/pb.gubernator.PeersV1/TransferBuckets", start,
                      ok=False)
        await context.abort(grpc.StatusCode.FAILED_PRECONDITION, str(e))
    m.observe_rpc("/pb.gubernator.PeersV1/TransferBuckets", start,
                  ok=True)
    return ack


async def serve_register_globals(inst: Instance, request,
                                 context) -> "pb.RegisterGlobalsResp":
    start = time.monotonic()
    m = inst.metrics
    specs = [(s.key, s.limit, s.duration, int(s.algorithm))
             for s in request.specs]
    try:
        await inst.register_globals(specs)
    except Exception as e:
        m.observe_rpc("/pb.gubernator.PeersV1/RegisterGlobals", start,
                      ok=False)
        await context.abort(grpc.StatusCode.FAILED_PRECONDITION, str(e))
    m.observe_rpc("/pb.gubernator.PeersV1/RegisterGlobals", start,
                  ok=True)
    return pb.RegisterGlobalsResp()


async def serve_apply_global_registration(
        inst: Instance, request,
        context) -> "pb.ApplyGlobalRegistrationResp":
    start = time.monotonic()
    m = inst.metrics
    specs = [(s.key, s.limit, s.duration, int(s.algorithm))
             for s in request.specs]
    try:
        await inst.apply_global_registration(
            specs, request.now, request.activate)
    except Exception as e:
        m.observe_rpc("/pb.gubernator.PeersV1/ApplyGlobalRegistration",
                      start, ok=False)
        await context.abort(grpc.StatusCode.FAILED_PRECONDITION, str(e))
    m.observe_rpc("/pb.gubernator.PeersV1/ApplyGlobalRegistration",
                  start, ok=True)
    return pb.ApplyGlobalRegistrationResp()


async def serve_update_peer_globals(inst: Instance, request,
                                    context) -> "pb.UpdatePeerGlobalsResp":
    from gubernator_tpu.api.types import UpdatePeerGlobal
    start = time.monotonic()
    ups = [
        UpdatePeerGlobal(
            key=g.key,
            status=pb.resp_from_pb(g.status),
            algorithm=g.algorithm,
            duration=g.duration,
        )
        for g in request.globals
    ]
    await inst.update_peer_globals(ups)
    inst.metrics.observe_rpc(
        "/pb.gubernator.PeersV1/UpdatePeerGlobals", start, ok=True)
    return pb.UpdatePeerGlobalsResp()


class _V1Servicer:
    def __init__(self, instance: Instance):
        self.instance = instance

    async def GetRateLimits(self, data: bytes, context):
        tracer = self.instance.tracer
        if tracer is None or not tracer.enabled:
            return await serve_get_rate_limits(self.instance, data, context)
        with tracer.start_trace("rpc", _traceparent_from(context)):
            return await serve_get_rate_limits(self.instance, data, context)

    async def HealthCheck(self, request, context):
        # the reference's stats-handler observes EVERY RPC, HealthCheck
        # included (prometheus.go:104-137)
        start = time.monotonic()
        h = await self.instance.health_check()
        self.instance.metrics.observe_rpc(
            "/pb.gubernator.V1/HealthCheck", start, ok=True)
        return pb.HealthCheckResp(
            status=h.status, message=h.message, peer_count=h.peer_count)


class _PeersServicer:
    def __init__(self, instance: Instance):
        self.instance = instance

    async def GetPeerRateLimits(self, data: bytes, context):
        # owner-side root of a forwarded request: the traceparent metadata
        # the forwarding node attached stitches this node's spans into the
        # SAME trace (one trace across owner and non-owner)
        tracer = self.instance.tracer
        if tracer is None or not tracer.enabled:
            return await serve_peer_rate_limits(self.instance, data, context)
        with tracer.start_trace("peer_rpc", _traceparent_from(context)):
            return await serve_peer_rate_limits(self.instance, data, context)

    async def TransferBuckets(self, data: bytes, context):
        return await serve_transfer_buckets(self.instance, data, context)

    async def RegisterGlobals(self, request, context):
        return await serve_register_globals(self.instance, request, context)

    async def ApplyGlobalRegistration(self, request, context):
        return await serve_apply_global_registration(
            self.instance, request, context)

    async def UpdatePeerGlobals(self, request, context):
        return await serve_update_peer_globals(
            self.instance, request, context)


class GrpcServer:
    def __init__(self, instance: Instance, address: str,
                 max_message_mb: int = 1,
                 reuse_port: Optional[bool] = None):
        self.instance = instance
        # 1MB max receive, like the reference (cmd/gubernator/main.go:59-61)
        options = [
            ("grpc.max_receive_message_length", max_message_mb * 1024 * 1024),
        ]
        if reuse_port is not None:
            # frontdoor workers set this explicitly: True shards one
            # listening port across worker processes (kernel-level accept
            # balancing), False forces distinct per-worker ports
            options.append(("grpc.so_reuseport", 1 if reuse_port else 0))
        self.server = grpc.aio.server(options=options)
        add_v1_servicer(self.server, _V1Servicer(instance))
        add_peers_servicer(self.server, _PeersServicer(instance))
        self.port = self.server.add_insecure_port(address)
        host = address.rsplit(":", 1)[0]
        self.address = f"{host}:{self.port}"

    async def start(self) -> None:
        await self.server.start()

    async def stop(self, grace: Optional[float] = 1.0) -> None:
        await self.server.stop(grace)
