"""Device-time flight recorder: measured kernel attribution for the
serving window.

The kernel census (`scripts/probe_census.py`) counts executed kernels
from the traced jaxpr — a box-independent program property — but cannot
say which kernels own the ~0.15 ms/kernel dispatch wall.  This module is
the measurement side of that reconciliation (ROADMAP item 1):

  * `parse_run_dir` / `load_trace_events` — parse the `trace.json.gz`
    files a `jax.profiler` capture leaves under its run dir (gzip+json,
    dependency-free) into chrome-trace complete events;
  * `self_times` — per-(pid, tid) interval nesting turns the raw events
    into per-kernel SELF time (a fusion nested inside an executable
    wrapper is not double-counted) and attributes each kernel to a
    serving arm by the `guber_*` trace annotations the engine stamps
    around dispatch/fetch/analytics (core/engine.py);
  * `KernelTable` — a rolling fold of those rows, normalized to
    ms/window, joined against the SAME arm classes the census counts;
  * `WindowClock` — the always-on dispatch→fetch-ready clock the
    pipeline feeds per drain (EWMA + `guber_tpu_device_window_ms{arm}`
    histogram; disabled path = one attribute check) with a bounded ring
    of slow-window records carrying trace-ID exemplars, so a p99 window
    links to its stitched trace in `/v1/admin/debug`;
  * `DevprofController` — the `GUBER_DEVPROF=periodic` continuous mode:
    a shedding background thread that re-arms an N-drain capture,
    parses, folds into the rolling table, and discards the trace dir;
  * `build_census_arms` / `measure_census_arms` — the five census arm
    programs as runnable specs, so the census count and the measured
    ms/window for one arm come from the SAME traced program
    (probe_census.py and the tier-1 devprof suite both build from here).

Malformed or empty traces degrade to a logged no-op — a broken capture
must never fail a request or a bench run.
"""

from __future__ import annotations

import gzip
import json
import logging
import os
import shutil
import tempfile
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from gubernator_tpu.config import env_float, env_int

log = logging.getLogger("gubernator.devprof")

# serving-arm vocabulary: the census arm classes (probe_census.py) plus
# the runtime-only buckets the trace annotations distinguish
ARM_DRAIN = "composed_drain"
ARM_ANALYTICS = "composed_analytics"
ARM_FUSED = "fused_window"
ARM_FETCH = "fetch"
ARM_OTHER = "xla_shoulder"

# trace-annotation name -> arm, most specific first (core/engine.py stamps
# these around every dispatch/fetch/analytics call)
ANNOTATION_ARMS: Tuple[Tuple[str, str], ...] = (
    ("guber_analytics", ARM_ANALYTICS),
    ("guber_fetch", ARM_FETCH),
    ("guber_drain", ARM_DRAIN),
    ("guber_window", ARM_FUSED),
)

# host-side scaffolding that must not masquerade as device kernels in the
# measured table (python source events, pjit wrappers, runtime plumbing)
_NOISE_PREFIXES = (
    "$", "PjitFunction", "ParseArguments", "ThreadpoolListener",
    "TfrtCpu", "ThunkExecutor", "XlaModule", "ProgramRegion",
    "RunBackend", "HloModule", "profiler",
)


def _is_noise(name: str) -> bool:
    return name.startswith(_NOISE_PREFIXES)


def _annotation_arm(name: str) -> Optional[str]:
    for prefix, arm in ANNOTATION_ARMS:
        if name.startswith(prefix):
            return arm
    return None


# ------------------------------------------------------------------ parsing


def find_trace_files(run_dir: str) -> List[str]:
    """Every `*.trace.json.gz` under a jax.profiler run dir (the profiler
    nests them under plugins/profile/<timestamp>/<host>.trace.json.gz)."""
    out: List[str] = []
    for root, _dirs, files in os.walk(run_dir):
        for f in files:
            if f.endswith(".trace.json.gz"):
                out.append(os.path.join(root, f))
    return sorted(out)


def load_trace_events(path: str) -> List[dict]:
    """Chrome-trace complete events (ph == "X", positive duration) from
    one trace file; malformed input degrades to a logged empty list."""
    try:
        with gzip.open(path, "rt", encoding="utf-8", errors="replace") as fh:
            data = json.load(fh)
        events = data.get("traceEvents")
        if not isinstance(events, list):
            log.warning("devprof: %s has no traceEvents list", path)
            return []
        return [e for e in events
                if isinstance(e, dict) and e.get("ph") == "X"
                and isinstance(e.get("dur"), (int, float)) and e["dur"] > 0
                and isinstance(e.get("ts"), (int, float))
                and isinstance(e.get("name"), str)]
    except (OSError, ValueError, EOFError) as e:
        log.warning("devprof: unreadable trace %s: %s", path, e)
        return []


def parse_run_dir(run_dir: str) -> List[dict]:
    """All complete events from every trace file under `run_dir` (empty
    and logged when the capture produced nothing parseable)."""
    events: List[dict] = []
    files = find_trace_files(run_dir)
    if not files:
        log.warning("devprof: no trace.json.gz under %s", run_dir)
        return events
    for path in files:
        events.extend(load_trace_events(path))
    return events


def self_times(events: List[dict],
               arm_hint: Optional[str] = None) -> List[Tuple[str, float, str]]:
    """(kernel name, self-time ms, arm) rows from raw trace events.

    Self time = duration minus same-track nested children, so a fusion
    inside an executable wrapper counts once.  Arm attribution: the
    `arm_hint` when the whole capture is arm-scoped (measured census
    probe), else the narrowest `guber_*` annotation interval covering the
    event midpoint — annotations and kernels land on DIFFERENT threads
    (the annotation on the engine thread, the kernel on the runtime's
    executor), and drains serialize on one engine thread, so time-window
    containment is the sound join.  Kernels outside any annotation are
    the XLA shoulders.
    """
    # annotation intervals across every track (ts/dur are microseconds)
    spans: List[Tuple[float, float, str]] = []
    for e in events:
        arm = _annotation_arm(e["name"])
        if arm is not None:
            spans.append((e["ts"], e["ts"] + e["dur"], arm))
    spans.sort(key=lambda s: (s[0], -(s[1] - s[0])))

    def arm_of(mid: float) -> str:
        best = None
        best_len = None
        for s0, s1, arm in spans:
            if s0 > mid:
                break
            if s1 >= mid and (best_len is None or s1 - s0 < best_len):
                best, best_len = arm, s1 - s0
        return best if best is not None else ARM_OTHER

    tracks: Dict[tuple, List[dict]] = {}
    for e in events:
        name = e["name"]
        if _is_noise(name) or _annotation_arm(name) is not None:
            continue
        tracks.setdefault((e.get("pid"), e.get("tid")), []).append(e)

    rows: List[Tuple[str, float, str]] = []

    def flush(done: list) -> None:
        ev = done[2]
        self_us = max(0.0, ev["dur"] - done[1])
        arm = arm_hint or arm_of(ev["ts"] + ev["dur"] / 2.0)
        rows.append((ev["name"], self_us / 1000.0, arm))

    for track in tracks.values():
        track.sort(key=lambda e: (e["ts"], -e["dur"]))
        stack: List[list] = []  # [end_us, child_sum_us, event]
        for e in track:
            ts, dur = e["ts"], e["dur"]
            while stack and stack[-1][0] <= ts:
                flush(stack.pop())
            if stack:
                stack[-1][1] += dur
            stack.append([ts + dur, 0.0, e])
        while stack:
            flush(stack.pop())
    return rows


# -------------------------------------------------------------- kernel table


class KernelTable:
    """Rolling per-kernel attribution: (arm, name) -> {count, total_ms},
    normalized to ms/window by the windows each fold covered.  Keyed by
    arm AND kernel name — XLA emits bare HLO instruction names (fusion.3)
    that repeat across executables, and a name-only key would fold a
    later arm's kernels under whichever arm saw the name first.
    Thread-safe (the continuous controller folds from its own thread
    while the admin plane snapshots)."""

    def __init__(self) -> None:
        self._rows: Dict[Tuple[str, str], dict] = {}
        self._windows = 0.0
        self._folds = 0
        self._lock = threading.Lock()

    def fold(self, events: List[dict], windows: float = 1.0,
             arm_hint: Optional[str] = None) -> int:
        """Fold one parsed capture covering `windows` request windows into
        the table; returns the number of kernel rows folded (0 = the
        capture was empty/malformed — a logged no-op)."""
        rows = self_times(events, arm_hint=arm_hint)
        if not rows:
            log.warning("devprof: capture folded 0 kernel rows "
                        "(empty or unclassifiable trace)")
            return 0
        with self._lock:
            self._windows += max(1.0, float(windows))
            self._folds += 1
            for name, ms, arm in rows:
                row = self._rows.get((arm, name))
                if row is None:
                    row = self._rows[(arm, name)] = {
                        "count": 0, "total_ms": 0.0}
                row["count"] += 1
                row["total_ms"] += ms
        return len(rows)

    def ms_per_window(self) -> Dict[str, float]:
        """Measured ms/window decomposition per arm — the table the
        census's kernels/window is reconciled against."""
        with self._lock:
            if not self._windows:
                return {}
            out: Dict[str, float] = {}
            for (arm, _name), row in self._rows.items():
                out[arm] = out.get(arm, 0.0) + row["total_ms"]
            return {arm: ms / self._windows for arm, ms in out.items()}

    def snapshot(self, top: int = 50) -> dict:
        with self._lock:
            windows = self._windows
            rows = sorted(self._rows.items(),
                          key=lambda kv: -kv[1]["total_ms"])[:top]
            table = [{"kernel": name, "arm": arm, "count": r["count"],
                      "total_ms": round(r["total_ms"], 4),
                      "ms_per_window":
                          round(r["total_ms"] / windows, 5) if windows
                          else 0.0}
                     for (arm, name), r in rows]
            folds = self._folds
        return {"windows": windows, "folds": folds, "rows": table,
                "ms_per_window": {a: round(v, 5)
                                  for a, v in self.ms_per_window().items()}}


# -------------------------------------------------------------- window clock


class WindowClock:
    """Always-on per-executable window clock: the pipeline feeds one
    dispatch→fetch-ready observation per drain, keyed by the executable
    arm (fused_window / composed_drain / composed_analytics).  Keeps a
    per-arm EWMA, feeds the `guber_tpu_device_window_ms{arm}` histogram,
    and records slow windows into a bounded ring WITH the trace-ID
    exemplars of the requests that rode them — the p99 link back to a
    stitched trace."""

    ALPHA = 0.2

    def __init__(self, metrics=None, ring: Optional[int] = None,
                 slow_ms: Optional[float] = None) -> None:
        self.metrics = metrics
        self.slow_ms = (env_float("GUBER_DEVPROF_SLOW_MS", 50.0)
                        if slow_ms is None else float(slow_ms))
        n = env_int("GUBER_DEVPROF_RING", 64) if ring is None else int(ring)
        self._slow: List[dict] = []
        self._slow_cap = max(1, n)
        self._ewma: Dict[str, float] = {}
        self._count: Dict[str, int] = {}
        self._lock = threading.Lock()

    def observe(self, arm: str, seconds: float,
                trace_ids: Optional[Callable[[], List[str]]] = None,
                windows: int = 1) -> bool:
        """One drain's dispatch→fetch-ready duration.  `trace_ids` is a
        thunk evaluated ONLY when the window is slow (the fast path never
        walks the job list).  Returns True when the window was recorded as
        a slow exemplar."""
        ms = max(0.0, seconds) * 1000.0
        m = self.metrics
        if m is not None:
            m.device_window_ms.labels(arm=arm).observe(ms)
        with self._lock:
            prev = self._ewma.get(arm)
            ew = ms if prev is None else prev + self.ALPHA * (ms - prev)
            self._ewma[arm] = ew
            self._count[arm] = self._count.get(arm, 0) + 1
        if m is not None:
            m.device_window_ewma.labels(arm=arm).set(ew)
        # slow = past the absolute floor AND well past this arm's norm
        if ms < self.slow_ms or ms < 3.0 * ew:
            return False
        rec = {"arm": arm, "ms": round(ms, 3), "windows": windows,
               "at": time.time(),
               "trace_ids": (trace_ids() if trace_ids is not None else [])}
        with self._lock:
            self._slow.append(rec)
            if len(self._slow) > self._slow_cap:
                del self._slow[0]
        return True

    def snapshot(self) -> dict:
        with self._lock:
            arms = {arm: {"ewma_ms": round(ew, 4),
                          "count": self._count.get(arm, 0)}
                    for arm, ew in self._ewma.items()}
            slow = list(self._slow[-16:])
        return {"arms": arms, "slow_windows": slow}


# ------------------------------------------------------- continuous profiling


class DevprofController:
    """`GUBER_DEVPROF=periodic`: every `interval` seconds, arm an N-drain
    `jax.profiler` capture through the instance's ProfileCapture, wait for
    it to complete, parse + fold the trace into the rolling KernelTable,
    and delete the trace dir.  Sheds (skips the cycle, counted) whenever a
    capture is already in flight — an operator-armed capture always wins —
    and cancels a capture the traffic never completed."""

    def __init__(self, profile, table: KernelTable,
                 interval: Optional[float] = None,
                 drains: Optional[int] = None,
                 metrics=None,
                 windows_fn: Optional[Callable[[], int]] = None) -> None:
        self.profile = profile
        self.table = table
        self.metrics = metrics
        self.interval = (env_float("GUBER_DEVPROF_INTERVAL_S", 30.0,
                                   minimum=0.05)
                         if interval is None else max(0.05, float(interval)))
        self.drains = (env_int("GUBER_DEVPROF_DRAINS", 8)
                       if drains is None else max(1, int(drains)))
        self.windows_fn = windows_fn
        self.cycles = 0
        self.sheds = 0
        self.kernel_rows = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._tmp: Optional[str] = None

    # split out so tests drive one deterministic cycle without the thread
    def run_once(self, capture_timeout: Optional[float] = None) -> bool:
        if self.profile is None or self.profile.armed:
            self.sheds += 1
            self._count("shed")
            return False
        tmp = self._tmp = tempfile.mkdtemp(prefix="guber-devprof-")
        try:
            w0 = self.windows_fn() if self.windows_fn is not None else 0
            out = self.profile.arm(self.drains, tmp)
            if not out.get("armed"):
                self.sheds += 1
                self._count("shed")
                return False
            budget = (self.interval if capture_timeout is None
                      else capture_timeout)
            deadline = time.monotonic() + budget
            while (self.profile.armed and time.monotonic() < deadline
                   and not self._stop.is_set()):
                time.sleep(0.02)
            if self.profile.armed:
                # traffic too idle to complete N drains inside the budget:
                # stop the capture and fold whatever it caught
                self.profile.cancel()
            # `armed` flips False BEFORE jax.profiler.stop_trace finishes
            # dumping (the engine thread drops the lock first), so wait
            # for the trace files to land before parsing the dir
            settle = time.monotonic() + 5.0
            while (not find_trace_files(tmp)
                   and time.monotonic() < settle
                   and not self._stop.is_set()):
                time.sleep(0.05)
            if find_trace_files(tmp):
                time.sleep(0.1)  # let the in-flight dump finish its write
            w1 = self.windows_fn() if self.windows_fn is not None else 0
            windows = max(1, w1 - w0) if self.windows_fn else self.drains
            events = parse_run_dir(tmp)
            folded = self.table.fold(events, windows=windows)
            self.kernel_rows += folded
            self.cycles += 1
            self._count("folded" if folded else "empty")
            return folded > 0
        finally:
            shutil.rmtree(tmp, ignore_errors=True)
            self._tmp = None

    def _count(self, status: str) -> None:
        if self.metrics is not None:
            self.metrics.devprof_captures.labels(status=status).inc()

    def _loop(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                self.run_once()
            except Exception:  # noqa: BLE001 — profiling never kills serving
                log.exception("devprof: periodic capture cycle failed")

    def start(self) -> None:
        if self._thread is None:
            self._thread = threading.Thread(target=self._loop,
                                            name="guber-devprof", daemon=True)
            self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None
        # if the join timed out mid-cycle (stop_trace can block past it),
        # the thread's finally never ran — reap its capture dir here so a
        # shutdown never strands trace output on disk
        tmp = self._tmp
        if tmp is not None:
            shutil.rmtree(tmp, ignore_errors=True)
            self._tmp = None

    def status(self) -> dict:
        return {"interval_s": self.interval, "drains": self.drains,
                "cycles": self.cycles, "sheds": self.sheds,
                "kernel_rows": self.kernel_rows,
                "running": self._thread is not None}


class Devprof:
    """Instance-level facade: the rolling kernel table, the pipeline's
    window clock (wired by core/service.py), and the optional continuous
    controller."""

    def __init__(self, mode: str = "", metrics=None, profile=None,
                 windows_fn: Optional[Callable[[], int]] = None,
                 interval: Optional[float] = None,
                 drains: Optional[int] = None) -> None:
        self.mode = mode or "off"
        self.table = KernelTable()
        self.clock: Optional[WindowClock] = None
        self.controller: Optional[DevprofController] = None
        if mode == "periodic" and profile is not None:
            self.controller = DevprofController(
                profile, self.table, interval=interval, drains=drains,
                metrics=metrics, windows_fn=windows_fn)

    def start(self) -> None:
        if self.controller is not None:
            self.controller.start()

    def close(self) -> None:
        if self.controller is not None:
            self.controller.stop()

    def status(self) -> dict:
        snap = self.table.snapshot(top=0)
        out = {"mode": self.mode,
               "table": {"windows": snap["windows"],
                         "folds": snap["folds"],
                         "ms_per_window": snap["ms_per_window"]}}
        if self.clock is not None:
            out["clock"] = self.clock.snapshot()
        if self.controller is not None:
            out["controller"] = self.controller.status()
        return out

    def kernels_snapshot(self, census: Optional[dict] = None,
                         top: int = 50) -> dict:
        """The `/v1/admin/kernels` payload: census count × measured ms
        side-by-side per arm, plus the rolling kernel table and the
        window clock."""
        table = self.table.snapshot(top=top)
        measured = table["ms_per_window"]
        arms = {}
        for arm in sorted(set(list(measured) + list(census or {}))):
            arms[arm] = {
                "census_kernels_per_window":
                    (census or {}).get(arm),
                "measured_ms_per_window": measured.get(arm),
            }
        out = {"arms": arms, "table": table["rows"],
               "windows": table["windows"]}
        if self.clock is not None:
            out["clock"] = self.clock.snapshot()
        if self.controller is not None:
            out["controller"] = self.controller.status()
        return out


# ------------------------------------------------- census arms, measured pass


def build_census_arms(k: int = 8):
    """The serving-arm programs the kernel census counts
    (probe_census.py), as runnable specs over a tiny single-device probe
    engine: [{name, fn, args, windows, measure_fn}].  `fn` is what the
    census traces (identical numbers to the historical probe); the
    measured pass compiles `measure_fn` (only fused_window differs — the
    Pallas megakernel needs interpret mode off-TPU) and runs it under a
    real `jax.profiler` capture."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from gubernator_tpu.config import AnalyticsConfig
    from gubernator_tpu.core import engine as em
    from gubernator_tpu.core.engine import RateLimitEngine
    from gubernator_tpu.ops import kernel, pallas_kernel as pk
    from gubernator_tpu.parallel.mesh import make_mesh

    t0 = 1_700_000_000_000
    mesh = make_mesh(jax.devices()[:1])
    eng = RateLimitEngine(mesh=mesh, capacity_per_shard=256,
                          batch_per_shard=64, global_capacity=32,
                          global_batch_per_shard=8, max_global_updates=8)
    s, b = eng.num_shards, eng.batch_per_shard

    st1 = kernel.BucketState.zeros(eng.capacity_per_shard)
    packed1 = jnp.zeros((b, 2), jnp.int64)

    def xla64(state, packed, now):
        return kernel.window_step(state, kernel.decode_batch(packed), now)

    def c32(state, packed, now):
        st, out = pk.window_step_compact32_xla(
            state, kernel.decode_batch(packed), now)
        return st, kernel.encode_output_word(out, now)

    def fusedw(state, packed, now):
        return pk.window_step_fused(state, packed, now, interpret=False)

    interp = jax.default_backend() != "tpu"

    def fusedw_measure(state, packed, now):
        return pk.window_step_fused(state, packed, now, interpret=interp)

    packed = np.zeros((k, s, b, 2), np.int64)
    nows = np.full(k, t0, np.int64)
    gb, ga, upd = eng.empty_drain_control()
    fdrain = em._compiled_pipeline_step_global_impl(eng.mesh, False, True,
                                                    True, True)
    conf = AnalyticsConfig()
    eng.enable_analytics(conf)
    geom = (conf.sketch_depth, conf.sketch_width, conf.tenant_slots,
            conf.topk, conf.over_weight)
    fan = em._compiled_pipeline_step_global_impl(eng.mesh, False, True, True,
                                                 True, geom)
    ten = np.zeros((k, s, b), np.int32)

    # mixed-algorithm composed window: every wire algorithm (token, leaky,
    # GCRA, sliding-window, concurrency) live in ONE packed window's lanes.
    # The census is data-independent, so this arm traces the SAME program
    # as composed_drain — which is the point the scoreboard makes: the
    # algorithm plane rides the ladder as select-chain depth, not extra
    # kernels.  The measured pass drives real mixed-algorithm lanes
    # through all five transition ladders.
    lane = np.arange(b, dtype=np.int64)
    mix1 = kernel.encode_batch_host(
        lane % eng.capacity_per_shard, np.ones(b, np.int64),
        np.full(b, 100, np.int64), np.full(b, 60_000, np.int64),
        lane % 5, np.zeros(b, np.int64))
    packed_mix = np.broadcast_to(mix1, (k, s, b, 2)).copy()

    one = (st1, packed1, jnp.int64(t0))
    drain_args = (eng.state, eng.gstate, eng.gcfg, packed, gb, ga, upd, nows)
    mix_args = (eng.state, eng.gstate, eng.gcfg, packed_mix, gb, ga, upd,
                nows)
    an_args = drain_args + (eng._an_sketch, ten, jnp.int64(0))
    return [
        {"name": "int64_xla", "fn": xla64, "args": one, "windows": 1,
         "measure_fn": xla64},
        {"name": "compact32_xla", "fn": c32, "args": one, "windows": 1,
         "measure_fn": c32},
        {"name": "fused_window", "fn": fusedw, "args": one, "windows": 1,
         "measure_fn": fusedw_measure},
        {"name": "composed_drain", "fn": fdrain, "args": drain_args,
         "windows": k, "measure_fn": fdrain},
        {"name": "composed_mixed_algos", "fn": fdrain, "args": mix_args,
         "windows": k, "measure_fn": fdrain},
        {"name": "composed_analytics", "fn": fan, "args": an_args,
         "windows": k, "measure_fn": fan},
    ]


def measure_census_arms(arms=None, iters: int = 2,
                        table: Optional[KernelTable] = None) -> dict:
    """Compile each census arm, warm it, run `iters` iterations under an
    arm-scoped `jax.profiler` capture, and parse the trace into measured
    ms/window — the join key is the arm NAME, so every census kernel
    class gets a measured entry from a real parsed trace.  Returns
    {"arms": {name: {...}}, "kernel_table": snapshot} and folds into
    `table` when given (the Instance's rolling table)."""
    import jax

    if arms is None:
        arms = build_census_arms()
    if table is None:
        table = KernelTable()
    measured: Dict[str, dict] = {}
    # arms sharing one body (composed_drain / composed_mixed_algos differ
    # only in data) share one jitted wrapper so the body compiles once
    jits: Dict[int, object] = {}
    for spec in arms:
        name, windows = spec["name"], spec["windows"]
        fn = spec.get("measure_fn") or spec["fn"]
        jf = jits.get(id(fn))
        if jf is None:
            jf = jits[id(fn)] = jax.jit(fn)
        out = jf(*spec["args"])
        jax.block_until_ready(out)
        tmp = tempfile.mkdtemp(prefix=f"guber-measure-{name}-")
        try:
            jax.profiler.start_trace(tmp)
            try:
                for _ in range(max(1, iters)):
                    out = jf(*spec["args"])
                    jax.block_until_ready(out)
            finally:
                jax.profiler.stop_trace()
            events = parse_run_dir(tmp)
            rows = self_times(events, arm_hint=name)
            total_ms = sum(ms for _n, ms, _a in rows)
            table.fold(events, windows=windows * max(1, iters),
                       arm_hint=name)
            measured[name] = {
                "measured_ms_per_window":
                    round(total_ms / (windows * max(1, iters)), 5),
                "kernel_events": len(rows),
            }
        finally:
            shutil.rmtree(tmp, ignore_errors=True)
    return {"arms": measured, "kernel_table": table.snapshot()}


_census_cache: Optional[Dict[str, float]] = None
_census_lock = threading.Lock()


def census_table(refresh: bool = False) -> Dict[str, float]:
    """Per-arm census kernels/window (cached — tracing five arms costs
    seconds, and the census only changes when the program does)."""
    global _census_cache
    with _census_lock:
        if _census_cache is not None and not refresh:
            return _census_cache
        import jax

        from gubernator_tpu.ops import pallas_kernel as pk

        out: Dict[str, float] = {}
        for spec in build_census_arms():
            total = pk.kernel_census(
                jax.make_jaxpr(spec["fn"])(*spec["args"]))
            out[spec["name"]] = round(total / spec["windows"], 1)
        _census_cache = out
        return out
