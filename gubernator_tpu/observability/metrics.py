"""Prometheus metrics with the reference's metric names.

Metric surface parity (SURVEY.md §5):
  cache_size, cache_access_count{type}          reference cache/lru.go:56-59
  async_durations, broadcast_durations          reference global.go:44-51
  grpc_request_counts{status}/{method},
  grpc_request_duration_milliseconds            reference prometheus.go:52-59

Plus TPU-native additions under guber_tpu_*: device window count, window
occupancy, device step duration.
"""

from __future__ import annotations

import math
import threading
import time
from typing import Dict, Optional

from prometheus_client import (
    CollectorRegistry,
    Counter,
    Gauge,
    Histogram,
    generate_latest,
)
from prometheus_client import CONTENT_TYPE_LATEST

# Canonical stage names of the request lifecycle, in pipeline order.
# observability/tracing.py spans, the stage histograms, and the debug
# snapshot all use exactly these labels so dashboards, traces, and the
# `cli debug` table line up column-for-column.
STAGES = (
    "enqueue",          # submit -> appended to the pending window
    "admission_wait",   # time queued before a dispatch takes the request
    "window_fill",      # host-side window build (pack keys, stage cols)
    "device_dispatch",  # engine thread: device step launch through done
    "drain_commit",     # fetch thread: device->host readback + replies
    "peer_forward",     # non-owner hop: peer-lane RPC round trip
    "global_broadcast", # GLOBAL lane: owner's broadcast to all peers
)


class _StageRing:
    """Fixed-size ring of recent stage durations (seconds) behind one
    lock — the rolling-window source for the p50/p95/p99 snapshot.  A
    Prometheus histogram alone can't answer "p99 over the last minute"
    without a scraping sidecar; the ring keeps the last `size` samples so
    the debug endpoint and `cli load` read live quantiles in-process."""

    __slots__ = ("_buf", "_size", "_idx", "_count", "_lock")

    def __init__(self, size: int = 1024):
        self._buf = [0.0] * size
        self._size = size
        self._idx = 0
        self._count = 0
        self._lock = threading.Lock()

    def observe(self, seconds: float) -> None:
        with self._lock:
            self._buf[self._idx] = seconds
            self._idx = (self._idx + 1) % self._size
            if self._count < self._size:
                self._count += 1

    def snapshot(self) -> Optional[dict]:
        with self._lock:
            n = self._count
            if n == 0:
                return None
            samples = sorted(self._buf[:n] if n < self._size
                             else list(self._buf))

        def pct(p: float) -> float:
            return samples[min(n - 1, int(math.ceil(p * n)) - 1)] * 1000.0

        return {
            "count": n,
            "p50_ms": pct(0.50),
            "p95_ms": pct(0.95),
            "p99_ms": pct(0.99),
            "mean_ms": sum(samples) / n * 1000.0,
        }


class Metrics:
    """Per-instance metric registry (instances in one process each get their
    own, like each reference node's prometheus.Registry, main.go:53)."""

    def __init__(self, registry: Optional[CollectorRegistry] = None):
        self.registry = registry or CollectorRegistry()
        self._scrape_hooks = []
        self.cache_size = Gauge(
            "cache_size",
            "Size of the cache which holds the rate limits.",
            registry=self.registry,
        )
        self.cache_access_count = Counter(
            "cache_access_count",
            "Cache access counts.",
            ["type"],
            registry=self.registry,
        )
        self.async_durations = Histogram(
            "async_durations",
            "The duration of GLOBAL async sends in seconds.",
            registry=self.registry,
        )
        self.broadcast_durations = Histogram(
            "broadcast_durations",
            "The duration of GLOBAL broadcasts to peers in seconds.",
            registry=self.registry,
        )
        self.grpc_request_counts = Counter(
            "grpc_request_counts",
            "The count of gRPC requests.",
            ["status", "method"],
            registry=self.registry,
        )
        self.grpc_request_duration = Histogram(
            "grpc_request_duration_milliseconds",
            "The timings of gRPC requests in milliseconds.",
            ["method"],
            registry=self.registry,
        )
        # TPU-native
        self.window_count = Counter(
            "guber_tpu_windows_total",
            "Device windows dispatched.",
            registry=self.registry,
        )
        self.window_occupancy = Histogram(
            "guber_tpu_window_occupancy",
            "Requests per device window.",
            buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1000),
            registry=self.registry,
        )
        # duplicate-run aggregation: decisions served vs lanes staged —
        # rate(decisions)/rate(lanes) is the live fold factor
        self.agg_decisions = Counter(
            "guber_tpu_aggregation_decisions_total",
            "Decisions served by the pipelined drain.",
            registry=self.registry,
        )
        self.agg_lanes = Counter(
            "guber_tpu_aggregation_lanes_total",
            "Device lanes staged by the pipelined drain.",
            registry=self.registry,
        )
        self.window_duration = Histogram(
            "guber_tpu_window_duration_seconds",
            "Wall time of one device window step.",
            registry=self.registry,
        )
        # fused-path adoption + drain depth (core/pipeline.py): how many
        # drains lowered to the fused megakernel, and how many windows deep
        # each drain's K-stack actually ran — rate(fused)/rate(windows) is
        # live adoption, the depth histogram is the decisions-per-dispatch
        # lever the cost model optimizes
        self.fused_drains = Counter(
            "guber_tpu_fused_drains_total",
            "Pipeline drains served by the fused Pallas megakernel.",
            registry=self.registry,
        )
        self.drain_depth = Histogram(
            "guber_tpu_drain_depth_windows",
            "Occupied window depth K per pipeline drain.",
            buckets=(0, 1, 2, 4, 8, 16, 32, 64, 128, 256, 512),
            registry=self.registry,
        )
        # kernel-ladder scoreboard (daemon boot + the devprof admin
        # endpoint): executed-kernel census of the composed serving
        # arm, kernels per window.  A property of the traced program — the
        # same number on every box — so a step in this gauge across a
        # deploy IS a serving-ladder regression (scripts/bench_compare.py
        # gates the same census absolutely)
        self.kernels_per_window = Gauge(
            "guber_tpu_kernels_per_window",
            "Executed-kernel census of the composed serving window "
            "(traced-program property; lower is better).",
            registry=self.registry,
        )
        # overlapped drain pipeline (core/pipeline.py): concurrent drains in
        # flight, the host/device/fetch overlap achieved, and staging arena
        # recycling (core/window_buffers.py) — overlap_ratio is
        # sum(stage busy) / pipeline-active wall, so 1.0 means strictly
        # serial stages and ~depth means perfect overlap
        self.pipeline_inflight_windows = Gauge(
            "guber_tpu_pipeline_inflight_windows",
            "Drain windows currently in flight between dispatch and commit.",
            registry=self.registry,
        )
        self.pipeline_overlap_ratio = Gauge(
            "guber_tpu_pipeline_overlap_ratio",
            "Aggregate stage busy time divided by pipeline-active wall time "
            "(1.0 = serial, >1 = host/device/fetch stages overlapped).",
            registry=self.registry,
        )
        self.window_buffer_reuse = Counter(
            "guber_tpu_window_buffer_reuse_total",
            "Drain staging arena acquisitions by outcome.",
            ["event"],  # reuse | alloc
            registry=self.registry,
        )
        # deferred-fetch dispatch chain (core/pipeline.py): the adaptive
        # stride (drains per stacked D2H fetch), how many dispatched
        # drains currently await the chain's shared fetch, and the fetch
        # round trips the chain has elided altogether
        self.chain_fetch_stride = Gauge(
            "guber_tpu_chain_fetch_stride",
            "Current deferred-fetch chain stride (drains per stacked "
            "fetch; 1 = fetch every drain).",
            registry=self.registry,
        )
        self.chain_inflight_windows = Gauge(
            "guber_tpu_chain_inflight_windows",
            "Dispatched drains currently chained awaiting the shared "
            "stacked fetch.",
            registry=self.registry,
        )
        self.chain_fetch_elided = Counter(
            "guber_tpu_chain_fetch_elided_total",
            "Device-to-host fetch round trips elided by chaining drains "
            "behind one stacked fetch.",
            registry=self.registry,
        )
        # device-time flight recorder (observability/devprof.py): the
        # always-on dispatch->fetch-ready window clock per executable arm
        # (fused_window / composed_drain / composed_analytics), its EWMA,
        # and the continuous-mode capture outcomes
        self.device_window_ms = Histogram(
            "guber_tpu_device_window_ms",
            "Dispatch-to-fetch-ready wall time of one drain window, by "
            "executable arm.",
            ["arm"],
            buckets=(0.25, 0.5, 1, 2.5, 5, 10, 25, 50, 100, 250, 1000),
            registry=self.registry,
        )
        self.device_window_ewma = Gauge(
            "guber_tpu_device_window_ewma_ms",
            "EWMA of the dispatch-to-fetch-ready window time, by "
            "executable arm.",
            ["arm"],
            registry=self.registry,
        )
        self.devprof_captures = Counter(
            "guber_tpu_devprof_captures_total",
            "Continuous-profiling capture cycles by outcome (folded = "
            "parsed into the kernel table; shed = skipped, a capture was "
            "already in flight; empty = trace parsed to nothing).",
            ["status"],  # folded | shed | empty
            registry=self.registry,
        )
        # state lifecycle (state/snapshot.py, state/migrate.py): the slot
        # occupancy gauges come from engine.cache_stats at scrape time
        self.cache_slots = Gauge(
            "guber_tpu_cache_slots",
            "Arena slot occupancy by state.",
            ["state"],  # free | live | expired
            registry=self.registry,
        )
        self.snapshot_duration = Histogram(
            "guber_tpu_snapshot_duration_seconds",
            "Wall time of one arena snapshot (export + serialize + write).",
            registry=self.registry,
        )
        self.snapshot_size = Gauge(
            "guber_tpu_snapshot_bytes",
            "Size of the last written snapshot in bytes.",
            registry=self.registry,
        )
        self.snapshot_total = Counter(
            "guber_tpu_snapshots_total",
            "Snapshot attempts.",
            ["status"],  # success | failed
            registry=self.registry,
        )
        self.restore_age = Gauge(
            "guber_tpu_restore_age_seconds",
            "Age of the snapshot restored at boot (0 when cold-started).",
            registry=self.registry,
        )
        self.migrated_keys = Counter(
            "guber_tpu_migrated_keys_total",
            "Bucket rows shipped or imported by live key migration.",
            ["direction"],  # out | in
            registry=self.registry,
        )
        self.migration_skipped_stale = Counter(
            "guber_tpu_migration_skipped_stale_total",
            "Incoming migrated rows dropped because a fresher local entry "
            "existed.",
            registry=self.registry,
        )
        # tiered key state (state/tiers.py): hot-arena <-> warm-store flow
        self.tier_events = Counter(
            "guber_tpu_tier_events_total",
            "Tiered key-state events by kind: promote/demote row moves, "
            "warm_hit/cold_miss on staging lookups behind a table miss, "
            "warm_evict overflow drops, demote_drop dead-or-expired spills, "
            "demote_stale same-drain victims dropped to cold.",
            ["event"],
            registry=self.registry,
        )
        self.tier_warm_rows = Gauge(
            "guber_tpu_tier_warm_rows",
            "Rows resident in the warm tier.",
            registry=self.registry,
        )
        self.tier_warm_bytes = Gauge(
            "guber_tpu_tier_warm_bytes",
            "Host bytes allocated to the warm tier's SoA store.",
            registry=self.registry,
        )
        # QoS subsystem (gubernator_tpu/qos/): admission queue, sheds by
        # reason, the AIMD window, and per-peer breaker state
        self.qos_queue_depth = Gauge(
            "guber_qos_queue_depth",
            "Pending decisions held in the bounded admission queue.",
            registry=self.registry,
        )
        self.qos_shed = Counter(
            "guber_qos_shed_total",
            "Requests shed by admission control, by reason.",
            ["reason"],  # queue_full | deadline | breaker_open
            registry=self.registry,
        )
        self.qos_effective_window = Gauge(
            "guber_qos_effective_window",
            "Congestion-adaptive window size (decisions per dispatch).",
            registry=self.registry,
        )
        self.qos_drain_latency_ewma = Gauge(
            "guber_qos_drain_latency_ewma_seconds",
            "EWMA of observed drain wall time feeding the AIMD.",
            registry=self.registry,
        )
        self.qos_drain_depth_ewma = Gauge(
            "guber_qos_drain_depth_ewma",
            "EWMA of occupied drain depth feeding the AIMD.",
            registry=self.registry,
        )
        self.breaker_state = Gauge(
            "guber_qos_breaker_state",
            "Per-peer circuit breaker state "
            "(0=closed, 1=half_open, 2=open).",
            ["peer"],
            registry=self.registry,
        )
        self.peer_retries = Counter(
            "guber_qos_peer_retries_total",
            "Peer-lane RPC retries after transient failures.",
            ["peer"],
            registry=self.registry,
        )
        self.fail_open_served = Counter(
            "guber_qos_fail_open_total",
            "Forwards answered locally (non-authoritative) while the "
            "owner's breaker was open.",
            registry=self.registry,
        )
        # self-healing ring (net/health.py + global_sync hinted handoff):
        # what we failed to send, what we buffered instead of dropping,
        # and what the failure detector thinks of each peer
        self.global_send_errors = Counter(
            "global_send_errors_total",
            "Failed per-peer GLOBAL aggregated-hit sends (after the peer "
            "lane's own retries).",
            ["peer"],
            registry=self.registry,
        )
        self.broadcast_errors = Counter(
            "broadcast_errors_total",
            "Failed per-peer GLOBAL owner-broadcast sends.",
            ["peer"],
            registry=self.registry,
        )
        self.hints = Counter(
            "guber_hints_total",
            "Hinted-handoff buffer events, by event "
            "(queued | replayed | expired).",
            ["event", "peer"],
            registry=self.registry,
        )
        self.peer_health_state = Gauge(
            "guber_peer_health_state",
            "Failure-detector verdict per peer (0=up, 1=suspect, 2=down).",
            ["peer"],
            registry=self.registry,
        )
        self.ring_rehomes = Counter(
            "guber_ring_rehomes_total",
            "Automatic ring membership changes driven by the failure "
            "detector, by direction (down | up).",
            ["direction"],
            registry=self.registry,
        )
        # stage-latency decomposition (observability/tracing.py records the
        # same boundaries as spans): per-stage wall time at window/drain
        # granularity, always on — a few µs per window, amortized over up
        # to 1000 decisions
        self.stage_duration = Histogram(
            "guber_tpu_stage_duration_ms",
            "Wall time of one request-lifecycle stage in milliseconds.",
            ["stage"],
            buckets=(0.05, 0.1, 0.25, 0.5, 1, 2.5, 5, 10, 25, 50, 100,
                     250, 500, 1000, 2500),
            registry=self.registry,
        )
        # traffic analytics (ops/analytics.py device reduction +
        # observability/analytics.py host merge): hot keys, per-tenant
        # accounting, device-computed arena occupancy/churn
        self.hot_key_hits = Counter(
            "guber_tpu_hot_key_hits_total",
            "Hits attributed to device-reported hot keys (top-K only; "
            "unresolved slots render as s<shard>:slot<n>).",
            ["key"],
            registry=self.registry,
        )
        self.tenant_decisions = Counter(
            "guber_tpu_tenant_decisions_total",
            "Decisions per fairness tenant, by outcome "
            "(under_limit | over_limit).",
            ["tenant", "outcome"],
            registry=self.registry,
        )
        self.arena_churn = Counter(
            "guber_tpu_arena_churn_total",
            "Bucket initializations seen by the drain reduction (slot "
            "allocations + window resets — the arena's write churn).",
            registry=self.registry,
        )
        self.arena_occupancy = Gauge(
            "guber_tpu_arena_occupancy_slots",
            "Device-computed arena slot occupancy from the last drain's "
            "expiry plane, by state (live | expired).",
            ["state"],
            registry=self.registry,
        )
        # algorithm plane (gubernator_tpu/algorithms/): per-algorithm
        # decision mix, and the host-side concurrency-lease book
        self.algo_decisions = Counter(
            "guber_tpu_decisions_total",
            "Rate-limit decisions served, by algorithm "
            "(token_bucket | leaky_bucket | gcra | sliding_window | "
            "concurrency).",
            ["algorithm"],
            registry=self.registry,
        )
        self.lease_held = Gauge(
            "guber_tpu_lease_held_slots",
            "Concurrency-lease slots currently held across all keys "
            "(host lease book; the device free-slot counters are the "
            "admission truth).",
            registry=self.registry,
        )
        self.lease_clients = Gauge(
            "guber_tpu_lease_clients",
            "Distinct clients holding at least one concurrency lease.",
            registry=self.registry,
        )
        self.lease_keys = Gauge(
            "guber_tpu_lease_keys",
            "Distinct keys with at least one live concurrency lease.",
            registry=self.registry,
        )
        self.lease_releases = Counter(
            "guber_tpu_lease_releases_total",
            "Lease slots released on behalf of clients, by reason "
            "(explicit | stream_close | peer_down | expired).",
            ["reason"],
            registry=self.registry,
        )
        # SLO burn-rate engine (observability/analytics.py SLOEngine)
        self.slo_burn_rate = Gauge(
            "guber_slo_burn_rate",
            "Error-budget burn rate per objective and window "
            "(1.0 = burning exactly the budget).",
            ["slo", "window"],
            registry=self.registry,
        )
        self.slo_firing = Gauge(
            "guber_slo_firing",
            "Multi-window burn-rate alert state per objective "
            "(1 = firing).",
            ["slo"],
            registry=self.registry,
        )
        # multi-process front door (frontdoor.py): per-worker counters
        # live in the shared-memory status block and aggregate here at
        # scrape time (watch_frontdoor's delta pattern), like the
        # reference's collect-at-scrape stats handler
        self.frontdoor_workers = Gauge(
            "guber_tpu_frontdoor_workers",
            "Configured frontdoor acceptor worker processes "
            "(0 = classic single-process serving).",
            registry=self.registry,
        )
        self.frontdoor_rpcs = Counter(
            "guber_tpu_frontdoor_rpcs_total",
            "RPCs completed through the frontdoor shm ring, per worker.",
            ["worker"],
            registry=self.registry,
        )
        self.frontdoor_sheds = Counter(
            "guber_tpu_frontdoor_sheds_total",
            "Requests shed in-band by frontdoor workers (draining / "
            "saturated / ring_full), per worker.",
            ["worker"],
            registry=self.registry,
        )
        self.frontdoor_restarts = Counter(
            "guber_tpu_frontdoor_restarts_total",
            "Frontdoor worker crash-restarts performed by the hub.",
            registry=self.registry,
        )
        self.shm_ring_depth = Gauge(
            "guber_tpu_shm_ring_depth",
            "Published-but-unconsumed submissions in each worker's shm "
            "ring at scrape time.",
            ["worker"],
            registry=self.registry,
        )
        self.shm_ring_stalls = Counter(
            "guber_tpu_shm_ring_stalls_total",
            "Producer-side ring-full events (every slab in flight; the "
            "worker shed in-band with reason ring_full), per worker.",
            ["worker"],
            registry=self.registry,
        )
        # worker-side response encoding (frontdoor.py): path=worker means
        # the worker built protobuf bytes from decision columns the engine
        # left in the completion-ring slab; path=engine means the slab
        # carried pre-serialized bytes (encode_mode=engine, or a response
        # shape columns cannot express, e.g. errors / owner metadata)
        self.frontdoor_encode = Counter(
            "guber_tpu_frontdoor_encode_total",
            "GetRateLimits responses delivered per worker, by encode "
            "path (worker = encoded from completion-ring decision "
            "columns; engine = pre-serialized on the engine).",
            ["worker", "path"],
            registry=self.registry,
        )
        self.frontdoor_batched_rpcs = Counter(
            "guber_tpu_frontdoor_batched_rpcs_total",
            "RPCs coalesced into multi-RPC columnar slab records by "
            "batched wire reads, per worker.",
            ["worker"],
            registry=self.registry,
        )
        self.frontdoor_batch_flushes = Counter(
            "guber_tpu_frontdoor_batch_flushes_total",
            "Multi-RPC batch records published to the shm ring "
            "(KIND_BATCH_COLS), per worker.",
            ["worker"],
            registry=self.registry,
        )
        # trace propagation across the shm hand-off (frontdoor.py): RPCs
        # that arrived with a sampled traceparent the worker could NOT
        # carry through the slab record (raw-bytes fallback records have
        # no trace region; a coalesced batch carries only its first
        # member's context)
        self.frontdoor_trace_drops = Counter(
            "guber_tpu_frontdoor_trace_drops_total",
            "Sampled trace contexts dropped at the shm hand-off, per "
            "worker (raw-record fallback, or non-first members of a "
            "coalesced batch).",
            ["worker"],
            registry=self.registry,
        )
        # cluster scale-out surface (core/service.py): ring membership and
        # the cross-node forwarding tax the load harness
        # (scripts/load_cluster.py) reads to report peer overhead
        self.cluster_peers = Gauge(
            "guber_tpu_cluster_peers",
            "Peers in the installed consistent-hash ring, self included "
            "(0 until the first membership update).",
            registry=self.registry,
        )
        self.cluster_forwarded = Counter(
            "guber_tpu_cluster_forwarded_total",
            "Rate-limit items forwarded to their owning peer (both the "
            "per-item path and the native lane's spliced batches).",
            registry=self.registry,
        )
        self._stage_rings: Dict[str, _StageRing] = {}
        self._stage_rings_lock = threading.Lock()
        self._slo_sink = None

    def add_scrape_hook(self, fn) -> None:
        """Register a callable run before every expose() — the analog of the
        reference's Collector.Collect pulling live stats at scrape time
        (cache/lru.go:160-172, gubernator.go:313-322)."""
        self._scrape_hooks.append(fn)

    def watch_engine(self, engine) -> None:
        """Export the engine's cache stats at scrape time through ONE
        coherent accessor (engine.cache_stats): the cache_size gauge,
        hit/miss counters advanced by delta since the last scrape, and the
        free/live/expired slot occupancy gauges all come from the same
        read, so a scrape never mixes counters from different moments."""
        last = {"hit": 0, "miss": 0}

        def refresh():
            st = engine.cache_stats()
            self.cache_size.set(st["size"])
            for state in ("free", "live", "expired"):
                self.cache_slots.labels(state=state).set(st[state])
            if st["hits"] > last["hit"]:
                self.cache_access_count.labels(type="hit").inc(
                    st["hits"] - last["hit"])
                last["hit"] = st["hits"]
            if st["misses"] > last["miss"]:
                self.cache_access_count.labels(type="miss").inc(
                    st["misses"] - last["miss"])
                last["miss"] = st["misses"]

        self.add_scrape_hook(refresh)

    def watch_tiers(self, engine) -> None:
        """Export the warm tier's occupancy and event counters at scrape
        time from ONE engine.tier_stats read (same delta pattern as
        watch_engine: the TierManager keeps plain ints, the scrape
        advances the prometheus counters by the difference)."""
        events = {
            "promote": "promotions",
            "demote": "demotions",
            "warm_hit": "warm_hits",
            "cold_miss": "cold_misses",
            "warm_evict": "warm_evictions",
            "demote_drop": "demote_dropped_expired",
            "demote_stale": "demote_dropped_stale",
        }
        last = {k: 0 for k in events}

        def refresh():
            st = engine.tier_stats()
            if st is None:
                return
            self.tier_warm_rows.set(st["warm_rows"])
            self.tier_warm_bytes.set(st["warm_bytes"])
            for label, field in events.items():
                cur = st[field]
                if cur > last[label]:
                    self.tier_events.labels(event=label).inc(
                        cur - last[label])
                    last[label] = cur

        self.add_scrape_hook(refresh)

    def watch_leases(self, book) -> None:
        """Export the concurrency-lease book's occupancy at scrape time
        from ONE book.stats() read (keys/clients/held move together)."""

        def refresh():
            keys, clients, held = book.stats()
            self.lease_keys.set(keys)
            self.lease_clients.set(clients)
            self.lease_held.set(held)

        self.add_scrape_hook(refresh)

    def observe_algorithm(self, algorithm: str, n: int = 1) -> None:
        self.algo_decisions.labels(algorithm=algorithm).inc(n)

    def observe_lease_release(self, reason: str, n: int) -> None:
        if n > 0:
            self.lease_releases.labels(reason=reason).inc(n)

    def watch_qos(self, qos) -> None:
        """Export the QoS control state at scrape time: queue depth, the
        adaptive window, and the drain-latency EWMA all from the same
        QoSManager read."""

        def refresh():
            self.qos_queue_depth.set(qos.admission.pending)
            self.qos_effective_window.set(qos.congestion.effective_window())
            self.qos_drain_latency_ewma.set(qos.congestion.latency_ewma)
            self.qos_drain_depth_ewma.set(qos.congestion.depth_ewma)

        self.add_scrape_hook(refresh)

    def watch_analytics(self, analytics=None, slo=None) -> None:
        """Export the traffic-analytics occupancy gauges and the SLO
        burn rates at scrape time, and route the shed funnel
        (observe_shed) into the SLO engine's availability/shed-rate
        objectives — sheds are QoS events but SLO evidence."""
        if slo is not None:
            self._slo_sink = slo

        def refresh():
            if analytics is not None:
                occ = analytics.occupancy()
                for state in ("live", "expired"):
                    self.arena_occupancy.labels(state=state).set(occ[state])
            if slo is not None:
                for name, obj in slo.burn_rates().items():
                    for win, burn in obj["windows"].items():
                        self.slo_burn_rate.labels(
                            slo=name, window=win).set(burn)
                    self.slo_firing.labels(slo=name).set(
                        1 if obj["firing"] else 0)

        self.add_scrape_hook(refresh)

    def watch_frontdoor(self, hub) -> None:
        """Export the frontdoor hub's per-worker shared-memory counters at
        scrape time: the workers bump raw int64 cells in the status block
        (no prometheus client in the worker processes), and this hook
        advances the engine-side counters by the delta since the last
        scrape — the same pattern watch_engine uses for cache stats."""
        from gubernator_tpu.core import shm_ring as _sr
        last: Dict[tuple, int] = {}

        def _delta(w: str, field: int, counter, **lbls) -> None:
            cur = hub.status.get_w(int(w), field)
            prev = last.get((w, field), 0)
            if cur > prev:
                counter.labels(worker=w, **lbls).inc(cur - prev)
                last[(w, field)] = cur

        def refresh():
            self.frontdoor_workers.set(hub.workers)
            if hub.status is None:
                return
            for i in range(hub.workers):
                w = str(i)
                _delta(w, _sr.W_RPCS, self.frontdoor_rpcs)
                _delta(w, _sr.W_SHEDS, self.frontdoor_sheds)
                _delta(w, _sr.W_STALLS, self.shm_ring_stalls)
                _delta(w, _sr.W_ENCODES, self.frontdoor_encode,
                       path="worker")
                _delta(w, _sr.W_ENC_FALLBACK, self.frontdoor_encode,
                       path="engine")
                _delta(w, _sr.W_BATCH_RPCS, self.frontdoor_batched_rpcs)
                _delta(w, _sr.W_BATCH_FLUSHES, self.frontdoor_batch_flushes)
                _delta(w, _sr.W_TRACE_DROPS, self.frontdoor_trace_drops)
                if hub.chans:
                    self.shm_ring_depth.labels(worker=w).set(
                        hub.chans[i].sub_depth())
            cur = hub.restarts
            prev = last.get(("", "restarts"), 0)
            if cur > prev:
                self.frontdoor_restarts.inc(cur - prev)
                last[("", "restarts")] = cur

        self.add_scrape_hook(refresh)

    def observe_hot_key(self, key: str, hits: int) -> None:
        if hits > 0:
            self.hot_key_hits.labels(key=key).inc(hits)

    def observe_tenant(self, tenant: str, under: int, over: int) -> None:
        if under > 0:
            self.tenant_decisions.labels(
                tenant=tenant, outcome="under_limit").inc(under)
        if over > 0:
            self.tenant_decisions.labels(
                tenant=tenant, outcome="over_limit").inc(over)

    def observe_churn(self, inits: int) -> None:
        if inits > 0:
            self.arena_churn.inc(inits)

    def observe_shed(self, reason: str, n: int = 1) -> None:
        self.qos_shed.labels(reason=reason).inc(n)
        if self._slo_sink is not None:
            self._slo_sink.observe_shed(n)

    _BREAKER_STATES = {"closed": 0, "half_open": 1, "open": 2}

    def observe_breaker(self, peer: str, state: str) -> None:
        self.breaker_state.labels(peer=peer).set(
            self._BREAKER_STATES.get(state, 0))

    def observe_peer_retry(self, peer: str) -> None:
        self.peer_retries.labels(peer=peer).inc()

    def observe_global_error(self, peer: str, kind: str,
                             queued: int = 0) -> None:
        """One failed per-peer GLOBAL send (kind: hits|update), plus how
        many NEW hint entries it buffered."""
        if kind == "update":
            self.broadcast_errors.labels(peer=peer).inc()
        else:
            self.global_send_errors.labels(peer=peer).inc()
        if queued > 0:
            self.hints.labels(event="queued", peer=peer).inc(queued)

    def observe_hints(self, peer: str, replayed: int = 0,
                      expired: int = 0) -> None:
        if replayed:
            self.hints.labels(event="replayed", peer=peer).inc(replayed)
        if expired:
            self.hints.labels(event="expired", peer=peer).inc(expired)

    _HEALTH_STATES = {"up": 0, "suspect": 1, "down": 2}

    def observe_peer_health(self, peer: str, state: str) -> None:
        self.peer_health_state.labels(peer=peer).set(
            self._HEALTH_STATES.get(state, 0))

    def observe_rehome(self, direction: str) -> None:
        self.ring_rehomes.labels(direction=direction).inc()

    def observe_snapshot(self, seconds: float, size_bytes: int,
                         ok: bool) -> None:
        self.snapshot_total.labels(
            status="success" if ok else "failed").inc()
        if ok:
            self.snapshot_duration.observe(seconds)
            self.snapshot_size.set(size_bytes)

    def observe_migration(self, moved: int = 0, imported: int = 0,
                          skipped_stale: int = 0) -> None:
        if moved:
            self.migrated_keys.labels(direction="out").inc(moved)
        if imported:
            self.migrated_keys.labels(direction="in").inc(imported)
        if skipped_stale:
            self.migration_skipped_stale.inc(skipped_stale)

    def observe_stage(self, stage: str, seconds: float) -> None:
        """Record one stage duration into both the Prometheus histogram
        (milliseconds, for dashboards) and the in-process ring (for the
        rolling p50/p95/p99 snapshot)."""
        if seconds < 0.0:
            seconds = 0.0
        self.stage_duration.labels(stage=stage).observe(seconds * 1000.0)
        ring = self._stage_rings.get(stage)
        if ring is None:
            with self._stage_rings_lock:
                ring = self._stage_rings.setdefault(stage, _StageRing())
        ring.observe(seconds)

    def stage_snapshot(self) -> Dict[str, dict]:
        """Rolling per-stage quantiles, `engine.cache_stats`-style: one
        coherent read of every stage ring, keyed by stage name in
        pipeline order (stages with no samples yet are omitted)."""
        out: Dict[str, dict] = {}
        with self._stage_rings_lock:
            rings = dict(self._stage_rings)
        for stage in STAGES:
            ring = rings.pop(stage, None)
            if ring is not None:
                snap = ring.snapshot()
                if snap is not None:
                    out[stage] = snap
        for stage, ring in rings.items():  # non-canonical stages last
            snap = ring.snapshot()
            if snap is not None:
                out[stage] = snap
        return out

    def expose(self) -> bytes:
        for fn in self._scrape_hooks:
            fn()
        return generate_latest(self.registry)

    def observe_rpc(self, method: str, start: float, ok: bool) -> None:
        """Per-RPC accounting (replaces the reference's gRPC stats-handler
        channel pipeline, prometheus.go:65-134)."""
        self.grpc_request_counts.labels(
            status="success" if ok else "failed", method=method
        ).inc()
        self.grpc_request_duration.labels(method=method).observe(
            (time.monotonic() - start) * 1000.0
        )
