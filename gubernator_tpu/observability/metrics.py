"""Prometheus metrics with the reference's metric names.

Metric surface parity (SURVEY.md §5):
  cache_size, cache_access_count{type}          reference cache/lru.go:56-59
  async_durations, broadcast_durations          reference global.go:44-51
  grpc_request_counts{status}/{method},
  grpc_request_duration_milliseconds            reference prometheus.go:52-59

Plus TPU-native additions under guber_tpu_*: device window count, window
occupancy, device step duration.
"""

from __future__ import annotations

import time
from typing import Optional

from prometheus_client import (
    CollectorRegistry,
    Counter,
    Gauge,
    Histogram,
    generate_latest,
)
from prometheus_client import CONTENT_TYPE_LATEST  # noqa: F401


class Metrics:
    """Per-instance metric registry (instances in one process each get their
    own, like each reference node's prometheus.Registry, main.go:53)."""

    def __init__(self, registry: Optional[CollectorRegistry] = None):
        self.registry = registry or CollectorRegistry()
        self._scrape_hooks = []
        self.cache_size = Gauge(
            "cache_size",
            "Size of the cache which holds the rate limits.",
            registry=self.registry,
        )
        self.cache_access_count = Counter(
            "cache_access_count",
            "Cache access counts.",
            ["type"],
            registry=self.registry,
        )
        self.async_durations = Histogram(
            "async_durations",
            "The duration of GLOBAL async sends in seconds.",
            registry=self.registry,
        )
        self.broadcast_durations = Histogram(
            "broadcast_durations",
            "The duration of GLOBAL broadcasts to peers in seconds.",
            registry=self.registry,
        )
        self.grpc_request_counts = Counter(
            "grpc_request_counts",
            "The count of gRPC requests.",
            ["status", "method"],
            registry=self.registry,
        )
        self.grpc_request_duration = Histogram(
            "grpc_request_duration_milliseconds",
            "The timings of gRPC requests in milliseconds.",
            ["method"],
            registry=self.registry,
        )
        # TPU-native
        self.window_count = Counter(
            "guber_tpu_windows_total",
            "Device windows dispatched.",
            registry=self.registry,
        )
        self.window_occupancy = Histogram(
            "guber_tpu_window_occupancy",
            "Requests per device window.",
            buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1000),
            registry=self.registry,
        )
        # duplicate-run aggregation: decisions served vs lanes staged —
        # rate(decisions)/rate(lanes) is the live fold factor
        self.agg_decisions = Counter(
            "guber_tpu_aggregation_decisions_total",
            "Decisions served by the pipelined drain.",
            registry=self.registry,
        )
        self.agg_lanes = Counter(
            "guber_tpu_aggregation_lanes_total",
            "Device lanes staged by the pipelined drain.",
            registry=self.registry,
        )
        self.window_duration = Histogram(
            "guber_tpu_window_duration_seconds",
            "Wall time of one device window step.",
            registry=self.registry,
        )
        # fused-path adoption + drain depth (core/pipeline.py): how many
        # drains lowered to the fused megakernel, and how many windows deep
        # each drain's K-stack actually ran — rate(fused)/rate(windows) is
        # live adoption, the depth histogram is the decisions-per-dispatch
        # lever the cost model optimizes
        self.fused_drains = Counter(
            "guber_tpu_fused_drains_total",
            "Pipeline drains served by the fused Pallas megakernel.",
            registry=self.registry,
        )
        self.drain_depth = Histogram(
            "guber_tpu_drain_depth_windows",
            "Occupied window depth K per pipeline drain.",
            buckets=(0, 1, 2, 4, 8, 16, 32, 64, 128, 256, 512),
            registry=self.registry,
        )

    def add_scrape_hook(self, fn) -> None:
        """Register a callable run before every expose() — the analog of the
        reference's Collector.Collect pulling live stats at scrape time
        (cache/lru.go:160-172, gubernator.go:313-322)."""
        self._scrape_hooks.append(fn)

    def watch_engine(self, engine) -> None:
        """Export the engine's cache stats at scrape time: cache_size gauge
        plus hit/miss counters advanced by delta since the last scrape."""
        last = {"hit": 0, "miss": 0}

        def refresh():
            self.cache_size.set(engine.cache_size)
            hits, misses = engine.cache_hits, engine.cache_misses
            if hits > last["hit"]:
                self.cache_access_count.labels(type="hit").inc(hits - last["hit"])
                last["hit"] = hits
            if misses > last["miss"]:
                self.cache_access_count.labels(type="miss").inc(misses - last["miss"])
                last["miss"] = misses

        self.add_scrape_hook(refresh)

    def expose(self) -> bytes:
        for fn in self._scrape_hooks:
            fn()
        return generate_latest(self.registry)

    def observe_rpc(self, method: str, start: float, ok: bool) -> None:
        """Per-RPC accounting (replaces the reference's gRPC stats-handler
        channel pipeline, prometheus.go:65-134)."""
        self.grpc_request_counts.labels(
            status="success" if ok else "failed", method=method
        ).inc()
        self.grpc_request_duration.labels(method=method).observe(
            (time.monotonic() - start) * 1000.0
        )
