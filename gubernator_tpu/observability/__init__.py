"""Observability subsystem: metrics, tracing, and runtime introspection.

Public API — import from here, not the submodules:

  * `Metrics` — per-instance Prometheus registry with the reference's
    metric names (metrics.py);
  * `Tracer` / `get_tracer` — the lightweight span recorder and the
    process-default instance configured from GUBER_TRACE_* (tracing.py);
  * `ProfileCapture` / `build_debug_snapshot` — on-demand device capture
    and the `/v1/admin/debug` operator view (introspect.py);
  * `TrafficAnalytics` / `SLOEngine` — host side of the device-computed
    traffic analytics (hot-key top-K, per-tenant accounting) and the
    multi-window burn-rate alerting engine (analytics.py).
"""

from gubernator_tpu.observability.analytics import (
    SLOEngine,
    TrafficAnalytics,
)
from gubernator_tpu.observability.devprof import (
    Devprof,
    DevprofController,
    KernelTable,
    WindowClock,
)
from gubernator_tpu.observability.introspect import (
    ProfileCapture,
    build_debug_snapshot,
)
from gubernator_tpu.observability.metrics import (
    CONTENT_TYPE_LATEST,
    STAGES,
    Metrics,
)
from gubernator_tpu.observability.tracing import (
    NOOP_SPAN,
    SpanContext,
    Tracer,
    current_context,
    get_tracer,
    parse_traceparent,
)

__all__ = [
    "CONTENT_TYPE_LATEST",
    "Devprof",
    "DevprofController",
    "KernelTable",
    "Metrics",
    "WindowClock",
    "NOOP_SPAN",
    "ProfileCapture",
    "STAGES",
    "SLOEngine",
    "SpanContext",
    "Tracer",
    "TrafficAnalytics",
    "build_debug_snapshot",
    "current_context",
    "get_tracer",
    "parse_traceparent",
]
