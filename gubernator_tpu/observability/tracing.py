"""Request-lifecycle tracing: a lightweight span recorder.

No OpenTelemetry dependency — the serving path needs span *recording* to
cost nanoseconds when sampling is off, and the otel SDK's context plumbing
is orders of magnitude heavier than this hot path can afford.  What this
module keeps from the otel model is the wire contract, so real tracing
backends can still consume us:

  * trace context propagates as a W3C `traceparent`
    (`00-<32hex trace>-<16hex span>-<2hex flags>`) — over HTTP as the
    header of the same name (api/http_gateway.py) and over the gRPC peer
    lane as invocation metadata (net/peers.py -> server.py), so a
    forwarded (non-owner) request yields ONE stitched trace whose spans
    cover the client hop, the peer forward, and the owner-side drain;
  * optional OTLP/HTTP JSON export behind `GUBER_TRACE_EXPORT` (an
    endpoint like http://collector:4318/v1/traces), hand-rolled with
    urllib on a background thread — export failures degrade to a
    once-per-endpoint warning, never to request latency.

Sampling (`GUBER_TRACE_SAMPLE`, 0.0-1.0) is decided ONCE at the root span
per request; everything downstream keys off the SpanContext being None
(not sampled) or not, so the disabled path is a single attribute check.

Spans land in a bounded ring (deque) read by the `/v1/admin/debug`
endpoint and tests; the recorder never allocates when tracing is off.
"""

from __future__ import annotations

import collections
import json
import logging
import os
import queue
import random
import threading
import time
import urllib.request
from contextvars import ContextVar
from typing import Dict, List, Optional

log = logging.getLogger("gubernator.tracing")

TRACEPARENT = "traceparent"

# the ambient trace context for the current async task / thread;
# None = this request is not sampled (or tracing is off entirely)
_current: ContextVar[Optional["SpanContext"]] = ContextVar(
    "guber_trace_ctx", default=None)


def current_context() -> Optional["SpanContext"]:
    """The sampled SpanContext of the request being served, or None."""
    return _current.get()


class SpanContext:
    """Identity of one *sampled* request's position in its trace.  Only
    ever constructed for sampled requests — `ctx is None` IS the not-
    sampled fast path, so no `sampled` flag exists."""

    __slots__ = ("trace_id", "span_id", "enqueued_at")

    def __init__(self, trace_id: str, span_id: str):
        self.trace_id = trace_id
        self.span_id = span_id
        # stamped by the batcher/pipeline submit path so the drain can
        # record this request's enqueue span without a side table
        self.enqueued_at: float = 0.0

    def traceparent(self) -> str:
        return f"00-{self.trace_id}-{self.span_id}-01"


def parse_traceparent(value: Optional[str]) -> Optional[SpanContext]:
    """Parse an incoming W3C traceparent; None on anything malformed (a
    bad header must never fail the request, it just starts a new trace).
    An unsampled flag (…-00) returns None: the caller decided not to
    trace, and we honor it."""
    if not value:
        return None
    parts = value.strip().split("-")
    if len(parts) < 4 or len(parts[1]) != 32 or len(parts[2]) != 16:
        return None
    try:
        int(parts[1], 16), int(parts[2], 16)
        flags = int(parts[3], 16)
    except ValueError:
        return None
    if not flags & 0x01:
        return None
    return SpanContext(parts[1], parts[2])


class Span:
    """One finished-or-open span.  Mutable `end` so the context-manager
    form stays allocation-light; recorded into the tracer ring on exit."""

    __slots__ = ("name", "trace_id", "span_id", "parent_id", "start",
                 "end", "wall_start", "node", "attrs")

    def __init__(self, name: str, ctx: SpanContext, parent_id: str,
                 node: str, start: float, wall_start: float):
        self.name = name
        self.trace_id = ctx.trace_id
        self.span_id = ctx.span_id
        self.parent_id = parent_id
        self.start = start          # monotonic seconds
        self.end = 0.0
        self.wall_start = wall_start  # epoch seconds (export timestamps)
        self.node = node
        self.attrs: Optional[Dict[str, str]] = None

    @property
    def duration(self) -> float:
        return max(0.0, self.end - self.start)

    def set_attr(self, key: str, value) -> None:
        if self.attrs is None:
            self.attrs = {}
        self.attrs[key] = str(value)

    def to_dict(self) -> dict:
        d = {"name": self.name, "trace_id": self.trace_id,
             "span_id": self.span_id, "parent_id": self.parent_id,
             "node": self.node, "duration_ms": self.duration * 1000.0,
             "start": self.wall_start}
        if self.attrs:
            d["attrs"] = self.attrs
        return d


class _NoopSpan:
    """Shared do-nothing span for the unsampled path: every method is a
    no-op and the context manager restores nothing."""

    __slots__ = ()
    ctx = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def set_attr(self, key, value):
        pass

    def finish(self):
        pass


NOOP_SPAN = _NoopSpan()


class _ActiveSpan:
    """Context-manager wrapper that installs its ctx as current and
    records itself into the tracer ring on exit."""

    __slots__ = ("span", "ctx", "_tracer", "_token")

    def __init__(self, tracer: "Tracer", span: Span, ctx: SpanContext):
        self.span = span
        self.ctx = ctx
        self._tracer = tracer
        self._token = None

    def set_attr(self, key, value):
        self.span.set_attr(key, value)

    def __enter__(self):
        self._token = _current.set(self.ctx)
        return self

    def __exit__(self, exc_type, exc, tb):
        if self._token is not None:
            _current.reset(self._token)
            self._token = None
        self.finish()
        return False

    def finish(self):
        if self.span.end == 0.0:
            self.span.end = self._tracer.now_fn()
            self._tracer.record(self.span)


def _ids(n_bytes: int) -> str:
    return "%0*x" % (n_bytes * 2, random.getrandbits(n_bytes * 8))


class Tracer:
    """Per-instance span recorder (instances in one process each get their
    own, like Metrics; `get_tracer()` hands out the process default).

    `sample`: probability a root request starts a trace (0 disables).
    Tests flip `tracer.sample = 1.0` after boot — sampling is re-read per
    request."""

    def __init__(self, sample: Optional[float] = None,
                 export: Optional[str] = None,
                 node: str = "", max_spans: int = 2048,
                 now_fn=time.monotonic):
        from gubernator_tpu.config import env_float
        self.sample = (env_float("GUBER_TRACE_SAMPLE", 0.0)
                       if sample is None else float(sample))
        self.sample = min(1.0, self.sample)
        self.node = node
        self.now_fn = now_fn
        self._spans: collections.deque = collections.deque(maxlen=max_spans)
        self._lock = threading.Lock()
        self._exporter: Optional[_OtlpExporter] = None
        endpoint = (os.environ.get("GUBER_TRACE_EXPORT", "")
                    if export is None else export)
        if endpoint:
            self._exporter = _OtlpExporter(endpoint)

    @property
    def enabled(self) -> bool:
        return self.sample > 0.0

    # ------------------------------------------------------------ span API

    def start_trace(self, name: str, traceparent: Optional[str] = None):
        """Root span for one inbound request.  An incoming traceparent
        continues the caller's trace (every propagated request is
        sampled — the upstream node already paid the sampling dice roll);
        otherwise sample locally.  Returns NOOP_SPAN when not sampled."""
        ctx = parse_traceparent(traceparent)
        if ctx is None:
            if not (self.sample > 0.0 and random.random() < self.sample):
                return NOOP_SPAN
            ctx = SpanContext(_ids(16), _ids(8))
            parent = ""
        else:
            # the incoming span id is our parent; we become a fresh span
            parent = ctx.span_id
            ctx = SpanContext(ctx.trace_id, _ids(8))
        span = Span(name, ctx, parent, self.node, self.now_fn(), time.time())
        return _ActiveSpan(self, span, ctx)

    def span(self, name: str, ctx: Optional[SpanContext] = None):
        """Child span under `ctx` (or the ambient current context).
        Returns NOOP_SPAN when the request is unsampled — the disabled
        hot path is one ContextVar read and a None check."""
        parent = ctx if ctx is not None else _current.get()
        if parent is None:
            return NOOP_SPAN
        child = SpanContext(parent.trace_id, _ids(8))
        span = Span(name, child, parent.span_id, self.node, self.now_fn(),
                    time.time())
        return _ActiveSpan(self, span, child)

    def record_span(self, ctx: Optional[SpanContext], name: str,
                    start: float, end: float, parent: bool = True,
                    attrs: Optional[dict] = None) -> None:
        """Record a completed span with explicit monotonic timestamps —
        the form the drain uses for stage spans measured on the engine
        thread (the span's lifetime doesn't nest in any `with` block)."""
        if ctx is None:
            return
        child = SpanContext(ctx.trace_id, _ids(8))
        span = Span(name, child, ctx.span_id if parent else "", self.node,
                    start, time.time() - (self.now_fn() - start))
        span.end = end
        if attrs:
            for k, v in attrs.items():
                span.set_attr(k, v)
        self.record(span)

    def record(self, span: Span) -> None:
        with self._lock:
            self._spans.append(span)
        if self._exporter is not None:
            self._exporter.offer(span)

    # ----------------------------------------------------------- inspection

    def spans(self, trace_id: Optional[str] = None) -> List[Span]:
        with self._lock:
            out = list(self._spans)
        if trace_id is not None:
            out = [s for s in out if s.trace_id == trace_id]
        return out

    def recent_traces(self, limit: int = 10) -> List[dict]:
        """Newest-first trace summaries for the debug endpoint: span
        count, total wall, and the slowest stage of each trace."""
        with self._lock:
            spans = list(self._spans)
        by_trace: Dict[str, List[Span]] = {}
        order: List[str] = []
        for s in spans:
            if s.trace_id not in by_trace:
                by_trace[s.trace_id] = []
                order.append(s.trace_id)
            by_trace[s.trace_id].append(s)
        out = []
        for tid in reversed(order[-limit:]):
            group = by_trace[tid]
            slowest = max(group, key=lambda s: s.duration)
            roots = [s for s in group if not s.parent_id]
            out.append({
                "trace_id": tid,
                "spans": len(group),
                "root": roots[0].name if roots else group[0].name,
                "duration_ms": (max(s.end for s in group)
                                - min(s.start for s in group)) * 1000.0,
                "slowest_span": slowest.name,
                "slowest_ms": slowest.duration * 1000.0,
                "nodes": sorted({s.node for s in group if s.node}),
            })
        return out

    def close(self) -> None:
        if self._exporter is not None:
            self._exporter.close()


class _OtlpExporter:
    """Best-effort OTLP/HTTP JSON shipper on one daemon thread.  The
    serving path only ever pays a non-blocking queue put; a full queue
    drops spans (observability must shed before it backpressures)."""

    def __init__(self, endpoint: str, flush_interval: float = 1.0):
        self.endpoint = endpoint
        self.flush_interval = flush_interval
        self._q: "queue.Queue[Optional[Span]]" = queue.Queue(maxsize=8192)
        self._warned = False
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="guber-trace-export")
        self._thread.start()

    def offer(self, span: Span) -> None:
        try:
            self._q.put_nowait(span)
        except queue.Full:
            pass

    def _run(self) -> None:
        batch: List[Span] = []
        while True:
            try:
                item = self._q.get(timeout=self.flush_interval)
            except queue.Empty:
                item = None
            if item is not None:
                batch.append(item)
                if len(batch) < 512:
                    continue
            if batch:
                self._ship(batch)
                batch = []

    def _ship(self, batch: List[Span]) -> None:
        # epoch-ns timestamps from the span's wall_start + duration
        def ns(t: float) -> str:
            return str(int(t * 1e9))

        body = json.dumps({"resourceSpans": [{
            "resource": {"attributes": [{
                "key": "service.name",
                "value": {"stringValue": "gubernator-tpu"}}]},
            "scopeSpans": [{
                "scope": {"name": "gubernator_tpu.observability.tracing"},
                "spans": [{
                    "traceId": s.trace_id,
                    "spanId": s.span_id,
                    **({"parentSpanId": s.parent_id} if s.parent_id else {}),
                    "name": s.name,
                    "kind": 1,
                    "startTimeUnixNano": ns(s.wall_start),
                    "endTimeUnixNano": ns(s.wall_start + s.duration),
                    "attributes": [
                        {"key": k, "value": {"stringValue": v}}
                        for k, v in ({"node": s.node} | (s.attrs or {})).items()
                        if v],
                } for s in batch],
            }],
        }]}).encode("utf-8")
        req = urllib.request.Request(
            self.endpoint, data=body, method="POST",
            headers={"Content-Type": "application/json"})
        try:
            urllib.request.urlopen(req, timeout=5.0).close()
        except Exception as e:
            if not self._warned:
                self._warned = True
                log.warning("OTLP export to %s failed (%s); further "
                            "failures are silent", self.endpoint, e)

    def close(self) -> None:
        pass  # daemon thread; nothing to join


_default_tracer: Optional[Tracer] = None
_default_lock = threading.Lock()


def get_tracer() -> Tracer:
    """The process-default tracer, configured from GUBER_TRACE_* env —
    what library embedders share when they don't inject their own."""
    global _default_tracer
    if _default_tracer is None:
        with _default_lock:
            if _default_tracer is None:
                _default_tracer = Tracer()
    return _default_tracer
