"""Runtime introspection: the debug snapshot and on-demand device capture.

`build_debug_snapshot` assembles the one-read operator view served by
`GET /v1/admin/debug` (api/http_gateway.py) and `cli debug` (cmd/cli.py):
arena occupancy, admission queue depth, per-peer breaker states, the AIMD
congestion window, per-stage latency quantiles, and recent-trace
summaries — every number from the same accessors the control loops read,
so what the operator sees is what the controllers saw.

`ProfileCapture` wraps the next N pipeline drains in
`jax.profiler.start_trace/stop_trace` (the GUBER_PROFILE plumbing from
bench.py, now armable at runtime via `POST /v1/admin/profile`).  The
armed check runs on the single engine thread around each dispatch, so
when disarmed the hot path pays one integer compare.
"""

from __future__ import annotations

import logging
import os
import threading
import time

log = logging.getLogger("gubernator.introspect")


class ProfileCapture:
    """Arm-and-forget device profiler: `arm(n, dir)` from the admin plane,
    `before_drain()`/`after_drain()` from the engine thread around each
    dispatch.  All state transitions happen under the lock, but the
    disarmed fast path reads the plain int `_remaining` first — stale
    reads only ever delay a capture by one drain, never corrupt one."""

    def __init__(self):
        self._lock = threading.Lock()
        self._remaining = 0
        self._dir = ""
        self._active = False

    @property
    def armed(self) -> bool:
        return self._remaining > 0 or self._active

    def arm(self, drains: int, trace_dir: str = "") -> dict:
        """Schedule a capture of the next `drains` dispatches.  Default
        directory comes from GUBER_PROFILE (bench.py's knob) or a
        timestamped /tmp path."""
        trace_dir = (trace_dir or os.environ.get("GUBER_PROFILE", "")
                     or f"/tmp/guber-profile-{int(time.time())}")
        with self._lock:
            if self._active or self._remaining > 0:
                return {"armed": False, "error": "capture already in "
                        "progress", "dir": self._dir}
            self._remaining = max(1, int(drains))
            self._dir = trace_dir
        return {"armed": True, "drains": self._remaining, "dir": trace_dir}

    # ------------------------------------------------- engine-thread hooks

    def before_drain(self) -> None:
        """Engine thread, just before a dispatch: start the device trace
        on the first armed drain."""
        with self._lock:
            if self._remaining <= 0 or self._active:
                return
            self._active = True
        try:
            import jax
            jax.profiler.start_trace(self._dir)
            log.info("profile capture started -> %s (%d drains)",
                     self._dir, self._remaining)
        except Exception:
            log.exception("profile capture failed to start")
            with self._lock:
                self._active = False
                self._remaining = 0

    def after_drain(self) -> None:
        """Engine thread, after a dispatch completed: stop once the armed
        count runs out."""
        with self._lock:
            if not self._active:
                return
            self._remaining -= 1
            if self._remaining > 0:
                return
            self._active = False
        try:
            import jax
            jax.profiler.stop_trace()
            log.info("profile capture stopped -> %s", self._dir)
        except Exception:
            log.exception("profile capture failed to stop")

    def cancel(self) -> None:
        """Disarm an in-flight capture (continuous profiling's recovery
        path when traffic never completes the armed drain count): stop the
        device trace if it started, drop any remaining armed drains."""
        with self._lock:
            was_active = self._active
            self._active = False
            self._remaining = 0
        if not was_active:
            return
        try:
            import jax
            jax.profiler.stop_trace()
            log.info("profile capture cancelled -> %s", self._dir)
        except Exception:
            log.exception("profile capture failed to cancel")

    def status(self) -> dict:
        with self._lock:
            return {"active": self._active, "remaining": self._remaining,
                    "dir": self._dir}


def _jsonable(d: dict) -> dict:
    """Coerce numpy scalars (engine counters) to plain Python types so the
    snapshot always survives json.dumps."""
    out = {}
    for k, v in d.items():
        if isinstance(v, dict):
            out[k] = _jsonable(v)
        elif isinstance(v, (bool, int, float, str)) or v is None:
            out[k] = v
        elif hasattr(v, "item"):
            out[k] = v.item()
        else:
            out[k] = str(v)
    return out


def build_debug_snapshot(instance) -> dict:
    """One coherent operator view of a core.service.Instance."""
    out: dict = {
        "address": instance.advertise_address,
        "mesh_mode": instance.mesh_mode,
        "standalone": instance.standalone,
        "engine": _jsonable(instance.engine.cache_stats()),
    }
    if instance.qos is not None:
        adm = instance.qos.admission
        cong = instance.qos.congestion
        out["admission"] = {
            "pending": adm.pending,
            "pending_peak": adm.pending_peak,
            "max_pending": adm.max_pending,
            "saturated": adm.saturated,
            "inflight_windows": adm.inflight_windows,
            "shed_counts": dict(adm.shed_counts),
        }
        out["congestion"] = {
            "effective_window": cong.effective_window(),
            "latency_ewma_ms": cong.latency_ewma * 1000.0,
            "depth_ewma": cong.depth_ewma,
            "congested": cong.congested,
            "increases": cong.increases,
            "decreases": cong.decreases,
            "stage_ewma_ms": {k: v * 1000.0
                              for k, v in cong.stage_ewma.items()},
        }
    out["peers"] = [
        {"host": p.host, "is_owner": p.is_owner,
         "breaker": p.breaker.state}
        for p in instance.peer_list()
    ]
    # what the GLOBAL plane failed to deliver + what the hint buffer holds
    gm = getattr(instance, "global_mgr", None)
    if gm is not None:
        out["global_sync"] = {
            "send_errors": dict(gm.send_errors),
            "broadcast_errors": dict(gm.broadcast_errors),
            "hints": gm.hints.snapshot(),
        }
    monitor = getattr(instance, "monitor", None)
    if monitor is not None:
        out["health"] = monitor.snapshot()
    frontdoor = getattr(instance, "frontdoor", None)
    if frontdoor is not None:
        out["frontdoor"] = _jsonable(frontdoor.debug_snapshot())
    from gubernator_tpu.net.faults import FAULTS
    if FAULTS.enabled:
        out["faults"] = FAULTS.describe()
    pipe = instance.batcher.pipeline
    if pipe is not None:
        out["pipeline"] = {
            "in_flight": pipe._in_flight,
            "rpc_served": pipe.rpc_served,
            "decisions_staged": pipe.decisions_staged,
            "lanes_staged": pipe.lanes_staged,
            "fused_serving": pipe.fused_serving,
            "staged_serving": pipe.staged_serving,
            "lockstep": pipe.lockstep,
            "depth": pipe.depth,
            "overlap": pipe.overlap_snapshot(),
        }
    analytics = getattr(instance, "analytics", None)
    if analytics is not None:
        snap = analytics.snapshot()
        out["analytics"] = {
            "totals": snap["totals"],
            "occupancy": snap["occupancy"],
            "tenants": snap["tenants"],
            "topk": snap["topk"][:10],  # the full table lives at /topk
        }
    tiers = getattr(instance.engine, "tier_stats", lambda: None)()
    if tiers is not None:
        out["tiers"] = tiers
    slo = getattr(instance, "slo", None)
    if slo is not None:
        out["slo"] = slo.snapshot()
    out["stages"] = instance.metrics.stage_snapshot()
    tracer = getattr(instance, "tracer", None)
    if tracer is not None:
        out["tracing"] = {
            "sample": tracer.sample,
            "recent_traces": tracer.recent_traces(),
        }
    profile = getattr(instance.batcher, "profile", None)
    if profile is not None:
        out["profile"] = profile.status()
    devprof = getattr(instance, "devprof", None)
    if devprof is not None:
        out["devprof"] = devprof.status()
    return out
