"""Host side of the traffic-analytics layer + the SLO burn-rate engine.

`TrafficAnalytics` consumes the per-shard stats vectors the drain's
device reduction ships with each drain result (ops/analytics.py layout)
and maintains the operator-facing state: a rolling hot-key top-K merged
across drains (scored by the device's cumulative count-min estimate,
decayed in lockstep with the on-device sketch halving), per-tenant usage
totals keyed by the qos/fairness tenant (the request `name`), outcome
totals, and the device-computed arena occupancy/churn.  It also owns the
two small registries the pipeline needs while STAGING a drain: the
tenant-name → small-int mapping (the device tracks ids, not strings) and
the (shard, slot) → key labels that turn candidate rows back into
human-readable keys (native-fastpath lanes never materialize keys on the
host, so their slots render as ``s<shard>:slot<n>`` until a python-path
request labels them).

`SLOEngine` evaluates configured objectives (drain p99, shed rate,
availability) as multi-window multi-burn-rate alerts in the Google SRE
workbook style: burn = bad_fraction / error_budget, and an alert fires
only when BOTH a long window and its short companion (window/12) exceed
the window's threshold — fast burns trip the short-window pair quickly,
slow leaks trip the long pair, and a recovered burst un-fires as soon as
the short window drains.  The clock is injectable for deterministic
tests.

Both classes are plain host Python fed from the pipeline's completion
path; neither touches the device.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict, List, Optional

import numpy as np

from gubernator_tpu.ops import analytics as ops

OTHER_TENANT = "other"


class TrafficAnalytics:
    """Rolling merge of the device stats vectors, one per instance."""

    def __init__(self, conf, metrics=None, now_fn=None):
        self.conf = conf
        self.metrics = metrics
        self._now = now_fn or (lambda: time.time() * 1000.0)
        self._lock = threading.Lock()
        # tenant registry: name -> id in [1, tenant_slots); 0 = other.
        self._tenant_ids: Dict[str, int] = {}
        self._tenant_names: Dict[int, str] = {0: OTHER_TENANT}
        # (shard, slot) -> key string, bounded; insertion order approximates
        # recency well enough for eviction (keys re-label on every staging).
        self._labels: Dict[tuple, str] = {}
        self._label_cap = max(4096, 8 * conf.topk)
        # rolling top-K table: (shard, slot) -> row dict
        self._table: Dict[tuple, dict] = {}
        self._table_cap = 8 * conf.topk
        self._last_decay = None
        self.totals = {
            "decisions": 0, "hits": 0, "under_limit": 0, "over_limit": 0,
            "inits": 0, "drains": 0,
        }
        self._occupancy = {"live": 0, "expired": 0}
        self._tenant_totals: Dict[str, dict] = {}

    # ------------------------------------------------- staging-side registries

    def tenant_id(self, name: str) -> int:
        """Small-int id for a tenant name; the device scatter adds by id.
        Once the registry is full, new tenants share row 0 ("other") —
        bounded accounting beats unbounded label cardinality."""
        tid = self._tenant_ids.get(name)
        if tid is not None:
            return tid
        with self._lock:
            tid = self._tenant_ids.get(name)
            if tid is None:
                nxt = len(self._tenant_ids) + 1
                tid = nxt if nxt < self.conf.tenant_slots else 0
                self._tenant_ids[name] = tid
                if tid:
                    self._tenant_names[tid] = name
        return tid

    def label_slot(self, shard: int, slot: int, key: str) -> None:
        """Remember which key occupies (shard, slot) so candidate rows
        resolve to names.  Called from the staging path — keep it cheap."""
        labels = self._labels
        labels[(shard, slot)] = key
        if len(labels) > self._label_cap:
            # drop the oldest ~25% (dict preserves insertion order)
            for k in list(labels)[:self._label_cap // 4]:
                labels.pop(k, None)

    def key_for(self, shard: int, slot: int) -> str:
        return self._labels.get((shard, slot)) or f"s{shard}:slot{slot}"

    # --------------------------------------------------------------- ingest

    def decay_flag(self, now_ms: Optional[float] = None) -> int:
        """1 when the halving cadence elapsed (passed to the device
        reduction as its `decay` scalar), else 0.  The host table halves
        in `ingest` on the same flag so both sides stay comparable."""
        if not self.conf.decay_ms:
            return 0
        now_ms = self._now() if now_ms is None else now_ms
        if self._last_decay is None:
            self._last_decay = now_ms
            return 0
        if now_ms - self._last_decay >= self.conf.decay_ms:
            self._last_decay = now_ms
            return 1
        return 0

    def ingest(self, stats, decayed: int = 0) -> None:
        """Merge one drain's stats block [S_local, V] (host numpy, from
        engine._fetch_local).  Runs on the pipeline completion thread."""
        stats = np.asarray(stats)
        T, K = self.conf.tenant_slots, self.conf.topk
        hdr = stats[:, :ops.HEADER].sum(axis=0)
        trows = stats[:, ops.HEADER:ops.HEADER + T * ops.TENANT_COLS]
        trows = trows.reshape(-1, T, ops.TENANT_COLS).sum(axis=0)
        cands = stats[:, ops.HEADER + T * ops.TENANT_COLS:]
        cands = cands.reshape(-1, K, ops.CAND_COLS)

        m = self.metrics
        with self._lock:
            self.totals["drains"] += 1
            self.totals["decisions"] += int(hdr[ops.IDX_LANES])
            self.totals["hits"] += int(hdr[ops.IDX_HITS])
            self.totals["under_limit"] += int(hdr[ops.IDX_UNDER])
            self.totals["over_limit"] += int(hdr[ops.IDX_OVER])
            self.totals["inits"] += int(hdr[ops.IDX_INIT])
            # occupancy is a level, not a delta: per-shard rows sum to the
            # whole local arena
            self._occupancy = {
                "live": int(stats[:, ops.IDX_LIVE].sum()),
                "expired": int(stats[:, ops.IDX_EXPIRED].sum()),
            }
            if decayed:
                for row in self._table.values():
                    row["score"] >>= 1
                self._table = {k: r for k, r in self._table.items()
                               if r["score"] > 0}

            now_ms = self._now()
            hot = []  # (key, drain_hits) for metrics, outside the lock
            for shard in range(cands.shape[0]):
                for slot, est, dh, dov in cands[shard]:
                    if slot < 0:
                        continue
                    row = self._table.get((shard, slot))
                    if row is None:
                        row = self._table[(shard, slot)] = {
                            "shard": int(shard), "slot": int(slot),
                            "score": 0, "hits": 0, "over": 0, "last_seen": 0}
                    # the estimate is cumulative (the resident sketch), so
                    # overwrite; hits/over are this drain's increments
                    row["score"] = int(est)
                    row["hits"] += int(dh)
                    row["over"] += int(dov)
                    row["last_seen"] = now_ms
                    if dh or dov:
                        hot.append((self.key_for(shard, int(slot)),
                                    int(dh) + int(dov)))
            if len(self._table) > self._table_cap:
                keep = sorted(self._table.items(),
                              key=lambda kv: kv[1]["score"],
                              reverse=True)[:self._table_cap]
                self._table = dict(keep)

            tenant_deltas = []
            for tid in np.nonzero(trows[:, 0])[0]:
                dec, th, tov = (int(x) for x in trows[tid])
                name = self._tenant_names.get(int(tid), OTHER_TENANT)
                tot = self._tenant_totals.setdefault(
                    name, {"decisions": 0, "hits": 0, "over_limit": 0})
                tot["decisions"] += dec
                tot["hits"] += th
                tot["over_limit"] += tov
                tenant_deltas.append((name, dec - tov, tov))

        if m is not None:
            m.observe_churn(int(hdr[ops.IDX_INIT]))
            for key, h in hot:
                m.observe_hot_key(key, h)
            for name, under, over in tenant_deltas:
                m.observe_tenant(name, under, over)

    # ------------------------------------------------------------ snapshots

    def occupancy(self) -> dict:
        with self._lock:
            return dict(self._occupancy)

    def topk_snapshot(self, n: Optional[int] = None) -> List[dict]:
        n = n or self.conf.topk
        with self._lock:
            rows = sorted(self._table.values(),
                          key=lambda r: r["score"], reverse=True)[:n]
            return [{"key": self.key_for(r["shard"], r["slot"]), **r}
                    for r in rows]

    def snapshot(self) -> dict:
        with self._lock:
            totals = dict(self.totals)
            occupancy = dict(self._occupancy)
            tenants = {k: dict(v) for k, v in self._tenant_totals.items()}
        return {
            "totals": totals,
            "occupancy": occupancy,
            "tenants": tenants,
            "topk": self.topk_snapshot(),
        }


class SLOEngine:
    """Multi-window multi-burn-rate evaluation of configured objectives.

    Evidence arrives as good/bad event counts per objective and lands in
    1-second buckets; burn rates are computed over each configured
    (window, threshold) pair at read time, so tests drive it with a fake
    clock and get deterministic firings."""

    BUCKET_S = 1.0

    def __init__(self, conf, now_fn=None):
        self.conf = conf
        self._now = now_fn or time.monotonic
        self._lock = threading.Lock()
        self._windows = conf.windows()
        self._max_window = max(w for w, _ in self._windows)
        # objective -> error budget (allowed bad fraction)
        self.objectives = {
            "drain_p99": conf.drain_budget,
            "shed_rate": conf.shed_budget,
            "availability": 1.0 - conf.availability,
        }
        # objective -> deque of [bucket_ts, good, bad]
        self._buckets = {name: deque() for name in self.objectives}

    def _record(self, name: str, good: int = 0, bad: int = 0) -> None:
        now = self._now()
        ts = int(now / self.BUCKET_S)
        with self._lock:
            dq = self._buckets[name]
            if dq and dq[-1][0] == ts:
                dq[-1][1] += good
                dq[-1][2] += bad
            else:
                dq.append([ts, good, bad])
            horizon = ts - int(self._max_window / self.BUCKET_S) - 1
            while dq and dq[0][0] < horizon:
                dq.popleft()

    # ------------------------------------------------------------- evidence

    def observe_drain(self, wall_seconds: float, decisions: int) -> None:
        """One completed drain: latency evidence for drain_p99, served
        decisions as the good mass for shed_rate/availability."""
        slow = wall_seconds * 1000.0 > self.conf.drain_p99_ms
        self._record("drain_p99", good=0 if slow else 1, bad=1 if slow else 0)
        if decisions > 0:
            self._record("shed_rate", good=decisions)
            self._record("availability", good=decisions)

    def observe_shed(self, n: int = 1) -> None:
        self._record("shed_rate", bad=n)
        self._record("availability", bad=n)

    def observe_error(self, n: int = 1) -> None:
        self._record("availability", bad=n)

    # --------------------------------------------------------------- reading

    def _bad_fraction(self, name: str, window_s: float, now: float) -> float:
        cutoff = int((now - window_s) / self.BUCKET_S)
        good = bad = 0
        for ts, g, b in self._buckets[name]:
            if ts > cutoff:
                good += g
                bad += b
        total = good + bad
        return (bad / total) if total else 0.0

    def burn_rates(self) -> Dict[str, dict]:
        """{objective: {budget, windows: {"300s": burn, ...}, firing}} —
        firing iff ANY (window, threshold) pair has burn > threshold in
        both the window and its window/12 short companion."""
        now = self._now()
        out: Dict[str, dict] = {}
        with self._lock:
            for name, budget in self.objectives.items():
                wins, firing = {}, False
                for win, thr in self._windows:
                    burn = self._bad_fraction(name, win, now) / budget
                    short = self._bad_fraction(
                        name, max(win / 12.0, self.BUCKET_S), now) / budget
                    wins[f"{int(win)}s"] = round(burn, 4)
                    if burn > thr and short > thr:
                        firing = True
                out[name] = {"budget": budget, "windows": wins,
                             "firing": firing}
        return out

    def snapshot(self) -> dict:
        return {
            "objectives": {
                "drain_p99_ms": self.conf.drain_p99_ms,
                "drain_budget": self.conf.drain_budget,
                "shed_budget": self.conf.shed_budget,
                "availability": self.conf.availability,
            },
            "burn_windows": [
                {"window_s": w, "threshold": t} for w, t in self._windows],
            "burn_rates": self.burn_rates(),
        }
