"""Clients for the V1 service (async + sync), plus dial helpers.

Covers both reference clients: the Go thin dial helper (client.go:38-49) and
the Python package (python/gubernator/__init__.py) — one stub class works
with sync and aio channels because grpc exposes the same unary_unary API on
both.  Helpers mirror client.go:52-82.
"""

from __future__ import annotations

import random
import string
from typing import List, Optional, Sequence

import grpc

from gubernator_tpu.api import pb
from gubernator_tpu.api.grpc_api import V1Stub
from gubernator_tpu.api.types import (
    HealthCheckResp,
    RateLimitReq,
    RateLimitResp,
    millisecond_now,
)


def dial_v1_server(address: str) -> "Client":
    """Connect to any node in the cluster (insecure, like client.go:38-49)."""
    return Client(address)


class Client:
    """Synchronous client."""

    def __init__(self, address: str):
        self.channel = grpc.insecure_channel(address)
        self.stub = V1Stub(self.channel)

    def get_rate_limits(self, requests: Sequence[RateLimitReq],
                        timeout: Optional[float] = None) -> List[RateLimitResp]:
        msg = pb.GetRateLimitsReq(requests=[pb.req_to_pb(r) for r in requests])
        resp = self.stub.GetRateLimits(msg, timeout=timeout)
        return [pb.resp_from_pb(m) for m in resp.responses]

    def health_check(self, timeout: Optional[float] = None) -> HealthCheckResp:
        h = self.stub.HealthCheck(pb.HealthCheckReq(), timeout=timeout)
        return HealthCheckResp(status=h.status, message=h.message,
                               peer_count=h.peer_count)

    def close(self) -> None:
        self.channel.close()


class AsyncClient:
    """grpc.aio client with the same surface."""

    def __init__(self, address: str):
        self.channel = grpc.aio.insecure_channel(address)
        self.stub = V1Stub(self.channel)

    async def get_rate_limits(self, requests: Sequence[RateLimitReq],
                              timeout: Optional[float] = None) -> List[RateLimitResp]:
        msg = pb.GetRateLimitsReq(requests=[pb.req_to_pb(r) for r in requests])
        resp = await self.stub.GetRateLimits(msg, timeout=timeout)
        return [pb.resp_from_pb(m) for m in resp.responses]

    async def health_check(self, timeout: Optional[float] = None) -> HealthCheckResp:
        h = await self.stub.HealthCheck(pb.HealthCheckReq(), timeout=timeout)
        return HealthCheckResp(status=h.status, message=h.message,
                               peer_count=h.peer_count)

    async def close(self) -> None:
        await self.channel.close()


# ---- misc helpers (client.go:52-82) ----

def to_timestamp(duration_ms: int) -> int:
    """Convert a duration from now into a ms-epoch timestamp."""
    return millisecond_now() + duration_ms


def random_peer(peers: List[str]) -> str:
    return random.choice(peers)


def random_string(prefix: str, n: int = 10) -> str:
    return prefix + "".join(random.choices(string.ascii_letters + string.digits, k=n))
