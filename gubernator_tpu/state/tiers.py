"""Tiered key-state hierarchy: the fixed arena as a managed cache over an
unbounded (2^30+) logical keyspace.

Three tiers, coldest reconstructible from nothing:

  hot   the dense SoA device arena (ops/kernel.py BucketState) — layout,
        kernels and every bench path untouched; the SlotTable still owns
        which key occupies which slot.
  warm  this module: a host-side SoA store of LIVE bucket rows evicted
        from the arena, held in the snapshot serialization from
        state/snapshot.py — either absolute int64 times or compact32
        pair-rebased deltas against the store epoch, encoded/decoded in
        BATCHES through the fused megakernel's own jitted codec
        (snapshot.rebase_encode/rebase_decode) so the warm image cannot
        drift from the serving path's int32 time math.
  cold  nothing stored.  A miss in both tiers re-initializes from the
        request's self-describing config — exactly the reference's
        stateless-client semantics, so "arena full" becomes a cache-miss
        cost instead of a correctness cliff.

Demotion rides SlotTable._reclaim (state/arena.py spill hooks): evicting a
committed LIVE entry hands (key, slot) to `TierManager.on_spill`; the
engine gathers every spilled device row in ONE batched gather at the
pre-dispatch fence (core/engine.py _tier_fence), while the victim rows are
still intact on device.  Promotion happens at window-encode time: a
warm-resident key rehydrates into a freshly upserted slot and its row is
scattered back in the same fence, BEFORE the drain dispatches — so
decisions are bit-identical to an infinite-arena oracle (tests/
test_tiers.py runs the differential suite).  A key evicted and re-
requested within one un-dispatched drain short-circuits: the pending
spill becomes the promotion's row source (gather → scatter, never touching
the warm store), which keeps the demote→re-promote-mid-stream case exact.

Victim selection is heat-aware: the per-drain device analytics (PR 8
count-min hot-key scores, fetched at zero extra round trips) feed a
host-side heat estimate; the SlotTable ranks its LRU-head sample by heat
and spills the coldest.  With analytics off every heat reads 0.0 and the
policy degrades to the seed's strict LRU.

The warm tier requires the Python routing backend (the native C++ router
keeps fingerprints, not key strings — the same constraint as live key
migration) and a single-process engine; `RateLimitEngine.enable_tiers`
enforces both.
"""

from __future__ import annotations

import logging
from collections import OrderedDict
from typing import Dict, List, Optional

import numpy as np

from gubernator_tpu.state.snapshot import rebase_decode, rebase_encode

log = logging.getLogger("gubernator.tiers")

_ROW_FIELDS = ("limit", "duration", "remaining", "tstamp", "expire", "algo")
_VAL_FIELDS = ("limit", "duration", "remaining")
_TIME_FIELDS = ("tstamp", "expire")

# pallas_kernel._REBASE_LIM: the compact32 clip range around the epoch
_REBASE_LIM = (2 ** 31) - 16
_I32 = 2 ** 31


def _pad_pow2(n: int) -> int:
    """Same shape bucketing as core/engine._pad_pow2: the jitted codec
    compiles for a handful of batch shapes, not one per call."""
    return max(8, 1 << (n - 1).bit_length())


class WarmStore:
    """Fixed-capacity host SoA store of demoted bucket rows.

    Rows live in one of two layouts (per store, chosen at construction):

      int64      every column int64 (algo int32) — always representable.
      compact32  limit/duration/remaining int32; tstamp/expire int32
                 deltas pair-rebased against the store epoch — half the
                 bytes per row.  Rows outside the rebase clip range or
                 int32 value range go to a small int64 overflow side map
                 instead of being truncated, so the layout choice is never
                 lossy.

    Keys index an insertion-ordered map (oldest first); on overflow the
    store evicts an EXPIRED resident first, else the oldest — cold is
    reconstructible, so dropping is a miss cost, not data loss.
    """

    def __init__(self, capacity: int, layout: str = "int64",
                 epoch: int = 0):
        if capacity <= 0:
            raise ValueError("warm capacity must be positive")
        if layout not in ("int64", "compact32"):
            raise ValueError(f"unknown warm layout {layout!r}")
        self.capacity = capacity
        self.layout = layout
        self.epoch = int(epoch)
        compact = layout == "compact32"
        vdt = np.int32 if compact else np.int64
        tdt = np.int32 if compact else np.int64
        self._cols: Dict[str, np.ndarray] = {
            "limit": np.zeros(capacity, vdt),
            "duration": np.zeros(capacity, vdt),
            "remaining": np.zeros(capacity, vdt),
            "tstamp": np.zeros(capacity, tdt),
            "expire": np.zeros(capacity, tdt),
            "algo": np.zeros(capacity, np.int32),
        }
        # absolute expire per row (int64) regardless of layout: expiry
        # checks and overflow eviction never pay a decode
        self._abs_expire = np.zeros(capacity, np.int64)
        self._index: "OrderedDict[str, int]" = OrderedDict()
        self._free = list(range(capacity - 1, -1, -1))
        # compact32 rows that failed the range check, canonical int64
        self._over: Dict[str, dict] = {}
        self.evictions = 0

    def __len__(self) -> int:
        return len(self._index) + len(self._over)

    def __contains__(self, key: str) -> bool:
        return key in self._index or key in self._over

    def expire_of(self, key: str) -> Optional[int]:
        i = self._index.get(key)
        if i is not None:
            return int(self._abs_expire[i])
        row = self._over.get(key)
        return None if row is None else row["expire"]

    def nbytes(self) -> int:
        """Allocated SoA bytes plus the overflow side map estimate."""
        soa = sum(a.nbytes for a in self._cols.values())
        return soa + self._abs_expire.nbytes + 96 * len(self._over)

    # ----------------------------------------------------------------- put

    def _compact_ok(self, row: dict) -> bool:
        for f in _VAL_FIELDS:
            if not (-_I32 <= row[f] < _I32):
                return False
        for f in _TIME_FIELDS:
            d = row[f] - self.epoch
            if not (-_REBASE_LIM <= d <= _REBASE_LIM):
                return False
        return True

    def _alloc(self, key: str, now: int) -> Optional[int]:
        if self._free:
            i = self._free.pop()
        else:
            victim = None
            for scanned, (k, ri) in enumerate(self._index.items()):
                if self._abs_expire[ri] <= now:
                    victim = k
                    break
                if scanned >= 8:
                    break
            if victim is None:
                if not self._index:
                    return None  # capacity entirely held by overflow rows
                victim = next(iter(self._index))
            i = self._index.pop(victim)
            self.evictions += 1
        self._index[key] = i
        return i

    def put_batch(self, rows: List[dict], now: int) -> int:
        """Insert canonical int64 row dicts (encode once, batched).  A key
        already resident is overwritten in place.  Returns rows stored."""
        if not rows:
            return 0
        if self.layout == "compact32":
            fits = [self._compact_ok(r) for r in rows]
            for r, ok in zip(rows, fits):
                if not ok:
                    self._over[r["key"]] = {f: int(r[f]) for f in _ROW_FIELDS}
                    self._over[r["key"]]["key"] = r["key"]
                    self._index.pop(r["key"], None)
            rows = [r for r, ok in zip(rows, fits) if ok]
            if not rows:
                return len(fits)
        idxs = []
        kept = []
        for r in rows:
            key = r["key"]
            self._over.pop(key, None)
            i = self._index.get(key)
            if i is not None:
                self._index.move_to_end(key)
            else:
                i = self._alloc(key, now)
                if i is None:
                    self.evictions += 1
                    continue
            idxs.append(i)
            kept.append(r)
        if not kept:
            return 0
        n = len(kept)
        ii = np.asarray(idxs, np.int64)
        for f in _VAL_FIELDS + ("algo",):
            self._cols[f][ii] = [r[f] for r in kept]
        times = np.asarray([[r["tstamp"], r["expire"]] for r in kept],
                           np.int64)
        if self.layout == "compact32":
            m = _pad_pow2(n)
            padded = np.zeros((m, 2), np.int64)
            padded[:n] = times
            rel = rebase_encode(padded, np.zeros((m, 2), bool), self.epoch)
            self._cols["tstamp"][ii] = rel[:n, 0]
            self._cols["expire"][ii] = rel[:n, 1]
        else:
            self._cols["tstamp"][ii] = times[:, 0]
            self._cols["expire"][ii] = times[:, 1]
        self._abs_expire[ii] = times[:, 1]
        return n

    # ---------------------------------------------------------------- take

    def take(self, key: str, now: int) -> Optional[dict]:
        """Remove and return the row for `key`, or None when absent or
        already expired (an expired warm row reads as a miss on device
        anyway — promoting it would only ship dead weight).

        compact32 rows come back RAW (rel=True, int32 deltas): the caller
        batch-decodes at the dispatch fence through the kernel codec, so
        per-key takes stay allocation-only."""
        row = self._over.pop(key, None)
        if row is not None:
            if row["expire"] <= now:
                return None
            out = dict(row)
            out["rel"] = False
            return out
        i = self._index.pop(key, None)
        if i is None:
            return None
        self._free.append(i)
        if self._abs_expire[i] <= now:
            return None
        out = {f: int(self._cols[f][i]) for f in _ROW_FIELDS}
        out["key"] = key
        out["rel"] = self.layout == "compact32"
        out["abs_expire"] = int(self._abs_expire[i])
        return out

    # ------------------------------------------------------- serialization

    def export_rows(self) -> tuple:
        """(keys, {field: int64 array}) — every resident row in canonical
        absolute int64 form (snapshot persistence; state/snapshot.py packs
        these as optional npz arrays, old readers simply ignore them)."""
        keys = list(self._index.keys())
        cols = {}
        if keys:
            ii = np.asarray([self._index[k] for k in keys], np.int64)
            for f in _VAL_FIELDS + ("algo",):
                cols[f] = self._cols[f][ii].astype(np.int64)
            if self.layout == "compact32":
                n = len(keys)
                m = _pad_pow2(n)
                rel = np.zeros((m, 2), np.int32)
                rel[:n, 0] = self._cols["tstamp"][ii]
                rel[:n, 1] = self._cols["expire"][ii]
                out = rebase_decode(rel, self.epoch)
                cols["tstamp"] = out[:n, 0]
                cols["expire"] = out[:n, 1]
            else:
                cols["tstamp"] = self._cols["tstamp"][ii].astype(np.int64)
                cols["expire"] = self._cols["expire"][ii].astype(np.int64)
        else:
            cols = {f: np.empty(0, np.int64) for f in _ROW_FIELDS}
        for key, row in self._over.items():
            keys.append(key)
            for f in _ROW_FIELDS:
                cols[f] = np.append(cols[f], np.int64(row[f]))
        return keys, cols

    def restore_rows(self, keys: List[str], cols: Dict[str, np.ndarray],
                     now: int, shift: int = 0) -> int:
        """Re-insert exported rows (daemon restart: the warm tier rides the
        same snapshot machinery as the arena).  `shift` rebases times into
        a new clock domain, mirroring engine.import_state."""
        rows = []
        for j, key in enumerate(keys):
            row = {f: int(cols[f][j]) for f in _ROW_FIELDS}
            if shift and row["expire"]:
                row["tstamp"] += shift
                row["expire"] += shift
            row["key"] = key
            if row["expire"] > now:
                rows.append(row)
        return self.put_batch(rows, now)


class TierManager:
    """Bookkeeping between the SlotTable spill hooks, the warm store, and
    the engine's pre-dispatch fence.  All methods run on the engine's
    single dispatch thread (the same quiesce contract as migration), so no
    locking is needed."""

    def __init__(self, conf, epoch: int, analytics=None):
        self.conf = conf
        self.warm = WarmStore(conf.warm_rows, conf.layout, epoch)
        self.analytics = analytics
        self._heat: Dict[str, float] = {}
        self.fences = 0
        # key -> (shard, slot): committed victims evicted since the last
        # fence, device rows still intact until the next dispatch
        self.pending_spills: "OrderedDict[str, tuple]" = OrderedDict()
        # key -> [shard, slot, row|None, spill_src|None]: rows to scatter
        # at the fence.  row is a WarmStore.take dict; spill_src routes a
        # demote→re-promote-in-one-drain key straight from the gather.
        self.pending_promos: "OrderedDict[str, list]" = OrderedDict()
        self.counters = {
            "promotions": 0,
            "promotions_from_spill": 0,
            "demotions": 0,
            "demote_dropped_expired": 0,
            "demote_dropped_stale": 0,
            "warm_hits": 0,
            "cold_misses": 0,
        }

    # ------------------------------------------------------------ heat feed

    def heat(self, key: str) -> float:
        return self._heat.get(key, 0.0)

    def refresh_heat(self) -> None:
        """Pull the analytics rolling top-K into the per-key heat map the
        eviction sampler reads.  Cheap (top-K is small); called from
        tier_maintain and periodically from the fence."""
        if self.analytics is None:
            return
        try:
            self._heat = {r["key"]: float(r["score"])
                          for r in self.analytics.topk_snapshot()}
        except Exception:  # observability must never break serving
            log.exception("tier heat refresh failed")

    # --------------------------------------------------------- spill intake

    def on_spill(self, shard: int, key: str, slot: int, expire: int,
                 stale: bool) -> None:
        """SlotTable spill hook: a committed entry was evicted.  `stale`
        means the victim was touched by the current un-dispatched drain
        (only possible when every LRU-head candidate was) — its device row
        misses that drain's hits, so it drops to cold instead of storing a
        wrong row."""
        promo = self.pending_promos.pop(key, None)
        if promo is not None:
            # a key promoted THIS drain got evicted again before dispatch:
            # the row never reached the device, so just return it to warm
            # (or drop a from-spill promo back to the spill list)
            if promo[3] is not None:
                self.pending_spills[key] = promo[3]
            elif promo[2] is not None:
                self._restore_row(promo[2])
            return
        if stale:
            self.counters["demote_dropped_stale"] += 1
            return
        self.pending_spills[key] = (shard, slot)

    def _restore_row(self, row: dict) -> None:
        """Put a previously taken row back (promotion cancelled before its
        scatter).  Raw compact rows re-encode through put_batch after an
        exact python-side reabs (rel values are unclipped by construction,
        so epoch + rel is the codec's own inverse)."""
        canon = {f: int(row[f]) for f in _VAL_FIELDS + ("algo",)}
        if row.get("rel"):
            canon["tstamp"] = self.warm.epoch + int(row["tstamp"])
            canon["expire"] = self.warm.epoch + int(row["expire"])
        else:
            canon["tstamp"] = int(row["tstamp"])
            canon["expire"] = int(row["expire"])
        canon["key"] = row["key"]
        self.warm.put_batch([canon], now=0)

    # ----------------------------------------------------- staging promotion

    def stage_promote(self, shard: int, table, key: str, now: int,
                      duration: int) -> Optional[int]:
        """Called from engine._stage_requests for a key absent from the hot
        table.  Returns the upserted slot when the key rehydrates from the
        warm tier (or from a same-drain pending spill), else None — the
        caller then takes the ordinary cold-miss lookup path."""
        src = self.pending_spills.pop(key, None)
        if src is not None:
            # demoted earlier in this drain, now requested again: the old
            # device row is still intact — route it through the fence
            # gather into the new slot
            slot = table.upsert(key, now, now + duration)
            self.pending_promos[key] = [shard, slot, None, src]
            self.counters["warm_hits"] += 1
            self.counters["promotions_from_spill"] += 1
            return slot
        row = self.warm.take(key, now)
        if row is None:
            self.counters["cold_misses"] += 1
            return None
        expire = row["abs_expire"] if row.get("rel") else row["expire"]
        slot = table.upsert(key, now, expire)
        self.pending_promos[key] = [shard, slot, row, None]
        self.counters["warm_hits"] += 1
        return slot

    # ------------------------------------------------------------- the fence

    def drain_pending(self) -> tuple:
        """Hand the fence its work lists and reset: (spills, promos) where
        spills is [(key, shard, slot)] and promos is the pending_promos
        values with their keys."""
        spills = [(k, s[0], s[1]) for k, s in self.pending_spills.items()]
        promos = [(k, p) for k, p in self.pending_promos.items()]
        self.pending_spills = OrderedDict()
        self.pending_promos = OrderedDict()
        return spills, promos

    def decode_rows(self, rows: List[dict]) -> List[dict]:
        """Batch-decode raw compact32 rows to canonical int64 through the
        kernel codec (one call per fence, padded shape bucketing)."""
        rel_rows = [r for r in rows if r.get("rel")]
        if rel_rows:
            n = len(rel_rows)
            m = _pad_pow2(n)
            rel = np.zeros((m, 2), np.int32)
            for j, r in enumerate(rel_rows):
                rel[j, 0] = r["tstamp"]
                rel[j, 1] = r["expire"]
            out = rebase_decode(rel, self.warm.epoch)
            for j, r in enumerate(rel_rows):
                r["tstamp"] = int(out[j, 0])
                r["expire"] = int(out[j, 1])
                r["rel"] = False
        return rows

    # ------------------------------------------------------------- reporting

    def stats(self) -> dict:
        out = dict(self.counters)
        out.update({
            "warm_rows": len(self.warm),
            "warm_capacity": self.warm.capacity,
            "warm_bytes": self.warm.nbytes(),
            "warm_evictions": self.warm.evictions,
            "warm_layout": self.warm.layout,
            "fences": self.fences,
            "pending_spills": len(self.pending_spills),
            "pending_promotions": len(self.pending_promos),
        })
        return out
