"""Versioned, checksummed snapshots of the device arena + key maps.

The rate-limiter analogue of the reference's Loader/PersistentStore
(persistent_store.go): a daemon restart must not zero every counter.  A
snapshot captures

  * the SoA arena planes (regular [S_local, C] + GLOBAL [G] + gcfg) as a
    device->host export,
  * the key->slot maps (Python SlotTable keys, or the native router's
    fingerprint table — entry index == device slot, so fingerprints alone
    keep the restored map coherent with the restored planes),
  * metadata: geometry, creation time, layout, compact-soundness.

Two on-disk time layouts, chosen per snapshot:

  "int64"     tstamp/expire stored as absolute ms-epoch int64 — always valid.
  "compact32" tstamp/expire stored as int32 deltas REBASED against the
              snapshot timestamp, and limit/duration/remaining truncated to
              int32 — half the plane bytes.  The rebase runs through
              ops/pallas_kernel's _pair_rebase/_pair_reabs (the fused
              megakernel's own helpers), so the snapshot codec CANNOT drift
              from the serving path's int32 time math.  Chosen only when
              every live value round-trips exactly (engine export checks),
              so restore is bit-identical to the int64 layout either way.

Restore rebases times back to absolute by default (downtime counts against
TTLs, matching an uninterrupted oracle).  `rebase_to` instead shifts every
timestamp by (rebase_to - snapshot now) — for restoring into a different
clock domain while preserving each bucket's REMAINING lifetime.

File format (version 1):

  8 bytes   magic b"GUBSNAP\\x01"
  4 bytes   format version (u32 LE)
  4 bytes   crc32 of the payload (u32 LE)
  payload   npz archive (numpy savez) holding the meta JSON + every array

A truncated or bit-flipped file fails the crc (or the parse) and raises
SnapshotError — restore_engine turns that into a logged cold start, never a
crash.
"""

from __future__ import annotations

import io
import json
import logging
import os
import struct
import zlib
from dataclasses import dataclass, field
from typing import Dict, List, Optional

import numpy as np

log = logging.getLogger("gubernator.snapshot")

MAGIC = b"GUBSNAP\x01"
VERSION = 1

# int32 sentinel marking a never-initialized slot's times in the compact32
# layout (expire == 0 on device).  Outside the +/-_REBASE_LIM clip range, so
# it can never collide with a real rebased delta.
DEAD_REL = -(2 ** 31)

_REG_PLANES = ("limit", "duration", "remaining", "tstamp", "expire", "algo")
_CFG_PLANES = ("limit", "duration", "algo")

# Top of the known algorithm alphabet (api/types.py Algorithm.CONCURRENCY).
# Restored rows above this were written by a newer build whose packed-column
# semantics this one cannot interpret — see _drop_unknown_algorithm_rows.
_MAX_ALGO = 4


class SnapshotError(Exception):
    """Unusable snapshot: bad magic/version/checksum, truncated payload, or
    a geometry mismatch with the restoring engine."""


@dataclass
class ArenaSnapshot:
    """Host-side image of one engine's state (this process's shard blocks).

    planes/gplanes/gcfg hold int64/int32 numpy arrays in the INT64 layout —
    the compact32 encoding exists only on the wire (serialize/deserialize),
    so every in-memory consumer sees one canonical form.
    """

    now: int                      # ms epoch at export
    layout: str                   # requested wire layout: int64 | compact32
    num_shards: int
    capacity_per_shard: int
    global_capacity: int
    num_local_shards: int
    local_shard_offset: int
    compact_sound: bool
    backend: str                  # "python" | "native"
    planes: Dict[str, np.ndarray]     # regular arena [S_local, C]
    gplanes: Dict[str, np.ndarray]    # GLOBAL arena [G]
    gcfg: Dict[str, np.ndarray]       # GLOBAL config [G]
    # python backend: per local shard, (keys, slot i32[n], expire i64[n])
    tables: List[tuple] = field(default_factory=list)
    # native backend: per local shard, (fp u64[n], slot i32[n], expire i64[n])
    native_tables: List[tuple] = field(default_factory=list)
    gtable: tuple = ()            # (keys, slot, expire) of the GLOBAL table
    gpending: List[str] = field(default_factory=list)
    # warm tier (state/tiers.py), when enabled at export: (keys,
    # {plane: int64[n]}) in canonical absolute form.  Optional npz keys on
    # the wire — version-1 readers that predate tiers simply ignore them,
    # and their absence restores as an empty warm store (no version bump).
    warm: Optional[tuple] = None
    # concurrency-lease book rows (algorithms/leases.py export_rows):
    # [(key, client, count, expire)].  Same optional-npz-key pattern as
    # `warm` — absent restores as an empty book, no version bump.
    leases: List[tuple] = field(default_factory=list)

    def total_keys(self) -> int:
        reg = (sum(len(t[1]) for t in self.native_tables)
               if self.backend == "native"
               else sum(len(t[1]) for t in self.tables))
        return reg + (len(self.gtable[1]) if self.gtable else 0)


# ---------------------------------------------------------------- time codec


def _pair_codec():
    """The fused megakernel's (lo, hi) int32 rebase helpers, jitted once
    over flat arrays.  Importing lazily keeps `state` free of jax at module
    import (host-only tools load this module too)."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    from gubernator_tpu.ops import pallas_kernel as pk

    @jax.jit
    def enc(t, now):
        pair = lax.bitcast_convert_type(t, jnp.int32)       # [N, 2]
        npair = lax.bitcast_convert_type(now, jnp.int32)    # [2]
        return pk._pair_rebase(pair[:, 0], pair[:, 1], npair[0], npair[1])

    @jax.jit
    def dec(rel, now):
        npair = lax.bitcast_convert_type(now, jnp.int32)
        lo, hi = pk._pair_reabs(rel, npair[0], npair[1])
        return lax.bitcast_convert_type(
            jnp.stack([lo, hi], axis=-1), jnp.int64)

    return enc, dec


_codec = None


def _codec_fns():
    global _codec
    if _codec is None:
        _codec = _pair_codec()
    return _codec


def rebase_encode(times: np.ndarray, dead: np.ndarray, now: int) -> np.ndarray:
    """int64 ms-epoch -> int32 delta vs `now` via _pair_rebase; dead slots
    (expire == 0 on device) carry the DEAD_REL sentinel instead."""
    enc, _ = _codec_fns()
    rel = np.asarray(enc(np.ascontiguousarray(times, np.int64).reshape(-1),
                         np.int64(now)))
    rel = rel.reshape(times.shape).copy()
    rel[dead] = DEAD_REL
    return rel


def rebase_decode(rel: np.ndarray, now: int) -> np.ndarray:
    """Inverse of rebase_encode: int32 delta -> absolute int64 (sentinel
    slots decode back to 0)."""
    _, dec = _codec_fns()
    out = np.asarray(dec(np.ascontiguousarray(rel, np.int32).reshape(-1),
                         np.int64(now)))
    out = out.reshape(rel.shape).copy()
    out[rel == DEAD_REL] = 0
    return out


def compact_encodable(snap: "ArenaSnapshot") -> bool:
    """May this snapshot travel in the compact32 layout losslessly?  Times
    of live slots must sit within the rebase clip range of snap.now, and
    every value plane must fit int32 (the same caps the compact serving
    wire enforces — engine._compact_sound implies them for live rows, but a
    pre-soundness-trip arena may hold wider values, so check the data)."""
    lim = (2 ** 31) - 16  # pallas_kernel._REBASE_LIM
    i32 = 2 ** 31

    def _planes_ok(planes):
        dead = planes["expire"] == 0
        for name in ("limit", "duration", "remaining"):
            a = planes[name]
            if a.size and (a.min() < -i32 or a.max() >= i32):
                return False
        for name in ("tstamp", "expire"):
            d = planes[name][~dead] - snap.now
            if d.size and (d.min() < -lim or d.max() > lim):
                return False
        return True

    return _planes_ok(snap.planes) and _planes_ok(snap.gplanes) and all(
        not (a.size and (a.min() < -i32 or a.max() >= i32))
        for n, a in snap.gcfg.items() if n != "algo")


# -------------------------------------------------------------- wire format


def _pack_keys(keys: List[str]):
    blob = b"".join(k.encode("utf-8") for k in keys)
    ends = np.cumsum([len(k.encode("utf-8")) for k in keys]).astype(np.int64) \
        if keys else np.empty(0, np.int64)
    return np.frombuffer(blob, np.uint8).copy(), ends


def _unpack_keys(blob: np.ndarray, ends: np.ndarray) -> List[str]:
    raw = blob.tobytes()
    keys, start = [], 0
    for end in ends.tolist():
        keys.append(raw[start:end].decode("utf-8"))
        start = end
    return keys


def dumps(snap: ArenaSnapshot) -> bytes:
    """Serialize with the layout the snapshot asks for, silently widening
    to int64 when compact32 cannot represent the data exactly."""
    layout = snap.layout
    if layout == "compact32" and not compact_encodable(snap):
        log.warning("snapshot data exceeds the compact32 range; "
                    "writing the int64 layout instead")
        layout = "int64"

    arrays: Dict[str, np.ndarray] = {}

    def put_planes(prefix: str, planes: Dict[str, np.ndarray]):
        dead = planes["expire"] == 0
        for name, a in planes.items():
            if layout == "compact32" and name in ("tstamp", "expire"):
                arrays[f"{prefix}{name}"] = rebase_encode(a, dead, snap.now)
            elif layout == "compact32" and name in ("limit", "duration",
                                                    "remaining"):
                arrays[f"{prefix}{name}"] = a.astype(np.int32)
            else:
                arrays[f"{prefix}{name}"] = a

    put_planes("reg_", snap.planes)
    put_planes("g_", snap.gplanes)
    for name, a in snap.gcfg.items():
        arrays[f"gcfg_{name}"] = a

    for i, (keys, slots, expires) in enumerate(snap.tables):
        blob, ends = _pack_keys(keys)
        arrays[f"t{i}_keys"] = blob
        arrays[f"t{i}_ends"] = ends
        arrays[f"t{i}_slot"] = np.asarray(slots, np.int32)
        arrays[f"t{i}_expire"] = np.asarray(expires, np.int64)
    for i, (fp, slots, expires) in enumerate(snap.native_tables):
        arrays[f"n{i}_fp"] = np.asarray(fp, np.uint64)
        arrays[f"n{i}_slot"] = np.asarray(slots, np.int32)
        arrays[f"n{i}_expire"] = np.asarray(expires, np.int64)
    if snap.gtable:
        keys, slots, expires = snap.gtable
        blob, ends = _pack_keys(keys)
        arrays["gt_keys"] = blob
        arrays["gt_ends"] = ends
        arrays["gt_slot"] = np.asarray(slots, np.int32)
        arrays["gt_expire"] = np.asarray(expires, np.int64)
    if snap.warm is not None:
        # warm rows travel int64 canonical regardless of the plane layout:
        # the store re-encodes per its own epoch on restore, and these rows
        # are few relative to the arena planes
        wkeys, wcols = snap.warm
        blob, ends = _pack_keys(wkeys)
        arrays["warm_keys"] = blob
        arrays["warm_ends"] = ends
        for name in _REG_PLANES:
            arrays[f"warm_{name}"] = np.asarray(wcols[name], np.int64)
    if snap.leases:
        lkeys, lclients, lcount, lexpire = zip(*snap.leases)
        blob, ends = _pack_keys(list(lkeys))
        arrays["lease_keys"] = blob
        arrays["lease_ends"] = ends
        cblob, cends = _pack_keys(list(lclients))
        arrays["lease_clients"] = cblob
        arrays["lease_cends"] = cends
        arrays["lease_count"] = np.asarray(lcount, np.int64)
        arrays["lease_expire"] = np.asarray(lexpire, np.int64)

    meta = {
        "now": snap.now,
        "layout": layout,
        "num_shards": snap.num_shards,
        "capacity_per_shard": snap.capacity_per_shard,
        "global_capacity": snap.global_capacity,
        "num_local_shards": snap.num_local_shards,
        "local_shard_offset": snap.local_shard_offset,
        "compact_sound": snap.compact_sound,
        "backend": snap.backend,
        "gpending": list(snap.gpending),
    }
    arrays["__meta__"] = np.frombuffer(
        json.dumps(meta).encode("utf-8"), np.uint8).copy()

    buf = io.BytesIO()
    np.savez(buf, **arrays)
    payload = buf.getvalue()
    head = MAGIC + struct.pack("<II", VERSION, zlib.crc32(payload))
    return head + payload


def loads(data: bytes) -> ArenaSnapshot:
    """Parse + verify a snapshot blob; raises SnapshotError on anything
    short of a bit-exact, version-compatible payload."""
    if len(data) < len(MAGIC) + 8 or data[:len(MAGIC)] != MAGIC:
        raise SnapshotError("not a gubernator snapshot (bad magic)")
    version, crc = struct.unpack_from("<II", data, len(MAGIC))
    if version != VERSION:
        raise SnapshotError(f"unsupported snapshot version {version}")
    payload = data[len(MAGIC) + 8:]
    if zlib.crc32(payload) != crc:
        raise SnapshotError("snapshot checksum mismatch (truncated or "
                            "corrupted file)")
    try:
        with np.load(io.BytesIO(payload)) as z:
            arrays = {k: z[k] for k in z.files}
        meta = json.loads(arrays.pop("__meta__").tobytes().decode("utf-8"))
    except SnapshotError:
        raise
    except Exception as e:
        raise SnapshotError(f"malformed snapshot payload: {e}") from None

    layout = meta["layout"]
    now = int(meta["now"])

    def get_planes(prefix: str) -> Dict[str, np.ndarray]:
        planes = {}
        for name in _REG_PLANES:
            a = arrays[f"{prefix}{name}"]
            if layout == "compact32" and name in ("tstamp", "expire"):
                a = rebase_decode(a, now)
            elif name != "algo":
                a = a.astype(np.int64)
            planes[name] = a
        return planes

    try:
        planes = get_planes("reg_")
        gplanes = get_planes("g_")
        gcfg = {name: arrays[f"gcfg_{name}"] for name in _CFG_PLANES}
        tables, native_tables = [], []
        for i in range(int(meta["num_local_shards"])):
            if f"t{i}_slot" in arrays:
                tables.append((
                    _unpack_keys(arrays[f"t{i}_keys"], arrays[f"t{i}_ends"]),
                    arrays[f"t{i}_slot"], arrays[f"t{i}_expire"]))
            elif f"n{i}_slot" in arrays:
                native_tables.append((
                    arrays[f"n{i}_fp"], arrays[f"n{i}_slot"],
                    arrays[f"n{i}_expire"]))
        gtable = ()
        if "gt_slot" in arrays:
            gtable = (_unpack_keys(arrays["gt_keys"], arrays["gt_ends"]),
                      arrays["gt_slot"], arrays["gt_expire"])
        warm = None
        if "warm_ends" in arrays:
            warm = (_unpack_keys(arrays["warm_keys"], arrays["warm_ends"]),
                    {name: arrays[f"warm_{name}"].astype(np.int64)
                     for name in _REG_PLANES})
        leases = []
        if "lease_ends" in arrays:
            leases = list(zip(
                _unpack_keys(arrays["lease_keys"], arrays["lease_ends"]),
                _unpack_keys(arrays["lease_clients"],
                             arrays["lease_cends"]),
                arrays["lease_count"].tolist(),
                arrays["lease_expire"].tolist()))
    except KeyError as e:
        raise SnapshotError(f"snapshot payload missing array {e}") from None

    snap = ArenaSnapshot(
        now=now, layout=layout,
        num_shards=int(meta["num_shards"]),
        capacity_per_shard=int(meta["capacity_per_shard"]),
        global_capacity=int(meta["global_capacity"]),
        num_local_shards=int(meta["num_local_shards"]),
        local_shard_offset=int(meta["local_shard_offset"]),
        compact_sound=bool(meta["compact_sound"]),
        backend=meta["backend"],
        planes=planes, gplanes=gplanes, gcfg=gcfg,
        tables=tables, native_tables=native_tables, gtable=gtable,
        gpending=list(meta.get("gpending", ())),
        warm=warm, leases=leases,
    )
    _drop_unknown_algorithm_rows(snap)
    return snap


def _drop_unknown_algorithm_rows(snap: ArenaSnapshot) -> int:
    """Forward-compat restore: rows whose algorithm value is outside the
    alphabet this build knows (> _MAX_ALGO) were written by a newer version
    whose packed-column semantics we cannot interpret — e.g. a sliding
    register decoded as a token balance would serve nonsense.  Those rows
    log-and-drop to a cold start: expiry is forced to the dead sentinel and
    their key-table entries are removed, so the keys re-init on first
    touch.  Returns the number of rows dropped."""

    def _bad_slots(planes):
        a = np.asarray(planes["algo"])
        return ((a < 0) | (a > _MAX_ALGO)) & (np.asarray(
            planes["expire"]) != 0)

    def _prune_table(table, drop):
        keys, slots, expires = table
        slots = np.asarray(slots)
        keep = [j for j, sl in enumerate(slots.tolist()) if sl not in drop]
        if isinstance(keys, list):
            kept_keys = [keys[j] for j in keep]
        else:
            kept_keys = np.asarray(keys)[keep]
        return (kept_keys, slots[keep], np.asarray(expires)[keep])

    dropped = 0
    bad = _bad_slots(snap.planes)
    if bad.any():
        dropped += int(bad.sum())
        snap.planes["expire"] = np.where(bad, 0, snap.planes["expire"])
        for s in range(bad.shape[0]):
            drop = set(np.nonzero(bad[s])[0].tolist())
            if not drop:
                continue
            if s < len(snap.tables):
                snap.tables[s] = _prune_table(snap.tables[s], drop)
            if s < len(snap.native_tables):
                snap.native_tables[s] = _prune_table(
                    snap.native_tables[s], drop)
    gbad = _bad_slots(snap.gplanes)
    ga = np.asarray(snap.gcfg["algo"])
    gbad = gbad | ((ga < 0) | (ga > _MAX_ALGO)) & (
        np.asarray(snap.gplanes["expire"]) != 0)
    if gbad.any():
        dropped += int(gbad.sum())
        snap.gplanes["expire"] = np.where(gbad, 0, snap.gplanes["expire"])
        if snap.gtable:
            snap.gtable = _prune_table(
                snap.gtable, set(np.nonzero(gbad)[0].tolist()))
    if dropped:
        log.warning(
            "snapshot carries %d rows with unknown algorithm values "
            "(newer writer?); dropping them to a cold start", dropped)
    return dropped


# ---------------------------------------------------------------- file I/O


def save(snap: ArenaSnapshot, path: str) -> int:
    """Atomic write (tmp + rename): a crash mid-write leaves the previous
    snapshot intact.  Returns the byte size written."""
    from gubernator_tpu.net.faults import FAULTS, SEAM_SNAPSHOT_IO
    if FAULTS.enabled:
        FAULTS.on_sync(SEAM_SNAPSHOT_IO, path)
    data = dumps(snap)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    return len(data)


def load(path: str) -> ArenaSnapshot:
    from gubernator_tpu.net.faults import FAULTS, SEAM_SNAPSHOT_IO
    if FAULTS.enabled:
        FAULTS.on_sync(SEAM_SNAPSHOT_IO, path)
    with open(path, "rb") as f:
        return loads(f.read())


def snapshot_path(directory: str, local_shard_offset: int = 0,
                  multiprocess: bool = False) -> str:
    """One file per process: mesh processes share GUBER_SNAPSHOT_DIR, so
    each writes its own local shard blocks keyed by shard offset."""
    name = (f"arena-r{local_shard_offset}.snap" if multiprocess
            else "arena.snap")
    return os.path.join(directory, name)


def restore_engine(engine, path: str, rebase_to: Optional[int] = None,
                   metrics=None) -> Optional[ArenaSnapshot]:
    """Daemon-boot restore: load + import, degrading to a cold arena (with
    a warning) on ANY failure — a corrupt snapshot must never block a boot.
    Returns the snapshot on success, None on cold start."""
    try:
        snap = load(path)
    except FileNotFoundError:
        log.info("no snapshot at %s; starting cold", path)
        return None
    except (SnapshotError, OSError) as e:
        # OSError covers real disk failures AND the injected snapshot_io
        # faults (net/faults.py FaultError is an OSError by design) — both
        # must degrade to a cold start, never a failed boot
        log.warning("snapshot %s unusable (%s); starting cold", path, e)
        return None
    try:
        engine.import_state(snap, rebase_to=rebase_to)
    except Exception as e:
        log.warning("snapshot %s failed to import (%s); starting cold",
                    path, e)
        return None
    if metrics is not None:
        from gubernator_tpu.api.types import millisecond_now
        metrics.restore_age.set(max(0.0, (millisecond_now() - snap.now)
                                    / 1000.0))
    log.info("restored %d keys from %s (age %.1fs)", snap.total_keys(), path,
             max(0, _age_ms(snap)) / 1000.0)
    return snap


def _age_ms(snap: ArenaSnapshot) -> int:
    from gubernator_tpu.api.types import millisecond_now
    return millisecond_now() - snap.now
