"""Live key migration on a peer-ring change.

When the cluster membership changes, the consistent-hash ring re-homes a
fraction of the key space (~1/N of keys on an N-node grow — the ring's
minimal-movement property).  The reference simply lets re-homed counters
restart from zero on their new owner; here the OLD owner ships each moved
key's live device bucket row to the NEW owner over the TransferBuckets peer
lane, so `remaining`/`reset_time` survive the ring change.

Split of responsibilities:

  ownership_diff       pure: which keys move where, given old/new host sets
  encode/decode_rows   the TransferBuckets wire payload (versioned JSON —
                       control-plane volume, not the serving path)
  Instance.migrate_keys     source side: diff, export, ship, drop local
  Instance.transfer_buckets dest side: import with init-flag semantics that
                            never clobber a fresher local entry
                            (engine.import_rows / import_global_rows)

GLOBAL keys re-REGISTER on the new owner (config + replicated state row
move) but are NOT dropped at the source: every node keeps a serving replica
of GLOBAL keys; only ownership (who aggregates async hits) moves.

Requires the Python SlotTable routing backend (EngineConfig
use_native=False): the native C++ router keeps 64-bit fingerprints, not key
strings, and a fingerprint cannot be re-hashed onto the ring.
"""

from __future__ import annotations

import json
import logging
from typing import Dict, Iterable, List, Sequence, Tuple

from gubernator_tpu.parallel.router import ConsistentHashRing

log = logging.getLogger("gubernator.migrate")

WIRE_VERSION = 1

_ROW_FIELDS = ("key", "limit", "duration", "remaining", "tstamp", "expire",
               "algo")
_GROW_FIELDS = _ROW_FIELDS + ("cfg_limit", "cfg_duration", "cfg_algo")


class MigrationError(Exception):
    """Malformed transfer payload or ack."""


def _ring_of(hosts: Iterable[str]) -> ConsistentHashRing:
    ring: ConsistentHashRing[str] = ConsistentHashRing()
    for h in hosts:
        ring.add(h, h)
    return ring


def ownership_diff(keys: Sequence[str], old_hosts: Iterable[str],
                   new_hosts: Iterable[str]) -> Dict[str, List[str]]:
    """Which of `keys` change owner between the two memberships?
    Returns {new_owner_host: [keys]} — only re-homed keys appear, so on an
    N -> N+1 grow this is ~1/(N+1) of the key space, per the ring's
    minimal-movement property."""
    old = _ring_of(old_hosts)
    new = _ring_of(new_hosts)
    moved: Dict[str, List[str]] = {}
    for k in keys:
        o = old.get(k)
        n = new.get(k)
        if o != n:
            moved.setdefault(n, []).append(k)
    return moved


# -------------------------------------------------------------- wire codec


def encode_rows(regular: Sequence[dict], global_: Sequence[dict],
                leases: Sequence[Sequence] = ()) -> bytes:
    """`leases`: concurrency-lease book rows riding along with their keys,
    [key, client, count, expire, name, unique_key, limit, duration] (the
    last four may be empty/zero when the source lost the request template).
    The key is OPTIONAL on the wire — old importers ignore it, old exporters
    simply never send it — so the wire version stays 1."""
    msg = {
        "v": WIRE_VERSION,
        "regular": [[r[f] for f in _ROW_FIELDS] for r in regular],
        "global": [[r[f] for f in _GROW_FIELDS] for r in global_],
    }
    if leases:
        msg["leases"] = [list(row) for row in leases]
    return json.dumps(msg).encode("utf-8")


def decode_rows(data: bytes) -> Tuple[List[dict], List[dict], List[list]]:
    try:
        msg = json.loads(data.decode("utf-8"))
        if msg["v"] != WIRE_VERSION:
            raise MigrationError(
                f"unsupported transfer wire version {msg['v']}")
        regular = [dict(zip(_ROW_FIELDS, r)) for r in msg["regular"]]
        global_ = [dict(zip(_GROW_FIELDS, r)) for r in msg["global"]]
        leases = [list(r) for r in msg.get("leases", ())]
    except MigrationError:
        raise
    except Exception as e:
        raise MigrationError(f"malformed transfer payload: {e}") from None
    for rows, fields in ((regular, _ROW_FIELDS), (global_, _GROW_FIELDS)):
        for r in rows:
            if not isinstance(r["key"], str) or any(
                    not isinstance(r[f], int) for f in fields[1:]):
                raise MigrationError("malformed transfer row")
    for row in leases:
        if (len(row) < 4 or not isinstance(row[0], str)
                or not isinstance(row[1], str)
                or not isinstance(row[2], int)
                or not isinstance(row[3], int)):
            raise MigrationError("malformed transfer lease row")
    return regular, global_, leases


def encode_ack(imported: int, skipped: int, gimported: int,
               gskipped: int) -> bytes:
    return json.dumps({
        "v": WIRE_VERSION, "imported": imported, "skipped_stale": skipped,
        "gimported": gimported, "gskipped_stale": gskipped,
    }).encode("utf-8")


def decode_ack(data: bytes) -> dict:
    try:
        msg = json.loads(data.decode("utf-8"))
        return {k: int(msg[k]) for k in
                ("imported", "skipped_stale", "gimported", "gskipped_stale")}
    except Exception as e:
        raise MigrationError(f"malformed transfer ack: {e}") from None
