"""Host-side key→slot management for the device state arenas.

The reference's cache is a map + doubly-linked LRU list holding Go objects
(cache/lru.go:30-96).  Here the *values* live on the device as dense SoA
arrays (ops/kernel.py BucketState) and the host keeps only the key→slot
mapping, LRU order, and hit/miss stats.  Responsibilities are split:

  host (this module):  which slot a key occupies, capacity eviction
                       (evict-oldest-on-overflow, lru.go:92-94), LRU touch on
                       access (lru.go:116), hit/miss counters (lru.go:112-119).
  device (kernel):     the actual bucket values, and lazy TTL expiry
                       (lru.go:110-114) — an expired slot re-initializes
                       in-kernel without any host round trip.

Because TTL expiry is resolved on the device, the host tracks only an
*estimate* of each entry's expiry (refreshed to now+duration on every access)
which it uses for hit/miss accounting and to prefer reclaiming expired slots.
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Optional, Tuple


class SlotTable:
    """Fixed-capacity key→slot table with LRU eviction.

    `lookup` returns (slot, is_init): is_init is True when the key was just
    assigned a (possibly recycled) slot, telling the kernel to take the
    cache-miss path regardless of what the slot's previous tenant left behind.
    """

    __slots__ = ("capacity", "_entries", "_free", "hits", "misses")

    def __init__(self, capacity: int):
        if capacity <= 0:
            raise ValueError("capacity must be positive")
        self.capacity = capacity
        # key -> [slot, expire_estimate_ms]; insertion order == LRU order
        # (oldest first), maintained with move_to_end on access.
        self._entries: "OrderedDict[str, list]" = OrderedDict()
        self._free = list(range(capacity - 1, -1, -1))
        self.hits = 0
        self.misses = 0

    def __len__(self) -> int:
        return len(self._entries)

    def __contains__(self, key: str) -> bool:
        return key in self._entries

    def lookup(self, key: str, now: int, duration: int) -> Tuple[int, bool]:
        """Find or allocate the slot for `key`. Returns (slot, is_init)."""
        ent = self._entries.get(key)
        if ent is not None:
            # Reference counts an expired entry as a miss (lru.go:110-114);
            # we approximate with the host-side expiry estimate.
            if ent[1] < now:
                self.misses += 1
            else:
                self.hits += 1
            ent[1] = now + duration
            self._entries.move_to_end(key)
            return ent[0], False

        self.misses += 1
        if self._free:
            slot = self._free.pop()
        else:
            # Evict the least-recently-used entry (lru.go:92-94,131-136).
            _, old = self._entries.popitem(last=False)
            slot = old[0]
        self._entries[key] = [slot, now + duration]
        return slot, True

    def peek(self, key: str) -> Optional[int]:
        """Slot for key without LRU touch or allocation; None if absent."""
        ent = self._entries.get(key)
        return None if ent is None else ent[0]

    def remove(self, key: str) -> None:
        ent = self._entries.pop(key, None)
        if ent is not None:
            self._free.append(ent[0])
