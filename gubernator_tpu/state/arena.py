"""Host-side key→slot management for the device state arenas.

The reference's cache is a map + doubly-linked LRU list holding Go objects
(cache/lru.go:30-96).  Here the *values* live on the device as dense SoA
arrays (ops/kernel.py BucketState) and the host keeps only the key→slot
mapping, LRU order, and hit/miss stats.  Responsibilities are split:

  host (this module):  which slot a key occupies, capacity eviction
                       (evict-oldest-on-overflow, lru.go:92-94), LRU touch on
                       access (lru.go:116), hit/miss counters (lru.go:112-119).
  device (kernel):     the actual bucket values, and lazy TTL expiry
                       (lru.go:110-114) — an expired slot re-initializes
                       in-kernel without any host round trip.

Because TTL expiry is resolved on the device, the host tracks only an
*estimate* of each entry's expiry (refreshed to now+duration on every access)
which it uses for hit/miss accounting and to prefer reclaiming expired slots.
"""

from __future__ import annotations

import heapq
from collections import OrderedDict
from typing import Optional, Tuple


class SlotTable:
    """Fixed-capacity key→slot table with LRU eviction.

    `lookup` returns (slot, is_init): is_init is True when the key was just
    assigned a (possibly recycled) slot, telling the kernel to take the
    cache-miss path regardless of what the slot's previous tenant left behind.
    """

    __slots__ = ("capacity", "_entries", "_free", "hits", "misses",
                 "_seq", "_uncommitted", "_expiry_heap")

    def __init__(self, capacity: int):
        if capacity <= 0:
            raise ValueError("capacity must be positive")
        self.capacity = capacity
        # key -> [slot, expire_estimate_ms, pending_init, seen_seq];
        # insertion order == LRU order (oldest first), maintained with
        # move_to_end on access.  pending_init stays set until a device
        # dispatch commits the window that initialized the slot
        # (commit_window): an aborted pack must NOT consume the init flag,
        # or a retry could inherit a recycled slot's previous tenant's
        # still-live device state.
        self._entries: "OrderedDict[str, list]" = OrderedDict()
        self._free = list(range(capacity - 1, -1, -1))
        self.hits = 0
        self.misses = 0
        self._seq = 0
        self._uncommitted: list = []
        # lazy min-heap of (expire_estimate, key): lets a full table reclaim
        # an EXPIRED slot before evicting a live LRU victim.  Entries go
        # stale when a key is re-touched (its real expiry moved); staleness
        # is detected on pop by comparing against the entry's current value.
        self._expiry_heap: list = []

    def __len__(self) -> int:
        return len(self._entries)

    def __contains__(self, key: str) -> bool:
        return key in self._entries

    def begin_window(self) -> None:
        """Start packing a new window: later duplicate lookups of a
        pending-init key within THIS window report is_init=False (the kernel
        sequences in-window duplicates itself)."""
        self._seq += 1
        self._uncommitted = []

    def commit_window(self) -> None:
        """The window packed since begin_window was dispatched: its fresh
        allocations are now device-initialized."""
        for ent in self._uncommitted:
            ent[2] = False
        self._uncommitted = []

    def lookup(self, key: str, now: int, duration: int) -> Tuple[int, bool]:
        """Find or allocate the slot for `key`. Returns (slot, is_init)."""
        ent = self._entries.get(key)
        if ent is not None:
            # Reference counts an expired entry as a miss (lru.go:110-114);
            # we approximate with the host-side expiry estimate.
            if ent[1] < now:
                self.misses += 1
            else:
                self.hits += 1
            ne = now + duration
            if ent[1] != ne:
                # hint-churn suppression (mirrors native/host_router.cc):
                # re-push only when the expiry moved by more than duration/4
                # or backwards; _reclaim checks the entry's CURRENT expiry,
                # so sparser hints stay correct while the heap stays bounded
                push = ne - ent[1] > duration // 4 or ne < ent[1]
                ent[1] = ne
                if push:
                    heapq.heappush(self._expiry_heap, (ne, key))
            self._entries.move_to_end(key)
            if ent[2] and ent[3] != self._seq:
                # allocated by an earlier window that never dispatched
                ent[3] = self._seq
                self._uncommitted.append(ent)
                return ent[0], True
            return ent[0], False

        self.misses += 1
        if self._free:
            slot = self._free.pop()
        else:
            slot = self._reclaim(now)
        ent = [slot, now + duration, True, self._seq]
        self._entries[key] = ent
        heapq.heappush(self._expiry_heap, (now + duration, key))
        self._uncommitted.append(ent)
        return slot, True

    def _reclaim(self, now: int) -> int:
        """Free a slot from a full table: prefer an EXPIRED entry (its
        device state reads as a miss anyway, kernel lazy-TTL), falling back
        to strict LRU eviction (lru.go:92-94,131-136).

        Mirrors native/host_router.cc try_reclaim_expired: reclaim is
        decided by the entry's CURRENT expiry (hints may be sparse under
        push suppression), a hint whose entry refreshed past `now` is
        re-pushed at the current expiry, and work per attempt is capped so
        an allocation never stalls on a stale-hint burst."""
        heap = self._expiry_heap
        repush = []
        out = None
        for _ in range(32):
            if not heap or heap[0][0] >= now:
                break
            exp, key = heapq.heappop(heap)
            ent = self._entries.get(key)
            if ent is None:
                continue  # dead hint
            if ent[1] < now:  # truly expired (current expiry, not hint's)
                del self._entries[key]
                out = ent[0]
                break
            repush.append((ent[1], key))
        for node in repush:
            heapq.heappush(heap, node)
        if out is not None:
            return out
        if len(heap) > 4 * self.capacity:  # compact stale heap nodes
            self._expiry_heap = [(e[1], k) for k, e in self._entries.items()]
            heapq.heapify(self._expiry_heap)
        _, old = self._entries.popitem(last=False)
        return old[0]

    def peek(self, key: str) -> Optional[int]:
        """Slot for key without LRU touch or allocation; None if absent."""
        ent = self._entries.get(key)
        return None if ent is None else ent[0]

    def remove(self, key: str) -> None:
        ent = self._entries.pop(key, None)
        if ent is not None:
            # the entry may still sit in _uncommitted (allocated this
            # window): commit_window would then mutate a freed entry, and a
            # reuse of the slot could have its init flag cleared by the OLD
            # entry's commit — drop it from the pending list with the entry
            self._uncommitted = [e for e in self._uncommitted if e is not ent]
            self._free.append(ent[0])

    # ------------------------------------------------------- state lifecycle

    def stats(self, now: int) -> dict:
        """Occupancy by the host-side expiry estimate: free slots, live and
        expired resident entries (state/snapshot + cache_stats surface)."""
        live = sum(1 for e in self._entries.values() if e[1] >= now)
        return {
            "free": self.capacity - len(self._entries),
            "live": live,
            "expired": len(self._entries) - live,
        }

    def export_entries(self):
        """(key, slot, expire_estimate) in LRU order (oldest first).

        Entries still pending device init are skipped: their device rows
        were never written, so a snapshot of them would resurrect whatever
        the slot's previous tenant left behind."""
        return [(k, e[0], e[1]) for k, e in self._entries.items() if not e[2]]

    def restore_entries(self, entries) -> None:
        """Rebuild the table from export_entries() output (oldest first).
        Replaces all current state; restored entries are committed (their
        device rows are restored by the same snapshot)."""
        self._entries = OrderedDict()
        used = set()
        for key, slot, expire in entries:
            if not (0 <= slot < self.capacity) or slot in used:
                raise ValueError(f"invalid slot {slot} for key {key!r}")
            used.add(slot)
            self._entries[key] = [int(slot), int(expire), False, 0]
        self._free = [s for s in range(self.capacity - 1, -1, -1)
                      if s not in used]
        self._expiry_heap = [(e[1], k) for k, e in self._entries.items()]
        heapq.heapify(self._expiry_heap)
        self._uncommitted = []

    def upsert(self, key: str, now: int, expire_estimate: int) -> int:
        """Slot for `key`, allocating if absent, with the expiry estimate
        set exactly (migration import: the caller writes the device row in
        the same quiesced section, so the entry is born committed — no
        pending init that a later window commit could clear)."""
        ent = self._entries.get(key)
        if ent is not None:
            if ent[1] != expire_estimate:
                ent[1] = expire_estimate
                heapq.heappush(self._expiry_heap, (expire_estimate, key))
            self._entries.move_to_end(key)
            return ent[0]
        slot = self._free.pop() if self._free else self._reclaim(now)
        self._entries[key] = [slot, expire_estimate, False, self._seq]
        heapq.heappush(self._expiry_heap, (expire_estimate, key))
        return slot

    def is_pending(self, key: str) -> bool:
        """True while the key's slot awaits its initializing dispatch."""
        ent = self._entries.get(key)
        return bool(ent is not None and ent[2])

    def keys(self):
        return list(self._entries.keys())
