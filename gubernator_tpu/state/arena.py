"""Host-side key→slot management for the device state arenas.

The reference's cache is a map + doubly-linked LRU list holding Go objects
(cache/lru.go:30-96).  Here the *values* live on the device as dense SoA
arrays (ops/kernel.py BucketState) and the host keeps only the key→slot
mapping, LRU order, and hit/miss stats.  Responsibilities are split:

  host (this module):  which slot a key occupies, capacity eviction
                       (evict-oldest-on-overflow, lru.go:92-94), LRU touch on
                       access (lru.go:116), hit/miss counters (lru.go:112-119).
  device (kernel):     the actual bucket values, and lazy TTL expiry
                       (lru.go:110-114) — an expired slot re-initializes
                       in-kernel without any host round trip.

Because TTL expiry is resolved on the device, the host tracks only an
*estimate* of each entry's expiry (refreshed to now+duration on every access)
which it uses for hit/miss accounting and to prefer reclaiming expired slots.
"""

from __future__ import annotations

import heapq
from collections import OrderedDict, deque
from typing import Optional, Tuple


class SlotTable:
    """Fixed-capacity key→slot table with LRU eviction.

    `lookup` returns (slot, is_init): is_init is True when the key was just
    assigned a (possibly recycled) slot, telling the kernel to take the
    cache-miss path regardless of what the slot's previous tenant left behind.
    """

    __slots__ = ("capacity", "_entries", "_free", "hits", "misses",
                 "_seq", "_uncommitted", "_expiry_heap", "_n_expired",
                 "_stats_now", "_expired_pool", "spill_cb", "heat_fn",
                 "victim_sample")

    # entry field indices (see the _entries comment below)
    _SLOT, _EXPIRE, _PENDING, _SEEN, _EXPFLAG, _TOUCH = range(6)

    def __init__(self, capacity: int):
        if capacity <= 0:
            raise ValueError("capacity must be positive")
        self.capacity = capacity
        # key -> [slot, expire_estimate_ms, pending_init, seen_seq,
        #         expired_flag, touch_seq];
        # insertion order == LRU order (oldest first), maintained with
        # move_to_end on access.  pending_init stays set until a device
        # dispatch commits the window that initialized the slot
        # (commit_window): an aborted pack must NOT consume the init flag,
        # or a retry could inherit a recycled slot's previous tenant's
        # still-live device state.  expired_flag mirrors
        # `expire_estimate < stats horizon` (incremental O(1) stats);
        # touch_seq stamps the drain that last looked the key up, so the
        # tier spill path can refuse victims whose device rows are about to
        # mutate in the not-yet-dispatched drain.
        self._entries: "OrderedDict[str, list]" = OrderedDict()
        self._free = list(range(capacity - 1, -1, -1))
        self.hits = 0
        self.misses = 0
        self._seq = 0
        self._uncommitted: list = []
        # lazy min-heap of (expire_estimate, key): lets a full table reclaim
        # an EXPIRED slot before evicting a live LRU victim.  Entries go
        # stale when a key is re-touched (its real expiry moved); staleness
        # is detected on pop by comparing against the entry's current value.
        self._expiry_heap: list = []
        # incremental occupancy accounting (O(1) stats): count of entries
        # whose expired_flag is set, the stats-call high-water `now` the
        # flags are exact against, and the keys flagged by the lazy heap
        # advance (their heap node was consumed; _reclaim consults this
        # pool first so expired-preference survives a stats() call).
        self._n_expired = 0
        self._stats_now = 0
        self._expired_pool: deque = deque()
        # Tier hooks (state/tiers.py): spill_cb(key, slot, expire, stale)
        # fires when _reclaim evicts a COMMITTED entry, so its device row
        # can demote to the warm tier instead of being lost; heat_fn(key)
        # ranks LRU-head eviction candidates (lowest heat evicted first);
        # victim_sample bounds how many candidates are ranked.  All unset
        # (the default) leaves reclaim byte-identical to the untiered path.
        self.spill_cb = None
        self.heat_fn = None
        self.victim_sample = 1

    def __len__(self) -> int:
        return len(self._entries)

    def __contains__(self, key: str) -> bool:
        return key in self._entries

    def begin_window(self) -> None:
        """Start packing a new window: later duplicate lookups of a
        pending-init key within THIS window report is_init=False (the kernel
        sequences in-window duplicates itself)."""
        self._seq += 1
        self._uncommitted = []

    def commit_window(self) -> None:
        """The window packed since begin_window was dispatched: its fresh
        allocations are now device-initialized."""
        for ent in self._uncommitted:
            ent[2] = False
        self._uncommitted = []

    def lookup(self, key: str, now: int, duration: int) -> Tuple[int, bool]:
        """Find or allocate the slot for `key`. Returns (slot, is_init)."""
        ent = self._entries.get(key)
        if ent is not None:
            # Reference counts an expired entry as a miss (lru.go:110-114);
            # we approximate with the host-side expiry estimate.
            if ent[1] < now:
                self.misses += 1
            else:
                self.hits += 1
            ne = now + duration
            if ent[1] != ne:
                # hint-churn suppression (mirrors native/host_router.cc):
                # re-push only when the expiry moved by more than duration/4
                # or backwards; _reclaim checks the entry's CURRENT expiry,
                # so sparser hints stay correct while the heap stays bounded.
                # A flagged entry's heap node was consumed by the lazy stats
                # advance, so unflagging MUST re-push unconditionally.
                push = ne - ent[1] > duration // 4 or ne < ent[1] or ent[4]
                ent[1] = ne
                self._reflag(key, ent, ne)
                if push:
                    heapq.heappush(self._expiry_heap, (ne, key))
            ent[5] = self._seq
            self._entries.move_to_end(key)
            if ent[2] and ent[3] != self._seq:
                # allocated by an earlier window that never dispatched
                ent[3] = self._seq
                self._uncommitted.append(ent)
                return ent[0], True
            return ent[0], False

        self.misses += 1
        if self._free:
            slot = self._free.pop()
        else:
            slot = self._reclaim(now)
        ent = [slot, now + duration, True, self._seq, False, self._seq]
        self._entries[key] = ent
        self._reflag(key, ent, now + duration)
        heapq.heappush(self._expiry_heap, (now + duration, key))
        self._uncommitted.append(ent)
        return slot, True

    def _reflag(self, key: str, ent: list, new_expire: int) -> None:
        """Keep `expired_flag == (expire < stats horizon)` exact across an
        expiry change, so stats() stays a subtraction."""
        if ent[4]:
            if new_expire >= self._stats_now:
                ent[4] = False
                self._n_expired -= 1
        elif new_expire < self._stats_now:
            ent[4] = True
            self._n_expired += 1
            self._expired_pool.append(key)

    def _reclaim(self, now: int) -> int:
        """Free a slot from a full table: prefer an EXPIRED entry (its
        device state reads as a miss anyway, kernel lazy-TTL), falling back
        to strict LRU eviction (lru.go:92-94,131-136).

        Mirrors native/host_router.cc try_reclaim_expired: reclaim is
        decided by the entry's CURRENT expiry (hints may be sparse under
        push suppression), a hint whose entry refreshed past `now` is
        re-pushed at the current expiry, and work per attempt is capped so
        an allocation never stalls on a stale-hint burst.

        With the tier hooks installed (state/tiers.py) the LIVE victim is
        picked by heat among the first `victim_sample` eligible LRU-head
        entries and handed to spill_cb for demotion to the warm tier;
        entries touched by the CURRENT drain are skipped where possible —
        their device rows mutate in the not-yet-dispatched drain, so a
        pre-dispatch gather of them would be stale."""
        heap = self._expiry_heap
        pool = self._expired_pool
        budget = 32
        # flagged-expired keys whose heap node was consumed by stats():
        # the pool keeps expired-preference intact after a lazy advance
        while pool and budget > 0:
            budget -= 1
            key = pool.popleft()
            ent = self._entries.get(key)
            if ent is None or not ent[4]:
                continue  # dead or refreshed since flagging
            if ent[1] >= now:
                # flagged against a later stats horizon than this reclaim's
                # clock — still counted expired, just not reclaimable yet
                pool.append(key)
                break
            return self._evict(key, ent)
        repush = []
        out = None
        for _ in range(budget):
            if not heap or heap[0][0] >= now:
                break
            exp, key = heapq.heappop(heap)
            ent = self._entries.get(key)
            if ent is None:
                continue  # dead hint
            if ent[1] < now:  # truly expired (current expiry, not hint's)
                out = self._evict(key, ent)
                break
            repush.append((ent[1], key))
        for node in repush:
            heapq.heappush(heap, node)
        if out is not None:
            return out
        if len(heap) > 4 * self.capacity:  # compact stale heap nodes
            self._expiry_heap = [(e[1], k) for k, e in self._entries.items()]
            heapq.heapify(self._expiry_heap)
        return self._evict(*self._pick_live_victim())

    def _pick_live_victim(self) -> tuple:
        """LRU-head victim, heat-ranked when the tier hooks are installed.
        Without hooks this is exactly popitem(last=False) — the seed path."""
        if self.spill_cb is None and self.heat_fn is None:
            key = next(iter(self._entries))
            return key, self._entries[key]
        sample = max(1, self.victim_sample)
        best = None
        fallback = None
        scanned = 0
        eligible = 0
        for k, e in self._entries.items():
            scanned += 1
            if fallback is None:
                fallback = (k, e)
            if e[5] != self._seq:
                heat = self.heat_fn(k) if self.heat_fn is not None else 0.0
                if best is None or heat < best[0]:
                    best = (heat, k, e)
                eligible += 1
                if eligible >= sample:
                    break
            # entries touched by this drain are skipped while alternatives
            # exist: spilling one pre-dispatch would lose the drain's
            # staged hits.  The scan is capped so an all-hot head never
            # turns an allocation into an O(capacity) walk.
            if scanned >= 4 * sample:
                break
        if best is not None:
            return best[1], best[2]
        return fallback  # every candidate is hot-path-touched: strict LRU

    def _evict(self, key: str, ent: list) -> int:
        """Drop `key` from the table, keeping the incremental occupancy
        counts exact and offering committed victims to the tier spill
        hook.  Returns the freed slot."""
        del self._entries[key]
        if ent[4]:
            self._n_expired -= 1
        if ent[2]:
            # pending-init victim: its device row was never written, and
            # commit_window must not flip the init flag of a freed entry
            self._uncommitted = [e for e in self._uncommitted if e is not ent]
        elif self.spill_cb is not None:
            self.spill_cb(key, ent[0], ent[1], ent[5] == self._seq)
        return ent[0]

    def peek(self, key: str) -> Optional[int]:
        """Slot for key without LRU touch or allocation; None if absent."""
        ent = self._entries.get(key)
        return None if ent is None else ent[0]

    def remove(self, key: str) -> None:
        ent = self._entries.pop(key, None)
        if ent is not None:
            # the entry may still sit in _uncommitted (allocated this
            # window): commit_window would then mutate a freed entry, and a
            # reuse of the slot could have its init flag cleared by the OLD
            # entry's commit — drop it from the pending list with the entry
            self._uncommitted = [e for e in self._uncommitted if e is not ent]
            if ent[4]:
                self._n_expired -= 1
            self._free.append(ent[0])

    # ------------------------------------------------------- state lifecycle

    def stats(self, now: int) -> dict:
        """Occupancy by the host-side expiry estimate: free slots, live and
        expired resident entries (state/snapshot + cache_stats surface).

        O(1) amortized: the expired count is maintained incrementally (the
        expired_flag transitions at refresh/evict/remove), and each call
        advances the lazy expiry heap past `now` — every pop is charged to
        the push or expiry-crossing event that created it, so a per-drain
        scrape never rescans the arena (the seed did an O(capacity) sweep
        here on every call)."""
        if now < self._stats_now:
            # clock regression (tests mixing time domains): the flags are
            # exact against the high-water horizon only — fall back to the
            # full scan rather than report a wrong split
            live = sum(1 for e in self._entries.values() if e[1] >= now)
        else:
            heap = self._expiry_heap
            pool = self._expired_pool
            entries = self._entries
            while heap and heap[0][0] < now:
                _, key = heapq.heappop(heap)
                ent = entries.get(key)
                if ent is None:
                    continue  # dead hint
                if ent[1] < now:
                    if not ent[4]:
                        ent[4] = True
                        self._n_expired += 1
                        pool.append(key)
                    # no re-push: the pool now tracks it for _reclaim
                else:
                    # refreshed past the hint under push suppression —
                    # re-arm at the current expiry
                    heapq.heappush(heap, (ent[1], key))
            self._stats_now = now
            live = len(entries) - self._n_expired
        return {
            "free": self.capacity - len(self._entries),
            "live": live,
            "expired": len(self._entries) - live,
        }

    def export_entries(self):
        """(key, slot, expire_estimate) in LRU order (oldest first).

        Entries still pending device init are skipped: their device rows
        were never written, so a snapshot of them would resurrect whatever
        the slot's previous tenant left behind."""
        return [(k, e[0], e[1]) for k, e in self._entries.items() if not e[2]]

    def restore_entries(self, entries) -> None:
        """Rebuild the table from export_entries() output (oldest first).
        Replaces all current state; restored entries are committed (their
        device rows are restored by the same snapshot)."""
        self._entries = OrderedDict()
        used = set()
        for key, slot, expire in entries:
            if not (0 <= slot < self.capacity) or slot in used:
                raise ValueError(f"invalid slot {slot} for key {key!r}")
            used.add(slot)
            self._entries[key] = [int(slot), int(expire), False, 0, False, -1]
        self._free = [s for s in range(self.capacity - 1, -1, -1)
                      if s not in used]
        self._expiry_heap = [(e[1], k) for k, e in self._entries.items()]
        heapq.heapify(self._expiry_heap)
        self._uncommitted = []
        self._n_expired = 0
        self._stats_now = 0
        self._expired_pool = deque()

    def upsert(self, key: str, now: int, expire_estimate: int) -> int:
        """Slot for `key`, allocating if absent, with the expiry estimate
        set exactly (migration import: the caller writes the device row in
        the same quiesced section, so the entry is born committed — no
        pending init that a later window commit could clear)."""
        ent = self._entries.get(key)
        if ent is not None:
            if ent[1] != expire_estimate:
                ent[1] = expire_estimate
                self._reflag(key, ent, expire_estimate)
                heapq.heappush(self._expiry_heap, (expire_estimate, key))
            ent[5] = self._seq
            self._entries.move_to_end(key)
            return ent[0]
        slot = self._free.pop() if self._free else self._reclaim(now)
        ent = [slot, expire_estimate, False, self._seq, False, self._seq]
        self._entries[key] = ent
        self._reflag(key, ent, expire_estimate)
        heapq.heappush(self._expiry_heap, (expire_estimate, key))
        return slot

    def is_pending(self, key: str) -> bool:
        """True while the key's slot awaits its initializing dispatch."""
        ent = self._entries.get(key)
        return bool(ent is not None and ent[2])

    def keys(self):
        return list(self._entries.keys())
