"""In-process multi-node cluster harness for tests and local development.

The reference's answer to "multi-node without a real cluster"
(cluster/cluster.go:29-124): N full Instances, each with its own gRPC server
on a real loopback socket, wired into a static full-mesh peer list with
IsOwner set by address match — no discovery backend.  Global sync is tuned
fast for tests (50ms, cluster.go:87).

Every instance shares the process's device mesh but owns its own arenas, so
the cluster really exercises the cross-host protocol (forwarding, hit
aggregation, broadcasts) over real gRPC.
"""

from __future__ import annotations

import logging
import random
from dataclasses import replace
from typing import List, Optional, Sequence

from gubernator_tpu.config import BehaviorConfig, Config, EngineConfig, PeerInfo
from gubernator_tpu.core.service import Instance
from gubernator_tpu.server import GrpcServer

log = logging.getLogger("gubernator.cluster")


class ClusterNode:
    def __init__(self, instance: Instance, server: GrpcServer):
        self.instance = instance
        self.server = server
        self.address = server.address


class Cluster:
    def __init__(self):
        self.nodes: List[ClusterNode] = []
        # remembered for add_instance: a node joining later must be built
        # with the SAME configs as the founding members
        self._behaviors: Optional[BehaviorConfig] = None
        self._engine: Optional[EngineConfig] = None

    @property
    def addresses(self) -> List[str]:
        return [n.address for n in self.nodes]

    def get_peer(self) -> str:
        """A random node address (cluster.go:55-57) — tests dial randomly so
        routing/forwarding is exercised implicitly."""
        return random.choice(self.addresses)

    def peer_at(self, idx: int) -> str:
        return self.nodes[idx].address

    def instance_at(self, idx: int) -> Instance:
        return self.nodes[idx].instance

    async def owner_index_of(self, key: str) -> int:
        """Index of the node owning `key` — lets tests pick a deliberately
        non-owner node (functional_test.go:283-285)."""
        inst = self.nodes[0].instance
        owner = inst.get_peer(key)
        return self.addresses.index(owner.host)

    async def _rewire(self) -> None:
        """Install the current membership on every node (IsOwner by address
        match, cluster.go:35-45)."""
        for node in self.nodes:
            infos = [PeerInfo(address=a, is_owner=(a == node.address))
                     for a in self.addresses]
            await node.instance.set_peers(infos)

    async def add_instance(self, address: str = "127.0.0.1:0") -> ClusterNode:
        """Grow the ring by one node, then LIVE-MIGRATE the re-homed keys:
        after the new membership is installed everywhere, every existing
        node diffs old->new ownership and ships its moved bucket rows to
        their new owners (Instance.migrate_keys) — ~1/(N+1) of the key
        space moves, everything else stays untouched."""
        old_hosts = self.addresses
        conf = Config(behaviors=replace(self._behaviors or BehaviorConfig()),
                      engine=self._engine or EngineConfig(),
                      advertise_address=address)
        inst = Instance(conf)
        server = GrpcServer(inst, address)
        await server.start()
        inst.advertise_address = server.address
        inst.tracer.node = server.address
        node = ClusterNode(inst, server)
        self.nodes.append(node)
        await self._rewire()
        for n in self.nodes[:-1]:
            await n.instance.migrate_keys(old_hosts, self.addresses)
        return node

    async def remove_instance(self, idx: int) -> None:
        """Shrink the ring: the departing node first ships EVERY key it
        owns to the surviving membership (its migrate_keys diff is old
        membership -> membership-without-self, so all its keys re-home),
        then leaves the ring and stops.  A failed handoff must NOT leave
        the survivors' rings still naming the departed node — they get
        rewired (keys restart cold) no matter what the migration did."""
        node = self.nodes[idx]
        old_hosts = self.addresses
        new_hosts = [a for a in old_hosts if a != node.address]
        try:
            # departing node still has the OLD ring installed, so its picker
            # can reach every destination peer while it drains itself
            await node.instance.migrate_keys(old_hosts, new_hosts)
        except Exception:
            log.exception("departing node %s failed its handoff; its keys "
                          "restart cold on the survivors", node.address)
        self.nodes.pop(idx)
        await self._rewire()
        await node.server.stop()
        node.instance.close()

    async def kill_instance(self, idx: int) -> ClusterNode:
        """CRASH a node: stop its server and engine with NO handoff and NO
        rewire — the survivors' rings still name it, exactly like a real
        peer death.  Recovery is the failure detector's job (net/health.py).
        Returns the removed node so chaos tests can assert against it."""
        node = self.nodes.pop(idx)
        try:
            await node.server.stop(grace=0.0)
        except Exception:
            log.exception("killing %s: server stop failed", node.address)
        try:
            node.instance.close()
        except Exception:
            log.exception("killing %s: instance close failed", node.address)
        return node

    async def stop(self) -> None:
        """Stop every node, tolerating per-node failures: one failing
        server.stop() must not leak every later node's server and engine
        thread (that leak poisons the whole test process)."""
        errors = []
        for n in self.nodes:
            try:
                await n.server.stop()
            except Exception as e:
                errors.append(e)
                log.exception("cluster stop: server %s", n.address)
            try:
                n.instance.close()
            except Exception as e:
                errors.append(e)
                log.exception("cluster stop: instance %s", n.address)
        self.nodes = []
        if errors:
            raise errors[0]


async def start_with(
    addresses: Sequence[str],
    behaviors: Optional[BehaviorConfig] = None,
    engine: Optional[EngineConfig] = None,
) -> Cluster:
    """Boot one Instance+server per address and wire the full mesh
    (cluster.go:70-118)."""
    if behaviors is None:
        # fast global sync for tests (cluster.go:87)
        behaviors = BehaviorConfig(global_sync_wait=0.05)
    if engine is None:
        engine = EngineConfig(
            capacity_per_shard=512, batch_per_shard=128,
            global_capacity=128, global_batch_per_shard=32,
            max_global_updates=32,
        )
    cluster = Cluster()
    cluster._behaviors = behaviors
    cluster._engine = engine
    try:
        for addr in addresses:
            conf = Config(behaviors=replace(behaviors), engine=engine,
                          advertise_address=addr)
            inst = Instance(conf)
            server = GrpcServer(inst, addr)
            await server.start()
            inst.advertise_address = server.address
            # ephemeral-port boot resolves the address late; re-label the
            # tracer so stitched traces name each node distinctly
            inst.tracer.node = server.address
            cluster.nodes.append(ClusterNode(inst, server))

        # compile the shared device step before serving — otherwise the first
        # real window pays a multi-second jit while peer batch RPCs time out
        cluster.nodes[0].instance.engine.warmup()

        peers = [PeerInfo(address=a) for a in cluster.addresses]
        for node in cluster.nodes:
            # IsOwner marks self by address match (cluster.go:35-45)
            infos = [PeerInfo(address=p.address,
                              is_owner=(p.address == node.address))
                     for p in peers]
            await node.instance.set_peers(infos)
    except Exception:
        await cluster.stop()
        raise
    return cluster


async def start(count: int = 6,
                behaviors: Optional[BehaviorConfig] = None,
                engine: Optional[EngineConfig] = None) -> Cluster:
    """N nodes on ephemeral loopback ports (cluster.go:70-76)."""
    return await start_with(["127.0.0.1:0"] * count, behaviors, engine)
