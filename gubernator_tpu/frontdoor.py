"""Multi-process front door: SO_REUSEPORT-sharded gRPC acceptors with
shared-memory columnar hand-off to the engine process.

One GIL-bound asyncio process doing accept/parse/encode caps e2e serving
far below what the host pipeline can drain (~566k/s served vs ~1.34M/s
drained, BASELINE.md round 6).  This module splits serving into N
frontend WORKER processes and the one ENGINE process:

  * every worker binds the SAME public port via SO_REUSEPORT (the kernel
    load-balances accepted connections across workers); when the kernel
    or a port collision refuses that, a worker degrades to its own
    ephemeral port, published in the status block for per-worker-port
    discovery (surfaced in `cli debug`);
  * each worker runs its own event loop and parses GetRateLimitsReq
    bytes ONCE, in C (native frontdoor_parse_req), straight into packed
    request columns inside a shared-memory slab (core/shm_ring.py) — the
    request never re-crosses the process boundary as Python objects;
  * the engine keeps sole ownership of the device, the lockstep drain,
    GLOBAL sync, and the arena.  COLS records ride the pipeline as
    ColsJobs; everything else (small RPCs, full-path requests, the whole
    PeersV1 plane) ships as RAW bytes and runs LITERALLY the same
    server.py serve_* coroutines the single-process servicers run —
    byte-identical decisions and responses by construction;
  * the RESPONSE direction mirrors the request one
    (GUBER_FRONTDOOR_ENCODE=worker, the default): the engine's completion
    writes packed DECISION columns (status/limit/remaining/reset + shed
    flag) into the completion-ring slab and each WORKER serializes the
    protobuf in its own process (native frontdoor_encode_resp, pb
    fallback) — protobuf encode never runs on the engine loop, for COLS
    and RAW/shed GetRateLimits paths alike.  Responses that cannot be
    expressed as columns (error strings, exotic metadata) fall back to
    engine-side serialization, counted in encode_fallbacks;
  * workers coalesce wire reads (GUBER_FRONTDOOR_BATCH_READS): RPCs that
    land in the same event-loop tick parse into ONE slab as a
    KIND_BATCH_COLS record — one ring publish, one pipeline job — and
    the completion columns split back per-RPC by the counts region;
  * workers answer HealthCheck locally from the engine-heartbeated
    status block (a health probe never queues behind a saturated engine
    loop) and shed in-band — no cross-process round-trip — on the shared
    draining/saturation flags and on ring exhaustion (shed_reason
    ring_full).  The saturation shed is deliberately coarser than the
    engine's per-item admission (which may still admit while saturated):
    a transient divergence under overload, traded for the CONCUR-style
    zero-round-trip shed; draining sheds match the single-process path
    exactly.

Workers import jax only as a side effect of the package __init__ (x64
flag); they pin jax_platforms=cpu before any possible backend init so
the engine's accelerator is never touched from a worker process.
"""

from __future__ import annotations

import asyncio
import itertools
import logging
import multiprocessing
import os
import threading
import time
from typing import Dict, List, Optional

import grpc

import numpy as np

from gubernator_tpu.core import shm_ring
from gubernator_tpu.core.shm_ring import (
    FLAG_COLS_OK,
    FLAG_DRAINING,
    FLAG_SATURATED,
    KIND_APPLY_GREG,
    KIND_BATCH_COLS,
    KIND_COLS,
    KIND_PEER_RL,
    KIND_RAW,
    KIND_REGISTER,
    KIND_TRANSFER,
    KIND_UPDATE_GLOBALS,
    MAX_BATCH_RPCS,
    SHED_CODE_REASONS,
    SHED_REASON_CODES,
    FrontdoorStatus,
    WorkerChannel,
)

log = logging.getLogger("gubernator.frontdoor")

_PREFIX_SEQ = itertools.count()

_INTERNAL = 13  # grpc.StatusCode.INTERNAL.value[0]
_CODE_BY_VALUE = {c.value[0]: c for c in grpc.StatusCode}


class FrontdoorAbort(Exception):
    """Engine-side analog of grpc context.abort(): carries the status the
    worker must abort the client RPC with."""

    def __init__(self, code: grpc.StatusCode, message: str):
        super().__init__(message)
        self.code = code
        self.message = message


class _EngineContext:
    """The slice of grpc.aio's ServicerContext the server.py serve_*
    bodies actually touch, backed by a shm record."""

    def __init__(self, deadline: float = 0.0):
        self._deadline = deadline  # absolute time.monotonic(); 0 = none

    def time_remaining(self) -> Optional[float]:
        if not self._deadline:
            return None
        return max(0.0, self._deadline - time.monotonic())

    def invocation_metadata(self):
        return ()

    async def abort(self, code: grpc.StatusCode, message: str = ""):
        raise FrontdoorAbort(code, message)


# =========================================================== worker process


class _Worker:
    """Per-process state of one frontdoor worker (runs in the spawned
    child; never imports the engine)."""

    def __init__(self, worker_id: int, chan: WorkerChannel,
                 status: FrontdoorStatus, fastpath_min: int,
                 encode_mode: str = "worker", batch_reads: int = 8):
        self.worker_id = worker_id
        self.chan = chan
        self.status = status
        self.fastpath_min = fastpath_min
        self.encode_mode = encode_mode
        # coalescing implies worker-side encode: a batch completion is
        # columnar (or per-RPC parts), never one engine-encoded buffer
        self.batch_reads = batch_reads if encode_mode == "worker" else 0
        from gubernator_tpu import native
        from gubernator_tpu.api import pb, types
        self.native = native
        self.native_ok = native.available()
        self.pb = pb
        self.types = types
        self._req_id = 0
        self._waiters: Dict[int, asyncio.Future] = {}
        self._batches: Dict[int, tuple] = {}  # rid -> (futs, counts)
        self._pending: List[tuple] = []       # (data, fut, deadline, tp)
        self._ebuf: Optional[np.ndarray] = None
        self._loop: Optional[asyncio.AbstractEventLoop] = None

    def _bump(self, field: int, n: int = 1) -> None:
        self.status.bump_w(self.worker_id, field, n)

    def traceparent(self, context) -> Optional[tuple]:
        """The RPC's sampled W3C traceparent as shm trace-region ints
        (trace_id_hi, trace_id_lo, span_id), or None when absent,
        malformed, or unsampled.  Parsed once HERE, in the worker — the
        engine only ever sees the three fixed-width words (the front-door
        trace blackout fix)."""
        md = getattr(context, "invocation_metadata", None)
        if not callable(md):
            return None
        raw = None
        for k, v in (md() or ()):
            if k == "traceparent":
                raw = v if isinstance(v, str) else \
                    bytes(v).decode("ascii", "replace")
                break
        if not raw:
            return None
        from gubernator_tpu.observability.tracing import parse_traceparent
        ctx = parse_traceparent(raw)
        if ctx is None:
            return None
        return (int(ctx.trace_id[:16], 16), int(ctx.trace_id[16:], 16),
                int(ctx.span_id, 16))

    # -------------------------------------------------------- response encode

    def encode_cols(self, st, li, re, rs, fl, off: int, n: int) -> bytes:
        """Serialize n decisions starting at column offset `off` — the
        worker-side response encode.  Native lane first (byte-compatible
        with the engine's fastpath_encode_w), pb objects as the fallback
        (byte-identical to the classic engine serialization, same
        runtime)."""
        if self.native_ok:
            need = n * 96 + 64
            if self._ebuf is None or self._ebuf.nbytes < need:
                self._ebuf = np.empty(max(need, 1 << 16), np.uint8)
            m = self.native.frontdoor_encode_resp(
                st[off:off + n], li[off:off + n], re[off:off + n],
                rs[off:off + n], fl[off:off + n], n, self._ebuf)
            if m >= 0:
                return bytes(self._ebuf[:m])
        pb, types = self.pb, self.types
        resps = []
        for i in range(off, off + n):
            code = int(fl[i])
            md = ({"shed": "true", "shed_reason": SHED_CODE_REASONS[code]}
                  if code else {})
            resps.append(types.RateLimitResp(
                status=int(st[i]), limit=int(li[i]),
                remaining=int(re[i]), reset_time=int(rs[i]), metadata=md))
        return pb.GetRateLimitsResp(responses=[
            pb.resp_to_pb(r) for r in resps]).SerializeToString()

    # ------------------------------------------------------------- transport

    async def roundtrip(self, slot: int, req_id: int, context) -> bytes:
        """Submit a written slab and await its completion; abort the
        client RPC when the engine said to."""
        fut = self._loop.create_future()
        self._waiters[req_id] = fut
        self.chan.submit(slot)
        try:
            status, payload = await fut
        finally:
            self._waiters.pop(req_id, None)
        if status != 0:
            await context.abort(
                _CODE_BY_VALUE.get(status, grpc.StatusCode.INTERNAL),
                payload.decode("utf-8", "replace"))
        self._bump(shm_ring.W_RPCS)
        return payload

    async def poll_loop(self) -> None:
        """Completion pump: the only consumer of the completion ring.
        Columnar completions (length < 0) are ENCODED here, while the
        worker still owns the slab; the slot is freed only after its
        response has been materialized."""
        while True:
            comps = self.chan.poll_completions_raw()
            if comps:
                for slot, req_id, status, length in comps:
                    try:
                        self._deliver(slot, req_id, status, length)
                    finally:
                        self.chan.free_slot(slot)
                await asyncio.sleep(0)
            else:
                await asyncio.sleep(0.0005)

    def _deliver(self, slot: int, req_id: int, status: int,
                 length: int) -> None:
        batch = self._batches.pop(req_id, None)
        if batch is not None:
            self._deliver_batch(batch, slot, status, length)
            return
        fut = self._waiters.pop(req_id, None)
        if fut is None or fut.done():
            return
        if length < 0:  # decision columns: worker-side encode
            n = -length
            st, li, re, rs, fl = self.chan.resp_views(slot)
            payload = self.encode_cols(st, li, re, rs, fl, 0, n)
            self._bump(shm_ring.W_ENCODES)
            fut.set_result((0, payload))
        else:
            if status == 0:
                self._bump(shm_ring.W_ENC_FALLBACK)
            fut.set_result((status, bytes(self.chan.slab(slot)[:length])))

    def _deliver_batch(self, batch: tuple, slot: int, status: int,
                       length: int) -> None:
        futs, counts = batch
        if status != 0:  # abort fans out to every coalesced RPC
            payload = bytes(self.chan.slab(slot)[:length])
            for f in futs:
                if not f.done():
                    f.set_result((status, payload))
            return
        if length < 0:  # concatenated decision columns, split by counts
            st, li, re, rs, fl = self.chan.resp_views(slot)
            off = 0
            for f, cnt in zip(futs, counts):
                payload = self.encode_cols(st, li, re, rs, fl, off, cnt)
                off += cnt
                self._bump(shm_ring.W_ENCODES)
                if not f.done():
                    f.set_result((0, payload))
        else:  # bytes-form fallback: per-RPC serialized parts
            lengths, view = self.chan.batch_payload(slot, len(futs), length)
            off = 0
            for f, ln in zip(futs, lengths):
                payload = bytes(view[off:off + ln])
                off += ln
                self._bump(shm_ring.W_ENC_FALLBACK)
                if not f.done():
                    f.set_result((0, payload))

    def flush_batch(self) -> None:
        """Coalesce this tick's pending GetRateLimits RPCs into ONE
        KIND_BATCH_COLS slab + ONE ring publish.  RPCs the C parser
        rejects (or that overflow the slab) resolve to None and rerun
        the classic single-record path in their handler."""
        pending = self._pending
        self._pending = []
        if not pending:
            return
        if len(pending) == 1:  # nothing to amortize
            if not pending[0][1].done():
                pending[0][1].set_result(None)
            return
        slot = self.chan.alloc()
        if slot is None:  # handlers shed ring_full on their own alloc
            for _, fut, _, _ in pending:
                if not fut.done():
                    fut.set_result(None)
            return
        kb, ke, hi, li, du, al, nl = self.chan.cols_views(slot)
        counts: List[int] = []
        futs: List[asyncio.Future] = []
        tps: List[Optional[tuple]] = []
        singles: List[asyncio.Future] = []
        base, koff = 0, 0
        dmin = 0.0
        for data, fut, deadline, tp in pending:
            n = -1
            if base < self.chan.cap_items and len(counts) < MAX_BATCH_RPCS:
                n = self.native.frontdoor_parse_req(
                    data, kb[koff:], ke[base:], hi[base:], li[base:],
                    du[base:], al[base:], nl[base:],
                    self.chan.cap_items - base)
            if n <= 0:
                singles.append(fut)
                continue
            if koff:
                ke[base:base + n] += koff
            koff = int(ke[base + n - 1])
            base += n
            counts.append(n)
            futs.append(fut)
            tps.append(tp)
            if deadline and (dmin == 0.0 or deadline < dmin):
                dmin = deadline
        # ONE trace region per record: the first traced member's context
        # rides the slab; every other traced member is an honest drop
        # (guber_tpu_frontdoor_trace_drops_total)
        carried = next((t for t in tps if t is not None), None)
        extra = sum(1 for t in tps if t is not None) - (1 if carried else 0)
        if extra > 0:
            self._bump(shm_ring.W_TRACE_DROPS, extra)
        if not counts:
            self.chan.unalloc(slot)
        elif len(counts) == 1:  # degenerate: a plain COLS record
            rid = self.next_id()
            if carried is not None:
                self.chan.set_trace(slot, *carried)
            else:
                self.chan.clear_trace(slot)
            self.chan.commit_cols(slot, rid, counts[0], koff, dmin)
            self._waiters[rid] = futs[0]
            self.chan.submit(slot)
        else:
            rid = self.next_id()
            if carried is not None:
                self.chan.set_trace(slot, *carried)
            else:
                self.chan.clear_trace(slot)
            self.chan.commit_batch(slot, rid, counts, koff, dmin)
            self._batches[rid] = (futs, counts)
            self._bump(shm_ring.W_BATCH_FLUSHES)
            self._bump(shm_ring.W_BATCH_RPCS, len(counts))
            self.chan.submit(slot)
        for fut in singles:
            if not fut.done():
                fut.set_result(None)

    def next_id(self) -> int:
        self._req_id += 1
        return self._req_id

    def shed_bytes(self, pb, data: bytes, reason: str):
        """In-band worker-local shed: the same shed_response items the
        engine's admission controller would build, without the ring trip."""
        from gubernator_tpu.qos.admission import shed_response
        try:
            req = pb.GetRateLimitsReq.FromString(data)
        except Exception:
            return None  # caller aborts INVALID_ARGUMENT
        self._bump(shm_ring.W_SHEDS, max(1, len(req.requests)))
        return pb.GetRateLimitsResp(responses=[
            pb.resp_to_pb(shed_response(r, reason)) for r in req.requests
        ]).SerializeToString()


class _WorkerV1:
    def __init__(self, w: _Worker):
        self.w = w
        from gubernator_tpu.api import pb
        self.pb = pb

    async def GetRateLimits(self, data: bytes, context):
        from gubernator_tpu.qos.admission import (SHED_DRAINING,
                                                  SHED_QUEUE_FULL,
                                                  SHED_RING_FULL)
        w = self.w
        st = w.status
        reason = None
        slot = None
        use_batch = (w.batch_reads > 1 and w.native_ok
                     and st.flag(FLAG_COLS_OK))
        if st.flag(FLAG_DRAINING):
            reason = SHED_DRAINING
        elif st.flag(FLAG_SATURATED):
            reason = SHED_QUEUE_FULL
        elif not use_batch:  # batching defers alloc to the flush
            slot = w.chan.alloc()
            if slot is None:
                # every slab in flight: the producer-side stall signal
                w._bump(shm_ring.W_STALLS)
                reason = SHED_RING_FULL
        if reason is not None:
            out = w.shed_bytes(self.pb, data, reason)
            if out is None:
                await context.abort(grpc.StatusCode.INVALID_ARGUMENT,
                                    "malformed GetRateLimitsReq")
            return out
        deadline = 0.0
        tr = getattr(context, "time_remaining", None)
        if callable(tr):
            rem = tr()
            if rem is not None:
                deadline = time.monotonic() + rem
        tp = w.traceparent(context)
        if use_batch:
            # batched wire reads: park this RPC for the tick's flush —
            # RPCs of any size coalesce into one slab + one publish (the
            # COLS size floor does not apply: a batch of small RPCs IS a
            # big columnar record).  None = the parser rejected it (or
            # the batch filled); rerun the classic single path below.
            fut = w._loop.create_future()
            w._pending.append((data, fut, deadline, tp))
            if len(w._pending) == 1:
                w._loop.call_soon(w.flush_batch)
            elif len(w._pending) >= min(w.batch_reads, MAX_BATCH_RPCS):
                w.flush_batch()
            res = await fut
            if res is not None:
                status, payload = res
                if status != 0:
                    await context.abort(
                        _CODE_BY_VALUE.get(status,
                                           grpc.StatusCode.INTERNAL),
                        payload.decode("utf-8", "replace"))
                w._bump(shm_ring.W_RPCS)
                return payload
            slot = w.chan.alloc()
            if slot is None:
                w._bump(shm_ring.W_STALLS)
                out = w.shed_bytes(self.pb, data, SHED_RING_FULL)
                if out is None:
                    await context.abort(grpc.StatusCode.INVALID_ARGUMENT,
                                        "malformed GetRateLimitsReq")
                return out
        rid = w.next_id()
        if (w.native_ok and st.flag(FLAG_COLS_OK)
                and len(data) >= w.fastpath_min):
            # the zero-copy lane: C-parse the request columns STRAIGHT
            # into the shm slab.  Any rejection (full-path behaviors,
            # range fallbacks, malformed bytes, oversize) ships RAW so
            # the engine decides exactly like the single-process path.
            kb, ke, hi, li, du, al, nl = w.chan.cols_views(slot)
            n = w.native.frontdoor_parse_req(data, kb, ke, hi, li, du,
                                             al, nl, w.chan.cap_items)
            if n > 0:
                if tp is not None:
                    w.chan.set_trace(slot, *tp)
                else:
                    w.chan.clear_trace(slot)
                w.chan.commit_cols(slot, rid, n, int(ke[n - 1]), deadline)
                return await w.roundtrip(slot, rid, context)
        if tp is not None:
            # RAW records carry the original request bytes, not the trace
            # region — the caller's trace cannot follow this record
            w._bump(shm_ring.W_TRACE_DROPS)
        if not w.chan.write_raw(slot, KIND_RAW, rid, data, deadline):
            w.chan.unalloc(slot)
            await context.abort(grpc.StatusCode.RESOURCE_EXHAUSTED,
                                "request exceeds shm slab")
        return await w.roundtrip(slot, rid, context)

    async def HealthCheck(self, request, context):
        # served ENTIRELY worker-local from the engine-heartbeated status
        # block: a health probe never shares the saturated engine loop
        # (the thundering-herd p99 fix)
        w = self.w
        w._bump(shm_ring.W_HEALTHCHECKS)
        status, message, peer_count = w.status.health()
        if w.status.heartbeat_age() > 15.0:
            status, message = 1, "engine heartbeat stale"
        return self.pb.HealthCheckResp(
            status="healthy" if status == 0 else "unhealthy",
            message=message, peer_count=peer_count)


class _WorkerPeers:
    def __init__(self, w: _Worker):
        self.w = w
        from gubernator_tpu.api import pb
        self.pb = pb

    async def _raw(self, kind: int, data: bytes, context) -> bytes:
        w = self.w
        slot = w.chan.alloc()
        if slot is None:
            w._bump(shm_ring.W_STALLS)
            await context.abort(grpc.StatusCode.RESOURCE_EXHAUSTED,
                                "frontdoor ring full")
        rid = w.next_id()
        deadline = 0.0
        tr = getattr(context, "time_remaining", None)
        if callable(tr):
            rem = tr()
            if rem is not None:
                deadline = time.monotonic() + rem
        if not w.chan.write_raw(slot, kind, rid, data, deadline):
            w.chan.unalloc(slot)
            await context.abort(grpc.StatusCode.RESOURCE_EXHAUSTED,
                                "request exceeds shm slab")
        return await w.roundtrip(slot, rid, context)

    async def GetPeerRateLimits(self, data: bytes, context):
        return await self._raw(KIND_PEER_RL, data, context)

    async def TransferBuckets(self, data: bytes, context):
        return await self._raw(KIND_TRANSFER, data, context)

    async def RegisterGlobals(self, request, context):
        out = await self._raw(KIND_REGISTER, request.SerializeToString(),
                              context)
        return self.pb.RegisterGlobalsResp.FromString(out)

    async def ApplyGlobalRegistration(self, request, context):
        out = await self._raw(KIND_APPLY_GREG, request.SerializeToString(),
                              context)
        return self.pb.ApplyGlobalRegistrationResp.FromString(out)

    async def UpdatePeerGlobals(self, request, context):
        out = await self._raw(KIND_UPDATE_GLOBALS,
                              request.SerializeToString(), context)
        return self.pb.UpdatePeerGlobalsResp.FromString(out)


async def _worker_amain(worker_id: int, prefix: str, slots: int,
                        slab_bytes: int, listen_host: str, port_hint: int,
                        fastpath_min: int, encode_mode: str = "worker",
                        batch_reads: int = 8) -> None:
    from gubernator_tpu.api.grpc_api import (add_peers_servicer,
                                             add_v1_servicer)
    chan = WorkerChannel.attach(f"{prefix}_r{worker_id}", slots, slab_bytes)
    status = FrontdoorStatus.attach(f"{prefix}_st",
                                    workers=port_hint_workers(prefix))
    w = _Worker(worker_id, chan, status, fastpath_min,
                encode_mode=encode_mode, batch_reads=batch_reads)
    w._loop = asyncio.get_running_loop()

    server = grpc.aio.server(options=[
        ("grpc.max_receive_message_length", 1024 * 1024),
        ("grpc.so_reuseport", 1),
    ])
    add_v1_servicer(server, _WorkerV1(w))
    add_peers_servicer(server, _WorkerPeers(w))

    if worker_id == 0:
        port = server.add_insecure_port(f"{listen_host}:{port_hint}")
    else:
        # wait for worker 0 to publish the shared port, then join it via
        # SO_REUSEPORT; a refused bind degrades to an own ephemeral port
        p0 = 0
        for _ in range(300):
            p0 = status.get_w(0, shm_ring.W_PORT)
            if p0:
                break
            await asyncio.sleep(0.05)
        port = server.add_insecure_port(f"{listen_host}:{p0}") if p0 else 0
        if port == 0:
            port = server.add_insecure_port(f"{listen_host}:0")
    if port == 0:
        log.error("frontdoor worker %d could not bind", worker_id)
        return
    await server.start()
    status.set_w(worker_id, shm_ring.W_PORT, port)

    stop = asyncio.Event()
    loop = asyncio.get_running_loop()
    import signal as _signal
    for sig in (_signal.SIGINT, _signal.SIGTERM):
        try:
            loop.add_signal_handler(sig, stop.set)
        except (NotImplementedError, RuntimeError):
            pass
    poller = asyncio.create_task(w.poll_loop())

    ppid = os.getppid()
    while not stop.is_set():
        try:
            await asyncio.wait_for(stop.wait(), timeout=1.0)
        except asyncio.TimeoutError:
            pass
        # orphan guard: the engine died without SIGTERMing us
        if os.getppid() != ppid or w.status.heartbeat_age() > 30.0:
            break
    poller.cancel()
    await server.stop(grace=0.25)
    chan.close()
    status.close()


def port_hint_workers(prefix: str) -> int:
    """Worker count is encoded in the segment prefix by the hub so the
    status block can be attached without an extra argument."""
    return int(prefix.rsplit("_w", 1)[1])


def worker_main(worker_id: int, prefix: str, slots: int, slab_bytes: int,
                listen_host: str, port_hint: int, fastpath_min: int,
                encode_mode: str = "worker", batch_reads: int = 8) -> None:
    """Spawn entry point (multiprocessing 'spawn' context).  The package
    __init__ imported jax; pin this process to the CPU platform before
    anything could lazily initialize a backend — the accelerator belongs
    to the engine process alone."""
    try:
        import jax
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass
    logging.basicConfig(level=logging.INFO)
    asyncio.run(_worker_amain(worker_id, prefix, slots, slab_bytes,
                              listen_host, port_hint, fastpath_min,
                              encode_mode, batch_reads))


# ============================================================ engine process


def columnify_resps(resps):
    """Pack a list of RateLimitResp into decision columns for a
    complete_cols completion (worker-side response encode), or None when
    any response cannot be expressed as columns — an error string, or
    metadata other than exactly qos/admission.py's shed shape — in which
    case the hub serializes engine-side (counted in encode_fallbacks)."""
    n = len(resps)
    st = np.empty(n, np.int64)
    li = np.empty(n, np.int64)
    re = np.empty(n, np.int64)
    rs = np.empty(n, np.int64)
    fl = np.zeros(n, np.int32)
    for i, r in enumerate(resps):
        if r.error:
            return None
        md = r.metadata
        if md:
            code = (SHED_REASON_CODES.get(md.get("shed_reason", ""))
                    if len(md) == 2 and md.get("shed") == "true" else None)
            if code is None:
                return None
            fl[i] = code
        st[i] = r.status
        li[i] = r.limit
        re[i] = r.remaining
        rs[i] = r.reset_time
    return st, li, re, rs, fl


class FrontdoorHub:
    """Engine-side owner of the front door: creates the shm segments,
    spawns/monitors/restarts the workers, consumes every submission ring,
    and serves each record on the engine event loop through the SAME
    server.py serve_* bodies the single-process servicers use."""

    def __init__(self, instance, workers: int, ring_slots: int,
                 slab_bytes: int, listen_address: str,
                 encode: str = "worker", batch_reads: int = 8):
        self.instance = instance
        self.workers = workers
        self.ring_slots = ring_slots
        self.slab_bytes = slab_bytes
        self.encode = encode if encode in ("worker", "engine") else "worker"
        self.batch_reads = batch_reads
        # responses that could NOT be columnified (error strings, exotic
        # metadata) and fell back to engine-side serialization
        self.encode_fallbacks = 0
        host, _, port = listen_address.rpartition(":")
        self._listen_host = host or "localhost"
        self._port_hint = int(port or 0)
        # pid + per-process sequence keeps segment names unique even when
        # several hubs coexist in one engine process (tests, blue/green)
        self.prefix = f"gfd{os.getpid()}x{next(_PREFIX_SEQ)}_w{workers}"
        self.status: Optional[FrontdoorStatus] = None
        self.chans: List[WorkerChannel] = []
        self.procs: List[Optional[multiprocessing.Process]] = []
        self.epochs: List[int] = []
        self.restarts = 0
        self.records_served = 0
        self.address = ""
        self.port = 0
        self._locks: List[threading.Lock] = []
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._stop_evt = threading.Event()
        self._consumer: Optional[threading.Thread] = None
        self._tasks: List[asyncio.Task] = []
        self._mp = multiprocessing.get_context("spawn")

    # ------------------------------------------------------------- lifecycle

    def _spawn(self, i: int) -> None:
        from gubernator_tpu.server import FASTPATH_MIN_BYTES
        p = self._mp.Process(
            target=worker_main,
            args=(i, self.prefix, self.ring_slots, self.slab_bytes,
                  # after the first bind, respawns must re-claim the SAME
                  # public port (an ephemeral hint of 0 would move it)
                  self._listen_host, self.port or self._port_hint,
                  FASTPATH_MIN_BYTES, self.encode, self.batch_reads),
            daemon=True)
        p.start()
        self.procs[i] = p
        self.status.set_w(i, shm_ring.W_PID, p.pid)
        self.status.set_w(i, shm_ring.W_EPOCH, self.epochs[i])

    async def start(self) -> None:
        self._loop = asyncio.get_running_loop()
        self.status = FrontdoorStatus.create(f"{self.prefix}_st",
                                             self.workers)
        self.status.beat()
        self._refresh_flags()
        self.chans = [
            WorkerChannel.create(f"{self.prefix}_r{i}", self.ring_slots,
                                 self.slab_bytes)
            for i in range(self.workers)
        ]
        self._locks = [threading.Lock() for _ in range(self.workers)]
        self.procs = [None] * self.workers
        self.epochs = [0] * self.workers
        for i in range(self.workers):
            self._spawn(i)
        self._consumer = threading.Thread(target=self._consume_loop,
                                          name="frontdoor-consumer",
                                          daemon=True)
        self._consumer.start()
        self._tasks = [
            asyncio.create_task(self._status_loop()),
            asyncio.create_task(self._monitor_loop()),
        ]
        # the public address is worker 0's bound port (every worker shares
        # it under SO_REUSEPORT; stragglers publish their fallback ports
        # in the status block, visible in `cli debug`)
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            self.port = self.status.get_w(0, shm_ring.W_PORT)
            if self.port:
                break
            await asyncio.sleep(0.05)
        if not self.port:
            raise RuntimeError("frontdoor worker 0 never bound its port")
        self.address = f"{self._listen_host}:{self.port}"

    def set_draining(self) -> None:
        if self.status is not None:
            self.status.set_flag(FLAG_DRAINING, True)

    async def stop(self) -> None:
        self.set_draining()
        for t in self._tasks:
            t.cancel()
        self._tasks = []
        for p in self.procs:
            if p is not None and p.is_alive():
                p.terminate()
        joins = [p for p in self.procs if p is not None]
        if joins:
            def _join():
                for p in joins:
                    p.join(timeout=3.0)
                    if p.is_alive():
                        p.kill()
                        p.join(timeout=1.0)
            await self._loop.run_in_executor(None, _join)
        self._stop_evt.set()
        if self._consumer is not None:
            self._consumer.join(timeout=2.0)
            self._consumer = None
        for ch in self.chans:
            ch.close()
        self.chans = []
        if self.status is not None:
            self.status.close()
            self.status = None

    # ----------------------------------------------------- engine-side loops

    def _refresh_flags(self) -> None:
        inst = self.instance
        st = self.status
        st.beat()
        saturated = (inst.qos is not None
                     and inst.qos.admission.saturated)
        st.set_flag(FLAG_SATURATED, bool(saturated))
        pl = getattr(inst.batcher, "pipeline", None)
        from gubernator_tpu import native
        cols_ok = bool(
            native.available() and pl is not None and pl.enabled
            and pl.rpc_enabled and inst.engine._compact_enabled
            and not pl._ring_peers and not inst.mesh_mode)
        st.set_flag(FLAG_COLS_OK, cols_ok)

    async def _status_loop(self) -> None:
        from gubernator_tpu.core.service import HEALTHY
        while True:
            try:
                self._refresh_flags()
                h = await self.instance.health_check()
                self.status.set_health(0 if h.status == HEALTHY else 1,
                                       h.message, h.peer_count)
            except Exception:
                log.exception("frontdoor status refresh failed")
            await asyncio.sleep(0.2)

    async def _monitor_loop(self) -> None:
        backoff = [0.5] * self.workers
        next_ok = [0.0] * self.workers
        while True:
            await asyncio.sleep(0.5)
            for i, p in enumerate(self.procs):
                if p is None or p.is_alive():
                    backoff[i] = 0.5
                    continue
                now = time.monotonic()
                if now < next_ok[i]:
                    continue
                # exponential respawn backoff: a worker that dies at boot
                # (bad port, broken env) must not melt the engine loop
                next_ok[i] = now + backoff[i]
                backoff[i] = min(5.0, backoff[i] * 2)
                # crash-restart: a dead worker's in-flight records are
                # client-visible connection drops already (their TCP
                # connections died with the worker).  Bump the epoch so
                # late completions drop, reset the rings BEFORE the
                # respawn so the fresh worker sees empty queues — no
                # partial commit can survive the boundary.
                log.warning("frontdoor worker %d (pid %s) died; restarting",
                            i, p.pid)
                self.restarts += 1
                self.epochs[i] += 1
                self.status.bump_w(i, shm_ring.W_RESTARTS)
                with self._locks[i]:
                    self.chans[i].reset()
                self._spawn(i)

    def _consume_loop(self) -> None:
        """Submission-ring consumer thread: pops records and hands each to
        the engine event loop.  The pop itself is lock-free against the
        worker; the per-channel lock only serializes against monitor
        resets."""
        while not self._stop_evt.is_set():
            got = False
            for i in range(self.workers):
                with self._locks[i]:
                    recs = self.chans[i].pop()
                    epoch = self.epochs[i]
                for rec in recs:
                    got = True
                    asyncio.run_coroutine_threadsafe(
                        self._serve(i, epoch, rec), self._loop)
            if not got:
                time.sleep(0.0005)

    # -------------------------------------------------------------- serving

    async def _serve(self, wid: int, epoch: int, rec) -> None:
        try:
            payload = await self._dispatch(rec)
            status = 0
        except FrontdoorAbort as e:
            status = e.code.value[0]
            payload = e.message.encode()
        except Exception as e:  # engine bug: surface as INTERNAL
            log.exception("frontdoor record failed (kind %d)", rec.kind)
            status = _INTERNAL
            payload = str(e).encode()
        self.records_served += 1
        # epoch guard: after a crash-restart the slot belongs to the NEW
        # worker's free pool — a stale completion (bytes OR columns) must
        # not touch it: the respawned worker would otherwise encode a
        # dead epoch's decisions against a recycled slab
        if self.epochs[wid] != epoch:
            return
        ch = self.chans[wid]
        if status == 0 and isinstance(payload, tuple):
            if payload[0] == "cols":  # worker-side encode
                st, li, re, rs, fl = payload[1]
                ch.complete_cols(rec.slot, rec.req_id, st, li, re, rs, fl)
            else:  # "bparts": per-RPC serialized parts of a batch
                ch.complete_batch_bytes(rec.slot, rec.req_id, payload[1])
        else:
            ch.complete(rec.slot, rec.req_id, status, payload)

    async def _dispatch(self, rec):
        from gubernator_tpu import server as srv
        from gubernator_tpu.api import pb
        inst = self.instance
        ctx = _EngineContext(rec.deadline)
        if rec.kind == KIND_COLS:
            return await self._serve_cols(rec, ctx)
        if rec.kind == KIND_BATCH_COLS:
            return await self._serve_batch(rec, ctx)
        if rec.kind == KIND_RAW:
            # ONE code path for the response direction: the inner body
            # returns resps from the Python path, and worker-encode mode
            # ships them as columns just like the COLS lane — small and
            # exotic requests no longer fall back to engine serialization
            kind, val = await srv.serve_get_rate_limits_inner(
                inst, rec.payload, ctx)
            if kind == "bytes":
                return val
            return self._finish_resps(val)
        if rec.kind == KIND_PEER_RL:
            return await srv.serve_peer_rate_limits(inst, rec.payload, ctx)
        if rec.kind == KIND_TRANSFER:
            return await srv.serve_transfer_buckets(inst, rec.payload, ctx)
        if rec.kind == KIND_REGISTER:
            req = pb.RegisterGlobalsReq.FromString(rec.payload)
            out = await srv.serve_register_globals(inst, req, ctx)
            return out.SerializeToString()
        if rec.kind == KIND_APPLY_GREG:
            req = pb.ApplyGlobalRegistrationReq.FromString(rec.payload)
            out = await srv.serve_apply_global_registration(inst, req, ctx)
            return out.SerializeToString()
        if rec.kind == KIND_UPDATE_GLOBALS:
            req = pb.UpdatePeerGlobalsReq.FromString(rec.payload)
            out = await srv.serve_update_peer_globals(inst, req, ctx)
            return out.SerializeToString()
        raise FrontdoorAbort(grpc.StatusCode.UNIMPLEMENTED,
                             f"unknown frontdoor record kind {rec.kind}")

    async def _serve_cols(self, rec, ctx: _EngineContext):
        """Worker-parsed columns: the mirror of serve_get_rate_limits with
        the C parse already done.  The columns passed frontdoor_parse_req's
        acceptance rules — exactly the native lane's — so the pipeline
        never range-falls-back on them; the Python fallback below only
        runs on saturation or a pipeline/membership gate, and reconstructs
        the requests exactly (name_lens splits each assembled hash key)."""
        inst = self.instance
        m = inst.metrics
        start = time.monotonic()
        want_cols = self.encode == "worker"
        qos_saturated = (inst.qos is not None
                         and inst.qos.admission.saturated)
        if not qos_saturated:
            out = await inst.batcher.submit_cols(rec.cols, rec.n,
                                                 want_cols=want_cols,
                                                 ctx=self._span_ctx(rec))
            if out is not None:
                m.observe_rpc("/pb.gubernator.V1/GetRateLimits", start,
                              ok=True)
                if want_cols:  # (status, limit, remaining, reset) arrays
                    return ("cols", (*out, None))
                return out
        resps = await self._py_fallback(rec, ctx, m, start)
        return self._finish_resps(resps)

    def _span_ctx(self, rec):
        """Rebuild the worker-propagated traceparent (shm trace region)
        as a SpanContext so the pipeline roots its drain spans under the
        caller's trace; None when the record carried no trace or tracing
        is off."""
        tr = getattr(self.instance, "tracer", None)
        if rec.trace is None or tr is None or not tr.enabled:
            return None
        from gubernator_tpu.observability.tracing import SpanContext
        hi, lo, span = rec.trace
        return SpanContext(f"{hi:016x}{lo:016x}", f"{span:016x}")

    async def _py_fallback(self, rec, ctx: _EngineContext, m, start):
        """Reconstruct the record's requests from its columns and run the
        engine's full Python path (shared by COLS and BATCH fallbacks)."""
        from gubernator_tpu.api.types import RateLimitReq
        from gubernator_tpu.core.service import BatchTooLargeError
        inst = self.instance
        kb, ke, hits, limits, durations, algos = rec.cols
        key_all = bytes(kb)
        reqs = []
        prev = 0
        for j in range(rec.n):
            end = int(ke[j])
            nl = int(rec.name_lens[j])
            k = key_all[prev:end]
            reqs.append(RateLimitReq(
                name=k[:nl].decode("utf-8", "replace"),
                unique_key=k[nl + 1:].decode("utf-8", "replace"),
                hits=int(hits[j]), limit=int(limits[j]),
                duration=int(durations[j]), algorithm=int(algos[j])))
            prev = end
        deadline = None
        if inst.qos is not None:
            deadline = inst.qos.deadline_from_timeout(ctx.time_remaining())
        try:
            resps = await inst.get_rate_limits(reqs, deadline=deadline)
        except BatchTooLargeError as e:
            m.observe_rpc("/pb.gubernator.V1/GetRateLimits", start, ok=False)
            raise FrontdoorAbort(grpc.StatusCode.OUT_OF_RANGE, str(e))
        m.observe_rpc("/pb.gubernator.V1/GetRateLimits", start, ok=True)
        return resps

    async def _serve_batch(self, rec, ctx: _EngineContext):
        """A KIND_BATCH_COLS record: several coalesced RPCs' columns as
        ONE pipeline job, completed as ONE columnar entry the worker
        splits back per-RPC by the counts region.  Batches only exist in
        worker-encode mode, so the completion is columns (or per-RPC
        bytes parts on the rare non-columnifiable fallback)."""
        inst = self.instance
        m = inst.metrics
        start = time.monotonic()
        qos_saturated = (inst.qos is not None
                         and inst.qos.admission.saturated)
        if not qos_saturated:
            out = await inst.batcher.submit_cols(rec.cols, rec.n,
                                                 want_cols=True,
                                                 ctx=self._span_ctx(rec))
            if out is not None:
                for _ in rec.counts:
                    m.observe_rpc("/pb.gubernator.V1/GetRateLimits", start,
                                  ok=True)
                return ("cols", (*out, None))
        resps = await self._py_fallback(rec, ctx, m, start)
        cols = columnify_resps(resps)
        if cols is not None:
            return ("cols", cols)
        # per-RPC serialized parts: split the responses by the request
        # counts so every coalesced RPC still gets ITS response
        from gubernator_tpu.api import pb
        parts = []
        off = 0
        for cnt in rec.counts:
            parts.append(pb.GetRateLimitsResp(responses=[
                pb.resp_to_pb(r) for r in resps[off:off + cnt]
            ]).SerializeToString())
            off += cnt
        self.encode_fallbacks += 1
        return ("bparts", parts)

    def _finish_resps(self, resps):
        """The response-direction tail shared by every GetRateLimits
        fallback: columnify for worker-side encode, or (engine mode /
        non-columnifiable responses) serialize here and count it."""
        from gubernator_tpu.api import pb
        if self.encode == "worker":
            cols = columnify_resps(resps)
            if cols is not None:
                return ("cols", cols)
            self.encode_fallbacks += 1
        return pb.GetRateLimitsResp(
            responses=[pb.resp_to_pb(r) for r in resps]).SerializeToString()

    # -------------------------------------------------------- observability

    def stats(self) -> dict:
        """Aggregates for the metrics scrape hook (watch_frontdoor)."""
        s = {"workers": self.workers, "restarts": self.restarts,
             "rpcs": 0, "sheds": 0, "healthchecks": 0, "stalls": 0,
             "depth": 0, "inflight": 0, "encodes": 0, "enc_fallbacks": 0,
             "batch_rpcs": 0, "batch_flushes": 0, "trace_drops": 0,
             "engine_encode_fallbacks": self.encode_fallbacks}
        if self.status is None:
            return s
        for i in range(self.workers):
            s["rpcs"] += self.status.get_w(i, shm_ring.W_RPCS)
            s["sheds"] += self.status.get_w(i, shm_ring.W_SHEDS)
            s["healthchecks"] += self.status.get_w(i, shm_ring.W_HEALTHCHECKS)
            s["stalls"] += self.status.get_w(i, shm_ring.W_STALLS)
            s["encodes"] += self.status.get_w(i, shm_ring.W_ENCODES)
            s["enc_fallbacks"] += self.status.get_w(i,
                                                    shm_ring.W_ENC_FALLBACK)
            s["batch_rpcs"] += self.status.get_w(i, shm_ring.W_BATCH_RPCS)
            s["batch_flushes"] += self.status.get_w(i,
                                                    shm_ring.W_BATCH_FLUSHES)
            s["trace_drops"] += self.status.get_w(i, shm_ring.W_TRACE_DROPS)
        for ch in self.chans:
            s["depth"] += ch.sub_depth()
            s["inflight"] += ch.inflight()
        return s

    def debug_snapshot(self) -> dict:
        ports = [self.status.get_w(i, shm_ring.W_PORT)
                 for i in range(self.workers)] if self.status else []
        rows = []
        for i in range(self.workers):
            rows.append({
                "pid": self.status.get_w(i, shm_ring.W_PID),
                "port": ports[i],
                "epoch": self.epochs[i],
                "restarts": self.status.get_w(i, shm_ring.W_RESTARTS),
                "rpcs": self.status.get_w(i, shm_ring.W_RPCS),
                "sheds": self.status.get_w(i, shm_ring.W_SHEDS),
                "healthchecks": self.status.get_w(i, shm_ring.W_HEALTHCHECKS),
                "stalls": self.status.get_w(i, shm_ring.W_STALLS),
                "encodes": self.status.get_w(i, shm_ring.W_ENCODES),
                "enc_fallbacks": self.status.get_w(i,
                                                   shm_ring.W_ENC_FALLBACK),
                "batch_rpcs": self.status.get_w(i, shm_ring.W_BATCH_RPCS),
                "batch_flushes": self.status.get_w(i,
                                                   shm_ring.W_BATCH_FLUSHES),
                "trace_drops": self.status.get_w(i, shm_ring.W_TRACE_DROPS),
                "ring_depth": self.chans[i].sub_depth() if self.chans else 0,
                "inflight": self.chans[i].inflight() if self.chans else 0,
            })
        return {
            "workers": self.workers,
            "address": self.address,
            "port_mode": ("reuseport"
                          if len(set(p for p in ports if p)) <= 1
                          else "per-worker-ports"),
            "ring_slots": self.ring_slots,
            "slab_bytes": self.slab_bytes,
            "restarts": self.restarts,
            "records_served": self.records_served,
            "encode_mode": self.encode,
            "batch_reads": self.batch_reads,
            "engine_encode_fallbacks": self.encode_fallbacks,
            "per_worker": rows,
        }
