"""Native host runtime: ctypes bindings for the C++ window router.

Compiles host_router.cc on first use (g++ -O2 -shared) and caches the .so
next to the source; falls back cleanly if no toolchain is present — callers
check `available()` and use the Python router otherwise.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading
from typing import Optional

import numpy as np

log = logging.getLogger("gubernator.native")

_HERE = os.path.dirname(__file__)
_SRC = os.path.join(_HERE, "host_router.cc")
_SO = os.path.join(_HERE, "libhost_router.so")

_lib = None
_lib_lock = threading.Lock()
_lib_failed = False


def _build() -> None:
    cmd = ["g++", "-O2", "-shared", "-fPIC", "-std=c++17", "-o", _SO, _SRC]
    subprocess.run(cmd, check=True, capture_output=True)


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _lib_failed
    with _lib_lock:
        if _lib is not None or _lib_failed:
            return _lib
        try:
            if (not os.path.exists(_SO)
                    or os.path.getmtime(_SO) < os.path.getmtime(_SRC)):
                _build()
            lib = ctypes.CDLL(_SO)
        except Exception as e:
            log.warning("native router unavailable (%s); using Python path", e)
            _lib_failed = True
            return None

        i32p = ctypes.POINTER(ctypes.c_int32)
        i64p = ctypes.POINTER(ctypes.c_int64)
        u8p = ctypes.POINTER(ctypes.c_uint8)
        lib.router_new.restype = ctypes.c_void_p
        lib.router_new.argtypes = [ctypes.c_int32, ctypes.c_int32]
        lib.router_new_mesh.restype = ctypes.c_void_p
        lib.router_new_mesh.argtypes = [ctypes.c_int32] * 4
        lib.router_free.argtypes = [ctypes.c_void_p]
        for fn in ("router_pack", "router_pack_window"):
            getattr(lib, fn).restype = ctypes.c_int64
            getattr(lib, fn).argtypes = [
                ctypes.c_void_p, u8p, i64p, ctypes.c_int64,
                i64p, i64p, i64p, i32p, ctypes.c_int64, ctypes.c_int32,
                i32p, i64p, i64p, i64p, i32p, u8p, i32p, i32p, i32p,
            ]
        for fn in ("router_size", "router_hits", "router_misses"):
            getattr(lib, fn).restype = ctypes.c_int64
            getattr(lib, fn).argtypes = [ctypes.c_void_p]
        lib.router_heap_size.restype = ctypes.c_int64
        lib.router_heap_size.argtypes = [ctypes.c_void_p, ctypes.c_int32]
        for fn in ("router_commit", "router_drain_begin", "router_abort",
                   "router_set_exact"):
            getattr(lib, fn).restype = None
            getattr(lib, fn).argtypes = [ctypes.c_void_p]
        lib.router_set_replay_cap.restype = None
        lib.router_set_replay_cap.argtypes = [ctypes.c_void_p,
                                              ctypes.c_int32]
        lib.fastpath_parse_stack.restype = ctypes.c_int64
        lib.fastpath_parse_stack.argtypes = [
            ctypes.c_void_p, u8p, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_int32, ctypes.c_int32, ctypes.c_int64, ctypes.c_int32,
            i64p, i32p, i32p, i32p, i32p, i32p, i64p, i64p, i32p,
        ]
        lib.fastpath_encode_parts.restype = ctypes.c_int64
        lib.fastpath_encode_parts.argtypes = [
            i64p, i64p, ctypes.c_int64, ctypes.c_int32, ctypes.c_int64,
            i32p, i32p, i32p, i64p, u8p, ctypes.c_int64, i64p, i32p,
        ]
        lib.router_set_ring.restype = None
        lib.router_set_ring.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint32), i32p,
            ctypes.c_int32, ctypes.c_int32,
        ]
        lib.router_pack_stack.restype = ctypes.c_int64
        lib.router_pack_stack.argtypes = [
            ctypes.c_void_p, u8p, i64p, ctypes.c_int64,
            i64p, i64p, i64p, i32p, ctypes.c_int64, ctypes.c_int32,
            ctypes.c_int32, i64p, i32p, i32p, i32p, i32p, i32p,
        ]
        lib.fastpath_encode_w.restype = ctypes.c_int64
        lib.fastpath_encode_w.argtypes = [
            i64p, i64p, ctypes.c_int64, ctypes.c_int32, ctypes.c_int64,
            i32p, i32p, i32p, i64p, u8p, ctypes.c_int64,
        ]
        lib.frontdoor_parse_req.restype = ctypes.c_int64
        lib.frontdoor_parse_req.argtypes = [
            u8p, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
            u8p, i64p, i64p, i64p, i64p, i32p, i32p,
        ]
        lib.frontdoor_encode_resp.restype = ctypes.c_int64
        lib.frontdoor_encode_resp.argtypes = [
            i64p, i64p, i64p, i64p, i32p, ctypes.c_int64,
            u8p, ctypes.c_int64,
        ]
        u64p = ctypes.POINTER(ctypes.c_uint64)
        lib.router_export_keys.restype = ctypes.c_int64
        lib.router_export_keys.argtypes = [
            ctypes.c_void_p, ctypes.c_int32, u64p, i32p, i64p,
        ]
        lib.router_import_keys.restype = ctypes.c_int64
        lib.router_import_keys.argtypes = [
            ctypes.c_void_p, ctypes.c_int32, u64p, i32p, i64p,
            ctypes.c_int64,
        ]
        lib.router_occupancy.restype = None
        lib.router_occupancy.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, i64p, i64p, i64p,
        ]
        _lib = lib
        return _lib


def available() -> bool:
    return _load() is not None


def _ptr(a: np.ndarray, ctype):
    return a.ctypes.data_as(ctypes.POINTER(ctype))


def frontdoor_parse_req(data: bytes, key_bytes: np.ndarray,
                        key_ends: np.ndarray, hits: np.ndarray,
                        limits: np.ndarray, durations: np.ndarray,
                        algos: np.ndarray, name_lens: np.ndarray,
                        max_items: int) -> int:
    """Stateless worker-side parse: serialized GetRateLimitsReq -> request
    columns in caller-owned buffers (the frontdoor worker writes straight
    into its shared-memory slab, core/shm_ring.py).  No Router* involved —
    frontdoor workers never hold engine state.  Returns n >= 0 (requests
    parsed) or a negative fallback code (the worker then ships the raw
    bytes instead); see host_router.cc frontdoor_parse_req.  Callers must
    check available() first."""
    lib = _load()
    if lib is None:
        return -1
    buf = ctypes.cast(ctypes.c_char_p(data), ctypes.POINTER(ctypes.c_uint8))
    return lib.frontdoor_parse_req(
        buf, len(data), max_items, key_bytes.nbytes,
        _ptr(key_bytes, ctypes.c_uint8), _ptr(key_ends, ctypes.c_int64),
        _ptr(hits, ctypes.c_int64), _ptr(limits, ctypes.c_int64),
        _ptr(durations, ctypes.c_int64), _ptr(algos, ctypes.c_int32),
        _ptr(name_lens, ctypes.c_int32))


def frontdoor_encode_resp(status: np.ndarray, limit: np.ndarray,
                          remaining: np.ndarray, reset: np.ndarray,
                          flags, n: int, out: np.ndarray) -> int:
    """Stateless worker-side encode: decision columns (ripped straight out
    of the completion-ring slab, core/shm_ring.py) -> serialized
    GetRateLimitsResp bytes in `out`.  The response-direction mirror of
    frontdoor_parse_req: the engine ships columns, the worker owns the
    protobuf.  flags is an int32 column (0 = plain decision, 1..5 = shed
    reason code per shm_ring.SHED_REASON_CODES) or None.  Returns the byte
    length, or -1 (out too small) / -2 (unknown shed code) — callers fall
    back to the Python pb encoder.  Check available() first."""
    lib = _load()
    if lib is None:
        return -1
    fl = _ptr(flags, ctypes.c_int32) if flags is not None else None
    return lib.frontdoor_encode_resp(
        _ptr(status, ctypes.c_int64), _ptr(limit, ctypes.c_int64),
        _ptr(remaining, ctypes.c_int64), _ptr(reset, ctypes.c_int64),
        fl, n, _ptr(out, ctypes.c_uint8), out.nbytes)


class NativeRouter:
    """Batch key→(shard, slot) resolution + window packing in one C call."""

    def __init__(self, num_shards: int, capacity_per_shard: int,
                 num_global_shards: int = None, shard_offset: int = 0):
        """num_shards = LOCAL shards staged by this process; in mesh mode
        keys hash over num_global_shards and mis-routed keys come back
        marked out_shard == -1 (reject before dispatching)."""
        lib = _load()
        if lib is None:
            raise RuntimeError("native router library unavailable")
        self._lib = lib
        if num_global_shards is None:
            num_global_shards = num_shards
        self._handle = lib.router_new_mesh(
            num_global_shards, shard_offset, num_shards, capacity_per_shard)
        self.num_shards = num_shards
        self.capacity_per_shard = capacity_per_shard
        self.exact = False

    def __del__(self):
        handle = getattr(self, "_handle", None)
        if handle:
            self._lib.router_free(handle)
            self._handle = None

    def pack(
        self,
        key_bytes: np.ndarray,   # uint8 concatenated keys
        key_ends: np.ndarray,    # int64 exclusive end offsets
        hits: np.ndarray, limits: np.ndarray, durations: np.ndarray,
        algos: np.ndarray, now: int, lanes: int,
        out_slot: np.ndarray, out_hits: np.ndarray, out_limit: np.ndarray,
        out_duration: np.ndarray, out_algo: np.ndarray,
        out_is_init: np.ndarray,
        out_shard: np.ndarray, out_lane: np.ndarray,
        shard_fill: np.ndarray,
    ) -> int:
        """Returns how many of the n requests were packed (< n on lane
        overflow; ship the window and repack the remainder)."""
        return self._pack_impl(self._lib.router_pack, key_bytes, key_ends,
                               hits, limits, durations, algos, now, lanes,
                               out_slot, out_hits, out_limit, out_duration,
                               out_algo, out_is_init, out_shard, out_lane,
                               shard_fill)

    def pack_window(self, *args) -> int:
        """router_pack under an open drain (shared pack sequence,
        accumulating commits): one caller-delimited window of a stacked
        dispatch.  Same arguments and return as pack()."""
        return self._pack_impl(self._lib.router_pack_window, *args)

    def _pack_impl(self, fn, key_bytes, key_ends, hits, limits, durations,
                   algos, now, lanes, out_slot, out_hits, out_limit,
                   out_duration, out_algo, out_is_init, out_shard, out_lane,
                   shard_fill) -> int:
        return fn(
            self._handle,
            _ptr(key_bytes, ctypes.c_uint8), _ptr(key_ends, ctypes.c_int64),
            len(key_ends),
            _ptr(hits, ctypes.c_int64), _ptr(limits, ctypes.c_int64),
            _ptr(durations, ctypes.c_int64), _ptr(algos, ctypes.c_int32),
            now, lanes,
            _ptr(out_slot, ctypes.c_int32), _ptr(out_hits, ctypes.c_int64),
            _ptr(out_limit, ctypes.c_int64), _ptr(out_duration, ctypes.c_int64),
            _ptr(out_algo, ctypes.c_int32), _ptr(out_is_init, ctypes.c_uint8),
            _ptr(out_shard, ctypes.c_int32), _ptr(out_lane, ctypes.c_int32),
            _ptr(shard_fill, ctypes.c_int32),
        )

    def commit(self) -> None:
        """Confirm the window(s) staged since the last drain_begin / pack
        were dispatched (clears their entries' init-pending flags)."""
        self._lib.router_commit(self._handle)

    def drain_begin(self) -> None:
        """Open a drain: one pack sequence shared by the following
        parse_stack/pack_stack calls, committed or aborted as a unit."""
        self._lib.router_drain_begin(self._handle)

    def abort(self) -> None:
        """The drain's dispatch failed: keep its fresh allocations pending
        so their next touch re-initializes the (never-written) slots."""
        self._lib.router_abort(self._handle)

    def set_exact_keys(self) -> None:
        """Opt-in exact-key collision guard (stores full keys; a 64-bit
        fingerprint collision then probes onward instead of merging two
        keys' counters).  Call before any key is inserted."""
        self._lib.router_set_exact(self._handle)
        self.exact = True

    def set_replay_cap(self, cap: int) -> None:
        """Bound on a NON-uniform duplicate-key run per device window:
        when one key accumulates `cap` mixed-config/zero-hit lanes in a
        window, its next lane opens a fresh window of the stack, keeping
        the kernel's per-window replay loop bounded (an unbounded replay
        is a multi-hundred-ms device execution — a DoS lever through the
        public RPC surface, and large enough ones crashed the TPU runtime
        worker).  Uniform hot-key duplicates are unaffected (closed form).
        0 disables; the default is 128."""
        self._lib.router_set_replay_cap(self._handle, int(cap))

    def fastpath_parse_stack(self, data: bytes, now: int, lanes: int,
                             K: int, max_items: int, packed: np.ndarray,
                             kcur: np.ndarray, shard_fill: np.ndarray,
                             out_row: np.ndarray, out_lane: np.ndarray,
                             out_pos: np.ndarray,
                             out_limit: np.ndarray, out_off: np.ndarray,
                             out_mlen: np.ndarray,
                             use_ring: bool = True) -> int:
        """Serialized GetRateLimitsReq -> lanes staged across a K-window
        compact stack.  Returns n >= 0 (requests parsed; ring-remote items
        are NOT staged and come back as out_row < -1 markers with their
        message byte ranges in out_off/out_mlen) or a negative fallback
        code; see host_router.cc.  use_ring=False treats every item as
        local (the authoritative peer-plane lane)."""
        # zero-copy read-only view of the immutable bytes
        buf = ctypes.cast(ctypes.c_char_p(data),
                          ctypes.POINTER(ctypes.c_uint8))
        return self._lib.fastpath_parse_stack(
            self._handle, buf, len(data), now, lanes, K, max_items,
            1 if use_ring else 0,
            _ptr(packed, ctypes.c_int64), _ptr(kcur, ctypes.c_int32),
            _ptr(shard_fill, ctypes.c_int32),
            _ptr(out_row, ctypes.c_int32), _ptr(out_lane, ctypes.c_int32),
            _ptr(out_pos, ctypes.c_int32),
            _ptr(out_limit, ctypes.c_int64), _ptr(out_off, ctypes.c_int64),
            _ptr(out_mlen, ctypes.c_int32),
        )

    def parse_stack_fast(self, data: bytes, now: int, lanes: int,
                         K: int, max_items: int, arena, scr,
                         use_ring: bool = True) -> int:
        """fastpath_parse_stack against a WindowArena + JobScratch
        (core/window_buffers.py): identical semantics, but every output
        pointer was derived once at buffer allocation instead of per call
        — the per-call ctypes pointer derivation is a measured fixed cost
        on the drain's host-encode stage."""
        buf = ctypes.cast(ctypes.c_char_p(data),
                          ctypes.POINTER(ctypes.c_uint8))
        return self._lib.fastpath_parse_stack(
            self._handle, buf, len(data), now, lanes, K, max_items,
            1 if use_ring else 0,
            arena.p_packed, arena.p_kcur, arena.p_fills,
            scr.p_row, scr.p_lane, scr.p_pos,
            scr.p_limit, scr.p_off, scr.p_mlen,
        )

    def pack_stack_fast(self, key_bytes: np.ndarray, key_ends: np.ndarray,
                        hits: np.ndarray, limits: np.ndarray,
                        durations: np.ndarray, algos: np.ndarray, now: int,
                        lanes: int, K: int, arena, scr) -> int:
        """router_pack_stack against a WindowArena + JobScratch (cached
        stack/demux pointers; the per-chunk request columns still derive
        theirs per call — they are fresh slices each drain)."""
        return self._lib.router_pack_stack(
            self._handle,
            _ptr(key_bytes, ctypes.c_uint8), _ptr(key_ends, ctypes.c_int64),
            len(key_ends),
            _ptr(hits, ctypes.c_int64), _ptr(limits, ctypes.c_int64),
            _ptr(durations, ctypes.c_int64), _ptr(algos, ctypes.c_int32),
            now, lanes, K,
            arena.p_packed, arena.p_kcur, arena.p_fills,
            scr.p_row, scr.p_lane, scr.p_pos,
        )

    def fastpath_encode_parts(self, w0: np.ndarray, item_limit: np.ndarray,
                              now: int, lanes: int, n: int,
                              out_row: np.ndarray, out_lane: np.ndarray,
                              out_pos: np.ndarray,
                              resp_buf: np.ndarray, item_off: np.ndarray,
                              item_len: np.ndarray,
                              climit: Optional[np.ndarray] = None) -> int:
        """Per-item FRAMED response segments for splicing with forwarded
        peers' bytes (mixed-ownership RPCs); see host_router.cc."""
        cl = _ptr(climit, ctypes.c_int64) if climit is not None else None
        m = self._lib.fastpath_encode_parts(
            _ptr(w0, ctypes.c_int64), _ptr(item_limit, ctypes.c_int64),
            now, lanes, n,
            _ptr(out_row, ctypes.c_int32), _ptr(out_lane, ctypes.c_int32),
            _ptr(out_pos, ctypes.c_int32),
            cl, _ptr(resp_buf, ctypes.c_uint8), resp_buf.nbytes,
            _ptr(item_off, ctypes.c_int64), _ptr(item_len, ctypes.c_int32),
        )
        if m < 0:
            raise RuntimeError("fastpath_encode_parts: buffer too small")
        return m

    def set_ring(self, points: np.ndarray, peer_of: np.ndarray,
                 self_idx: int) -> None:
        """Install (or clear, empty points) the cluster consistent-hash
        ring for per-item local-vs-forward classification.  Must run on the
        engine thread (serialized with staging calls)."""
        n = len(points)
        self._lib.router_set_ring(
            self._handle,
            points.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
            _ptr(peer_of, ctypes.c_int32), n, self_idx,
        )

    def pack_stack(self, key_bytes: np.ndarray, key_ends: np.ndarray,
                   hits: np.ndarray, limits: np.ndarray,
                   durations: np.ndarray, algos: np.ndarray, now: int,
                   lanes: int, K: int, packed: np.ndarray,
                   kcur: np.ndarray, shard_fill: np.ndarray,
                   out_row: np.ndarray, out_lane: np.ndarray,
                   out_pos: np.ndarray) -> int:
        """Columnar request list -> lanes staged across the K-window stack
        (same drain protocol as fastpath_parse_stack)."""
        return self._lib.router_pack_stack(
            self._handle,
            _ptr(key_bytes, ctypes.c_uint8), _ptr(key_ends, ctypes.c_int64),
            len(key_ends),
            _ptr(hits, ctypes.c_int64), _ptr(limits, ctypes.c_int64),
            _ptr(durations, ctypes.c_int64), _ptr(algos, ctypes.c_int32),
            now, lanes, K,
            _ptr(packed, ctypes.c_int64), _ptr(kcur, ctypes.c_int32),
            _ptr(shard_fill, ctypes.c_int32),
            _ptr(out_row, ctypes.c_int32), _ptr(out_lane, ctypes.c_int32),
            _ptr(out_pos, ctypes.c_int32),
        )

    def fastpath_encode_w(self, w0: np.ndarray, item_limit: np.ndarray,
                          now: int, lanes: int, n: int,
                          out_row: np.ndarray, out_lane: np.ndarray,
                          out_pos: np.ndarray, resp_buf: np.ndarray,
                          climit: Optional[np.ndarray] = None) -> int:
        """Fetched response-word plane -> serialized GetRateLimitsResp bytes
        (returns the length written into resp_buf).  climit: the device's
        limit plane, passed only when a stored-limit mismatch was flagged.
        out_pos: per-item synthesis info (aggregated runs), -1 = plain."""
        cl = _ptr(climit, ctypes.c_int64) if climit is not None else None
        m = self._lib.fastpath_encode_w(
            _ptr(w0, ctypes.c_int64), _ptr(item_limit, ctypes.c_int64),
            now, lanes, n,
            _ptr(out_row, ctypes.c_int32), _ptr(out_lane, ctypes.c_int32),
            _ptr(out_pos, ctypes.c_int32),
            cl, _ptr(resp_buf, ctypes.c_uint8), resp_buf.nbytes,
        )
        if m < 0:
            raise RuntimeError("fastpath_encode_w: response buffer too small")
        return m

    def export_keys(self, shard: int):
        """One local shard's resident committed entries, oldest first:
        (fp uint64[n], slot int32[n], expire int64[n]) — entry index ==
        device slot, so a snapshot needs no key strings to stay coherent
        with the restored arena planes."""
        cap = self.capacity_per_shard
        fp = np.empty(cap, np.uint64)
        slot = np.empty(cap, np.int32)
        expire = np.empty(cap, np.int64)
        n = self._lib.router_export_keys(
            self._handle, shard, _ptr(fp, ctypes.c_uint64),
            _ptr(slot, ctypes.c_int32), _ptr(expire, ctypes.c_int64))
        return fp[:n].copy(), slot[:n].copy(), expire[:n].copy()

    def import_keys(self, shard: int, fp: np.ndarray, slot: np.ndarray,
                    expire: np.ndarray) -> None:
        """Rebuild one local shard from export_keys output (oldest first).
        Raises on invalid slots or when the exact-key guard is active
        (exports carry no key bytes)."""
        fp = np.ascontiguousarray(fp, np.uint64)
        slot = np.ascontiguousarray(slot, np.int32)
        expire = np.ascontiguousarray(expire, np.int64)
        rc = self._lib.router_import_keys(
            self._handle, shard, _ptr(fp, ctypes.c_uint64),
            _ptr(slot, ctypes.c_int32), _ptr(expire, ctypes.c_int64),
            len(fp))
        if rc == -2:
            raise RuntimeError(
                "exact-keys native router cannot import a fingerprint-only "
                "snapshot")
        if rc != 0:
            raise ValueError("invalid or duplicate slot in key-map import")

    def occupancy(self, now: int):
        """(live, expired, free) slot counts over all local shards, judged
        by the host expiry estimate (engine.cache_stats)."""
        live = np.zeros(1, np.int64)
        expired = np.zeros(1, np.int64)
        free_slots = np.zeros(1, np.int64)
        self._lib.router_occupancy(
            self._handle, now, _ptr(live, ctypes.c_int64),
            _ptr(expired, ctypes.c_int64), _ptr(free_slots, ctypes.c_int64))
        return int(live[0]), int(expired[0]), int(free_slots[0])

    def heap_size(self, shard: int = 0) -> int:
        """Expiry-heap nodes (live + draining) for one shard — lets tests
        assert the bounded-heap guarantee at churn scale."""
        return self._lib.router_heap_size(self._handle, shard)

    @property
    def size(self) -> int:
        return self._lib.router_size(self._handle)

    @property
    def hits(self) -> int:
        return self._lib.router_hits(self._handle)

    @property
    def misses(self) -> int:
        return self._lib.router_misses(self._handle)
