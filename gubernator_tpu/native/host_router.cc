// Native host router: batch key -> (shard, slot) resolution for the window
// packer.
//
// The reference's equivalent work is a Go map lookup + LRU list touch under a
// mutex per request (cache/lru.go:104-121) plus a crc32 ring lookup
// (hash.go:80-96).  In this framework that host-side bookkeeping is the hot
// loop feeding the TPU (the kernel itself left Python long ago), so it is
// implemented natively: one C call resolves a whole window.
//
// Design:
//   * per-shard open-addressing hash table (linear probing, backward-shift
//     deletion), keyed by a 64-bit FNV-1a fingerprint of the key string.
//     Key bytes are NOT stored — at 100M keys the expected fingerprint
//     collision count is ~0.03 percent windows of one colliding pair
//     (n^2 / 2^65), and a collision merely merges two keys' counters.
//   * shard = crc32(key) % num_shards, matching the Python router
//     (core/engine.py shard_of) so native and Python paths route alike.
//   * strict LRU per shard via an intrusive doubly-linked list over entry
//     indices; eviction pops the tail exactly like the reference
//     (cache/lru.go:92-94,131-136).
//   * expiry estimates refresh on every touch; hit/miss counters match the
//     reference's semantics (expired-entry touch counts as a miss,
//     lru.go:110-119).
//
// Built as a plain shared library, loaded via ctypes (native/__init__.py).

#include <cstdint>
#include <cstdlib>
#include <cstring>

namespace {

// ---- hashing --------------------------------------------------------------

uint32_t crc32_table[256];
bool crc32_init_done = false;

void crc32_init() {
  if (crc32_init_done) return;
  for (uint32_t i = 0; i < 256; i++) {
    uint32_t c = i;
    for (int k = 0; k < 8; k++) c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
    crc32_table[i] = c;
  }
  crc32_init_done = true;
}

// IEEE crc32, matching zlib.crc32 / Go hash/crc32.ChecksumIEEE
uint32_t crc32(const uint8_t* data, int64_t len) {
  uint32_t c = 0xFFFFFFFFu;
  for (int64_t i = 0; i < len; i++)
    c = crc32_table[(c ^ data[i]) & 0xFF] ^ (c >> 8);
  return c ^ 0xFFFFFFFFu;
}

uint64_t fnv1a64(const uint8_t* data, int64_t len) {
  uint64_t h = 1469598103934665603ull;
  for (int64_t i = 0; i < len; i++) {
    h ^= data[i];
    h *= 1099511628211ull;
  }
  // never return 0: 0 marks an empty table cell
  return h ? h : 1ull;
}

// ---- per-shard table ------------------------------------------------------

constexpr int32_t NIL = -1;

struct Shard {
  // open-addressing table: cell -> entry index (or NIL)
  int32_t* cells;
  uint32_t mask;  // table size - 1 (power of two)

  // entry storage, one per device slot
  uint64_t* fp;        // fingerprint per entry (entry i owns device slot i)
  int64_t* expire;     // host-side expiry estimate
  uint32_t* cell_of;   // entry -> its cell (for O(1) delete)
  int32_t* prev;       // LRU links (head = MRU)
  int32_t* next;
  int32_t lru_head, lru_tail;
  int32_t* free_list;
  int32_t free_top;
  int32_t capacity;
  int64_t hits, misses, size;
};

struct Router {
  Shard* shards;
  int32_t num_shards;         // local shards staged by this process
  int32_t num_global_shards;  // hashing modulus (== num_shards single-proc)
  int32_t shard_offset;       // first local shard's global index
};

uint32_t next_pow2(uint32_t v) {
  v--;
  v |= v >> 1; v |= v >> 2; v |= v >> 4; v |= v >> 8; v |= v >> 16;
  return v + 1;
}

void shard_init(Shard* s, int32_t capacity) {
  uint32_t tsize = next_pow2((uint32_t)capacity * 2);
  s->cells = (int32_t*)malloc(sizeof(int32_t) * tsize);
  for (uint32_t i = 0; i < tsize; i++) s->cells[i] = NIL;
  s->mask = tsize - 1;
  s->fp = (uint64_t*)calloc(capacity, sizeof(uint64_t));
  s->expire = (int64_t*)calloc(capacity, sizeof(int64_t));
  s->cell_of = (uint32_t*)calloc(capacity, sizeof(uint32_t));
  s->prev = (int32_t*)malloc(sizeof(int32_t) * capacity);
  s->next = (int32_t*)malloc(sizeof(int32_t) * capacity);
  s->free_list = (int32_t*)malloc(sizeof(int32_t) * capacity);
  for (int32_t i = 0; i < capacity; i++) s->free_list[i] = capacity - 1 - i;
  s->free_top = capacity;
  s->lru_head = s->lru_tail = NIL;
  s->capacity = capacity;
  s->hits = s->misses = s->size = 0;
}

void lru_unlink(Shard* s, int32_t e) {
  if (s->prev[e] != NIL) s->next[s->prev[e]] = s->next[e];
  else s->lru_head = s->next[e];
  if (s->next[e] != NIL) s->prev[s->next[e]] = s->prev[e];
  else s->lru_tail = s->prev[e];
}

void lru_push_front(Shard* s, int32_t e) {
  s->prev[e] = NIL;
  s->next[e] = s->lru_head;
  if (s->lru_head != NIL) s->prev[s->lru_head] = e;
  s->lru_head = e;
  if (s->lru_tail == NIL) s->lru_tail = e;
}

// backward-shift deletion keeps probe chains tombstone-free
void table_delete_cell(Shard* s, uint32_t cell) {
  uint32_t hole = cell;
  uint32_t i = cell;
  for (;;) {
    i = (i + 1) & s->mask;
    int32_t e = s->cells[i];
    if (e == NIL) break;
    uint32_t home = (uint32_t)(s->fp[e] & s->mask);
    // can entry at i move into the hole? yes iff hole is within its probe path
    uint32_t dist_home_to_hole = (hole - home) & s->mask;
    uint32_t dist_home_to_i = (i - home) & s->mask;
    if (dist_home_to_hole <= dist_home_to_i) {
      s->cells[hole] = e;
      s->cell_of[e] = hole;
      hole = i;
    }
  }
  s->cells[hole] = NIL;
}

// returns slot; *is_init set when the key was (re)allocated
int32_t shard_lookup(Shard* s, uint64_t fp, int64_t now, int64_t duration,
                     uint8_t* is_init) {
  uint32_t cell = (uint32_t)(fp & s->mask);
  for (;;) {
    int32_t e = s->cells[cell];
    if (e == NIL) break;
    if (s->fp[e] == fp) {
      if (s->expire[e] < now) s->misses++;  // expired touch counts as a miss
      else s->hits++;
      s->expire[e] = now + duration;
      lru_unlink(s, e);
      lru_push_front(s, e);
      *is_init = 0;
      return e;
    }
    cell = (cell + 1) & s->mask;
  }
  // miss: allocate (free slot, else evict LRU tail)
  s->misses++;
  int32_t e;
  if (s->free_top > 0) {
    e = s->free_list[--s->free_top];
    s->size++;
  } else {
    e = s->lru_tail;
    lru_unlink(s, e);
    table_delete_cell(s, s->cell_of[e]);
    // the probe chain may have shifted into our target cell; re-probe
    cell = (uint32_t)(fp & s->mask);
    while (s->cells[cell] != NIL) cell = (cell + 1) & s->mask;
  }
  s->cells[cell] = e;
  s->cell_of[e] = cell;
  s->fp[e] = fp;
  s->expire[e] = now + duration;
  lru_push_front(s, e);
  *is_init = 1;
  return e;
}

}  // namespace

extern "C" {

// Mesh mode (parallel/distributed.py): keys hash over num_global_shards but
// this process only stages lanes for [shard_offset, shard_offset+num_shards).
// Single-process: global == local, offset 0 (router_new).
Router* router_new_mesh(int32_t num_global_shards, int32_t shard_offset,
                        int32_t num_local_shards,
                        int32_t capacity_per_shard) {
  crc32_init();
  Router* r = (Router*)malloc(sizeof(Router));
  r->num_shards = num_local_shards;
  r->num_global_shards = num_global_shards;
  r->shard_offset = shard_offset;
  r->shards = (Shard*)malloc(sizeof(Shard) * num_local_shards);
  for (int32_t i = 0; i < num_local_shards; i++)
    shard_init(&r->shards[i], capacity_per_shard);
  return r;
}

Router* router_new(int32_t num_shards, int32_t capacity_per_shard) {
  return router_new_mesh(num_shards, 0, num_shards, capacity_per_shard);
}

void router_free(Router* r) {
  for (int32_t i = 0; i < r->num_shards; i++) {
    Shard* s = &r->shards[i];
    free(s->cells); free(s->fp); free(s->expire); free(s->cell_of);
    free(s->prev); free(s->next); free(s->free_list);
  }
  free(r->shards);
  free(r);
}

// Resolve and pack one window.  Keys are concatenated UTF-8 bytes with
// exclusive end offsets.  Output lane arrays are [num_shards * lanes]
// row-major; slot lanes the packer doesn't fill must be pre-set to PAD by
// the caller.  Returns the number of requests packed: < n means the next
// request would overflow its shard's lane budget (caller ships this window
// and repacks the rest).
int64_t router_pack(
    Router* r,
    const uint8_t* key_bytes, const int64_t* key_ends, int64_t n,
    const int64_t* hits, const int64_t* limits, const int64_t* durations,
    const int32_t* algos, int64_t now, int32_t lanes,
    int32_t* out_slot, int64_t* out_hits, int64_t* out_limit,
    int64_t* out_duration, int32_t* out_algo, uint8_t* out_is_init,
    int32_t* out_shard, int32_t* out_lane, int32_t* shard_fill) {
  for (int64_t i = 0; i < n; i++) {
    int64_t beg = i == 0 ? 0 : key_ends[i - 1];
    int64_t len = key_ends[i] - beg;
    const uint8_t* key = key_bytes + beg;
    int32_t shard =
        (int32_t)(crc32(key, len) % (uint32_t)r->num_global_shards) -
        r->shard_offset;
    if (shard < 0 || shard >= r->num_shards) {
      // mis-routed key (mesh mode): mark it and let the caller reject the
      // batch before dispatching — it consumes no lane
      out_shard[i] = -1;
      out_lane[i] = -1;
      continue;
    }
    int32_t lane = shard_fill[shard];
    if (lane >= lanes) return i;
    uint8_t is_init = 0;
    int32_t slot = shard_lookup(&r->shards[shard], fnv1a64(key, len), now,
                                durations[i], &is_init);
    int64_t o = (int64_t)shard * lanes + lane;

    out_slot[o] = slot;
    out_hits[o] = hits[i];
    out_limit[o] = limits[i];
    out_duration[o] = durations[i];
    out_algo[o] = algos[i];
    out_is_init[o] = is_init;
    out_shard[i] = (int32_t)shard;
    out_lane[i] = lane;
    shard_fill[shard] = lane + 1;
  }
  return n;
}

int64_t router_size(Router* r) {
  int64_t total = 0;
  for (int32_t i = 0; i < r->num_shards; i++) total += r->shards[i].size;
  return total;
}

int64_t router_hits(Router* r) {
  int64_t total = 0;
  for (int32_t i = 0; i < r->num_shards; i++) total += r->shards[i].hits;
  return total;
}

int64_t router_misses(Router* r) {
  int64_t total = 0;
  for (int32_t i = 0; i < r->num_shards; i++) total += r->shards[i].misses;
  return total;
}

}  // extern "C"
