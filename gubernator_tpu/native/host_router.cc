// Native host router: batch key -> (shard, slot) resolution for the window
// packer.
//
// The reference's equivalent work is a Go map lookup + LRU list touch under a
// mutex per request (cache/lru.go:104-121) plus a crc32 ring lookup
// (hash.go:80-96).  In this framework that host-side bookkeeping is the hot
// loop feeding the TPU (the kernel itself left Python long ago), so it is
// implemented natively: one C call resolves a whole window.
//
// Design:
//   * per-shard open-addressing hash table (linear probing, backward-shift
//     deletion), keyed by a 64-bit FNV-1a fingerprint of the key string.
//     Key bytes are NOT stored — at 100M keys the expected fingerprint
//     collision count is ~0.03 percent windows of one colliding pair
//     (n^2 / 2^65), and a collision merely merges two keys' counters.
//   * shard = crc32(key) % num_shards, matching the Python router
//     (core/engine.py shard_of) so native and Python paths route alike.
//   * per-shard LRU via an intrusive doubly-linked list over entry indices.
//     A full shard first reclaims an EXPIRED slot (lazy expiry min-heap)
//     and only then evicts the LRU tail like the reference
//     (cache/lru.go:92-94,131-136) — so churny workloads never evict live
//     keys while dead ones occupy slots.
//   * expiry estimates refresh on every touch; hit/miss counters match the
//     reference's semantics (expired-entry touch counts as a miss,
//     lru.go:110-119).
//
// Built as a plain shared library, loaded via ctypes (native/__init__.py).

#include <cstdint>
#include <cstdlib>
#include <cstring>

namespace {

// ---- hashing --------------------------------------------------------------

uint32_t crc32_table[256];
bool crc32_init_done = false;

void crc32_init() {
  if (crc32_init_done) return;
  for (uint32_t i = 0; i < 256; i++) {
    uint32_t c = i;
    for (int k = 0; k < 8; k++) c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
    crc32_table[i] = c;
  }
  crc32_init_done = true;
}

// IEEE crc32, matching zlib.crc32 / Go hash/crc32.ChecksumIEEE
uint32_t crc32(const uint8_t* data, int64_t len) {
  uint32_t c = 0xFFFFFFFFu;
  for (int64_t i = 0; i < len; i++)
    c = crc32_table[(c ^ data[i]) & 0xFF] ^ (c >> 8);
  return c ^ 0xFFFFFFFFu;
}

uint64_t fnv1a64(const uint8_t* data, int64_t len) {
  uint64_t h = 1469598103934665603ull;
  for (int64_t i = 0; i < len; i++) {
    h ^= data[i];
    h *= 1099511628211ull;
  }
  // never return 0: 0 marks an empty table cell
  return h ? h : 1ull;
}

// ---- per-shard table ------------------------------------------------------

constexpr int32_t NIL = -1;

struct HeapNode {
  int64_t expire;
  int32_t e;
};

struct Shard {
  // open-addressing table: cell -> entry index (or NIL)
  int32_t* cells;
  uint32_t mask;  // table size - 1 (power of two)

  // entry storage, one per device slot
  uint64_t* fp;        // fingerprint per entry (entry i owns device slot i)
  int64_t* expire;     // host-side expiry estimate
  uint32_t* cell_of;   // entry -> its cell (for O(1) delete)
  int32_t* prev;       // LRU links (head = MRU)
  int32_t* next;
  int32_t lru_head, lru_tail;
  int32_t* free_list;
  int32_t free_top;
  int32_t capacity;
  int64_t hits, misses, size;
  // init-pending tracking: a freshly (re)allocated entry keeps reporting
  // is_init=1 until a device dispatch actually commits its window
  // (router_commit).  Without this, a pack that aborts before dispatch
  // would consume the flag, and a retry could inherit a recycled slot's
  // previous tenant's live device state.
  uint8_t* pending;
  uint32_t* seq;  // pack sequence that last reported is_init for the entry
  // lazy expiry min-heap: lets a full shard reclaim an EXPIRED slot before
  // evicting a live LRU victim.  Nodes go stale when an entry is re-touched
  // (its expiry moved) or evicted; staleness is detected on pop against the
  // entry's live expire + residency.  To BOUND the heap at 100M-key scale
  // without a stop-the-world rebuild (an O(capacity) pause lands mid-window
  // at that size), overflow swaps the heap aside and drains it back a few
  // nodes per touch (heap_old), and refresh pushes are suppressed when the
  // expiry moved by less than duration/4 (reclaim correctness survives
  // because a popped hint reclaims on the entry's CURRENT expiry, not the
  // hint's).
  HeapNode* heap;
  int64_t heap_len, heap_cap;
  HeapNode* heap_old;  // draining after an overflow swap (nullptr if none)
  int64_t heap_old_len;
  // exact-key guard (opt-in, router_set_exact): stores each entry's full
  // key so a 64-bit fingerprint collision probes onward instead of silently
  // merging two keys' counters.  nullptr when disabled.
  uint8_t** keys;
  int32_t* klen;
};

// One tracked key's segment stats for the replay-bound guard.  A cell is
// live iff seq == Router::drain_seq (stamp-validated: no per-drain clear).
struct RepCell {
  uint64_t fp;       // 0 = empty slot in the map
  int64_t h, l, d;   // the segment's first-lane request tuple
  uint32_t seq;
  int32_t shard;
  int32_t algo;
  int32_t lanes;     // lanes staged for this key in its current window
  int32_t nonuniform;  // 1 once any lane broke the uniform pattern
  // duplicate-run aggregation (stage-time, pass 2): while a key's run
  // stays uniform hits=1/limit>0, later items fold into ONE staged lane
  // (AGG_SLOT_BIT, kernel.py) instead of new lanes.  The fold compares
  // against the ARMED LANE's own tuple (agg_l/agg_d/agg_algo) — the
  // pass-1 cfg above is tracking state that the replay-cap reset
  // rewrites and MUST NOT gate folding (fuzz-caught: a stale-reset cfg
  // matched a later item into a different-config lane).  Every staged
  // lane of a key re-arms or invalidates the target, so the armed lane
  // is always the key's LATEST lane and folding never reorders.
  int64_t agg_off;   // w0 index of the aggregation lane, -1 none
  int32_t agg_k;     // window the lane lives in (stale => new lane)
  int32_t agg_n;     // items folded so far (next item's 0-based pos)
  int32_t slot;      // device slot of the lane (eviction check)
  int64_t agg_l, agg_d;  // the armed lane's limit/duration (hits == 1)
  int32_t agg_algo;
};

struct Router {
  Shard* shards;
  int32_t num_shards;         // local shards staged by this process
  int32_t num_global_shards;  // hashing modulus (== num_shards single-proc)
  int32_t shard_offset;       // first local shard's global index
  uint32_t pack_seq;          // increments per pack/parse call (or per drain)
  int64_t* commit_list;       // (shard << 32) | entry, pending inits staged
  int64_t commit_len, commit_cap;  //   by the LAST pack/parse call or drain
  int32_t exact;              // exact-key guard enabled
  uint8_t* scratch;           // assembled hash_key scratch (exact mode)
  int64_t scratch_cap;
  // cluster mode: the consistent-hash ring (reference hash.go:28-96) so
  // the RPC parser can classify items local-vs-forward per key.  Empty
  // (ring_len == 0) means standalone: every key is local.
  uint32_t* ring_points;      // sorted hash points
  int32_t* ring_peer;         // peer index per point
  int32_t ring_len;
  int32_t ring_self;          // this node's peer index
  // replay-bound tracker (see rep_track): per-drain open-addressing map
  // (shard, fp) -> this key's current-window segment stats, used to split
  // windows so the device kernel's per-window replay loop stays bounded.
  RepCell* rep;
  int64_t rep_cap;            // power of two, grown on load
  int64_t rep_live;           // live cells this drain (load control)
  uint32_t drain_seq;         // validity stamp (bumped per drain)
  int32_t replay_cap;         // max lanes of a NON-uniform segment per
                              // window; 0 disables the guard
};

uint32_t next_pow2(uint32_t v) {
  v--;
  v |= v >> 1; v |= v >> 2; v |= v >> 4; v |= v >> 8; v |= v >> 16;
  return v + 1;
}

void shard_init(Shard* s, int32_t capacity) {
  uint32_t tsize = next_pow2((uint32_t)capacity * 2);
  s->cells = (int32_t*)malloc(sizeof(int32_t) * tsize);
  for (uint32_t i = 0; i < tsize; i++) s->cells[i] = NIL;
  s->mask = tsize - 1;
  s->fp = (uint64_t*)calloc(capacity, sizeof(uint64_t));
  s->expire = (int64_t*)calloc(capacity, sizeof(int64_t));
  s->cell_of = (uint32_t*)calloc(capacity, sizeof(uint32_t));
  s->prev = (int32_t*)malloc(sizeof(int32_t) * capacity);
  s->next = (int32_t*)malloc(sizeof(int32_t) * capacity);
  s->free_list = (int32_t*)malloc(sizeof(int32_t) * capacity);
  for (int32_t i = 0; i < capacity; i++) s->free_list[i] = capacity - 1 - i;
  s->free_top = capacity;
  s->lru_head = s->lru_tail = NIL;
  s->capacity = capacity;
  s->hits = s->misses = s->size = 0;
  s->pending = (uint8_t*)calloc(capacity, sizeof(uint8_t));
  s->seq = (uint32_t*)calloc(capacity, sizeof(uint32_t));
  s->heap = nullptr;
  s->heap_len = s->heap_cap = 0;
  s->heap_old = nullptr;
  s->heap_old_len = 0;
  s->keys = nullptr;
  s->klen = nullptr;
}

// entry e is resident iff some table cell still points at it (cell_of is
// only maintained while resident, and removal clears the pointing cell)
inline bool is_resident(Shard* s, int32_t e) {
  return s->cells[s->cell_of[e]] == e;
}

// pop the min node off an arbitrary heap array (sift-down the last node)
inline HeapNode heap_pop_min(HeapNode* heap, int64_t* len) {
  HeapNode top = heap[0];
  heap[0] = heap[--*len];
  if (*len) {
    int64_t i = 0;
    HeapNode v = heap[0];
    for (;;) {
      int64_t l = 2 * i + 1, r = l + 1, m = i;
      int64_t best = v.expire;
      if (l < *len && heap[l].expire < best) {
        m = l;
        best = heap[l].expire;
      }
      if (r < *len && heap[r].expire < best) m = r;
      if (m == i) break;
      heap[i] = heap[m];
      i = m;
    }
    heap[i] = v;
  }
  return top;
}

void heap_insert(Shard* s, int64_t expire, int32_t e) {
  if (s->heap_len == s->heap_cap) {
    s->heap_cap = s->heap_cap ? s->heap_cap * 2 : 1024;
    s->heap = (HeapNode*)realloc(s->heap, sizeof(HeapNode) * s->heap_cap);
  }
  int64_t i = s->heap_len++;
  while (i > 0) {
    int64_t p = (i - 1) / 2;
    if (s->heap[p].expire <= expire) break;
    s->heap[i] = s->heap[p];
    i = p;
  }
  s->heap[i].expire = expire;
  s->heap[i].e = e;
}

// is node n still worth keeping as a reclaim hint?
inline bool hint_live(Shard* s, const HeapNode& n) {
  return s->cells[s->cell_of[n.e]] == n.e && s->expire[n.e] >= n.expire;
}

void heap_push(Shard* s, int64_t expire, int32_t e) {
  // Overflow: swap the (mostly stale) heap aside and drain it back
  // incrementally — a stop-the-world rebuild is an O(capacity) pause,
  // which at the 100M-key target lands mid-serving-window.
  if (s->heap_old == nullptr && s->heap_len > 4 * (int64_t)s->capacity) {
    s->heap_old = s->heap;
    s->heap_old_len = s->heap_len;
    s->heap = nullptr;
    s->heap_len = s->heap_cap = 0;
  }
  if (s->heap_old != nullptr) {
    // amortized drain: far faster than the ~1 push/touch growth rate
    for (int drained = 0; drained < 8 && s->heap_old_len > 0; drained++) {
      HeapNode n = heap_pop_min(s->heap_old, &s->heap_old_len);
      if (hint_live(s, n)) heap_insert(s, n.expire, n.e);
    }
    if (s->heap_old_len == 0) {
      free(s->heap_old);
      s->heap_old = nullptr;
    }
  }
  heap_insert(s, expire, e);
}


void push_commit(Router* r, int32_t shard, int32_t e) {
  if (r->commit_len == r->commit_cap) {
    r->commit_cap = r->commit_cap ? r->commit_cap * 2 : 256;
    r->commit_list = (int64_t*)realloc(r->commit_list,
                                       sizeof(int64_t) * r->commit_cap);
  }
  r->commit_list[r->commit_len++] = ((int64_t)shard << 32) | (uint32_t)e;
}

void lru_unlink(Shard* s, int32_t e) {
  if (s->prev[e] != NIL) s->next[s->prev[e]] = s->next[e];
  else s->lru_head = s->next[e];
  if (s->next[e] != NIL) s->prev[s->next[e]] = s->prev[e];
  else s->lru_tail = s->prev[e];
}

void lru_push_front(Shard* s, int32_t e) {
  s->prev[e] = NIL;
  s->next[e] = s->lru_head;
  if (s->lru_head != NIL) s->prev[s->lru_head] = e;
  s->lru_head = e;
  if (s->lru_tail == NIL) s->lru_tail = e;
}

// backward-shift deletion keeps probe chains tombstone-free
void table_delete_cell(Shard* s, uint32_t cell) {
  uint32_t hole = cell;
  uint32_t i = cell;
  for (;;) {
    i = (i + 1) & s->mask;
    int32_t e = s->cells[i];
    if (e == NIL) break;
    uint32_t home = (uint32_t)(s->fp[e] & s->mask);
    // can entry at i move into the hole? yes iff hole is within its probe path
    uint32_t dist_home_to_hole = (hole - home) & s->mask;
    uint32_t dist_home_to_i = (i - home) & s->mask;
    if (dist_home_to_hole <= dist_home_to_i) {
      s->cells[hole] = e;
      s->cell_of[e] = hole;
      hole = i;
    }
  }
  s->cells[hole] = NIL;
}

// Pop expired hints until one names a live-and-truly-expired entry;
// returns its entry index (removed from table+LRU, ready for reuse) or
// NIL.  Reclaim checks the entry's CURRENT expiry (not the hint's), so
// hints left behind by the push-suppression rule still reclaim correctly;
// a hint whose entry refreshed past `now` is RE-PUSHED at the entry's
// current expiry (conserves hint coverage for hot-then-idle keys).  Work
// per attempt is capped so an allocation never stalls on a stale-hint
// burst (it falls back to LRU eviction instead).
int32_t try_reclaim_expired(Shard* s, int64_t now) {
  HeapNode repush[32];
  int nr = 0;
  int32_t out = NIL;
  for (int iter = 0; iter < 32; iter++) {
    HeapNode* heap;
    int64_t* len;
    if (s->heap_len > 0 && s->heap[0].expire < now) {
      heap = s->heap;
      len = &s->heap_len;
    } else if (s->heap_old != nullptr && s->heap_old_len > 0 &&
               s->heap_old[0].expire < now) {
      heap = s->heap_old;
      len = &s->heap_old_len;
    } else {
      break;
    }
    HeapNode n = heap_pop_min(heap, len);
    if (!is_resident(s, n.e)) continue;  // dead hint
    if (s->expire[n.e] < now) {
      lru_unlink(s, n.e);
      table_delete_cell(s, s->cell_of[n.e]);
      out = n.e;
      break;
    }
    if (nr < 32) {  // refreshed entry: restore an exact hint
      repush[nr].expire = s->expire[n.e];
      repush[nr++].e = n.e;
    }
  }
  for (int i = 0; i < nr; i++) heap_insert(s, repush[i].expire, repush[i].e);
  if (s->heap_old != nullptr && s->heap_old_len == 0) {
    free(s->heap_old);
    s->heap_old = nullptr;
  }
  return out;
}

// returns slot; *is_init set when the device must (re)initialize it.
// cur_seq: the current pack call's sequence — a pending entry reports
// is_init only once per pack call (later duplicates in the same window see
// the in-window live register, kernel-side), but keeps reporting it across
// pack calls until router_commit confirms a dispatch wrote the slot.
// key/key_len: the full hash-key bytes, compared (and stored) only when the
// exact-key guard is on — a fingerprint collision then probes onward to its
// own cell instead of merging counters.
int32_t shard_lookup(Shard* s, uint64_t fp, int64_t now, int64_t duration,
                     uint32_t cur_seq, uint8_t* is_init,
                     const uint8_t* key = nullptr, int64_t key_len = 0) {
  uint32_t cell = (uint32_t)(fp & s->mask);
  for (;;) {
    int32_t e = s->cells[cell];
    if (e == NIL) break;
    if (s->fp[e] == fp &&
        (s->keys == nullptr ||
         (s->klen[e] == (int32_t)key_len &&
          memcmp(s->keys[e], key, key_len) == 0))) {
      if (s->expire[e] < now) s->misses++;  // expired touch counts as a miss
      else s->hits++;
      int64_t ne = now + duration;
      if (s->expire[e] != ne) {
        // hint-churn suppression: re-push only when the expiry moved by
        // more than duration/4 (or backwards).  Pop-time reclaim checks
        // the entry's CURRENT expiry and re-pushes refreshed hints, so
        // sparser hints stay correct — this is what keeps the heap bounded
        // at the 100M-key scale instead of growing one node per touch.
        bool push = ne - s->expire[e] > duration / 4 || ne < s->expire[e];
        s->expire[e] = ne;
        if (push) heap_push(s, ne, e);
      }
      lru_unlink(s, e);
      lru_push_front(s, e);
      if (s->pending[e] && s->seq[e] != cur_seq) {
        s->seq[e] = cur_seq;
        *is_init = 1;  // allocated by an earlier pack that never dispatched
      } else {
        *is_init = 0;
      }
      return e;
    }
    cell = (cell + 1) & s->mask;
  }
  // miss: allocate (free slot, else reclaim an expired slot, else evict
  // the LRU tail)
  s->misses++;
  int32_t e;
  if (s->free_top > 0) {
    e = s->free_list[--s->free_top];
    s->size++;
  } else {
    e = try_reclaim_expired(s, now);
    if (e == NIL) {
      e = s->lru_tail;
      lru_unlink(s, e);
      table_delete_cell(s, s->cell_of[e]);
    }
    // the probe chain may have shifted into our target cell; re-probe
    cell = (uint32_t)(fp & s->mask);
    while (s->cells[cell] != NIL) cell = (cell + 1) & s->mask;
  }
  s->cells[cell] = e;
  s->cell_of[e] = cell;
  s->fp[e] = fp;
  s->expire[e] = now + duration;
  heap_push(s, now + duration, e);
  lru_push_front(s, e);
  s->pending[e] = 1;
  s->seq[e] = cur_seq;
  if (s->keys != nullptr) {
    free(s->keys[e]);
    s->keys[e] = (uint8_t*)malloc(key_len ? key_len : 1);
    memcpy(s->keys[e], key, key_len);
    s->klen[e] = (int32_t)key_len;
  }
  *is_init = 1;
  return e;
}

}  // namespace

extern "C" {

// Mesh mode (parallel/distributed.py): keys hash over num_global_shards but
// this process only stages lanes for [shard_offset, shard_offset+num_shards).
// Single-process: global == local, offset 0 (router_new).
Router* router_new_mesh(int32_t num_global_shards, int32_t shard_offset,
                        int32_t num_local_shards,
                        int32_t capacity_per_shard) {
  crc32_init();
  Router* r = (Router*)malloc(sizeof(Router));
  r->num_shards = num_local_shards;
  r->num_global_shards = num_global_shards;
  r->shard_offset = shard_offset;
  r->shards = (Shard*)malloc(sizeof(Shard) * num_local_shards);
  for (int32_t i = 0; i < num_local_shards; i++)
    shard_init(&r->shards[i], capacity_per_shard);
  r->pack_seq = 0;
  r->commit_list = nullptr;
  r->commit_len = r->commit_cap = 0;
  r->exact = 0;
  r->scratch = nullptr;
  r->scratch_cap = 0;
  r->ring_points = nullptr;
  r->ring_peer = nullptr;
  r->ring_len = 0;
  r->ring_self = -1;
  r->rep = nullptr;
  r->rep_cap = 0;
  r->rep_live = 0;
  r->drain_seq = 0;
  r->replay_cap = 128;  // see rep_track; router_set_replay_cap overrides
  return r;
}

// Bound on NON-uniform duplicate-key segment length per device window
// (the kernel replays such segments one lane per round).  0 disables.
void router_set_replay_cap(Router* r, int32_t cap) {
  r->replay_cap = cap < 0 ? 0 : cap;
}

// Install (or clear, n == 0) the cluster's consistent-hash ring so
// fastpath_parse_stack can classify items per key.  points must be sorted
// ascending; peer_of[i] is the peer index owning point i; self_idx is this
// node's peer index.  Caller must serialize with staging calls (the engine
// executor thread does).
void router_set_ring(Router* r, const uint32_t* points,
                     const int32_t* peer_of, int32_t n, int32_t self_idx) {
  free(r->ring_points);
  free(r->ring_peer);
  r->ring_points = nullptr;
  r->ring_peer = nullptr;
  r->ring_len = n;
  r->ring_self = self_idx;
  if (n > 0) {
    r->ring_points = (uint32_t*)malloc(sizeof(uint32_t) * n);
    r->ring_peer = (int32_t*)malloc(sizeof(int32_t) * n);
    memcpy(r->ring_points, points, sizeof(uint32_t) * n);
    memcpy(r->ring_peer, peer_of, sizeof(int32_t) * n);
  }
}

// Enable the exact-key collision guard.  Must be called before any key is
// inserted (entries allocated earlier have no stored key to compare).
void router_set_exact(Router* r) {
  r->exact = 1;
  for (int32_t i = 0; i < r->num_shards; i++) {
    Shard* s = &r->shards[i];
    if (s->keys == nullptr) {
      s->keys = (uint8_t**)calloc(s->capacity, sizeof(uint8_t*));
      s->klen = (int32_t*)calloc(s->capacity, sizeof(int32_t));
    }
  }
}

// ---- drain protocol ------------------------------------------------------
// A drain is one engine-thread batch of stacked staging calls
// (fastpath_parse_stack / router_pack_stack) followed by ONE device
// dispatch.  All calls share one pack sequence (so a key allocated by an
// earlier call in the drain stops reporting is_init to later calls — its
// init lane is already staged in an earlier window of the same stack), and
// the pending-init commit list accumulates across the drain:
//   router_drain_begin -> stage... -> dispatch -> router_commit
//                                  \-> dispatch failed -> router_abort
// router_abort keeps the staged entries pending, so their next touch
// re-reports is_init and the device re-initializes the slot (the arena
// never saw the failed windows).
void router_drain_begin(Router* r) {
  r->pack_seq++;
  r->drain_seq++;   // invalidates every replay-guard cell (stamp check)
  r->rep_live = 0;
  // belt-and-braces: a crashed previous drain that called neither commit
  // nor abort must not have its pending inits cleared by THIS drain's
  // commit (the entries stay pending, so their next touch re-inits)
  r->commit_len = 0;
}

void router_abort(Router* r) { r->commit_len = 0; }

// Confirm that the window staged by the LAST pack/parse call was actually
// dispatched: its fresh allocations stop reporting is_init.
void router_commit(Router* r) {
  for (int64_t i = 0; i < r->commit_len; i++) {
    int32_t shard = (int32_t)(r->commit_list[i] >> 32);
    int32_t e = (int32_t)(r->commit_list[i] & 0xFFFFFFFF);
    r->shards[shard].pending[e] = 0;
  }
  r->commit_len = 0;
}

Router* router_new(int32_t num_shards, int32_t capacity_per_shard) {
  return router_new_mesh(num_shards, 0, num_shards, capacity_per_shard);
}

void router_free(Router* r) {
  for (int32_t i = 0; i < r->num_shards; i++) {
    Shard* s = &r->shards[i];
    free(s->cells); free(s->fp); free(s->expire); free(s->cell_of);
    free(s->prev); free(s->next); free(s->free_list);
    free(s->pending); free(s->seq); free(s->heap); free(s->heap_old);
    if (s->keys != nullptr) {
      for (int32_t e = 0; e < s->capacity; e++) free(s->keys[e]);
      free(s->keys);
      free(s->klen);
    }
  }
  free(r->shards);
  free(r->commit_list);
  free(r->scratch);
  free(r->ring_points);
  free(r->ring_peer);
  free(r->rep);
  free(r);
}

namespace {

// Shared body of router_pack / router_pack_window (the latter runs under
// an open drain: one pack sequence and an accumulating commit list across
// K caller-delimited windows, see router_drain_begin).
int64_t pack_full_impl(
    Router* r,
    const uint8_t* key_bytes, const int64_t* key_ends, int64_t n,
    const int64_t* hits, const int64_t* limits, const int64_t* durations,
    const int32_t* algos, int64_t now, int32_t lanes,
    int32_t* out_slot, int64_t* out_hits, int64_t* out_limit,
    int64_t* out_duration, int32_t* out_algo, uint8_t* out_is_init,
    int32_t* out_shard, int32_t* out_lane, int32_t* shard_fill) {
  for (int64_t i = 0; i < n; i++) {
    int64_t beg = i == 0 ? 0 : key_ends[i - 1];
    int64_t len = key_ends[i] - beg;
    const uint8_t* key = key_bytes + beg;
    int32_t shard =
        (int32_t)(crc32(key, len) % (uint32_t)r->num_global_shards) -
        r->shard_offset;
    if (shard < 0 || shard >= r->num_shards) {
      // mis-routed key (mesh mode): mark it and let the caller reject the
      // batch before dispatching — it consumes no lane
      out_shard[i] = -1;
      out_lane[i] = -1;
      continue;
    }
    int32_t lane = shard_fill[shard];
    if (lane >= lanes) return i;
    uint8_t is_init = 0;
    int32_t slot = shard_lookup(&r->shards[shard], fnv1a64(key, len), now,
                                durations[i], r->pack_seq, &is_init, key, len);
    if (is_init) push_commit(r, shard, slot);
    int64_t o = (int64_t)shard * lanes + lane;

    out_slot[o] = slot;
    out_hits[o] = hits[i];
    out_limit[o] = limits[i];
    out_duration[o] = durations[i];
    out_algo[o] = algos[i];
    out_is_init[o] = is_init;
    out_shard[i] = (int32_t)shard;
    out_lane[i] = lane;
    shard_fill[shard] = lane + 1;
  }
  return n;
}

}  // namespace

// Resolve and pack one window.  Keys are concatenated UTF-8 bytes with
// exclusive end offsets.  Output lane arrays are [num_shards * lanes]
// row-major; slot lanes the packer doesn't fill must be pre-set to PAD by
// the caller.  Returns the number of requests packed: < n means the next
// request would overflow its shard's lane budget (caller ships this window
// and repacks the rest).
int64_t router_pack(
    Router* r,
    const uint8_t* key_bytes, const int64_t* key_ends, int64_t n,
    const int64_t* hits, const int64_t* limits, const int64_t* durations,
    const int32_t* algos, int64_t now, int32_t lanes,
    int32_t* out_slot, int64_t* out_hits, int64_t* out_limit,
    int64_t* out_duration, int32_t* out_algo, uint8_t* out_is_init,
    int32_t* out_shard, int32_t* out_lane, int32_t* shard_fill) {
  r->pack_seq++;
  r->commit_len = 0;  // an uncommitted previous window stays pending
  return pack_full_impl(r, key_bytes, key_ends, n, hits, limits, durations,
                        algos, now, lanes, out_slot, out_hits, out_limit,
                        out_duration, out_algo, out_is_init, out_shard,
                        out_lane, shard_fill);
}

// Drain-protocol sibling of router_pack: caller delimits the windows of a
// stacked dispatch (RateLimitEngine.step_stacked) — one window per call,
// output arrays pointed at that window's slice of the stacked staging —
// under one router_drain_begin .. router_commit/router_abort bracket, so a
// key first seen in window k reports is_init exactly once across the
// whole stack.
int64_t router_pack_window(
    Router* r,
    const uint8_t* key_bytes, const int64_t* key_ends, int64_t n,
    const int64_t* hits, const int64_t* limits, const int64_t* durations,
    const int32_t* algos, int64_t now, int32_t lanes,
    int32_t* out_slot, int64_t* out_hits, int64_t* out_limit,
    int64_t* out_duration, int32_t* out_algo, uint8_t* out_is_init,
    int32_t* out_shard, int32_t* out_lane, int32_t* shard_fill) {
  return pack_full_impl(r, key_bytes, key_ends, n, hits, limits, durations,
                        algos, now, lanes, out_slot, out_hits, out_limit,
                        out_duration, out_algo, out_is_init, out_shard,
                        out_lane, shard_fill);
}

// ---- fast serving path --------------------------------------------------
//
// One C call takes a serialized GetRateLimitsReq straight to a staged
// compact-format device window (api/proto/gubernator.proto; wire format in
// ops/kernel.py "compact wire format"), and a second C call takes the
// fetched compact response straight to a serialized GetRateLimitsResp.
// This replaces the per-item Python protobuf decode + dataclass hops that
// otherwise bound the serving path (the reference's whole GetRateLimits
// walk, gubernator.go:75-166, is Go codegen + map ops; ours is two C calls
// and one device dispatch).
//
// The parser is deliberately narrow: BATCHING behavior, valid algorithm,
// nonempty name/key, compact-range hits/limit/duration.  Anything else
// returns a negative code and the caller falls back to the full Python
// path, which handles every semantic (per-item errors, GLOBAL, chunking).

namespace {

inline bool read_varint(const uint8_t** pp, const uint8_t* end,
                        uint64_t* out) {
  const uint8_t* p = *pp;
  uint64_t v = 0;
  int shift = 0;
  while (p < end && shift < 70) {
    uint8_t b = *p++;
    v |= (uint64_t)(b & 0x7F) << shift;
    if (!(b & 0x80)) {
      *out = v;
      *pp = p;
      return true;
    }
    shift += 7;
  }
  return false;
}

inline uint32_t crc32_update(uint32_t c, const uint8_t* d, int64_t n) {
  for (int64_t i = 0; i < n; i++)
    c = crc32_table[(c ^ d[i]) & 0xFF] ^ (c >> 8);
  return c;
}

inline uint64_t fnv1a_update(uint64_t h, const uint8_t* d, int64_t n) {
  for (int64_t i = 0; i < n; i++) {
    h ^= d[i];
    h *= 1099511628211ull;
  }
  return h;
}

inline int varint_size(uint64_t v) {
  int n = 1;
  while (v >= 0x80) {
    v >>= 7;
    n++;
  }
  return n;
}

inline uint8_t* write_varint(uint8_t* p, uint64_t v) {
  while (v >= 0x80) {
    *p++ = (uint8_t)(v | 0x80);
    v >>= 7;
  }
  *p++ = (uint8_t)v;
  return p;
}

constexpr int64_t COMPACT_MAX_HITS = 1ll << 28;
constexpr int64_t COMPACT_MAX_LIMIT = 1ll << 31;
constexpr int64_t COMPACT_MAX_DURATION = (1ll << 31) - 16;
// Algorithm-plane caps (ops/kernel.py): sliding windows interpolate across
// two buckets so the rebased-i32 proof needs now - window_start < 2*duration;
// concurrency hits are sign-extended through bit 27 of the compact hits field
// so releases (negative hits) survive the 28-bit encode.
constexpr int64_t SLIDING_MAX_DURATION = 1ll << 30;
constexpr int64_t CONC_MAX_HITS = 1ll << 27;

// Per-algorithm compact range gate.  algo 0..4 are stageable; anything the
// compact wire cannot carry exactly returns false and the caller falls back
// to the full python path (-2).
inline bool compact_ranges_ok(int64_t hits, int64_t limit, int64_t duration,
                              int64_t algo) {
  if (algo < 0 || algo > 4) return false;
  if (algo == 4) {
    if (hits <= -CONC_MAX_HITS || hits >= CONC_MAX_HITS) return false;
  } else {
    if (hits < 0 || hits >= COMPACT_MAX_HITS) return false;
  }
  if (limit < 0 || limit >= COMPACT_MAX_LIMIT) return false;
  int64_t dcap = algo == 3 ? SLIDING_MAX_DURATION : COMPACT_MAX_DURATION;
  if (duration < 0 || duration >= dcap) return false;
  return true;
}

}  // namespace

namespace {

constexpr int32_t MAX_STACK_ITEMS = 1024;  // > MAX_BATCH_SIZE (1000)
constexpr int32_t MAX_STACK_SHARDS = 256;

struct ParsedItem {
  const uint8_t* name;
  int64_t name_len;
  const uint8_t* key;
  int64_t key_len;
  int64_t hits, limit, duration;
  uint32_t algo;
  int32_t shard;  // local shard index
  uint64_t fp;
  int64_t scratch_off;  // assembled hash_key offset (exact mode)
  int32_t owner;        // ring peer index (-1 == local / no ring)
  int64_t msg_off;      // serialized RateLimitReq body within the RPC bytes
  int32_t msg_len;
};

// Parse one serialized RateLimitReq message body into *it (no validation).
// Returns false on malformed bytes.
bool parse_item(const uint8_t* q, const uint8_t* qend, ParsedItem* it,
                uint64_t* behavior) {
  it->name = nullptr;
  it->name_len = 0;
  it->key = nullptr;
  it->key_len = 0;
  it->hits = it->limit = it->duration = 0;
  it->algo = 0;
  *behavior = 0;
  while (q < qend) {
    uint64_t t;
    if (!read_varint(&q, qend, &t)) return false;
    uint64_t field = t >> 3;
    int wt = (int)(t & 7);
    if (wt == 2) {
      uint64_t l;
      if (!read_varint(&q, qend, &l) || l > (uint64_t)(qend - q))
        return false;
      if (field == 1) {
        it->name = q;
        it->name_len = (int64_t)l;
      } else if (field == 2) {
        it->key = q;
        it->key_len = (int64_t)l;
      }
      q += l;
    } else if (wt == 0) {
      uint64_t v;
      if (!read_varint(&q, qend, &v)) return false;
      if (field == 3) it->hits = (int64_t)v;
      else if (field == 4) it->limit = (int64_t)v;
      else if (field == 5) it->duration = (int64_t)v;
      else if (field == 6) it->algo = (uint32_t)v;
      else if (field == 7) *behavior = v;
    } else {
      return false;
    }
  }
  return true;
}

// Per-shard stack-fit check shared by the two staging entry points: can
// `demand[s]` more lanes be placed for every shard, given the monotonic
// window cursors?  (Windows fill per shard in cursor order, so the free
// space is the tail of the cursor's window plus every later window.)
// ---- replay-bound guard -------------------------------------------------
// The device kernel replays a NON-uniform duplicate-key segment one lane
// per while_loop round; an RPC carrying thousands of same-key lanes with
// mixed configs would compile into one multi-hundred-ms device execution
// (big enough ones crashed the TPU runtime worker — round-4 finding).
// Uniform hot keys are untouched (the closed form is O(1) regardless of
// length).  When a key's segment is known non-uniform and reaches
// replay_cap lanes in the current window, the NEXT lane forces its shard
// onto a fresh window of the stack, bounding every window's replay depth.
//
// Tracking runs in the side-effect-free pass 1 (keyed by (shard, fp) —
// the slot is not known until pass 2).  On a pack that later falls back,
// the counts persist for the drain: purely conservative (an earlier
// split next time), never wrong.

RepCell* rep_probe(Router* r, int32_t shard, uint64_t fp) {
  if (r->rep_cap == 0) {
    r->rep = (RepCell*)calloc(1024, sizeof(RepCell));
    if (!r->rep) return nullptr;  // OOM: guard degrades to off, no crash
    r->rep_cap = 1024;
  }
  uint64_t mask = (uint64_t)r->rep_cap - 1;
  uint64_t h = fp ^ ((uint64_t)(uint32_t)shard * 0x9E3779B97F4A7C15ull);
  for (int64_t probe = 0;; probe++) {
    RepCell* c = &r->rep[(h + probe) & mask];
    if (c->seq != r->drain_seq || c->fp == 0) return c;  // free (stale ok)
    if (c->fp == fp && c->shard == shard) return c;
    if (probe >= r->rep_cap) return nullptr;  // table saturated
  }
}

void rep_grow(Router* r) {
  int64_t old_cap = r->rep_cap;
  RepCell* old = r->rep;
  RepCell* grown = (RepCell*)calloc(old_cap * 2, sizeof(RepCell));
  if (!grown) return;  // OOM: keep the old table (denser probing, no crash)
  r->rep_cap = old_cap * 2;
  r->rep = grown;
  uint64_t mask = (uint64_t)r->rep_cap - 1;
  for (int64_t i = 0; i < old_cap; i++) {
    if (old[i].seq != r->drain_seq || old[i].fp == 0) continue;
    uint64_t h = old[i].fp ^
                 ((uint64_t)(uint32_t)old[i].shard * 0x9E3779B97F4A7C15ull);
    for (int64_t probe = 0;; probe++) {
      RepCell* c = &r->rep[(h + probe) & mask];
      if (c->seq != r->drain_seq || c->fp == 0) { *c = old[i]; break; }
    }
  }
  free(old);
}

// Track one local item; returns 1 if it must open a new window for its
// shard (the caller accounts the spill and pass 2 honors it).
inline int rep_track(Router* r, int32_t shard, uint64_t fp, int64_t h,
                     int64_t l, int64_t d, int32_t algo) {
  if (!r->replay_cap) return 0;
  if (fp == 0) fp = 1;
  if (r->rep_cap && r->rep_live * 2 >= r->rep_cap) rep_grow(r);
  RepCell* c = rep_probe(r, shard, fp);
  if (!c) return 0;  // saturated: guard degrades to off for new keys
  if (c->seq != r->drain_seq || c->fp == 0 ||
      !(c->fp == fp && c->shard == shard)) {
    r->rep_live++;
    *c = RepCell{fp, h, l, d, r->drain_seq, shard, algo, 1,
                 h == 0, -1, -1, 0, -1, 0, 0, 0};
    return 0;
  }
  c->lanes++;
  if (!c->nonuniform &&
      !(h == c->h && l == c->l && d == c->d && algo == c->algo && h > 0))
    c->nonuniform = 1;
  if (c->nonuniform && c->lanes > r->replay_cap) {
    // this lane starts the key's segment in a FRESH window
    *c = RepCell{fp, h, l, d, r->drain_seq, shard, algo, 1, h == 0,
                 -1, -1, 0, -1, 0, 0, 0};
    return 1;
  }
  return 0;
}

// Exact pass-1 placement check: walk the staged items per shard in order
// (fold-predicted items still count a lane — conservative; fold
// misprediction must never overflow pass 2), applying window spills and
// replay-cap splits exactly as stage_lane will.  items: per-item shard;
// bumps: per-item force-new flags.  Returns false if any shard would run
// past the K-th window.
bool stack_fits_exact(const int32_t* shards_arr, const uint8_t* bumps,
                      int64_t n, const int32_t* kcur,
                      const int32_t* shard_fill, int32_t S, int32_t lanes,
                      int32_t K) {
  int32_t simk[MAX_STACK_SHARDS];
  int32_t simfill[MAX_STACK_SHARDS];
  for (int32_t s = 0; s < S; s++) {
    simk[s] = kcur[s];
    simfill[s] = shard_fill[kcur[s] * S + s];
  }
  for (int64_t i = 0; i < n; i++) {
    int32_t s = shards_arr[i];
    if (s < 0) continue;  // forwarded / not staged
    if (bumps[i] && simfill[s] > 0) {
      simk[s]++;
      simfill[s] = 0;
    }
    if (simfill[s] >= lanes) {
      simk[s]++;
      simfill[s] = 0;
    }
    if (simk[s] >= K) return false;
    simfill[s]++;
  }
  return true;
}

// Stage one resolved item into the window stack.  packed is
// i64[K, S, lanes, 2]; out_row gets the flattened window-row index
// (widx * S + shard) so the encoder can address the fetched [K*S, lanes]
// response plane directly.
// AGG_SLOT_BIT mirror (ops/kernel.py): bit 30 of the packed slot+1 field
// marks an aggregated hits=1 run; the device answers with r_start and the
// encoder synthesizes each item's response from its 0-based position.
constexpr int64_t AGG_W0_BIT = 1ll << 30;

inline void stage_lane(Router* r, int32_t shard, uint64_t fp,
                       const uint8_t* key, int64_t key_len, int64_t now,
                       int64_t hits, int64_t limit, int64_t duration,
                       uint32_t algo, int32_t lanes, int32_t K,
                       int64_t* packed, int32_t* kcur, int32_t* shard_fill,
                       int32_t* out_row, int32_t* out_lane, int32_t* out_pos,
                       int64_t i, int force_new) {
  int32_t S = r->num_shards;
  // replay-bound split (rep_track said so in pass 1): this lane opens a
  // fresh window for its shard so the device replay loop stays bounded
  if (force_new && shard_fill[kcur[shard] * S + shard] > 0) kcur[shard]++;
  uint8_t is_init = 0;
  int32_t slot = shard_lookup(&r->shards[shard], fp, now, duration,
                              r->pack_seq, &is_init, key, key_len);
  // response synthesizable by pos; algo >= 2 never aggregates (posinfo
  // carries the algorithm in 2 bits only, and GCRA/sliding/concurrency
  // responses are not linear in the fold count anyway)
  bool synth = hits == 1 && limit > 0 && algo <= 1;
  // Probe the key's drain cell for BOTH synth and plain items: a plain
  // lane staged for this key must invalidate any armed aggregation lane
  // (folding a later item into a lane that sorts BEFORE the plain lane
  // would reorder the key's sequential semantics — and pass-1 state
  // cannot carry this, the replay-cap reset clears nonuniform).
  RepCell* c = r->replay_cap ? rep_probe(r, shard, fp) : nullptr;
  bool cell_live = c && c->seq == r->drain_seq && c->fp == (fp ? fp : 1) &&
                   c->shard == shard;
  if (synth && cell_live && !is_init &&
      c->agg_off >= 0 && c->agg_k == kcur[shard] && c->slot == slot &&
      c->agg_l == limit && c->agg_d == duration &&
      c->agg_algo == (int32_t)algo &&
      c->agg_n < (int32_t)(COMPACT_MAX_HITS - 1)) {
    // the cap keeps the folded count inside the 28-bit compact hits
    // field (folds consume no lanes, so stack capacity alone does not
    // bound it); at the cap the item below stages a fresh lane and
    // re-arms the cell there
    // fold into the existing aggregation lane: one more hit, no new lane
    packed[c->agg_off] += 1ll << 34;
    int64_t row_lane = c->agg_off / 2;
    out_row[i] = (int32_t)(row_lane / lanes);
    out_lane[i] = (int32_t)(row_lane % lanes);
    out_pos[i] = c->agg_n++ | ((int32_t)algo << 30);
    return;
  }
  int32_t k = kcur[shard];
  if (shard_fill[k * S + shard] >= lanes) k = ++kcur[shard];
  int32_t lane = shard_fill[k * S + shard]++;
  if (is_init) push_commit(r, shard, slot);
  int64_t row = (int64_t)k * S + shard;
  int64_t o = (row * lanes + lane) * 2;
  // algo rides in 3 bits: bit 33 plus bits 62..63, so legacy token/leaky
  // words stay bit-identical; hits are masked because concurrency releases
  // are negative (sign-extended from bit 27 on decode)
  int64_t w0 = (int64_t)(slot + 1) | ((int64_t)is_init << 32) |
               ((int64_t)(algo & 1) << 33) |
               ((hits & (COMPACT_MAX_HITS - 1)) << 34) |
               ((int64_t)((algo >> 1) & 3) << 62);
  if (synth) {
    w0 |= AGG_W0_BIT;  // n=1 aggregate: device returns r_start
    out_pos[i] = 0 | ((int32_t)algo << 30);
    if (cell_live) {  // future uniform duplicates fold into this lane
      c->agg_off = o;
      c->agg_k = k;
      c->agg_n = 1;
      c->slot = slot;
      c->agg_l = limit;
      c->agg_d = duration;
      c->agg_algo = (int32_t)algo;
    }
  } else {
    out_pos[i] = -1;  // plain lane: legacy response decode
    if (cell_live) c->agg_off = -1;  // see probe comment above
  }
  packed[o] = w0;
  packed[o + 1] = limit | (duration << 32);
  out_row[i] = (int32_t)row;
  out_lane[i] = lane;
}

uint8_t* scratch_reserve(Router* r, int64_t need) {
  if (need > r->scratch_cap) {
    int64_t cap = r->scratch_cap ? r->scratch_cap : 4096;
    while (cap < need) cap *= 2;
    r->scratch = (uint8_t*)realloc(r->scratch, cap);
    r->scratch_cap = cap;
  }
  return r->scratch;
}

// Successor point with wraparound (reference hash.go:80-96 / the Python
// ring's bisect_left): owner of hash h.
inline int32_t ring_owner(const Router* r, uint32_t h) {
  int32_t lo = 0, hi = r->ring_len;
  while (lo < hi) {
    int32_t mid = (lo + hi) / 2;
    if (r->ring_points[mid] < h) lo = mid + 1;
    else hi = mid;
  }
  if (lo == r->ring_len) lo = 0;
  return r->ring_peer[lo];
}

}  // namespace

// Parse a serialized GetRateLimitsReq and stage it into a STACK of K
// compact-format windows (one drain = many such calls + one stacked device
// dispatch; see router_drain_begin).  Items spill to later windows when
// their shard's current window is full; the per-shard cursor `kcur`
// (caller-owned, zeroed at drain start) only moves forward, so all staging
// for a shard — and therefore for any single key — is window-monotonic
// across the whole drain, preserving sequential per-key semantics through
// the device-side scan.
//
// Two passes: pass 1 parses, validates and hashes every item with NO side
// effects (a fallback return leaves the router and the stack untouched —
// no allocations, no evictions, no consumed lanes); pass 2 stages
// unconditionally.
//
// packed: i64[K, S, lanes, 2] pre-zeroed; shard_fill: i32[K, S];
// kcur: i32[S].  out_row/out_lane/out_limit: per-item demux info
// (out_limit feeds the response encoder, which echoes the request limit —
// see fastpath_encode_w).
//
// Cluster mode (router_set_ring installed): items whose ring owner is a
// DIFFERENT peer are not staged; they come back marked
// out_row[i] = -2 - owner with their serialized RateLimitReq body range in
// out_off/out_mlen, so the host forwards just those items without
// re-parsing the RPC (reference analog: the per-item owner-vs-forward
// split, gubernator.go:114-152).
//
// Returns the request count n >= 0, or:
//   -1  malformed protobuf
//   -2  a request needs the full path (behavior/algorithm/validation/range)
//   -3  more than max_items requests
//   -6  the RPC does not fit in this stack's remaining lanes (caller
//       dispatches the stack and retries on a fresh one; -6 on a FRESH
//       stack means the RPC can never fit and must take the full path)
// use_ring == 0 treats every item as local even when a ring is installed:
// the peer-plane lane (GetPeerRateLimits) is authoritative for whatever it
// receives, like the reference owner (gubernator.go:210-227).
int64_t fastpath_parse_stack(Router* r, const uint8_t* buf, int64_t len,
                             int64_t now, int32_t lanes, int32_t K,
                             int64_t max_items, int32_t use_ring,
                             int64_t* packed,
                             int32_t* kcur, int32_t* shard_fill,
                             int32_t* out_row, int32_t* out_lane,
                             int32_t* out_pos,
                             int64_t* out_limit, int64_t* out_off,
                             int32_t* out_mlen) {
  int32_t S = r->num_shards;
  if (S > MAX_STACK_SHARDS) return -2;
  if (max_items > MAX_STACK_ITEMS) max_items = MAX_STACK_ITEMS;
  static thread_local ParsedItem items[MAX_STACK_ITEMS];
  static thread_local uint8_t bump[MAX_STACK_ITEMS];
  static thread_local int32_t item_shard[MAX_STACK_ITEMS];

  // ---- pass 1: parse + validate + hash, no side effects on the router
  //      tables (the replay-bound tracker is drain-scoped and purely
  //      conservative on aborted packs — see rep_track) ----
  const uint8_t* p = buf;
  const uint8_t* end = buf + len;
  int64_t n = 0;
  int64_t scratch_need = 0;
  while (p < end) {
    uint64_t tag;
    if (!read_varint(&p, end, &tag)) return -1;
    if (tag != ((1u << 3) | 2)) {  // only field 1: repeated RateLimitReq
      int wt = (int)(tag & 7);
      if (wt == 0) {
        uint64_t dummy;
        if (!read_varint(&p, end, &dummy)) return -1;
      } else if (wt == 2) {
        uint64_t l;
        if (!read_varint(&p, end, &l) || l > (uint64_t)(end - p))
          return -1;
        p += l;
      } else {
        return -1;
      }
      continue;
    }
    uint64_t mlen;
    if (!read_varint(&p, end, &mlen) || mlen > (uint64_t)(end - p))
      return -1;
    if (n >= max_items) return -3;
    ParsedItem* it = &items[n];
    it->msg_off = p - buf;
    it->msg_len = (int32_t)mlen;
    uint64_t behavior;
    if (!parse_item(p, p + mlen, it, &behavior)) return -1;
    p += mlen;

    if (it->name_len == 0 || it->key_len == 0) return -2;
    if (behavior != 0) return -2;  // BATCHING only
    // concurrency rides the python path: the host lease book needs
    // per-item visibility the bytes lane does not surface
    if (it->algo == 4) return -2;
    if (!compact_ranges_ok(it->hits, it->limit, it->duration, it->algo))
      return -2;

    // hash key = name + "_" + unique_key (client.go:33-35), streamed
    uint8_t sep = '_';
    uint32_t c = 0xFFFFFFFFu;
    c = crc32_update(c, it->name, it->name_len);
    c = crc32_update(c, &sep, 1);
    c = crc32_update(c, it->key, it->key_len);
    uint32_t crc = c ^ 0xFFFFFFFFu;

    it->owner = -1;  // local
    if (use_ring && r->ring_len > 0) {
      int32_t owner = ring_owner(r, crc);
      if (owner != r->ring_self) {
        it->owner = owner;  // forwarded: parsed but never staged
        bump[n] = 0;
        item_shard[n] = -1;
        n++;
        continue;
      }
    }

    uint64_t fp = fnv1a_update(1469598103934665603ull, it->name,
                               it->name_len);
    fp = fnv1a_update(fp, &sep, 1);
    fp = fnv1a_update(fp, it->key, it->key_len);
    it->fp = fp ? fp : 1;

    int32_t shard = (int32_t)(crc % (uint32_t)r->num_global_shards) -
                    r->shard_offset;
    if (shard < 0 || shard >= S) return -2;  // not ours: full path routes it
    it->shard = shard;
    item_shard[n] = shard;
    bump[n] = (uint8_t)rep_track(r, shard, it->fp, it->hits, it->limit,
                                 it->duration, (int32_t)it->algo);
    if (r->exact) {
      it->scratch_off = scratch_need;
      scratch_need += it->name_len + 1 + it->key_len;
    }
    n++;
  }
  // Exact placement simulation: spills and replay-cap splits are applied
  // as pass 2 will; fold-predicted duplicates still count a lane
  // (conservative — fold prediction can break on mid-drain eviction, and
  // pass 2 must never overflow).
  if (!stack_fits_exact(item_shard, bump, n, kcur, shard_fill, S, lanes, K))
    return -6;

  // ---- pass 2: stage (cannot fail) ----
  uint8_t* scratch = r->exact ? scratch_reserve(r, scratch_need) : nullptr;
  for (int64_t i = 0; i < n; i++) {
    ParsedItem* it = &items[i];
    if (it->owner >= 0) {  // forwarded item: marker + message byte range
      out_row[i] = -2 - it->owner;
      out_lane[i] = -1;
      out_pos[i] = -1;
      out_limit[i] = it->limit;
      out_off[i] = it->msg_off;
      out_mlen[i] = it->msg_len;
      continue;
    }
    const uint8_t* kb = nullptr;
    int64_t kl = 0;
    if (r->exact) {
      kb = scratch + it->scratch_off;
      uint8_t* w = scratch + it->scratch_off;
      memcpy(w, it->name, it->name_len);
      w[it->name_len] = '_';
      memcpy(w + it->name_len + 1, it->key, it->key_len);
      kl = it->name_len + 1 + it->key_len;
    }
    stage_lane(r, it->shard, it->fp, kb, kl, now, it->hits, it->limit,
               it->duration, it->algo, lanes, K, packed, kcur, shard_fill,
               out_row, out_lane, out_pos, i, bump[i]);
    out_limit[i] = it->limit;
  }
  return n;
}

// Stateless pass-1 of fastpath_parse_stack for the frontdoor workers
// (core/shm_ring.py): parse + validate a serialized GetRateLimitsReq into
// request COLUMNS written to caller-owned (shared-memory) buffers, with
// exactly the acceptance rules of the engine's native RPC lane — so a
// worker-parsed RPC never range-falls-back inside the engine, and a
// rejected one ships as RAW bytes instead.  Touches NO router state:
// workers run this without a Router* (they never see the engine's
// tables), and the engine re-stages the columns via router_pack_stack.
// key_bytes gets concat(name + '_' + unique_key) per item (client.go:33-35,
// the same assembled hash key router_pack_stack hashes); key_ends are
// cumulative exclusive offsets; name_lens keeps each item's name length so
// the engine's rare fallback lane can split the assembled key back into
// (name, unique_key) exactly — COLS records then never need the original
// bytes appended.
// Returns the request count n >= 0, or:
//   -1  malformed protobuf
//   -2  a request needs the full path (behavior/algorithm/validation/range)
//   -3  more than max_items requests
//   -4  concatenated keys exceed key_cap bytes
int64_t frontdoor_parse_req(const uint8_t* buf, int64_t len,
                            int64_t max_items, int64_t key_cap,
                            uint8_t* key_bytes, int64_t* key_ends,
                            int64_t* hits, int64_t* limits,
                            int64_t* durations, int32_t* algos,
                            int32_t* name_lens) {
  const uint8_t* p = buf;
  const uint8_t* end = buf + len;
  int64_t n = 0;
  int64_t koff = 0;
  while (p < end) {
    uint64_t tag;
    if (!read_varint(&p, end, &tag)) return -1;
    if (tag != ((1u << 3) | 2)) {  // only field 1: repeated RateLimitReq
      int wt = (int)(tag & 7);
      if (wt == 0) {
        uint64_t dummy;
        if (!read_varint(&p, end, &dummy)) return -1;
      } else if (wt == 2) {
        uint64_t l;
        if (!read_varint(&p, end, &l) || l > (uint64_t)(end - p))
          return -1;
        p += l;
      } else {
        return -1;
      }
      continue;
    }
    uint64_t mlen;
    if (!read_varint(&p, end, &mlen) || mlen > (uint64_t)(end - p))
      return -1;
    if (n >= max_items) return -3;
    ParsedItem it;
    uint64_t behavior;
    if (!parse_item(p, p + mlen, &it, &behavior)) return -1;
    p += mlen;

    if (it.name_len == 0 || it.key_len == 0) return -2;
    if (behavior != 0) return -2;  // BATCHING only
    if (it.algo == 4) return -2;  // python path (lease book visibility)
    if (!compact_ranges_ok(it.hits, it.limit, it.duration, it.algo))
      return -2;

    int64_t kl = it.name_len + 1 + it.key_len;
    if (koff + kl > key_cap) return -4;
    memcpy(key_bytes + koff, it.name, it.name_len);
    key_bytes[koff + it.name_len] = '_';
    memcpy(key_bytes + koff + it.name_len + 1, it.key, it.key_len);
    koff += kl;
    key_ends[n] = koff;
    hits[n] = it.hits;
    limits[n] = it.limit;
    durations[n] = it.duration;
    algos[n] = (int32_t)it.algo;
    name_lens[n] = (int32_t)it.name_len;
    n++;
  }
  return n;
}

// Response-direction mirror of frontdoor_parse_req (core/shm_ring.py):
// encode DECISION COLUMNS (status, limit, remaining, reset_time, shed
// flag) into a serialized GetRateLimitsResp, in the worker's process —
// the engine's completion path ships columns over the completion-ring
// slab and never serializes protobuf for columnar records.  Stateless
// like the parse lane: no Router*, byte-compatible with the engine's
// fastpath_encode_w emit loop (proto3 zero-field omission) plus the
// metadata map entries of qos/admission.py's shed_response for flagged
// items.  flags[i] == 0 is a plain decision; 1..5 index SHED_REASONS
// (the code table mirrored in shm_ring.py SHED_REASON_CODES).
// Returns the byte length, or -1 if out_cap is too small, or -2 for an
// unknown shed code (caller falls back to the Python encoder).
static const char* SHED_REASONS[] = {
    "", "queue_full", "deadline", "breaker_open", "draining", "ring_full"};
constexpr int64_t N_SHED_REASONS = 6;

int64_t frontdoor_encode_resp(const int64_t* status, const int64_t* limit,
                              const int64_t* remaining, const int64_t* reset,
                              const int32_t* flags, int64_t n,
                              uint8_t* out, int64_t out_cap) {
  uint8_t* w = out;
  uint8_t* wend = out + out_cap;
  for (int64_t i = 0; i < n; i++) {
    int64_t st = status[i], li = limit[i], re = remaining[i], rs = reset[i];
    int32_t fl = flags ? flags[i] : 0;
    if (fl < 0 || fl >= N_SHED_REASONS) return -2;
    // RateLimitResp: status=1, limit=2, remaining=3, reset_time=4,
    // metadata=6 map<string,string> (proto3: zero-valued fields omitted)
    int body = 0;
    if (st) body += 1 + varint_size((uint64_t)st);
    if (li) body += 1 + varint_size((uint64_t)li);
    if (re) body += 1 + varint_size((uint64_t)re);
    if (rs) body += 1 + varint_size((uint64_t)rs);
    int64_t rl = 0;
    if (fl) {
      rl = (int64_t)strlen(SHED_REASONS[fl]);
      // entry "shed" -> "true": 0x32 len {0x0a 4 shed 0x12 4 true}
      // entry "shed_reason" -> reason: 0x32 len {0x0a 11 ... 0x12 rl ...}
      body += 14 + 1 + (int)varint_size((uint64_t)(15 + rl)) + 15 + (int)rl;
    }
    if (w + 1 + varint_size((uint64_t)body) + body > wend) return -1;
    *w++ = (1u << 3) | 2;  // GetRateLimitsResp.responses
    w = write_varint(w, (uint64_t)body);
    if (st) {
      *w++ = (1u << 3) | 0;
      w = write_varint(w, (uint64_t)st);
    }
    if (li) {
      *w++ = (2u << 3) | 0;
      w = write_varint(w, (uint64_t)li);
    }
    if (re) {
      *w++ = (3u << 3) | 0;
      w = write_varint(w, (uint64_t)re);
    }
    if (rs) {
      *w++ = (4u << 3) | 0;
      w = write_varint(w, (uint64_t)rs);
    }
    if (fl) {
      *w++ = (6u << 3) | 2;  // metadata["shed"] = "true"
      *w++ = 12;
      *w++ = (1u << 3) | 2;
      *w++ = 4;
      memcpy(w, "shed", 4);
      w += 4;
      *w++ = (2u << 3) | 2;
      *w++ = 4;
      memcpy(w, "true", 4);
      w += 4;
      *w++ = (6u << 3) | 2;  // metadata["shed_reason"] = reason
      w = write_varint(w, (uint64_t)(15 + rl));
      *w++ = (1u << 3) | 2;
      *w++ = 11;
      memcpy(w, "shed_reason", 11);
      w += 11;
      *w++ = (2u << 3) | 2;
      *w++ = (uint8_t)rl;
      memcpy(w, SHED_REASONS[fl], (size_t)rl);
      w += rl;
    }
  }
  return w - out;
}

// Columnar-input sibling of fastpath_parse_stack for already-parsed request
// lists (the batcher's Python-side jobs).  Same drain protocol, same
// monotonic spill, same no-side-effects-on-fallback guarantee.
// Returns n >= 0, or -2 (a value outside the compact ranges: caller routes
// the job through the full-format path), -3 (too many items), -5 (a key
// routed to a shard this process does not own), -6 (stack full).
int64_t router_pack_stack(Router* r, const uint8_t* key_bytes,
                          const int64_t* key_ends, int64_t n,
                          const int64_t* hits, const int64_t* limits,
                          const int64_t* durations, const int32_t* algos,
                          int64_t now, int32_t lanes, int32_t K,
                          int64_t* packed, int32_t* kcur,
                          int32_t* shard_fill, int32_t* out_row,
                          int32_t* out_lane, int32_t* out_pos) {
  int32_t S = r->num_shards;
  if (S > MAX_STACK_SHARDS) return -2;
  if (n > MAX_STACK_ITEMS) return -3;
  static thread_local uint64_t fps[MAX_STACK_ITEMS];
  static thread_local int32_t shards[MAX_STACK_ITEMS];
  static thread_local uint8_t bump2[MAX_STACK_ITEMS];

  for (int64_t i = 0; i < n; i++) {
    if (!compact_ranges_ok(hits[i], limits[i], durations[i], algos[i]))
      return -2;
    int64_t beg = i == 0 ? 0 : key_ends[i - 1];
    int64_t len = key_ends[i] - beg;
    const uint8_t* key = key_bytes + beg;
    int32_t shard = (int32_t)(crc32(key, len) %
                              (uint32_t)r->num_global_shards) -
                    r->shard_offset;
    if (shard < 0 || shard >= S) return -5;
    shards[i] = shard;
    fps[i] = fnv1a64(key, len);
    bump2[i] = (uint8_t)rep_track(r, shard, fps[i], hits[i], limits[i],
                                  durations[i], algos[i]);
  }
  if (!stack_fits_exact(shards, bump2, n, kcur, shard_fill, S, lanes, K))
    return -6;

  for (int64_t i = 0; i < n; i++) {
    int64_t beg = i == 0 ? 0 : key_ends[i - 1];
    stage_lane(r, shards[i], fps[i], key_bytes + beg, key_ends[i] - beg,
               now, hits[i], limits[i], durations[i], (uint32_t)algos[i],
               lanes, K, packed, kcur, shard_fill, out_row, out_lane,
               out_pos, i, bump2[i]);
  }
  return n;
}

// Encode the fetched response-word plane (w0 = i64[K*S, lanes], the packed
// status/remaining/reset word — see ops/kernel.py encode_output_word) as a
// serialized GetRateLimitsResp for the n requests at
// (out_row[i], out_lane[i]).  The response's `limit` field echoes the
// REQUEST limit (item_limit, captured at parse time) — stored-vs-request
// limit mismatches are rare (a config change on a live bucket), so the
// device ships the full limit plane only when its per-window mismatch flag
// fires, and `climit` is non-null only then.
// Returns the byte length, or -1 if out_cap is too small.

// Decode one response word for item i: aggregated/synthesizable items
// (out_pos[i] >= 0: bits 0..29 the item's 0-based position in its run,
// bit 30 the algorithm) synthesize from r_start; plain items read the
// word directly.  See AGG_W0_BIT / ops/kernel.py transition(agg=...).
inline void decode_word_item(int64_t word, int64_t now, int32_t posinfo,
                             int64_t* status, int64_t* remaining,
                             int64_t* reset) {
  int64_t enc = (word >> 32) & 0xFFFFFFFFll;
  if (posinfo >= 0) {
    int64_t pos = posinfo & 0x3FFFFFFF;
    int32_t algo = (posinfo >> 30) & 1;
    int64_t r_start = word & 0x7FFFFFFFll;
    bool under = pos < r_start;
    *status = under ? 0 : 1;
    *remaining = under ? r_start - pos - 1 : 0;
    *reset = (enc == 0 || (algo == 1 && under)) ? 0 : now + enc - 1;
  } else {
    *status = (word >> 31) & 1;
    *remaining = word & 0x7FFFFFFFll;
    *reset = enc == 0 ? 0 : now + enc - 1;
  }
}

int64_t fastpath_encode_w(const int64_t* w0, const int64_t* item_limit,
                          int64_t now, int32_t lanes, int64_t n,
                          const int32_t* out_row, const int32_t* out_lane,
                          const int32_t* out_pos,
                          const int64_t* climit, uint8_t* out,
                          int64_t out_cap) {
  uint8_t* w = out;
  uint8_t* wend = out + out_cap;
  for (int64_t i = 0; i < n; i++) {
    int64_t o = (int64_t)out_row[i] * lanes + out_lane[i];
    int64_t word = w0[o];
    int64_t limit = climit ? climit[o] : item_limit[i];
    int64_t status, remaining, reset;
    decode_word_item(word, now, out_pos ? out_pos[i] : -1,
                     &status, &remaining, &reset);

    // RateLimitResp: status=1, limit=2, remaining=3, reset_time=4
    // (proto3: zero-valued fields are omitted)
    int body = 0;
    if (status) body += 1 + varint_size((uint64_t)status);
    if (limit) body += 1 + varint_size((uint64_t)limit);
    if (remaining) body += 1 + varint_size((uint64_t)remaining);
    if (reset) body += 1 + varint_size((uint64_t)reset);
    if (w + 1 + varint_size((uint64_t)body) + body > wend) return -1;
    *w++ = (1u << 3) | 2;  // GetRateLimitsResp.responses
    w = write_varint(w, (uint64_t)body);
    if (status) {
      *w++ = (1u << 3) | 0;
      w = write_varint(w, (uint64_t)status);
    }
    if (limit) {
      *w++ = (2u << 3) | 0;
      w = write_varint(w, (uint64_t)limit);
    }
    if (remaining) {
      *w++ = (3u << 3) | 0;
      w = write_varint(w, (uint64_t)remaining);
    }
    if (reset) {
      *w++ = (4u << 3) | 0;
      w = write_varint(w, (uint64_t)reset);
    }
  }
  return w - out;
}

// Encode the fetched response-word plane as PER-ITEM FRAMED segments —
// each local item becomes `0x0a + varint(len) + RateLimitResp body` at
// out[item_off[i] .. +item_len[i]] (the framing of one repeated-field
// entry, identical in GetRateLimitsResp and GetPeerRateLimitsResp).
// Forwarded items (rows[i] < 0) get item_len[i] == 0; the host splices the
// peer's framed response bytes there instead.  Returns total bytes
// written, or -1 if out_cap is too small.
int64_t fastpath_encode_parts(const int64_t* w0, const int64_t* item_limit,
                              int64_t now, int32_t lanes, int64_t n,
                              const int32_t* rows, const int32_t* lanes_arr,
                              const int32_t* out_pos,
                              const int64_t* climit, uint8_t* out,
                              int64_t out_cap, int64_t* item_off,
                              int32_t* item_len) {
  uint8_t* w = out;
  uint8_t* wend = out + out_cap;
  for (int64_t i = 0; i < n; i++) {
    if (rows[i] < 0) {
      item_off[i] = w - out;
      item_len[i] = 0;
      continue;
    }
    int64_t o = (int64_t)rows[i] * lanes + lanes_arr[i];
    int64_t word = w0[o];
    int64_t limit = climit ? climit[o] : item_limit[i];
    int64_t status, remaining, reset;
    decode_word_item(word, now, out_pos ? out_pos[i] : -1,
                     &status, &remaining, &reset);

    int body = 0;
    if (status) body += 1 + varint_size((uint64_t)status);
    if (limit) body += 1 + varint_size((uint64_t)limit);
    if (remaining) body += 1 + varint_size((uint64_t)remaining);
    if (reset) body += 1 + varint_size((uint64_t)reset);
    if (w + 1 + varint_size((uint64_t)body) + body > wend) return -1;
    uint8_t* seg = w;
    *w++ = (1u << 3) | 2;
    w = write_varint(w, (uint64_t)body);
    if (status) {
      *w++ = (1u << 3) | 0;
      w = write_varint(w, (uint64_t)status);
    }
    if (limit) {
      *w++ = (2u << 3) | 0;
      w = write_varint(w, (uint64_t)limit);
    }
    if (remaining) {
      *w++ = (3u << 3) | 0;
      w = write_varint(w, (uint64_t)remaining);
    }
    if (reset) {
      *w++ = (4u << 3) | 0;
      w = write_varint(w, (uint64_t)reset);
    }
    item_off[i] = seg - out;
    item_len[i] = (int32_t)(w - seg);
  }
  return w - out;
}

// total expiry-heap nodes (live + draining) for one shard — test/debug
// observability for the bounded-heap guarantees above
int64_t router_heap_size(Router* r, int32_t shard) {
  Shard* s = &r->shards[shard];
  return s->heap_len + s->heap_old_len;
}

int64_t router_size(Router* r) {
  int64_t total = 0;
  for (int32_t i = 0; i < r->num_shards; i++) total += r->shards[i].size;
  return total;
}

int64_t router_hits(Router* r) {
  int64_t total = 0;
  for (int32_t i = 0; i < r->num_shards; i++) total += r->shards[i].hits;
  return total;
}

int64_t router_misses(Router* r) {
  int64_t total = 0;
  for (int32_t i = 0; i < r->num_shards; i++) total += r->shards[i].misses;
  return total;
}

// ---- state lifecycle (gubernator_tpu/state/snapshot.py) -------------------

// Export one local shard's resident, committed entries oldest-first (LRU
// tail -> head): fingerprint, device slot (entry index IS the slot), and
// host expiry estimate.  Output buffers must hold `capacity` items.
// Pending entries are skipped — their device rows were never written, so a
// snapshot of them would resurrect the slot's previous tenant.
int64_t router_export_keys(Router* r, int32_t shard, uint64_t* out_fp,
                           int32_t* out_slot, int64_t* out_expire) {
  Shard* s = &r->shards[shard];
  int64_t n = 0;
  for (int32_t e = s->lru_tail; e != NIL; e = s->prev[e]) {
    if (s->pending[e]) continue;
    out_fp[n] = s->fp[e];
    out_slot[n] = e;
    out_expire[n] = s->expire[e];
    n++;
  }
  return n;
}

// Rebuild one local shard from router_export_keys output (oldest first).
// Each entry lands at its exported entry index — the index is the device
// slot the restored arena planes address.  Returns 0; -1 on an invalid or
// duplicate slot; -2 when the exact-key guard is on (key bytes are not
// part of the export, and fingerprint-only entries would make every
// exact-mode lookup probe past them forever).
int64_t router_import_keys(Router* r, int32_t shard, const uint64_t* fps,
                           const int32_t* slots, const int64_t* expires,
                           int64_t n) {
  Shard* s = &r->shards[shard];
  if (s->keys != nullptr) return -2;
  int32_t capacity = s->capacity;
  for (int64_t i = 0; i < n; i++)
    if (slots[i] < 0 || slots[i] >= capacity) return -1;
  for (uint32_t i = 0; i <= s->mask; i++) s->cells[i] = NIL;
  s->heap_len = 0;
  if (s->heap_old != nullptr) {
    free(s->heap_old);
    s->heap_old = nullptr;
    s->heap_old_len = 0;
  }
  s->lru_head = s->lru_tail = NIL;
  memset(s->pending, 0, (size_t)capacity);
  memset(s->seq, 0, (size_t)capacity * sizeof(uint32_t));
  uint8_t* used = (uint8_t*)calloc(capacity, 1);
  for (int64_t i = 0; i < n; i++) {
    int32_t e = slots[i];
    if (used[e]) {
      free(used);
      return -1;
    }
    used[e] = 1;
    uint32_t cell = (uint32_t)(fps[i] & s->mask);
    while (s->cells[cell] != NIL) cell = (cell + 1) & s->mask;
    s->cells[cell] = e;
    s->cell_of[e] = cell;
    s->fp[e] = fps[i];
    s->expire[e] = expires[i];
    lru_push_front(s, e);  // oldest-first input => head ends up MRU
    heap_push(s, expires[i], e);
  }
  // rebuild the free list so pops come back ascending, like shard_init
  s->free_top = 0;
  for (int32_t e = capacity - 1; e >= 0; e--)
    if (!used[e]) s->free_list[s->free_top++] = e;
  free(used);
  s->size = n;
  return 0;
}

// Occupancy by the host expiry estimate over all local shards: live and
// expired resident entries plus free slots (engine.cache_stats surface).
void router_occupancy(Router* r, int64_t now, int64_t* out_live,
                      int64_t* out_expired, int64_t* out_free) {
  int64_t live = 0, expired = 0, free_slots = 0;
  for (int32_t si = 0; si < r->num_shards; si++) {
    Shard* s = &r->shards[si];
    free_slots += s->capacity - s->size;
    for (int32_t e = s->lru_head; e != NIL; e = s->next[e]) {
      if (s->expire[e] >= now) live++;
      else expired++;
    }
  }
  *out_live = live;
  *out_expired = expired;
  *out_free = free_slots;
}

}  // extern "C"
