"""Host-level consistent-hash peer picker.

Inside one mesh the keyspace partitions by `crc32(key) % shards`
(core/engine.py); *across* hosts we keep a consistent-hash ring exactly
compatible with the reference (hash.go:28-96): crc32 IEEE of the peer
address, one point per host, sorted ring, binary-search successor with
wraparound — so a mixed cluster of reference nodes and gubernator-tpu nodes
routes every key to the same owner.
"""

from __future__ import annotations

import bisect
import zlib
from typing import Generic, List, Optional, TypeVar

T = TypeVar("T")


class ConsistentHashRing(Generic[T]):
    """PeerPicker (reference peers.go:26-33 / hash.go:28-96)."""

    def __init__(self):
        self._points: List[int] = []  # sorted hash points
        self._by_point = {}  # point -> peer
        self._by_host = {}  # host -> peer

    def new(self) -> "ConsistentHashRing[T]":
        return ConsistentHashRing()

    @staticmethod
    def _hash(data: str) -> int:
        return zlib.crc32(data.encode("utf-8"))

    def add(self, host: str, peer: T) -> None:
        point = self._hash(host)
        if point not in self._by_point:
            bisect.insort(self._points, point)
        self._by_point[point] = peer
        self._by_host[host] = peer

    def size(self) -> int:
        return len(self._points)

    def peers(self) -> List[T]:
        return list(self._by_host.values())

    def get_by_host(self, host: str) -> Optional[T]:
        return self._by_host.get(host)

    def get(self, key: str) -> T:
        """Owner peer for a hash key; raises if the ring is empty."""
        if not self._points:
            raise RuntimeError("unable to pick a peer; pool is empty")
        h = self._hash(key)
        idx = bisect.bisect_left(self._points, h)
        if idx == len(self._points):
            idx = 0  # wrap to the first point
        return self._by_point[self._points[idx]]

    def ring_table(self):
        """(sorted points, peer per point) — the native RPC parser's
        classification table (host_router.cc router_set_ring)."""
        return list(self._points), [self._by_point[p] for p in self._points]


class MeshShardPicker(Generic[T]):
    """Mesh-mode PeerPicker: key -> global shard -> owning process -> host.

    In mesh mode the keyspace partition is the mesh's shard axis, so host
    routing must agree with the engine's `crc32(key) % num_shards` exactly
    (a ring would route by host hash and disagree).  Hosts register in
    process-rank order via add(); get() then maps shard -> rank.
    """

    def __init__(self, shard_to_process: List[int], rank_hosts: List[str]):
        self._shard_to_process = shard_to_process  # global shard -> rank
        self._rank_hosts = rank_hosts  # rank -> host address (fixed at boot)
        self._by_host = {}

    @classmethod
    def for_mesh(cls, mesh, rank_hosts: List[str]) -> "MeshShardPicker[T]":
        shard_to_process = [int(d.process_index)
                            for d in mesh.devices.reshape(-1)]
        if max(shard_to_process) >= len(rank_hosts):
            raise ValueError(
                f"mesh spans {max(shard_to_process) + 1} processes but only "
                f"{len(rank_hosts)} peer addresses were given")
        return cls(shard_to_process, list(rank_hosts))

    def new(self) -> "MeshShardPicker[T]":
        return MeshShardPicker(self._shard_to_process, self._rank_hosts)

    def add(self, host: str, peer: T) -> None:
        if host not in self._rank_hosts:
            raise ValueError(
                f"host {host!r} is not in the mesh peer list {self._rank_hosts}")
        self._by_host[host] = peer

    def size(self) -> int:
        return len(self._by_host)

    def peers(self) -> List[T]:
        return list(self._by_host.values())

    def get_by_host(self, host: str) -> Optional[T]:
        return self._by_host.get(host)

    def get(self, key: str) -> T:
        """Rank-exact routing: a missing (e.g. connect-failed) peer raises
        rather than shifting other ranks' shards onto the wrong host."""
        if not self._by_host:
            raise RuntimeError("unable to pick a peer; pool is empty")
        shard = zlib.crc32(key.encode("utf-8")) % len(self._shard_to_process)
        host = self._rank_hosts[self._shard_to_process[shard]]
        peer = self._by_host.get(host)
        if peer is None:
            raise RuntimeError(
                f"mesh peer {host} (owner of shard {shard}) is not connected")
        return peer
