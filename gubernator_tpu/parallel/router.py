"""Host-level consistent-hash peer picker.

Inside one mesh the keyspace partitions by `crc32(key) % shards`
(core/engine.py); *across* hosts we keep a consistent-hash ring exactly
compatible with the reference (hash.go:28-96): crc32 IEEE of the peer
address, one point per host, sorted ring, binary-search successor with
wraparound — so a mixed cluster of reference nodes and gubernator-tpu nodes
routes every key to the same owner.
"""

from __future__ import annotations

import bisect
import zlib
from typing import Generic, List, Optional, TypeVar

T = TypeVar("T")


class ConsistentHashRing(Generic[T]):
    """PeerPicker (reference peers.go:26-33 / hash.go:28-96)."""

    def __init__(self):
        self._points: List[int] = []  # sorted hash points
        self._by_point = {}  # point -> peer
        self._by_host = {}  # host -> peer

    def new(self) -> "ConsistentHashRing[T]":
        return ConsistentHashRing()

    @staticmethod
    def _hash(data: str) -> int:
        return zlib.crc32(data.encode("utf-8"))

    def add(self, host: str, peer: T) -> None:
        point = self._hash(host)
        if point not in self._by_point:
            bisect.insort(self._points, point)
        self._by_point[point] = peer
        self._by_host[host] = peer

    def size(self) -> int:
        return len(self._points)

    def peers(self) -> List[T]:
        return list(self._by_host.values())

    def get_by_host(self, host: str) -> Optional[T]:
        return self._by_host.get(host)

    def get(self, key: str) -> T:
        """Owner peer for a hash key; raises if the ring is empty."""
        if not self._points:
            raise RuntimeError("unable to pick a peer; pool is empty")
        h = self._hash(key)
        idx = bisect.bisect_left(self._points, h)
        if idx == len(self._points):
            idx = 0  # wrap to the first point
        return self._by_point[self._points[idx]]
