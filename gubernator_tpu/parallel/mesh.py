"""Device mesh construction for keyspace sharding.

The reference partitions its keyspace over a consistent-hash ring of Go
processes (hash.go:28-96); here the partition axis is a 1D `jax.sharding.Mesh`
named "shard" — one shard per chip, state placed with NamedSharding so the
per-shard blocks live in each chip's HBM and the GLOBAL reconciliation rides
ICI collectives instead of gRPC.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

SHARD_AXIS = "shard"


def make_mesh(devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """A 1D mesh over the given (default: all) devices, axis name "shard"."""
    if devices is None:
        devices = jax.devices()
    return Mesh(np.asarray(devices), (SHARD_AXIS,))
