"""Device mesh construction for keyspace sharding.

The reference partitions its keyspace over a consistent-hash ring of Go
processes (hash.go:28-96); here the partition axis is a 1D `jax.sharding.Mesh`
named "shard" — one shard per chip, state placed with NamedSharding so the
per-shard blocks live in each chip's HBM and the GLOBAL reconciliation rides
ICI collectives instead of gRPC.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

SHARD_AXIS = "shard"


def make_mesh(devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """A 1D mesh over the given (default: all) devices, axis name "shard"."""
    if devices is None:
        devices = jax.devices()
    return Mesh(np.asarray(devices), (SHARD_AXIS,))


# Canonical PartitionSpecs for the engine's arrays (single source of truth
# for the executables' shard_map specs — core/engine.py):
#   shard_spec      [S, ...] per-shard blocks (bucket arena, window lanes)
#   stacked_spec    [K, S, ...] pipeline-drain stacks (leading window axis
#                   replicated, shard axis second — the plane arena's
#                   stacked wire layout)
#   replicated_spec GLOBAL arena / control-plane inputs (identical on
#                   every shard; mutated only through the psum)
def shard_spec() -> P:
    return P(SHARD_AXIS)


def stacked_spec() -> P:
    return P(None, SHARD_AXIS)


def replicated_spec() -> P:
    return P()
