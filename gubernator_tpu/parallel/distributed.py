"""Multi-host mesh mode: one SPMD device mesh spanning daemon processes.

The reference scales out as N independent nodes exchanging gRPC
(peers.go:130-172).  This framework supports that same topology ("node
mode": every daemon owns its chips and its slice of the keyspace, peer plane
over gRPC — see net/peers.py), and additionally a TPU-native topology this
module enables:

  MESH MODE — all hosts join one `jax.sharding.Mesh` via
  `jax.distributed.initialize`; the bucket arena is one global array sharded
  over every chip of every host; each host packs request lanes for its local
  shards and all hosts dispatch the SAME compiled window step in lockstep.
  Cross-shard traffic inside the mesh needs no RPCs at all, and the GLOBAL
  reconciliation psum rides ICI within a slice / DCN across slices — the
  collective replaces the reference's async-hits + broadcast gRPC dance
  entirely (global.go:72-232).

Lockstep is a hard requirement: every process must issue the same sequence
of engine dispatches (the collectives inside the step otherwise deadlock).
The serving layer guarantees this by flushing windows on a fixed clock
(tick even when empty) rather than on demand.

Env surface (daemon wiring):
  GUBER_MESH_COORDINATOR   host:port of process 0 (enables mesh mode)
  GUBER_MESH_NUM_PROCESSES total process count
  GUBER_MESH_PROCESS_ID    this process's rank
"""

from __future__ import annotations

import os
from typing import Optional

import jax
import numpy as np

from gubernator_tpu.parallel.mesh import SHARD_AXIS, make_mesh


def initialize_from_env() -> bool:
    """Join the distributed runtime if GUBER_MESH_COORDINATOR is set.

    Returns True when mesh mode is active.  Must run before any other JAX
    call in the process (jax.distributed.initialize constraint)."""
    coord = os.environ.get("GUBER_MESH_COORDINATOR", "")
    if not coord:
        return False
    jax.distributed.initialize(
        coordinator_address=coord,
        num_processes=int(os.environ["GUBER_MESH_NUM_PROCESSES"]),
        process_id=int(os.environ["GUBER_MESH_PROCESS_ID"]),
    )
    return True


def global_mesh():
    """The mesh over every device of every process (shard axis)."""
    return make_mesh(jax.devices())


def local_device_indices(mesh) -> list[int]:
    """Flat mesh-device indices owned by this process (its shard ids)."""
    devs = mesh.devices.reshape(-1)
    return [i for i, d in enumerate(devs)
            if d.process_index == jax.process_index()]


def owning_process(shard: int, mesh) -> int:
    """Which process owns a global shard index (for host-side routing)."""
    return int(mesh.devices.reshape(-1)[shard].process_index)
