"""Multi-host mesh mode: one SPMD device mesh spanning daemon processes.

The reference scales out as N independent nodes exchanging gRPC
(peers.go:130-172).  This framework supports that same topology ("node
mode": every daemon owns its chips and its slice of the keyspace, peer plane
over gRPC — see net/peers.py), and additionally a TPU-native topology this
module enables:

  MESH MODE — all hosts join one `jax.sharding.Mesh` via
  `jax.distributed.initialize`; the bucket arena is one global array sharded
  over every chip of every host; each host packs request lanes for its local
  shards and all hosts dispatch the SAME compiled window step in lockstep.
  Cross-shard traffic inside the mesh needs no RPCs at all, and the GLOBAL
  reconciliation psum rides ICI within a slice / DCN across slices — the
  collective replaces the reference's async-hits + broadcast gRPC dance
  entirely (global.go:72-232).

Lockstep is a hard requirement: every process must issue the same sequence
of engine dispatches (the collectives inside the step otherwise deadlock).
The serving layer guarantees this by flushing windows on a fixed clock
(tick even when empty) rather than on demand.

Env surface (daemon wiring):
  GUBER_MESH_COORDINATOR   host:port of process 0 (enables mesh mode)
  GUBER_MESH_NUM_PROCESSES total process count
  GUBER_MESH_PROCESS_ID    this process's rank
"""

from __future__ import annotations

import os
from functools import lru_cache as _functools_lru_cache

import jax
import numpy as np

from gubernator_tpu.parallel.mesh import (SHARD_AXIS, make_mesh, shard_spec,
                                          stacked_spec)


def initialize_from_env() -> bool:
    """Join the distributed runtime if GUBER_MESH_COORDINATOR is set.

    Returns True when mesh mode is active.  Must run before any other JAX
    call in the process (jax.distributed.initialize constraint)."""
    coord = os.environ.get("GUBER_MESH_COORDINATOR", "")
    if not coord:
        return False
    jax.distributed.initialize(
        coordinator_address=coord,
        num_processes=int(os.environ["GUBER_MESH_NUM_PROCESSES"]),
        process_id=int(os.environ["GUBER_MESH_PROCESS_ID"]),
    )
    return True


def global_mesh():
    """The mesh over every device of every process (shard axis)."""
    return make_mesh(jax.devices())


@_functools_lru_cache(maxsize=None)
def shard_sharding(mesh):
    """NamedSharding for [S, ...] per-shard arrays (cached per mesh).

    Staging rebuilds the same placement for every dispatch; meshes are
    long-lived and hashable, so cache the NamedSharding objects instead of
    re-deriving them on the hot path."""
    from jax.sharding import NamedSharding
    return NamedSharding(mesh, shard_spec())


@_functools_lru_cache(maxsize=None)
def stacked_sharding(mesh):
    """NamedSharding for [K, S, ...] drain stacks (cached per mesh)."""
    from jax.sharding import NamedSharding
    return NamedSharding(mesh, stacked_spec())


def local_device_indices(mesh) -> list[int]:
    """Flat mesh-device indices owned by this process (its shard ids)."""
    devs = mesh.devices.reshape(-1)
    return [i for i, d in enumerate(devs)
            if d.process_index == jax.process_index()]


def owning_process(shard: int, mesh) -> int:
    """Which process owns a global shard index (for host-side routing)."""
    return int(mesh.devices.reshape(-1)[shard].process_index)


def agree_epoch_ms(mesh) -> int:
    """Every process learns process 0's wall clock via one tiny collective.

    The lockstep window clock derives each tick's timestamp from this agreed
    epoch, because the window `now` is a replicated step input that must be
    bit-identical on every process (engine._resolve_now)."""
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from gubernator_tpu.api.types import millisecond_now

    local = np.full(
        (len(local_device_indices(mesh)),),
        millisecond_now() if jax.process_index() == 0 else 0,
        np.int64,
    )
    sh = NamedSharding(mesh, P(SHARD_AXIS))
    gv = jax.make_array_from_process_local_data(sh, local,
                                                (mesh.devices.size,))

    def fn(v):
        first = lax.axis_index(SHARD_AXIS) == 0
        return lax.psum(jnp.where(first, v[0], jnp.int64(0)), SHARD_AXIS)[None]

    from gubernator_tpu.compat import shard_map
    out = jax.jit(shard_map(fn, mesh=mesh, in_specs=P(SHARD_AXIS),
                            out_specs=P(SHARD_AXIS)))(gv)
    return int(np.asarray(out.addressable_shards[0].data)[0])


class LockstepClock:
    """Deterministic per-tick timestamps shared by every mesh process.

    Tick i's window timestamp is epoch + i*interval — identical everywhere
    by construction.  Hosts pace ticks with their local clocks; the
    collectives inside each window act as the rendezvous, so skew shows up
    as backpressure, never as divergent state."""

    def __init__(self, epoch_ms: int, interval_s: float):
        self.epoch_ms = epoch_ms
        self.interval_s = interval_s
        self.tick = 0

    def next_now(self) -> int:
        # rounded per tick from the exact float interval, so logical time
        # never drifts from wall time even for sub-millisecond ticks
        now = self.epoch_ms + round(self.tick * self.interval_s * 1000)
        self.tick += 1
        return now
