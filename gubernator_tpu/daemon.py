"""Server daemon: the composition root.

Equivalent of the reference's cmd/gubernator/main.go:40-140: env config,
device engine (in place of the LRU cache), gRPC server, discovery pool
(k8s | etcd | static), HTTP gateway with /metrics, SIGINT/SIGTERM graceful
shutdown.  Run as `python -m gubernator_tpu.daemon` (flags: --config
<env-file>, --debug — the reference's only two flags,
cmd/gubernator/config.go:63-66).
"""

from __future__ import annotations

import argparse
import asyncio
import logging
import signal
from typing import Optional

from gubernator_tpu.config import (
    BehaviorConfig,
    Config,
    DaemonConfig,
    config_from_env,
)
from gubernator_tpu.api.http_gateway import HttpGateway
from gubernator_tpu.core.service import Instance
from gubernator_tpu.server import GrpcServer

log = logging.getLogger("gubernator.daemon")


def apply_platform_env() -> None:
    """Honor GUBER_JAX_PLATFORM (e.g. 'cpu', 'tpu') before first device use.

    Needed because ambient JAX_PLATFORMS may be pinned by site config; this
    routes through jax.config which wins over the environment."""
    import os
    platform = os.environ.get("GUBER_JAX_PLATFORM")
    if platform:
        import jax
        jax.config.update("jax_platforms", platform)


class Daemon:
    def __init__(self, conf: DaemonConfig):
        self.conf = conf
        self.instance: Optional[Instance] = None
        self.grpc: Optional[GrpcServer] = None
        self.frontdoor = None  # FrontdoorHub when GUBER_FRONTDOOR_WORKERS > 0
        self.http: Optional[HttpGateway] = None
        self.pool = None
        self.monitor = None  # net/health.py HeartbeatMonitor (static pools)
        self._snapshot_task: Optional[asyncio.Task] = None
        self._lease_sweep_task: Optional[asyncio.Task] = None
        # phase names appended as stop() executes them, in order — the
        # shutdown-ordering contract the signal-path tests assert
        self.shutdown_phases: list = []

    def _snapshot_file(self) -> str:
        from gubernator_tpu.state.snapshot import snapshot_path
        eng = self.instance.engine
        return snapshot_path(self.conf.snapshot_dir,
                             local_shard_offset=eng.local_shard_offset,
                             multiprocess=eng.multiprocess)

    async def _snapshot_once(self) -> None:
        try:
            await self.instance.save_snapshot(self._snapshot_file())
        except Exception:
            self.instance.metrics.observe_snapshot(0.0, 0, ok=False)
            log.exception("periodic snapshot failed")

    async def _snapshot_loop(self) -> None:
        interval = self.conf.snapshot_interval_ms / 1000.0
        while True:
            await asyncio.sleep(interval)
            await self._snapshot_once()

    async def _lease_sweep_loop(self, interval_ms: int) -> None:
        """Periodically drop expired grants from the concurrency-lease
        book (GUBER_LEASE_SWEEP_MS).  The device buckets already expired,
        so this only keeps the lease gauges and per-client holds honest."""
        from gubernator_tpu.api.types import millisecond_now
        while True:
            await asyncio.sleep(interval_ms / 1000.0)
            try:
                dropped = self.instance.leases.sweep(millisecond_now())
                if dropped:
                    self.instance.metrics.observe_lease_release(
                        "expired", sum(c for _, _, c in dropped))
            except Exception:
                log.exception("lease sweep failed")

    async def start(self) -> None:
        c = self.conf
        apply_platform_env()

        # Mesh mode: join the jax.distributed runtime BEFORE any device use;
        # the arena then shards over every process's chips and all hosts
        # dispatch windows on the lockstep clock (parallel/distributed.py).
        import os
        from gubernator_tpu.parallel.distributed import initialize_from_env
        mesh = None
        mesh_peers = None
        if initialize_from_env():
            from gubernator_tpu.parallel.distributed import global_mesh
            mesh = global_mesh()
            peers_env = os.environ.get("GUBER_MESH_PEERS", "")
            mesh_peers = [a.strip() for a in peers_env.split(",") if a.strip()]
            if not mesh_peers:
                raise ValueError(
                    "mesh mode requires GUBER_MESH_PEERS (gRPC addresses in "
                    "process-rank order)")
            import jax
            if len(mesh_peers) != jax.process_count():
                raise ValueError(
                    f"GUBER_MESH_PEERS lists {len(mesh_peers)} addresses but "
                    f"the mesh has {jax.process_count()} processes — the "
                    "list must name every process, in rank order")
            log.info("mesh mode: %d processes, %d global shards",
                     len(mesh_peers), mesh.devices.size)

        # deterministic fault injection (net/faults.py): GUBER_FAULTS is
        # read ONCE here — a production boot without it pays one attribute
        # check per seam crossing
        from gubernator_tpu.net.faults import FAULTS
        FAULTS.load_from_env()

        self.instance = Instance(Config(
            behaviors=c.behaviors,
            engine=c.engine,
            advertise_address=c.advertise_address,
            qos=c.qos,
            health=c.health,
        ), mesh=mesh, mesh_peers=mesh_peers)
        # compile the device step before accepting traffic; mesh mode needs a
        # cluster-agreed timestamp (all processes warm up in lockstep)
        if mesh_peers is not None:
            eng = self.instance.engine
            eng.warmup(now=self.instance.batcher.clock.epoch_ms,
                       k_stack=c.behaviors.lockstep_stack)
            gk_file = os.environ.get("GUBER_GLOBAL_KEYS_FILE", "")
            if gk_file:
                import json
                with open(gk_file) as f:
                    specs = [(d["key"], d["limit"], d["duration"],
                              d.get("algorithm", 0))
                             for d in (json.loads(ln) for ln in f
                                       if ln.strip())]
                eng.register_global_keys(
                    specs, now=self.instance.batcher.clock.epoch_ms)
                log.info("registered %d GLOBAL keys", len(specs))
        else:
            self.instance.engine.warmup()

        # State lifecycle: restore the arena BEFORE serving (a corrupt or
        # missing snapshot degrades to a cold start, never a failed boot),
        # then re-snapshot periodically and once on clean shutdown.  In
        # mesh mode every process restores its own shard blocks from the
        # shared directory at the same pre-lockstep point.
        if c.snapshot_dir:
            import os as _os
            _os.makedirs(c.snapshot_dir, exist_ok=True)
            from gubernator_tpu.state.snapshot import restore_engine
            loop = asyncio.get_running_loop()
            snap = await loop.run_in_executor(
                self.instance.batcher._executor,
                lambda: restore_engine(self.instance.engine,
                                       self._snapshot_file(),
                                       metrics=self.instance.metrics))
            if snap is not None and getattr(snap, "leases", None):
                # re-register restored concurrency leases (the device
                # free-slot counters came back with the arena planes)
                self.instance.leases.import_rows(snap.leases)
            self._snapshot_task = asyncio.create_task(self._snapshot_loop())
            log.info("snapshots -> %s every %dms", c.snapshot_dir,
                     c.snapshot_interval_ms)

        sweep_ms = getattr(getattr(c, "leases", None),
                           "sweep_interval_ms", 0)
        if sweep_ms > 0:
            self._lease_sweep_task = asyncio.create_task(
                self._lease_sweep_loop(sweep_ms))

        if c.frontdoor_workers > 0 and mesh_peers is None:
            # multi-process front door (frontdoor.py): N acceptor worker
            # processes share the gRPC port via SO_REUSEPORT and hand
            # records to this engine over shm rings; this process binds
            # no public gRPC port of its own.  Mesh mode keeps the
            # classic in-process server: lockstep ticks own the loop.
            from gubernator_tpu.frontdoor import FrontdoorHub
            self.frontdoor = FrontdoorHub(
                self.instance, workers=c.frontdoor_workers,
                ring_slots=c.shm_ring_slots, slab_bytes=c.shm_slab_bytes,
                listen_address=c.grpc_listen_address,
                encode=c.frontdoor_encode,
                batch_reads=c.frontdoor_batch_reads)
            await self.frontdoor.start()
            # surfaced in /v1/admin/debug + metrics like any subsystem
            self.instance.frontdoor = self.frontdoor
            self.instance.metrics.watch_frontdoor(self.frontdoor)
            log.info("frontdoor: %d workers on %s (engine pid %d)",
                     c.frontdoor_workers, self.frontdoor.address,
                     os.getpid())
        else:
            if c.frontdoor_workers > 0:
                log.warning("GUBER_FRONTDOOR_WORKERS ignored in mesh mode")
            self.grpc = GrpcServer(self.instance, c.grpc_listen_address)
            await self.grpc.start()
            log.info("gRPC listening on %s", self.grpc.address)

        # Kernel-ladder scoreboard: publish guber_tpu_kernels_per_window
        # at boot so operators see the ladder height without running
        # bench.  Tracing the census arms costs seconds, so it runs off
        # the serving path on a daemon thread — and only here, in the
        # long-running daemon: embedded instances (in-process clusters,
        # tests) leave the gauge to the admin kernels endpoint.
        import threading
        threading.Thread(target=self.instance._publish_census,
                         name="guber-census", daemon=True).start()

        static_peers = os.environ.get("GUBER_STATIC_PEERS", "")
        if mesh_peers is not None:
            # mesh membership is fixed by process rank; discovery backends
            # don't apply (elasticity = re-forming the mesh)
            from gubernator_tpu.discovery.static import StaticPool
            self.pool = StaticPool(
                addresses=mesh_peers,
                advertise_address=c.advertise_address,
                on_update=self.instance.set_peers,
            )
            await self.pool.start()
            self.instance.batcher.start_lockstep()
        elif c.k8s_enabled:
            from gubernator_tpu.discovery.kubernetes import K8sPool
            self.pool = K8sPool(
                namespace=c.k8s_namespace,
                pod_ip=c.k8s_pod_ip,
                pod_port=c.k8s_pod_port,
                selector=c.k8s_endpoints_selector,
                on_update=self.instance.set_peers,
            )
            await self.pool.start()
        elif c.etcd_enabled:
            from gubernator_tpu.discovery.etcd import EtcdPool
            self.pool = EtcdPool(
                endpoints=c.etcd_addresses,
                advertise_address=c.advertise_address,
                on_update=self.instance.set_peers,
                prefix=c.etcd_prefix,
                username=c.etcd_username,
                password=c.etcd_password,
                ssl_context=c.etcd_ssl_context(),
            )
            await self.pool.start()
        elif static_peers:
            from gubernator_tpu.discovery.static import StaticPool
            addresses = [a.strip() for a in static_peers.split(",")
                         if a.strip()]
            self.pool = StaticPool(
                addresses=addresses,
                advertise_address=c.advertise_address,
                on_update=self.instance.set_peers,
            )
            await self.pool.start()
            # Static pools have no discovery backend to remove dead peers —
            # the heartbeat failure detector is their self-healing layer
            # (k8s/etcd pools already watch membership; mesh membership is
            # fixed by process rank).
            if c.health.heartbeat_enabled:
                from gubernator_tpu.net.health import HeartbeatMonitor
                self.monitor = HeartbeatMonitor(
                    self.instance, addresses, conf=c.health)
                self.instance.monitor = self.monitor
                self.monitor.start()
                log.info("heartbeat detector on %d peers (interval %.1fs, "
                         "down after %d misses)", len(addresses) - 1,
                         c.health.heartbeat_interval, c.health.suspect_after)

        self.http = HttpGateway(self.instance, c.http_listen_address)
        await self.http.start()
        log.info("HTTP gateway listening on %s", c.http_listen_address)

    async def stop(self) -> None:
        """Graceful departure, in phases (each bounded, none skippable by
        a failure in the previous one):

          1. stop the failure detector (it must not react to our own
             departure);
          2. drain — close admission intake (new work sheds in-band with
             reason `draining`) and wait out already-admitted decisions;
          3. flush the GlobalManager (queued aggregated hits/updates ship
             now instead of being dropped by stop());
          4. handoff — when a surviving ring remains, ship every key this
             node owns to the survivors (skipped entirely when this node
             is the whole ring: a handoff with no destination must not
             hang the shutdown);
          5. final snapshot (AFTER handoff: the snapshot then records the
             post-departure state, so a restart doesn't resurrect keys
             the survivors now own);
          6. teardown: discovery, http, grpc, instance
             (main.go:127-139 order).
        """
        await self._stop_monitor()
        await self._drain_requests()
        await self._flush_globals()
        await self._handoff_keys()
        await self._final_snapshot()
        await self._teardown()

    def _phase(self, name: str) -> None:
        self.shutdown_phases.append(name)

    async def _stop_monitor(self) -> None:
        self._phase("monitor_stop")
        if self.monitor is not None:
            try:
                await self.monitor.stop()
            except Exception:
                log.exception("stopping heartbeat monitor failed")

    async def _drain_requests(self) -> None:
        self._phase("drain")
        if self.frontdoor is not None:
            # workers shed new work in-band (reason `draining`) without a
            # ring round-trip from here on
            self.frontdoor.set_draining()
        if self.instance is None:
            return
        try:
            await self.instance.drain(self.conf.health.drain_timeout)
        except Exception:
            log.exception("drain failed; continuing shutdown")

    async def _flush_globals(self) -> None:
        self._phase("global_flush")
        if self.instance is None:
            return
        try:
            await asyncio.wait_for(self.instance.global_mgr.flush(),
                                   self.conf.health.drain_timeout)
        except Exception:
            log.exception("global flush failed; continuing shutdown")

    async def _handoff_keys(self) -> None:
        inst = self.instance
        if inst is None:
            return
        all_hosts = [p.host for p in inst.peer_list()]
        survivors = [h for h in all_hosts if h != inst.advertise_address]
        if not survivors:
            # no surviving ring (standalone, or last node standing): the
            # final snapshot is the only continuity there is
            self._phase("handoff_skipped")
            return
        self._phase("handoff")
        try:
            totals = await asyncio.wait_for(
                inst.migrate_keys(all_hosts, survivors),
                self.conf.health.drain_timeout)
            log.info("departure handoff: %s", totals)
        except Exception:
            log.exception("departure handoff failed; survivors restart "
                          "these keys cold")

    async def _final_snapshot(self) -> None:
        if self._snapshot_task is None:
            return
        self._phase("snapshot")
        self._snapshot_task.cancel()
        try:
            await self._snapshot_task
        except asyncio.CancelledError:
            pass
        # final snapshot while the engine is serving-quiesced: a clean
        # shutdown loses zero decisions
        await self._snapshot_once()

    async def _teardown(self) -> None:
        self._phase("teardown")
        if self._lease_sweep_task is not None:
            self._lease_sweep_task.cancel()
            try:
                await self._lease_sweep_task
            except asyncio.CancelledError:
                pass
        if self.pool is not None:
            await self.pool.close()
        if self.http is not None:
            await self.http.stop()
        if self.frontdoor is not None:
            await self.frontdoor.stop()
        if self.grpc is not None:
            await self.grpc.stop()
        if self.instance is not None:
            await self.instance.aclose()


async def _amain(conf: DaemonConfig) -> None:
    daemon = Daemon(conf)
    await daemon.start()
    stop = asyncio.Event()
    loop = asyncio.get_running_loop()
    for sig in (signal.SIGINT, signal.SIGTERM):
        loop.add_signal_handler(sig, stop.set)
    await stop.wait()
    log.info("caught signal; shutting down")
    await daemon.stop()


def main(argv=None) -> None:
    p = argparse.ArgumentParser("gubernator-tpu")
    p.add_argument("--config", dest="config_file", default=None,
                   help="environment config file (KEY=value lines)")
    p.add_argument("--debug", action="store_true")
    args = p.parse_args(argv)

    conf = config_from_env(args.config_file)
    import os
    if args.debug or conf.debug:
        logging.basicConfig(level=logging.DEBUG)
        log.debug("debug enabled")
    else:
        logging.basicConfig(level=logging.INFO)

    asyncio.run(_amain(conf))


if __name__ == "__main__":
    main()
