"""JAX version compatibility shims.

The codebase targets the current JAX API (``jax.shard_map`` with
``check_vma``, ``jax.typeof`` aval inspection, ``ShapeDtypeStruct(vma=...)``)
but must also run on the 0.4.x line, where ``shard_map`` still lives under
``jax.experimental`` with the ``check_rep`` spelling, vma tags do not exist,
and ``ShapeDtypeStruct`` has no ``vma`` parameter.  Everything
version-dependent funnels through here so the call sites stay written
against the modern API.
"""

from __future__ import annotations

import jax

__all__ = ["shard_map", "typeof_vma", "shape_dtype_struct"]

_HAS_NEW_SHARD_MAP = hasattr(jax, "shard_map")
_HAS_TYPEOF = hasattr(jax, "typeof")


def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool = True):
    """``jax.shard_map`` with the replication-check flag under either of its
    two historical names (``check_vma`` today, ``check_rep`` on 0.4.x).

    On the 0.4.x fallback the check is forced OFF regardless of the caller:
    that line's checker has no replication rule for ``while`` (every window
    executable carries the replay ``lax.while_loop``), so ``check_rep=True``
    raises NotImplementedError on the engine's default paths.  The check is
    a trace-time safety net, not part of the computation — dropping it
    changes nothing the executables produce."""
    if _HAS_NEW_SHARD_MAP:
        try:
            return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                                 out_specs=out_specs, check_vma=check_vma)
        except TypeError:
            return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                                 out_specs=out_specs, check_rep=check_vma)
    from jax.experimental.shard_map import shard_map as _shard_map
    return _shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                      check_rep=False)


def typeof_vma(x):
    """The vma tag of ``x``'s abstract type, or None where vma does not
    exist (outside shard_map, under check_vma=False, or on 0.4.x)."""
    if not _HAS_TYPEOF:
        return None
    return getattr(jax.typeof(x), "vma", None)


def shape_dtype_struct(shape, dtype, vma=None):
    """``jax.ShapeDtypeStruct`` that forwards ``vma`` only on JAX versions
    whose constructor accepts it (a non-None vma can only have come from
    ``typeof_vma`` on such a version)."""
    if vma is None:
        return jax.ShapeDtypeStruct(shape, dtype)
    return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
