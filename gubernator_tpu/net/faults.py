"""Deterministic fault injection: seams at the I/O edges of the node.

The chaos suite (tests/test_chaos.py) needs to make peers unreachable,
disks fail, and dispatches die — *deterministically*, in-process, with no
iptables or real crashes.  This module is the one switchboard: call sites
at the three seams guard on `FAULTS.enabled` (a single attribute check
when off, the same discipline as the tracing-off path) and, when a rule
matches, delay and/or fail the operation through a seeded RNG so the same
seed replays the same failure schedule.

Seams (the `seam` argument at each call site):

  peer_rpc        net/peers.py — every cross-host RPC attempt (forwards,
                  global sends, migrations, health probes).  An injected
                  failure raises FaultError, which the peer lane
                  normalizes to a retryable UNAVAILABLE-class PeerError —
                  it counts against the breaker exactly like a dead peer.
  snapshot_io     state/snapshot.py — snapshot file write/read.
                  FaultError subclasses OSError so the existing
                  degrade-to-cold-start handling applies unchanged.
  engine_dispatch core/batcher.py — the device window dispatch on the
                  engine thread (window waiters see the failure, the
                  serving loop survives).

Configuration, either programmatically::

    from gubernator_tpu.net.faults import FAULTS
    FAULTS.configure("peer_rpc", drop=1.0, match="127.0.0.1:9001")
    ...
    FAULTS.clear()

or via the environment (read once by the daemon at boot)::

    GUBER_FAULTS="peer_rpc:drop=0.1,delay_ms=50;snapshot_io:error"
    GUBER_FAULTS_SEED=7

Rule grammar: `seam:kv,kv;seam:kv` with kv one of `drop=P` (fail with
probability P), `delay_ms=N` (sleep N ms first), `error` (drop=1.0),
`match=SUBSTR` (only targets containing SUBSTR), `times=N` (fire the
fault at most N times, then pass).  Multiple rules per seam are allowed;
the first matching rule wins.
"""

from __future__ import annotations

import asyncio
import logging
import os
import random
import time
from typing import Dict, List, Optional

log = logging.getLogger("gubernator.faults")

SEAM_PEER_RPC = "peer_rpc"
SEAM_SNAPSHOT_IO = "snapshot_io"
SEAM_ENGINE_DISPATCH = "engine_dispatch"


class FaultError(OSError):
    """An injected failure.  OSError so the snapshot-IO handlers degrade
    exactly like a real disk error; the peer lane normalizes it to a
    retryable PeerError (net/peers.py)."""

    def __init__(self, seam: str, target: str = ""):
        self.seam = seam
        self.target = target
        super().__init__(f"injected fault at {seam}"
                         + (f" -> '{target}'" if target else ""))


class _Rule:
    __slots__ = ("drop", "delay", "match", "remaining", "fired")

    def __init__(self, drop: float = 0.0, delay: float = 0.0,
                 match: str = "", times: Optional[int] = None):
        self.drop = min(1.0, max(0.0, drop))
        self.delay = max(0.0, delay)
        self.match = match
        self.remaining = times  # None = unlimited
        self.fired = 0

    def matches(self, target: str) -> bool:
        return not self.match or self.match in target

    def describe(self) -> dict:
        d = {"drop": self.drop, "delay_ms": self.delay * 1000.0,
             "fired": self.fired}
        if self.match:
            d["match"] = self.match
        if self.remaining is not None:
            d["remaining"] = self.remaining
        return d


class FaultInjector:
    """Rules keyed by seam, decided through one seeded RNG.  `enabled` is
    the hot-path gate: False whenever no rule is installed, so a
    production node pays exactly one attribute check per seam crossing."""

    def __init__(self, seed: int = 0):
        self.enabled = False
        self._rules: Dict[str, List[_Rule]] = {}
        self._rng = random.Random(seed)
        self._seed = seed

    # ------------------------------------------------------------- config

    def seed(self, seed: int) -> None:
        """Re-seed the decision RNG: the same seed + the same call
        sequence replays the same drop schedule."""
        self._seed = seed
        self._rng = random.Random(seed)

    def configure(self, seam: str, drop: float = 0.0, delay_ms: float = 0.0,
                  match: str = "", times: Optional[int] = None) -> None:
        """Install one rule on `seam` (programmatic API)."""
        self._rules.setdefault(seam, []).append(
            _Rule(drop=drop, delay=delay_ms / 1000.0, match=match,
                  times=times))
        self.enabled = True

    def load_spec(self, spec: str, seed: Optional[int] = None) -> None:
        """Parse the GUBER_FAULTS grammar (see module docstring)."""
        if seed is not None:
            self.seed(seed)
        for part in spec.split(";"):
            part = part.strip()
            if not part:
                continue
            seam, _, kvs = part.partition(":")
            seam = seam.strip()
            if not seam:
                raise ValueError(f"malformed fault rule '{part}'")
            kw: dict = {}
            for kv in kvs.split(","):
                kv = kv.strip()
                if not kv:
                    continue
                k, _, v = kv.partition("=")
                k = k.strip()
                if k == "drop":
                    kw["drop"] = float(v)
                elif k == "delay_ms":
                    kw["delay_ms"] = float(v)
                elif k == "error":
                    kw["drop"] = 1.0
                elif k == "match":
                    kw["match"] = v.strip()
                elif k == "times":
                    kw["times"] = int(v)
                else:
                    raise ValueError(
                        f"unknown fault key '{k}' in rule '{part}'")
            self.configure(seam, **kw)

    def load_from_env(self) -> bool:
        """Daemon boot: install GUBER_FAULTS / GUBER_FAULTS_SEED if set.
        Returns True when a spec was installed."""
        spec = os.environ.get("GUBER_FAULTS", "")
        if not spec:
            return False
        seed = int(os.environ.get("GUBER_FAULTS_SEED", "0"))
        self.load_spec(spec, seed=seed)
        log.warning("fault injection ACTIVE: %s (seed %d)", spec, seed)
        return True

    def clear(self) -> None:
        self._rules.clear()
        self.enabled = False

    def describe(self) -> dict:
        return {seam: [r.describe() for r in rules]
                for seam, rules in self._rules.items()}

    # -------------------------------------------------------------- seams

    def _decide(self, seam: str, target: str):
        """(delay_seconds, rule_to_fire | None) for this crossing."""
        delay = 0.0
        for rule in self._rules.get(seam, ()):
            if not rule.matches(target):
                continue
            if rule.remaining is not None and rule.remaining <= 0:
                continue
            delay += rule.delay
            if rule.drop > 0.0 and self._rng.random() < rule.drop:
                rule.fired += 1
                if rule.remaining is not None:
                    rule.remaining -= 1
                return delay, rule
            return delay, None
        return delay, None

    async def on_async(self, seam: str, target: str = "") -> None:
        """Async seam crossing: sleep the injected delay, then raise
        FaultError if a rule fires.  Call ONLY behind `if FAULTS.enabled`."""
        delay, fired = self._decide(seam, target)
        if delay > 0.0:
            await asyncio.sleep(delay)
        if fired is not None:
            raise FaultError(seam, target)

    def on_sync(self, seam: str, target: str = "") -> None:
        """Sync seam crossing (engine thread, snapshot IO)."""
        delay, fired = self._decide(seam, target)
        if delay > 0.0:
            time.sleep(delay)
        if fired is not None:
            raise FaultError(seam, target)


# the process-wide injector every seam guards on; tests that configure it
# MUST clear() it again (the chaos fixtures do)
FAULTS = FaultInjector()
