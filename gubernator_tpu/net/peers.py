"""Cross-host peer client: gRPC connection + request batching window.

One PeerClient per remote peer, owning the connection and the BATCHING
aggregation window (reference peers.go:35-207): BATCHING/GLOBAL requests
queue until batch_limit (1000) or batch_wait (500µs), then ship as one
GetPeerRateLimits RPC whose responses demux back by index; NO_BATCHING goes
as an immediate single-item RPC.

This client is only for the *cross-host* plane — peers within one mesh are
chips and talk via collectives, not RPCs (SURVEY.md §2 parallelism table).
"""

from __future__ import annotations

import asyncio
import logging
from typing import List, Optional

import grpc

from gubernator_tpu.api import pb
from gubernator_tpu.api.grpc_api import PeersV1Stub
from gubernator_tpu.api.types import Behavior, RateLimitReq, RateLimitResp
from gubernator_tpu.config import BehaviorConfig, QoSConfig
from gubernator_tpu.core.interval import ArmedInterval
from gubernator_tpu.net.faults import FAULTS, SEAM_PEER_RPC, FaultError
from gubernator_tpu.observability.tracing import TRACEPARENT, current_context
from gubernator_tpu.qos.breaker import CircuitBreaker, backoff_delays

log = logging.getLogger("gubernator.peers")


class PeerError(Exception):
    """Typed peer-lane failure with the peer host attached.

    Every transport failure on the forward lane (raw AioRpcError, asyncio
    timeout) normalizes to this, so shed/fallback logic and tests match on
    a stable type instead of grpc internals.  `retryable` marks transient
    transport conditions (UNAVAILABLE / DEADLINE_EXCEEDED) that count
    against the peer's circuit breaker."""

    def __init__(self, host: str, message: str, code=None,
                 retryable: bool = False):
        self.host = host
        self.code = code
        self.retryable = retryable
        super().__init__(f"peer '{host}': {message}")


class BreakerOpenError(PeerError):
    """The peer's circuit breaker is open: the call was rejected locally
    without touching the network.  core/service.py turns this into the
    configured fail-open (local non-authoritative answer) or fail-closed
    (in-band shed) behavior."""

    def __init__(self, host: str):
        super().__init__(host, "circuit breaker open", retryable=False)


# transient transport conditions: retried with jittered backoff and
# counted against the breaker (everything else is the caller's problem)
_TRANSIENT_CODES = (grpc.StatusCode.UNAVAILABLE,
                    grpc.StatusCode.DEADLINE_EXCEEDED)


class PeerClient:
    def __init__(self, behaviors: BehaviorConfig, host: str, qos=None):
        """qos: the Instance's QoSManager — supplies the breaker (with its
        injectable clock + state-gauge hook) and retry policy.  None gets
        default-config resilience (standalone embedding, tests)."""
        self.host = host
        self.conf = behaviors
        self.is_owner = False  # True when this entry names the local instance
        # insecure channel, like the reference (peers.go:132)
        self.channel = grpc.aio.insecure_channel(host)
        self.stub = PeersV1Stub(self.channel)
        self._raw_batch = None  # bytes-level relay, built on first use
        self._raw_transfer = None  # bytes-level bucket-migration lane
        self._v1 = None  # V1 stub for heartbeat probes, built on first use
        self._pending: List[tuple] = []  # (req, future, trace ctx|None)
        self._interval: Optional[ArmedInterval] = None
        self._waiter: Optional[asyncio.Task] = None
        # ---- resilience (gubernator_tpu/qos/breaker.py)
        self._qos = qos
        qconf = qos.conf if qos is not None else QoSConfig()
        self.retries = qconf.peer_retries
        self.retry_base = qconf.retry_base
        self.retry_cap = qconf.retry_cap
        self.breaker = (qos.make_breaker(host) if qos is not None
                        else CircuitBreaker(
                            fail_threshold=qconf.breaker_fail_threshold,
                            open_duration=qconf.breaker_open_duration,
                            half_open_probes=qconf.breaker_half_open_probes))
        self._sleep = asyncio.sleep  # injectable for deterministic tests

    # ------------------------------------------------------------ resilience

    @staticmethod
    def _normalize(host: str, e: Exception) -> PeerError:
        """Fold any transport failure into a typed PeerError."""
        if isinstance(e, PeerError):
            return e
        code = None
        code_fn = getattr(e, "code", None)
        if callable(code_fn):
            try:
                code = code_fn()
            except Exception:
                code = None
        if isinstance(e, FaultError):
            # injected partition (net/faults.py): indistinguishable from a
            # dead peer by design
            return PeerError(host, str(e),
                             code=grpc.StatusCode.UNAVAILABLE,
                             retryable=True)
        if isinstance(e, (asyncio.TimeoutError, TimeoutError)):
            return PeerError(host, "request timed out",
                             code=grpc.StatusCode.DEADLINE_EXCEEDED,
                             retryable=True)
        details_fn = getattr(e, "details", None)
        msg = None
        if callable(details_fn):
            try:
                msg = details_fn()
            except Exception:
                msg = None
        return PeerError(host, msg or str(e), code=code,
                         retryable=code in _TRANSIENT_CODES)

    async def _call(self, do):
        """Run one RPC attempt closure through the resilience layer:
        breaker gate -> attempt -> jittered-backoff retries on transient
        UNAVAILABLE-class failures -> typed PeerError out.  Success and
        (final) transient failure feed the breaker; non-transient errors
        (bad request, peer-side app errors) do not trip it."""
        if not self.breaker.allow():
            raise BreakerOpenError(self.host)
        delays = backoff_delays(self.retries, self.retry_base, self.retry_cap)
        attempt = 0
        while True:
            try:
                if FAULTS.enabled:
                    await FAULTS.on_async(SEAM_PEER_RPC, self.host)
                out = await do()
            except (grpc.RpcError, asyncio.TimeoutError, TimeoutError,
                    FaultError) as e:
                err = self._normalize(self.host, e)
                if err.retryable and attempt < self.retries:
                    attempt += 1
                    if (self._qos is not None
                            and self._qos.metrics is not None):
                        self._qos.metrics.observe_peer_retry(self.host)
                    await self._sleep(next(delays))
                    continue
                if err.retryable:
                    self.breaker.record_failure()
                else:
                    # the peer answered (with an application error): it is
                    # alive, which is what the breaker tracks
                    self.breaker.record_success()
                raise err from e
            self.breaker.record_success()
            return out

    async def health_check(self, timeout: float = 0.5):
        """One heartbeat probe against this peer's V1 HealthCheck
        (net/health.py's detector drives this).  Deliberately OUTSIDE the
        resilience layer: no retries (the detector's suspicion count IS
        the retry policy) and no breaker gate (an open breaker must never
        stop the detector from noticing the peer came back).  The
        peer_rpc fault seam still applies, so an injected partition
        blacks out heartbeats exactly like real traffic."""
        if FAULTS.enabled:
            await FAULTS.on_async(SEAM_PEER_RPC, self.host)
        if self._v1 is None:
            from gubernator_tpu.api.grpc_api import V1Stub
            self._v1 = V1Stub(self.channel)
        return await self._v1.HealthCheck(pb.HealthCheckReq(),
                                          timeout=timeout)

    # ------------------------------------------------------------ forwarding

    async def get_peer_rate_limit(self, req: RateLimitReq) -> RateLimitResp:
        """Forward one request, batching per behavior (peers.go:73-91)."""
        if req.behavior in (Behavior.BATCHING, Behavior.GLOBAL):
            return await self._batched(req)
        resps = await self.get_peer_rate_limits([req])
        return resps[0]

    async def get_peer_rate_limits(self, reqs: List[RateLimitReq],
                                   ctx=None) -> List[RateLimitResp]:
        """One unary batch RPC; validates response length (peers.go:93-105).

        `ctx` (or the ambient sampled SpanContext) rides the RPC as
        `traceparent` invocation metadata so the owner's spans stitch into
        the caller's trace."""
        if ctx is None:
            ctx = current_context()
        md = ((TRACEPARENT, ctx.traceparent()),) if ctx is not None else None
        msg = pb.GetPeerRateLimitsReq(requests=[pb.req_to_pb(r) for r in reqs])
        resp = await self._call(lambda: self.stub.GetPeerRateLimits(
            msg, timeout=self.conf.batch_timeout, metadata=md))
        if len(resp.rate_limits) != len(reqs):
            raise RuntimeError(
                "number of rate limits in peer response does not match request")
        return [pb.resp_from_pb(m) for m in resp.rate_limits]

    async def update_peer_globals(self, globals_: List) -> None:
        """Push authoritative global statuses (peers.go:107-109)."""
        msg = pb.UpdatePeerGlobalsReq(globals=[
            pb.UpdatePeerGlobal(
                key=g.key,
                status=pb.resp_to_pb(g.status),
                algorithm=int(g.algorithm),
                duration=g.duration,
            )
            for g in globals_
        ])
        await self._call(lambda: self.stub.UpdatePeerGlobals(
            msg, timeout=self.conf.global_timeout))

    async def get_peer_rate_limits_raw(self, data: bytes) -> bytes:
        """Bytes-level batch relay: the caller splices serialized
        RateLimitReq frames straight into the request and gets framed
        responses back — the whole forward path without materializing
        protobuf objects (used by the pipeline's mixed-RPC flow)."""
        if self._raw_batch is None:
            self._raw_batch = self.channel.unary_unary(
                "/pb.gubernator.PeersV1/GetPeerRateLimits",
                request_serializer=lambda b: b,
                response_deserializer=lambda b: b)
        return await self._call(lambda: self._raw_batch(
            data, timeout=self.conf.batch_timeout))

    async def transfer_buckets(self, payload: bytes) -> bytes:
        """Ship migrated bucket rows to this peer (state/migrate.py wire
        payload) and return its ack.  Bytes-level like the raw batch relay:
        the codec lives in one module, not in generated protos."""
        if self._raw_transfer is None:
            self._raw_transfer = self.channel.unary_unary(
                "/pb.gubernator.PeersV1/TransferBuckets",
                request_serializer=lambda b: b,
                response_deserializer=lambda b: b)
        return await self._call(lambda: self._raw_transfer(
            payload, timeout=self.conf.batch_timeout))

    async def register_globals(self, specs: List[tuple]) -> None:
        """Forward (key, limit, duration, algorithm) registrations to the
        mesh registrar (api/proto/peers.proto RegisterGlobals)."""
        msg = pb.RegisterGlobalsReq(specs=[
            pb.GlobalSpec(key=k, limit=lim, duration=dur, algorithm=int(a))
            for (k, lim, dur, a) in specs
        ])
        await self._call(lambda: self.stub.RegisterGlobals(
            msg, timeout=self.conf.global_timeout))

    async def apply_global_registration(self, specs: List[tuple], now: int,
                                        activate: bool) -> None:
        """Registrar-side fan-out of one registration phase."""
        msg = pb.ApplyGlobalRegistrationReq(
            specs=[pb.GlobalSpec(key=k, limit=lim, duration=dur,
                                 algorithm=int(a))
                   for (k, lim, dur, a) in specs],
            now=now, activate=activate)
        await self._call(lambda: self.stub.ApplyGlobalRegistration(
            msg, timeout=self.conf.global_timeout))

    # -------------------------------------------------------------- batching

    async def _batched(self, req: RateLimitReq) -> RateLimitResp:
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        # capture the ambient trace context NOW — the flusher task that
        # ships the window has no ambient ctx of its own
        self._pending.append((req, fut, current_context()))
        if len(self._pending) >= self.conf.batch_limit:
            self._flush()
        elif len(self._pending) == 1:
            if self._interval is None:
                self._interval = ArmedInterval(self.conf.batch_wait)
            self._interval.arm()
            if self._waiter is None or self._waiter.done():
                self._waiter = asyncio.create_task(self._wait_interval())
        return await fut

    async def _wait_interval(self) -> None:
        await self._interval.wait()
        if self._pending:
            self._flush()

    def _flush(self) -> None:
        window = self._pending
        self._pending = []
        asyncio.create_task(self._send_window(window))

    async def _send_window(self, window: List[tuple]) -> None:
        reqs = [w[0] for w in window]
        # the window carries many requests but one RPC: propagate the first
        # sampled context (a shared-batch trace is stitched, not per-item)
        ctx = next((w[2] for w in window if w[2] is not None), None)
        try:
            resps = await self.get_peer_rate_limits(reqs, ctx=ctx)
        except Exception as e:
            # the whole batch failed; every waiter sees the error
            # (peers.go:189-196)
            for w in window:
                if not w[1].done():
                    w[1].set_exception(e)
            return
        for w, resp in zip(window, resps):
            if not w[1].done():
                w[1].set_result(resp)

    async def close(self) -> None:
        """Disconnect (the reference leaks old PeerClients on membership
        churn — gubernator.go:276 TODO; we close them)."""
        if self._interval is not None:
            self._interval.stop()
        await self.channel.close()
