"""Heartbeat failure detector: suspicion counts -> confirmed-down -> re-home.

The discovery backends (k8s watch, etcd lease) already remove dead peers;
a GUBER_STATIC_PEERS pool never does — a crashed peer stays in the ring
forever and every key it owns blackholes (until PR 4's breaker degrades
each call, which heals nothing).  This monitor closes that gap with the
simplest detector that composes with what exists (SWIM's full protocol —
indirect probes, gossip dissemination — is deliberately out of scope for
a pool small enough to probe all-to-all):

  * every `heartbeat_interval` each peer gets one V1 HealthCheck probe on
    its OWN PeerClient (separate from the serving ring's clients, so
    set_peers closing a departed client never kills its probe channel,
    and an open serving breaker never blocks recovery detection);
  * `suspect_after` CONSECUTIVE failures confirm a peer DOWN: its breaker
    is force-tripped (stop burning forward latency on a peer we know is
    dead) and the ring re-homes around it (service.rehome -> set_peers +
    migrate_keys);
  * `recover_after` CONSECUTIVE successes confirm a DOWN peer UP again:
    breaker force-closed, ring re-homes to include it, and the
    GlobalManager replays its hinted payloads.  The two-sided hysteresis
    bounds how often a flapping peer can churn the ring.

Everything is injectable (probe_fn, now_fn, sleep) and `probe_once()` is
public, so the chaos suite drives whole failure timelines without real
time; the peer_rpc fault seam applies to probes exactly like traffic, so
an injected partition blacks out heartbeats too.
"""

from __future__ import annotations

import asyncio
import logging
import time
from typing import Dict, List, Optional, Sequence

from gubernator_tpu.config import HealthConfig
from gubernator_tpu.net.peers import PeerClient

log = logging.getLogger("gubernator.health")

UP = "up"
SUSPECT = "suspect"
DOWN = "down"


class _PeerState:
    __slots__ = ("host", "state", "fail_streak", "ok_streak",
                 "probes", "failures", "last_change")

    def __init__(self, host: str, now: float):
        self.host = host
        self.state = UP
        self.fail_streak = 0
        self.ok_streak = 0
        self.probes = 0
        self.failures = 0
        self.last_change = now


class HeartbeatMonitor:
    def __init__(self, instance, addresses: Sequence[str],
                 conf: Optional[HealthConfig] = None,
                 probe_fn=None, now_fn=time.monotonic, sleep=asyncio.sleep):
        """addresses: full static membership INCLUDING this node (its own
        entry is skipped); the monitor's view of who *should* be in the
        ring is this list — confirmed-down peers are subtracted from it,
        never forgotten, so they rejoin automatically on recovery.

        probe_fn(host) -> awaitable: injectable probe for tests; default
        probes V1 HealthCheck through a dedicated PeerClient."""
        self.instance = instance
        self.conf = conf or HealthConfig()
        self.now_fn = now_fn
        self._sleep = sleep
        self._probe_fn = probe_fn
        self.self_host = instance.advertise_address
        self._peers: Dict[str, _PeerState] = {}
        self._clients: Dict[str, PeerClient] = {}
        now = now_fn()
        for addr in addresses:
            if addr and addr != self.self_host:
                self._peers[addr] = _PeerState(addr, now)
        self._task: Optional[asyncio.Task] = None
        self._stopped = asyncio.Event()

    # ------------------------------------------------------------- probing

    async def _probe(self, host: str) -> bool:
        try:
            if self._probe_fn is not None:
                await self._probe_fn(host)
            else:
                client = self._clients.get(host)
                if client is None:
                    client = PeerClient(self.instance.conf.behaviors, host,
                                        qos=None)
                    self._clients[host] = client
                await client.health_check(
                    timeout=self.conf.heartbeat_timeout)
            return True
        except Exception:
            return False

    async def probe_once(self) -> None:
        """One full probe round (all peers concurrently) + verdict
        updates.  The run loop calls this every heartbeat_interval; tests
        call it directly to step the detector deterministically."""
        hosts = list(self._peers)
        results = await asyncio.gather(*(self._probe(h) for h in hosts))
        for host, ok in zip(hosts, results):
            await self._account(host, ok)

    async def _account(self, host: str, ok: bool) -> None:
        st = self._peers.get(host)
        if st is None:
            return
        st.probes += 1
        if ok:
            st.ok_streak += 1
            st.fail_streak = 0
            if st.state == SUSPECT:
                self._transition(st, UP)
            elif st.state == DOWN and st.ok_streak >= self.conf.recover_after:
                self._transition(st, UP)
                await self._on_peer_up(host)
        else:
            st.failures += 1
            st.fail_streak += 1
            st.ok_streak = 0
            if st.state == UP:
                self._transition(st, SUSPECT)
            if (st.state == SUSPECT
                    and st.fail_streak >= self.conf.suspect_after):
                self._transition(st, DOWN)
                await self._on_peer_down(host)

    def _transition(self, st: _PeerState, state: str) -> None:
        if state == st.state:
            return
        log.log(logging.WARNING if state != UP else logging.INFO,
                "peer '%s': %s -> %s", st.host, st.state, state)
        st.state = state
        st.last_change = self.now_fn()
        metrics = getattr(self.instance, "metrics", None)
        if metrics is not None:
            metrics.observe_peer_health(st.host, state)

    # ------------------------------------------------------------- verdicts

    def membership(self) -> List[str]:
        """Who the ring should contain right now: the static pool minus
        confirmed-down peers, plus this node."""
        alive = [h for h, st in self._peers.items() if st.state != DOWN]
        return sorted(alive + [self.self_host])

    async def _on_peer_down(self, host: str) -> None:
        # stop paying forward latency for a peer the detector knows is
        # dead — the breaker's own clockwork would need fail_threshold
        # more losses to notice
        qos = getattr(self.instance, "qos", None)
        if qos is not None:
            breaker = qos.breakers.get(host)
            if breaker is not None:
                breaker.trip()
        # give back the concurrency slots the dead peer's clients hold —
        # nobody is left on that side to send the releases
        release = getattr(self.instance, "release_peer_leases", None)
        if release is not None:
            try:
                await release(host)
            except Exception as e:
                log.error("lease release after '%s' went down failed: %s",
                          host, e)
        try:
            await self.instance.rehome(self.membership(), direction="down")
        except Exception as e:
            log.error("re-home after '%s' went down failed: %s", host, e)

    async def _on_peer_up(self, host: str) -> None:
        qos = getattr(self.instance, "qos", None)
        if qos is not None:
            breaker = qos.breakers.get(host)
            if breaker is not None:
                breaker.reset()
        try:
            await self.instance.rehome(self.membership(), direction="up")
        except Exception as e:
            log.error("re-home after '%s' recovered failed: %s", host, e)
        self.instance.on_peer_recovered(host)

    # ------------------------------------------------------------- lifecycle

    def start(self) -> None:
        if self._task is None or self._task.done():
            self._stopped.clear()
            self._task = asyncio.create_task(self._run())

    async def _run(self) -> None:
        while not self._stopped.is_set():
            try:
                await self.probe_once()
            except Exception as e:  # the detector must outlive any probe bug
                log.error("heartbeat round failed: %s", e)
            try:
                await asyncio.wait_for(self._stopped.wait(),
                                       self.conf.heartbeat_interval)
            except asyncio.TimeoutError:
                pass

    async def stop(self) -> None:
        self._stopped.set()
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except (asyncio.CancelledError, Exception):
                pass
            self._task = None
        for client in self._clients.values():
            try:
                await client.close()
            except Exception:
                pass
        self._clients.clear()

    # ------------------------------------------------------------- introspect

    def snapshot(self) -> dict:
        now = self.now_fn()
        return {
            "self": self.self_host,
            "interval_s": self.conf.heartbeat_interval,
            "suspect_after": self.conf.suspect_after,
            "recover_after": self.conf.recover_after,
            "peers": {
                h: {
                    "state": st.state,
                    "fail_streak": st.fail_streak,
                    "ok_streak": st.ok_streak,
                    "probes": st.probes,
                    "failures": st.failures,
                    "since_change_s": round(now - st.last_change, 3),
                }
                for h, st in self._peers.items()
            },
        }
