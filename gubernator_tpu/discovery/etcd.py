"""etcd-based peer discovery via the etcd v3 HTTP/JSON gateway.

Functional equivalent of the reference's EtcdPool (etcd.go:47-316): each node
self-registers under `<prefix><advertise_address>` with a leased key (TTL
30s, etcd.go:39), keeps the lease alive (re-registering if it expires,
etcd.go:247-298), and watches the prefix — any change rebuilds the full peer
list and fires OnUpdate → Instance.set_peers (etcd.go:150-209, restart with
5s backoff).

The reference links the etcd Go client; this image has no Python etcd
client, so we speak the stable v3 JSON gateway (/v3/kv/*, /v3/lease/*,
/v3/watch) over aiohttp — same server-side semantics, zero extra deps.
Unlike the reference (which never sets IsOwner on etcd-discovered peers —
a noted inconsistency, SURVEY.md §3.5), we mark self by advertise address.
"""

from __future__ import annotations

import asyncio
import base64
import json
import logging
from typing import Awaitable, Callable, Dict, List, Optional

import aiohttp

from gubernator_tpu.config import PeerInfo

log = logging.getLogger("gubernator.etcd")

LEASE_TTL_S = 30  # reference etcdTimeout lease TTL (etcd.go:39)
BACKOFF_S = 5.0  # watch restart backoff (etcd.go:199)

OnUpdate = Callable[[List[PeerInfo]], Awaitable[None]]


def _b64(s: str) -> str:
    return base64.b64encode(s.encode()).decode()


def _unb64(s: str) -> str:
    return base64.b64decode(s).decode()


def _prefix_range_end(prefix: str) -> str:
    raw = bytearray(prefix.encode())
    for i in range(len(raw) - 1, -1, -1):
        if raw[i] < 0xFF:
            raw[i] += 1
            return _b64(bytes(raw[: i + 1]).decode("latin-1"))
    return _b64("\0")


class EtcdPool:
    def __init__(
        self,
        endpoints: List[str],
        advertise_address: str,
        on_update: OnUpdate,
        prefix: str = "/gubernator/peers/",
        username: str = "",
        password: str = "",
        ssl_context=None,
    ):
        if not advertise_address:
            raise ValueError("AdvertiseAddress is required")  # etcd.go:68
        self.base = endpoints[0].rstrip("/")
        if not self.base.startswith("http"):
            # TLS-configured connections default to the https scheme
            # (the reference's etcd client switches transports on conf.TLS)
            scheme = "https://" if ssl_context is not None else "http://"
            self.base = scheme + self.base
        self.prefix = prefix
        self.advertise_address = advertise_address
        self.on_update = on_update
        self.username = username
        self.password = password
        self.ssl_context = ssl_context
        self._session: Optional[aiohttp.ClientSession] = None
        self._lease_id: Optional[int] = None
        self._peers: Dict[str, PeerInfo] = {}
        self._tasks: List[asyncio.Task] = []
        self._closed = False

    async def _post(self, path: str, payload: dict) -> dict:
        async with self._session.post(self.base + path, json=payload) as r:
            r.raise_for_status()
            return await r.json()

    def _connector(self) -> Optional[aiohttp.TCPConnector]:
        if self.ssl_context is None:
            return None
        return aiohttp.TCPConnector(ssl=self.ssl_context)

    async def start(self) -> None:
        headers = {}
        if self.username:
            # v3 JSON gateway auth: exchange user/pass for a token
            async with aiohttp.ClientSession(connector=self._connector()) as s:
                async with s.post(self.base + "/v3/auth/authenticate", json={
                    "name": self.username, "password": self.password}) as r:
                    r.raise_for_status()
                    headers["Authorization"] = (await r.json())["token"]
        self._session = aiohttp.ClientSession(
            headers=headers, connector=self._connector())
        await self._register()
        await self._collect()
        self._tasks.append(asyncio.create_task(self._keepalive_loop()))
        self._tasks.append(asyncio.create_task(self._watch_loop()))

    # ------------------------------------------------------------ registration

    async def _register(self) -> None:
        """Grant a lease and put our key under it (etcd.go:211-245)."""
        grant = await self._post("/v3/lease/grant", {"TTL": str(LEASE_TTL_S)})
        self._lease_id = int(grant["ID"])
        key = self.prefix + self.advertise_address
        await self._post("/v3/kv/put", {
            "key": _b64(key),
            "value": _b64(self.advertise_address),
            "lease": str(self._lease_id),
        })

    async def _keepalive_loop(self) -> None:
        """Heartbeat the lease; on failure re-register (etcd.go:247-298)."""
        while not self._closed:
            await asyncio.sleep(LEASE_TTL_S / 3)
            try:
                resp = await self._post("/v3/lease/keepalive",
                                        {"ID": str(self._lease_id)})
                ttl = int(resp.get("result", {}).get("TTL", 0))
                if ttl <= 0:
                    raise RuntimeError("lease expired")
            except Exception as e:
                if self._closed:
                    return
                log.warning("lease keep-alive failed (%s); re-registering", e)
                await asyncio.sleep(BACKOFF_S)
                try:
                    await self._register()
                except Exception as e2:
                    log.error("re-register failed: %s", e2)

    # ----------------------------------------------------------------- watch

    async def _collect(self) -> None:
        """Initial full read of the prefix (etcd.go:132-148)."""
        resp = await self._post("/v3/kv/range", {
            "key": _b64(self.prefix),
            "range_end": _prefix_range_end(self.prefix),
        })
        self._peers = {}
        for kv in resp.get("kvs", []):
            addr = _unb64(kv["value"])
            self._peers[_unb64(kv["key"])] = PeerInfo(address=addr)
        await self._fire()

    async def _watch_loop(self) -> None:
        """Stream watch events; restart with backoff (etcd.go:150-209)."""
        while not self._closed:
            try:
                payload = json.dumps({"create_request": {
                    "key": _b64(self.prefix),
                    "range_end": _prefix_range_end(self.prefix),
                }})
                async with self._session.post(self.base + "/v3/watch",
                                              data=payload) as r:
                    async for line in r.content:
                        if self._closed:
                            return
                        if not line.strip():
                            continue
                        msg = json.loads(line)
                        events = msg.get("result", {}).get("events", [])
                        if events:
                            await self._apply_events(events)
            except Exception as e:
                if self._closed:
                    return
                log.warning("etcd watch interrupted (%s); restarting", e)
                await asyncio.sleep(BACKOFF_S)
                try:
                    await self._collect()
                except Exception:
                    pass

    async def _apply_events(self, events: List[dict]) -> None:
        # PUT adds/updates a peer; DELETE (lease expiry) removes it
        # (etcd.go:168-182)
        for ev in events:
            kv = ev.get("kv", {})
            key = _unb64(kv.get("key", ""))
            if ev.get("type") == "DELETE":
                self._peers.pop(key, None)
            else:
                self._peers[key] = PeerInfo(address=_unb64(kv.get("value", "")))
        await self._fire()

    async def _fire(self) -> None:
        peers = [
            PeerInfo(address=p.address,
                     is_owner=(p.address == self.advertise_address))
            for p in self._peers.values()
        ]
        await self.on_update(peers)

    async def close(self) -> None:
        """Deregister and stop (etcd.go:283-295)."""
        self._closed = True
        for t in self._tasks:
            t.cancel()
        try:
            if self._lease_id is not None:
                await self._post("/v3/kv/deleterange",
                                 {"key": _b64(self.prefix + self.advertise_address)})
                await self._post("/v3/lease/revoke", {"ID": str(self._lease_id)})
        except Exception:
            pass
        if self._session is not None:
            await self._session.close()
