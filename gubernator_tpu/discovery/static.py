"""Static peer list "discovery" — a fixed membership pushed once.

The reference has no static backend (only etcd/k8s); this is the simplest
OnUpdate source, used by the daemon's GUBER_STATIC_PEERS extension and by
embedding users who manage membership themselves (the reference's library
embedding story, architecture.md:79-91: call SetPeers yourself).
"""

from __future__ import annotations

from typing import Awaitable, Callable, List

from gubernator_tpu.config import PeerInfo

OnUpdate = Callable[[List[PeerInfo]], Awaitable[None]]


class StaticPool:
    def __init__(self, addresses: List[str], advertise_address: str,
                 on_update: OnUpdate):
        self.addresses = addresses
        self.advertise_address = advertise_address
        self.on_update = on_update

    async def start(self) -> None:
        peers = [
            PeerInfo(address=a, is_owner=(a == self.advertise_address))
            for a in self.addresses
        ]
        await self.on_update(peers)

    async def close(self) -> None:
        pass
