"""Kubernetes peer discovery: watch the Endpoints API for pod membership.

Functional equivalent of the reference's K8sPool (kubernetes.go:35-161):
watch Endpoints in our namespace filtered by a label selector; the peer list
is every ready pod IP plus the configured port; self is marked by PodIP
match (kubernetes.go:148-150).  No self-registration — kubelet readiness
drives membership.

The reference links client-go's SharedIndexInformer; this image has no
Python k8s client, so we speak the core REST API directly (in-cluster
service-account token + CA, watch=true streaming) over aiohttp — the same
watch/relist protocol an informer uses.
"""

from __future__ import annotations

import asyncio
import json
import logging
import os
import ssl
from typing import Awaitable, Callable, List, Optional

import aiohttp

from gubernator_tpu.config import PeerInfo

log = logging.getLogger("gubernator.k8s")

SA_DIR = "/var/run/secrets/kubernetes.io/serviceaccount"
BACKOFF_S = 5.0

OnUpdate = Callable[[List[PeerInfo]], Awaitable[None]]


class K8sPool:
    def __init__(
        self,
        namespace: str,
        pod_ip: str,
        pod_port: str,
        selector: str,
        on_update: OnUpdate,
        api_base: Optional[str] = None,
        token: Optional[str] = None,
        ssl_context: Optional[ssl.SSLContext] = None,
    ):
        self.namespace = namespace
        self.pod_ip = pod_ip
        self.pod_port = pod_port
        self.selector = selector
        self.on_update = on_update
        # in-cluster config (the reference uses rest.InClusterConfig,
        # kubernetes.go:57); tests may inject api_base/token directly
        if api_base is None:
            host = os.environ.get("KUBERNETES_SERVICE_HOST", "kubernetes.default.svc")
            port = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
            api_base = f"https://{host}:{port}"
        self.api_base = api_base.rstrip("/")
        if token is None and os.path.exists(f"{SA_DIR}/token"):
            token = open(f"{SA_DIR}/token").read().strip()
        self.token = token or ""
        if ssl_context is None and os.path.exists(f"{SA_DIR}/ca.crt"):
            ssl_context = ssl.create_default_context(cafile=f"{SA_DIR}/ca.crt")
        self.ssl_context = ssl_context
        self._session: Optional[aiohttp.ClientSession] = None
        self._task: Optional[asyncio.Task] = None
        self._closed = False

    def _url(self, watch: bool, resource_version: str = "") -> str:
        url = (f"{self.api_base}/api/v1/namespaces/{self.namespace}/endpoints"
               f"?labelSelector={self.selector}")
        if watch:
            url += "&watch=true"
            if resource_version:
                url += f"&resourceVersion={resource_version}"
        return url

    async def start(self) -> None:
        headers = {}
        if self.token:
            headers["Authorization"] = f"Bearer {self.token}"
        self._session = aiohttp.ClientSession(headers=headers)
        self._task = asyncio.create_task(self._run())

    async def _run(self) -> None:
        while not self._closed:
            try:
                # list, then watch from the returned resourceVersion — the
                # informer pattern (kubernetes.go:78-104)
                async with self._session.get(self._url(False),
                                             ssl=self.ssl_context) as r:
                    r.raise_for_status()
                    listing = await r.json()
                await self._update_from(listing.get("items", []))
                rv = listing.get("metadata", {}).get("resourceVersion", "")
                async with self._session.get(self._url(True, rv),
                                             ssl=self.ssl_context,
                                             timeout=aiohttp.ClientTimeout(total=None)) as r:
                    async for line in r.content:
                        if self._closed:
                            return
                        if not line.strip():
                            continue
                        ev = json.loads(line)
                        if ev.get("type") in ("ADDED", "MODIFIED", "DELETED"):
                            # simplest correct reaction: relist
                            # (update/delete handlers, kubernetes.go:105-123)
                            break
            except Exception as e:
                if self._closed:
                    return
                log.warning("k8s endpoints watch interrupted (%s); retrying", e)
                await asyncio.sleep(BACKOFF_S)

    async def _update_from(self, endpoints_items: List[dict]) -> None:
        """Peer list = ready pod IPs + configured port (kubernetes.go:135-156)."""
        peers: List[PeerInfo] = []
        for item in endpoints_items:
            for subset in item.get("subsets", []) or []:
                for addr in subset.get("addresses", []) or []:
                    ip = addr.get("ip", "")
                    if not ip:
                        continue
                    peers.append(PeerInfo(
                        address=f"{ip}:{self.pod_port}",
                        is_owner=(ip == self.pod_ip),
                    ))
        await self.on_update(peers)

    async def close(self) -> None:
        self._closed = True
        if self._task is not None:
            self._task.cancel()
        if self._session is not None:
            await self._session.close()
