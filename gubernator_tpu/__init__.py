"""gubernator-tpu: a TPU-native distributed rate-limiting framework.

A ground-up redesign of Gubernator (reference: /root/reference, mailgun/gubernator
v0.5.0) for TPU hardware.  Where the reference keeps each rate-limit counter in a
per-node LRU map mutated under a mutex (reference cache/lru.go:30,
algorithms.go:24-186), this framework keeps the whole keyspace as dense
structure-of-arrays state resident in TPU HBM, evaluates every batching window
with one fused XLA/Pallas kernel (ops/kernel.py), partitions keys over a
`jax.sharding.Mesh` axis instead of a consistent-hash ring of Go processes
(reference hash.go:28-96), and replaces the GLOBAL behavior's async gRPC hit
broadcast (reference global.go:72-232) with a `lax.psum` over the mesh axis.

Rate-limit quantities (hits/limit/remaining) and millisecond-epoch timestamps
are int64 on the wire (reference proto/gubernator.proto:97-143), so the device
state is int64 as well; we therefore enable JAX x64 support at import time,
before any tracing can happen.
"""

import jax

jax.config.update("jax_enable_x64", True)

from gubernator_tpu.api.types import (  # noqa: E402
    Algorithm,
    Behavior,
    Status,
    RateLimitReq,
    RateLimitResp,
    HealthCheckResp,
    Second,
    Minute,
    Hour,
    Millisecond,
)

__version__ = "0.1.0"

__all__ = [
    "Algorithm",
    "Behavior",
    "Status",
    "RateLimitReq",
    "RateLimitResp",
    "HealthCheckResp",
    "Second",
    "Minute",
    "Hour",
    "Millisecond",
    "__version__",
]
