from setuptools import find_packages, setup

setup(
    name="gubernator-tpu",
    version="0.1.0",
    description="TPU-native distributed rate-limiting service",
    packages=find_packages(include=["gubernator_tpu", "gubernator_tpu.*"]),
    package_data={"gubernator_tpu.api": ["proto/*.proto", "proto/*.py"]},
    python_requires=">=3.10",
    install_requires=[
        "jax",
        "numpy",
        "grpcio",
        "protobuf",
        "aiohttp",
        "prometheus-client",
    ],
    entry_points={
        "console_scripts": [
            "gubernator-tpu=gubernator_tpu.daemon:main",
            "gubernator-tpu-cluster=gubernator_tpu.cmd.cluster_main:main",
            "gubernator-tpu-cli=gubernator_tpu.cmd.cli:main",
        ],
    },
)
